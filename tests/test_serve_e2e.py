"""End-to-end: real (tiny random-weight) model served over real embedded NATS
— the reference's full capability surface in one flow: publish to Object
Store, pull_model, list_models, chat_model (plain + streaming), delete_model
(SURVEY.md §4.2 + §7 minimum slice)."""

import json

import jax

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.gguf.constants import TokenType
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store import JetStreamStoreModule, ModelStore
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect
from nats_llm_studio_tpu.transport.jetstream import ObjectStore

from conftest import async_test


def byte_level_tokenizer_md(vocab_size: int) -> dict:
    """gpt2-family tokenizer covering all bytes (any text encodes), padded to
    the model vocab; last id is the eos/control token."""
    from nats_llm_studio_tpu.gguf.tokenizer import _byte_to_unicode

    b2u = _byte_to_unicode()
    tokens = [b2u[b] for b in range(256)]
    while len(tokens) < vocab_size - 1:
        tokens.append(f"<filler_{len(tokens)}>")
    tokens.append("<|eot|>")
    types = [int(TokenType.NORMAL)] * (vocab_size - 1) + [int(TokenType.CONTROL)]
    return {
        "tokenizer.ggml.model": "gpt2",
        "tokenizer.ggml.tokens": tokens,
        "tokenizer.ggml.token_type": types,
        "tokenizer.ggml.merges": [],
        "tokenizer.ggml.eos_token_id": vocab_size - 1,
        "tokenizer.ggml.add_bos_token": False,
    }


def build_tiny_gguf(path):
    cfg = ModelConfig.tiny(vocab_size=300, n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(7))
    export_params_to_gguf(
        path, params, cfg, tokenizer_md=byte_level_tokenizer_md(300), name="tiny-e2e"
    )
    return cfg


class E2E:
    async def __aenter__(self):
        self.broker = await EmbeddedBroker().start()
        JetStreamStoreModule(self.broker).install()
        self.nc = await connect(self.broker.url)
        self.objstore = ObjectStore(self.nc, timeout=5.0)
        return self

    async def __aexit__(self, *exc):
        await self.nc.close()
        await self.broker.stop()

    async def req(self, op, payload, timeout=50.0):
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        msg = await self.nc.request(f"lmstudio.{op}", body, timeout=timeout)
        return json.loads(msg.payload)


@async_test
async def test_full_model_lifecycle_over_nats(tmp_path):
    async with E2E() as h:
        # publisher side: export + upload the model to the bucket
        src = tmp_path / "tiny.gguf"
        build_tiny_gguf(src)
        pub_store = ModelStore(tmp_path / "publisher", objstore=h.objstore)
        pub_store.import_file(src, "acme/tiny-e2e")
        await pub_store.publish_model("acme/tiny-e2e")

        # worker side: empty cache, object store-backed registry
        worker_store = ModelStore(tmp_path / "worker", objstore=h.objstore)
        registry = LocalRegistry(worker_store, dtype="float32")
        worker = Worker(WorkerConfig(nats_url=h.broker.url), registry)
        await worker.start()

        # 1. pull_model from the bucket (lms get analog)
        resp = await h.req("pull_model", {"identifier": "acme/tiny-e2e"})
        assert resp["ok"], resp
        assert "tiny.gguf" in resp["data"]["output"]

        # 2. list_models: cached, not loaded
        resp = await h.req("list_models", {})
        entries = resp["data"]["models"]["data"]
        assert [e["id"] for e in entries] == ["acme/tiny-e2e"]
        assert entries[0]["state"] == "not-loaded"

        # 3. chat_model: real forward pass + sampling + detokenize
        resp = await h.req(
            "chat_model",
            {
                "model": "acme/tiny-e2e",
                "messages": [{"role": "user", "content": "hi there"}],
                "max_tokens": 6,
                "temperature": 0.0,
            },
        )
        assert resp["ok"], resp
        body = resp["data"]["response"]
        assert body["object"] == "chat.completion"
        assert body["usage"]["completion_tokens"] >= 1
        assert isinstance(body["choices"][0]["message"]["content"], str)
        assert "stats" in body  # tok/s + ttft observability

        # greedy determinism end-to-end
        resp2 = await h.req(
            "chat_model",
            {
                "model": "acme/tiny-e2e",
                "messages": [{"role": "user", "content": "hi there"}],
                "max_tokens": 6,
                "temperature": 0.0,
            },
        )
        assert (
            resp2["data"]["response"]["choices"][0]["message"]["content"]
            == body["choices"][0]["message"]["content"]
        )

        # 4. list_models now shows loaded
        resp = await h.req("list_models", {})
        assert resp["data"]["models"]["data"][0]["state"] == "loaded"

        # 5. streaming: chunks then terminal aggregate with usage
        chunks = []
        final = None
        async for msg in h.nc.request_stream(
            "lmstudio.chat_model",
            json.dumps(
                {
                    "model": "acme/tiny-e2e",
                    "stream": True,
                    "messages": [{"role": "user", "content": "stream me"}],
                    "max_tokens": 5,
                    "temperature": 0.0,
                }
            ).encode(),
            timeout=50.0,
        ):
            body = json.loads(msg.payload)
            if (msg.headers or {}).get("Nats-Stream-Done"):
                final = body
                break
            chunks.append(body["data"]["chunk"])
        assert final is not None and final["ok"], final
        streamed = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        agg = final["data"]["response"]["choices"][0]["message"]["content"]
        assert streamed == agg

        # 6. health reflects engine registry
        resp = await h.req("health", {})
        assert resp["data"]["models_loaded"] == 1

        # 7. delete_model unloads + removes the cache dir
        resp = await h.req("delete_model", {"model_id": "acme/tiny-e2e"})
        assert resp["ok"], resp
        assert "acme" in resp["data"]["deleted_dir"]
        resp = await h.req("list_models", {})
        assert resp["data"]["models"]["data"] == []

        # 8. chat after delete -> model not found error envelope
        resp = await h.req(
            "chat_model", {"model": "acme/tiny-e2e", "messages": [{"role": "user", "content": "x"}]}
        )
        assert not resp["ok"] and "not found" in resp["error"]

        await worker.drain()


@async_test
async def test_sync_model_from_bucket_subject_real_store(tmp_path):
    """The conceptual fifth subject (README.md:286-318) made real."""
    async with E2E() as h:
        src = tmp_path / "m.gguf"
        build_tiny_gguf(src)
        pub = ModelStore(tmp_path / "pub", objstore=h.objstore)
        pub.import_file(src, "acme/sync-model")
        await pub.publish_model("acme/sync-model")

        worker_store = ModelStore(tmp_path / "worker", objstore=h.objstore)
        worker = Worker(WorkerConfig(nats_url=h.broker.url), LocalRegistry(worker_store))
        await worker.start()
        resp = await h.req(
            "sync_model_from_bucket", {"object_name": "acme/sync-model/m.gguf"}
        )
        assert resp["ok"], resp
        assert resp["data"]["local_path"].endswith("m.gguf")
        assert worker_store.lookup("acme/sync-model") is not None
        await worker.drain()


@async_test
async def test_multi_worker_fanout_real_models(tmp_path):
    """BASELINE config 5 shape: Object Store fan-out + concurrent chat load
    across two queue-group workers, each running a real engine."""
    import asyncio

    async with E2E() as h:
        src = tmp_path / "fan.gguf"
        build_tiny_gguf(src)
        pub = ModelStore(tmp_path / "pub", objstore=h.objstore)
        pub.import_file(src, "acme/fan")
        await pub.publish_model("acme/fan")

        workers = []
        for i in range(2):
            store = ModelStore(tmp_path / f"w{i}", objstore=h.objstore)
            w = Worker(WorkerConfig(nats_url=h.broker.url), LocalRegistry(store, dtype="float32"))
            await w.start()
            workers.append(w)

        # both workers pull via the queue group until each has the model
        # (queue groups load-balance, so loop until both caches are warm)
        for _ in range(20):
            resp = await h.req("pull_model", {"identifier": "acme/fan"})
            assert resp["ok"], resp
            if all((tmp_path / f"w{i}" / "acme" / "fan").is_dir() for i in range(2)):
                break
        assert all((tmp_path / f"w{i}" / "acme" / "fan").is_dir() for i in range(2))

        body = {
            "model": "acme/fan",
            "messages": [{"role": "user", "content": "fan out"}],
            "max_tokens": 4,
            "temperature": 0.0,
        }
        results = await asyncio.gather(*[h.req("chat_model", body, timeout=90.0) for _ in range(10)])
        assert all(r["ok"] for r in results), results
        # identical greedy output regardless of which worker served it
        texts = {r["data"]["response"]["choices"][0]["message"]["content"] for r in results}
        assert len(texts) == 1
        served = [w._requests_total for w in workers]
        assert sum(served) >= 10
        for w in workers:
            await w.drain()

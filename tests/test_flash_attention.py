"""Flash-attention kernel vs the dense reference (Pallas interpreter on the
CPU backend — same kernel logic the TPU compiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.ops.flash_attention import flash_attention
from nats_llm_studio_tpu.ops.layers import gqa_attention, gqa_attention_hmajor

RNG = jax.random.PRNGKey(42)


def _reference_causal(q, k, v, scale):
    b, t = q.shape[:2]
    pos = jnp.arange(t)
    mask = (pos[None, None, :] <= pos[None, :, None]).repeat(b, axis=0)  # [B,T,T]
    return gqa_attention(q, k, v, mask, scale)


@pytest.mark.parametrize(
    "b,t,hq,hkv,d,bq,bk",
    [
        (1, 64, 4, 4, 32, 16, 16),  # MHA, tiles divide T
        (2, 48, 8, 2, 16, 16, 16),  # GQA group 4
        (1, 37, 4, 2, 16, 16, 16),  # ragged T -> padding path
        (1, 8, 2, 1, 8, 128, 128),  # T smaller than a tile
        (2, 130, 4, 4, 16, 64, 32), # uneven q/k tiles + padding
    ],
)
def test_flash_matches_reference(b, t, hq, hkv, d, bq, bk):
    kq, kk, kv = jax.random.split(RNG, 3)
    q = jax.random.normal(kq, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, hkv, d), jnp.float32)
    scale = d**-0.5
    want = _reference_causal(q, k, v, scale)
    got = flash_attention(q, k, v, scale, block_q=bq, block_k=bk, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_flash_scale_applied():
    q = jax.random.normal(RNG, (1, 16, 2, 8), jnp.float32)
    a = flash_attention(q, q, q, 0.1, interpret=True)
    b = flash_attention(q, q, q, 1.0, interpret=True)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_model_forward_with_flash_matches_dense():
    """Full-model prefill with the flash path must match the XLA mask path."""
    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[5, 6, 7, 8, 9, 10, 11]], jnp.int32)
    k, v = make_cache(cfg, 1, 32)
    ref, k_ref, _ = forward(params, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    cfg_f = cfg.with_(use_flash_attention=True)
    k, v = make_cache(cfg_f, 1, 32)
    got, k_got, _ = forward(params, cfg_f, tokens, k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(np.asarray(k_got), np.asarray(k_ref), rtol=1e-5, atol=1e-5)

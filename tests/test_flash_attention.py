"""Flash-attention kernel vs the dense reference (Pallas interpreter on the
CPU backend — same kernel logic the TPU compiles)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.ops.flash_attention import flash_attention
from nats_llm_studio_tpu.ops.layers import gqa_attention, gqa_attention_hmajor

RNG = jax.random.PRNGKey(42)


def _reference_causal(q, k, v, scale):
    b, t = q.shape[:2]
    pos = jnp.arange(t)
    mask = (pos[None, None, :] <= pos[None, :, None]).repeat(b, axis=0)  # [B,T,T]
    return gqa_attention(q, k, v, mask, scale)


@pytest.mark.parametrize(
    "b,t,hq,hkv,d,bq,bk",
    [
        (1, 64, 4, 4, 32, 16, 16),  # MHA, tiles divide T
        (2, 48, 8, 2, 16, 16, 16),  # GQA group 4
        (1, 37, 4, 2, 16, 16, 16),  # ragged T -> padding path
        (1, 8, 2, 1, 8, 128, 128),  # T smaller than a tile
        (2, 130, 4, 4, 16, 64, 32), # uneven q/k tiles + padding
    ],
)
def test_flash_matches_reference(b, t, hq, hkv, d, bq, bk):
    kq, kk, kv = jax.random.split(RNG, 3)
    q = jax.random.normal(kq, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, hkv, d), jnp.float32)
    scale = d**-0.5
    want = _reference_causal(q, k, v, scale)
    got = flash_attention(q, k, v, scale, block_q=bq, block_k=bk, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_flash_scale_applied():
    q = jax.random.normal(RNG, (1, 16, 2, 8), jnp.float32)
    a = flash_attention(q, q, q, 0.1, interpret=True)
    b = flash_attention(q, q, q, 1.0, interpret=True)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_model_forward_with_flash_matches_dense():
    """Full-model prefill with the flash path must match the XLA mask path."""
    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[5, 6, 7, 8, 9, 10, 11]], jnp.int32)
    k, v = make_cache(cfg, 1, 32)
    ref, k_ref, _ = forward(params, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    cfg_f = cfg.with_(use_flash_attention=True)
    k, v = make_cache(cfg_f, 1, 32)
    got, k_got, _ = forward(params, cfg_f, tokens, k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(np.asarray(k_got), np.asarray(k_ref), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# cache-backed chunk attention (chunked-prefill continuation)
# ---------------------------------------------------------------------------


def _reference_chunk(q, k_slab, v_slab, scale, start):
    """Dense reference: queries at [start, start+C) over the cache slab
    (history visible, chunk causal, beyond masked). k/v heads-major."""
    b, c, hq, d = q.shape
    kw = k_slab.shape[2]
    q_pos = start + jnp.arange(c)
    k_pos = jnp.arange(kw)
    mask = (k_pos[None, None, :] <= q_pos[None, :, None]).repeat(b, axis=0)
    return gqa_attention_hmajor(q, k_slab, v_slab, mask, scale)


@pytest.mark.parametrize(
    "b,c,kw,start,hq,hkv,d,bq,bk",
    [
        (1, 16, 64, 0, 4, 4, 32, 16, 16),    # first chunk (pure causal)
        (1, 16, 64, 16, 4, 2, 16, 16, 16),   # mid chunk with history
        (2, 16, 64, 48, 8, 2, 16, 16, 16),   # last chunk, GQA group 4
        (1, 24, 96, 40, 4, 2, 16, 16, 16),   # unaligned start vs tiles
        (2, 16, 64, 32, 4, 4, 16, 64, 128),  # blocks larger than shapes
    ],
)
def test_flash_chunk_matches_reference(b, c, kw, start, hq, hkv, d, bq, bk):
    from nats_llm_studio_tpu.ops.flash_attention import flash_attention_chunk

    kq, kk, kv = jax.random.split(RNG, 3)
    q = jax.random.normal(kq, (b, c, hq, d), jnp.float32)
    k_slab = jax.random.normal(kk, (b, hkv, kw, d), jnp.float32)
    v_slab = jax.random.normal(kv, (b, hkv, kw, d), jnp.float32)
    scale = d**-0.5
    want = _reference_chunk(q, k_slab, v_slab, scale, start)
    got = flash_attention_chunk(
        q, k_slab, v_slab, scale, jnp.int32(start), block_q=bq, block_k=bk,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_flash_chunk_one_program_all_starts():
    """The same compiled program must serve every chunk offset (start is a
    traced scalar-prefetch operand, not a static arg)."""
    from nats_llm_studio_tpu.ops.flash_attention import flash_attention_chunk

    kq, kk, kv = jax.random.split(RNG, 3)
    b, c, kw, hq, hkv, d = 1, 16, 64, 4, 2, 16
    q = jax.random.normal(kq, (b, c, hq, d), jnp.float32)
    k_slab = jax.random.normal(kk, (b, hkv, kw, d), jnp.float32)
    v_slab = jax.random.normal(kv, (b, hkv, kw, d), jnp.float32)
    scale = d**-0.5
    for start in (0, 16, 32, 48):
        want = _reference_chunk(q, k_slab, v_slab, scale, start)
        got = flash_attention_chunk(
            q, k_slab, v_slab, scale, jnp.int32(start), block_q=16,
            block_k=16, interpret=True,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "b,c,kw,start,hq,hkv,d,bq,bk",
    [
        (1, 16, 64, 16, 4, 2, 16, 16, 32),   # mid chunk with history, GQA 2
        (2, 16, 64, 48, 8, 2, 16, 16, 32),   # last chunk, GQA group 4
        (1, 24, 96, 40, 4, 2, 16, 16, 32),   # unaligned start vs tiles
    ],
)
def test_flash_chunk_kvq_matches_dequantized_reference(b, c, kw, start, hq,
                                                       hkv, d, bq, bk):
    """The int8-KV chunk kernel (per-tile VMEM dequant) must match the
    dense reference computed over the explicitly dequantized slab — the
    serving path's math, minus the full-window HBM transient."""
    from nats_llm_studio_tpu.ops.flash_attention import flash_attention_chunk_kvq
    from nats_llm_studio_tpu.ops.kvcache import quantize_rows

    kq_, kk, kv = jax.random.split(RNG, 3)
    q = jax.random.normal(kq_, (b, c, hq, d), jnp.float32)
    k_slab = jax.random.normal(kk, (b, hkv, kw, d), jnp.float32)
    v_slab = jax.random.normal(kv, (b, hkv, kw, d), jnp.float32)
    kq = quantize_rows(k_slab)  # codes [b,hkv,kw,d] + scales [b,hkv,kw]
    vq = quantize_rows(v_slab)
    k_deq = kq.q.astype(jnp.float32) * kq.s[..., None]
    v_deq = vq.q.astype(jnp.float32) * vq.s[..., None]
    scale = d**-0.5
    want = _reference_chunk(q, k_deq, v_deq, scale, start)
    got = flash_attention_chunk_kvq(
        q, kq.q, kq.s, vq.q, vq.s, scale, jnp.int32(start),
        block_q=bq, block_k=bk, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_chunk_continuation_untileable_window_falls_back_dense():
    """A cache window only 8-aligned (e.g. 88) cannot tile for bf16 — the
    model must fall back to the dense path instead of raising at trace
    time mid-serving (review r4 finding)."""
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=88, dtype="bfloat16",
                           use_flash_attention=True)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    k, v = make_cache(cfg, 1, 88)
    # first chunk at start 0, then a continuation at start 4 — the branch
    # that would hit flash_attention_chunk's tiling ValueError
    logits, k, v = forward(params, cfg, tokens, k, v,
                           jnp.zeros((1,), jnp.int32), uniform_start=True)
    logits2, k, v = forward(params, cfg, tokens, k, v,
                            jnp.full((1,), 4, jnp.int32), uniform_start=True)
    # dense reference on a plain config
    cfg_d = cfg.with_(use_flash_attention=False)
    kd, vd = make_cache(cfg_d, 1, 88)
    ref1, kd, vd = forward(params, cfg_d, tokens, kd, vd, jnp.zeros((1,), jnp.int32))
    ref2, kd, vd = forward(params, cfg_d, tokens, kd, vd, jnp.full((1,), 4, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(ref2),
                               rtol=2e-2, atol=2e-2)

"""Sampling invariance tests for the constrained-decoding extension.

The ``mask`` parameter added to ``_pick`` / ``_log_weights`` must be a
bitwise no-op when absent: ``_pick_ref`` / ``_log_weights_ref`` below are
verbatim copies of the pre-extension implementations, and every
unconstrained path is asserted bit-identical against them — greedy,
seeded temperature, top-k, top-p, and the per-row ``sample_rows`` stream.
With a mask, selection must stay inside the allowed set and greedy must
equal argmax over the allowed logits; at the batcher level, masked greedy
through ``ContinuousBatcher`` must reproduce a from-scratch reference
loop token for token on both KV layouts."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.engine.sampling import (
    _log_weights,
    _pick,
    sample_rows,
    spec_accept_rows,
)
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import (
    ensure_lm_head,
    forward,
    init_params,
    make_cache,
)
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

from conftest import async_test

CANDIDATES = 64
_NEG_INF = jnp.float32(-jnp.inf)


# -- verbatim pre-extension implementations (the invariance baseline) -------


def _pick_ref(logits, gumbel, temperature, top_k, top_p) -> jax.Array:
    """Shared sort-free selection. gumbel: [B, V] standard Gumbel noise."""
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]

    greedy = jnp.argmax(logits, axis=-1)
    full_pick = jnp.argmax(logits / safe_t + gumbel, axis=-1)

    c = min(CANDIDATES, v)
    cand, cand_idx = jax.lax.top_k(logits, c)
    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k <= 0, c, jnp.minimum(top_k, c))[:, None]
    keep = ranks < k_eff
    probs = jax.nn.softmax(cand / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]
    g_cand = jnp.take_along_axis(gumbel, cand_idx, axis=-1)
    masked = jnp.where(keep, cand / safe_t, _NEG_INF)
    drawn = jnp.argmax(masked + g_cand, axis=-1)
    cand_pick = jnp.take_along_axis(cand_idx, drawn[:, None], axis=-1)[:, 0]

    restricted = ((top_k > 0) & (top_k < v)) | (top_p < 1.0)
    pick = jnp.where(restricted, cand_pick, full_pick)
    return jnp.where(temperature <= 0.0, greedy, pick).astype(jnp.int32)


def _log_weights_ref(logits, temperature, top_k, top_p) -> jax.Array:
    b, v = logits.shape
    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (b,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (b,))
    safe_t = jnp.maximum(temperature, 1e-6)[:, None]

    c = min(CANDIDATES, v)
    cand, cand_idx = jax.lax.top_k(logits, c)
    ranks = jnp.arange(c)[None, :]
    k_eff = jnp.where(top_k <= 0, c, jnp.minimum(top_k, c))[:, None]
    keep = ranks < k_eff
    probs = jax.nn.softmax(cand / safe_t, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep &= (cum - probs) < top_p[:, None]
    rows = jnp.arange(b)[:, None]
    masked = jnp.full((b, v), _NEG_INF).at[rows, cand_idx].set(
        jnp.where(keep, cand / safe_t, _NEG_INF)
    )
    restricted = (((top_k > 0) & (top_k < v)) | (top_p < 1.0))[:, None]
    return jnp.where(restricted, masked, logits / safe_t)


SETTINGS = [
    (0.0, 0, 1.0),   # greedy
    (0.8, 0, 1.0),   # unrestricted temperature
    (1.3, 5, 1.0),   # top-k
    (0.7, 0, 0.9),   # top-p
    (1.0, 8, 0.75),  # both
]


def _logits_gumbel(b=6, v=200, seed=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    logits = jax.random.normal(k1, (b, v), jnp.float32) * 3.0
    gumbel = jax.random.gumbel(k2, (b, v), jnp.float32)
    return logits, gumbel


@pytest.mark.parametrize("temp,tk,tp", SETTINGS)
def test_pick_no_mask_bit_identical(temp, tk, tp):
    for seed in range(3):
        logits, gumbel = _logits_gumbel(seed=seed)
        got = _pick(logits, gumbel, temp, tk, tp)
        want = _pick_ref(logits, gumbel, temp, tk, tp)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("temp,tk,tp", SETTINGS)
def test_log_weights_no_mask_bit_identical(temp, tk, tp):
    logits, _ = _logits_gumbel(seed=11)
    got = np.asarray(_log_weights(logits, temp, tk, tp))
    want = np.asarray(_log_weights_ref(logits, temp, tk, tp))
    # -inf == -inf must also compare equal — array_equal handles it
    np.testing.assert_array_equal(got, want)


def test_sample_rows_no_mask_bit_identical():
    logits, _ = _logits_gumbel(seed=5)
    b, v = logits.shape
    seeds = jnp.arange(100, 100 + b, dtype=jnp.int32)
    steps = jnp.arange(b, dtype=jnp.int32) * 3

    def row_gumbel(seed, step):
        k = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.gumbel(k, (v,), jnp.float32)

    gumbel = jax.vmap(row_gumbel)(seeds, steps)
    for temp, tk, tp in SETTINGS:
        got = sample_rows(logits, seeds, steps, temp, tk, tp)
        want = _pick_ref(logits, gumbel, temp, tk, tp)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("temp,tk,tp", SETTINGS)
def test_masked_pick_stays_in_allowed_set(temp, tk, tp):
    logits, gumbel = _logits_gumbel(seed=7)
    b, v = logits.shape
    mask = np.zeros((b, v), dtype=bool)
    rng = np.random.default_rng(3)
    for i in range(b):
        mask[i, rng.choice(v, size=17, replace=False)] = True
    picked = np.asarray(_pick(logits, gumbel, temp, tk, tp, mask=jnp.asarray(mask)))
    for i in range(b):
        assert mask[i, picked[i]], (i, picked[i])
    if temp <= 0.0:
        # masked greedy == argmax over the allowed logits
        want = np.where(mask, np.asarray(logits), -np.inf).argmax(axis=-1)
        np.testing.assert_array_equal(picked, want)


def test_masked_log_weights_bans_tokens():
    logits, _ = _logits_gumbel(seed=9)
    b, v = logits.shape
    mask = np.ones((b, v), dtype=bool)
    mask[:, ::2] = False  # ban every even token id
    w = np.asarray(_log_weights(logits, 0.9, 0, 1.0, mask=jnp.asarray(mask)))
    assert np.all(w[:, ::2] == -np.inf)
    assert np.all(np.isfinite(w[:, 1::2]))
    # all-True mask is the identity
    w_id = np.asarray(
        _log_weights(logits, 0.9, 0, 1.0, mask=jnp.ones((b, v), dtype=bool))
    )
    np.testing.assert_array_equal(w_id, np.asarray(_log_weights_ref(logits, 0.9, 0, 1.0)))


def test_masked_spec_accept_greedy_stays_in_allowed_set():
    b, t, v = 3, 4, 120
    logits = jax.random.normal(jax.random.PRNGKey(21), (b, t, v), jnp.float32)
    mask = np.zeros((b, t, v), dtype=bool)
    allowed = np.arange(10, 40)
    mask[:, :, allowed] = True
    masked_greedy = np.where(mask, np.asarray(logits), -np.inf).argmax(axis=-1)
    drafts = jnp.asarray(masked_greedy[:, : t - 1], jnp.int32)
    toks, n_emit = spec_accept_rows(
        logits, drafts, jnp.full((b,), t - 1, jnp.int32),
        jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
        temperature=0.0, mask=jnp.asarray(mask),
    )
    toks, n_emit = np.asarray(toks), np.asarray(n_emit)
    # drafts equal to the masked argmax: all accepted + masked-greedy bonus
    np.testing.assert_array_equal(n_emit, np.full((b,), t))
    np.testing.assert_array_equal(toks, masked_greedy)


# -- batcher-level: masked greedy vs a from-scratch reference loop ----------


class AllowSet:
    """Minimal token-DFA fake: every state allows the same id set."""

    def __init__(self, allowed, vocab):
        self.allowed = sorted(allowed)
        self.vocab = vocab
        self.start = 0

    def mask(self, state):
        m = np.zeros(self.vocab, dtype=bool)
        m[self.allowed] = True
        return m

    def advance(self, state, tid):
        return state + 1 if tid in self.allowed else None

    def live(self, state):
        return True

    def accepting(self, state):
        return True


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def masked_greedy_reference(cfg, params, prompt, n, allowed):
    """Full re-forward each step: no KV cache, no batcher — the slowest,
    most obviously-correct masked greedy decode."""
    params = ensure_lm_head(params)
    allow = np.zeros(cfg.vocab_size, dtype=bool)
    allow[list(allowed)] = True
    toks = list(prompt)
    out = []
    for _ in range(n):
        k, v = make_cache(cfg, 1, seq_len=64)
        logits, _, _ = forward(
            params, cfg, jnp.asarray([toks], jnp.int32), k, v,
            jnp.zeros((1,), jnp.int32),
        )
        row = np.asarray(logits[0, len(toks) - 1], np.float32)
        t = int(np.where(allow, row, -np.inf).argmax())
        out.append(t)
        toks.append(t)
    return out


@pytest.mark.parametrize("paged", [True, False])
@async_test
async def test_batcher_masked_greedy_matches_reference(model, paged):
    cfg, params = model
    allowed = list(range(10, 30))
    prompts = [[1, 2, 3], [9, 8, 7, 6]]
    want = [masked_greedy_reference(cfg, params, p, 6, allowed) for p in prompts]

    b = ContinuousBatcher(
        params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64],
        paged=paged, spec_decode_k=(0 if paged else 3),
    )
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            dfa = AllowSet(allowed, cfg.vocab_size)
            return [t async for t in b.submit(p, sp, constrain=dfa)]

        got = await asyncio.gather(*[run(p) for p in prompts])
        assert list(got) == want
    finally:
        b.stop()


@async_test
async def test_batcher_unconstrained_rides_along_unchanged(model):
    """An unconstrained greedy request decoding alongside a constrained one
    (i.e. through the masked ext program with an all-True row) must produce
    exactly what it produces alone through the plain program."""
    cfg, params = model
    prompt = [5, 4, 3, 2]
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        alone = [t async for t in b.submit(prompt, sp)]

        dfa = AllowSet(list(range(10, 30)), cfg.vocab_size)

        async def constrained():
            return [t async for t in b.submit([1, 2], sp, constrain=dfa)]

        async def plain():
            return [t async for t in b.submit(prompt, sp)]

        rc, rn = await asyncio.gather(constrained(), plain())
        assert rn == alone
        assert all(t in dfa.allowed for t in rc)
    finally:
        b.stop()


@async_test
async def test_batcher_logprobs_greedy_top_entry_is_chosen_token(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=5)
        plain = [t async for t in b.submit([3, 1, 4], sp)]
        out = []
        async for batch in b.submit_batched(
            [3, 1, 4], sp, want_logprobs=True, top_logprobs=4
        ):
            out.extend(batch)
        toks = [t for t, _, _, _ in out]
        assert toks == plain  # logprobs request decodes the same tokens
        for tok, lp, top_ids, top_lps in out:
            assert lp <= 0.0
            assert len(top_ids) >= 4 and len(top_lps) >= 4
            # greedy: the chosen token is the most likely one
            assert top_ids[0] == tok
            assert abs(top_lps[0] - lp) < 1e-5
            assert all(a >= b2 for a, b2 in zip(top_lps, top_lps[1:]))
    finally:
        b.stop()

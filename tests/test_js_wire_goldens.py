"""JetStream API wire goldens (VERDICT r3 #10): the live `nats-server`
binary is absent in this image, so JetStream wire-compat is pinned the same
way the core protocol's is (tests/test_wire_goldens.py) — byte sequences in
the exact shapes a real nats-server 2.10.x JetStream API puts on the wire,
fed through our parser and client logic, plus a check that OUR embedded
broker's replies carry the headers a foreign nats.go Object Store client
requires.

Reference: the Object Store bucket flow is the model-distribution path
(/root/reference/README.md:250-318); real clients are nats.go/nats CLI, so
these frames are what they emit/expect against a stock server.
"""

import asyncio
import base64
import json

import pytest

from nats_llm_studio_tpu.transport import protocol as p
from nats_llm_studio_tpu.transport.jetstream import (
    ObjectNotFound,
    ObjectStore,
    ObjectStoreError,
)

from conftest import async_test


# ---------------------------------------------------------------------------
# recorded server -> client reply frames (nats-server 2.10.x DIRECT GET)
# ---------------------------------------------------------------------------

# a real DIRECT.GET hit: HMSG on the reply inbox, stored message's headers
# replaced by the Nats-* result headers, payload = the stored chunk bytes.
# (Header block shapes from nats-server 2.10 direct-get responder.)
_DG_HDRS = (
    b"NATS/1.0\r\n"
    b"Nats-Stream: OBJ_llm-models\r\n"
    b"Nats-Subject: $O.llm-models.C.abc123\r\n"
    b"Nats-Sequence: 42\r\n"
    b"Nats-Time-Stamp: 2024-03-01T12:00:00.000000000Z\r\n"
    b"Nats-Num-Pending: 0\r\n"
    b"\r\n"
)
DIRECT_GET_HIT = (
    b"HMSG _INBOX.dg.1 7 " + str(len(_DG_HDRS)).encode() + b" "
    + str(len(_DG_HDRS) + 5).encode() + b"\r\n" + _DG_HDRS + b"CHUNK\r\n"
)

def test_direct_get_hit_frame_parses_headers_and_payload():
    parser = p.Parser()
    events = list(parser.feed(DIRECT_GET_HIT))
    assert len(events) == 1
    ev = events[0]
    assert isinstance(ev, p.MsgEvent)
    assert ev.payload == b"CHUNK"
    assert ev.headers["Nats-Stream"] == "OBJ_llm-models"
    assert ev.headers["Nats-Subject"] == "$O.llm-models.C.abc123"
    assert ev.headers["Nats-Sequence"] == "42"
    assert ev.headers["Nats-Time-Stamp"].endswith("Z")


def test_direct_get_miss_inline_status_parses():
    hdr = b"NATS/1.0 404 Message Not Found\r\n\r\n"
    frame = (
        b"HMSG _INBOX.dg.2 7 " + str(len(hdr)).encode() + b" "
        + str(len(hdr)).encode() + b"\r\n" + hdr + b"\r\n"
    )
    events = list(p.Parser().feed(frame))
    assert len(events) == 1
    ev = events[0]
    assert ev.payload == b""
    # inline status lands under the reserved Status key, description included
    assert ev.headers["Status"].startswith("404")
    assert "Message Not Found" in ev.headers["Status"]


# ---------------------------------------------------------------------------
# our client against real-shaped API responses (fake connection)
# ---------------------------------------------------------------------------


class _FakeNC:
    """Captures requests; replies from a queue of (headers, payload)."""

    def __init__(self):
        self.sent: list[tuple[str, bytes]] = []
        self.replies: list[tuple[dict | None, bytes]] = []

    async def request(self, subject, payload=b"", timeout=2.0, headers=None):
        self.sent.append((subject, payload))
        h, body = self.replies.pop(0)
        return p_msg(h, body)

    async def publish(self, subject, payload=b"", reply=None, headers=None):
        self.sent.append((subject, payload))

    async def flush(self, timeout: float = 10.0):
        pass


def p_msg(headers, payload):
    from nats_llm_studio_tpu.transport.client import Msg

    return Msg(subject="_INBOX.x", payload=payload, reply=None, headers=headers)


@async_test
async def test_client_emits_real_api_request_shapes():
    """The subjects/payloads OUR client puts on the wire must be the ones a
    stock JetStream server routes: $JS.API.STREAM.CREATE.<stream> with the
    stream config, $JS.API.DIRECT.GET.<stream> with last_by_subj on the
    url-safe-base64 metadata subject."""
    nc = _FakeNC()
    os_ = ObjectStore(nc)  # type: ignore[arg-type]
    # real-shape create response (full echo + did_create + $JS type tag)
    nc.replies.append((None, json.dumps({
        "type": "io.nats.jetstream.api.v1.stream_create_response",
        "did_create": True,
        "config": {"name": "OBJ_llm-models", "subjects": ["$O.llm-models.C.>",
                                                          "$O.llm-models.M.>"],
                   "retention": "limits", "allow_direct": True,
                   "duplicate_window": 120000000000},
        "state": {"messages": 0, "bytes": 0, "first_seq": 0, "last_seq": 0},
        "created": "2024-03-01T12:00:00.000000000Z",
    }).encode()))
    await os_.ensure_bucket("llm-models")
    subject, payload = nc.sent[0]
    assert subject == "$JS.API.STREAM.CREATE.OBJ_llm-models"
    cfg = json.loads(payload)
    assert cfg["name"] == "OBJ_llm-models"
    assert cfg["subjects"] == ["$O.llm-models.C.>", "$O.llm-models.M.>"]
    assert cfg["allow_direct"] is True

    # info(): DIRECT.GET with last_by_subj on the b64 metadata subject
    meta = {"name": "pub/model/f.gguf", "bucket": "llm-models", "nuid": "N1",
            "size": 5, "chunks": 1, "digest": "SHA-256=x", "mtime": ""}
    nc.replies.append(({"Nats-Subject": "$O.llm-models.M.x",
                        "Nats-Sequence": "7"},
                       json.dumps(meta).encode()))
    info = await os_.info("llm-models", "pub/model/f.gguf")
    subject, payload = nc.sent[1]
    assert subject == "$JS.API.DIRECT.GET.OBJ_llm-models"
    b64 = base64.urlsafe_b64encode(b"pub/model/f.gguf").decode()
    assert json.loads(payload) == {"last_by_subj": f"$O.llm-models.M.{b64}"}
    assert info.size == 5 and info.nuid == "N1"


@async_test
async def test_client_maps_real_error_shapes():
    """Real-server error envelopes: {"error":{"code","err_code",
    "description"}} with the api.v1 type tag -> typed exceptions."""
    nc = _FakeNC()
    os_ = ObjectStore(nc)  # type: ignore[arg-type]
    nc.replies.append((None, json.dumps({
        "type": "io.nats.jetstream.api.v1.stream_info_response",
        "error": {"code": 404, "err_code": 10059,
                  "description": "stream not found"},
    }).encode()))
    with pytest.raises(ObjectNotFound):
        await os_._api("STREAM.INFO.OBJ_missing")

    nc.replies.append((None, json.dumps({
        "type": "io.nats.jetstream.api.v1.stream_create_response",
        "error": {"code": 400, "err_code": 10058,
                  "description": "stream name in subject does not match request"},
    }).encode()))
    with pytest.raises(ObjectStoreError):
        await os_._api("STREAM.CREATE.OBJ_bad", {"name": "other"})

    # DIRECT.GET miss via inline-status headers (parsed Status key)
    nc.replies.append(({"Status": "404 Message Not Found"}, b""))
    with pytest.raises(ObjectNotFound):
        await os_._direct_get("OBJ_llm-models", {"last_by_subj": "$O.x.M.y"})


# ---------------------------------------------------------------------------
# our broker's replies carry the headers foreign clients require
# ---------------------------------------------------------------------------


@async_test
async def test_embedded_direct_get_reply_has_result_headers(tmp_path):
    from nats_llm_studio_tpu.store import JetStreamStoreModule
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    broker = await EmbeddedBroker().start()
    JetStreamStoreModule(broker, store_dir=tmp_path / "js").install()
    nc = await connect(broker.url)
    try:
        store = ObjectStore(nc)
        await store.ensure_bucket("b")
        await store.put("b", "m/f.gguf", b"PAYLOAD")
        b64 = base64.urlsafe_b64encode(b"m/f.gguf").decode()
        msg = await nc.request(
            "$JS.API.DIRECT.GET.OBJ_b",
            json.dumps({"last_by_subj": f"$O.b.M.{b64}"}).encode(),
            timeout=5.0,
        )
        # the nats.go object-store client reads these three headers; missing
        # any of them breaks foreign-client reads against our broker
        assert msg.headers["Nats-Stream"] == "OBJ_b"
        assert msg.headers["Nats-Subject"].startswith("$O.b.M.")
        assert int(msg.headers["Nats-Sequence"]) >= 1
        meta = json.loads(msg.payload)
        assert meta["name"] == "m/f.gguf" and meta["size"] == 7
    finally:
        await nc.close()
        await broker.stop()

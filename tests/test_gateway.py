"""Gateway tests (gateway/server.py) against the fake echo backend:
OpenAI payload translation edge cases, SSE round-trips, the mid-stream
client-disconnect -> consumer-gone -> slot-freed chain, pre-bus 400s for
garbled ``response_format``, and the structured 503 + Retry-After shape
when the retry budget is spent."""

import asyncio
import json

import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.gateway import Gateway
from nats_llm_studio_tpu.gateway.server import BadRequest, translate_chat_payload
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.api import EngineError
from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect

from conftest import async_test
from fakes import EchoEngine, FakeRegistry


# -- payload translation (no bus) -------------------------------------------


def test_translate_minimal_payload_defaults():
    payload, stream = translate_chat_payload(
        {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    )
    assert payload == {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
    assert "max_tokens" not in payload  # engine default applies
    assert stream is False


def test_translate_drops_unknown_fields():
    payload, stream = translate_chat_payload({
        "model": "m",
        "messages": [{"role": "user", "content": "hi"}],
        "stream": True,
        "frequency_penalty": 0.5,       # unsupported: dropped, not failed
        "presence_penalty": 0.1,
        "tool_choice": "auto",
        "metadata": {"x": 1},
        "temperature": 0.5,
        "n": 2,
    })
    assert stream is True
    assert "frequency_penalty" not in payload
    assert "tool_choice" not in payload
    assert payload["temperature"] == 0.5 and payload["n"] == 2


def test_translate_max_completion_tokens_alias():
    payload, _ = translate_chat_payload({
        "model": "m",
        "messages": [{"role": "user", "content": "hi"}],
        "max_completion_tokens": 17,
    })
    assert payload["max_tokens"] == 17


@pytest.mark.parametrize("body,msg", [
    ([1, 2], "JSON object"),
    ({"messages": [{"role": "user"}]}, "'model'"),
    ({"model": "", "messages": [{"role": "user"}]}, "'model'"),
    ({"model": "m"}, "'messages'"),
    ({"model": "m", "messages": []}, "'messages'"),
    ({"model": "m", "messages": ["hi"]}, "messages[0]"),
    ({"model": "m", "messages": [{"content": "hi"}]}, "messages[0]"),
    ({"model": "m", "messages": [{"role": "user"}], "max_tokens": "12"},
     "'max_tokens'"),
    ({"model": "m", "messages": [{"role": "user"}], "n": True}, "'n'"),
    ({"model": "m", "messages": [{"role": "user"}], "temperature": "hot"},
     "'temperature'"),
    ({"model": "m", "messages": [{"role": "user"}],
      "response_format": {"type": "yaml"}}, "response_format"),
    ({"model": "m", "messages": [{"role": "user"}],
      "response_format": {"type": "json_schema", "json_schema": 3}},
     "response_format"),
])
def test_translate_rejects_garbled_payloads(body, msg):
    with pytest.raises(BadRequest, match=msg.replace("[", r"\[").replace("]", r"\]")):
        translate_chat_payload(body)


# -- HTTP harness ------------------------------------------------------------


class CountingRegistry(FakeRegistry):
    """Counts engine lookups: a request rejected at the gateway must leave
    this at zero (the 400 never touched the batcher seam)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.engine_lookups = 0

    async def get_engine(self, model_id):
        self.engine_lookups += 1
        return await super().get_engine(model_id)


class SheddingRegistry(FakeRegistry):
    """Every chat sheds with the retryable overload envelope."""

    async def get_engine(self, model_id):
        raise EngineError("overloaded: test shed, retry on another worker")


class SlowEngine(EchoEngine):
    """First chunk immediately, then parks forever; ``closed`` records the
    GeneratorExit from the worker's consumer-gone abort."""

    def __init__(self, model_id):
        super().__init__(model_id)
        self.closed = asyncio.Event()

    async def chat_stream(self, payload):
        try:
            yield {
                "object": "chat.completion.chunk",
                "model": self.model_id,
                "choices": [{"index": 0, "delta": {"content": "tick "}}],
            }
            await asyncio.sleep(3600)
        finally:
            self.closed.set()


class SlowRegistry(FakeRegistry):
    def __init__(self):
        super().__init__()
        self.engines = {"fake-echo-1": SlowEngine("fake-echo-1")}


class GatewayHarness:
    """Embedded broker + N workers + one Gateway on an ephemeral port."""

    def __init__(self, registries=None, n_workers=1, chat_timeout_s=5.0,
                 **gateway_kwargs):
        self.registries = registries
        self.n_workers = n_workers
        self.chat_timeout_s = chat_timeout_s
        self.gateway_kwargs = gateway_kwargs

    async def __aenter__(self):
        self.broker = await EmbeddedBroker().start()
        if self.registries is None:
            self.registries = [FakeRegistry() for _ in range(self.n_workers)]
        self.workers = []
        for reg in self.registries:
            w = Worker(
                WorkerConfig(nats_url=self.broker.url,
                             cluster_advert_interval_s=0.05),
                reg,
            )
            await w.start()
            self.workers.append(w)
        self.nc = await connect(self.broker.url)
        self.gw = Gateway(
            self.nc, port=0, chat_timeout_s=self.chat_timeout_s,
            retry=RetryPolicy(max_attempts=2, backoff_s=0.01,
                              retry_on_timeout=True),
            **self.gateway_kwargs,
        )
        await self.gw.start()
        return self

    async def __aexit__(self, *exc):
        await self.gw.stop()
        await self.nc.close()
        for w in self.workers:
            await w.drain()
        await self.broker.stop()

    async def open(self):
        return await asyncio.open_connection("127.0.0.1", self.gw.port)

    async def request(self, method, path, body=None, headers=None):
        """One request/response on a fresh connection; returns
        (status, headers, parsed-JSON body)."""
        reader, writer = await self.open()
        try:
            await _send(writer, method, path, body, headers)
            return await _read_response(reader)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass


async def _send(writer, method, path, body=None, headers=None):
    raw = b"" if body is None else json.dumps(body).encode()
    head = f"{method} {path} HTTP/1.1\r\nHost: t\r\nContent-Length: {len(raw)}\r\n"
    for k, v in (headers or {}).items():
        head += f"{k}: {v}\r\n"
    writer.write(head.encode() + b"\r\n" + raw)
    await writer.drain()


async def _read_head(reader):
    head = await reader.readuntil(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    status = int(lines[0].split(" ")[1])
    headers = {}
    for line in lines[1:]:
        if ":" in line:
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
    return status, headers


async def _read_response(reader):
    status, headers = await _read_head(reader)
    n = int(headers.get("content-length", "0"))
    raw = await reader.readexactly(n) if n else await reader.read()
    return status, headers, json.loads(raw) if raw else None


async def _read_sse_events(reader):
    """Read SSE frames until EOF (Connection: close delimits the body)."""
    raw = await reader.read()
    events = []
    for frame in raw.decode().split("\n\n"):
        if frame.startswith("data: "):
            events.append(frame[len("data: "):])
    return events


CHAT = {"model": "fake-echo-1",
        "messages": [{"role": "user", "content": "hi there"}]}


# -- tests -------------------------------------------------------------------


@async_test
async def test_healthz_and_models():
    async with GatewayHarness() as h:
        status, _, body = await h.request("GET", "/healthz")
        assert status == 200 and body["status"] == "ok"

        status, _, body = await h.request("GET", "/v1/models")
        assert status == 200
        assert body["object"] == "list"
        assert [m["id"] for m in body["data"]] == ["fake-echo-1"]


@async_test
async def test_chat_missing_max_tokens_and_unknown_fields_ok():
    async with GatewayHarness() as h:
        body = dict(CHAT)
        body["frequency_penalty"] = 0.25  # unknown to this backend: ignored
        body["tools"] = []
        status, _, resp = await h.request("POST", "/v1/chat/completions", body)
        assert status == 200
        assert resp["object"] == "chat.completion"
        assert resp["choices"][0]["message"]["content"] == "echo: hi there"
        assert resp["id"]  # gateway backfills an id when the engine omits it


@async_test
async def test_chat_unknown_model_404():
    async with GatewayHarness() as h:
        body = {"model": "nope", "messages": [{"role": "user", "content": "x"}]}
        status, _, resp = await h.request("POST", "/v1/chat/completions", body)
        assert status == 404
        assert resp["error"]["code"] == "model_not_found"


@async_test
async def test_garbled_response_format_400_without_touching_worker():
    reg = CountingRegistry()
    async with GatewayHarness(registries=[reg]) as h:
        body = dict(CHAT)
        body["response_format"] = {"type": "json_schema", "json_schema": "x"}
        status, _, resp = await h.request("POST", "/v1/chat/completions", body)
        assert status == 400
        assert resp["error"]["type"] == "invalid_request_error"
        assert "json_schema" in resp["error"]["message"]
        # the 400 was produced before any bus traffic: no engine lookup
        assert reg.engine_lookups == 0
        assert h.workers[0]._requests_total == 0


@async_test
async def test_bad_json_and_wrong_method():
    async with GatewayHarness() as h:
        reader, writer = await h.open()
        writer.write(b"POST /v1/chat/completions HTTP/1.1\r\nHost: t\r\n"
                     b"Content-Length: 3\r\n\r\n{{{")
        await writer.drain()
        status, _, resp = await _read_response(reader)
        writer.close()
        assert status == 400 and "JSON" in resp["error"]["message"]

        status, headers, _ = await h.request("GET", "/v1/chat/completions")
        assert status == 405 and headers.get("allow") == "POST"

        status, _, _ = await h.request("GET", "/v1/nothing")
        assert status == 404


@async_test
async def test_streaming_sse_round_trip():
    async with GatewayHarness() as h:
        reader, writer = await h.open()
        body = dict(CHAT, stream=True)
        await _send(writer, "POST", "/v1/chat/completions", body)
        status, headers = await _read_head(reader)
        assert status == 200
        assert headers["content-type"] == "text/event-stream"
        events = await _read_sse_events(reader)
        writer.close()

        assert events[-1] == "[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        assert text == "echo: hi there "
        assert all(c["object"] == "chat.completion.chunk" for c in chunks)
        assert all(c["id"] == chunks[0]["id"] for c in chunks)
        # final chunk carries the finish_reason, api.openai.com style
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"
        assert chunks[-1]["choices"][0]["delta"] == {}


@async_test
async def test_mid_stream_disconnect_cancels_the_slot():
    """Client vanishes mid-stream: the consumer-gone cancel must travel
    gateway -> router -> transport -> worker -> engine generator, ending in
    a GeneratorExit that frees the slot."""
    reg = SlowRegistry()
    engine = reg.engines["fake-echo-1"]
    async with GatewayHarness(registries=[reg]) as h:
        reader, writer = await h.open()
        await _send(writer, "POST", "/v1/chat/completions",
                    dict(CHAT, stream=True))
        status, _ = await _read_head(reader)
        assert status == 200
        first = await reader.readuntil(b"\n\n")  # one chunk arrived
        assert b"tick" in first
        # hang up mid-stream
        writer.close()
        await asyncio.wait_for(engine.closed.wait(), timeout=20.0)
        # the worker counted the abort (and the slot was freed via aclose)
        for _ in range(100):
            if h.workers[0]._streams_cancelled:
                break
            await asyncio.sleep(0.05)
        assert h.workers[0]._streams_cancelled == 1
        assert h.gw.client_disconnects >= 1


@async_test
async def test_retry_exhaustion_is_structured_503():
    """Every worker sheds every attempt: the gateway must answer with a
    parseable 503 + Retry-After, not a bare exception string."""
    async with GatewayHarness(registries=[SheddingRegistry()]) as h:
        status, headers, resp = await h.request(
            "POST", "/v1/chat/completions", CHAT
        )
        assert status == 503
        assert int(headers["retry-after"]) >= 1
        err = resp["error"]
        assert err["type"] == "overloaded_error"
        assert err["code"] == "worker_unavailable"
        assert err["retry_after_s"] >= 1
        # the final retryable envelope's message, not a bare traceback
        assert "retry on another worker" in err["message"]


@async_test
async def test_no_worker_times_out_to_503():
    async with GatewayHarness(n_workers=0, chat_timeout_s=0.4) as h:
        status, headers, resp = await h.request(
            "POST", "/v1/chat/completions", CHAT
        )
        assert status == 503
        assert "retry-after" in headers
        assert resp["error"]["type"] == "overloaded_error"


@async_test
async def test_streaming_exhaustion_before_first_chunk_is_http_503():
    async with GatewayHarness(registries=[SheddingRegistry()]) as h:
        status, headers, resp = await h.request(
            "POST", "/v1/chat/completions", dict(CHAT, stream=True)
        )
        # no preamble had been sent, so the error is a proper HTTP response
        assert status == 503
        assert "retry-after" in headers
        assert resp["error"]["code"] == "worker_unavailable"

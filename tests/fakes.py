"""Fake engine + registry: the deterministic-echo backend SURVEY.md §4.2 calls
for, substituted at the Registry seam (serve/api.py)."""

from __future__ import annotations

import asyncio

from nats_llm_studio_tpu.serve.api import ChatEngine, EngineError, ModelNotFound, Registry


class EchoEngine(ChatEngine):
    """Echoes the last user message back, one whitespace token at a time."""

    def __init__(self, model_id: str, delay_s: float = 0.0):
        self.model_id = model_id
        self.delay_s = delay_s

    def _reply_text(self, payload: dict) -> str:
        msgs = payload.get("messages") or []
        last_user = next((m["content"] for m in reversed(msgs) if m.get("role") == "user"), "")
        return f"echo: {last_user}"

    def _completion(self, payload: dict, text: str) -> dict:
        n_prompt = sum(len(str(m.get("content", "")).split()) for m in payload.get("messages", []))
        n_out = len(text.split())
        return {
            "id": f"chatcmpl-fake-{self.model_id}",
            "object": "chat.completion",
            "model": self.model_id,
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "stop",
                }
            ],
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out,
            },
        }

    async def chat(self, payload: dict) -> dict:
        if self.delay_s:
            await asyncio.sleep(self.delay_s)
        return self._completion(payload, self._reply_text(payload))

    async def chat_stream(self, payload: dict):
        text = self._reply_text(payload)
        for i, word in enumerate(text.split()):
            yield {
                "object": "chat.completion.chunk",
                "model": self.model_id,
                "choices": [{"index": 0, "delta": {"content": word + " "}}],
            }
        yield self._completion(payload, text)

    def info(self) -> dict:
        return {
            "id": self.model_id,
            "object": "model",
            "type": "llm",
            "publisher": "fake",
            "state": "loaded",
        }


class FakeRegistry(Registry):
    def __init__(self, models: list[str] | None = None, delay_s: float = 0.0):
        self.engines = {m: EchoEngine(m, delay_s) for m in (models or ["fake-echo-1"])}
        self.pulled: list[str] = []
        self.deleted: list[str] = []

    async def list_models(self) -> dict:
        return {"object": "list", "data": [e.info() for e in self.engines.values()]}

    async def pull(self, identifier: str) -> str:
        self.pulled.append(identifier)
        self.engines[identifier] = EchoEngine(identifier)
        return f"downloaded {identifier}"

    async def delete(self, model_id: str) -> str:
        if model_id not in self.engines:
            e = EngineError(f"model directory not found: /fake/models/{model_id}")
            e.dir = f"/fake/models/{model_id}"
            raise e
        del self.engines[model_id]
        self.deleted.append(model_id)
        return f"/fake/models/{model_id}"

    async def get_engine(self, model_id: str) -> ChatEngine:
        if model_id not in self.engines:
            raise ModelNotFound(model_id)
        return self.engines[model_id]

    async def sync_from_bucket(self, name: str, model_id: str | None = None) -> str:
        return f"/fake/models/{name}"

    def stats(self) -> dict:
        return {"models_loaded": sorted(self.engines)}

    def loaded_engines(self) -> dict:
        return dict(self.engines)

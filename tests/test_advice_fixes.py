"""Regression tests for the round-1 advisor findings (ADVICE.md):

1. shutdown must end waitlisted (admitted-to-inbox but not yet slotted)
   requests, not just active slots, so library callers never hang;
2. the flash prefill path must stay correct at start_pos > 0 (chunked
   prefill) by falling back to full-cache attention;
3. integer GGUF storage types must round-trip values above 2**24 and BF16
   must pass NaN through;
4. flash tile sizes must come out as multiples of 8 even for ragged T;
5. the batcher's end reason must reach the caller (finish_reason fidelity).
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.gguf.constants import GGMLType
from nats_llm_studio_tpu.gguf.quants import dequantize, quantize
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# -- 1: shutdown drains the waitlist ----------------------------------------


@async_test
async def test_shutdown_ends_waitlisted_requests(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64])
    sp = SamplingParams(temperature=0.0, max_tokens=50)
    first_tok = asyncio.Event()
    reasons: dict[int, str] = {}

    async def run(i):
        info: dict = {}
        async for _ in b.submit([1 + i, 2, 3], sp, info=info):
            first_tok.set()
        reasons[i] = info.get("finish_reason", "missing")

    # one request occupies the single slot; two more sit in the waitlist
    tasks = [asyncio.create_task(run(i)) for i in range(3)]
    await asyncio.wait_for(first_tok.wait(), timeout=30)
    await asyncio.to_thread(b.stop)
    # every submit must terminate — before the fix, waitlisted callers hung
    await asyncio.wait_for(asyncio.gather(*tasks), timeout=10)
    assert set(reasons) == {0, 1, 2}
    assert "shutdown" in reasons.values()


@async_test
async def test_submit_after_stop_raises(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64])
    b.start()
    await asyncio.to_thread(b.stop)
    with pytest.raises(RuntimeError):
        async for _ in b.submit([1, 2], SamplingParams(max_tokens=2)):
            pass


# -- 2: chunked prefill correctness with flash enabled ----------------------


def test_chunked_prefill_matches_full_with_flash():
    cfg = ModelConfig.tiny(n_layers=2, use_flash_attention=True)
    params = init_params(cfg, jax.random.PRNGKey(1))
    toks = jnp.asarray([[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]], jnp.int32)
    zero = jnp.zeros((1,), jnp.int32)

    k, v = make_cache(cfg, 1, 32)
    want, _, _ = forward(params, cfg, toks, k, v, zero)

    k, v = make_cache(cfg, 1, 32)
    _, k, v = forward(params, cfg, toks[:, :8], k, v, zero)
    got_tail, _, _ = forward(params, cfg, toks[:, 8:], k, v, jnp.full((1,), 8, jnp.int32))
    np.testing.assert_allclose(
        np.asarray(got_tail), np.asarray(want[:, 8:]), rtol=5e-3, atol=5e-3
    )


# -- 3: quantize fidelity ----------------------------------------------------


def test_quantize_int_types_exact_above_2_24():
    big = np.asarray([2**24 + 1, -(2**31) + 7, 2**24 + 3, 12345, -1, 0, 77, 2**30 + 1],
                     dtype=np.int64)
    for t in (GGMLType.I32, GGMLType.I64):
        out = dequantize(quantize(big, t), t, big.size)
        np.testing.assert_array_equal(np.asarray(out, dtype=np.int64), big)


def test_quantize_bf16_nan_passthrough():
    x = np.asarray([1.0, np.nan, -2.5, np.inf, -np.inf, 0.0], dtype=np.float32)
    out = dequantize(quantize(x, GGMLType.BF16), GGMLType.BF16, x.size)
    assert np.isnan(out[1])
    np.testing.assert_array_equal(out[[0, 2, 3, 4, 5]], x[[0, 2, 3, 4, 5]])


# -- 4: flash tiles stay multiples of 8 -------------------------------------


def test_flash_ragged_t_uses_aligned_tiles():
    from nats_llm_studio_tpu.ops.flash_attention import flash_attention
    from nats_llm_studio_tpu.ops.layers import gqa_attention

    b, t, h, d = 1, 100, 2, 16  # t=100 used to clamp block_q to 100 (not %8)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)
    pos = jnp.arange(t)
    mask = (pos[None, None, :] <= pos[None, :, None]).repeat(b, axis=0)
    want = gqa_attention(q, k, v, mask, d**-0.5)
    got = flash_attention(q, k, v, d**-0.5, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


# -- 5: finish_reason fidelity ----------------------------------------------


@async_test
async def test_finish_reason_propagates(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=16, buckets=[8, 16])
    try:
        # cache capacity: prompt 10 + decode hits max_seq 16 before max_tokens
        info: dict = {}
        toks = [t async for t in b.submit(list(range(1, 11)), SamplingParams(
            temperature=0.0, max_tokens=100), info=info)]
        assert info["finish_reason"] == "length"
        assert 0 < len(toks) < 100

        # stop token
        first = toks[0] if toks else 1
        info2: dict = {}
        _ = [t async for t in b.submit(list(range(1, 11)), SamplingParams(
            temperature=0.0, max_tokens=100, stop_ids=frozenset({first})), info=info2)]
        assert info2["finish_reason"] == "stop"
    finally:
        b.stop()

"""Worker handler-layer tests over real (embedded) NATS with a fake engine —
the integration tier SURVEY.md §4.2 specifies. Exercises every validation
branch of the reference handlers (nats_llm_studio.go:254-262, :293-300,
:331-345), the envelope contract, queue-group scale-out with two workers, and
token streaming."""

import asyncio
import collections
import json

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

from conftest import async_test
from fakes import FakeRegistry


class Harness:
    def __init__(self, n_workers=1, models=None, delay_s=0.0):
        self.n_workers = n_workers
        self.models = models
        self.delay_s = delay_s

    async def __aenter__(self):
        self.broker = await EmbeddedBroker().start()
        self.registries = []
        self.workers = []
        for _ in range(self.n_workers):
            reg = FakeRegistry(models=self.models, delay_s=self.delay_s)
            w = Worker(WorkerConfig(nats_url=self.broker.url), reg)
            await w.start()
            self.registries.append(reg)
            self.workers.append(w)
        self.nc = await connect(self.broker.url)
        return self

    async def __aexit__(self, *exc):
        await self.nc.close()
        for w in self.workers:
            await w.drain()
        await self.broker.stop()

    async def req(self, op: str, payload, timeout=5.0):
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        msg = await self.nc.request(f"lmstudio.{op}", body, timeout=timeout)
        return json.loads(msg.payload)


@async_test
async def test_list_models_envelope():
    async with Harness(models=["m1", "m2"]) as h:
        resp = await h.req("list_models", {})
        assert resp["ok"] is True
        assert "error" not in resp
        assert resp["data"]["http_status"] == 200
        ids = [m["id"] for m in resp["data"]["models"]["data"]]
        assert sorted(ids) == ["m1", "m2"]
        assert resp["data"]["models"]["object"] == "list"


@async_test
async def test_pull_model_validation_and_success():
    async with Harness() as h:
        resp = await h.req("pull_model", {})
        assert resp["ok"] is False and resp["error"] == "'identifier' is required"

        resp = await h.req("pull_model", b"{not json")
        assert resp["ok"] is False and resp["error"].startswith("invalid JSON in PullModel")

        resp = await h.req("pull_model", {"identifier": "pub/new-model"})
        assert resp["ok"] is True
        assert resp["data"]["model"] == "pub/new-model"
        assert "output" in resp["data"]
        assert h.registries[0].pulled == ["pub/new-model"]


@async_test
async def test_delete_model_validation_success_and_missing_dir():
    async with Harness(models=["m1"]) as h:
        resp = await h.req("delete_model", {})
        assert resp["ok"] is False and resp["error"] == "'model_id' is required"

        resp = await h.req("delete_model", {"model_id": "m1"})
        assert resp["ok"] is True
        assert resp["data"]["model"] == "m1"
        assert resp["data"]["deleted_dir"].endswith("m1")

        # missing model: error carries the attempted dir (go :304-313)
        resp = await h.req("delete_model", {"model_id": "ghost"})
        assert resp["ok"] is False
        assert "model directory not found" in resp["error"]
        assert resp["data"]["dir"].endswith("ghost")


@async_test
async def test_chat_model_validation_branches():
    async with Harness() as h:
        resp = await h.req("chat_model", b"")
        assert resp["ok"] is False and "empty payload" in resp["error"]

        resp = await h.req("chat_model", b"not json at all")
        assert resp["ok"] is False and resp["error"].startswith("invalid JSON in ChatModel")

        resp = await h.req("chat_model", {"messages": []})
        assert resp["ok"] is False and resp["error"] == "'model' is required in ChatModel"

        resp = await h.req("chat_model", {"model": "nope", "messages": []})
        assert resp["ok"] is False and "model not found" in resp["error"]


@async_test
async def test_chat_model_success_shape():
    async with Harness() as h:
        payload = {
            "model": "fake-echo-1",
            "messages": [
                {"role": "system", "content": "Always answer in rhymes."},
                {"role": "user", "content": "hello tpu"},
            ],
        }
        resp = await h.req("chat_model", payload)
        assert resp["ok"] is True
        data = resp["data"]
        assert data["http_status"] == 200
        response = data["response"]
        assert response["object"] == "chat.completion"
        assert response["choices"][0]["message"]["content"] == "echo: hello tpu"
        assert response["usage"]["completion_tokens"] == 3
        assert response["usage"]["total_tokens"] > 3


@async_test
async def test_chat_model_streaming():
    async with Harness() as h:
        payload = {
            "model": "fake-echo-1",
            "stream": True,
            "messages": [{"role": "user", "content": "a b c"}],
        }
        chunks, final = [], None
        async for m in h.nc.request_stream("lmstudio.chat_model", json.dumps(payload).encode(), timeout=10):
            body = json.loads(m.payload)
            if m.headers and "Nats-Stream-Done" in m.headers:
                final = body
            else:
                chunks.append(body["data"]["chunk"])
        assert final is not None and final["ok"] is True
        text = "".join(c["choices"][0]["delta"]["content"] for c in chunks)
        assert text.strip() == "echo: a b c"
        assert final["data"]["response"]["choices"][0]["message"]["content"] == "echo: a b c"


@async_test
async def test_health_subject():
    async with Harness(models=["m1"]) as h:
        resp = await h.req("health", {})
        assert resp["ok"] is True
        assert resp["data"]["status"] == "ok"
        assert resp["data"]["models_loaded"] == ["m1"]
        assert resp["data"]["queue_group"] == "lmstudio-workers"


@async_test
async def test_sync_model_from_bucket_subject():
    async with Harness() as h:
        resp = await h.req("sync_model_from_bucket", {})
        assert resp["ok"] is False and resp["error"] == "'object_name' is required"

        resp = await h.req("sync_model_from_bucket", {"object_name": "pub/model/file.gguf"})
        assert resp["ok"] is True
        assert resp["data"]["local_path"].endswith("pub/model/file.gguf")


@async_test
async def test_two_workers_queue_group_scale_out():
    """README.md:478-484: multiple workers under one queue group split load;
    each request is answered exactly once."""
    async with Harness(n_workers=2) as h:
        N = 40
        results = await asyncio.gather(
            *[
                h.req("chat_model", {"model": "fake-echo-1", "messages": [{"role": "user", "content": f"r{i}"}]})
                for i in range(N)
            ]
        )
        assert all(r["ok"] for r in results)
        served = collections.Counter()
        for i, w in enumerate(h.workers):
            served[i] = w._requests_total
        assert sum(served.values()) == N
        assert all(v > 0 for v in served.values()), f"load not balanced: {served}"


@async_test
async def test_unexpected_exception_still_replies_error_envelope():
    """An exception escaping a handler (not EngineError) must produce an
    error envelope, not leave the requester to time out — the reference
    replies on every failure path (nats_llm_studio.go:207-226)."""

    class ExplodingRegistry(FakeRegistry):
        async def list_models(self):
            raise RuntimeError("boom")

    broker = await EmbeddedBroker().start()
    try:
        w = Worker(WorkerConfig(nats_url=broker.url), ExplodingRegistry())
        await w.start()
        nc = await connect(broker.url)
        msg = await nc.request("lmstudio.list_models", b"{}", timeout=5.0)
        resp = json.loads(msg.payload)
        assert resp["ok"] is False
        assert "internal error" in resp["error"] and "boom" in resp["error"]
        await nc.close()
        await w.drain()
    finally:
        await broker.stop()


@async_test
async def test_metrics_subject():
    """metrics — full observability snapshot: worker totals, registry stats,
    per-engine batcher counters, device list (SURVEY.md §5)."""
    async with Harness() as h:
        resp = await h.req("metrics", {})
        assert resp["ok"] is True
        d = resp["data"]
        assert d["requests_total"] >= 0
        assert "registry" in d and "engines" in d
        assert isinstance(d["devices"], list) and d["devices"]
        assert {"id", "platform", "kind"} <= set(d["devices"][0])


@async_test
async def test_profile_subject(tmp_path):
    """profile — captures a jax.profiler trace and replies with its path.
    A client-supplied 'dir' must be IGNORED (round-2 advisor, medium: bus
    clients are untrusted; an honored path would be an arbitrary-directory
    write primitive on the worker host)."""
    import os

    async with Harness() as h:
        client_dir = tmp_path / "client-chosen"
        resp = await h.req(
            "profile", {"seconds": 0.2, "dir": str(client_dir)}, timeout=30.0
        )
        assert resp["ok"] is True
        trace_dir = resp["data"]["trace_dir"]
        assert os.path.isdir(trace_dir)
        assert not client_dir.exists()  # the client's path was not honored
        assert not str(trace_dir).startswith(str(tmp_path))
        found = []
        for root, _, files in os.walk(trace_dir):
            found += files
        assert found  # a trace artifact was written
        bad = await h.req("profile", {"seconds": "xx"})
        assert bad["ok"] is False
        nan = await h.req("profile", b'{"seconds": NaN}')
        assert nan["ok"] is False and "finite" in nan["error"]

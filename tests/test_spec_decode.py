"""Speculative decoding tests: prompt-lookup proposals, the rejection-
sampling acceptance rule (distribution-preserving), and end-to-end
equivalence of the speculative batcher against non-speculative decoding
(greedy must be bit-identical; temperature>0 must be token-identical to
the Generator's reference speculative loop under the same seed/k)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import Generator, SamplingParams
from nats_llm_studio_tpu.engine.sampling import (
    _log_weights,
    sample_rows,
    spec_accept_rows,
)
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.serve.spec import NGramIndex

from conftest import async_test

# a prompt whose greedy continuation cycles (high n-gram hit rate on tiny
# random weights) and one with no internal repetition (zero-hit)
REP = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]
FLAT = [1, 9, 3, 17, 2, 11]


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def gen(model):
    cfg, params = model
    return Generator(params, cfg, max_seq_len=128, buckets=[8, 16, 32, 64, 128])


# ---------------------------------------------------------------------------
# prompt-lookup proposal index
# ---------------------------------------------------------------------------


def test_ngram_index_proposes_continuation():
    idx = NGramIndex([1, 2, 3, 9, 1, 2, 3], max_ngram=3, min_ngram=1)
    # tail trigram (1,2,3) last occurred ending at index 2: the proposal is
    # what followed it
    assert idx.propose(2) == [9, 1]
    assert idx.propose(4) == [9, 1, 2, 3]


def test_ngram_index_zero_hit():
    idx = NGramIndex([1, 2, 3, 4, 5], max_ngram=3, min_ngram=1)
    assert idx.propose(4) == []


def test_ngram_index_append_updates_tail():
    idx = NGramIndex([1, 2, 3], max_ngram=3, min_ngram=1)
    assert idx.propose(2) == []
    idx.append(1)  # history [1,2,3,1]: tail unigram (1,) seen at index 0
    assert idx.propose(2) == [2, 3]
    idx.extend([2, 3])  # [1,2,3,1,2,3]: trigram hit beats the unigram
    assert idx.propose(3) == [1, 2, 3]


def test_ngram_index_prefers_longest_match():
    # unigram tail 7 occurs after 9; bigram (5, 7) occurs after 8 — the
    # longer context must win
    idx = NGramIndex([7, 9, 5, 7, 8, 5, 7], max_ngram=3, min_ngram=1)
    assert idx.propose(1) == [8]


# ---------------------------------------------------------------------------
# rejection-sampling acceptance preserves the sampling distribution
# ---------------------------------------------------------------------------


def _empirical(tokens: np.ndarray, v: int) -> np.ndarray:
    return np.bincount(tokens, minlength=v) / float(len(tokens))


@pytest.mark.parametrize(
    "top_k,top_p",
    [(0, 1.0), (5, 1.0), (0, 0.8)],
    ids=["unrestricted", "topk5", "topp08"],
)
def test_spec_accept_matches_plain_distribution(top_k, top_p):
    """Seeded statistical check: the first token emitted by the rejection
    sampler (accept-or-resample against a point-mass draft) has the same
    distribution the plain sampler draws from."""
    v, n = 16, 4000
    rng = np.random.default_rng(7)
    row = jnp.asarray(rng.normal(size=(v,)) * 2.0, jnp.float32)
    logits = jnp.broadcast_to(row, (n, v))
    seeds = jnp.arange(n, dtype=jnp.int32)
    steps = jnp.zeros((n,), jnp.int32)
    temp = jnp.full((n,), 1.0, jnp.float32)
    tk = jnp.full((n,), top_k, jnp.int32)
    tp = jnp.full((n,), top_p, jnp.float32)

    # analytic target: softmax of the (possibly truncated) log-weights
    p_ref = np.asarray(
        jax.nn.softmax(_log_weights(row[None, :], temp[:1], tk[:1], tp[:1]))
    )[0]

    # draft a mid-probability token so both accept and reject paths run
    d = int(np.argsort(p_ref)[-2])
    verify_logits = jnp.broadcast_to(row, (n, 2, v))
    drafts = jnp.full((n, 1), d, jnp.int32)
    dlen = jnp.ones((n,), jnp.int32)
    out, n_emit = spec_accept_rows(
        verify_logits, drafts, dlen, seeds, steps, temp, tk, tp
    )
    out, n_emit = np.asarray(out), np.asarray(n_emit)
    assert set(np.unique(n_emit)) <= {1, 2}
    # both paths must actually be exercised
    assert 0.05 < float((n_emit == 2).mean()) < 0.95 or p_ref[d] > 0.9

    spec_emp = _empirical(out[:, 0], v)
    plain = np.asarray(sample_rows(logits, seeds, steps, temp, tk, tp))
    plain_emp = _empirical(plain, v)

    tv_spec = 0.5 * np.abs(spec_emp - p_ref).sum()
    tv_plain = 0.5 * np.abs(plain_emp - p_ref).sum()
    assert tv_plain < 0.03  # sanity: the plain sampler matches its target
    assert tv_spec < 0.03, f"spec TV {tv_spec:.4f} vs plain TV {tv_plain:.4f}"

    # truncation must be respected exactly (zero-probability tokens never
    # emitted), not just approximately
    banned = np.flatnonzero(p_ref == 0.0)
    assert not np.isin(out[:, 0], banned).any()


def test_spec_bonus_token_distribution():
    """When every draft is accepted, the bonus token is a PLAIN sample from
    the last verify position — check it against the analytic distribution."""
    v, n = 16, 4000
    rng = np.random.default_rng(11)
    row0 = np.asarray(rng.normal(size=(v,)), np.float32)
    row1 = np.asarray(rng.normal(size=(v,)) * 2.0, np.float32)
    d = int(row0.argmax())
    row0[d] += 50.0  # p0(d) ~ 1: the draft is (almost) always accepted
    verify_logits = jnp.broadcast_to(
        jnp.asarray(np.stack([row0, row1])), (n, 2, v)
    )
    seeds = jnp.arange(n, dtype=jnp.int32)
    steps = jnp.zeros((n,), jnp.int32)
    temp = jnp.full((n,), 1.0, jnp.float32)
    tk = jnp.zeros((n,), jnp.int32)
    tp = jnp.ones((n,), jnp.float32)
    out, n_emit = spec_accept_rows(
        verify_logits,
        jnp.full((n, 1), d, jnp.int32),
        jnp.ones((n,), jnp.int32),
        seeds, steps, temp, tk, tp,
    )
    out, n_emit = np.asarray(out), np.asarray(n_emit)
    full = n_emit == 2
    assert full.mean() > 0.99
    p1 = np.asarray(jax.nn.softmax(jnp.asarray(row1)))
    emp = _empirical(out[full, 1], v)
    assert 0.5 * np.abs(emp - p1).sum() < 0.03


def test_spec_accept_greedy_is_argmax_prefix():
    """Greedy rows accept exactly the longest draft prefix equal to the
    model argmax, then emit the argmax at the first mismatch."""
    v = 8
    rows = np.zeros((1, 4, v), np.float32)
    argmaxes = [3, 5, 2, 6]
    for t, a in enumerate(argmaxes):
        rows[0, t, a] = 5.0
    out, n_emit = spec_accept_rows(
        jnp.asarray(rows),
        jnp.asarray([[3, 5, 7]], jnp.int32),  # third draft wrong (7 != 2)
        jnp.asarray([3], jnp.int32),
        jnp.asarray([0], jnp.int32),
        jnp.asarray([0], jnp.int32),
        jnp.asarray([0.0], jnp.float32),
        jnp.asarray([0], jnp.int32),
        jnp.asarray([1.0], jnp.float32),
    )
    assert int(n_emit[0]) == 3
    assert np.asarray(out)[0, :3].tolist() == [3, 5, 2]


# ---------------------------------------------------------------------------
# end-to-end equivalence through the batcher
# ---------------------------------------------------------------------------


async def _batch_run(cfg, params, prompts, sp, k, burst=1):
    b = ContinuousBatcher(
        params, cfg, max_slots=4, max_seq_len=128, buckets=[8, 128],
        spec_decode_k=k, decode_burst=burst,
    )
    try:
        async def one(p):
            return [t async for t in b.submit(p, sp)]

        got = await asyncio.gather(*[one(p) for p in prompts])
        return list(got), b.stats.snapshot()
    finally:
        b.stop()


@async_test
async def test_greedy_spec_bit_identical_high_hit(model, gen):
    """Repetition-heavy prompt: verifies fire, drafts get accepted, and the
    output is still bit-identical to the non-speculative Generator."""
    cfg, params = model
    sp = SamplingParams(temperature=0.0, max_tokens=40)
    want = [t for t, _ in gen.generate(REP, sp)]
    got, stats = await _batch_run(cfg, params, [REP], sp, k=4)
    assert got[0] == want
    assert stats["spec_verifies"] > 0
    assert stats["spec_accepted"] > 0


@async_test
async def test_greedy_spec_bit_identical_zero_hit(model, gen):
    """No n-gram hits: the batcher must degrade to plain decoding with the
    same greedy output (acceptance handles whatever drafting produces)."""
    cfg, params = model
    sp = SamplingParams(temperature=0.0, max_tokens=40)
    want = [t for t, _ in gen.generate(FLAT, sp)]
    got, stats = await _batch_run(cfg, params, [FLAT], sp, k=4)
    assert got[0] == want


@async_test
async def test_greedy_spec_concurrent_matches_single_stream(model, gen):
    cfg, params = model
    prompts = [REP, FLAT, [2, 3, 2, 3, 2, 3, 2], [8]]
    sp = SamplingParams(temperature=0.0, max_tokens=24)
    want = [[t for t, _ in gen.generate(p, sp)] for p in prompts]
    got, stats = await _batch_run(cfg, params, prompts, sp, k=4)
    assert got == want
    assert stats["spec_drafted"] >= stats["spec_accepted"]


@async_test
async def test_spec_disabled_above_max_active(model, gen):
    """Occupancy past spec_max_active pauses verify dispatches but plain
    positional decoding must still produce correct greedy output."""
    cfg, params = model
    prompts = [REP, FLAT, [2, 3, 2, 3, 2, 3, 2], [8]]
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    want = [[t for t, _ in gen.generate(p, sp)] for p in prompts]
    b = ContinuousBatcher(
        params, cfg, max_slots=4, max_seq_len=128, buckets=[8, 128],
        spec_decode_k=4, spec_max_active=1, decode_burst=1,
    )
    try:
        async def one(p):
            return [t async for t in b.submit(p, sp)]

        got = await asyncio.gather(*[one(p) for p in prompts])
        assert list(got) == want
    finally:
        b.stop()


@async_test
async def test_temperature_batcher_matches_reference_loop(model, gen):
    """temperature > 0, single request, decode_burst=1: the batcher's
    speculative path is token-identical to the Generator's reference
    speculative loop (same seed, same k, same proposal points)."""
    cfg, params = model
    for prompt in (REP, FLAT):
        sp = SamplingParams(
            temperature=0.9, max_tokens=30, seed=1234, top_k=40, top_p=0.95
        )
        ref = [t for t, _ in gen.generate_speculative(prompt, sp, spec_k=4)]
        got, _ = await _batch_run(cfg, params, [prompt], sp, k=4)
        assert got[0] == ref


def test_greedy_reference_loop_matches_generate(model, gen):
    """The Generator's speculative loop is itself bit-identical to plain
    generate() at temperature 0 (acceptance == argmax prefix)."""
    sp = SamplingParams(temperature=0.0, max_tokens=40)
    for prompt in (REP, FLAT):
        want = [t for t, _ in gen.generate(prompt, sp)]
        got = [t for t, _ in gen.generate_speculative(prompt, sp, spec_k=4)]
        assert got == want


def test_warmup_covers_decode(model):
    """warmup() must block on BOTH the prefill and decode outputs of every
    bucket (the old code only waited on the last bucket's prefill logits),
    and must leave the generator fully usable."""
    cfg, params = model
    g = Generator(params, cfg, max_seq_len=64, buckets=[8, 16, 32, 64])
    g.warmup()
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    out = [t for t, _ in g.generate([1, 2, 3], sp)]
    assert len(out) == 4

"""Elastic autoscaling (ISSUE 15).

The tentpole control loop end to end: plan() hysteresis/bounds against a
synthetic clock, the spawn circuit breaker provably halting a spawn storm,
grace-window expiry of spawns that never advertise, warm prefix-cache
handoff (batcher-level hot_prefixes -> export -> import round trip, the
worker kv_handoff/kv_import subjects with validation and graceful no-ops on
fake engines), the drained-worker restart suppression satellite, and two
live-broker chaos tests: kill-and-replace under a fake-engine load wave,
and the real-engine acceptance e2e — a killed worker's replacement serves
its first request with persistent-compile-cache hits and a nonzero
prefix-cache hit rate from the donor's warm handoff.
"""

import asyncio
import functools
import json
import time

import jax
import numpy as np
import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.obs import compile_cache_counts, install_compile_cache_listener
from nats_llm_studio_tpu.obs.aggregator import Aggregator
from nats_llm_studio_tpu.serve import Autoscaler, Worker
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.serve.kv_transfer import decode_kv_blob, encode_kv_blob
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.serve.worker import KV_MODEL_HEADER
from nats_llm_studio_tpu.store.manager import ModelStore
from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect
from nats_llm_studio_tpu.transport import protocol as p
from nats_llm_studio_tpu.transport.envelope import deadline_header_value

from conftest import async_test
from fakes import FakeRegistry
from test_cluster import ClusterHarness
from test_serve_e2e import byte_level_tokenizer_md

MID = "acme/tiny-autoscale"


def _async_test_long(fn):
    """Like conftest.async_test, with headroom for three real engine loads."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=180.0))

    return wrapper


class StubNC:
    """Duck-typed client for pure control-loop tests: records every event
    publish and directed request, answers requests with an ok envelope."""

    def __init__(self):
        self.published: list[tuple[str, dict]] = []
        self.requests: list[tuple[str, dict]] = []

    async def publish(self, subject, payload, headers=None):
        self.published.append((subject, json.loads(payload)))

    async def request(self, subject, payload=b"", timeout=2.0, headers=None,
                      retry=None):
        self.requests.append((subject, json.loads(payload or b"{}")))

        class _Reply:
            payload = b'{"ok":true,"data":{}}'

        return _Reply()

    async def subscribe(self, subject, cb=None, queue=None):
        class _Sub:
            async def unsubscribe(self):
                pass

        return _Sub()


def _adv(wid, depth=0, brownout=0, draining=False):
    return {"worker_id": wid, "queue_depth": depth, "brownout": brownout,
            "draining": draining}


def _seed(a, now, *adverts):
    for d in adverts:
        a._members[d["worker_id"]] = {"mono": now, "advert": d}


def _metric(prom: str, name: str) -> float:
    for line in prom.splitlines():
        if line.startswith(name) and not line.startswith("#"):
            return float(line.rsplit(None, 1)[1])
    raise AssertionError(f"{name} missing from exposition:\n{prom}")


def events(nc: StubNC, action: str) -> list[dict]:
    return [e for _, e in nc.published if e.get("action") == action]


# -- plan(): pure policy against a synthetic clock ----------------------------


def test_plan_scale_up_hysteresis_cooldown_and_max_bound():
    a = Autoscaler(StubNC(), min_workers=1, max_workers=3, up_dwell_s=2.0,
                   down_dwell_s=30.0, cooldown_s=5.0, up_queue_depth=8.0,
                   stale_after_s=1e9, handoff_prefixes=0)
    t = 1000.0
    _seed(a, t, _adv("w-a", depth=9), _adv("w-b", depth=9))
    assert a.plan(t) is None          # pressure noted; the dwell starts
    assert a.plan(t + 1.0) is None    # still dwelling
    # pressure that breaks before the dwell elapses resets the clock
    _seed(a, t, _adv("w-a", depth=2), _adv("w-b", depth=2))
    assert a.plan(t + 1.5) is None
    assert a._pressure_since is None
    _seed(a, t, _adv("w-a", depth=9), _adv("w-b", depth=9))
    assert a.plan(t + 2.0) is None    # dwell restarted here
    d = a.plan(t + 4.0)
    assert d == {"action": "spawn", "reason": "queue_depth avg 9.0",
                 "workers_live": 2}
    # cooldown gates everything, even persisting pressure
    a._cooldown_until = t + 10.0
    assert a.plan(t + 5.0) is None
    # pressed against the ceiling the plan yields to shedding
    a._cooldown_until = -float("inf")
    _seed(a, t, _adv("w-a", 9), _adv("w-b", 9), _adv("w-c", 9))
    assert a.plan(t + 6.0) is None


def test_plan_slo_burn_counts_as_pressure():
    a = Autoscaler(StubNC(), min_workers=1, max_workers=3, up_dwell_s=1.0,
                   cooldown_s=0.0, stale_after_s=1e9, handoff_prefixes=0)
    t = 2000.0
    _seed(a, t, _adv("w-a", depth=0))
    a._last_burn_mono = t             # the aggregator just alerted
    assert a.plan(t) is None
    d = a.plan(t + 1.0)
    assert d is not None and d["action"] == "spawn"
    assert d["reason"] == "slo_burn"


def test_plan_below_min_spawns_immediately_and_counts_pending():
    a = Autoscaler(StubNC(), min_workers=2, max_workers=4, stale_after_s=1e9,
                   handoff_prefixes=0)
    # an empty fleet is replaced NOW — no dwell on a dead worker's absence
    d = a.plan(3000.0)
    assert d == {"action": "spawn", "reason": "below_min", "workers_live": 0}
    # a spawn already in flight counts against the floor (no double-spawn)
    a._pending["w-x"] = {"mono": 3000.0, "proc": None}
    _seed(a, 3000.0, _adv("w-a"))
    assert a.plan(3001.0) is None


def test_plan_scale_down_picks_least_loaded_and_respects_floor():
    a = Autoscaler(StubNC(), min_workers=1, max_workers=4, down_dwell_s=3.0,
                   cooldown_s=0.0, stale_after_s=1e9, handoff_prefixes=0)
    t = 4000.0
    _seed(a, t, _adv("w-a", depth=1), _adv("w-b", depth=0))
    assert a.plan(t) is None          # idle dwell starts
    d = a.plan(t + 3.0)
    assert d == {"action": "drain", "reason": "idle", "victim": "w-b",
                 "workers_live": 2}
    # at the floor nothing drains, however idle
    a2 = Autoscaler(StubNC(), min_workers=1, max_workers=4, down_dwell_s=0.0,
                    stale_after_s=1e9, handoff_prefixes=0)
    _seed(a2, t, _adv("w-only"))
    assert a2.plan(t) is None


# -- tick(): actions, grace expiry, the circuit breaker -----------------------


@async_test
async def test_tick_drain_hands_off_to_best_survivor():
    nc = StubNC()
    drained = []
    a = Autoscaler(nc, min_workers=1, max_workers=4, down_dwell_s=0.0,
                   cooldown_s=0.0, handoff_prefixes=4, stale_after_s=1e9,
                   drain_fn=lambda wid, to: drained.append((wid, to)))
    t = 5000.0
    _seed(a, t, _adv("w-a", depth=1), _adv("w-b", depth=0),
          _adv("w-c", depth=0))
    d = await a.tick(t)
    assert d is not None and d["action"] == "drain" and d["victim"] == "w-b"
    # the victim's hot cache goes to the least-loaded survivor, not nowhere
    assert drained == [("w-b", "w-c")]
    assert a.drains_total == 1
    ev = events(nc, "drain")
    assert len(ev) == 1
    assert ev[0]["kind"] == "autoscale" and ev[0]["handoff_to"] == "w-c"


@async_test
async def test_tick_expires_unadvertised_spawn_and_kills_the_proc():
    class FakeProc:
        killed = False

        def poll(self):
            return None

        def kill(self):
            self.killed = True

    proc = FakeProc()
    a = Autoscaler(StubNC(), min_workers=1, max_workers=4, spawn_grace_s=5.0,
                   cooldown_s=0.0, stale_after_s=1e9, handoff_prefixes=0,
                   spawn_fn=lambda wid: proc)
    t = 6000.0
    d = await a.tick(t)               # below_min: spawn goes pending
    assert d is not None and d["action"] == "spawn"
    assert a.spawns_total == 1 and len(a._pending) == 1
    await a.tick(t + 6.0)             # grace blown: the hung proc dies
    assert proc.killed is True
    assert a.spawn_failures_total == 1
    # below_min re-spawned a fresh pending in the very same tick — the
    # floor is never left unfilled while the breaker is closed
    assert a.spawns_total == 2 and len(a._pending) == 1


@async_test
async def test_first_advert_of_pending_spawn_triggers_warm_handoff():
    nc = StubNC()
    a = Autoscaler(nc, min_workers=2, max_workers=4, cooldown_s=0.0,
                   handoff_prefixes=4, stale_after_s=1e9,
                   spawn_fn=lambda wid: None)
    t = 7000.0
    _seed(a, t, _adv("w-donor", depth=0))
    d = await a.tick(t)               # 1 live < min 2
    assert d is not None and d["reason"] == "below_min"
    wid = next(iter(a._pending))
    a.observe_advert(wid, _adv(wid))
    assert a._pending == {}           # live now; failures streak resets
    assert a._consecutive_failures == 0
    for _ in range(5):                # let the background handoff task land
        await asyncio.sleep(0.01)
    handoffs = [(s, b) for s, b in nc.requests if s.endswith(".kv_handoff")]
    assert handoffs == [
        ("lmstudio.worker.w-donor.kv_handoff", {"to": wid, "limit": 4})
    ]


@async_test
async def test_spawn_circuit_breaker_halts_the_spawn_storm():
    """ISSUE 15 acceptance: consecutive spawn failures open the breaker,
    further wanted spawns are suppressed with ONE reasoned event (no storm,
    no event flood), and spawning resumes after the breaker cooldown."""
    nc = StubNC()
    attempts = []

    def exploding_spawn(wid):
        attempts.append(wid)
        raise RuntimeError("exec format error")

    a = Autoscaler(nc, min_workers=1, max_workers=4, breaker_failures=3,
                   breaker_cooldown_s=100.0, cooldown_s=0.0,
                   stale_after_s=1e9, handoff_prefixes=0,
                   spawn_fn=exploding_spawn)
    t = 8000.0
    for i in range(3):                # empty fleet: below_min every tick
        await a.tick(t + i)
    assert len(attempts) == 3
    assert a.spawn_failures_total == 3
    assert a.breaker_open(t + 3) is True
    prom = a.render_prometheus(now=t + 3)
    assert _metric(prom, "lmstudio_autoscale_spawn_failures_total") == 3
    assert _metric(prom, "lmstudio_autoscale_spawns_total") == 0
    assert _metric(prom, "lmstudio_autoscale_drains_total") == 0
    assert _metric(prom, "lmstudio_autoscale_breaker_open") == 1
    # the storm is halted: seven more pressured ticks attempt nothing
    for i in range(3, 10):
        await a.tick(t + i)
    assert len(attempts) == 3
    await asyncio.sleep(0.02)         # drain the _emit_soon background tasks
    assert len(events(nc, "spawn_failed")) == 3
    suppressed = events(nc, "spawn_suppressed")
    assert len(suppressed) == 1       # announced once, not per tick
    assert suppressed[0]["reason"] == "breaker_open"
    assert suppressed[0]["wanted"] == "below_min"
    # past the cooldown the breaker closes and spawning resumes
    await a.tick(t + 200.0)
    assert len(attempts) == 4
    await a.stop()


# -- warm handoff: batcher-level enumeration + round trip ---------------------


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _batcher(params, cfg, **kw):
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_cache_blocks", 16)
    return ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                             buckets=[8, 64], paged=True, **kw)


async def _greedy(b, prompt, n=8):
    sp = SamplingParams(temperature=0.0, max_tokens=n)
    return [t async for t in b.submit(list(prompt), sp)]


@async_test
async def test_hot_prefixes_enumerates_mru_first_and_feeds_handoff(model):
    cfg, params = model
    pa = [(i * 7 + 3) % cfg.vocab_size for i in range(16)]  # 2 chunks of 8
    pb = [(i * 5 + 1) % cfg.vocab_size for i in range(16)]
    a, b = _batcher(params, cfg), _batcher(params, cfg)
    try:
        await _greedy(a, pa)
        await _greedy(a, pb)
        hot = a.prefix_cache.hot_prefixes(4)
        assert hot, "a warmed cache enumerated nothing"
        assert hot[0][:16] == pb      # most-recently-used first
        assert any(path[:16] == pa for path in hot)
        assert a.prefix_cache.hot_prefixes(1) == hot[:1]
        assert a.prefix_cache.hot_prefixes(0) == []
        # the enumerated path feeds export directly: the handoff pipeline
        # round-trips into a cold peer...
        export = await asyncio.to_thread(a.export_prefix_blocks, hot[0])
        assert export is not None and export["chunks"]
        imported = await asyncio.to_thread(
            b.import_prefix_blocks, decode_kv_blob(encode_kv_blob(export))
        )
        assert imported["tokens"] == len(export["token_ids"])
        # ...which now admits the hot prompt with a prefix hit
        await _greedy(b, pb)
        assert b.prefix_cache.counters()["hit_tokens"] > 0
    finally:
        a.stop()
        b.stop()


# -- worker subjects on fake engines: validation + graceful no-ops ------------


@async_test
async def test_kv_handoff_and_import_subjects_on_fake_engines():
    async with ClusterHarness(n_workers=2) as h:
        wa, wb = h.workers
        # a handoff between engines with no KV surface is a graceful no-op
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_handoff",
                              {"to": wb.worker_id})
        assert resp["ok"] is True
        assert resp["data"] == {"to": wb.worker_id, "sent": 0, "failed": 0,
                                "tokens": 0}
        # validation
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_handoff", {})
        assert resp["ok"] is False and "'to' is required" in resp["error"]
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_handoff",
                              {"to": wa.worker_id})
        assert resp["ok"] is False and "self" in resp["error"]
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_handoff",
                              {"to": wb.worker_id, "limit": "lots"})
        assert resp["ok"] is False and "integer" in resp["error"]
        # kv_import: a raw blob must name its model in the header
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_import", b"KVX1junk")
        assert resp["ok"] is False and KV_MODEL_HEADER in resp["error"]
        # a corrupt blob is a counted transfer failure, not a crash
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_import", b"KVX1junk",
                              headers={KV_MODEL_HEADER: "fake-echo-1"})
        assert resp["ok"] is False and "error in kv import" in resp["error"]
        assert wa._kv_transfer_failures == 1
        # an object-store ref missing its fields is rejected up front
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_import",
                              {"model": "fake-echo-1"})
        assert resp["ok"] is False and "'model' and 'object'" in resp["error"]
        # a well-formed blob into an engine with no import hook: graceful
        export = {"token_ids": list(range(8)), "chunk_tokens": 8,
                  "chunks": [{"k": np.zeros((1, 2, 8, 2, 4), np.float32),
                              "v": np.zeros((1, 2, 8, 2, 4), np.float32)}]}
        resp, _ = await h.req(f"worker.{wa.worker_id}.kv_import",
                              encode_kv_blob(export),
                              headers={KV_MODEL_HEADER: "fake-echo-1"})
        assert resp["ok"] is True
        assert resp["data"] == {"imported": False, "reason": "no_import"}
        # the families exist even at zero, so dashboards can assert on them
        prom = (await h.nc.request(
            f"lmstudio.worker.{wb.worker_id}.metrics.prom", b"", timeout=10
        )).payload.decode()
        assert "lmstudio_warm_handoff_sent_total" in prom
        assert "lmstudio_warm_handoff_received_total" in prom


@async_test
async def test_admin_drain_carries_handoff_to():
    async with ClusterHarness(n_workers=2) as h:
        wa, wb = h.workers
        resp, _ = await h.req("admin.drain", {"worker_id": wa.worker_id,
                                              "handoff_to": wb.worker_id})
        assert resp["ok"] is True
        assert resp["data"]["draining"] is True
        # fake engines hand nothing over, but the handoff rode the drain
        assert resp["data"]["handoff"] == {"to": wb.worker_id, "sent": 0,
                                           "failed": 0, "tokens": 0}


# -- the drained-worker restart suppression satellite -------------------------


class _StubEngine:
    batcher = None

    async def unload(self):
        pass


@async_test
async def test_restart_engine_suppressed_while_draining(tmp_path):
    reg = LocalRegistry(ModelStore(tmp_path / "models"), restart_backoff_s=0.2)
    reg._engines["m"] = _StubEngine()
    # entry guard: a draining registry refuses before any teardown
    reg.set_draining(True)
    assert await reg.restart_engine("m") == "draining"
    assert "m" in reg._engines
    # post-backoff guard: the drain lands while the restart sleeps out its
    # backoff — the engine is torn down but never resurrected
    reg.set_draining(False)
    task = asyncio.ensure_future(reg.restart_engine("m", reason="hung"))
    await asyncio.sleep(0.05)
    reg.set_draining(True)
    assert await task == "draining"
    assert "m" not in reg._engines
    assert reg.engine_restarts_total == 0


# -- the autoscaler's exposition rides the cluster scrape ---------------------


def test_aggregator_merges_autoscaler_exposition():
    a = Autoscaler(StubNC(), handoff_prefixes=0)
    agg = Aggregator(None, extra_expositions=[a.render_prometheus])
    text = agg.render_cluster()
    assert "lmstudio_autoscale_spawns_total" in text
    assert "lmstudio_autoscale_breaker_open" in text
    # a broken extra source must not break the scrape
    agg2 = Aggregator(
        None, extra_expositions=[lambda: 1 / 0, a.render_prometheus]
    )
    assert "lmstudio_autoscale_spawns_total" in agg2.render_cluster()


# -- kill-and-replace under load (fake engines, real broker) ------------------


@async_test
async def test_kill_and_replace_under_load():
    """Sever a worker mid-wave: every request is served (retries absorb the
    kill — zero timeout expiries), the autoscaler detects the dead member
    via advert staleness and spawns a replacement below the floor."""
    async with ClusterHarness(n_workers=2, advert_interval_s=0.05) as h:
        spawned = []

        async def spawn_fn(wid):
            w = Worker(
                WorkerConfig(nats_url=h.broker.url, worker_id=wid,
                             cluster_advert_interval_s=0.05),
                FakeRegistry(),
            )
            await w.start()
            spawned.append(w)

        a = Autoscaler(h.nc, min_workers=2, max_workers=3, interval_s=0.05,
                       stale_after_s=0.4, spawn_grace_s=10.0, cooldown_s=0.3,
                       handoff_prefixes=0, spawn_fn=spawn_fn)
        # steady-state start: subscribe first, let both members advertise,
        # THEN run the loop — under a loaded CPU the loop's settle window
        # alone may not outlast the first adverts
        await a.start(control_loop=False)
        try:
            deadline = time.monotonic() + 5.0
            while len(a.live_workers()) < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            assert len(a.live_workers()) == 2
            a._task = asyncio.ensure_future(a._loop())

            async def one(i):
                body = json.dumps(h.chat(f"r{i}")).encode()
                msg = await h.nc.request(
                    "lmstudio.chat_model", body, timeout=1.0,
                    headers={p.DEADLINE_HEADER: deadline_header_value(20.0)},
                    retry=RetryPolicy(max_attempts=40, backoff_s=0.05,
                                      jitter=0.0, retry_on_timeout=True),
                )
                return json.loads(msg.payload)

            wave = [asyncio.ensure_future(one(i)) for i in range(12)]
            await asyncio.sleep(0.1)
            await h.workers[0].nc.close()   # kill: no drain, no goodbye
            results = await asyncio.gather(*wave)
            assert all(r["ok"] for r in results), results

            deadline = time.monotonic() + 10.0
            while ((a.spawns_total < 1 or len(a.live_workers()) < 2)
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.05)
            assert a.spawns_total >= 1
            assert a.spawn_failures_total == 0
            assert len(a.live_workers()) >= 2
            assert any(w.worker_id.startswith("w-as") for w in spawned)
            prom = a.render_prometheus()
            assert _metric(prom, "lmstudio_autoscale_spawns_total") >= 1
        finally:
            await a.stop()
            for w in spawned:
                await w.drain()


@async_test
async def test_pull_precompile_transcript_stable_and_unloads(tmp_path):
    """Pull-time precompile is invisible on the wire: the pull reply stays
    exactly the store transcript ("pulled"), and an engine loaded only for
    the compile is unloaded on the way out — pull leaves the model
    cached-not-loaded while the compiled programs persist on disk. A model
    that was already resident stays resident."""
    from nats_llm_studio_tpu.serve import registry as registry_mod

    store = ModelStore(tmp_path / "models")
    reg = LocalRegistry(store, dtype="float32", pull_precompile=True)
    calls = {"warm": 0, "unload": 0}

    class _Batcher:
        def warm_chunk_programs(self):
            calls["warm"] += 1
            return 3

    class _Engine:
        batcher = _Batcher()

        async def unload(self):
            calls["unload"] += 1

    eng = _Engine()

    async def fake_pull(identifier):
        return tmp_path / "models" / identifier, "pulled"

    async def fake_get_engine(model_id):
        reg._engines[model_id] = eng
        return eng

    store.pull = fake_pull
    reg.get_engine = fake_get_engine
    reg._mesh_unservable = lambda path: None
    real_gate = registry_mod._compile_cache_dir_configured
    registry_mod._compile_cache_dir_configured = lambda: True
    try:
        out = await reg.pull("acme/tiny")
        assert out == "pulled"                 # wire transcript untouched
        assert calls["warm"] == 1              # the grid WAS compiled
        assert calls["unload"] == 1            # load served only the compile
        assert "acme/tiny" not in reg.loaded_engines()

        # already resident: the re-pull re-warms but must not unload
        reg._engines["acme/tiny"] = eng
        out = await reg.pull("acme/tiny")
        assert out == "pulled"
        assert calls["warm"] == 2
        assert calls["unload"] == 1
        assert "acme/tiny" in reg.loaded_engines()
    finally:
        registry_mod._compile_cache_dir_configured = real_gate


# -- the acceptance e2e: real engines, kill, precompiled + warm replacement ---


def _publish_tiny(models_dir, model_id=MID, seed=11):
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    d = models_dir / model_id
    d.mkdir(parents=True, exist_ok=True)
    export_params_to_gguf(
        d / "m.gguf", params, cfg, name=model_id,
        tokenizer_md=byte_level_tokenizer_md(cfg.vocab_size),
    )


def _registry(models):
    return LocalRegistry(
        ModelStore(models), dtype="float32", max_batch_slots=2,
        max_seq_len=64, prefill_chunk=8, prefix_cache_blocks=16,
    )


def _chat_body(text, max_tokens=8):
    return json.dumps({
        "model": MID,
        "messages": [{"role": "user", "content": text}],
        "max_tokens": max_tokens,
        "temperature": 0.0,
    }).encode()


@_async_test_long
async def test_autoscaler_replaces_killed_worker_with_warm_replacement(tmp_path):
    """ISSUE 15 acceptance: under a request wave, killing a worker triggers
    an autoscaler spawn; the replacement's first serve hits the persistent
    XLA compile cache AND the prefix cache warmed by the donor's kv_handoff
    push, and every wave request is served or cleanly retryable."""
    install_compile_cache_listener()
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        # donor and victim share one registry: one engine load covers both
        shared = _registry(models)
        donor = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-donor",
                         cluster_advert_interval_s=0.1,
                         kv_transfer_timeout_s=120.0),
            shared,
        )
        victim = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-victim",
                         cluster_advert_interval_s=0.1,
                         kv_transfer_timeout_s=120.0),
            shared,
        )
        await donor.start()
        await victim.start()
        nc = await connect(broker.url)

        # warm the donor: load the engine, seed its radix cache
        warm_body = _chat_body("warm the handoff path")
        env = json.loads((await nc.request(
            "lmstudio.worker.w-donor.chat_model", warm_body, timeout=120
        )).payload)
        assert env["ok"] is True, env
        assert shared.loaded_engines()[MID].batcher.prefix_cache.blocks > 0

        spawned = []

        async def spawn_fn(wid):
            w = Worker(
                WorkerConfig(nats_url=broker.url, worker_id=wid,
                             cluster_advert_interval_s=0.1,
                             kv_transfer_timeout_s=120.0),
                _registry(models),
            )
            await w.start()
            spawned.append(w)

        scaler = Autoscaler(nc, min_workers=2, max_workers=3, interval_s=0.1,
                            stale_after_s=0.6, spawn_grace_s=60.0,
                            cooldown_s=1.0, handoff_prefixes=4,
                            spawn_fn=spawn_fn)
        await scaler.start()
        try:
            deadline = time.monotonic() + 10.0
            while (len(scaler.live_workers()) < 2
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.05)
            assert sorted(scaler.live_workers()) == ["w-donor", "w-victim"]
            cc_before = compile_cache_counts()

            async def one(i):
                body = _chat_body(f"wave request number {i:02d}")
                msg = await nc.request(
                    "lmstudio.chat_model", body, timeout=5.0,
                    headers={p.DEADLINE_HEADER: deadline_header_value(90.0)},
                    retry=RetryPolicy(max_attempts=10, backoff_s=0.1,
                                      jitter=0.0, retry_on_timeout=True),
                )
                return json.loads(msg.payload)

            wave = [asyncio.ensure_future(one(i)) for i in range(6)]
            await asyncio.sleep(0.2)
            await victim.nc.close()     # the kill
            results = await asyncio.gather(*wave)
            # served or cleanly retryable — never a timeout expiry (gather
            # would have raised) or a non-retryable error
            assert all(r["ok"] or r.get("retryable") for r in results), results
            assert any(r["ok"] for r in results)

            # the autoscaler notices the stale member, spawns a replacement,
            # and fires the donor's warm handoff at its first advert
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline:
                if spawned and spawned[0]._warm_handoff_received >= 1:
                    break
                await asyncio.sleep(0.1)
            assert scaler.spawns_total >= 1
            assert spawned, "the autoscaler never spawned a replacement"
            repl = spawned[0]
            assert repl._warm_handoff_received >= 1
            assert donor._warm_handoff_sent >= 1

            # first serve on the replacement: prefix hits from the handoff,
            # jit programs from the persistent compile cache
            env = json.loads((await nc.request(
                f"lmstudio.worker.{repl.worker_id}.chat_model", warm_body,
                timeout=120,
            )).payload)
            assert env["ok"] is True, env
            ctr = repl.registry.loaded_engines()[MID].batcher \
                .prefix_cache.counters()
            assert ctr["hits"] >= 1 and ctr["hit_tokens"] > 0
            cc_after = compile_cache_counts()
            assert cc_after["hits"] > cc_before["hits"]
            prom = scaler.render_prometheus()
            assert _metric(prom, "lmstudio_autoscale_spawns_total") >= 1
        finally:
            await scaler.stop()
            for w in spawned:
                await w.drain()
        await nc.close()
        await donor.drain()
        await victim.drain()
    finally:
        await broker.stop()

"""Tensor-parallel serving equivalence (PR 6 tentpole).

The batcher's jit grid carries explicit shardings end-to-end when built on
a mesh; on the CPU backend with 8 forced host devices (conftest.py) the
same greedy decode must be BIT-IDENTICAL at tp=1 vs tp=2/4 — including the
prefix-cache hit path and the speculative-decode path — or the sharding
constraints changed the math, not just the layout. Also pins the
``serving_mesh`` env-knob semantics, the replicated-KV GQA fallback, the
pull-time unservable gate, and the tp-divided HBM estimates.
"""

import asyncio

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.parallel import build_mesh, serving_mesh
from nats_llm_studio_tpu.parallel.memory import estimate_device_bytes
from nats_llm_studio_tpu.parallel.sharding import (
    cache_spec,
    kv_replicated,
    row_cache_spec,
    shard_params,
    validate_mesh_for_config,
)
from nats_llm_studio_tpu.serve.api import EngineError
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.serve.prefix_cache import prefix_block_bytes
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store.manager import ModelStore

from conftest import async_test
from test_serve_e2e import byte_level_tokenizer_md


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def tp_mesh(tp: int):
    return build_mesh(f"tp={tp}", devices=jax.devices()[:tp])


async def _greedy_batch(params, cfg, prompts, n, mesh=None, **kw):
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                          buckets=[8, 64], mesh=mesh, **kw)
    try:
        async def one(p):
            sp = SamplingParams(temperature=0.0, max_tokens=n)
            return [t async for t in b.submit(p, sp)]

        return await asyncio.gather(*[one(p) for p in prompts])
    finally:
        b.stop()


# -- the tentpole: bit-identical greedy decode across tp widths --------------


@pytest.mark.parametrize("tp", [2, 4])
@async_test
async def test_tp_greedy_matches_tp1(model, tp):
    """tp=2 shards the tiny config's 2 KV heads; tp=4 exceeds them and
    takes the replicated-KV GQA fallback — both must reproduce the
    unsharded batcher's greedy tokens exactly."""
    cfg, params = model
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30, 40, 50]]
    want = await _greedy_batch(params, cfg, prompts, 6)

    mesh = tp_mesh(tp)
    assert kv_replicated(mesh, cfg) == (tp > cfg.n_kv_heads)
    sharded = shard_params(params, mesh, cfg)
    got = await _greedy_batch(sharded, cfg, prompts, 6, mesh=mesh)
    assert got == want


@pytest.mark.parametrize("tp", [2, 4])
@async_test
async def test_tp_prefix_cache_hit_matches_tp1(model, tp):
    """The prefix-cache hit path (cached-block copy-in + suffix prefill)
    runs through the sharded ring: a resent prompt must produce identical
    tokens at tp>1, and the second submit must actually HIT."""
    cfg, params = model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(16)]

    async def run(p, mesh):
        b = ContinuousBatcher(p, cfg, max_slots=2, max_seq_len=64,
                              buckets=[8, 64], prefill_chunk=8,
                              prefix_cache_blocks=8, mesh=mesh)
        try:
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            first = [t async for t in b.submit(prompt, sp)]
            again = [t async for t in b.submit(prompt, sp)]
            hits = b.prefix_cache.counters()["hits"]
            return first, again, hits
        finally:
            b.stop()

    w_first, w_again, _ = await run(params, None)
    mesh = tp_mesh(tp)
    sharded = shard_params(params, mesh, cfg)
    g_first, g_again, hits = await run(sharded, mesh)
    assert g_first == w_first
    assert g_again == w_again
    assert hits >= 1  # the resend took the hit path, not a cold prefill


@pytest.mark.parametrize("tp", [2, 4])
@async_test
async def test_tp_spec_decode_matches_tp1(model, tp):
    """Speculative decoding (positional cache layout + spec_verify jit)
    under tp: drafts verify against sharded K/V and greedy output stays
    exactly the no-spec, no-mesh sequence."""
    cfg, params = model
    prompts = [[5, 6, 7, 8] * 4, [3, 1, 4, 1, 5, 9, 2, 6]]
    want = await _greedy_batch(params, cfg, prompts, 8)

    mesh = tp_mesh(tp)
    sharded = shard_params(params, mesh, cfg)
    got = await _greedy_batch(sharded, cfg, prompts, 8, mesh=mesh,
                              spec_decode_k=4, decode_burst=1)
    assert got == want


# -- mesh knob + validation semantics ----------------------------------------


def test_serving_mesh_semantics():
    n = len(jax.devices())
    assert n >= 8, "conftest must force 8 host devices"
    for off in ("off", "none", "0", "1", "tp=1"):
        assert serving_mesh(off) is None
    auto = serving_mesh("auto")
    assert auto is not None and auto.shape["tp"] == n
    assert serving_mesh("") .shape["tp"] == n
    # single-device hosts serve unsharded under auto
    assert serving_mesh("auto", devices=jax.devices()[:1]) is None
    # explicit specs take the first axis-product devices
    two = serving_mesh("tp=2")
    assert two is not None and dict(two.shape) == {"tp": 2}
    with pytest.raises(ValueError):
        serving_mesh(f"tp={2 * n}")  # more than the host has


def test_validate_mesh_replicated_kv_fallback():
    cfg = ModelConfig.tiny()  # n_heads=4, n_kv_heads=2, d_ff=128
    m2, m4, m8 = tp_mesh(2), tp_mesh(4), tp_mesh(8)
    validate_mesh_for_config(m2, cfg)  # 2 | n_kv_heads: plain sharding
    assert not kv_replicated(m2, cfg)
    validate_mesh_for_config(m4, cfg)  # 4 > n_kv_heads, 4 | n_heads: fallback
    assert kv_replicated(m4, cfg)
    with pytest.raises(ValueError, match="unservable on this mesh"):
        validate_mesh_for_config(m8, cfg)  # 8 does not divide n_heads=4
    # fallback drops tp from the cache heads axis so writes never reshard
    assert cache_spec(m4, cfg)[2] is None
    assert row_cache_spec(m4, cfg)[2] is None
    assert cache_spec(m2, cfg)[2] == "tp"
    assert row_cache_spec(m2, cfg)[2] == "tp"


# -- honest per-device sizing under tp ---------------------------------------


def test_sharded_cache_bytes_divide_by_tp():
    cfg = ModelConfig.tiny()
    whole = estimate_device_bytes(cfg, {}, batch=2, seq_len=64)
    tp2 = estimate_device_bytes(cfg, {"tp": 2}, batch=2, seq_len=64)
    tp4 = estimate_device_bytes(cfg, {"tp": 4}, batch=2, seq_len=64)
    assert tp2["kv_cache"] == whole["kv_cache"] // 2
    # replicated-KV fallback (tp=4 > n_kv_heads=2): cache bytes stay whole
    assert tp4["kv_cache"] == whole["kv_cache"]
    assert tp2["params"] < whole["params"]

    pb1 = prefix_block_bytes(cfg, chunk=8)
    pb2 = prefix_block_bytes(cfg, chunk=8, tp=2)
    kv1 = pb1 - 4 * cfg.vocab_size  # the logits row never shards
    assert pb2 - 4 * cfg.vocab_size == kv1 // 2


# -- registry integration: pull-time gate + sharded load + health ------------


def _publish(models_dir, model_id, cfg, seed=0):
    params = init_params(cfg, jax.random.PRNGKey(seed))
    d = models_dir / model_id
    d.mkdir(parents=True)
    export_params_to_gguf(
        d / "m.gguf", params, cfg, name=model_id,
        tokenizer_md=byte_level_tokenizer_md(cfg.vocab_size),
    )


@async_test
async def test_pull_rejects_unservable_model(tmp_path):
    """A model whose head layout this worker's mesh cannot shard is
    refused at PULL time with a retryable cause-tagged envelope — not a
    crash at the first chat."""
    models = tmp_path / "models"
    cfg = ModelConfig.tiny(n_heads=6, n_kv_heads=2)  # 8 divides neither
    _publish(models, "acme/odd", cfg)
    store = ModelStore(models)
    reg = LocalRegistry(store, dtype="float32", mesh=tp_mesh(8),
                        max_batch_slots=2, max_seq_len=64)

    async def fake_pull(identifier, model_id=None):
        return store.model_dir(identifier, strict=False), "pulled"

    store.pull = fake_pull
    with pytest.raises(EngineError, match="unservable on this mesh"):
        await reg.pull("acme/odd")
    with pytest.raises(EngineError, match="retry on another worker"):
        await reg.pull("acme/odd")
    # a servable model passes the same gate
    _publish(models, "acme/even", ModelConfig.tiny(n_heads=8, n_kv_heads=8),
             seed=1)
    assert await reg.pull("acme/even") == "pulled"


@async_test
async def test_registry_sharded_load_serves_and_reports_mesh(tmp_path):
    """End to end through the registry: a mesh-backed LocalRegistry loads
    the GGUF sharded (load_params_sharded), chats through the sharded
    batcher, and surfaces the mesh shape in engine_health() and stats()."""
    models = tmp_path / "models"
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    _publish(models, "acme/tp", cfg)
    reg = LocalRegistry(ModelStore(models), dtype="float32", mesh=tp_mesh(2),
                        max_batch_slots=2, max_seq_len=64)
    eng = await reg.get_engine("acme/tp")
    try:
        out = await eng.chat(
            {"messages": [{"role": "user", "content": "hi"}],
             "max_tokens": 3, "temperature": 0.0}
        )
        assert out["choices"][0]["message"]["content"] is not None
        health = reg.engine_health()
        assert health["acme/tp"]["mesh"] == {"tp": 2}
        assert reg.stats()["mesh"] == {"tp": 2}
    finally:
        await eng.unload()

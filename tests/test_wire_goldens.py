"""Golden wire-bytes tests: the in-tree parser/serializers against byte
sequences in the exact shapes a real nats-server 2.10.x / nats.go 1.47 session
puts on the wire (VERDICT round-2 missing #4: the binary isn't in this
environment, so wire-compat is pinned by recorded-shape goldens instead —
including the server quirks: trailing space after the INFO JSON, single-quoted
-ERR text, verbose +OK). The live-binary interop test
(test_golden_fixtures.py::test_client_against_real_nats_server) runs wherever
``nats-server`` exists on PATH.

Reference contract: /root/reference/README.md:86-88, 508-562 (clients are
``nats req`` / nats.go — the wire bytes below are what those emit/expect).
"""

import pytest

from nats_llm_studio_tpu.transport import protocol as p

# ---------------------------------------------------------------------------
# recorded server -> client session (nats-server 2.10.12 shapes)
# ---------------------------------------------------------------------------

# real nats-server terminates the INFO JSON with ONE SPACE before CRLF
SERVER_INFO = (
    b'INFO {"server_id":"NDUYLGUUNSD53CLY6BKN2LY7EUGMVGSBB6DMNMCKJLSQZAG2D7RKHELP",'
    b'"server_name":"NDUYLGUUNSD53CLY6BKN2LY7EUGMVGSBB6DMNMCKJLSQZAG2D7RKHELP",'
    b'"version":"2.10.12","proto":1,"git_commit":"121169ea","go":"go1.21.8",'
    b'"host":"0.0.0.0","port":4222,"headers":true,"max_payload":1048576,'
    b'"client_id":5,"client_ip":"127.0.0.1"} \r\n'
)

SERVER_STREAM = (
    SERVER_INFO
    + b"PONG\r\n"
    + b"MSG echo.svc 1 _INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R 2\r\nhi\r\n"
    # headers: "NATS/1.0\r\n" (10) + "Foo: Bar\r\n" (10) + "\r\n" (2) = 22
    + b"HMSG _INBOX.reply 2 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    # no-responders status message: headers only, zero payload
    + b"HMSG _INBOX.reply 2 16 16\r\nNATS/1.0 503\r\n\r\n\r\n"
    + b"+OK\r\n"
    + b"-ERR 'Authorization Violation'\r\n"
)


def _events(stream: bytes, chunk: int):
    parser = p.Parser()
    out = []
    for i in range(0, len(stream), chunk):
        out.extend(parser.feed(stream[i : i + chunk]))
    return out


@pytest.mark.parametrize("chunk", [len(SERVER_STREAM), 64, 1])
def test_parse_recorded_server_stream(chunk):
    """The client-side parser must consume a real server session byte-exactly,
    at any fragmentation (1-byte chunks prove incremental parsing)."""
    evs = _events(SERVER_STREAM, chunk)
    assert [type(e).__name__ for e in evs] == [
        "InfoEvent", "CtrlEvent", "MsgEvent", "MsgEvent", "MsgEvent",
        "CtrlEvent", "ErrEvent",
    ]
    info = evs[0].info
    assert info["version"] == "2.10.12"
    assert info["max_payload"] == 1048576
    assert info["headers"] is True

    assert evs[1].op == "PONG"

    msg = evs[2]
    assert (msg.subject, msg.sid, msg.payload) == ("echo.svc", "1", b"hi")
    assert msg.reply == "_INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R"
    assert msg.headers is None

    hmsg = evs[3]
    assert hmsg.payload == b"hello"
    assert hmsg.headers == {"Foo": "Bar"}

    status = evs[4]
    assert status.payload == b""
    assert status.headers == {"Status": "503"}  # no-responders

    assert evs[5].op == "OK"
    assert evs[6].message == "Authorization Violation"


# ---------------------------------------------------------------------------
# recorded client -> server session (nats.go v1.47 shapes)
# ---------------------------------------------------------------------------

CLIENT_STREAM = (
    b'CONNECT {"verbose":false,"pedantic":false,"tls_required":false,"name":"",'
    b'"lang":"go","version":"1.47.0","protocol":1,"echo":true,"headers":true,'
    b'"no_responders":true}\r\n'
    + b"PING\r\n"
    + b"SUB _INBOX.x7GgaxoLKIuizCqULbRSpj.* 2\r\n"
    + b"SUB lmstudio.chat_model lmstudio-workers 3\r\n"
    + b"PUB lmstudio.list_models _INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R 2\r\n{}\r\n"
    + b"HPUB greet 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    + b"UNSUB 2 1\r\n"
)


@pytest.mark.parametrize("chunk", [len(CLIENT_STREAM), 1])
def test_parse_recorded_client_stream(chunk):
    """The broker-side parser must consume what real nats.go clients send."""
    evs = _events(CLIENT_STREAM, chunk)
    assert [type(e).__name__ for e in evs] == [
        "ConnectEvent", "CtrlEvent", "SubEvent", "SubEvent", "MsgEvent",
        "MsgEvent", "UnsubEvent",
    ]
    assert evs[0].options["lang"] == "go"
    assert evs[0].options["headers"] is True
    assert evs[1].op == "PING"
    assert (evs[2].subject, evs[2].queue, evs[2].sid) == (
        "_INBOX.x7GgaxoLKIuizCqULbRSpj.*", None, "2",
    )
    # queue-group subscribe: the reference's scale-out contract
    # (README.md:478-484) — queue name rides between subject and sid
    assert (evs[3].subject, evs[3].queue, evs[3].sid) == (
        "lmstudio.chat_model", "lmstudio-workers", "3",
    )
    pub = evs[4]
    assert (pub.op, pub.subject, pub.payload) == ("PUB", "lmstudio.list_models", b"{}")
    assert pub.reply == "_INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R"
    hpub = evs[5]
    assert (hpub.op, hpub.payload, hpub.headers) == ("HPUB", b"hello", {"Foo": "Bar"})
    assert (evs[6].sid, evs[6].max_msgs) == ("2", 1)


# ---------------------------------------------------------------------------
# serializer goldens: our bytes must be exactly what a real peer expects
# ---------------------------------------------------------------------------


def test_serializer_golden_bytes():
    assert p.encode_sub("echo.svc", "1") == b"SUB echo.svc 1\r\n"
    assert p.encode_sub("req.*", "2", "workers") == b"SUB req.* workers 2\r\n"
    assert p.encode_unsub("2") == b"UNSUB 2\r\n"
    assert p.encode_unsub("2", 1) == b"UNSUB 2 1\r\n"
    assert p.encode_pub("greet", b"hi") == b"PUB greet 2\r\nhi\r\n"
    assert (
        p.encode_pub("greet", b"hi", reply="_INBOX.a.b")
        == b"PUB greet _INBOX.a.b 2\r\nhi\r\n"
    )
    # HPUB sizes: header block length, then TOTAL (headers + payload)
    assert (
        p.encode_pub("greet", b"hello", headers={"Foo": "Bar"})
        == b"HPUB greet 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    )
    assert (
        p.encode_msg("greet", "9", b"hello", headers={"Foo": "Bar"})
        == b"HMSG greet 9 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    )
    assert p.encode_msg("s", "1", b"") == b"MSG s 1 0\r\n\r\n"
    assert p.encode_err("Slow Consumer") == b"-ERR 'Slow Consumer'\r\n"
    assert p.PING == b"PING\r\n" and p.PONG == b"PONG\r\n" and p.OK == b"+OK\r\n"


# ---------------------------------------------------------------------------
# KV transfer blob goldens (disaggregated prefill/decode, serve/kv_transfer.py)
# ---------------------------------------------------------------------------
#
# The KVX1 byte layout is a cross-worker wire contract: a prefill worker on
# one build must produce bytes a decode worker on another build can import.
# These goldens pin the exact serialization of a dense-bf16 export and a KVQ
# (int8 codes + f32 scales) export; any byte-level change MUST bump the magic
# and regenerate these fixtures (see the module docstring of kv_transfer.py).

GOLDEN_KV_DENSE_BF16 = bytes.fromhex(
    "4b565831a30000007b226368756e6b5f746f6b656e73223a342c226474797065223a2262"
    "666c6f61743136222c226b5f7368617065223a5b312c322c312c342c325d2c226c61796f"
    "7574223a2264656e7365222c226c6f67697473223a5b66616c73652c747275655d2c226e"
    "5f6368756e6b73223a322c22746f6b656e5f696473223a5b312c322c332c342c352c362c"
    "372c385d2c2276657273696f6e223a312c22766f636162223a347d0000003f803fc03f00"
    "4020404040604080409040a040b040c040d040e040f040803fc03f004020404040604080"
    "409040a040b040c040d040e040f04000410841004020404040604080409040a040b040c0"
    "40d040e040f04000410841104118414040604080409040a040b040c040d040e040f04000"
    "41084110411841204128410000003e0000c0bf000040400000403f"
)

GOLDEN_KV_KVQ_INT8 = bytes.fromhex(
    "4b565831bb0000007b226368756e6b5f746f6b656e73223a342c226474797065223a2269"
    "6e7438222c226b5f7368617065223a5b312c322c312c342c325d2c226c61796f7574223a"
    "226b7671222c226c6f67697473223a5b747275655d2c226e5f6368756e6b73223a312c22"
    "735f7368617065223a5b312c322c312c345d2c227363616c655f6474797065223a22666c"
    "6f61743332222c22746f6b656e5f696473223a5b352c362c372c385d2c2276657273696f"
    "6e223a312c22766f636162223a327df8f9fafbfcfdfeff00010203040506070000003f00"
    "00403f0000803f0000a03f0000c03f0000e03f0000004000001040f9fafbfcfdfeff0001"
    "020304050607080000003f0000403f0000803f0000a03f0000c03f0000e03f0000004000"
    "00104000000040000000bf"
)


def _golden_dense_export():
    import ml_dtypes
    import numpy as np

    bf16 = np.dtype(ml_dtypes.bfloat16)

    def leaf(seed):
        return (
            np.arange(16, dtype=np.float32).reshape(1, 2, 1, 4, 2) * 0.5 + seed
        ).astype(bf16)

    return {
        "token_ids": list(range(1, 9)),
        "chunk_tokens": 4,
        "chunks": [
            {"k": leaf(0.0), "v": leaf(1.0), "logits": None},
            {"k": leaf(2.0), "v": leaf(3.0),
             "logits": np.array([0.125, -1.5, 3.0, 0.75], dtype=np.float32)},
        ],
    }


def _golden_kvq_export():
    import numpy as np

    def leaf(seed):
        q = (
            np.arange(16, dtype=np.int16).reshape(1, 2, 1, 4, 2) - 8 + seed
        ).astype(np.int8)
        s = np.arange(8, dtype=np.float32).reshape(1, 2, 1, 4) * 0.25 + 0.5
        return (q, s)

    return {
        "token_ids": [5, 6, 7, 8],
        "chunk_tokens": 4,
        "chunks": [
            {"k": leaf(0), "v": leaf(1),
             "logits": np.array([2.0, -0.5], dtype=np.float32)},
        ],
    }


@pytest.mark.parametrize(
    "build,golden",
    [
        (_golden_dense_export, GOLDEN_KV_DENSE_BF16),
        (_golden_kvq_export, GOLDEN_KV_KVQ_INT8),
    ],
    ids=["dense-bf16", "kvq-int8"],
)
def test_kv_blob_golden_bytes(build, golden):
    """Byte-exact serialization of the two KV layouts a transfer can carry."""
    from nats_llm_studio_tpu.serve.kv_transfer import encode_kv_blob

    blob = encode_kv_blob(build())
    assert blob[:4] == b"KVX1"
    assert blob == golden


@pytest.mark.parametrize(
    "build,golden",
    [
        (_golden_dense_export, GOLDEN_KV_DENSE_BF16),
        (_golden_kvq_export, GOLDEN_KV_KVQ_INT8),
    ],
    ids=["dense-bf16", "kvq-int8"],
)
def test_kv_blob_golden_decodes(build, golden):
    """The pinned golden bytes decode back to the source arrays bit-exactly
    (a FUTURE build must keep decoding blobs shipped by this one)."""
    import numpy as np

    from nats_llm_studio_tpu.serve.kv_transfer import decode_kv_blob

    want = build()
    got = decode_kv_blob(golden)
    assert got["token_ids"] == want["token_ids"]
    assert got["chunk_tokens"] == want["chunk_tokens"]
    assert len(got["chunks"]) == len(want["chunks"])
    for gc, wc in zip(got["chunks"], want["chunks"]):
        for name in ("k", "v"):
            if isinstance(wc[name], tuple):
                assert np.array_equal(gc[name][0], wc[name][0])
                assert np.array_equal(gc[name][1], wc[name][1])
                assert gc[name][0].dtype == wc[name][0].dtype
            else:
                assert gc[name].dtype == wc[name].dtype
                assert np.array_equal(
                    gc[name].view(np.uint16), wc[name].view(np.uint16)
                )
        if wc["logits"] is None:
            assert gc["logits"] is None
        else:
            assert np.array_equal(gc["logits"], wc["logits"])


def test_kv_blob_rejects_corruption():
    """Malformed blobs must raise KVTransferFormatError, never import."""
    from nats_llm_studio_tpu.serve.kv_transfer import (
        KVTransferFormatError,
        decode_kv_blob,
    )

    good = GOLDEN_KV_DENSE_BF16
    with pytest.raises(KVTransferFormatError):
        decode_kv_blob(b"NOPE" + good[4:])  # bad magic
    with pytest.raises(KVTransferFormatError):
        decode_kv_blob(good[:-3])  # truncated body
    with pytest.raises(KVTransferFormatError):
        decode_kv_blob(good + b"\x00")  # trailing bytes
    with pytest.raises(KVTransferFormatError):
        # header length pointing past the end of the blob
        decode_kv_blob(good[:4] + b"\xff\xff\xff\x7f" + good[8:])


def test_serializer_roundtrip_through_parser():
    """Everything we emit must parse back identically (self-consistency on
    top of the golden shapes)."""
    stream = (
        p.encode_connect({"verbose": False, "headers": True})
        + p.PING
        + p.encode_sub("a.b", "1", "grp")
        + p.encode_pub("a.b", b"payload", reply="r.1", headers={"K": "V"})
        + p.encode_unsub("1", 5)
    )
    evs = _events(stream, 1)
    kinds = [type(e).__name__ for e in evs]
    assert kinds == ["ConnectEvent", "CtrlEvent", "SubEvent", "MsgEvent", "UnsubEvent"]
    assert evs[3].payload == b"payload" and evs[3].headers == {"K": "V"}

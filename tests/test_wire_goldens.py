"""Golden wire-bytes tests: the in-tree parser/serializers against byte
sequences in the exact shapes a real nats-server 2.10.x / nats.go 1.47 session
puts on the wire (VERDICT round-2 missing #4: the binary isn't in this
environment, so wire-compat is pinned by recorded-shape goldens instead —
including the server quirks: trailing space after the INFO JSON, single-quoted
-ERR text, verbose +OK). The live-binary interop test
(test_golden_fixtures.py::test_client_against_real_nats_server) runs wherever
``nats-server`` exists on PATH.

Reference contract: /root/reference/README.md:86-88, 508-562 (clients are
``nats req`` / nats.go — the wire bytes below are what those emit/expect).
"""

import pytest

from nats_llm_studio_tpu.transport import protocol as p

# ---------------------------------------------------------------------------
# recorded server -> client session (nats-server 2.10.12 shapes)
# ---------------------------------------------------------------------------

# real nats-server terminates the INFO JSON with ONE SPACE before CRLF
SERVER_INFO = (
    b'INFO {"server_id":"NDUYLGUUNSD53CLY6BKN2LY7EUGMVGSBB6DMNMCKJLSQZAG2D7RKHELP",'
    b'"server_name":"NDUYLGUUNSD53CLY6BKN2LY7EUGMVGSBB6DMNMCKJLSQZAG2D7RKHELP",'
    b'"version":"2.10.12","proto":1,"git_commit":"121169ea","go":"go1.21.8",'
    b'"host":"0.0.0.0","port":4222,"headers":true,"max_payload":1048576,'
    b'"client_id":5,"client_ip":"127.0.0.1"} \r\n'
)

SERVER_STREAM = (
    SERVER_INFO
    + b"PONG\r\n"
    + b"MSG echo.svc 1 _INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R 2\r\nhi\r\n"
    # headers: "NATS/1.0\r\n" (10) + "Foo: Bar\r\n" (10) + "\r\n" (2) = 22
    + b"HMSG _INBOX.reply 2 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    # no-responders status message: headers only, zero payload
    + b"HMSG _INBOX.reply 2 16 16\r\nNATS/1.0 503\r\n\r\n\r\n"
    + b"+OK\r\n"
    + b"-ERR 'Authorization Violation'\r\n"
)


def _events(stream: bytes, chunk: int):
    parser = p.Parser()
    out = []
    for i in range(0, len(stream), chunk):
        out.extend(parser.feed(stream[i : i + chunk]))
    return out


@pytest.mark.parametrize("chunk", [len(SERVER_STREAM), 64, 1])
def test_parse_recorded_server_stream(chunk):
    """The client-side parser must consume a real server session byte-exactly,
    at any fragmentation (1-byte chunks prove incremental parsing)."""
    evs = _events(SERVER_STREAM, chunk)
    assert [type(e).__name__ for e in evs] == [
        "InfoEvent", "CtrlEvent", "MsgEvent", "MsgEvent", "MsgEvent",
        "CtrlEvent", "ErrEvent",
    ]
    info = evs[0].info
    assert info["version"] == "2.10.12"
    assert info["max_payload"] == 1048576
    assert info["headers"] is True

    assert evs[1].op == "PONG"

    msg = evs[2]
    assert (msg.subject, msg.sid, msg.payload) == ("echo.svc", "1", b"hi")
    assert msg.reply == "_INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R"
    assert msg.headers is None

    hmsg = evs[3]
    assert hmsg.payload == b"hello"
    assert hmsg.headers == {"Foo": "Bar"}

    status = evs[4]
    assert status.payload == b""
    assert status.headers == {"Status": "503"}  # no-responders

    assert evs[5].op == "OK"
    assert evs[6].message == "Authorization Violation"


# ---------------------------------------------------------------------------
# recorded client -> server session (nats.go v1.47 shapes)
# ---------------------------------------------------------------------------

CLIENT_STREAM = (
    b'CONNECT {"verbose":false,"pedantic":false,"tls_required":false,"name":"",'
    b'"lang":"go","version":"1.47.0","protocol":1,"echo":true,"headers":true,'
    b'"no_responders":true}\r\n'
    + b"PING\r\n"
    + b"SUB _INBOX.x7GgaxoLKIuizCqULbRSpj.* 2\r\n"
    + b"SUB lmstudio.chat_model lmstudio-workers 3\r\n"
    + b"PUB lmstudio.list_models _INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R 2\r\n{}\r\n"
    + b"HPUB greet 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    + b"UNSUB 2 1\r\n"
)


@pytest.mark.parametrize("chunk", [len(CLIENT_STREAM), 1])
def test_parse_recorded_client_stream(chunk):
    """The broker-side parser must consume what real nats.go clients send."""
    evs = _events(CLIENT_STREAM, chunk)
    assert [type(e).__name__ for e in evs] == [
        "ConnectEvent", "CtrlEvent", "SubEvent", "SubEvent", "MsgEvent",
        "MsgEvent", "UnsubEvent",
    ]
    assert evs[0].options["lang"] == "go"
    assert evs[0].options["headers"] is True
    assert evs[1].op == "PING"
    assert (evs[2].subject, evs[2].queue, evs[2].sid) == (
        "_INBOX.x7GgaxoLKIuizCqULbRSpj.*", None, "2",
    )
    # queue-group subscribe: the reference's scale-out contract
    # (README.md:478-484) — queue name rides between subject and sid
    assert (evs[3].subject, evs[3].queue, evs[3].sid) == (
        "lmstudio.chat_model", "lmstudio-workers", "3",
    )
    pub = evs[4]
    assert (pub.op, pub.subject, pub.payload) == ("PUB", "lmstudio.list_models", b"{}")
    assert pub.reply == "_INBOX.x7GgaxoLKIuizCqULbRSpj.szcGXj1R"
    hpub = evs[5]
    assert (hpub.op, hpub.payload, hpub.headers) == ("HPUB", b"hello", {"Foo": "Bar"})
    assert (evs[6].sid, evs[6].max_msgs) == ("2", 1)


# ---------------------------------------------------------------------------
# serializer goldens: our bytes must be exactly what a real peer expects
# ---------------------------------------------------------------------------


def test_serializer_golden_bytes():
    assert p.encode_sub("echo.svc", "1") == b"SUB echo.svc 1\r\n"
    assert p.encode_sub("req.*", "2", "workers") == b"SUB req.* workers 2\r\n"
    assert p.encode_unsub("2") == b"UNSUB 2\r\n"
    assert p.encode_unsub("2", 1) == b"UNSUB 2 1\r\n"
    assert p.encode_pub("greet", b"hi") == b"PUB greet 2\r\nhi\r\n"
    assert (
        p.encode_pub("greet", b"hi", reply="_INBOX.a.b")
        == b"PUB greet _INBOX.a.b 2\r\nhi\r\n"
    )
    # HPUB sizes: header block length, then TOTAL (headers + payload)
    assert (
        p.encode_pub("greet", b"hello", headers={"Foo": "Bar"})
        == b"HPUB greet 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    )
    assert (
        p.encode_msg("greet", "9", b"hello", headers={"Foo": "Bar"})
        == b"HMSG greet 9 22 27\r\nNATS/1.0\r\nFoo: Bar\r\n\r\nhello\r\n"
    )
    assert p.encode_msg("s", "1", b"") == b"MSG s 1 0\r\n\r\n"
    assert p.encode_err("Slow Consumer") == b"-ERR 'Slow Consumer'\r\n"
    assert p.PING == b"PING\r\n" and p.PONG == b"PONG\r\n" and p.OK == b"+OK\r\n"


def test_serializer_roundtrip_through_parser():
    """Everything we emit must parse back identically (self-consistency on
    top of the golden shapes)."""
    stream = (
        p.encode_connect({"verbose": False, "headers": True})
        + p.PING
        + p.encode_sub("a.b", "1", "grp")
        + p.encode_pub("a.b", b"payload", reply="r.1", headers={"K": "V"})
        + p.encode_unsub("1", 5)
    )
    evs = _events(stream, 1)
    kinds = [type(e).__name__ for e in evs]
    assert kinds == ["ConnectEvent", "CtrlEvent", "SubEvent", "MsgEvent", "UnsubEvent"]
    assert evs[3].payload == b"payload" and evs[3].headers == {"K": "V"}

"""The driver-visible bench's end-to-end NATS mode must keep working: it is
the artifact that records TTFT/throughput each round. Smoke it at tiny scale
on the CPU backend."""

import sys
from pathlib import Path

import jax
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent))


def test_e2e_nats_bench_smoke():
    import bench
    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import ensure_lm_head, init_params

    cfg = ModelConfig.tiny(vocab_size=300, n_layers=2, max_seq_len=256)
    params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
    out = bench.e2e_nats_bench(cfg, params, "bench/tiny", clients_a=2, clients_b=2)
    assert set(out) >= {"ttft_p50_ms", "ttft_p95_ms", "e2e_tok_s",
                        "ttft_clients", "e2e_tok_s_clients", "transport_rt_ms"}
    assert out["ttft_clients"] == 2 and out["e2e_tok_s_clients"] == 2
    assert out["ttft_p50_ms"] > 0 and out["e2e_tok_s"] > 0
    # per-phase occupancy + queue-delay + parse-failure fields exist
    assert out["throughput_wave"]["parse_failures"] == 0
    assert "tokens_per_step_avg" in out["throughput_wave"]["batcher_phase"]
    assert "admit_queue_delay_p95_ms" in out["throughput_wave"]["batcher_phase"]
    # round-5 phases: ring-compaction recovery + bounded-overload shedding
    ring = out["ring_compaction"]
    assert ring["parse_failures"] == 0
    assert {"ring_compactions", "survivor_gap_post_roll_p50_ms"} <= set(ring)
    ov = out["overload"]
    assert ov["completed"] >= 1
    assert "admit_queue_delay_p95_ms" in ov["batcher_phase"]
    assert "batcher_shed_total" in ov and "sheds_observed_by_clients" in ov
    # bounds were restored after the overload phase
    assert "shed" in out["batcher"] and "cancelled" in out["batcher"]


def test_moe_bench_smoke():
    """The MoE routed-vs-dense ablation path must run (tiny geometry on
    CPU); speedup ratios are reported, both dispatch forms measured."""
    import bench
    from nats_llm_studio_tpu.models.config import ModelConfig

    cfg = ModelConfig.tiny(
        n_experts=4, n_experts_used=2, d_ff=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, dtype="bfloat16",
    )
    out = bench.moe_bench(cfg=cfg, batch=2, prompt_len=8, seq_len=64, steps=4)
    assert out["routed"]["tok_s"] > 0 and out["dense"]["tok_s"] > 0
    assert out["routed_decode_speedup"] > 0
    assert out["routed_prefill_speedup"] > 0
    assert out["geometry"]["n_experts"] == 4
    assert out["prefill_deep"]["routed"] > 0 and out["prefill_deep"]["dense"] > 0
    assert out["prefill_deep"]["routed_speedup"] > 0
    # round-5: small-batch ablation + measured capacity-overflow drop rates
    small = out["small_batch"]
    assert small["b1"]["routed_tok_s"] > 0 and small["b4"]["dense_tok_s"] > 0
    assert 0.0 <= small["drop_fraction"]["decode_b1"] <= 1.0
    assert "prefill_4x128" in small["drop_fraction"]


def test_obs_overhead_bench_smoke():
    """The flight-recorder overhead phase must run at tiny scale: both arms
    measured, the recorder-on arm actually sampled frames, and the noise-
    floor-guarded overhead bound held (the phase asserts it internally)."""
    import bench
    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import ensure_lm_head, init_params

    cfg = ModelConfig.tiny(vocab_size=300, n_layers=2, max_seq_len=256)
    params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
    out = bench.obs_overhead_bench(
        cfg, params, seq=128, slots=2, n_reqs=2, max_new=12, rounds=2
    )
    assert out["frames_sampled"] > 0
    assert len(out["off_tok_s"]) == 2 and len(out["on_tok_s"]) == 2
    assert out["off_median_tok_s"] > 0 and out["on_median_tok_s"] > 0
    assert out["overhead_pct"] < max(1.0, out["noise_floor_pct"])


def test_e2e_long_context_bench_smoke(monkeypatch):
    """The long-context serving wave (VERDICT r3 missing #1) at tiny scale:
    real prompt_tokens come back from usage, interference gaps and
    per-phase batcher stats are recorded."""
    import bench
    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import ensure_lm_head, init_params

    monkeypatch.setenv("BENCH_LONG_SEQ", "256")
    monkeypatch.setenv("BENCH_LONG_SLOTS", "4")
    monkeypatch.setenv("BENCH_LONG_CHUNK", "32")
    cfg = ModelConfig.tiny(vocab_size=300, n_layers=2, max_seq_len=256)
    params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
    monkeypatch.setenv("BENCH_XL_SEQ", "256")
    out = bench.e2e_long_context_bench(
        cfg, params, "bench/tiny", n_long=2, long_tokens=150, xl_tokens=200
    )
    lw = out["long_wave"]
    # prompt token counts are MEASURED (usage block), >= the requested size
    assert lw["prompt_tokens_each"] >= 150
    assert out["xl_single"]["prompt_tokens"] >= 200
    assert lw["parse_failures"] == 0
    assert lw["ttft_p50_ms"] > 0 and lw["prefill_tok_s"] > 0
    assert lw["interference_gap_p95_ms"] >= lw["interference_gap_p50_ms"] >= 0
    assert lw["batcher_phase"]["tokens"] > 0

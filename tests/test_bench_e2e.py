"""The driver-visible bench's end-to-end NATS mode must keep working: it is
the artifact that records TTFT/throughput each round. Smoke it at tiny scale
on the CPU backend."""

import sys
from pathlib import Path

import jax
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent))


def test_e2e_nats_bench_smoke():
    import bench
    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import ensure_lm_head, init_params

    cfg = ModelConfig.tiny(vocab_size=300, n_layers=2, max_seq_len=256)
    params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
    out = bench.e2e_nats_bench(cfg, params, "bench/tiny", clients_a=2, clients_b=2)
    assert set(out) >= {"ttft_p50_ms", "ttft_p95_ms", "e2e_tok_s",
                        "ttft_clients", "e2e_tok_s_clients", "transport_rt_ms"}
    assert out["ttft_clients"] == 2 and out["e2e_tok_s_clients"] == 2
    assert out["ttft_p50_ms"] > 0 and out["e2e_tok_s"] > 0

"""Disaggregated prefill/decode serving (ISSUE 13 tentpole).

A prefill-role worker exports a prompt's finished paged-KV blocks (device ->
host, dense bf16/f32 or KVQ codes+scales, with the chunk-end logits) and a
decode-role peer imports them into its own block pool + radix prefix cache,
so the chat decodes from a (partial or full) prefix hit with no repeated
prefill work. The acceptance bar everywhere in this file is BIT-IDENTITY:
greedy output through a transferred prefill must equal greedy output with
local prefill, through the live batcher — the transfer is an optimization,
never a numerics fork.

Layers covered:
* batcher level: export -> KVX1 blob -> import round trips (dense, KVQ int8,
  and import into a tp=2-sharded pool on the 8 forced host devices)
* worker level: the two-hop ``X-KV-Prefill-Worker`` pull between two real
  engines, transfer-failure fallback to local prefill (bogus peer), and a
  seeded mid-transfer worker-death sever (transport/faults.py)
"""

import asyncio
import json

import jax
import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.sharding import shard_params
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.serve.kv_transfer import decode_kv_blob, encode_kv_blob
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store.manager import ModelStore
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect, faults
from nats_llm_studio_tpu.transport import protocol as p

from conftest import async_test
from test_serve_e2e import byte_level_tokenizer_md

MID = "acme/tiny-disagg"


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _batcher(params, cfg, mesh=None, **kw):
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("prefix_cache_blocks", 16)
    return ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                             buckets=[8, 64], mesh=mesh, paged=True, **kw)


async def _greedy(b, prompt, n=10):
    sp = SamplingParams(temperature=0.0, max_tokens=n)
    return [t async for t in b.submit(list(prompt), sp)]


# -- batcher-level round trips ------------------------------------------------


@async_test
async def test_transfer_roundtrip_bit_identity(model):
    """Export from batcher A -> wire blob -> import into batcher B: B's
    greedy output must be bit-identical to A's, and B must serve the prompt
    as a FULL prefix hit (zero local prefill — the tentpole claim)."""
    cfg, params = model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(16)]  # 2 chunks of 8
    a, b = _batcher(params, cfg), _batcher(params, cfg)
    try:
        want = await _greedy(a, prompt)
        export = await asyncio.to_thread(a.export_prefix_blocks, prompt)
        assert export is not None
        assert export["token_ids"] == prompt
        assert len(export["chunks"]) == 2
        blob = encode_kv_blob(export)
        imported = await asyncio.to_thread(
            b.import_prefix_blocks, decode_kv_blob(blob)
        )
        assert imported["tokens"] == 16
        got = await _greedy(b, prompt)
        assert got == want
        assert b.prefix_cache.counters()["full_hits"] >= 1
    finally:
        a.stop()
        b.stop()


@async_test
async def test_transfer_roundtrip_kvq():
    """KVQ layout: int8 codes + f32 scales ship verbatim, so the importing
    batcher decodes the same quantized cache bit-for-bit."""
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128, kv_quant="int8")
    params = init_params(cfg.with_(kv_quant="none"), jax.random.PRNGKey(2))
    prompt = [(i * 5 + 1) % cfg.vocab_size for i in range(16)]
    a, b = _batcher(params, cfg), _batcher(params, cfg)
    try:
        want = await _greedy(a, prompt)
        export = await asyncio.to_thread(a.export_prefix_blocks, prompt)
        assert export is not None
        k0 = export["chunks"][0]["k"]
        assert isinstance(k0, tuple)  # (codes, scales): the KVQ layout
        blob = encode_kv_blob(export)
        assert b'"layout":"kvq"' in blob[:256]
        imported = await asyncio.to_thread(
            b.import_prefix_blocks, decode_kv_blob(blob)
        )
        assert imported["tokens"] == 16
        got = await _greedy(b, prompt)
        assert got == want
    finally:
        a.stop()
        b.stop()


@async_test
async def test_transfer_import_into_tp2_pool(model):
    """Import into a tensor-parallel (tp=2 on forced host devices) batcher:
    the re-pinned sharded pool decodes the transferred prefill to the same
    greedy tokens as the unsharded exporter."""
    cfg, params = model
    prompt = [(i * 11 + 2) % cfg.vocab_size for i in range(16)]
    a = _batcher(params, cfg)
    mesh = build_mesh("tp=2", devices=jax.devices()[:2])
    b = _batcher(shard_params(params, mesh, cfg), cfg, mesh=mesh)
    try:
        want = await _greedy(a, prompt)
        export = await asyncio.to_thread(a.export_prefix_blocks, prompt)
        assert export is not None
        blob = encode_kv_blob(export)
        imported = await asyncio.to_thread(
            b.import_prefix_blocks, decode_kv_blob(blob)
        )
        assert imported["tokens"] == 16
        got = await _greedy(b, prompt)
        assert got == want
    finally:
        a.stop()
        b.stop()


@async_test
async def test_export_guards(model):
    """Short prompts (< one prefill chunk) and cache-less batchers export
    None — the worker layer turns that into a graceful no_export reply."""
    cfg, params = model
    b = _batcher(params, cfg)
    plain = _batcher(params, cfg, prefix_cache_blocks=0)
    try:
        await _greedy(b, [1, 2, 3], n=2)
        assert await asyncio.to_thread(b.export_prefix_blocks, [1, 2, 3]) is None
        # nothing prefilled for this prompt either: still a clean None after
        # the engine-level export path runs its own prefill (engine test
        # below); at batcher level a cold cache means no covered chunks
        assert await asyncio.to_thread(
            plain.export_prefix_blocks, list(range(16))
        ) is None
    finally:
        b.stop()
        plain.stop()


@async_test
async def test_import_rejects_mismatched_chunk_tokens(model):
    """An export produced under a different prefill_chunk must be refused —
    its blocks would misalign with this pool's chunk-trie."""
    cfg, params = model
    prompt = [(i * 3 + 1) % cfg.vocab_size for i in range(16)]
    a = _batcher(params, cfg)
    b = _batcher(params, cfg, prefill_chunk=16)
    try:
        await _greedy(a, prompt, n=2)
        export = await asyncio.to_thread(a.export_prefix_blocks, prompt)
        assert export is not None and export["chunk_tokens"] == 8
        with pytest.raises(ValueError, match="prefill-chunk mismatch"):
            await asyncio.to_thread(b.import_prefix_blocks, export)
    finally:
        a.stop()
        b.stop()


# -- worker-level two-hop -----------------------------------------------------


def _publish_tiny(models_dir, model_id=MID, seed=7):
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    d = models_dir / model_id
    d.mkdir(parents=True, exist_ok=True)
    export_params_to_gguf(
        d / "m.gguf", params, cfg, name=model_id,
        tokenizer_md=byte_level_tokenizer_md(cfg.vocab_size),
    )


def _registry(models):
    return LocalRegistry(
        ModelStore(models), dtype="float32", max_batch_slots=2,
        max_seq_len=64, prefill_chunk=8, prefix_cache_blocks=16,
    )


def _chat_body(text, max_tokens=8):
    return json.dumps({
        "model": MID,
        "messages": [{"role": "user", "content": text}],
        "max_tokens": max_tokens,
        "temperature": 0.0,
    }).encode()


@async_test
async def test_worker_two_hop_transfer_bit_identity(tmp_path):
    """The full disaggregated hop: a chat steered at the decode worker with
    ``X-KV-Prefill-Worker`` pulls KV from the prefill worker (which runs the
    prefill), and the response is bit-identical to serving the same body
    with local prefill. Role and transfer families land on health +
    Prometheus."""
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        wp = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-prefill",
                         worker_role="prefill"),
            _registry(models),
        )
        wd = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-decode",
                         worker_role="decode"),
            _registry(models),
        )
        await wp.start()
        await wd.start()
        nc = await connect(broker.url)
        body = _chat_body("move my kv blocks over")
        msg = await nc.request(
            "lmstudio.worker.w-decode.chat_model", body, timeout=60,
            headers={p.KV_PREFILL_HEADER: "w-prefill"},
        )
        env = json.loads(msg.payload)
        assert env["ok"] is True, env
        got = env["data"]["response"]["choices"][0]["message"]["content"]
        assert wd._kv_transfer_failures == 0
        assert wd._kv_transfer_bytes["import"] > 0
        assert wp._kv_transfer_bytes["export"] == wd._kv_transfer_bytes["import"]
        # local-prefill baseline: the prefill worker already holds this
        # prompt's cache, so serving there IS the local-prefill answer
        msg2 = await nc.request(
            "lmstudio.worker.w-prefill.chat_model", body, timeout=60
        )
        env2 = json.loads(msg2.payload)
        assert env2["ok"] is True, env2
        want = env2["data"]["response"]["choices"][0]["message"]["content"]
        assert got == want
        # role everywhere it should be: health, advert, exposition
        health = json.loads((await nc.request(
            "lmstudio.worker.w-decode.health", b"", timeout=10)).payload)
        assert health["data"]["role"] == "decode"
        assert wp.build_advert()["role"] == "prefill"
        prom = (await nc.request(
            "lmstudio.worker.w-decode.metrics.prom", b"", timeout=10
        )).payload.decode()
        assert 'role="decode"' in prom
        assert "lmstudio_kv_transfer_bytes_total" in prom
        assert "lmstudio_kv_transfer_failures_total" in prom
        await nc.close()
        await wp.drain()
        await wd.drain()
    finally:
        await broker.stop()


@async_test
async def test_transfer_failure_falls_back_to_local_prefill(tmp_path):
    """A bogus prefill peer (nobody on that subject) must cost one counted
    transfer failure and a short stall — never the request: the decode
    worker prefills locally and serves the identical greedy output."""
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        wd = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-decode",
                         worker_role="decode", kv_transfer_timeout_s=0.3),
            _registry(models),
        )
        await wd.start()
        nc = await connect(broker.url)
        body = _chat_body("serve me anyway")
        msg = await nc.request(
            "lmstudio.worker.w-decode.chat_model", body, timeout=60,
            headers={p.KV_PREFILL_HEADER: "w-ghost"},
        )
        env = json.loads(msg.payload)
        assert env["ok"] is True, env
        got = env["data"]["response"]["choices"][0]["message"]["content"]
        assert wd._kv_transfer_failures == 1
        # identical to a plain serve of the same body (local prefill both
        # times; the second is a prefix-cache hit)
        msg2 = await nc.request(
            "lmstudio.worker.w-decode.chat_model", body, timeout=60
        )
        env2 = json.loads(msg2.payload)
        assert env2["ok"] is True
        assert env2["data"]["response"]["choices"][0]["message"]["content"] == got
        prom = (await nc.request(
            "lmstudio.worker.w-decode.metrics.prom", b"", timeout=10
        )).payload.decode()
        assert "lmstudio_kv_transfer_failures_total" in prom
        await nc.close()
        await wd.drain()
    finally:
        await broker.stop()


@async_test
async def test_prefill_death_mid_transfer_falls_back(tmp_path):
    """Seeded chaos: the prefill worker's connection is severed on its 3rd
    inbox publish — mid-blob, with small transfer chunks forcing many
    publishes. The decode worker's pull idles out, counts one failure, and
    the chat is still served correctly by local prefill."""
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        wp = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-prefill",
                         worker_role="prefill", kv_transfer_chunk_bytes=2048,
                         max_reconnects=0),
            _registry(models),
        )
        wd = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-decode",
                         worker_role="decode", kv_transfer_timeout_s=1.0),
            _registry(models),
        )
        await wp.start()
        await wd.start()
        nc = await connect(broker.url)
        plan = faults.install(
            faults.FaultPlan(seed=5).sever_worker(
                "w-prefill", step=2, subject="_INBOX.>"
            )
        )
        try:
            msg = await nc.request(
                "lmstudio.worker.w-decode.chat_model",
                _chat_body("survive the severed prefill worker"), timeout=60,
                headers={p.KV_PREFILL_HEADER: "w-prefill"},
            )
        finally:
            faults.clear()
        env = json.loads(msg.payload)
        assert env["ok"] is True, env
        assert env["data"]["response"]["choices"][0]["message"]["content"]
        assert plan.done()  # the sever really fired mid-transfer
        assert wd._kv_transfer_failures == 1
        await nc.close()
        await wd.drain()
        await wp.drain()
    finally:
        await broker.stop()

"""Test configuration.

JAX runs on the CPU backend with 8 virtual devices so TP/EP/DP sharding logic
is exercised multi-"device" on one host (SURVEY.md §4.3) — must be set before
jax is first imported anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env pins the real TPU tunnel
# persistent compile cache: CPU-backend jit of the scan'd models dominates
# suite runtime otherwise
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# jax is pre-imported by the interpreter in this image, so env vars alone are
# too late — override through the config API as well (before first backend use)
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import asyncio
import functools

import pytest


def async_test(fn):
    """Run an async test via asyncio.run (no pytest-asyncio in this image)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=60.0))

    return wrapper


@pytest.fixture
def tmp_models_dir(tmp_path):
    d = tmp_path / "models"
    d.mkdir()
    return d

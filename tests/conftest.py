"""Test configuration.

JAX runs on the CPU backend with 8 virtual devices so TP/EP/DP sharding logic
is exercised multi-"device" on one host (SURVEY.md §4.3) — must be set before
jax is first imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import asyncio
import functools

import pytest


def async_test(fn):
    """Run an async test via asyncio.run (no pytest-asyncio in this image)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=60.0))

    return wrapper


@pytest.fixture
def tmp_models_dir(tmp_path):
    d = tmp_path / "models"
    d.mkdir()
    return d

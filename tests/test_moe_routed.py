"""Routed (sparse) MoE dispatch vs the dense reference (VERDICT.md item 4).

With a generous capacity factor no token drops, so routed output must equal
dense-dispatch output (same math, different data movement) — off-mesh and
expert-parallel over the 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import (
    _moe_ffn,
    forward,
    init_params,
    make_cache,
)
from nats_llm_studio_tpu.parallel.moe import _capacity, _route, routed_moe_ffn


def _cfg(**kw):
    base = dict(n_experts=8, n_experts_used=2, d_ff=32, n_layers=2,
                moe_capacity_factor=8.0)
    base.update(kw)
    return ModelConfig.tiny(**base)


def _layer_params(cfg, key):
    """One layer's MoE params (strip the [L] stack axis)."""
    p = init_params(cfg, key)["blocks"]
    return {k: v[0] for k, v in p.items() if k in
            ("router", "w_gate_e", "w_up_e", "w_down_e")}


def test_routed_matches_dense_single_shard():
    cfg = _cfg()
    p = _layer_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 5, cfg.d_model), jnp.float32)
    want = _moe_ffn(x, p, cfg)
    got = routed_moe_ffn(x, p, cfg, mesh=None, capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_routed_matches_dense_on_ep_mesh():
    from nats_llm_studio_tpu.parallel import build_mesh
    from nats_llm_studio_tpu.parallel.sharding import shard_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 3, cfg.d_model), jnp.float32)
    p = {k: v[0] for k, v in params["blocks"].items() if k in
         ("router", "w_gate_e", "w_up_e", "w_down_e")}
    want = _moe_ffn(x, p, cfg)

    mesh = build_mesh({"ep": 8}, jax.devices()[:8])
    sharded = shard_params(params, mesh)["blocks"]
    p_sh = {k: jax.tree.map(lambda a: a[0], sharded[k]) for k in p}
    got = jax.jit(
        lambda x, p: routed_moe_ffn(x, p, cfg, mesh=mesh, capacity_factor=8.0)
    )(x, p_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_routed_full_model_forward_matches_dense():
    cfg_d = _cfg()
    cfg_r = cfg_d.with_(use_routed_moe=True)
    params = init_params(cfg_d, jax.random.PRNGKey(4))
    toks = jnp.asarray([[3, 1, 4, 1, 5]], jnp.int32)
    zero = jnp.zeros((1,), jnp.int32)
    k, v = make_cache(cfg_d, 1, 16)
    want, _, _ = forward(params, cfg_d, toks, k, v, zero)
    k, v = make_cache(cfg_r, 1, 16)
    got, _, _ = forward(params, cfg_r, toks, k, v, zero)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_routed_int8_runs():
    from nats_llm_studio_tpu.ops.wquant import quantize_params

    cfg = _cfg(use_routed_moe=True)
    params = init_params(cfg, jax.random.PRNGKey(5))
    q = jax.tree.map(jnp.asarray, quantize_params(jax.tree.map(np.asarray, params)))
    k, v = make_cache(cfg, 1, 16)
    logits, _, _ = forward(q, cfg, jnp.ones((1, 4), jnp.int32), k, v,
                           jnp.zeros((1,), jnp.int32))
    assert logits.shape == (1, 4, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_capacity_overflow_drops_not_crashes():
    """With capacity factor << 1 every token competes for one slot per
    expert; output must stay finite and shaped (dropped contributions are
    zero, not NaN)."""
    cfg = _cfg()
    p = _layer_params(cfg, jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 8, cfg.d_model), jnp.float32)
    got = routed_moe_ffn(x, p, cfg, mesh=None, capacity_factor=0.05)
    assert got.shape == x.shape
    assert bool(jnp.isfinite(got).all())


def test_route_slot_assignment_unique_and_capped():
    cfg = _cfg()
    n, cap = 16, _capacity(16, cfg, 2.0)
    x = jax.random.normal(jax.random.PRNGKey(8), (n, cfg.d_model), jnp.float32)
    router = jax.random.normal(jax.random.PRNGKey(9), (cfg.d_model, cfg.n_experts),
                               jnp.float32)
    _, slot = _route(x, router, cfg, cap)
    real = np.asarray(slot).ravel()
    real = real[real < cfg.n_experts * cap]  # ignore trash slot
    assert len(np.unique(real)) == len(real)  # scatter indices are unique


def test_ep_dispatch_is_all_to_all_with_bounded_bytes():
    """VERDICT r2 weak #6: the EP exchange must be a true all-to-all of slot
    payloads, with per-shard exchanged bytes scaling with k/E (the assigned
    slots), not with ep (a replicate+psum of the full [N, D] output).

    Asserted against the LOWERED HLO: the collective is all-to-all (no
    all-reduce combine), and its operand is the [ep, E_local*C_pair, D]
    send buffer — whose size halves when ep doubles and doubles with k."""
    from nats_llm_studio_tpu.parallel import build_mesh
    from nats_llm_studio_tpu.parallel.moe import _capacity
    from nats_llm_studio_tpu.parallel.sharding import shard_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")

    def lowered_text(ep, k):
        cfg = _cfg(n_experts_used=k)
        mesh = build_mesh({"ep": ep}, jax.devices()[:ep])
        p = _layer_params(cfg, jax.random.PRNGKey(0))
        x = jnp.zeros((2, 8, cfg.d_model), jnp.float32)
        fn = jax.jit(lambda x, p: routed_moe_ffn(x, p, cfg, mesh=mesh,
                                                 capacity_factor=2.0))
        return cfg, ep, fn.lower(x, p).as_text()

    for ep, k in [(4, 2), (8, 2), (4, 4)]:
        cfg, ep_, text = lowered_text(ep, k)
        assert "all_to_all" in text, f"ep={ep} k={k}: no all_to_all in HLO"
        n = 2 * 8
        c_pair = _capacity(-(-n // ep) * ep // ep, cfg, 2.0)
        e_local = cfg.n_experts // ep
        # the send buffer's exact shape must appear as an all_to_all operand
        shape = f"tensor<{ep}x{e_local * c_pair}x{cfg.d_model}xf32>"
        a2a_lines = [l for l in text.splitlines() if "all_to_all" in l]
        assert any(shape in l for l in a2a_lines), (
            f"ep={ep} k={k}: expected a2a operand {shape}; got:\n"
            + "\n".join(a2a_lines[:4])
        )

    # bytes scaling: ep 4 -> 8 halves the per-shard send buffer; k 2 -> 4
    # doubles it (both through C_pair = ceil(cf*k*(N/ep)/E))
    n = 16
    c = lambda ep, k: _capacity(n // ep, _cfg(n_experts_used=k), 2.0)
    assert c(8, 2) * 8 * (8 // 8) <= c(4, 2) * 4 * (8 // 4)
    assert c(4, 4) == 2 * c(4, 2)


def test_routed_drop_fraction_matches_serving_capacity_semantics():
    """The drop diagnostic must mirror the serving path's capacity math:
    single-shard uses the global capacity; ep > 1 uses the per-(shard,
    expert) pair capacity over each local block — a skewed batch that fits
    globally can overflow per-shard, and the diagnostic must see it."""
    import jax
    import jax.numpy as jnp

    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import init_params
    from nats_llm_studio_tpu.parallel.moe import _capacity, routed_drop_fraction

    cfg = ModelConfig.tiny(
        n_experts=4, n_experts_used=2, d_ff=32, n_layers=1,
        n_heads=2, n_kv_heads=2, head_dim=8,
    )
    params = init_params(cfg, jax.random.PRNGKey(3))
    blk = jax.tree.map(lambda a: a[0], params["blocks"])
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 8, cfg.d_model), jnp.float32)

    d1 = routed_drop_fraction(x, blk, cfg, capacity_factor=2.0, ep=1)
    d4 = routed_drop_fraction(x, blk, cfg, capacity_factor=2.0, ep=4)
    assert 0.0 <= d1 <= 1.0 and 0.0 <= d4 <= 1.0
    # a tiny capacity factor must force visible drops in both modes
    tight1 = routed_drop_fraction(x, blk, cfg, capacity_factor=0.1, ep=1)
    tight4 = routed_drop_fraction(x, blk, cfg, capacity_factor=0.1, ep=4)
    assert tight1 > 0.0 and tight4 > 0.0
    # generous capacity drops nothing
    assert routed_drop_fraction(x, blk, cfg, capacity_factor=8.0, ep=1) == 0.0

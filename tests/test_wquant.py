"""Weight-only int8 quantization: numerics, forward fidelity, sharding,
and the 70B-on-v5e-8 memory budget (VERDICT.md next-round items 2 and 3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.ops.wquant import (
    QTensor,
    mm,
    q_einsum,
    quantize_params,
    quantize_weight,
)


def test_quantize_weight_roundtrip_error():
    rng = np.random.default_rng(0)
    w = rng.normal(size=(64, 32)).astype(np.float32)
    qt = quantize_weight(w)
    assert qt.q.dtype == np.int8 and qt.s.shape == (1, 32)
    back = qt.q.astype(np.float32) * qt.s
    # symmetric absmax int8: max error is half a quantization step per channel
    step = np.abs(w).max(axis=0) / 127.0
    assert (np.abs(back - w) <= step / 2 + 1e-7).all()


def test_quantize_weight_zero_channel():
    w = np.zeros((16, 4), np.float32)
    w[:, 1] = 3.0
    qt = quantize_weight(w)
    back = qt.q.astype(np.float32) * qt.s
    np.testing.assert_allclose(back, w, atol=1e-6)


def test_mm_matches_dequant():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(3, 64)), jnp.float32)
    w = rng.normal(size=(64, 32)).astype(np.float32)
    qt = quantize_weight(w)
    qt_dev = QTensor(q=jnp.asarray(qt.q), s=jnp.asarray(qt.s))
    got = mm(x, qt_dev)
    want = x @ (jnp.asarray(qt.q, jnp.float32) * jnp.asarray(qt.s))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_q_einsum_expert_shapes():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, 3, 16)), jnp.float32)
    w = rng.normal(size=(4, 16, 8)).astype(np.float32)  # [E, D, F]
    qt = quantize_weight(w)
    qt_dev = QTensor(q=jnp.asarray(qt.q), s=jnp.asarray(qt.s))
    got = q_einsum("btd,edf->btef", x, qt_dev)
    want = jnp.einsum("btd,edf->btef", x, jnp.asarray(qt.q, jnp.float32) * jnp.asarray(qt.s))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("moe", [False, True])
def test_int8_forward_close_to_fp(moe):
    kw = {"n_experts": 4, "n_experts_used": 2, "d_ff": 64} if moe else {}
    cfg = ModelConfig.tiny(n_layers=2, **kw)
    params = init_params(cfg, jax.random.PRNGKey(0))
    qparams = quantize_params(jax.tree.map(np.asarray, params))
    qparams = jax.tree.map(jnp.asarray, qparams)

    toks = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    zero = jnp.zeros((1,), jnp.int32)
    k, v = make_cache(cfg, 1, 16)
    want, _, _ = forward(params, cfg, toks, k, v, zero)
    k, v = make_cache(cfg, 1, 16)
    got, _, _ = forward(qparams, cfg, toks, k, v, zero)
    # int8 weight-only keeps logits close; greedy argmax should agree
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=0.1, atol=0.15)
    assert (jnp.argmax(got[:, -1], -1) == jnp.argmax(want[:, -1], -1)).all()


def test_int8_scan_decode_runs():
    """QTensor leaves must flow through lax.scan (L-axis slicing) and the
    decode path (t=1, start_pos>0)."""
    cfg = ModelConfig.tiny(n_layers=3)
    params = init_params(cfg, jax.random.PRNGKey(1))
    qparams = jax.tree.map(jnp.asarray, quantize_params(jax.tree.map(np.asarray, params)))
    k, v = make_cache(cfg, 2, 16)
    toks = jnp.ones((2, 4), jnp.int32)
    logits, k, v = forward(qparams, cfg, toks, k, v, jnp.zeros((2,), jnp.int32))
    logits, k, v = forward(
        qparams, cfg, jnp.ones((2, 1), jnp.int32), k, v, jnp.full((2,), 4, jnp.int32)
    )
    assert logits.shape == (2, 1, cfg.vocab_size)


def test_shard_params_with_qtensors():
    from nats_llm_studio_tpu.parallel import build_mesh
    from nats_llm_studio_tpu.parallel.sharding import shard_params

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = ModelConfig.tiny(n_layers=2, n_heads=8, n_kv_heads=8, d_ff=128)
    params = init_params(cfg, jax.random.PRNGKey(2))
    qparams = quantize_params(jax.tree.map(np.asarray, params))
    mesh = build_mesh({"tp": 8}, jax.devices()[:8])
    sharded = shard_params(qparams, mesh)
    wq = sharded["blocks"]["wq"]
    assert isinstance(wq, QTensor)
    # weight sharded over out-features, scale sharded identically on out
    assert wq.q.sharding.spec[-1] == "tp" and wq.s.sharding.spec[-1] == "tp"
    wo = sharded["blocks"]["wo"]
    assert wo.q.sharding.spec[1] == "tp"
    # scale's contraction axis has extent 1 -> must not be sharded
    assert wo.s.sharding.spec[1] is None

    # sharded int8 forward matches unsharded
    k, v = make_cache(cfg, 2, 16)
    toks = jnp.ones((2, 4), jnp.int32)
    want, _, _ = forward(jax.tree.map(jnp.asarray, qparams), cfg, toks, k, v,
                         jnp.zeros((2,), jnp.int32))
    from nats_llm_studio_tpu.parallel.sharding import shard_cache

    ks, vs = shard_cache(*make_cache(cfg, 2, 16), mesh)
    got, _, _ = forward(sharded, cfg, toks, ks, vs, jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)


def test_70b_int8_fits_v5e8_memory_budget():
    """BASELINE config 3: Llama-3-70B sharded TP=8 must fit 8 x 16 GB HBM as
    int8 + scales + KV cache, while bf16 must not. Pure shape math."""
    cfg = ModelConfig(
        arch="llama",
        vocab_size=128256,
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        head_dim=128,
        d_ff=28672,
        rope_theta=500000.0,
        max_seq_len=8192,
        dtype="bfloat16",
    )
    from nats_llm_studio_tpu.parallel.memory import estimate_device_bytes

    hbm = 16 * 2**30
    est8 = estimate_device_bytes(cfg, {"tp": 8}, quant="int8", batch=8, seq_len=4096)
    est16 = estimate_device_bytes(cfg, {"tp": 8}, quant="none", batch=8, seq_len=4096)
    assert est8["total"] < 0.9 * hbm, est8
    assert est16["total"] > hbm, est16

"""Cross-implementation fixtures (VERDICT round-1 item 7).

The GGUF/quant/tokenizer tests elsewhere round-trip through the in-tree
writer, so a shared layout misunderstanding would pass. Here the expected
values come from INDEPENDENT implementations written directly from the
public ggml format definitions (scalar, loop-by-loop, mirroring
llama.cpp's dequantize_row_* structure) and from hand-computed tokenizer
examples — none of it touches the in-tree vectorized decoders or encoder.
"""

import shutil

import numpy as np
import pytest

from nats_llm_studio_tpu.gguf.constants import GGMLType
from nats_llm_studio_tpu.gguf.quants import dequantize

RNG = np.random.default_rng(1234)


def _rand_f16(n: int) -> np.ndarray:
    """Random finite, well-scaled f16 values (as raw u16 view)."""
    vals = RNG.uniform(-2.0, 2.0, n).astype(np.float16)
    return vals.view(np.uint16)


# ---------------------------------------------------------------------------
# scalar reference dequantizers (from the public ggml block layouts)
# ---------------------------------------------------------------------------


def scalar_q8_0(block: bytes) -> list[float]:
    d = np.frombuffer(block[:2], np.float16)[0].astype(np.float32)
    qs = np.frombuffer(block[2:34], np.int8)
    return [float(d) * int(q) for q in qs]


def scalar_q4_0(block: bytes) -> list[float]:
    d = np.frombuffer(block[:2], np.float16)[0].astype(np.float32)
    qs = block[2:18]
    out = [0.0] * 32
    for i in range(16):
        out[i] = float(d) * ((qs[i] & 0x0F) - 8)
        out[i + 16] = float(d) * ((qs[i] >> 4) - 8)
    return out


def _q4k_scale_min(scales: bytes, j: int) -> tuple[int, int]:
    """6-bit (scale, min) pair j of the 12-byte Q4_K scales field."""
    if j < 4:
        sc = scales[j] & 63
        m = scales[j + 4] & 63
    else:
        sc = (scales[j + 4] & 0x0F) | ((scales[j - 4] >> 6) << 4)
        m = (scales[j + 4] >> 4) | ((scales[j] >> 6) << 4)
    return sc, m


def scalar_q4_k(block: bytes) -> list[float]:
    """256-element Q4_K super-block: d f16, dmin f16, scales[12], qs[128]."""
    d = float(np.frombuffer(block[:2], np.float16)[0])
    dmin = float(np.frombuffer(block[2:4], np.float16)[0])
    scales = block[4:16]
    qs = block[16:144]
    out = [0.0] * 256
    for chunk in range(4):  # 64 elements per chunk: 32 low then 32 high nibbles
        ql = qs[32 * chunk : 32 * chunk + 32]
        sc1, m1 = _q4k_scale_min(scales, 2 * chunk)
        sc2, m2 = _q4k_scale_min(scales, 2 * chunk + 1)
        for i in range(32):
            out[64 * chunk + i] = d * sc1 * (ql[i] & 0x0F) - dmin * m1
            out[64 * chunk + 32 + i] = d * sc2 * (ql[i] >> 4) - dmin * m2
    return out


def scalar_q6_k(block: bytes) -> list[float]:
    """256-element Q6_K super-block: ql[128], qh[64], scales[16] i8, d f16."""
    ql = block[0:128]
    qh = block[128:192]
    scales = np.frombuffer(block[192:208], np.int8)
    d = float(np.frombuffer(block[208:210], np.float16)[0])
    out = [0.0] * 256
    for n in range(2):  # two 128-element halves
        for l in range(32):
            is_ = l // 16
            q1 = ((ql[n * 64 + l] & 0x0F) | (((qh[n * 32 + l] >> 0) & 3) << 4)) - 32
            q2 = ((ql[n * 64 + l + 32] & 0x0F) | (((qh[n * 32 + l] >> 2) & 3) << 4)) - 32
            q3 = ((ql[n * 64 + l] >> 4) | (((qh[n * 32 + l] >> 4) & 3) << 4)) - 32
            q4 = ((ql[n * 64 + l + 32] >> 4) | (((qh[n * 32 + l] >> 6) & 3) << 4)) - 32
            out[n * 128 + l + 0] = d * int(scales[n * 8 + is_ + 0]) * q1
            out[n * 128 + l + 32] = d * int(scales[n * 8 + is_ + 2]) * q2
            out[n * 128 + l + 64] = d * int(scales[n * 8 + is_ + 4]) * q3
            out[n * 128 + l + 96] = d * int(scales[n * 8 + is_ + 6]) * q4
    return out


def _blocks(raw_per_block: list[bytes]) -> bytes:
    return b"".join(raw_per_block)


def test_q8_0_against_scalar_spec():
    blocks = []
    for _ in range(4):
        blocks.append(_rand_f16(1).tobytes() + RNG.integers(-128, 128, 32, np.int8).tobytes())
    want = [x for b in blocks for x in scalar_q8_0(b)]
    got = dequantize(_blocks(blocks), GGMLType.Q8_0, len(blocks) * 32)
    np.testing.assert_allclose(np.asarray(got, np.float32).ravel(), want, rtol=1e-6)


def test_q4_0_against_scalar_spec():
    blocks = []
    for _ in range(4):
        blocks.append(_rand_f16(1).tobytes() + RNG.integers(0, 256, 16, np.uint8).tobytes())
    want = [x for b in blocks for x in scalar_q4_0(b)]
    got = dequantize(_blocks(blocks), GGMLType.Q4_0, len(blocks) * 32)
    np.testing.assert_allclose(np.asarray(got, np.float32).ravel(), want, rtol=1e-6)


def test_q4_k_against_scalar_spec():
    blocks = []
    for _ in range(3):
        blocks.append(
            _rand_f16(2).tobytes()
            + RNG.integers(0, 256, 12, np.uint8).tobytes()
            + RNG.integers(0, 256, 128, np.uint8).tobytes()
        )
    want = [x for b in blocks for x in scalar_q4_k(b)]
    got = dequantize(_blocks(blocks), GGMLType.Q4_K, len(blocks) * 256)
    np.testing.assert_allclose(
        np.asarray(got, np.float32).ravel(), want, rtol=1e-5, atol=1e-5
    )


def test_q6_k_against_scalar_spec():
    blocks = []
    for _ in range(3):
        blocks.append(
            RNG.integers(0, 256, 128, np.uint8).tobytes()  # ql
            + RNG.integers(0, 256, 64, np.uint8).tobytes()  # qh
            + RNG.integers(-64, 64, 16, np.int8).tobytes()  # scales
            + _rand_f16(1).tobytes()
        )
    want = [x for b in blocks for x in scalar_q6_k(b)]
    got = dequantize(_blocks(blocks), GGMLType.Q6_K, len(blocks) * 256)
    np.testing.assert_allclose(
        np.asarray(got, np.float32).ravel(), want, rtol=1e-5, atol=1e-5
    )


# ---------------------------------------------------------------------------
# tokenizer goldens (hand-computed, not writer round-trips)
# ---------------------------------------------------------------------------


def test_byte_level_bpe_known_mapping_and_merge():
    """GPT-2 byte-level facts verifiable by hand: printable ASCII maps to
    itself, space maps to U+0120 ('Ġ'), and a single merge applies."""
    from nats_llm_studio_tpu.gguf.tokenizer import GGUFTokenizer

    vocab = ["A", "B", "AB", "Ġ", "ĠA", "C"]
    tok = GGUFTokenizer("gpt2", vocab, merges=["A B", "Ġ A"], add_bos=False)
    assert tok.encode("AB") == [2]  # merge "A B" -> "AB"
    assert tok.encode(" A") == [4]  # space -> Ġ, then merge "Ġ A"
    assert tok.encode("BA") == [1, 0]  # no merge defined for "B A"
    assert tok.decode([2, 3, 0]) == "AB A"  # Ġ decodes back to a space


def test_spm_known_greedy_merge():
    """SPM scores: higher score wins; ' ab' -> '▁ab' when that piece exists
    and outranks the alternatives (computed by hand)."""
    from nats_llm_studio_tpu.gguf.tokenizer import GGUFTokenizer

    vocab = ["<unk>", "▁", "a", "b", "ab", "▁a", "▁ab"]
    scores = [0.0, -10.0, -3.0, -3.0, -1.0, -2.0, -0.5]
    tok = GGUFTokenizer(
        "llama", vocab, scores=scores, bos_id=None, eos_id=None, add_bos=False
    )
    assert tok.encode("ab") == [6]  # SPM prefixes ' ', best single piece '▁ab'
    assert tok.decode([6]) == "ab"  # leading ▁ restores then strips the space
    assert tok.decode([5, 3]) == "ab"


# ---------------------------------------------------------------------------
# real nats-server interop (runs wherever the binary exists)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(shutil.which("nats-server") is None, reason="nats-server not installed")
def test_client_against_real_nats_server(tmp_path):
    """The in-tree client must speak to a stock nats-server: connect, PING,
    request/reply via a subscriber — proving the wire protocol is real NATS,
    not merely self-consistent with the in-tree broker."""
    import asyncio
    import socket
    import subprocess
    import time

    from nats_llm_studio_tpu.transport import connect

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        ["nats-server", "-a", "127.0.0.1", "-p", str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port), timeout=0.5).close()
                break
            except OSError:
                time.sleep(0.1)

        async def drive():
            nc = await connect(f"nats://127.0.0.1:{port}")
            sub = await nc.subscribe("echo.svc")

            async def responder():
                async for msg in sub:
                    await nc.publish(msg.reply, b"pong:" + msg.payload)
                    break

            task = asyncio.ensure_future(responder())
            reply = await nc.request("echo.svc", b"hi", timeout=5.0)
            assert reply.payload == b"pong:hi"
            task.cancel()
            await nc.close()

        asyncio.run(drive())
    finally:
        proc.terminate()
        proc.wait(timeout=10)

"""Multi-worker cluster tests (ISSUE 10): advert flow into the router's
member table, load/locality steering, the ``X-Excluded-Workers`` bounce
round-trip, shed-retried-onto-the-peer failover over the real queue group,
graceful drain handoff, and deadline-budget-capped retries."""

import asyncio
import json
import time

import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.serve import ClusterRouter, Worker, prompt_head_hash
from nats_llm_studio_tpu.serve.api import EngineError
from nats_llm_studio_tpu.serve.router import RecentHeads, RouterProcess
from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect
from nats_llm_studio_tpu.transport import protocol as p

from conftest import async_test
from fakes import FakeRegistry


class SheddingRegistry(FakeRegistry):
    """Sheds the first ``shed_times`` chats with the retryable overload
    envelope, then serves — the worker-side behavior a retry must survive."""

    def __init__(self, *args, shed_times: int = 10**9, **kwargs):
        super().__init__(*args, **kwargs)
        self.shed_times = shed_times
        self.sheds = 0

    async def get_engine(self, model_id):
        if self.sheds < self.shed_times:
            self.sheds += 1
            raise EngineError("overloaded: test shed, retry on another worker")
        return await super().get_engine(model_id)


class ClusterHarness:
    """N workers (fast adverts) + one client on an embedded broker."""

    def __init__(self, n_workers=2, registries=None, advert_interval_s=0.05,
                 roles=None):
        self.n_workers = n_workers
        self.registries = registries
        self.advert_interval_s = advert_interval_s
        self.roles = roles  # optional per-worker WORKER_ROLE list

    async def __aenter__(self):
        self.broker = await EmbeddedBroker().start()
        if self.registries is None:
            self.registries = [FakeRegistry() for _ in range(self.n_workers)]
        self.workers = []
        for i, reg in enumerate(self.registries):
            w = Worker(
                WorkerConfig(
                    nats_url=self.broker.url,
                    cluster_advert_interval_s=self.advert_interval_s,
                    worker_role=(self.roles[i] if self.roles else ""),
                ),
                reg,
            )
            await w.start()
            self.workers.append(w)
        self.nc = await connect(self.broker.url)
        return self

    async def __aexit__(self, *exc):
        await self.nc.close()
        for w in self.workers:
            await w.drain()
        await self.broker.stop()

    @staticmethod
    def chat(content="hi", model="fake-echo-1"):
        return {"model": model, "messages": [{"role": "user", "content": content}]}

    async def req(self, op, payload, timeout=5.0, headers=None, retry=None):
        body = payload if isinstance(payload, bytes) else json.dumps(payload).encode()
        msg = await self.nc.request(
            f"lmstudio.{op}", body, timeout=timeout, headers=headers, retry=retry
        )
        return json.loads(msg.payload), msg


# -- pure units --------------------------------------------------------------


def test_prompt_head_hash_is_length_delimited_and_budget_capped():
    # message boundaries can't collide: ("ab","c") vs ("a","bc")
    a = prompt_head_hash("m", [{"role": "u", "content": "ab"}, {"role": "u", "content": "c"}])
    b = prompt_head_hash("m", [{"role": "u", "content": "a"}, {"role": "u", "content": "bc"}])
    assert a != b
    # the model is part of the key (different vocab -> different token ids)
    msgs = [{"role": "user", "content": "hello"}]
    assert prompt_head_hash("m1", msgs) != prompt_head_hash("m2", msgs)
    # only the first `chars` characters count: equal heads hash equal
    long_a = [{"role": "user", "content": "abcd" + "X" * 50}]
    long_b = [{"role": "user", "content": "abcd" + "Y" * 50}]
    assert prompt_head_hash("m", long_a, chars=4) == prompt_head_hash("m", long_b, chars=4)
    assert prompt_head_hash("m", long_a, chars=8) != prompt_head_hash("m", long_b, chars=8)
    # malformed messages degrade to a model-only hash, never raise
    assert prompt_head_hash("m", None) == prompt_head_hash("m", "not-a-list")


def test_recent_heads_lru_eviction_and_refresh():
    lru = RecentHeads(capacity=2)
    lru.add("a")
    lru.add("b")
    lru.add("a")  # refresh: "b" is now oldest
    lru.add("c")
    assert lru.snapshot() == ["a", "c"]


def test_router_pick_ranking_staleness_and_mark_dead():
    r = ClusterRouter(None, stale_after_s=5.0)
    msgs = [{"role": "user", "content": "the shared prompt head"}]
    head = prompt_head_hash("m", msgs)

    # draining and excluded workers never win
    r.ingest({"worker_id": "w-a", "queue_depth": 0, "draining": True})
    r.ingest({"worker_id": "w-b", "queue_depth": 9})
    assert r.pick(model="m", messages=msgs) == "w-b"
    assert r.pick(model="m", messages=msgs, excluded=["w-b"]) is None

    # lower brownout beats lower depth; model-loaded beats depth
    r.ingest({"worker_id": "w-a", "queue_depth": 0, "brownout": 1, "draining": False})
    assert r.pick(model="m", messages=msgs) == "w-b"
    r.ingest({"worker_id": "w-a", "queue_depth": 0, "brownout": 0})
    r.ingest({"worker_id": "w-b", "queue_depth": 9, "models": ["m"]})
    assert r.pick(model="m", messages=msgs) == "w-b"

    # prefix-head locality wins outright — unless the sticky worker is
    # SHED_ONLY (brownout 2), where steering extra load at it is harmful
    r.ingest({"worker_id": "w-a", "queue_depth": 0, "models": ["m"], "heads": [head]})
    assert r.pick(model="m", messages=msgs) == "w-a"
    assert r.stats.locality_total == 1
    r.ingest({"worker_id": "w-a", "queue_depth": 0, "models": ["m"], "heads": [head],
              "brownout": 2})
    assert r.pick(model="m", messages=msgs) == "w-b"

    # out-of-order adverts are dropped by seq
    r.ingest({"worker_id": "w-b", "queue_depth": 1, "models": ["m"], "seq": 10})
    r.ingest({"worker_id": "w-b", "queue_depth": 99, "models": [], "seq": 9})
    assert r._members["w-b"].queue_depth == 1

    # ...but a respawned worker reusing the id (its seq restarted near
    # zero) must not be ignored until the stale window ages the ghost out
    # (ISSUE 15): seq <= SEQ_RESTART_MAX is accepted as a restart
    r.ingest({"worker_id": "w-b", "queue_depth": 3, "models": ["m"], "seq": 2})
    assert r._members["w-b"].queue_depth == 3
    # as is a backward jump beyond the reorder window; a small backward
    # step inside it is still just a late packet
    r.ingest({"worker_id": "w-b", "queue_depth": 1, "models": ["m"], "seq": 500})
    r.ingest({"worker_id": "w-b", "queue_depth": 99, "models": ["m"], "seq": 460})
    assert r._members["w-b"].queue_depth == 1  # within window: stale, dropped
    r.ingest({"worker_id": "w-b", "queue_depth": 7, "models": ["m"], "seq": 100})
    assert r._members["w-b"].queue_depth == 7  # beyond window: a restart

    # mark_dead drops the member NOW
    r.mark_dead("w-b")
    assert r.pick(model="m", messages=msgs) == "w-a"
    assert r.stats.dead_marked_total == 1

    # stale members fall out of the live view
    r2 = ClusterRouter(None, stale_after_s=0.05)
    r2.ingest({"worker_id": "w-z"})
    assert [m.worker_id for m in r2.members()] == ["w-z"]
    time.sleep(0.1)
    assert r2.members() == []
    assert r2.pick(model="m", messages=msgs) is None


def test_router_pick_pair_role_routing():
    """Role-aware pick_pair (ISSUE 13): prefill-role workers are held out
    of serving whenever any other worker is live, decode-role winners get
    paired with the best prefill peer (the two-hop), and everything
    degrades to monolithic picks when the topology loses a role."""
    r = ClusterRouter(None, stale_after_s=5.0)

    # roleless cluster: plain pick, never a prefill peer
    r.ingest({"worker_id": "w-a", "queue_depth": 0})
    assert r.pick_pair(model="m") == ("w-a", None)

    # prefill-role workers don't serve chats while any other worker is live
    r.ingest({"worker_id": "w-p", "queue_depth": 0, "role": "prefill"})
    assert r.pick_pair(model="m")[0] == "w-a"

    # a decode-role winner is paired with the best prefill peer
    r.ingest({"worker_id": "w-d", "queue_depth": 5, "role": "decode",
              "models": ["m"]})
    assert r.pick_pair(model="m") == ("w-d", "w-p")
    assert r.stats.two_hop_total == 1
    assert r.pick(model="m") == "w-d"  # pick() delegates to pick_pair()

    # a SHED_ONLY prefill peer is not worth the hop
    r.ingest({"worker_id": "w-p", "queue_depth": 0, "role": "prefill",
              "brownout": 2})
    assert r.pick_pair(model="m") == ("w-d", None)
    r.ingest({"worker_id": "w-p", "queue_depth": 0, "role": "prefill"})

    # a monolithic winner never hops
    r.ingest({"worker_id": "w-a", "queue_depth": 0, "models": ["m"]})
    assert r.pick_pair(model="m") == ("w-a", None)

    # only prefill-role workers left: they serve monolithically (degrade)
    r.mark_dead("w-a")
    r.mark_dead("w-d")
    assert r.pick_pair(model="m") == ("w-p", None)
    # exclusion applies to the serving end as usual
    assert r.pick_pair(model="m", excluded=["w-p"]) == (None, None)


# -- adverts + steering over the real broker ---------------------------------


@async_test
async def test_worker_adverts_populate_router_and_steer():
    async with ClusterHarness(n_workers=2) as h:
        router = await ClusterRouter(h.nc).start()
        try:
            deadline = time.monotonic() + 5.0
            while len(router.members()) < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            ids = sorted(m.worker_id for m in router.members())
            assert ids == sorted(w.worker_id for w in h.workers)
            for m in router.members():
                assert m.models == ("fake-echo-1",)
                assert m.draining is False

            msg = await router.request_chat(h.chat(), timeout=5.0)
            resp = json.loads(msg.payload)
            assert resp["ok"] is True
            assert (msg.headers or {}).get(p.WORKER_HEADER) in ids
            assert router.stats.routed_total == 1
            assert router.stats.fallback_total == 0
        finally:
            await router.stop()

        # a router with an empty member table degrades to the queue group —
        # attaching one is always safe
        cold = ClusterRouter(h.nc)  # never started: no adverts ingested
        msg = await cold.request_chat(h.chat(), timeout=5.0)
        assert json.loads(msg.payload)["ok"] is True
        assert cold.stats.fallback_total == 1
        assert cold.stats.routed_total == 0


@async_test
async def test_role_cluster_degrades_gracefully_without_kv_engines():
    """A prefill+decode topology over engines that can't export/import KV
    (fakes.EchoEngine has no import_prefix hook) still serves every chat:
    the router two-hops to the decode worker, which skips the pull without
    counting a transfer failure."""
    async with ClusterHarness(n_workers=2, roles=["prefill", "decode"]) as h:
        router = await ClusterRouter(h.nc).start()
        try:
            deadline = time.monotonic() + 5.0
            while len(router.members()) < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            roles = {m.worker_id: m.role for m in router.members()}
            assert sorted(roles.values()) == ["decode", "prefill"]
            decode_wid = next(w for w, role in roles.items() if role == "decode")

            msg = await router.request_chat(h.chat(), timeout=5.0)
            assert json.loads(msg.payload)["ok"] is True
            assert (msg.headers or {}).get(p.WORKER_HEADER) == decode_wid
            assert router.stats.two_hop_total == 1
            wd = next(w for w in h.workers if w.worker_id == decode_wid)
            assert wd._kv_transfer_failures == 0
        finally:
            await router.stop()


@async_test
async def test_directed_subjects_and_excluded_bounce_envelope():
    async with ClusterHarness(n_workers=1) as h:
        w = h.workers[0]
        wid = w.worker_id

        # directed health: draining state per worker, not queue-group roulette
        resp, _ = await h.req(f"worker.{wid}.health", {})
        assert resp["ok"] is True
        assert resp["data"]["worker_id"] == wid
        assert resp["data"]["draining"] is False

        # a chat naming this worker in X-Excluded-Workers bounces retryably
        # with the one-shot excluded_bounce marker — it never serves
        resp, msg = await h.req(
            f"worker.{wid}.chat_model", h.chat(),
            headers={p.EXCLUDED_WORKERS_HEADER: wid},
        )
        assert resp["ok"] is False
        assert resp["retryable"] is True
        assert "retry on another worker" in resp["error"]
        assert resp["data"]["excluded_bounce"] is True
        assert resp["data"]["worker_id"] == wid
        assert (msg.headers or {}).get(p.WORKER_HEADER) == wid
        assert w._excluded_bounce_total == 1


@async_test
async def test_excluded_bounce_roundtrips_through_client_retry():
    """Shed -> exclude -> redelivery bounces -> exclusion consumed -> served.
    A single-worker group must stay servable after one shed (the bounce is a
    one-shot deflection, not a permanent blacklist)."""
    reg = SheddingRegistry(shed_times=1)
    async with ClusterHarness(n_workers=1, registries=[reg]) as h:
        resp, msg = await h.req(
            "chat_model", h.chat(),
            retry=RetryPolicy(max_attempts=5, backoff_s=0.01, jitter=0.0),
        )
        assert resp["ok"] is True
        assert reg.sheds == 1
        # attempt 2 landed back on the only worker, which self-checked the
        # header and bounced instead of serving
        assert h.workers[0]._excluded_bounce_total == 1
        assert (msg.headers or {}).get(p.WORKER_HEADER) == h.workers[0].worker_id


@async_test
async def test_shed_by_one_worker_is_retried_onto_the_other():
    shedder = SheddingRegistry()  # sheds every chat, forever
    healthy = FakeRegistry()
    async with ClusterHarness(n_workers=2, registries=[shedder, healthy]) as h:
        resp, msg = await h.req(
            "chat_model", h.chat(),
            timeout=10.0,
            retry=RetryPolicy(max_attempts=12, backoff_s=0.01, jitter=0.0),
        )
        assert resp["ok"] is True
        assert (msg.headers or {}).get(p.WORKER_HEADER) == h.workers[1].worker_id
        # the healthy worker was never named in an exclusion header
        assert h.workers[1]._excluded_bounce_total == 0


@async_test
async def test_router_steers_retry_away_from_shedding_worker():
    """Steered failover is deterministic: the shed adds the worker to the
    exclusion list AND the pick filter, so the retry goes straight to the
    peer — no queue-group roulette, no redelivery bounce."""
    shedder = SheddingRegistry()
    healthy = FakeRegistry()
    async with ClusterHarness(n_workers=2, registries=[shedder, healthy]) as h:
        wid_shed = h.workers[0].worker_id
        wid_ok = h.workers[1].worker_id
        router = ClusterRouter(h.nc)  # not started: member table is injected
        router.ingest({"worker_id": wid_shed, "queue_depth": 0, "models": ["fake-echo-1"]})
        router.ingest({"worker_id": wid_ok, "queue_depth": 5, "models": ["fake-echo-1"]})
        assert router.pick(model="fake-echo-1") == wid_shed  # least loaded

        msg = await router.request_chat(
            h.chat(), timeout=5.0,
            retry=RetryPolicy(max_attempts=3, backoff_s=0.01, jitter=0.0),
        )
        resp = json.loads(msg.payload)
        assert resp["ok"] is True
        assert (msg.headers or {}).get(p.WORKER_HEADER) == wid_ok
        assert shedder.sheds == 1
        assert router.stats.routed_total == 2
        # directed steering honors the exclusion — the shedder never saw the
        # retry, so its self-check counter stayed at zero
        assert h.workers[0]._excluded_bounce_total == 0


@async_test
async def test_router_process_forwards_route_subject():
    async with ClusterHarness(n_workers=2) as h:
        proc = RouterProcess(h.nc, retry=RetryPolicy(max_attempts=3, backoff_s=0.01))
        await proc.start()
        try:
            deadline = time.monotonic() + 5.0
            while len(proc.router.members()) < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
            msg = await h.nc.request(
                "lmstudio.route.chat_model",
                json.dumps(h.chat("via router")).encode(),
                timeout=5.0,
            )
            resp = json.loads(msg.payload)
            assert resp["ok"] is True
            text = resp["data"]["response"]["choices"][0]["message"]["content"]
            assert text == "echo: via router"
            # the reply is relayed verbatim, serving worker header included
            wid = (msg.headers or {}).get(p.WORKER_HEADER)
            assert wid in {w.worker_id for w in h.workers}
        finally:
            await proc.stop()


# -- graceful drain handoff --------------------------------------------------


@async_test
async def test_admin_drain_hands_off_to_peer():
    async with ClusterHarness(n_workers=2) as h:
        wa, wb = h.workers
        resp, _ = await h.req("admin.drain", {"worker_id": wa.worker_id})
        assert resp["ok"] is True
        assert resp["data"]["worker_id"] == wa.worker_id
        assert resp["data"]["draining"] is True
        assert resp["data"]["finished_in_time"] is True
        assert wa.draining is True and wb.draining is False

        # the drained worker left the queue group before replying, so every
        # new queue-group request lands on the peer — no retries needed
        for i in range(10):
            resp, msg = await h.req("chat_model", h.chat(f"r{i}"))
            assert resp["ok"] is True
            assert (msg.headers or {}).get(p.WORKER_HEADER) == wb.worker_id

        # directed chat at the drained worker bounces retryably
        resp, _ = await h.req(f"worker.{wa.worker_id}.chat_model", h.chat())
        assert resp["ok"] is False and resp["retryable"] is True
        assert "worker draining" in resp["error"]
        assert wa._drain_bounce_total == 1

        # directed health and the advert both surface the drain state
        resp, _ = await h.req(f"worker.{wa.worker_id}.health", {})
        assert resp["data"]["status"] == "draining"
        assert resp["data"]["draining"] is True
        assert wa.build_advert()["draining"] is True

        # drain is idempotent
        resp, _ = await h.req("admin.drain", {"worker_id": wa.worker_id})
        assert resp["data"].get("already_draining") is True

        # a drain addressed to nobody gets no reply (peers stay silent so
        # the addressee's reply is THE reply) — the requester times out
        with pytest.raises(asyncio.TimeoutError):
            await h.req("admin.drain", {"worker_id": "w-nonexistent"}, timeout=0.3)

        # validation still replies
        resp, _ = await h.req("admin.drain", {})
        assert resp["ok"] is False and "worker_id" in resp["error"]


# -- deadline budget caps retries (satellite a) ------------------------------


@async_test
async def test_retry_stops_when_deadline_budget_exhausted():
    reg = SheddingRegistry()  # never serves: every attempt is a retryable shed
    async with ClusterHarness(n_workers=1, registries=[reg]) as h:
        t0 = time.monotonic()
        resp, _ = await h.req(
            "chat_model", h.chat(),
            timeout=0.6,
            retry=RetryPolicy(
                max_attempts=50, backoff_s=0.25, max_backoff_s=0.25,
                jitter=0.0,
            ),
        )
        elapsed = time.monotonic() - t0
        # the last retryable envelope is returned honestly once the budget
        # can't fund another backoff — NOT 50 attempts x 0.25s of spin
        assert resp["ok"] is False
        assert resp["retryable"] is True
        assert elapsed < 3.0
        assert reg.sheds + h.workers[0]._excluded_bounce_total <= 5

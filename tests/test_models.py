"""Model/engine tests on the JAX CPU backend (SURVEY.md §4.3): golden
consistency between prefill and incremental decode, GQA/MoE variants, GGUF
export->load roundtrip, sampling behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import Generator, SamplingParams, default_buckets
from nats_llm_studio_tpu.engine.sampling import sample
from nats_llm_studio_tpu.gguf import GGUFReader
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import (
    forward,
    init_params,
    load_params_from_gguf,
    make_cache,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_forward_shapes(tiny):
    cfg, params = tiny
    k, v = make_cache(cfg, 2, 64)
    tokens = jnp.array([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    logits, k, v = forward(params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32))
    assert logits.shape == (2, 4, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert k.shape == (2, cfg.n_layers, cfg.n_kv_heads, 64, cfg.head_dim)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_unrolled_decode_matches_scan(tiny):
    """decode_unroll=True (static layer indices, view slices) must produce
    identical logits and caches to the scanned decode."""
    cfg, params = tiny
    k, v = make_cache(cfg, 2, 64)
    tokens = jnp.array([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    _, k, v = forward(params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32))
    nxt = jnp.array([[9], [10]], jnp.int32)
    pos = jnp.full((2,), 4, jnp.int32)
    want, k_w, v_w = forward(params, cfg, nxt, k, v, pos)
    cfg_u = cfg.with_(decode_unroll=True)
    got, k_g, v_g = forward(params, cfg_u, nxt, k, v, pos, attn_window=32)
    import numpy as np

    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(k_g), np.asarray(k_w), rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v_g), np.asarray(v_w), rtol=1e-6, atol=1e-6)


def test_ring_decode_matches_positional(tiny):
    """Ring decode with ring_slot == uniform position must equal positional
    decode exactly (same slots, same mask), and further ring steps must stay
    consistent with the growing sequence."""
    import numpy as np

    cfg, params = tiny
    k, v = make_cache(cfg, 2, 64)
    tokens = jnp.array([[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
    _, k, v = forward(params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32))
    nxt = jnp.array([[9], [10]], jnp.int32)
    pos = jnp.full((2,), 4, jnp.int32)
    want, k_w, v_w = forward(params, cfg, nxt, k, v, pos)
    got, k_g, v_g = forward(params, cfg, nxt, k, v, pos, ring_slot=jnp.int32(4))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(k_g), np.asarray(k_w), rtol=1e-6, atol=1e-6)
    # second step continues the ring
    nxt2 = jnp.array([[11], [12]], jnp.int32)
    want2, _, _ = forward(params, cfg, nxt2, k_w, v_w, pos + 1)
    got2, _, _ = forward(params, cfg, nxt2, k_g, v_g, pos + 1, ring_slot=jnp.int32(5))
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2), rtol=1e-5, atol=1e-5)


def test_ring_decode_ragged_rows_and_wrap(tiny):
    """Ragged rows sharing ring slots: each row only sees its own recent
    tokens. Build it two ways — (a) ring steps on a shared cache with rows
    of different lengths, (b) per-row dense reference — and compare."""
    import numpy as np

    cfg, params = tiny
    S = 16
    # reference: row sequence [3,1,4,1,5] decoded one by one, positional
    seq = [3, 1, 4, 1, 5, 9, 2]
    k1, v1 = make_cache(cfg, 1, S)
    logits_ref, k1, v1 = forward(
        params, cfg, jnp.asarray([seq[:3]], jnp.int32), k1, v1, jnp.zeros((1,), jnp.int32)
    )
    ref_logits = []
    for i, t in enumerate(seq[3:]):
        out, k1, v1 = forward(
            params, cfg, jnp.asarray([[t]], jnp.int32), k1, v1,
            jnp.full((1,), 3 + i, jnp.int32),
        )
        ref_logits.append(np.asarray(out[0, -1]))

    # ring: same row admitted at ring head 2 (prefix occupying wrapped slots
    # S-1, 0, 1 ... exercises wraparound), another junk row occupies slot 1
    k, v = make_cache(cfg, 2, S)
    pre_k, pre_v = k1, v1  # [1, L, Hkv, S, D] with prefix at [0..3)
    # place row 0's 3-token prefix so it ENDS at ring head 1 (slots 15,0,1)
    def place(cache, pre, row):
        c = np.array(cache)
        p = np.asarray(pre)
        c[row, :, :, 15] = p[0, :, :, 0]
        c[row, :, :, 0] = p[0, :, :, 1]
        c[row, :, :, 1] = p[0, :, :, 2]
        return jnp.asarray(c)

    k = place(k, pre_k, 0)
    v = place(v, pre_v, 0)
    pos = jnp.asarray([3, 0], jnp.int32)  # row 1 empty (anything it sees is junk)
    ring = 2
    for i, t in enumerate(seq[3:]):
        toks = jnp.asarray([[t], [7]], jnp.int32)
        out, k, v = forward(params, cfg, toks, k, v, pos, ring_slot=jnp.int32(ring))
        np.testing.assert_allclose(
            np.asarray(out[0, -1]), ref_logits[i], rtol=2e-5, atol=2e-5
        )
        pos = pos + 1
        ring = (ring + 1) % S


def test_prefill_decode_consistency(tiny):
    """The golden test: token-by-token decode must reproduce the logits of a
    single full prefill — catches cache-write, mask, and RoPE offset bugs."""
    cfg, params = tiny
    seq = [3, 14, 15, 92, 65, 35, 89]
    full = jnp.asarray([seq], jnp.int32)
    k, v = make_cache(cfg, 1, 32)
    ref_logits, _, _ = forward(params, cfg, full, k, v, jnp.zeros((1,), jnp.int32))

    # prefill 4, decode the remaining 3 one at a time
    k, v = make_cache(cfg, 1, 32)
    logits, k, v = forward(params, cfg, full[:, :4], k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(logits[0, 3], ref_logits[0, 3], rtol=0.02, atol=5e-3)
    for t in range(4, len(seq)):
        logits, k, v = forward(
            params, cfg, full[:, t : t + 1], k, v, jnp.full((1,), t, jnp.int32)
        )
        np.testing.assert_allclose(logits[0, 0], ref_logits[0, t], rtol=0.02, atol=5e-3)


def test_right_padded_batch_matches_unpadded(tiny):
    """Right-padded rows must produce identical logits at real positions."""
    cfg, params = tiny
    k1, v1 = make_cache(cfg, 1, 32)
    a = [7, 8, 9]
    la, _, _ = forward(params, cfg, jnp.asarray([a], jnp.int32), k1, v1, jnp.zeros((1,), jnp.int32))
    k2, v2 = make_cache(cfg, 2, 32)
    batch = jnp.asarray([a + [0, 0], [1, 2, 3, 4, 5]], jnp.int32)
    lb, _, _ = forward(params, cfg, batch, k2, v2, jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(lb[0, : len(a)], la[0], rtol=0.02, atol=5e-3)


def test_mha_variant():
    cfg = ModelConfig.tiny(n_kv_heads=4)  # MHA: kv == q heads
    params = init_params(cfg, jax.random.PRNGKey(1))
    k, v = make_cache(cfg, 1, 16)
    logits, _, _ = forward(params, cfg, jnp.ones((1, 3), jnp.int32), k, v, jnp.zeros((1,), jnp.int32))
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_moe_forward_and_consistency():
    cfg = ModelConfig.tiny(n_experts=4, n_experts_used=2, d_ff=64)
    params = init_params(cfg, jax.random.PRNGKey(2))
    seq = [1, 2, 3, 4, 5]
    full = jnp.asarray([seq], jnp.int32)
    k, v = make_cache(cfg, 1, 16)
    ref, _, _ = forward(params, cfg, full, k, v, jnp.zeros((1,), jnp.int32))
    assert bool(jnp.all(jnp.isfinite(ref)))
    # decode consistency holds for MoE too
    k, v = make_cache(cfg, 1, 16)
    logits, k, v = forward(params, cfg, full[:, :3], k, v, jnp.zeros((1,), jnp.int32))
    for t in range(3, 5):
        logits, k, v = forward(params, cfg, full[:, t : t + 1], k, v, jnp.full((1,), t, jnp.int32))
        np.testing.assert_allclose(logits[0, 0], ref[0, t], rtol=0.02, atol=5e-3)


def test_granite_scales_change_logits(tiny):
    cfg, params = tiny
    g = cfg.with_(arch="granite", embedding_scale=2.0, residual_scale=0.5, logit_scale=0.25)
    k, v = make_cache(cfg, 1, 16)
    tokens = jnp.asarray([[1, 2, 3]], jnp.int32)
    base, _, _ = forward(params, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    k, v = make_cache(cfg, 1, 16)
    scaled, _, _ = forward(params, g, tokens, k, v, jnp.zeros((1,), jnp.int32))
    assert not np.allclose(base, scaled)


def test_gguf_export_load_roundtrip(tmp_path, tiny):
    cfg, params = tiny
    path = tmp_path / "tiny.gguf"
    export_params_to_gguf(path, params, cfg, name="tiny-rt")
    with GGUFReader(path) as r:
        cfg2 = ModelConfig.from_gguf_metadata(r.metadata).with_(dtype="float32")
        assert cfg2.n_layers == cfg.n_layers
        assert cfg2.n_kv_heads == cfg.n_kv_heads
        assert cfg2.head_dim == cfg.head_dim
        params2 = load_params_from_gguf(r, cfg2)
    tokens = jnp.asarray([[9, 8, 7, 6]], jnp.int32)
    k, v = make_cache(cfg, 1, 16)
    a, _, _ = forward(params, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    k, v = make_cache(cfg2, 1, 16)
    b, _, _ = forward(params2, cfg2, tokens, k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_gguf_export_load_roundtrip_moe(tmp_path):
    cfg = ModelConfig.tiny(n_experts=4, n_experts_used=2, d_ff=64)
    params = init_params(cfg, jax.random.PRNGKey(3))
    path = tmp_path / "tiny-moe.gguf"
    export_params_to_gguf(path, params, cfg, name="tiny-moe")
    with GGUFReader(path) as r:
        cfg2 = ModelConfig.from_gguf_metadata(r.metadata).with_(dtype="float32")
        assert cfg2.is_moe and cfg2.n_experts == 4
        params2 = load_params_from_gguf(r, cfg2)
    tokens = jnp.asarray([[5, 4, 3]], jnp.int32)
    k, v = make_cache(cfg, 1, 16)
    a, _, _ = forward(params, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    k, v = make_cache(cfg2, 1, 16)
    b, _, _ = forward(params2, cfg2, tokens, k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


def test_sample_greedy():
    logits = jnp.asarray([[0.1, 5.0, 0.2, 0.3], [4.0, 0.0, 0.0, 0.0]], jnp.float32)
    out = sample(logits, jax.random.PRNGKey(0), temperature=0.0)
    assert out.tolist() == [1, 0]


def test_sample_top_p_narrow_is_greedy():
    logits = jnp.asarray([[0.0, 8.0, 1.0, 2.0]], jnp.float32)
    for seed in range(5):
        out = sample(logits, jax.random.PRNGKey(seed), temperature=1.0, top_p=0.01)
        assert out.tolist() == [1]


def test_sample_top_k_limits_support():
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0, 5.0]], jnp.float32)
    seen = set()
    for seed in range(40):
        out = sample(logits, jax.random.PRNGKey(seed), temperature=2.0, top_k=2)
        seen.add(int(out[0]))
    assert seen <= {3, 4}
    assert len(seen) == 2  # both of the top-2 actually reachable


def test_sample_per_row_params():
    logits = jnp.tile(jnp.asarray([[0.0, 3.0, 1.0, 2.0]], jnp.float32), (2, 1))
    temp = jnp.asarray([0.0, 5.0])  # row0 greedy, row1 hot
    outs = {tuple(sample(logits, jax.random.PRNGKey(s), temperature=temp).tolist()) for s in range(30)}
    assert all(o[0] == 1 for o in outs)  # greedy row fixed
    assert len({o[1] for o in outs}) > 1  # hot row varies


# ---------------------------------------------------------------------------
# generator
# ---------------------------------------------------------------------------


def test_default_buckets():
    assert default_buckets(256, 32) == [32, 64, 128, 256]
    assert default_buckets(100, 32) == [32, 64, 100]


def test_generator_streams_and_stops(tiny):
    cfg, params = tiny
    gen = Generator(params, cfg, max_seq_len=64, buckets=[8, 16, 32, 64])
    sp = SamplingParams(temperature=0.0, max_tokens=8, seed=0)
    toks = [t for t, _ in gen.generate([1, 2, 3], sp)]
    assert 0 < len(toks) <= 8
    assert all(0 <= t < cfg.vocab_size for t in toks)
    # greedy determinism
    toks2 = [t for t, _ in gen.generate([1, 2, 3], sp)]
    assert toks == toks2


def test_generator_matches_forward_greedy(tiny):
    """Generator's bucketed prefill + fused decode must equal raw forward."""
    cfg, params = tiny
    prompt = [5, 6, 7]
    gen = Generator(params, cfg, max_seq_len=32, buckets=[4, 8, 16, 32])
    got = [t for t, _ in gen.generate(prompt, SamplingParams(temperature=0.0, max_tokens=4))]

    k, v = make_cache(cfg, 1, 32)
    ids = list(prompt)
    logits, k, v = forward(params, cfg, jnp.asarray([ids], jnp.int32), k, v, jnp.zeros((1,), jnp.int32))
    want = []
    nxt = int(jnp.argmax(logits[0, len(ids) - 1]))
    for step in range(4):
        want.append(nxt)
        logits, k, v = forward(
            params, cfg, jnp.asarray([[nxt]], jnp.int32), k, v,
            jnp.full((1,), len(ids) + step, jnp.int32),
        )
        nxt = int(jnp.argmax(logits[0, 0]))
    assert got == want


def test_generator_stop_ids(tiny):
    cfg, params = tiny
    gen = Generator(params, cfg, max_seq_len=32, buckets=[8, 32])
    # find the first greedy token, then declare it a stop id
    first = next(gen.generate([1, 2], SamplingParams(temperature=0.0, max_tokens=1)))[0]
    out = [
        t
        for t, _ in gen.generate(
            [1, 2], SamplingParams(temperature=0.0, max_tokens=8, stop_ids=frozenset({first}))
        )
    ]
    assert out == []


def test_generator_stats(tiny):
    cfg, params = tiny
    gen = Generator(params, cfg, max_seq_len=32, buckets=[8, 32])
    stats = None
    for _, stats in gen.generate([1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=5)):
        pass
    assert stats is not None
    assert stats.prompt_tokens == 4
    assert stats.completion_tokens >= 1
    assert stats.ttft_s > 0


def test_qwen2_bias_forward_and_roundtrip(tmp_path):
    """Qwen2-family: QKV biases change the logits, survive prefill/decode
    consistency, and round-trip through GGUF (including the rope pair
    permutation applied to q/k biases)."""
    cfg = ModelConfig.tiny(arch="qwen2", n_layers=2, attn_bias=True)
    params = init_params(cfg, jax.random.PRNGKey(3))
    assert "bq" in params["blocks"]
    tokens = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    k, v = make_cache(cfg, 1, 16)
    with_bias, k, v = forward(params, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    # decode step must match the full 5-token prefill at the same position
    # (pins the bias path through t==1 decode, not just prefill)
    nxt, _, _ = forward(
        params, cfg, jnp.asarray([[9]], jnp.int32), k, v, jnp.full((1,), 4, jnp.int32)
    )
    k5, v5 = make_cache(cfg, 1, 16)
    full5, _, _ = forward(
        params, cfg, jnp.asarray([[5, 6, 7, 8, 9]], jnp.int32), k5, v5,
        jnp.zeros((1,), jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(nxt[0, -1]), np.asarray(full5[0, -1]), rtol=2e-4, atol=2e-4
    )
    zeroed = dict(params)
    zeroed["blocks"] = dict(params["blocks"])
    for bk_ in ("bq", "bk", "bv"):
        zeroed["blocks"][bk_] = jnp.zeros_like(params["blocks"][bk_])
    k0, v0 = make_cache(cfg, 1, 16)
    no_bias, _, _ = forward(zeroed, cfg, tokens, k0, v0, jnp.zeros((1,), jnp.int32))
    assert not np.allclose(np.asarray(with_bias), np.asarray(no_bias))

    path = tmp_path / "qwen2.gguf"
    export_params_to_gguf(path, params, cfg, name="tiny-qwen2")
    with GGUFReader(path) as r:
        cfg2 = ModelConfig.from_gguf_metadata(r.metadata).with_(dtype="float32")
        assert cfg2.attn_bias  # derived from the architecture name
        params2 = load_params_from_gguf(r, cfg2)
    k2, v2 = make_cache(cfg2, 1, 16)
    again, _, _ = forward(params2, cfg2, tokens, k2, v2, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(again), np.asarray(with_bias), rtol=1e-5, atol=1e-5)


def test_gemma_family_forward_and_roundtrip(tmp_path):
    """Gemma-family: GELU MLP, tied embeddings with
    sqrt(d_model) embedding scaling — all derived from the arch name and
    consistent through prefill/decode and the GGUF round-trip."""
    cfg = ModelConfig.tiny(
        arch="gemma", n_layers=2, mlp_act="gelu",
        tie_embeddings=True, embedding_scale=8.0,  # sqrt(64)
    )
    params = init_params(cfg, jax.random.PRNGKey(4))
    assert "lm_head" not in params  # tied
    seq = [3, 14, 15, 9, 2, 6]
    k, v = make_cache(cfg, 1, 16)
    full, _, _ = forward(
        params, cfg, jnp.asarray([seq], jnp.int32), k, v, jnp.zeros((1,), jnp.int32)
    )
    # token-by-token decode reproduces the full prefill logits
    k, v = make_cache(cfg, 1, 16)
    _, k, v = forward(
        params, cfg, jnp.asarray([seq[:3]], jnp.int32), k, v, jnp.zeros((1,), jnp.int32)
    )
    outs = []
    for i, t in enumerate(seq[3:]):
        o, k, v = forward(
            params, cfg, jnp.asarray([[t]], jnp.int32), k, v,
            jnp.full((1,), 3 + i, jnp.int32),
        )
        outs.append(np.asarray(o[0, -1]))
    np.testing.assert_allclose(outs[-1], np.asarray(full[0, -1]), rtol=2e-4, atol=2e-4)

    path = tmp_path / "gemma.gguf"
    export_params_to_gguf(path, params, cfg, name="tiny-gemma")
    with GGUFReader(path) as r:
        cfg2 = ModelConfig.from_gguf_metadata(r.metadata).with_(dtype="float32")
        assert cfg2.mlp_act == "gelu" and not cfg2.norm_plus_one
        # (GGUF stores gemma norms with the +1 already folded in)
        assert cfg2.embedding_scale == 8.0
        params2 = load_params_from_gguf(r, cfg2)
    k2, v2 = make_cache(cfg2, 1, 16)
    again, _, _ = forward(
        params2, cfg2, jnp.asarray([seq], jnp.int32), k2, v2, jnp.zeros((1,), jnp.int32)
    )
    np.testing.assert_allclose(np.asarray(again), np.asarray(full), rtol=1e-5, atol=1e-5)


def test_unsupported_archs_rejected():
    """Architectures whose topology the model does not implement must fail
    loudly at config time, not half-run to garbage logits."""
    for arch in ("gemma2", "qwen2moe"):
        md = {"general.architecture": arch, f"{arch}.block_count": 2,
              f"{arch}.embedding_length": 64, f"{arch}.attention.head_count": 4}
        with pytest.raises(NotImplementedError):
            ModelConfig.from_gguf_metadata(md)

"""Split GGUF (llama.cpp gguf-split layout): shard auto-detection, merged
tensor view, and model loading parity with the single-file form — the shape
70B-class public checkpoints actually ship in."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.gguf import GGUFReader, GGUFShardedReader, open_gguf
from nats_llm_studio_tpu.gguf.writer import GGUFWriter
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import (
    forward,
    init_params,
    load_params_from_gguf,
    make_cache,
)


def _make_split(tmp_path, cfg, params, n_shards=2):
    """Re-emit a single-file export as a gguf-split shard set."""
    single = tmp_path / "model.gguf"
    export_params_to_gguf(single, params, cfg, name="tiny-split")
    with GGUFReader(single) as r:
        md = dict(r.metadata)
        names = list(r.tensors)
        arrays = {n: r.tensors[n].to_numpy().copy() for n in names}
        types = {n: r.tensors[n].ggml_type for n in names}
    per = -(-len(names) // n_shards)
    paths = []
    for i in range(n_shards):
        p = tmp_path / f"model-{i + 1:05d}-of-{n_shards:05d}.gguf"
        w = GGUFWriter(p)
        shard_md = dict(md) if i == 0 else {
            "general.architecture": md["general.architecture"]
        }
        shard_md |= {"split.no": i, "split.count": n_shards,
                     "split.tensors.count": len(names)}
        w.add_dict(shard_md)
        for n in names[i * per : (i + 1) * per]:
            w.add_tensor(n, arrays[n], types[n])
        w.write()
        paths.append(p)
    return single, paths


def test_split_auto_detect_and_parity(tmp_path):
    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(5))
    single, paths = _make_split(tmp_path, cfg, params)

    with GGUFReader(single) as ref:
        want_names = set(ref.tensors)
        cfg1 = ModelConfig.from_gguf_metadata(ref.metadata).with_(dtype="float32")
        p1 = load_params_from_gguf(ref, cfg1)

    # passing any shard path auto-discovers the siblings
    with open_gguf(paths[0]) as r:
        assert isinstance(r, GGUFShardedReader)
        assert set(r.tensors) == want_names
        cfg2 = ModelConfig.from_gguf_metadata(r.metadata).with_(dtype="float32")
        p2 = load_params_from_gguf(r, cfg2)

    tokens = jnp.asarray([[7, 8, 9, 10]], jnp.int32)
    k, v = make_cache(cfg1, 1, 16)
    a, _, _ = forward(p1, cfg1, tokens, k, v, jnp.zeros((1,), jnp.int32))
    k, v = make_cache(cfg2, 1, 16)
    b, _, _ = forward(p2, cfg2, tokens, k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_split_missing_shard_raises(tmp_path):
    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(5))
    _, paths = _make_split(tmp_path, cfg, params)
    paths[1].unlink()
    with pytest.raises(FileNotFoundError):
        open_gguf(paths[0])


def test_split_count_mismatch_raises(tmp_path):
    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(5))
    _, paths = _make_split(tmp_path, cfg, params, n_shards=2)
    with pytest.raises(ValueError):
        GGUFShardedReader([paths[0]])


def test_registry_loads_split_model(tmp_path):
    """LocalRegistry serves a model cached as a gguf-split shard set."""
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from test_serve_e2e import byte_level_tokenizer_md

    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store import ModelStore

    cfg = ModelConfig.tiny(vocab_size=300, n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(6))
    # export WITH tokenizer metadata, then shard it
    single = tmp_path / "m.gguf"
    export_params_to_gguf(
        single, params, cfg, tokenizer_md=byte_level_tokenizer_md(300), name="split-e2e"
    )
    with GGUFReader(single) as r:
        md = dict(r.metadata)
        names = list(r.tensors)
        arrays = {n: r.tensors[n].to_numpy().copy() for n in names}
        types = {n: r.tensors[n].ggml_type for n in names}
    model_dir = tmp_path / "models" / "acme" / "split"
    model_dir.mkdir(parents=True)
    per = -(-len(names) // 2)
    for i in range(2):
        w = GGUFWriter(model_dir / f"m-{i + 1:05d}-of-00002.gguf")
        shard_md = dict(md) if i == 0 else {"general.architecture": md["general.architecture"]}
        shard_md |= {"split.no": i, "split.count": 2, "split.tensors.count": len(names)}
        w.add_dict(shard_md)
        for n in names[i * per : (i + 1) * per]:
            w.add_tensor(n, arrays[n], types[n])
        w.write()

    reg = LocalRegistry(ModelStore(tmp_path / "models"), dtype="float32")

    async def drive():
        eng = await reg.get_engine("acme/split")
        out = await eng.chat(
            {"model": "acme/split", "messages": [{"role": "user", "content": "hi"}],
             "max_tokens": 4, "temperature": 0.0}
        )
        assert out["usage"]["completion_tokens"] == 4
        await eng.unload()

    import asyncio

    asyncio.run(drive())


def test_publish_and_pull_split_set(tmp_path):
    """publish_model uploads every shard; pulling by model id fetches the
    whole set, so the destination cache can actually load the model."""
    import asyncio

    from nats_llm_studio_tpu.store import JetStreamStoreModule, ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect
    from nats_llm_studio_tpu.transport.jetstream import ObjectStore

    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(5))
    src_dir = tmp_path / "src"
    src_dir.mkdir()
    _, paths = _make_split(src_dir, cfg, params)

    async def drive():
        broker = await EmbeddedBroker().start()
        JetStreamStoreModule(broker).install()
        nc = await connect(broker.url)
        objstore = ObjectStore(nc, timeout=5.0)
        ms_a = ModelStore(tmp_path / "worker_a", objstore=objstore)
        adir = ms_a.model_dir("acme/big")
        adir.mkdir(parents=True)
        for p in paths:
            (adir / p.name).write_bytes(p.read_bytes())
        await ms_a.publish_model("acme/big")
        ms_b = ModelStore(tmp_path / "worker_b", objstore=objstore)
        dest, transcript = await ms_b.pull("acme/big")
        got = sorted(f.name for f in ms_b.lookup("acme/big").files)
        assert got == sorted(p.name for p in paths), transcript
        # and the pulled set loads as one model
        with open_gguf(str(ms_b.model_dir("acme/big") / paths[0].name)) as r:
            assert isinstance(r, GGUFShardedReader)
        await nc.close()
        await broker.stop()

    asyncio.run(drive())


def test_pull_incomplete_split_set_fails_loudly(tmp_path):
    """A bucket holding only part of a shard set must fail the pull (and
    leave nothing committed in the cache) rather than cache an unloadable
    model."""
    import asyncio

    from nats_llm_studio_tpu.store import JetStreamStoreModule, ModelStore
    from nats_llm_studio_tpu.store.manager import StoreError
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect
    from nats_llm_studio_tpu.transport.jetstream import ObjectStore

    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(5))
    src_dir = tmp_path / "src"
    src_dir.mkdir()
    _, paths = _make_split(src_dir, cfg, params)

    async def drive():
        broker = await EmbeddedBroker().start()
        JetStreamStoreModule(broker).install()
        nc = await connect(broker.url)
        objstore = ObjectStore(nc, timeout=5.0)
        await objstore.ensure_bucket("llm-models")
        # only shard 1 of 2 makes it to the bucket
        await objstore.put(
            "llm-models", f"acme/big/{paths[0].name}", paths[0].read_bytes()
        )
        ms = ModelStore(tmp_path / "worker", objstore=objstore)
        with pytest.raises(StoreError, match="incomplete split set"):
            await ms.pull("acme/big")
        assert ms.lookup("acme/big") is None  # nothing committed
        await nc.close()
        await broker.stop()

    asyncio.run(drive())

"""Hierarchical KV tiers + slot suspend/resume (PR 19 tentpole).

Three layers of pinning:

* ``KVTierManager`` unit behavior — demote/lookup/LRU-to-spill round trips,
  restart ``warm_exports`` chain reassembly, and chaos containment (a
  severed Object Store mid-demotion loses only the cold copy; a faulted
  fetch is an honest miss).
* Engine bit-identity — chunks demoted out of the HBM prefix cache and
  promoted back (dense, int8 KVQ, tp=2 on the forced host devices) must
  reproduce the plain paged greedy sequence exactly, and a slot suspended
  under pool pressure (swap-don't-shed) must resume and finish with the
  ample-pool greedy tokens — including mid-spec-decode and schema-
  constrained slots, whose DFA state rides the suspended request.
* Bookkeeping — the pool is fully free after a suspend/resume storm, a
  suspended slot's deadline keeps running, and the SUSPEND chaos hook
  falls back to the honest retryable shed without stranding a refcount.
"""

import asyncio
import time

import jax
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.sharding import shard_params
from nats_llm_studio_tpu.serve.batcher import BatcherOverloaded, ContinuousBatcher
from nats_llm_studio_tpu.serve.constrain import TokenDFA
from nats_llm_studio_tpu.serve.kv_tiers import (
    KVTierManager,
    MemorySpillStore,
    path_hash,
)
from nats_llm_studio_tpu.transport import faults

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompt(n, mul=7, add=3, vocab=509):
    return [(i * mul + add) % vocab for i in range(n)]


async def _serve(b, prompts, n, constrain=None):
    sp = SamplingParams(temperature=0.0, max_tokens=n)

    async def one(p):
        return [t async for t in b.submit(p, sp, constrain=constrain)]

    return await asyncio.gather(*[one(p) for p in prompts])


async def _wait(pred, timeout=10.0, what="condition"):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.002)


# -- KVTierManager unit behavior ---------------------------------------------


def _leaves(seed, shape=(2, 4, 16, 8)):
    rng = np.random.default_rng(seed)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


def test_tier_demote_lookup_and_lru_spill_roundtrip():
    """Host LRU honors the byte budget by evicting to the spill store; the
    evicted entry comes back through ``lookup`` as a fetched blob."""
    store = MemorySpillStore()
    k, v = _leaves(1)
    entry_bytes = 2 * k.nbytes
    m = KVTierManager(2 * entry_bytes + 64, chunk_tokens=16, spill=store,
                      namespace="kv/t")
    try:
        keys = [tuple(range(i * 16, i * 16 + 16)) for i in range(3)]
        for i, key in enumerate(keys):
            ki, vi = _leaves(10 + i)
            assert m.demote(key, ki, vi, None)
        assert m.flush(), "spill thread did not drain"
        st = m.stats()
        # 3 demoted into a 2-entry budget: the LRU (keys[0]) spilled
        assert st["demoted_chunks"] == 3
        assert st["host_entries"] == 2
        assert st["host_evictions"] == 1 and st["spilled_blobs"] == 1
        # host hit refreshes recency
        assert m.lookup(keys[2]) is not None
        assert m.stats()["host_hits"] == 1
        # the spilled key round-trips: miss in host, fetched from the store
        got = m.lookup(keys[0])
        assert got is not None
        k0, _ = _leaves(10)
        assert np.array_equal(got.k, k0)
        st = m.stats()
        assert st["fetched_blobs"] == 1 and st["fetch_failures"] == 0
    finally:
        m.close()


def test_tier_warm_exports_skips_chain_with_missing_ancestor():
    """Restart reassembly only returns COMPLETE root→leaf chains: a chain
    whose ancestor blob was lost is skipped, never half-imported."""
    store = MemorySpillStore()
    a = [1] * 16, [1] * 32  # two chunk-prefix keys of chain A
    bb = [2] * 16, [2] * 32
    m = KVTierManager(0, chunk_tokens=16, spill=store, namespace="kv/w")
    try:
        for depth_keys in (a, bb):
            for key in depth_keys:
                ki, vi = _leaves(sum(key))
                m.demote(key, ki, vi, None)
        assert m.flush()
    finally:
        m.close()
    # lose chain A's root blob (index entry survives — the realistic
    # partial-failure shape after an Object Store prune or flake)
    store.delete(f"kv/w/{path_hash(tuple(a[0]))}")
    m2 = KVTierManager(0, chunk_tokens=16, spill=store, namespace="kv/w")
    try:
        exports = m2.warm_exports(limit=4)
        assert len(exports) == 1
        assert exports[0]["token_ids"] == [2] * 32
        assert len(exports[0]["chunks"]) == 2
    finally:
        m2.close()


def test_tier_spill_sever_is_contained():
    """A store severed mid-demotion loses exactly that blob: the failure is
    counted, later spills land, and the index never references a blob that
    was not written."""
    store = MemorySpillStore()
    faults.install(faults.FaultPlan().sever(faults.TIER_SPILL, 0))
    m = KVTierManager(0, chunk_tokens=16, spill=store, namespace="kv/s")
    try:
        for i in range(3):
            ki, vi = _leaves(20 + i)
            m.demote(tuple(range(i * 16, i * 16 + 16)), ki, vi, None)
        assert m.flush()
        st = m.stats()
        assert st["spill_failures"] == 1
        assert st["spilled_blobs"] == 2
    finally:
        faults.clear()
        m.close()
    import json

    idx = json.loads(store.get("kv/s/index"))
    assert len(idx) == 2
    for h in idx:
        assert store.get(f"kv/s/{h}") is not None, "index points at lost blob"


def test_tier_fetch_fault_is_honest_miss():
    """A faulted Object Store read is a counted miss, not corruption — and
    the next lookup (rule fired) succeeds."""
    store = MemorySpillStore()
    m = KVTierManager(0, chunk_tokens=16, spill=store, namespace="kv/f")
    try:
        key = tuple(range(16))
        ki, vi = _leaves(33)
        m.demote(key, ki, vi, None)
        assert m.flush()
        faults.install(faults.FaultPlan().drop(faults.TIER_FETCH, 0))
        try:
            assert m.lookup(key) is None
            assert m.stats()["fetch_failures"] == 1
        finally:
            faults.clear()
        got = m.lookup(key)
        assert got is not None and np.array_equal(got.k, ki)
    finally:
        m.close()


# -- demote → promote bit-identity through the engine ------------------------


def _tiered_batcher(params, cfg, mesh=None, spill=None, host_bytes=32 << 20,
                    **kw):
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], mesh=mesh, prefill_chunk=16,
                          prefix_cache_blocks=2, paged=True, **kw)
    b.kv_tiers = KVTierManager(host_bytes, chunk_tokens=b.prefill_chunk,
                               spill=spill, namespace="kv/test")
    return b


async def _demote_promote_cycle(b, p, q, n=6):
    """Serve P (caches 2 chunks), serve Q (evicts P's chunks → demote),
    re-serve P (promotion-on-hit). Returns (first, second) token lists."""
    first = (await _serve(b, [p], n))[0]
    await _serve(b, [q], n)
    second = (await _serve(b, [p], n))[0]
    return first, second


@async_test
async def test_demote_promote_bit_identity_dense(model):
    cfg, params = model
    p, q = _prompt(40), _prompt(40, mul=11, add=5)
    base = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                             buckets=[8, 64], prefill_chunk=16, paged=True)
    try:
        want = (await _serve(base, [p], 6))[0]
    finally:
        base.stop()
    b = _tiered_batcher(params, cfg)
    try:
        first, second = await _demote_promote_cycle(b, p, q)
        assert first == want
        assert second == want
        st = b.kv_tiers.stats()
        assert st["demoted_chunks"] >= 2, st
        assert st["promoted_chunks"] >= 2, st
        assert b.prefix_cache.hit_tokens >= 32
    finally:
        b.stop()


@pytest.mark.slow
@async_test
async def test_demote_promote_bit_identity_kvq(model):
    """int8 KV chunks demote as (codes, scales) pairs and promote back
    bit-identically against the same quantized engine without tiers."""
    cfg, params = model
    cfg_q = cfg.with_(kv_quant="int8")
    p, q = _prompt(40), _prompt(40, mul=11, add=5)
    base = ContinuousBatcher(params, cfg_q, max_slots=2, max_seq_len=64,
                             buckets=[8, 64], prefill_chunk=16, paged=True)
    try:
        want = (await _serve(base, [p], 6))[0]
    finally:
        base.stop()
    b = _tiered_batcher(params, cfg_q)
    try:
        first, second = await _demote_promote_cycle(b, p, q)
        assert first == want
        assert second == want
        assert b.kv_tiers.stats()["promoted_chunks"] >= 2
    finally:
        b.stop()


@pytest.mark.slow
@async_test
async def test_demote_promote_bit_identity_tp2(model):
    """Promotion writes land in the tp-sharded pool (re-pinned sharding)
    and still reproduce the unsharded greedy sequence."""
    cfg, params = model
    p, q = _prompt(40), _prompt(40, mul=11, add=5)
    base = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                             buckets=[8, 64], prefill_chunk=16, paged=True)
    try:
        want = (await _serve(base, [p], 6))[0]
    finally:
        base.stop()
    mesh = build_mesh("tp=2", devices=jax.devices()[:2])
    sharded = shard_params(params, mesh, cfg)
    b = _tiered_batcher(sharded, cfg, mesh=mesh)
    try:
        first, second = await _demote_promote_cycle(b, p, q)
        assert first == want
        assert second == want
        assert b.kv_tiers.stats()["promoted_chunks"] >= 2
    finally:
        b.stop()


# -- slot suspend/resume: swap-don't-shed ------------------------------------

# deterministic pool-pressure geometry (32-token blocks, max_seq 64 → at
# most 2 blocks per row, so NO slot ever grows mid-decode):
#   usable pool = 3 blocks; A (33-token prompt) admits with 2, decodes;
#   B (40-token prompt) needs 2 — its second chunk alloc fails with 1 free,
#   suspends A (frees 2), B admits and finishes, A resumes and finishes.
_SUSPEND_KW = dict(max_slots=2, max_seq_len=64, buckets=[8, 64],
                   prefill_chunk=32, kv_block_tokens=32, kv_pool_blocks=3,
                   decode_burst=1, admit_coalesce_ms=0.0, paged=True)


async def _pressure_pair(b, pa, pb, na, nb, constrain=None):
    """A first; once 2 of A's tokens arrived, B — whose admit exhausts the
    3-block pool. Returns (a_tokens, b_tokens)."""
    spa = SamplingParams(temperature=0.0, max_tokens=na)
    spb = SamplingParams(temperature=0.0, max_tokens=nb)
    started = asyncio.get_running_loop().create_future()

    async def run_a():
        out = []
        async for t in b.submit(pa, spa, constrain=constrain):
            out.append(t)
            if len(out) == 2 and not started.done():
                started.set_result(None)
        return out

    async def run_b():
        return [t async for t in b.submit(pb, spb, constrain=constrain)]

    ta = asyncio.ensure_future(run_a())
    await started
    tb = asyncio.ensure_future(run_b())
    return await ta, await tb


@async_test
async def test_suspend_resume_greedy_bit_identity(model):
    cfg, params = model
    pa, pb = _prompt(33), _prompt(40, mul=11, add=5)
    ample = ContinuousBatcher(params, cfg, **{**_SUSPEND_KW,
                                              "kv_pool_blocks": 0})
    try:
        want_a, want_b = await _serve(ample, [pa, pb], 12)
        want_b = want_b[:8]
    finally:
        ample.stop()
    b = ContinuousBatcher(params, cfg, **_SUSPEND_KW)
    try:
        got_a, got_b = await _pressure_pair(b, pa, pb, 12, 8)
        assert got_a == want_a, "suspended slot did not resume bit-identically"
        assert got_b == want_b
        assert b._suspend_stats["suspended_total"] >= 1
        assert b._suspend_stats["resumed_total"] >= 1
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 0
        await _wait(lambda: b.idle, what="slots drained")
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"], st
    finally:
        b.stop()


@pytest.mark.slow
@async_test
async def test_suspend_resume_mid_spec_decode(model):
    """The spec-decode slot mirror (draft state, rng steps) rides the
    suspended record; resume continues the exact greedy sequence."""
    cfg, params = model
    pa = ([3, 4, 5] * 9 + _prompt(6, mul=13))[:33]  # repetition: drafts hit
    pb = _prompt(40, mul=11, add=5)
    kw = {**_SUSPEND_KW, "spec_decode_k": 4}
    ample = ContinuousBatcher(params, cfg, **{**kw, "kv_pool_blocks": 0})
    try:
        want_a, want_b = await _serve(ample, [pa, pb], 12)
        want_b = want_b[:8]
    finally:
        ample.stop()
    b = ContinuousBatcher(params, cfg, **kw)
    try:
        got_a, got_b = await _pressure_pair(b, pa, pb, 12, 8)
        assert got_a == want_a
        assert got_b == want_b
        assert b._suspend_stats["suspended_total"] >= 1
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 0
    finally:
        b.stop()


class _EvenCharDFA:
    """Char DFA whose alphabet is just 'e': lifted over a vocabulary where
    even token ids map to 'e' and odd ids to no surface string, it bans
    every odd token forever — a real mask the ext decode path must apply
    on every step, before and after the suspension."""

    start = 0

    def step(self, state, ch):
        return 0 if ch == "e" else None

    def accepting(self, state):
        return True


def _even_dfa(vocab):
    strings = ["e" if t % 2 == 0 else None for t in range(vocab)]
    return TokenDFA(_EvenCharDFA(), strings, vocab, frozenset())


@pytest.mark.slow
@async_test
async def test_suspend_resume_constrained_slot(model):
    """A schema-constrained (ext-regime) slot suspends and resumes with its
    DFA state intact: output stays all-even and bit-identical."""
    cfg, params = model
    dfa = _even_dfa(cfg.vocab_size)
    pa, pb = _prompt(33), _prompt(40, mul=11, add=5)
    ample = ContinuousBatcher(params, cfg, **{**_SUSPEND_KW,
                                              "kv_pool_blocks": 0})
    try:
        want_a, want_b = await _serve(ample, [pa, pb], 12, constrain=dfa)
        want_b = want_b[:8]
    finally:
        ample.stop()
    b = ContinuousBatcher(params, cfg, **_SUSPEND_KW)
    try:
        got_a, got_b = await _pressure_pair(b, pa, pb, 12, 8, constrain=dfa)
        assert got_a == want_a and all(t % 2 == 0 for t in got_a)
        assert got_b == want_b
        assert b._suspend_stats["suspended_total"] >= 1
    finally:
        b.stop()


@pytest.mark.slow
@async_test
async def test_pool_fully_free_after_suspend_resume_storm(model):
    """Six no-growth requests over a 6-block pool on 4 slots: admissions
    must suspend victims (never shed), every request finishes with the
    ample-pool tokens, and the pool is fully free at the end."""
    cfg, params = model
    prompts = [_prompt(40, mul=5 + i, add=i) for i in range(6)]
    ample = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                              buckets=[8, 64], prefill_chunk=16, paged=True)
    try:
        want = await _serve(ample, prompts, 8)
    finally:
        ample.stop()
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                          buckets=[8, 64], prefill_chunk=16,
                          kv_pool_blocks=6, prefix_cache_blocks=2,
                          decode_burst=1, admit_coalesce_ms=0.0, paged=True)
    b.kv_tiers = KVTierManager(1 << 20, chunk_tokens=b.prefill_chunk)
    try:
        # max_tokens=8 keeps every row at exactly 3 blocks (48 tokens, no
        # growth) while keeping slots live long enough that later admits
        # in the same group hit a genuinely occupied pool
        got = await _serve(b, prompts, 8)
        assert got == want
        assert b._suspend_stats["suspended_total"] >= 1
        assert b._suspend_stats["resumed_total"] == \
            b._suspend_stats["suspended_total"]
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 0
        await _wait(lambda: b.idle, what="slots drained")
        assert not b._suspended
        b.drop_prefix_cache()
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"], st
        assert st["blocks_live"] == 0
    finally:
        b.stop()


@async_test
async def test_suspended_slot_deadline_keeps_running(model):
    """Brownout/deadline interaction: parking a slot does not stop its
    clock — an expired suspended request is failed with the retryable
    deadline cause instead of resuming into a blown budget."""
    cfg, params = model
    pa, pb = _prompt(33), _prompt(40, mul=11, add=5)
    b = ContinuousBatcher(params, cfg, **_SUSPEND_KW)
    try:
        spa = SamplingParams(temperature=0.0, max_tokens=20)
        spb = SamplingParams(temperature=0.0, max_tokens=20)
        started = asyncio.get_running_loop().create_future()

        async def run_a():
            out = []
            async for t in b.submit(pa, spa):
                out.append(t)
                if len(out) == 2 and not started.done():
                    started.set_result(None)
            return out

        ta = asyncio.ensure_future(run_a())
        await started
        tb = asyncio.ensure_future(_serve(b, [pb], 20))
        await _wait(lambda: b._suspended, what="slot suspension")
        # the clock ran out while parked (owner sweeps suspended slots
        # every tick, so this is observed before any resume)
        b._suspended[0].req.deadline = time.monotonic() - 1.0
        with pytest.raises(BatcherOverloaded) as ei:
            await ta
        assert "deadline exceeded while suspended" in str(ei.value)
        assert "retry" in str(ei.value)
        await tb  # the admit that caused the suspension still serves
        assert b._suspend_stats["suspended_deadline_expired"] == 1
        assert b.stats.shed_cause_counts().get("deadline", 0) == 1
        await _wait(lambda: b.idle, what="slots drained")
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"], st
    finally:
        b.stop()


@async_test
async def test_suspend_fault_falls_back_to_retryable_shed(model):
    """Chaos SUSPEND drop (worker dying mid-suspend): the victim slot is
    untouched, the admit that needed its blocks sheds honestly retryable,
    and no refcount is stranded."""
    cfg, params = model
    pa, pb = _prompt(33), _prompt(40, mul=11, add=5)
    b = ContinuousBatcher(params, cfg, **_SUSPEND_KW)
    faults.install(faults.FaultPlan().drop(faults.SUSPEND, 0))
    try:
        spa = SamplingParams(temperature=0.0, max_tokens=12)
        started = asyncio.get_running_loop().create_future()

        async def run_a():
            out = []
            async for t in b.submit(pa, spa):
                out.append(t)
                if len(out) == 2 and not started.done():
                    started.set_result(None)
            return out

        ta = asyncio.ensure_future(run_a())
        await started
        with pytest.raises(BatcherOverloaded) as ei:
            await _serve(b, [pb], 8)
        assert "retry" in str(ei.value)
        got_a = await ta  # the would-be victim kept decoding untouched
        assert len(got_a) == 12
        assert b._suspend_stats["suspend_failures"] >= 1
        assert b._suspend_stats["suspended_total"] == 0
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 1
        await _wait(lambda: b.idle, what="slots drained")
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"], st
    finally:
        faults.clear()
        b.stop()


@pytest.mark.slow
@async_test
async def test_decode_growth_exhaustion_suspends_grower(model):
    """Mid-decode table growth that finds the pool empty parks the growing
    slot (zero lost work) instead of shedding it — it resumes, regrows,
    and finishes with the ample-pool greedy tokens.

    16-token blocks, usable pool = 4: A (20-token prompt, 14 new) admits
    with 2 blocks and must grow a 3rd at position 32; B (17-token prompt,
    15 new) admits with 2 and never grows. Both decode in lockstep, so A's
    growth hits free=0 while B is still live."""
    cfg, params = model
    pa = _prompt(20)
    pb = _prompt(17, mul=11, add=5)
    kw = dict(max_slots=2, max_seq_len=64, buckets=[8, 64], prefill_chunk=16,
              decode_burst=1, admit_coalesce_ms=0.0, paged=True)
    ample = ContinuousBatcher(params, cfg, **kw)
    try:
        spa = SamplingParams(temperature=0.0, max_tokens=14)
        spb = SamplingParams(temperature=0.0, max_tokens=15)
        want_a = [t async for t in ample.submit(pa, spa)]
        want_b = [t async for t in ample.submit(pb, spb)]
    finally:
        ample.stop()
    b = ContinuousBatcher(params, cfg, kv_pool_blocks=4, **kw)
    try:
        spa = SamplingParams(temperature=0.0, max_tokens=14)
        spb = SamplingParams(temperature=0.0, max_tokens=15)

        async def run(p, sp):
            return [t async for t in b.submit(p, sp)]

        got_a, got_b = await asyncio.gather(run(pa, spa), run(pb, spb))
        assert got_a == want_a, "grower did not resume bit-identically"
        assert got_b == want_b
        assert b._suspend_stats["suspended_total"] >= 1
        assert b._suspend_stats["resumed_total"] >= 1
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 0
        await _wait(lambda: b.idle, what="slots drained")
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"], st
    finally:
        b.stop()


@async_test
async def test_decode_growth_exhaustion_sheds_without_cache_reset(model):
    """A lone slot whose full extent exceeds the pool can never be parked
    profitably: its growth failure is an honest retryable shed of THAT
    request only — no cache reset (pool epoch stays 0) and the engine
    keeps serving."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], prefill_chunk=16,
                          kv_pool_blocks=2, decode_burst=1,
                          admit_coalesce_ms=0.0, paged=True)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=14)
        with pytest.raises(BatcherOverloaded) as ei:
            [t async for t in b.submit(_prompt(20), sp)]
        assert "retry" in str(ei.value)
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 1
        # a follow-up that fits serves normally on the same cache
        sp2 = SamplingParams(temperature=0.0, max_tokens=4)
        out = [t async for t in b.submit(_prompt(10), sp2)]
        assert len(out) == 4
        await _wait(lambda: b.idle, what="slots drained")
        st = b.pool_stats()
        assert st["epoch"] == 0, "growth exhaustion must not reset the cache"
        assert st["blocks_free"] == st["blocks_total"], st
    finally:
        b.stop()


# -- promotion chaos + restart-with-warm-cache -------------------------------


@async_test
async def test_fetch_fault_during_promotion_keeps_serving(model):
    """A severed Object Store mid-promotion degrades to a plain prefill:
    same tokens, a counted fetch failure, no wedged admit."""
    cfg, params = model
    p, q = _prompt(40), _prompt(40, mul=11, add=5)
    store = MemorySpillStore()
    b = _tiered_batcher(params, cfg, spill=store, host_bytes=0)
    try:
        first = (await _serve(b, [p], 6))[0]
        await _serve(b, [q], 6)
        assert b.kv_tiers.flush()
        faults.install(faults.FaultPlan().drop(faults.TIER_FETCH, 0))
        try:
            second = (await _serve(b, [p], 6))[0]
        finally:
            faults.clear()
        assert second == first
        assert b.kv_tiers.stats()["fetch_failures"] >= 1
    finally:
        b.stop()


@async_test
async def test_restart_with_object_store_warm_cache(model):
    """Process-death survival: a FRESH engine + tier manager over the same
    spill store (no live donor) warm-imports the spilled chains and serves
    the repeat prompt with prefix hits and identical tokens."""
    cfg, params = model
    p, q = _prompt(40), _prompt(40, mul=11, add=5)
    store = MemorySpillStore()
    b1 = _tiered_batcher(params, cfg, spill=store, host_bytes=0)
    try:
        want = (await _serve(b1, [p], 6))[0]
        await _serve(b1, [q], 6)
    finally:
        b1.stop()  # close() flushes pending spills into the store
    assert len(store) > 1, "nothing spilled for the restart to import"

    b2 = _tiered_batcher(params, cfg, spill=store, host_bytes=0)
    try:
        b2.start()
        warm_tokens = 0
        for export in b2.kv_tiers.warm_exports(limit=4):
            warm_tokens += int(b2.import_prefix_blocks(export).get("tokens", 0))
        assert warm_tokens >= 32, "warm import covered no spilled chains"
        hit0 = b2.prefix_cache.hit_tokens
        got = (await _serve(b2, [p], 6))[0]
        assert got == want
        assert b2.prefix_cache.hit_tokens - hit0 >= 32
    finally:
        b2.stop()

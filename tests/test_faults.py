"""Fault tolerance: deterministic chaos harness, transport reconnect,
request retry, engine supervision and restart, poisoning.

The acceptance flow (ISSUE 4): with a seeded FaultPlan severing the broker
connection mid-run and injecting one pump-loop exception, every client
request completes after automatic reconnect + retry — no lost or duplicated
replies — and the lmstudio_reconnects_total / lmstudio_engine_restarts_total
families appear on the Prometheus exposition.
"""

import asyncio
import json
import time

import jax
import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.batcher import BatcherStopped, ContinuousBatcher
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store.manager import ModelStore
from nats_llm_studio_tpu.transport import (
    ConnectionClosedError,
    EmbeddedBroker,
    RetryPolicy,
    connect,
    envelope_error,
    envelope_ok,
)
from nats_llm_studio_tpu.transport import faults
from nats_llm_studio_tpu.transport.envelope import is_retryable_envelope

from conftest import async_test
from fakes import FakeRegistry
from test_serve_e2e import byte_level_tokenizer_md

MID = "acme/tiny-faults"


def _publish_tiny(models_dir, model_id=MID, seed=3):
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    d = models_dir / model_id
    d.mkdir(parents=True, exist_ok=True)
    export_params_to_gguf(
        d / "m.gguf", params, cfg, name=model_id,
        tokenizer_md=byte_level_tokenizer_md(cfg.vocab_size),
    )
    return cfg


async def _wait_for(pred, timeout=15.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def _chat_body(text, max_tokens=6, stream=False):
    return json.dumps(
        {
            "model": MID,
            "messages": [{"role": "user", "content": text}],
            "max_tokens": max_tokens,
            "temperature": 0.0,
            "stream": stream,
        }
    ).encode()


# -- FaultPlan units ---------------------------------------------------------


def test_faultplan_step_indexing_fires_once():
    plan = faults.FaultPlan(seed=1)
    plan.drop(faults.BROKER_PUBLISH, step=2, subject="a.b")
    # non-matching subjects never count against the rule
    assert plan.check(faults.BROKER_PUBLISH, "other") is None
    for i in range(2):  # hits 1, 2: below the 0-based step index
        assert plan.check(faults.BROKER_PUBLISH, "a.b") is None, i
    f = plan.check(faults.BROKER_PUBLISH, "a.b")  # hit 3 > step 2: fires
    assert f is not None and f.kind == "drop"
    # exactly once
    assert plan.check(faults.BROKER_PUBLISH, "a.b") is None
    assert plan.done()
    assert plan.fired() == [
        {"site": faults.BROKER_PUBLISH, "kind": "drop", "step": 2, "subject": "a.b"}
    ]


def test_faultplan_sites_are_independent():
    plan = (
        faults.FaultPlan()
        .raise_at(faults.PUMP, step=0, message="boom")
        .sever(faults.BROKER_PUBLISH, step=0)
    )
    assert not plan.done()
    f = plan.check(faults.PUMP)
    assert f is not None and isinstance(f.exception(), faults.InjectedFault)
    assert str(f.exception()) == "boom"
    assert not plan.done()  # sever has not fired yet
    assert plan.check(faults.BROKER_PUBLISH, "x").kind == "sever"
    assert plan.done()


def test_faultplan_env_parsing():
    env = {
        "CHAOS_SPEC": (
            "sever@broker.publish:3:subject=lmstudio.chat_model;"
            "raise@batcher.pump:40:msg=injected;"
            "delay@broker.publish:0:delay=0.25"
        ),
        "CHAOS_SEED": "9",
    }
    plan = faults.plan_from_env(env)
    assert plan is not None and plan.seed == 9
    kinds = [(f.kind, f.site, f.step) for f in plan.faults]
    assert kinds == [
        ("sever", "broker.publish", 3),
        ("raise", "batcher.pump", 40),
        ("delay", "broker.publish", 0),
    ]
    assert plan.faults[0].subject == "lmstudio.chat_model"
    assert plan.faults[1].message == "injected"
    assert plan.faults[2].delay_s == 0.25
    assert faults.plan_from_env({}) is None
    with pytest.raises(ValueError):
        faults.plan_from_env({"CHAOS_SPEC": "explode@nowhere:1"})


def test_retryable_envelope_detection():
    assert is_retryable_envelope(
        json.loads(envelope_error("worker draining, retry on another worker"))
    )
    assert is_retryable_envelope(json.loads(envelope_error("overloaded: full")))
    # explicit stamp wins even for unrecognized text
    assert is_retryable_envelope({"ok": False, "error": "custom", "retryable": True})
    assert not is_retryable_envelope(json.loads(envelope_error("model not found: x")))
    assert not is_retryable_envelope(json.loads(envelope_ok({"fine": 1})))
    # the stamp is additive: only present on retryable errors
    assert b"retryable" not in envelope_error("model not found: x")
    assert json.loads(envelope_error("worker draining, retry on another worker"))[
        "retryable"
    ] is True


# -- fail-fast closed-connection errors (satellite 2) ------------------------


@async_test
async def test_flush_and_request_fail_fast_when_connection_gone():
    broker = await EmbeddedBroker().start()
    nc = await connect(broker.url, max_reconnects=0)  # reconnect disabled
    await broker.stop()
    await _wait_for(lambda: nc._closed.is_set(), what="client close on EOF")
    t0 = time.monotonic()
    with pytest.raises(ConnectionClosedError):
        await nc.flush(timeout=30.0)
    with pytest.raises(ConnectionClosedError):
        await nc.request("any.subject", b"{}", timeout=30.0)
    # the whole point: errors surface immediately, not after the timeouts
    assert time.monotonic() - t0 < 5.0
    await nc.close()


@async_test
async def test_inflight_request_fails_fast_on_disconnect():
    """A request already waiting for its reply must fail the moment the
    connection drops (so a retry policy can re-issue after reconnect),
    not wait out its full timeout."""
    broker = await EmbeddedBroker().start()
    try:
        nc = await connect(broker.url, max_reconnects=0)
        task = asyncio.ensure_future(
            nc.request("nobody.listens", b"", timeout=30.0)
        )
        await asyncio.sleep(0.1)  # request published, future parked
        await broker.stop()
        t0 = time.monotonic()
        with pytest.raises(ConnectionClosedError):
            await task
        assert time.monotonic() - t0 < 5.0
        await nc.close()
    finally:
        await broker.stop()


# -- reconnect: resubscribe + pending-publish buffer -------------------------


@async_test
async def test_reconnect_restores_subscriptions_and_flushes_buffered_publishes():
    broker = await EmbeddedBroker().start()
    plan = faults.install(faults.FaultPlan(seed=0).sever(faults.BROKER_PUBLISH, 0, subject="kill.me"))
    try:
        nc = await connect(broker.url, reconnect_wait_s=0.02, reconnect_max_wait_s=0.1)
        sub = await nc.subscribe("t.data")
        await nc.flush()
        await nc.publish("kill.me", b"")  # broker severs OUR connection
        await _wait_for(lambda: not nc.is_connected or nc.reconnects >= 1,
                        what="disconnect noticed")
        # published while down: buffered, flushed on the fresh connection
        await nc.publish("t.data", b"after-reconnect")
        await _wait_for(lambda: nc.reconnects >= 1, what="reconnect")
        msg = await sub.next_msg(timeout=10)  # sub was re-issued automatically
        assert msg.payload == b"after-reconnect"
        assert nc.reconnects == 1
        assert nc.last_reconnect_s > 0
        assert plan.done()
        await nc.flush()  # fresh connection round-trips
        await nc.close()
    finally:
        faults.clear()
        await broker.stop()


@async_test
async def test_stream_fails_fast_on_mid_stream_disconnect():
    """request_stream must raise ConnectionClosedError on a reconnect gap —
    replies published while the link was down are gone; idling out (or
    silently resuming with missing chunks) would be data loss."""
    broker = await EmbeddedBroker().start()
    faults.install(faults.FaultPlan().sever(faults.BROKER_PUBLISH, 0, subject="kill.me"))
    try:
        nc = await connect(broker.url, reconnect_wait_s=0.02)
        responder = await connect(broker.url)

        async def on_req(msg):
            await responder.publish(msg.reply, b'{"chunk":1}')  # no Done header

        await responder.subscribe("svc.stream", cb=on_req)
        await responder.flush()
        agen = nc.request_stream("svc.stream", b"", timeout=20, idle_timeout=15)
        first = await agen.__anext__()
        assert json.loads(first.payload) == {"chunk": 1}
        await nc.publish("kill.me", b"")  # sever mid-stream
        with pytest.raises(ConnectionClosedError):
            await agen.__anext__()
        await nc.close()
        await responder.close()
    finally:
        faults.clear()
        await broker.stop()


# -- request retry policy ----------------------------------------------------


@async_test
async def test_request_retries_on_retryable_envelope():
    broker = await EmbeddedBroker().start()
    try:
        server = await connect(broker.url)
        calls = {"n": 0}

        async def handler(msg):
            calls["n"] += 1
            if calls["n"] <= 2:
                await msg.respond(
                    envelope_error("worker draining, retry on another worker")
                )
            else:
                await msg.respond(envelope_ok({"served_on_attempt": calls["n"]}))

        await server.subscribe("svc.flaky", cb=handler)
        await server.flush()
        nc = await connect(broker.url)

        # no retry: the retryable error envelope is returned as-is
        env = json.loads((await nc.request("svc.flaky", b"", timeout=5)).payload)
        assert env["ok"] is False and env["retryable"] is True
        calls["n"] = 0

        env = json.loads(
            (
                await nc.request(
                    "svc.flaky", b"", timeout=5,
                    retry=RetryPolicy(max_attempts=4, backoff_s=0.01),
                )
            ).payload
        )
        assert env["ok"] is True
        assert env["data"]["served_on_attempt"] == 3
        await nc.close()
        await server.close()
    finally:
        await broker.stop()


@async_test
async def test_request_retry_returns_final_envelope_honestly():
    broker = await EmbeddedBroker().start()
    try:
        server = await connect(broker.url)

        async def always_drain(msg):
            await msg.respond(envelope_error("worker draining, retry on another worker"))

        await server.subscribe("svc.alwaysdrain", cb=always_drain)
        await server.flush()
        nc = await connect(broker.url)
        env = json.loads(
            (
                await nc.request(
                    "svc.alwaysdrain", b"", timeout=5,
                    retry=RetryPolicy(max_attempts=2, backoff_s=0.01),
                )
            ).payload
        )
        # attempts exhausted: the last (still retryable) envelope is returned,
        # not swallowed into an exception
        assert env["ok"] is False and env["retryable"] is True
        await nc.close()
        await server.close()
    finally:
        await broker.stop()


# -- batcher pump crash ------------------------------------------------------


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@async_test
async def test_pump_crash_fails_inflight_with_retryable_error(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    # fire a few iterations in: the request is admitted and decoding
    faults.install(faults.FaultPlan().raise_at(faults.PUMP, step=4))
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=50)
        with pytest.raises(BatcherStopped) as ei:
            async for _ in b.submit([1, 2, 3], sp):
                pass
        assert "retry on another worker" in str(ei.value)
        assert not b.alive
        assert isinstance(b.crashed, faults.InjectedFault)
        assert b.stats.inflight_failed_retryable >= 1
        # slots cleared: the registry's eviction view stays sane
        await _wait_for(lambda: b.idle, what="slots cleared after crash")
        # submits after the crash are refused retryable, not hung
        with pytest.raises(BatcherStopped):
            async for _ in b.submit([4], sp):
                pass
    finally:
        faults.clear()
        b.stop()


# -- worker supervisor -------------------------------------------------------


class _DeadBatcher:
    alive = False
    idle = True
    _stopping = True

    def heartbeat_age_s(self):
        return 0.0


class _HungBatcher:
    alive = True
    idle = False
    _stopping = False

    def heartbeat_age_s(self):
        return 999.0


class _Eng:
    def __init__(self, batcher):
        self.batcher = batcher


class _SupervisedReg(FakeRegistry):
    def __init__(self, batcher):
        super().__init__(models=["m"])
        self._batcher = batcher
        self.restarts = []

    def loaded_engines(self):
        return {"m": _Eng(self._batcher)}

    async def restart_engine(self, model_id, reason="crash"):
        self.restarts.append((model_id, reason))
        return "restarted"


@async_test
async def test_supervisor_restarts_crashed_and_hung_engines():
    broker = await EmbeddedBroker().start()
    try:
        for batcher, expect in ((_DeadBatcher(), "crashed"), (_HungBatcher(), "hung")):
            reg = _SupervisedReg(batcher)
            cfg = WorkerConfig(
                nats_url=broker.url, supervise_interval_s=0.05,
                engine_heartbeat_timeout_s=1.0,
            )
            w = Worker(cfg, reg)
            await w.start()
            await _wait_for(lambda: reg.restarts, what=f"supervisor restart ({expect})")
            assert reg.restarts[0][0] == "m"
            assert expect in reg.restarts[0][1]
            await w.drain()
    finally:
        await broker.stop()


@async_test
async def test_supervisor_ignores_healthy_and_idle_engines(model):
    """An idle batcher blocks on its inbox and stops stamping its heartbeat —
    the supervisor must not flag it hung (the `not idle` guard)."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        out = [t async for t in b.submit([1, 2], SamplingParams(temperature=0.0, max_tokens=2))]
        assert len(out) == 2
        await asyncio.sleep(0.3)  # idle: heartbeat goes stale
        assert b.alive and b.idle
        broker = await EmbeddedBroker().start()
        try:
            reg = _SupervisedReg(b)
            w = Worker(
                WorkerConfig(
                    nats_url=broker.url, supervise_interval_s=0.05,
                    engine_heartbeat_timeout_s=0.1,  # << the idle staleness
                ),
                reg,
            )
            await w.start()
            await asyncio.sleep(0.4)
            assert reg.restarts == []  # alive + idle: never restarted
            await w.drain()
        finally:
            await broker.stop()
    finally:
        b.stop()


# -- worker drain with a chat in flight (satellite 3) ------------------------


@async_test
async def test_drain_midflight_yields_clean_retryable_envelope_and_retry_recovers(tmp_path):
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        reg_a = LocalRegistry(
            ModelStore(models), dtype="float32", max_batch_slots=1, max_seq_len=64
        )
        worker_a = Worker(WorkerConfig(nats_url=broker.url), reg_a)
        await worker_a.start()
        nc = await connect(broker.url)
        eng_a = await reg_a.get_engine(MID)

        # occupy worker A's single slot with a long chat...
        blocker = asyncio.ensure_future(
            nc.request("lmstudio.chat_model", _chat_body("blocker", max_tokens=50),
                       timeout=60)
        )
        await _wait_for(
            lambda: any(s is not None for s in eng_a.batcher._slots),
            what="blocker admitted to a slot",
        )
        # ...so the victim chat queues behind it
        victim = asyncio.ensure_future(
            nc.request("lmstudio.chat_model", _chat_body("victim", max_tokens=4),
                       timeout=60)
        )
        await _wait_for(
            lambda: eng_a.batcher._inbox.qsize() + eng_a.batcher._wl_len >= 1,
            what="victim queued",
        )
        # drain: the engine stops with the victim still queued, zero tokens out
        await asyncio.to_thread(eng_a.batcher.stop)

        env = json.loads((await victim).payload)
        assert env["ok"] is False
        assert "worker draining, retry on another worker" in env["error"]
        assert env["retryable"] is True  # the client retry policy's signal
        assert is_retryable_envelope(env)
        # the blocker had tokens in flight: truncated honestly, not errored
        blocker_env = json.loads((await blocker).payload)
        assert blocker_env["ok"] is True
        finish = blocker_env["data"]["response"]["choices"][0]["finish_reason"]
        assert finish == "shutdown"

        # end-to-end recovery: a healthy queue-group peer + client retry.
        # Worker A still answers with retryable envelopes (stopped engine),
        # so attempts bounce until one lands on worker B — bounded by the
        # retry budget, which makes the overall chance of failure ~2^-19.
        reg_b = LocalRegistry(
            ModelStore(models), dtype="float32", max_batch_slots=2, max_seq_len=64
        )
        worker_b = Worker(WorkerConfig(nats_url=broker.url), reg_b)
        await worker_b.start()
        env = json.loads(
            (
                await nc.request(
                    "lmstudio.chat_model", _chat_body("retry me", max_tokens=4),
                    timeout=60,
                    retry=RetryPolicy(max_attempts=20, backoff_s=0.02, max_backoff_s=0.2),
                )
            ).payload
        )
        assert env["ok"] is True, env
        await nc.close()
        await worker_a.drain()
        await worker_b.drain()
    finally:
        await broker.stop()


# -- acceptance: seeded chaos end-to-end -------------------------------------


@async_test
async def test_chaos_sever_and_pump_crash_full_recovery(tmp_path):
    """The ISSUE 4 acceptance flow: one seeded plan severs the requester's
    broker connection on the 3rd chat publish AND raises one injected
    exception inside the batcher pump loop. Every request must complete
    (reconnect + retry + supervisor engine restart), and the reconnect /
    restart counter families must appear on the Prometheus exposition."""
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        reg = LocalRegistry(
            ModelStore(models), dtype="float32", max_batch_slots=2, max_seq_len=64,
            restart_backoff_s=0.05, restart_backoff_max_s=0.2,
            max_restarts=10, restart_window_s=60.0,
        )
        worker = Worker(
            WorkerConfig(
                nats_url=broker.url, supervise_interval_s=0.1,
                engine_heartbeat_timeout_s=0.0,  # crash detection only
            ),
            reg,
        )
        await worker.start()
        nc = await connect(broker.url, reconnect_wait_s=0.02, reconnect_max_wait_s=0.2)

        # warm the engine outside the plan so fault steps land in serving
        env = json.loads(
            (await nc.request("lmstudio.chat_model", _chat_body("warmup"), timeout=60)).payload
        )
        assert env["ok"] is True, env

        plan = faults.install(
            faults.FaultPlan(seed=11)
            .sever(faults.BROKER_PUBLISH, 2, subject="lmstudio.chat_model")
            # ~2-3 checked pump iterations serve one short request (decode is
            # bursted), so step 8 lands mid-run of the 6-request loop
            .raise_at(faults.PUMP, 8, message="chaos pump fault")
        )
        retry = RetryPolicy(
            max_attempts=12, backoff_s=0.2, max_backoff_s=1.0, retry_on_timeout=True
        )
        n_ok = 0
        for i in range(6):
            msg = await nc.request(
                "lmstudio.chat_model", _chat_body(f"request {i}"), timeout=30,
                retry=retry,
            )
            env = json.loads(msg.payload)
            assert env["ok"] is True, (i, env)
            # exactly one terminal completion per request, never a duplicate
            assert env["data"]["response"]["object"] == "chat.completion"
            n_ok += 1
        assert n_ok == 6
        assert plan.done(), plan.describe()  # both faults actually fired
        assert nc.reconnects >= 1  # the sever was absorbed by a reconnect
        assert reg.engine_restarts_total >= 1  # the crash by a restart

        # now crash deterministically MID-REQUEST: the batcher is idle (its
        # current iteration's fault check already ran), so a step-0 raise
        # fires on the next checked iteration — with the long request below
        # either in a slot or still queued, and both paths count it
        restarts_before = reg.engine_restarts_total
        faults.install(faults.FaultPlan().raise_at(faults.PUMP, 0, message="mid-flight"))
        env = json.loads(
            (
                await nc.request(
                    "lmstudio.chat_model", _chat_body("victim", max_tokens=50),
                    timeout=30,
                )
            ).payload
        )
        assert env["ok"] is False and env["retryable"] is True, env
        assert "retry on another worker" in env["error"]
        await _wait_for(
            lambda: reg.engine_restarts_total > restarts_before,
            what="supervisor restart after mid-flight crash",
        )

        # health reports the relaunched engine live again
        health = json.loads((await nc.request("lmstudio.health", b"", timeout=10)).payload)
        assert health["data"]["engines"][MID]["alive"] is True
        assert health["data"]["engines"][MID]["ready"] is True

        prom = (await nc.request("lmstudio.metrics.prom", b"", timeout=10)).payload.decode()
        assert "lmstudio_reconnects_total" in prom
        assert "lmstudio_inflight_failed_retryable_total" in prom
        restarts = [
            line for line in prom.splitlines()
            if line.startswith("lmstudio_engine_restarts_total")
        ]
        assert restarts and float(restarts[0].split()[-1]) >= 1
        inflight = [
            line for line in prom.splitlines()
            if line.startswith("lmstudio_inflight_failed_retryable_total")
        ]
        assert inflight and float(inflight[0].split()[-1]) >= 1
        assert "lmstudio_engine_restart_ms" in prom

        await nc.close()
        await worker.drain()
    finally:
        faults.clear()
        await broker.stop()


# -- poisoning ---------------------------------------------------------------


@async_test
async def test_repeated_crashes_poison_engine_until_reset(tmp_path):
    models = tmp_path / "models"
    _publish_tiny(models)
    reg = LocalRegistry(
        ModelStore(models), dtype="float32", max_batch_slots=2, max_seq_len=64,
        max_restarts=0,  # the very first crash poisons
    )
    await reg.get_engine(MID)
    outcome = await reg.restart_engine(MID, reason="test crash")
    assert outcome == "poisoned"
    assert MID in reg.poisoned_models()
    assert reg.loaded_engines() == {}  # torn down, not relaunched
    with pytest.raises(Exception) as ei:
        await reg.get_engine(MID)
    assert "poisoned" in str(ei.value)
    # the refusal itself is retryable: a queue-group peer may be healthy
    assert is_retryable_envelope(json.loads(envelope_error(str(ei.value))))
    assert "poisoned" in reg.stats()
    # operator reset path: delete clears the poison mark (and the files)
    await reg.delete(MID)
    assert reg.poisoned_models() == {}
    from nats_llm_studio_tpu.serve.api import ModelNotFound

    with pytest.raises(ModelNotFound):
        await reg.get_engine(MID)


@async_test
async def test_restart_engine_relaunches_below_poison_threshold(tmp_path):
    models = tmp_path / "models"
    _publish_tiny(models)
    reg = LocalRegistry(
        ModelStore(models), dtype="float32", max_batch_slots=2, max_seq_len=64,
        restart_backoff_s=0.01, max_restarts=3,
    )
    await reg.get_engine(MID)
    outcome = await reg.restart_engine(MID, reason="crash")
    assert outcome == "restarted"
    assert reg.engine_restarts_total == 1
    assert reg.restart_latency_ms.snapshot().count == 1
    # the relaunched engine serves
    eng = await reg.get_engine(MID)
    out = await eng.chat(
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 3,
         "temperature": 0.0}
    )
    assert out["choices"][0]["message"]["content"] is not None
    health = reg.engine_health()
    assert health[MID]["alive"] and health[MID]["ready"]
    await reg.restart_engine(MID, reason="cleanup-stop")  # tidy teardown

"""Prefix KV cache tests: cached-prefix admits must reproduce single-stream
generation exactly (full hit, partial hit at a non-chunk boundary, quantized
KV blocks, eviction pressure), PREFIX_CACHE off must leave the batcher
byte-identical to the uncached path, and refcounted eviction must never free
a block an in-flight admit is still reading."""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from nats_llm_studio_tpu.engine.generator import Generator, SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.serve.prefix_cache import (
    PrefixCache,
    prefix_block_bytes,
    serving_chunk,
)

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(scope="module")
def kvq_model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64, kv_quant="int8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def reference_greedy(cfg, params, prompt, n):
    gen = Generator(params, cfg, max_seq_len=64, buckets=[8, 16, 32, 64])
    sp = SamplingParams(temperature=0.0, max_tokens=n)
    return [t for t, _ in gen.generate(prompt, sp)]


def make_batcher(params, cfg, blocks):
    return ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64],
        prefill_chunk=8, prefix_cache_blocks=blocks,
    )


async def _greedy(b, prompt, n):
    sp = SamplingParams(temperature=0.0, max_tokens=n)
    return [t async for t in b.submit(prompt, sp)]


# -- serving equivalence ------------------------------------------------------


@async_test
async def test_full_hit_matches_reference(model):
    """Resending a chunk-aligned prompt takes the full-hit path (first token
    sampled from stored chunk-end logits, NO prefill) and must still match
    the single-stream greedy reference."""
    cfg, params = model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(16)]  # 2 chunks
    want = reference_greedy(cfg, params, prompt, 6)
    b = make_batcher(params, cfg, blocks=8)
    try:
        assert await _greedy(b, prompt, 6) == want  # miss: populates
        assert await _greedy(b, prompt, 6) == want  # full hit
        c = b.prefix_cache.counters()
        assert c["full_hits"] >= 1
        assert c["hit_tokens"] >= 16
    finally:
        b.stop()


@async_test
async def test_partial_hit_non_chunk_boundary_matches_reference(model):
    """Two prompts sharing an 11-token prefix (chunk 8: one shared block,
    shared region ending MID-chunk) — the second admit must resume prefill
    from the chunk edge and match the reference exactly."""
    cfg, params = model
    shared = [(i * 5 + 1) % cfg.vocab_size for i in range(11)]
    p1 = shared + [(i * 3 + 2) % cfg.vocab_size for i in range(9)]
    p2 = shared + [(i * 11 + 4) % cfg.vocab_size for i in range(7)]
    want1 = reference_greedy(cfg, params, p1, 5)
    want2 = reference_greedy(cfg, params, p2, 5)
    b = make_batcher(params, cfg, blocks=8)
    try:
        assert await _greedy(b, p1, 5) == want1
        assert await _greedy(b, p2, 5) == want2
        c = b.prefix_cache.counters()
        assert c["hits"] >= 1
        assert c["hit_tokens"] >= 8  # exactly the one shared full chunk
    finally:
        b.stop()


@async_test
async def test_kv_quant_hit_matches_reference(kvq_model):
    """With an int8 serving cache the cached blocks are KVQ codes+scales; a
    hit re-installs the exact quantized values a prefill would have written,
    so greedy output stays bit-identical to the (quantized) reference."""
    cfg, params = kvq_model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(19)]
    want = reference_greedy(cfg, params, prompt, 6)
    b = make_batcher(params, cfg, blocks=8)
    try:
        assert await _greedy(b, prompt, 6) == want
        assert await _greedy(b, prompt, 6) == want
        assert b.prefix_cache.counters()["hits"] >= 1
    finally:
        b.stop()


@async_test
async def test_eviction_under_pressure_stays_correct(model):
    """A 2-block budget under three distinct 2-chunk prompts must evict —
    and every admit (hit, miss, post-eviction re-miss) must still match the
    reference."""
    cfg, params = model
    prompts = [
        [(i * 7 + 3) % cfg.vocab_size for i in range(16)],
        [(i * 5 + 1) % cfg.vocab_size for i in range(16)],
        [(i * 11 + 4) % cfg.vocab_size for i in range(16)],
    ]
    want = [reference_greedy(cfg, params, p, 4) for p in prompts]
    b = make_batcher(params, cfg, blocks=2)
    try:
        for p, w in zip(prompts, want):
            assert await _greedy(b, p, 4) == w
        # first prompt's blocks were evicted; resending must still be correct
        assert await _greedy(b, prompts[0], 4) == want[0]
        pc = b.prefix_cache
        assert pc.counters()["evicted_blocks"] > 0
        assert pc.blocks <= 2
    finally:
        b.stop()


@async_test
async def test_cache_off_matches_reference(model):
    """prefix_cache_blocks=0 (the PREFIX_CACHE=0 off-switch) disables the
    cache entirely: no PrefixCache object, outputs identical to the
    reference for repeated long prompts."""
    cfg, params = model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(16)]
    want = reference_greedy(cfg, params, prompt, 6)
    b = make_batcher(params, cfg, blocks=0)
    try:
        assert b.prefix_cache is None
        assert await _greedy(b, prompt, 6) == want
        assert await _greedy(b, prompt, 6) == want
    finally:
        b.stop()


@async_test
async def test_concurrent_hit_and_miss_group(model):
    """A hit-bearing long prompt arriving alongside a fresh long prompt:
    group formation routes the hit to the singleton hit path while the miss
    still admits (possibly grouped) — both must match the reference."""
    cfg, params = model
    p_hit = [(i * 7 + 3) % cfg.vocab_size for i in range(25)]
    p_miss = [(i * 5 + 1) % cfg.vocab_size for i in range(30)]
    want_hit = reference_greedy(cfg, params, p_hit, 5)
    want_miss = reference_greedy(cfg, params, p_miss, 5)
    b = make_batcher(params, cfg, blocks=8)
    try:
        assert await _greedy(b, p_hit, 5) == want_hit  # populate
        tasks = [
            asyncio.create_task(_greedy(b, p_hit, 5)),
            asyncio.create_task(_greedy(b, p_miss, 5)),
        ]
        await asyncio.sleep(0)
        got_hit, got_miss = await asyncio.gather(*tasks)
        assert got_hit == want_hit
        assert got_miss == want_miss
        assert b.prefix_cache.counters()["hits"] >= 1
    finally:
        b.stop()


# -- cache-structure unit tests (no model) ------------------------------------


def _blk(v, chunk=4):
    a = jnp.full((1, 2, 1, chunk, 2), float(v))
    return jnp.copy(a), jnp.copy(a)


def test_refcount_protects_pinned_blocks_across_eviction():
    """Evicting a pinned node must detach it from the tree WITHOUT freeing
    its arrays — the in-flight admit that pinned them is still issuing copy
    dispatches. release() then frees the dead node."""
    pc = PrefixCache(chunk=4, capacity_blocks=2)
    p1 = list(range(8))  # 2 chunks
    assert pc.insert(p1, [_blk(1), _blk(2)]) == 2
    # query longer than the cached path so BOTH nodes stay in the hit
    hit = pc.match(p1 + [91, 92, 93, 94])
    assert hit is not None and hit.tokens == 8 and len(hit.nodes) == 2
    pinned = list(hit.nodes)

    # capacity pressure from a different prompt evicts the pinned path
    pc.insert(list(range(100, 108)), [_blk(3), _blk(4)])
    assert pc.counters()["evicted_blocks"] >= 2
    assert pc.blocks <= 2
    for nd in pinned:
        assert nd.dead, "evicted-while-pinned node must be marked dead"
        assert nd.kb is not None and nd.vb is not None, (
            "eviction freed a block an active admit still reads"
        )
    # the detached path is gone from lookup
    assert pc.match(p1 + [91]) is None

    pc.release(hit)
    for nd in pinned:
        assert nd.kb is None and nd.vb is None, "release must free dead nodes"
    assert hit.nodes == []


def test_full_coverage_needs_end_logits():
    """A match covering the whole prompt is only a FULL hit when the last
    node stored its chunk-end logits; otherwise the final chunk is dropped
    so the batcher re-prefills it (and backfills the logits)."""
    pc = PrefixCache(chunk=4, capacity_blocks=8)
    p = list(range(8))
    pc.insert(p, [_blk(1), _blk(2)])  # harvested without logits
    hit = pc.match(p)
    assert hit is not None and hit.tokens == 4  # last chunk dropped
    assert hit.end_logits is None
    pc.release(hit)

    # backfill pass: same path re-inserted with logits on the final chunk
    pc.insert(p, [None, None], logits_list=[None, jnp.zeros((1, 1, 16))])
    hit = pc.match(p)
    assert hit is not None and hit.tokens == 8
    assert hit.end_logits is not None
    pc.release(hit)


def test_resize_zero_drops_everything_and_disables_insert():
    pc = PrefixCache(chunk=4, capacity_blocks=8)
    pc.insert(list(range(8)), [_blk(1), _blk(2)])
    assert pc.blocks == 2
    assert pc.resize(0) == 2
    assert pc.blocks == 0 and pc.bytes == 0
    assert pc.insert(list(range(8)), [_blk(1), _blk(2)]) == 0  # capacity 0


def test_block_bytes_estimate_covers_measured_blocks():
    """The registry prices HBM with prefix_block_bytes; a real block pair
    must never exceed the estimate (underestimating would oversubscribe
    admission)."""
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    chunk = serving_chunk(64, 8)
    pc = PrefixCache(chunk=chunk, capacity_blocks=4)
    k = jnp.zeros((1, cfg.n_layers, cfg.n_kv_heads, chunk, cfg.head_dim),
                  jnp.float32)
    pc.insert(list(range(chunk)), [(k, jnp.copy(k))])
    est = prefix_block_bytes(cfg, chunk)
    assert pc.bytes <= est

"""JSON-schema token DFA tests (serve/constrain.py).

The compile path is exercised end to end with a character-level fake
tokenizer: random mask-guided walks through the token DFA must always
terminate in a parseable, schema-valid JSON document, EOS must only be
reachable at accepting states, and garbled ``response_format`` values must
raise client-facing errors without compiling anything."""

import json

import numpy as np
import pytest

from nats_llm_studio_tpu.serve import constrain
from nats_llm_studio_tpu.serve.constrain import (
    ConstraintError,
    compile_token_dfa,
    token_strings,
    validate_response_format,
)


class CharTok:
    """Character-level fake tokenizer using the bare ``.tokens`` fallback of
    ``token_strings``: printable ASCII singletons plus a few multi-char
    merges and one control EOS id at the end."""

    def __init__(self):
        chars = [chr(c) for c in range(0x20, 0x7F)]
        merges = ['{"', '":', '", "', "true", "false", "null", "123", '"}']
        self.tokens = chars + merges + ["<eos>"]
        self._control_ids = frozenset({len(self.tokens) - 1})

    @property
    def eos_id(self):
        return len(self.tokens) - 1

    def decode(self, ids):
        return "".join(self.tokens[i] for i in ids)


SCHEMA = {
    "type": "object",
    "properties": {
        "name": {"type": "string"},
        "age": {"type": "integer"},
        "tag": {"enum": ["alpha", "beta"]},
    },
}


def walk(dfa, eos_id, rng, max_steps=4000):
    """Mask-guided random walk: at each state pick any allowed token. The
    DFA contract says this can only ever stop by emitting EOS at an
    accepting state — never by painting itself into a corner."""
    state = dfa.start
    toks = []
    for _ in range(max_steps):
        m = dfa.mask(state)
        assert m.any(), f"dead-ended at state {state} after {len(toks)} tokens"
        choices = np.flatnonzero(m)
        tid = int(rng.choice(choices))
        if tid == eos_id:
            assert dfa.accepting(state)
            return toks
        nxt = dfa.advance(state, tid)
        assert nxt is not None, (state, tid)
        toks.append(tid)
        state = nxt
    raise AssertionError("walk did not terminate")


def test_random_walks_produce_schema_valid_json():
    jsonschema = pytest.importorskip("jsonschema")
    tok = CharTok()
    dfa = compile_token_dfa(SCHEMA, tok, len(tok.tokens), eos_ids=[tok.eos_id])
    rng = np.random.default_rng(42)
    for _ in range(20):
        text = tok.decode(walk(dfa, tok.eos_id, rng))
        doc = json.loads(text)  # must parse
        jsonschema.validate(doc, SCHEMA)  # must validate
        # declared properties are all present (declaration order)
        assert list(doc) == ["name", "age", "tag"]
        assert doc["tag"] in ("alpha", "beta")


def test_greedy_style_walk_json_object_mode():
    """``{}`` (json_object mode) compiles to a generic bounded JSON value:
    every walk must terminate in something ``json.loads`` accepts."""
    tok = CharTok()
    dfa = compile_token_dfa({}, tok, len(tok.tokens), eos_ids=[tok.eos_id])
    rng = np.random.default_rng(7)
    for _ in range(10):
        text = tok.decode(walk(dfa, tok.eos_id, rng))
        json.loads(text)  # must parse


def test_eos_only_at_accepting_states():
    tok = CharTok()
    dfa = compile_token_dfa(SCHEMA, tok, len(tok.tokens), eos_ids=[tok.eos_id])
    # the empty document is not schema-valid: EOS banned at start
    assert not dfa.accepting(dfa.start)
    assert not dfa.mask(dfa.start)[tok.eos_id]
    assert dfa.advance(dfa.start, tok.eos_id) is None
    # after a full valid document (canonical tight JSON — the compiled
    # language omits insignificant whitespace) EOS becomes reachable
    text = '{"name":"x","age":3,"tag":"beta"}'
    state = dfa.start
    for ch in text:
        state = dfa.advance(state, tok.tokens.index(ch))
        assert state is not None, ch
    assert dfa.accepting(state)
    assert dfa.mask(state)[tok.eos_id]
    assert dfa.advance(state, tok.eos_id) == state


def test_banned_token_advance_returns_none():
    tok = CharTok()
    dfa = compile_token_dfa(SCHEMA, tok, len(tok.tokens), eos_ids=[tok.eos_id])
    # a document can only open with '{' (or a merge starting with it)
    assert dfa.advance(dfa.start, tok.tokens.index("x")) is None
    assert dfa.mask(dfa.start)[tok.tokens.index("{")]


def test_compile_cache_returns_identical_object():
    tok = CharTok()
    a = compile_token_dfa(SCHEMA, tok, len(tok.tokens), eos_ids=[tok.eos_id])
    b = compile_token_dfa(SCHEMA, tok, len(tok.tokens), eos_ids=[tok.eos_id])
    assert a is b


def test_empty_language_rejected_at_compile_time():
    class LettersOnly:
        tokens = list("abcdefgh")
        _control_ids = frozenset()

    with pytest.raises(ConstraintError, match="empty language"):
        compile_token_dfa(SCHEMA, LettersOnly(), len(LettersOnly.tokens))


def test_unserializable_schema_rejected():
    tok = CharTok()
    with pytest.raises(ConstraintError, match="not JSON-serializable"):
        compile_token_dfa({"x": object()}, tok, len(tok.tokens))


def test_token_strings_llama_family():
    class Llama:
        model = "llama"
        tokens = ["▁hello", "world", "<0x41>", "<0x80>", "<s>"]
        _control_ids = frozenset({4})

    out = token_strings(Llama(), 5)
    assert out[0] == " hello"
    assert out[1] == "world"
    assert out[2] == "A"  # printable byte token
    assert out[3] is None  # partial-UTF-8 byte token: banned
    assert out[4] is None  # control token: banned


def test_token_strings_gpt2_family():
    class Gpt2:
        model = "gpt2"
        # gpt2 byte-alphabet: 'Ġ' maps to space via _u2b
        tokens = ["Ġhi", "ok"]
        _control_ids = frozenset()
        _u2b = {"Ġ": 0x20}

    out = token_strings(Gpt2(), 2)
    assert out == [" hi", "ok"]


def test_validate_response_format_cases():
    assert validate_response_format(None) is None
    assert validate_response_format({"type": "text"}) is None
    assert validate_response_format({"type": "json_object"}) == {}
    rf = {"type": "json_schema", "json_schema": {"schema": SCHEMA}}
    assert validate_response_format(rf) == SCHEMA

    for bad in (
        "json",  # not an object
        {"type": "jsonschema"},  # unknown type
        {"type": "json_schema"},  # missing json_schema
        {"type": "json_schema", "json_schema": []},  # wrong shape
        {"type": "json_schema", "json_schema": {"schema": "x"}},  # wrong shape
    ):
        with pytest.raises(ValueError):
            validate_response_format(bad)


def test_enabled_env_gate(monkeypatch):
    monkeypatch.delenv("CONSTRAIN", raising=False)
    assert constrain.enabled()
    for off in ("0", "false", "off", " 0 "):
        monkeypatch.setenv("CONSTRAIN", off)
        assert not constrain.enabled()
    monkeypatch.setenv("CONSTRAIN", "1")
    assert constrain.enabled()

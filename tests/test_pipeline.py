"""Pipeline parallelism: the microbatched pp forward must reproduce the
dense single-device forward exactly — prefill, chunked continuation, and
one-token decode — on the virtual 8-device CPU mesh (SURVEY.md §4.3/§4.4
distributed test tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.pipeline import pipeline_forward
from nats_llm_studio_tpu.parallel.sharding import (
    shard_cache,
    shard_params,
    validate_mesh_for_config,
)


def _mesh(pp):
    if len(jax.devices()) < pp:
        pytest.skip(f"needs {pp} devices")
    return build_mesh({"pp": pp}, jax.devices()[:pp])


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=8, max_seq_len=64, vocab_size=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.mark.parametrize("n_mb", [2, 4])
def test_pp_prefill_matches_dense(model, n_mb):
    cfg, params = model
    mesh = _mesh(4)
    validate_mesh_for_config(mesh, cfg, allow_pp=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 9), 0, cfg.vocab_size)
    start = jnp.zeros((4,), jnp.int32)

    k, v = make_cache(cfg, 4, 32)
    want, wk, wv = forward(params, cfg, tokens, k, v, start)

    sp = shard_params(params, mesh)
    k, v = shard_cache(*make_cache(cfg, 4, 32), mesh)
    got, gk, gv = jax.jit(
        lambda p, tk, k, v, s: pipeline_forward(
            p, cfg, tk, k, v, s, mesh=mesh, n_microbatches=n_mb
        )
    )(sp, tokens, k, v, start)

    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(wk),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(wv),
                               rtol=2e-5, atol=2e-5)


def test_pp_decode_matches_dense(model):
    """Prefill through the pipeline, then three single-token decode steps —
    the cache handoff between calls must stay consistent."""
    cfg, params = model
    mesh = _mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 5), 0, cfg.vocab_size)
    start = jnp.zeros((2,), jnp.int32)

    k, v = make_cache(cfg, 2, 32)
    want, wk, wv = forward(params, cfg, tokens, k, v, start)

    sp = shard_params(params, mesh)
    gk, gv = shard_cache(*make_cache(cfg, 2, 32), mesh)
    got, gk, gv = pipeline_forward(sp, cfg, tokens, gk, gv, start, mesh=mesh,
                                   n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    nxt = jnp.argmax(want[:, -1, :], axis=-1).astype(jnp.int32)
    for i in range(3):
        pos = jnp.full((2,), 5 + i, jnp.int32)
        want, wk, wv = forward(params, cfg, nxt[:, None], wk, wv, pos)
        got, gk, gv = pipeline_forward(sp, cfg, nxt[:, None], gk, gv, pos,
                                       mesh=mesh, n_microbatches=2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5, err_msg=f"step {i}")
        nxt = jnp.argmax(want[:, -1, :], axis=-1).astype(jnp.int32)


def test_pp_chunked_continuation_matches_dense(model):
    """T > 1 at start_pos > 0 (chunked prefill continuation): the positional
    KV writes and the non-fresh attention path must stay consistent with
    the dense forward across the chunk boundary."""
    cfg, params = model
    mesh = _mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 9), 0, cfg.vocab_size)
    first, second = tokens[:, :5], tokens[:, 5:]
    zero = jnp.zeros((2,), jnp.int32)

    k, v = make_cache(cfg, 2, 32)
    _, wk, wv = forward(params, cfg, first, k, v, zero)
    want, wk, wv = forward(params, cfg, second, wk, wv, jnp.full((2,), 5, jnp.int32))

    sp = shard_params(params, mesh)
    gk, gv = shard_cache(*make_cache(cfg, 2, 32), mesh)
    _, gk, gv = pipeline_forward(sp, cfg, first, gk, gv, zero, mesh=mesh,
                                 n_microbatches=2)
    got, gk, gv = pipeline_forward(sp, cfg, second, gk, gv,
                                   jnp.full((2,), 5, jnp.int32), mesh=mesh,
                                   n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pp_rejected_on_dense_serving_path(model):
    """TPU_MESH=pp=N must fail loudly on the dense path — GSPMD would
    otherwise silently all-gather every layer's weights per step."""
    cfg, params = model
    mesh = _mesh(4)
    with pytest.raises(ValueError, match="pipeline"):
        validate_mesh_for_config(mesh, cfg)


def test_pp_logit_positions(model):
    cfg, params = model
    mesh = _mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 7), 0, cfg.vocab_size)
    start = jnp.zeros((2,), jnp.int32)
    lp = jnp.asarray([6, 3], jnp.int32)

    k, v = make_cache(cfg, 2, 32)
    want, _, _ = forward(params, cfg, tokens, k, v, start)
    sp = shard_params(params, mesh)
    k, v = shard_cache(*make_cache(cfg, 2, 32), mesh)
    got, _, _ = pipeline_forward(sp, cfg, tokens, k, v, start, mesh=mesh,
                                 n_microbatches=2, logit_positions=lp)
    np.testing.assert_allclose(np.asarray(got[0, 0]), np.asarray(want[0, 6]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got[1, 0]), np.asarray(want[1, 3]),
                               rtol=2e-5, atol=2e-5)


def test_pp_validation_errors(model):
    cfg, params = model
    mesh = _mesh(4)
    sp = shard_params(params, mesh)
    k, v = shard_cache(*make_cache(cfg, 4, 32), mesh)
    tokens = jnp.ones((4, 4), jnp.int32)
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_forward(sp, cfg, jnp.ones((3, 4), jnp.int32), k, v,
                         jnp.zeros((3,), jnp.int32), mesh=mesh, n_microbatches=2)
    bad = cfg.with_(n_layers=6)
    with pytest.raises(ValueError, match="divisible by pp"):
        pipeline_forward(sp, bad, tokens, k, v, jnp.zeros((4,), jnp.int32),
                         mesh=mesh)


def test_pp_quantized_kv_close_to_fp(model):
    """Pipeline forward over a QUANTIZED (KVQ) cache: row-block slicing and
    gated writes must move codes and scales together; logits stay close to
    the fp-cache pipeline and the argmax agrees."""
    cfg, params = model
    qcfg = cfg.with_(kv_quant="int8")
    mesh = _mesh(4)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 9), 0, cfg.vocab_size)
    start = jnp.zeros((4,), jnp.int32)

    sp = shard_params(params, mesh)
    k, v = shard_cache(*make_cache(cfg, 4, 32), mesh)
    want, _, _ = pipeline_forward(sp, cfg, tokens, k, v, start, mesh=mesh,
                                  n_microbatches=2)
    kq, vq = shard_cache(*make_cache(qcfg, 4, 32), mesh)
    got, kq, vq = pipeline_forward(sp, qcfg, tokens, kq, vq, start, mesh=mesh,
                                   n_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.15, atol=0.15)
    assert (np.asarray(got[:, -1].argmax(-1)) == np.asarray(want[:, -1].argmax(-1))).all()
    # decode step over the quantized pipeline cache stays consistent
    nxt = jnp.argmax(got[:, -1, :], axis=-1).astype(jnp.int32)
    pos = jnp.full((4,), 9, jnp.int32)
    got2, _, _ = pipeline_forward(sp, qcfg, nxt[:, None], kq, vq, pos,
                                  mesh=mesh, n_microbatches=2)
    assert got2.shape == (4, 1, cfg.vocab_size)

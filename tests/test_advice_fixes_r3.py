"""Round-3 judge/advisor fixes, pinned by tests.

* VERDICT.md weak #5: a stream ending without the terminal chat.completion
  aggregate must FAIL LOUDLY (terminal error envelope), never silently
  regenerate via engine.chat (double cost, possibly different completion).
* VERDICT.md weak #6: auto-unsub (UNSUB <sid> <max>) bookkeeping — the
  client must retire the subscription when the server-side count exhausts.
* ADVICE r3 low: store path components may not end in '.' or ' ' (Windows
  strips them — two advertised ids would collide on one directory).
* ADVICE r3 low: EP capacity is per (source-shard, expert); with
  cf >= E/k no routing skew can drop tokens, so ep>1 == ep=1 exactly.
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

from conftest import async_test
from fakes import EchoEngine, FakeRegistry


# ---------------------------------------------------------------------------
# streaming without aggregate -> loud terminal error
# ---------------------------------------------------------------------------


class TruncatedStreamEngine(EchoEngine):
    """Streams chunks but never the chat.completion aggregate (a broken
    engine); also counts chat() calls to prove no silent regeneration."""

    def __init__(self, model_id: str):
        super().__init__(model_id)
        self.chat_calls = 0

    async def chat(self, payload: dict) -> dict:
        self.chat_calls += 1
        return await super().chat(payload)

    async def chat_stream(self, payload: dict):
        yield {
            "object": "chat.completion.chunk",
            "model": self.model_id,
            "choices": [{"index": 0, "delta": {"content": "partial "}}],
        }
        # stream ends here: NO aggregate


@async_test
async def test_stream_without_aggregate_is_terminal_error_not_regeneration():
    broker = await EmbeddedBroker().start()
    reg = FakeRegistry(models=["broken"])
    eng = TruncatedStreamEngine("broken")
    reg.engines["broken"] = eng
    worker = Worker(WorkerConfig(nats_url=broker.url), reg)
    await worker.start()
    nc = await connect(broker.url)
    try:
        body = json.dumps(
            {"model": "broken", "messages": [{"role": "user", "content": "hi"}],
             "stream": True}
        ).encode()
        msgs = []
        async for msg in nc.request_stream("lmstudio.chat_model", body, timeout=10.0):
            msgs.append(msg)
        # terminal message arrived (stream ended cleanly) and is an ERROR
        terminal = msgs[-1]
        assert (terminal.headers or {}).get("Nats-Stream-Done") is not None
        env = json.loads(terminal.payload)
        assert env["ok"] is False
        assert "aggregate" in env["error"]
        # and the worker did NOT silently regenerate the completion
        assert eng.chat_calls == 0
    finally:
        await nc.close()
        await worker.drain()
        await broker.stop()


# ---------------------------------------------------------------------------
# auto-unsub bookkeeping
# ---------------------------------------------------------------------------


@async_test
async def test_auto_unsubscribe_retires_sub_at_count():
    broker = await EmbeddedBroker().start()
    nc = await connect(broker.url)
    pub = await connect(broker.url)
    try:
        sub = await nc.subscribe("auto.test")
        await sub.auto_unsubscribe(2)
        for i in range(4):
            await pub.publish("auto.test", f"m{i}".encode())
        await pub.flush()
        got = [await sub.next_msg(timeout=2.0)]
        got.append(await sub.next_msg(timeout=2.0))
        assert [m.payload for m in got] == [b"m0", b"m1"]
        # count exhausted: sub closed and removed from the client's table
        assert sub.closed
        assert sub.sid not in nc._subs
        with pytest.raises(BrokenPipeError):
            await sub.next_msg(timeout=0.5)
    finally:
        await nc.close()
        await pub.close()
        await broker.stop()


@async_test
async def test_auto_unsubscribe_after_delivery_retires_immediately():
    """UNSUB with max <= already-delivered count retires the sub at once."""
    broker = await EmbeddedBroker().start()
    nc = await connect(broker.url)
    pub = await connect(broker.url)
    try:
        sub = await nc.subscribe("auto.test2")
        await pub.publish("auto.test2", b"m0")
        await pub.flush()
        assert (await sub.next_msg(timeout=2.0)).payload == b"m0"
        await sub.auto_unsubscribe(1)  # already delivered 1
        assert sub.closed
        assert sub.sid not in nc._subs
    finally:
        await nc.close()
        await pub.close()
        await broker.stop()


@async_test
async def test_auto_unsub_exhausted_queue_member_not_picked():
    """Broker side of the same bound: UNSUB max <= delivered retires the
    queue-group member IMMEDIATELY — otherwise the broker could route a
    message to a sid the client already dropped and the message would be
    silently lost to the whole group."""
    broker = await EmbeddedBroker().start()
    nc = await connect(broker.url)
    live = await connect(broker.url)
    pub = await connect(broker.url)
    try:
        # deterministic: `dying` is the only member when "warm" routes
        dying = await nc.subscribe("qg.test", queue="g")
        await pub.publish("qg.test", b"warm")
        await pub.flush()
        assert (await dying.next_msg(timeout=2.0)).payload == b"warm"
        survivor = await live.subscribe("qg.test", queue="g")
        await live.flush()  # survivor's SUB processed before further PUBs
        # bound already met (delivered=1 >= max=1): the broker must retire
        # `dying` NOW; every subsequent message goes to the survivor
        await dying.auto_unsubscribe(1)
        await nc.flush()  # UNSUB processed by the broker before the PUBs
        for i in range(4):
            await pub.publish("qg.test", f"m{i}".encode())
        await pub.flush()
        for i in range(4):
            m = await survivor.next_msg(timeout=2.0)
            assert m.payload == f"m{i}".encode()
    finally:
        await nc.close()
        await live.close()
        await pub.close()
        await broker.stop()


# ---------------------------------------------------------------------------
# path-component hygiene (Windows trailing '.'/' ')
# ---------------------------------------------------------------------------


def test_model_id_components_may_not_end_in_dot_or_space():
    from nats_llm_studio_tpu.store.manager import StoreError, split_model_id

    assert split_model_id("meta/llama-3-8b") == ("meta", "llama-3-8b")
    assert split_model_id("a.b c") == ("local", "a.b c")  # interior ok
    # outer whitespace of the WHOLE id is normalized away before validation
    assert split_model_id(" model ") == ("local", "model")
    # trailing '_'/'-' are safe on every platform and must STAY valid:
    # ids cached by earlier releases must remain listable/deletable
    assert split_model_id("pub/llama-7b_") == ("pub", "llama-7b_")
    assert split_model_id("pub-/llama-") == ("pub-", "llama-")
    # trailing '.'/' ' on a component is rejected for CREATION (Windows
    # strips them — distinct ids would collide on one directory)
    for bad in ("model.", "pub./name", "pub /name", "pub/name."):
        with pytest.raises(StoreError):
            split_model_id(bad)
    # ...but the lenient mode (lookup/list/delete of dirs that already
    # exist) still accepts the legacy charset — same conservative set, no
    # traversal — so old caches stay reachable
    assert split_model_id("pub./name", strict=False) == ("pub.", "name")
    with pytest.raises(StoreError):
        split_model_id("../etc", strict=False)


def test_pull_object_rejects_hostile_object_names(tmp_path):
    """Object names are client-controlled; components becoming filesystem
    paths must pass the strict pattern (no traversal, no legacy charset —
    pulls must not recreate legacy-named dirs on fresh nodes)."""
    from nats_llm_studio_tpu.store.manager import ModelStore, StoreError

    store = ModelStore(tmp_path, objstore=object())  # validation precedes use
    for bad in ("a/../x/f.gguf", "pub./model/f.gguf", "pub/model./f.gguf"):
        with pytest.raises(StoreError):
            asyncio.run(store._pull_object(bad, None))
    assert not (tmp_path / "x").exists()


def test_legacy_dotted_dir_stays_listable_and_deletable(tmp_path):
    """A model cached by an earlier release under a now-strict-invalid name
    (trailing '.') must remain advertised and reclaimable over the bus."""
    from nats_llm_studio_tpu.store.manager import ModelStore, StoreError

    store = ModelStore(tmp_path)
    legacy = tmp_path / "pub" / "llama3."
    legacy.mkdir(parents=True)
    (legacy / "model.gguf").write_bytes(b"GGUF")
    ids = [c.model_id for c in store.cached()]
    assert "pub/llama3." in ids
    # trailing-SPACE legacy dirs are NOT advertised: the whole-id strip
    # makes such an id alias its sibling ('pub/llama3 ' -> 'pub/llama3'),
    # so deleting it would rmtree the WRONG model
    spacey = tmp_path / "pub" / "llama3 "
    spacey.mkdir(parents=True)
    (spacey / "model.gguf").write_bytes(b"GGUF")
    valid = tmp_path / "pub" / "llama3"
    valid.mkdir(parents=True)
    (valid / "model.gguf").write_bytes(b"GGUF")
    ids2 = [c.model_id for c in store.cached()]
    assert "pub/llama3 " not in ids2 and "pub/llama3" in ids2
    assert store.delete_local("pub/llama3 ").endswith("llama3")  # normalized
    assert spacey.exists() and not valid.exists()
    deleted = store.delete_local("pub/llama3.")
    assert deleted.endswith("llama3.")
    assert not legacy.exists()
    # creation-side strictness unchanged: import under that id still fails
    src = tmp_path / "src.gguf"
    src.write_bytes(b"GGUF")
    with pytest.raises(StoreError):
        store.import_file(src, "pub/llama3.")


# ---------------------------------------------------------------------------
# EP capacity: cf >= E/k makes skew drops impossible, ep>1 == ep=1
# ---------------------------------------------------------------------------


def test_ep_skewed_routing_no_drops_at_full_capacity_factor():
    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import init_params
    from nats_llm_studio_tpu.parallel import build_mesh
    from nats_llm_studio_tpu.parallel.moe import routed_moe_ffn
    from nats_llm_studio_tpu.parallel.sharding import shard_params

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    cfg = ModelConfig.tiny(n_experts=8, n_experts_used=2, d_ff=32, n_layers=1)
    params = init_params(cfg, jax.random.PRNGKey(0))
    p = {k: v[0] for k, v in params["blocks"].items() if k in
         ("router", "w_gate_e", "w_up_e", "w_down_e")}
    # force pathological skew: every token routes to experts 0 and 1
    router = np.zeros(p["router"].shape, np.float32)
    router[:, 0] = 10.0
    router[:, 1] = 9.0
    p = dict(p, router=jnp.asarray(router))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model), jnp.float32)

    # cf = E/k: per-pair capacity >= all of a shard's assignments -> no
    # drops possible under ANY skew (documented bound, parallel/moe.py)
    cf = cfg.n_experts / cfg.n_experts_used
    want = routed_moe_ffn(x, p, cfg, mesh=None, capacity_factor=cf)

    mesh = build_mesh({"ep": 4}, jax.devices()[:4])
    sh = shard_params({"blocks": {k: v[None] for k, v in p.items()}}, mesh)["blocks"]
    p_sh = {k: jax.tree.map(lambda a: a[0], sh[k]) for k in p}
    got = jax.jit(
        lambda x, p: routed_moe_ffn(x, p, cfg, mesh=mesh, capacity_factor=cf)
    )(x, p_sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

"""Pallas paged-decode kernel, grouped int4 weights, tp overlap (PR 17).

The decode tentpole has three coupled layers, each pinned here against the
incumbent path it replaces:

* ops/paged_attention.py — the Pallas decode kernel reads each slot's block
  table directly (no kv_pool_gather_view materialization, no pow2 window
  ladder). Greedy decode through the LIVE batcher must be token-identical
  to the XLA gather-view path on every serving shape the batcher routes:
  plain and grouped admits, chunked prefill, prefix-cache hits, int8 KVQ
  pools, speculative decode, and tp=2 across the 8 forced host devices
  (conftest.py). Off-TPU the kernel runs under the Pallas interpreter —
  same math, so the equivalence is real, just slow.
* ops/wquant.py int4 — grouped asymmetric QTensor4: round-trip error
  bounds per group size, the fused dequant-matmul against explicit
  dequantization, and end-to-end top-1 logit agreement on a random tiny
  model (the worst case for argmax stability — real checkpoints have far
  larger logit margins than noise weights).
* parallel/overlap.py — the ppermute-ring all-reduce behind TP_OVERLAP
  must keep greedy decode token-identical through the batcher (reduction
  order changes float rounding, not the argmax on these margins).

Plus the satellite knobs: DECODE_KERNEL resolution/downshift rules, the
DECODE_LADDER_RUNGS window-ladder cap, and the decode_recompiles counter.
"""

import asyncio
import os
from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import (
    ensure_lm_head,
    forward,
    init_params,
    make_cache,
)
from nats_llm_studio_tpu.ops.paged_attention import paged_decode_eligible
from nats_llm_studio_tpu.ops.wquant import (
    QTensor4,
    effective_group,
    mm,
    quantize_params,
    quantize_weight4,
)
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.sharding import shard_params
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@contextmanager
def _env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


async def _greedy_batch(params, cfg, prompts, n, kernel, mesh=None, **kw):
    """Greedy decode through a paged batcher with DECODE_KERNEL forced."""
    with _env(DECODE_KERNEL=kernel):
        b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                              buckets=[8, 64], mesh=mesh, paged=True, **kw)
    assert b.decode_kernel == kernel, (b.decode_kernel, kernel)
    try:
        async def one(p):
            sp = SamplingParams(temperature=0.0, max_tokens=n)
            return [t async for t in b.submit(p, sp)]

        return await asyncio.gather(*[one(p) for p in prompts])
    finally:
        b.stop()


PROMPTS = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30, 40, 50]]


# -- kernel equivalence through the live batcher ------------------------------


@async_test
async def test_pallas_greedy_matches_xla(model):
    """Solo + concurrent group admits: the kernel's online softmax over the
    whole table width reproduces the gather-view tokens exactly."""
    cfg, params = model
    want = await _greedy_batch(params, cfg, PROMPTS, 6, "xla")
    got = await _greedy_batch(params, cfg, PROMPTS, 6, "pallas")
    assert got == want


@async_test
async def test_pallas_kvq_greedy_matches_xla(model):
    """int8 KVQ pool: the kernel dequantizes codes in-VMEM; quantize-on-
    write must produce the same codes as the view path, so tokens match."""
    cfg, params = model
    qcfg = cfg.with_(kv_quant="int8")
    want = await _greedy_batch(params, qcfg, PROMPTS, 6, "xla")
    got = await _greedy_batch(params, qcfg, PROMPTS, 6, "pallas")
    assert got == want


@async_test
async def test_pallas_chunked_prefill_and_prefix_hit_match(model):
    """Chunked admits + a prefix-cache resend: the hit path re-enters
    decode through block tables the kernel must walk identically."""
    cfg, params = model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(18)]

    async def run(kernel):
        with _env(DECODE_KERNEL=kernel):
            b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                                  buckets=[8, 64], prefill_chunk=8,
                                  prefix_cache_blocks=16, paged=True)
        try:
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            first = [t async for t in b.submit(prompt, sp)]
            again = [t async for t in b.submit(prompt, sp)]
            return first, again, b.prefix_cache.counters()["hits"]
        finally:
            b.stop()

    w_first, w_again, w_hits = await run("xla")
    p_first, p_again, p_hits = await run("pallas")
    assert p_first == w_first and p_again == w_again
    assert w_hits >= 1 and p_hits >= 1


@async_test
async def test_pallas_spec_decode_matches(model):
    """spec_verify through the kernel (W = k+1 rows per step) accepts and
    emits exactly the plain greedy sequence."""
    cfg, params = model
    prompt = [7, 8, 9, 7, 8, 9, 7, 8]  # repetition: prompt-lookup drafts hit
    want = await _greedy_batch(params, cfg, [prompt], 10, "xla")
    got = await _greedy_batch(params, cfg, [prompt], 10, "pallas",
                              spec_decode_k=4)
    assert got == want


@async_test
async def test_pallas_tp2_matches_unsharded(model):
    """tp=2 on the forced host devices: the kernel runs per-shard under
    shard_map (heads split, tables replicated) and still matches the
    unsharded XLA tokens."""
    cfg, params = model
    want = await _greedy_batch(params, cfg, PROMPTS[:3], 6, "xla")
    mesh = build_mesh("tp=2", devices=jax.devices()[:2])
    sharded = shard_params(params, mesh, cfg)
    got = await _greedy_batch(sharded, cfg, PROMPTS[:3], 6, "pallas",
                              mesh=mesh)
    assert got == want


@async_test
async def test_tp_overlap_greedy_matches(model):
    """TP_OVERLAP=1: the decode projections' all-reduce rides the ppermute
    ring — different reduction order, same greedy tokens."""
    cfg, params = model
    want = await _greedy_batch(params, cfg, PROMPTS[:3], 6, "xla")
    mesh = build_mesh("tp=2", devices=jax.devices()[:2])
    sharded = shard_params(params, mesh, cfg)
    with _env(TP_OVERLAP="1"):
        got = await _greedy_batch(sharded, cfg, PROMPTS[:3], 6, "pallas",
                                  mesh=mesh)
    assert got == want


# -- knob resolution, ladder cap, recompile counter ---------------------------


def test_decode_kernel_resolution(model):
    cfg, params = model

    def make(paged=True, **env):
        with _env(**env):
            b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                                  buckets=[8, 64], paged=paged)
        b.stop()
        return b.decode_kernel

    # auto off-TPU -> xla (the interpreter is for tests, not serving)
    assert make(DECODE_KERNEL="auto") == "xla"
    assert make() == make(DECODE_KERNEL="auto")
    # forced values are honored off-TPU (pallas via the interpreter)
    assert make(DECODE_KERNEL="pallas") == "pallas"
    assert make(DECODE_KERNEL="xla") == "xla"
    # the legacy contiguous layout has no kernel choice
    assert make(paged=False, DECODE_KERNEL="pallas") == "xla"
    with pytest.raises(ValueError, match="DECODE_KERNEL"):
        make(DECODE_KERNEL="mosaic")


def test_window_ladder_cap(model):
    """DECODE_LADDER_RUNGS bounds the pow2 window ladder: every bucket is
    >= the floor, so the distinct-window count (== compiled decode
    programs) is capped regardless of max_seq."""
    cfg, params = model

    def floors(rungs):
        with _env(DECODE_LADDER_RUNGS=str(rungs)):
            b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                                  buckets=[8, 64], paged=True)
        b.stop()
        wins = {b._win_bucket(n) for n in range(1, 65)}
        return b._win_floor, wins

    floor2, wins2 = floors(2)
    assert floor2 == 32 and wins2 == {32, 64}
    floor6, wins6 = floors(6)
    assert floor6 == 8
    assert len(wins6) <= 6 and min(wins6) == 8 and max(wins6) == 64
    # every window is a pow2 (paged_window relies on T | window)
    assert all(w & (w - 1) == 0 for w in wins6)


@async_test
async def test_decode_recompile_counter(model):
    """stats.decode_recompiles counts first-seen decode program keys and
    shows up in both counters() and snapshot() (the worker exposes it as
    lmstudio_decode_recompiles_total)."""
    cfg, params = model
    with _env(DECODE_KERNEL="xla"):
        b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                              buckets=[8, 64], paged=True)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=6)

        async def one(p):
            return [t async for t in b.submit(p, sp)]

        await asyncio.gather(*[one(list(p)) for p in PROMPTS])
        n = b.stats.decode_recompiles
        assert n >= 1
        assert n == len(b._compiled_keys)
        assert b.stats.counters()["decode_recompiles"] == n
        assert b.stats.snapshot()["decode_recompiles"] == n
        # a repeat of the same shapes compiles nothing new
        await asyncio.gather(*[one(list(p)) for p in PROMPTS])
        assert b.stats.decode_recompiles == n
    finally:
        b.stop()


def test_paged_decode_eligible_rules():
    # f32 pool: 8-row sublanes, D must tile the 128-lane axis
    assert paged_decode_eligible(16, 128, 4, False)
    assert not paged_decode_eligible(12, 128, 4, False)   # T % 8
    assert not paged_decode_eligible(16, 64, 4, False)    # D % 128
    # bf16 pool: 16-row sublanes
    assert paged_decode_eligible(16, 128, 2, False)
    assert not paged_decode_eligible(24, 128, 2, False)
    # int8 KVQ codes: 32-row sublanes
    assert paged_decode_eligible(32, 128, 2, True)
    assert not paged_decode_eligible(16, 128, 2, True)
    # the shard_map heads split needs Hkv % tp == 0
    assert paged_decode_eligible(16, 128, 4, False, hkv=2, tp=2)
    assert not paged_decode_eligible(16, 128, 4, False, hkv=1, tp=2)


# -- grouped int4 quantization ------------------------------------------------


def test_int4_roundtrip_error_bounds():
    """Grouped asymmetric int4 round-trip stays inside GGUF Q4_1-class
    error, tightening as the group shrinks."""
    rng = np.random.default_rng(0)
    w = rng.standard_normal((256, 96)).astype(np.float32)
    errs = {}
    for g in (16, 32, 64):
        qt = quantize_weight4(w, group=g)
        assert qt.group == g
        deq = np.asarray(qt.dequant(jnp.float32))
        errs[g] = float(np.sqrt(np.mean((w - deq) ** 2))
                        / np.sqrt(np.mean(w ** 2)))
        assert errs[g] < 0.10, (g, errs[g])
    assert errs[16] < errs[32] < errs[64]  # finer groups -> less error
    # codes unpack to [0, 15] and the logical shape survives packing
    qt = quantize_weight4(w, group=32)
    codes = np.asarray(qt.codes())
    assert qt.shape == w.shape and codes.min() >= 0 and codes.max() <= 15


def test_int4_group_degradation_and_packing_guard():
    assert effective_group(64, 32) == 32
    assert effective_group(64, 128) == 64    # clamps to the axis
    assert effective_group(50, 32) == 10     # largest even divisor <= 32
    with pytest.raises(ValueError, match="even contraction"):
        quantize_weight4(np.zeros((7, 4), np.float32))


def test_int4_fused_matmul_matches_dequant():
    """The fused grouped dequant-matmul (_mm4, no float weight
    materialized) equals x @ dequant(w) to float tolerance."""
    rng = np.random.default_rng(1)
    w = rng.standard_normal((128, 48)).astype(np.float32)
    x = jnp.asarray(rng.standard_normal((3, 5, 128)).astype(np.float32))
    qt = jax.tree.map(jnp.asarray, quantize_weight4(w, group=32))
    want = x @ qt.dequant(jnp.float32)
    got = mm(x, qt)
    assert jnp.max(jnp.abs(got - want)) < 1e-3


@async_test
async def test_registry_int4_gguf_load(model, tmp_path):
    """quant="int4" through the registry's GGUF host path: every eligible
    leaf lands as grouped QTensor4 and the engine serves greedy tokens —
    the WQUANT=int4 knob is load-path-complete, not just an ops feature."""
    from nats_llm_studio_tpu.models.export import export_params_to_gguf
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store.manager import ModelStore

    from test_serve_e2e import byte_level_tokenizer_md

    cfg, params = model
    d = tmp_path / "acme" / "int4"
    d.mkdir(parents=True)
    export_params_to_gguf(d / "m.gguf", params, cfg, name="acme/int4",
                          tokenizer_md=byte_level_tokenizer_md(cfg.vocab_size))
    reg = LocalRegistry(ModelStore(tmp_path), dtype="float32",
                        max_batch_slots=2, max_seq_len=64,
                        quant="int4", wquant_group=32)
    eng = await reg.get_engine("acme/int4")
    try:
        leaves = jax.tree.leaves(
            eng.batcher.params, is_leaf=lambda x: isinstance(x, QTensor4))
        assert sum(isinstance(x, QTensor4) for x in leaves) > 0
        out = None
        async for chunk in eng.chat_stream(
            {"messages": [{"role": "user", "content": "hi"}],
             "max_tokens": 6, "temperature": 0.0}
        ):
            if chunk.get("object") == "chat.completion":
                out = chunk
        assert out is not None
        assert out["usage"]["completion_tokens"] >= 1
    finally:
        await eng.unload()


def test_int4_top1_logit_agreement(model):
    """End-to-end: int4-quantized tiny-model logits keep top-1 agreement
    with the float reference on random weights — the worst case, since
    noise weights have near-tied logits; real checkpoints sit far above
    this floor."""
    cfg, params = model
    full = ensure_lm_head(params)
    p4 = quantize_params(full, mode="int4", group=32)
    assert any(isinstance(x, QTensor4) for x in jax.tree.leaves(
        p4, is_leaf=lambda x: isinstance(x, QTensor4)))
    tokens = jax.random.randint(jax.random.PRNGKey(7), (4, 24), 0,
                                cfg.vocab_size)
    zeros = jnp.zeros((4,), jnp.int32)
    k, v = make_cache(cfg, 4, 64)
    ref, *_ = forward(full, cfg, tokens=tokens, k_cache=k, v_cache=v,
                      start_pos=zeros)
    k, v = make_cache(cfg, 4, 64)
    got, *_ = forward(p4, cfg, tokens=tokens, k_cache=k, v_cache=v,
                      start_pos=zeros)
    agree = float(jnp.mean(jnp.argmax(ref, -1) == jnp.argmax(got, -1)))
    rel = float(jnp.sqrt(jnp.mean((ref - got) ** 2))
                / jnp.sqrt(jnp.mean(ref ** 2)))
    assert agree >= 0.7, agree
    assert rel < 0.2, rel

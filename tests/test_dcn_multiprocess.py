"""Multi-process (DCN-path) smoke as a test artifact (VERDICT r3 #8): the
`jax.distributed.initialize` path must RUN — two coordinator-connected
processes, a global mesh spanning both, one cross-process psum, one sharded
forward. The heavy lifting lives in scripts/dcn_smoke.py (also runnable
standalone on real multi-host by changing the coordinator address)."""

import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_two_process_mesh_psum_and_forward():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "dcn_smoke.py")],
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "DCN_SMOKE PASS" in proc.stdout

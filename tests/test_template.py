"""Direct unit tests for serve/template.py: the GGUF-embedded jinja path,
the family fallbacks keyed off vocab markers, stop-token resolution, and the
chat_model wiring through JaxChatEngine._encode_prompt — previously covered
only indirectly through the serving e2e tests.
"""

import pytest

from nats_llm_studio_tpu.gguf.constants import KEY_CHAT_TEMPLATE
from nats_llm_studio_tpu.serve import template
from nats_llm_studio_tpu.serve.template import (
    render_chat_template,
    stop_token_ids,
)

MESSAGES = [
    {"role": "system", "content": "be brief"},
    {"role": "user", "content": "hi"},
]


class StubTokenizer:
    """Just the surface template.py and _encode_prompt touch: a vocab map,
    an eos id, and encode()."""

    def __init__(self, vocab: dict[str, int], eos_id: int | None = None):
        self.vocab = vocab
        self.eos_id = eos_id
        self.encoded: list[str] = []

    def encode(self, text: str) -> list[int]:
        self.encoded.append(text)
        return list(range(len(text.split())))


# -- jinja path ---------------------------------------------------------------


@pytest.mark.skipif(template._JINJA is None, reason="jinja2 not installed")
def test_jinja_template_renders_with_special_tokens():
    md = {
        KEY_CHAT_TEMPLATE: (
            "{{ bos_token }}{% for m in messages %}"
            "[{{ m.role }}]{{ m.content }}{{ eos_token }}{% endfor %}"
            "{% if add_generation_prompt %}[assistant]{% endif %}"
        ),
        "tokenizer.ggml.tokens": ["<s>", "</s>"],
        "tokenizer.ggml.bos_token_id": 0,
        "tokenizer.ggml.eos_token_id": 1,
    }
    out = render_chat_template(md, MESSAGES)
    assert out == "<s>[system]be brief</s>[user]hi</s>[assistant]"
    # add_generation_prompt=False drops the trailing assistant cue
    out = render_chat_template(md, MESSAGES, add_generation_prompt=False)
    assert out.endswith("[user]hi</s>")


def test_broken_jinja_template_falls_back():
    """A malformed embedded template must never fail the chat — the
    vocab-marker fallback serves instead (here: chatml)."""
    md = {
        KEY_CHAT_TEMPLATE: "{% for m in messages %}{{ unclosed",
        "tokenizer.ggml.tokens": ["<|im_start|>", "<|im_end|>"],
    }
    out = render_chat_template(md, MESSAGES)
    assert out.startswith("<|im_start|>system\nbe brief<|im_end|>\n")
    assert out.endswith("<|im_start|>assistant\n")


# -- family fallbacks keyed off vocab markers --------------------------------


def test_llama3_fallback_format():
    md = {"tokenizer.ggml.tokens": ["<|start_header_id|>", "<|eot_id|>"]}
    out = render_chat_template(md, MESSAGES)
    assert out.startswith("<|begin_of_text|>")
    assert "<|start_header_id|>system<|end_header_id|>\n\nbe brief<|eot_id|>" in out
    assert "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>" in out
    assert out.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")


def test_granite_fallback_format():
    md = {"tokenizer.ggml.tokens": ["<|start_of_role|>", "<|end_of_role|>"]}
    out = render_chat_template(md, MESSAGES)
    assert "<|start_of_role|>user<|end_of_role|>hi<|end_of_text|>\n" in out
    assert out.endswith("<|start_of_role|>assistant<|end_of_role|>")


def test_chatml_fallback_and_generic_default():
    md = {"tokenizer.ggml.tokens": ["<|im_start|>"]}
    out = render_chat_template(md, MESSAGES)
    assert "<|im_start|>user\nhi<|im_end|>\n" in out
    # no markers and no template at all: plain role-prefixed lines
    out = render_chat_template({}, MESSAGES)
    assert out == "system: be brief\nuser: hi\nassistant:"
    # missing role/content default to user/empty instead of raising
    out = render_chat_template({}, [{}], add_generation_prompt=False)
    assert out == "user: \n"


def test_llama3_marker_wins_over_later_families():
    """Dispatch precedence is llama3 > granite > chatml when a vocab
    carries several marker sets."""
    md = {"tokenizer.ggml.tokens": [
        "<|start_header_id|>", "<|start_of_role|>", "<|im_start|>",
    ]}
    assert render_chat_template(md, MESSAGES).startswith("<|begin_of_text|>")


# -- stop tokens --------------------------------------------------------------


def test_stop_token_ids_collects_eos_and_vocab_markers():
    tok = StubTokenizer(
        vocab={"<|eot_id|>": 7, "</s>": 3, "hello": 11}, eos_id=2
    )
    ids = stop_token_ids(tok)
    assert ids == frozenset({2, 3, 7})  # eos + known markers, never "hello"
    # no eos, empty vocab: empty set rather than an error
    assert stop_token_ids(StubTokenizer(vocab={})) == frozenset()


# -- chat_model wiring (serve/registry.py) -----------------------------------


def test_engine_encode_prompt_renders_template_then_encodes():
    """JaxChatEngine._encode_prompt — the path every chat_model request
    takes — must feed the RENDERED template to the tokenizer, and the
    engine's stop ids must come from the same vocab."""
    from nats_llm_studio_tpu.serve.registry import JaxChatEngine

    tok = StubTokenizer(vocab={"<|eot_id|>": 9}, eos_id=9)
    eng = JaxChatEngine(
        "acme/tpl", batcher=None, tokenizer=tok, cfg=None,
        meta={"tokenizer.ggml.tokens": ["<|start_header_id|>"]},
    )
    ids = eng._encode_prompt({"messages": MESSAGES})
    assert len(tok.encoded) == 1
    prompt = tok.encoded[0]
    assert prompt.startswith("<|begin_of_text|>")
    assert prompt.endswith("<|start_header_id|>assistant<|end_header_id|>\n\n")
    assert ids == tok.encode(prompt)  # encoder output passed through verbatim
    assert eng._sampling({}).stop_ids == frozenset({9})

"""Cluster-scope observability plane (ISSUE 14 tentpole).

Three layers: cross-process trace assembly (Span batches on
``lmstudio.obs.spans`` -> SpanStore -> ``lmstudio.debug.trace.<id>``),
fleet metrics aggregation (per-worker scrape -> delta-first merge ->
``lmstudio.cluster.metrics.prom``), and multi-window SLO burn-rate alerts
(``slo_burn`` on ``lmstudio.events``).

Unit coverage runs against synthetic expositions and hand-built span dicts;
the acceptance e2e drives a real two-hop disaggregated chat (HTTP gateway ->
router steering -> decode worker -> prefill worker KV pull) over the
embedded broker and asserts ONE assembled tree with consistent parent links
plus aggregator/bench p95 parity on the same scrape.
"""

import asyncio
import json
import math
import time

from nats_llm_studio_tpu.obs import (
    Aggregator,
    LogHistogram,
    PromRenderer,
    SloEvaluator,
    SpanStore,
    assemble_trace,
    bucket_pairs,
    merge,
    merge_expositions,
    new_trace_id,
    parse_span_context,
    quantile,
    span_context_value,
)

from conftest import async_test
from test_obs import check_prom_exposition

INF = math.inf


# -- delta-first histogram merge ---------------------------------------------


def test_merge_exact_on_hand_built_series():
    """Two elided cumulative series with different edges: deltas convert
    per-series first, the +Inf overflow collapses to that series' last
    finite edge, quantiles land on upper bucket edges."""
    a = [(10.0, 4.0), (100.0, 6.0), (INF, 6.0)]  # 4 in (0,10], 2 in (10,100]
    b = [(50.0, 10.0), (INF, 11.0)]  # 10 in (0,50], 1 overflow -> edge 50
    m = merge([a, b])
    assert m.count == 17.0
    # edge cum: 10 -> 4, 50 -> 15 (10 + collapsed overflow), 100 -> 17
    assert m.quantile(0.2) == 10.0
    assert m.quantile(0.5) == 50.0
    assert m.quantile(0.95) == 100.0
    want_mean = (5.0 * 4 + 55.0 * 2 + 25.0 * 10 + 50.0 * 1) / 17.0
    assert abs(m.mean - want_mean) < 1e-9
    want_var = (
        4 * (5.0 - want_mean) ** 2 + 2 * (55.0 - want_mean) ** 2
        + 10 * (25.0 - want_mean) ** 2 + 1 * (50.0 - want_mean) ** 2
    ) / 17.0
    assert abs(m.variance - want_var) < 1e-9
    assert abs(m.std - want_var ** 0.5) < 1e-9
    # single-series shorthand agrees with the merge of one
    assert quantile(a, 0.95) == merge([a]).quantile(0.95)


def test_merge_ignores_counter_resets_and_empty():
    assert merge([]).count == 0
    assert merge([]).quantile(0.95) == 0.0
    # a cumulative decrease (counter reset mid-scrape) drops, not poisons
    m = merge([[(10.0, 5.0), (100.0, 3.0), (INF, 3.0)]])
    assert m.count == 5.0
    assert m.quantile(0.99) == 10.0


def test_merge_of_rendered_expositions_matches_single_histogram():
    """Recording the same values into two per-worker histograms, rendering,
    and merging the expositions gives the identical quantile as one
    histogram holding all values — the renderers share the bucket ladder,
    elision and all."""
    values_a = [3.0, 7.0, 40.0, 900.0]
    values_b = [5.0, 5.0, 60.0, 2500.0, 2500.0]
    ha, hb, hall = LogHistogram(), LogHistogram(), LogHistogram()
    for v in values_a:
        ha.record(v)
        hall.record(v)
    for v in values_b:
        hb.record(v)
        hall.record(v)
    texts = []
    for wid, h in (("w1", ha), ("w2", hb)):
        r = PromRenderer(default_labels={"worker_id": wid})
        r.histogram("lmstudio_ttft_ms", h.snapshot(), help="ttft")
        texts.append(r.render())
    m = merge(bucket_pairs(t, "lmstudio_ttft_ms") for t in texts)
    assert m.count == len(values_a) + len(values_b)
    for q in (0.5, 0.9, 0.95, 0.99):
        # identical ladder: merged quantile == whole-population histogram
        # quantile's bucket upper edge
        one = merge([bucket_pairs(_render_one(hall), "lmstudio_ttft_ms")])
        assert m.quantile(q) == one.quantile(q), q


def _render_one(h):
    r = PromRenderer(default_labels={"worker_id": "all"})
    r.histogram("lmstudio_ttft_ms", h.snapshot(), help="ttft")
    return r.render()


def test_merged_cluster_exposition_passes_strict_checker():
    """Satellite: the merged (worker_id-dropped) exposition satisfies the
    same strict Prometheus contract the per-worker output does — one TYPE
    per family, cumulative-monotone buckets, +Inf == _count."""
    texts = []
    for wid, n in (("w1", 3), ("w2", 8)):
        h = LogHistogram()
        for i in range(n):
            h.record(10.0 * (i + 1))
        r = PromRenderer(default_labels={"worker_id": wid})
        r.counter("lmstudio_requests_total", n, help="requests")
        r.counter("lmstudio_tokens_total", n * 4,
                  labels={"model": "acme/m"}, help="tokens")
        r.gauge("lmstudio_slots_busy", n % 2, help="busy")
        r.histogram("lmstudio_ttft_ms", h.snapshot(), help="ttft")
        texts.append(r.render())
    merged = merge_expositions(texts)
    types = check_prom_exposition(merged)
    assert types["lmstudio_requests_total"] == "counter"
    assert types["lmstudio_ttft_ms"] == "histogram"
    assert 'worker_id=' not in merged  # the label the merge exists to drop
    assert "lmstudio_requests_total 11" in merged  # counters sum
    # the merged histogram holds every record from both workers
    assert merge([bucket_pairs(merged, "lmstudio_ttft_ms")]).count == 11


# -- span context + assembly -------------------------------------------------


def test_span_context_roundtrip_and_lenient_parse():
    tid, sid = new_trace_id(), "ab12cd34ef56ab78"
    value = span_context_value(tid, sid)
    assert value.startswith("00-") and value.endswith("-01")
    assert parse_span_context(value) == (tid, sid)
    for bad in (None, "", "garbage", "00-onlytrace", "00--x-01"):
        assert parse_span_context(bad) is None


def test_assemble_trace_parent_links_orphans_and_ordering():
    tid = "t" * 16

    def span(sid, parent, t0, stage="s"):
        return {"trace_id": tid, "span_id": sid, "stage": stage,
                "parent_span_id": parent, "t0": t0, "t1": t0 + 1.0}

    spans = [
        span("root", "", 1.0, "gateway.request"),
        span("late-child", "root", 3.0),
        span("early-child", "root", 2.0),
        span("grand", "early-child", 2.5),
        span("orphan", "never-arrived", 0.5),  # lost parent -> extra root
        span("self", "self", 4.0),  # self-parent cannot recurse
    ]
    tree = assemble_trace(tid, spans)
    assert tree["span_count"] == 6
    roots = tree["roots"]
    assert [r["span_id"] for r in roots] == ["orphan", "root", "self"]
    root = roots[1]
    # children sort by wall t0, causality comes from the links
    assert [c["span_id"] for c in root["children"]] == [
        "early-child", "late-child"
    ]
    assert [c["span_id"] for c in root["children"][0]["children"]] == ["grand"]


def test_span_store_bounds_and_resend_updates():
    store = SpanStore(max_traces=2, max_spans_per_trace=2)
    assert store.add({"nope": 1}) is False  # malformed -> dropped, counted
    assert store.dropped_total == 1
    assert store.add({"trace_id": "t1", "span_id": "a", "stage": "x"})
    assert store.add({"trace_id": "t1", "span_id": "b", "stage": "x"})
    assert store.add({"trace_id": "t1", "span_id": "c", "stage": "x"}) is False
    # a re-send of a known span id updates in place (retries re-emit)
    assert store.add({"trace_id": "t1", "span_id": "a", "stage": "y"})
    assert {s["stage"] for s in store.get("t1")} == {"x", "y"}
    store.add({"trace_id": "t2", "span_id": "a", "stage": "x"})
    store.add({"trace_id": "t3", "span_id": "a", "stage": "x"})
    assert len(store) == 2  # oldest-touched trace evicted
    assert store.get("t2") and store.get("t3") and not store.get("t1")


# -- SLO burn-rate evaluation ------------------------------------------------


def _sample(ttft_pairs=(), requests=0.0, sheds=0.0, failed=0.0):
    return {"ttft": list(ttft_pairs), "requests": requests,
            "sheds": sheds, "failed": failed}


def test_slo_fires_only_when_both_windows_burn():
    slo = SloEvaluator(ttft_p95_ms=100.0, window_s=60.0, fast_window_s=5.0)
    assert slo.observe(0.0, {"w": _sample()}) == []  # idle baseline
    # a 1000ms TTFT burst lands inside both windows -> 10x burn in each
    alerts = slo.observe(
        100.0, {"w": _sample(ttft_pairs=[(1000.0, 10.0), (INF, 10.0)],
                             requests=10.0)}
    )
    assert len(alerts) == 1
    a = alerts[0]
    assert a["objective"] == "ttft_p95"
    assert a["target"] == 100.0
    assert a["burn_fast"] >= 10.0 and a["burn_slow"] >= 10.0
    assert a["observed_slow"] == 1000.0
    assert a["per_worker"]["w"]["ttft_p95_ms"] == 1000.0
    assert slo.last_burns["ttft_p95"]["fast"] >= 10.0


def test_slo_idle_fast_window_burns_zero_and_gates_the_alert():
    """The burst sits only in the slow window: the fast window's deltas are
    empty (no traffic is not an SLO violation), so no page."""
    slo = SloEvaluator(ttft_p95_ms=100.0, window_s=60.0, fast_window_s=5.0)
    slo.observe(0.0, {"w": _sample()})
    bad = _sample(ttft_pairs=[(1000.0, 10.0), (INF, 10.0)], requests=10.0)
    slo._snaps.append((50.0, {"w": bad}))  # burst at t=50, no alert check
    alerts = slo.observe(100.0, {"w": bad})  # unchanged since t=50
    assert alerts == []
    assert slo.last_burns["ttft_p95"]["slow"] >= 10.0
    assert slo.last_burns["ttft_p95"]["fast"] == 0.0


def test_slo_alert_debounce_honors_min_gap():
    slo = SloEvaluator(ttft_p95_ms=100.0, window_s=60.0, fast_window_s=5.0,
                       min_alert_gap_s=5.0)
    slo.observe(0.0, {"w": _sample()})

    def burst(cum):
        return {"w": _sample(ttft_pairs=[(1000.0, cum), (INF, cum)],
                             requests=cum)}

    assert len(slo.observe(100.0, burst(10.0))) == 1
    assert slo.observe(101.0, burst(20.0)) == []  # gap 1s < 5s: debounced
    assert len(slo.observe(106.0, burst(30.0))) == 1  # gap expired


def test_slo_served_ratio_and_shed_rate_objectives():
    slo = SloEvaluator(ttft_p95_ms=1e9, window_s=60.0, fast_window_s=5.0,
                       served_ratio=0.99, shed_ratio=0.05)
    slo.observe(0.0, {"w": _sample()})
    # 100 requests, 20 shed, 10 retryable-failed: served 0.7 (30x the 1%
    # budget), shed 0.2 (4x the 5% budget) -> both alert
    alerts = slo.observe(
        100.0, {"w": _sample(requests=100.0, sheds=20.0, failed=10.0)}
    )
    by_obj = {a["objective"]: a for a in alerts}
    assert set(by_obj) == {"served_ratio", "shed_rate"}
    assert abs(by_obj["served_ratio"]["observed_slow"] - 0.7) < 1e-9
    assert abs(by_obj["shed_rate"]["observed_slow"] - 0.2) < 1e-9
    assert by_obj["served_ratio"]["per_worker"]["w"]["sheds"] == 20.0


def test_slo_counter_reset_clamps_to_zero():
    slo = SloEvaluator(ttft_p95_ms=100.0, window_s=60.0, fast_window_s=5.0)
    slo.observe(0.0, {"w": _sample(requests=500.0, sheds=400.0)})
    # the worker restarted: cumulatives fell — deltas clamp at 0, no alert
    alerts = slo.observe(100.0, {"w": _sample(requests=3.0, sheds=1.0)})
    assert alerts == []
    assert slo.last_burns["shed_rate"]["slow"] == 0.0


def test_slo_sample_from_exposition_reads_the_objective_families():
    h = LogHistogram()
    for v in (12.0, 700.0):
        h.record(v)
    r = PromRenderer(default_labels={"worker_id": "w9"})
    r.histogram("lmstudio_ttft_ms", h.snapshot(), help="ttft")
    r.counter("lmstudio_batcher_requests_total", 7, help="reqs")
    r.counter("lmstudio_batcher_shed_by_cause_total", 2,
              labels={"cause": "queue_full"}, help="sheds")
    r.counter("lmstudio_inflight_failed_retryable_total", 1, help="failed")
    s = SloEvaluator.sample_from_exposition(r.render())
    assert s["requests"] == 7.0 and s["sheds"] == 2.0 and s["failed"] == 1.0
    assert merge([s["ttft"]]).count == 2


# -- acceptance e2e: two-hop disaggregated trace + p95 parity ----------------


async def _http_get_text(port, path):
    from test_gateway import _read_head, _send

    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        await _send(writer, "GET", path)
        status, headers = await _read_head(reader)
        n = int(headers.get("content-length", "0"))
        raw = await reader.readexactly(n) if n else await reader.read()
        return status, raw.decode()
    finally:
        writer.close()


def _walk(node, out):
    out.append(node)
    for c in node["children"]:
        _walk(c, out)


@async_test
async def test_two_hop_trace_assembly_p95_parity_and_slo_e2e(tmp_path):
    """ISSUE 14 acceptance: a real disaggregated chat through the HTTP
    gateway yields ONE assembled tree on ``lmstudio.debug.trace.<id>`` with
    gateway.request -> router.attempt -> worker.serve(decode) ->
    worker.kv_pull -> worker.kv_export(prefill) parent links; the
    aggregator's cluster TTFT p95 equals bench.py's merge on the same
    scrape; a deliberately impossible TTFT objective fires slo_burn on the
    events subject; the merged cluster exposition and the gateway's
    /metrics both pass the strict checker."""
    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.gateway import Gateway
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    from test_disagg import MID, _publish_tiny, _registry
    from test_gateway import _read_response, _send

    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    wp = wd = gw = agg = nc = None
    try:
        wp = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-prefill",
                         worker_role="prefill",
                         cluster_advert_interval_s=0.2),
            _registry(models),
        )
        wd = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-decode",
                         worker_role="decode",
                         cluster_advert_interval_s=0.2),
            _registry(models),
        )
        await wp.start()
        await wd.start()
        nc = await connect(broker.url)
        # the impossible TTFT target makes any real chat burn both windows
        agg = Aggregator(nc, scrape_interval_s=0.5, slo_ttft_p95_ms=0.001)
        await agg.start(scrape_loop=False)
        gw = Gateway(nc, port=0, chat_timeout_s=50.0)
        await gw.start()

        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if len(gw.router.members()) == 2 and len(agg.live_workers()) == 2:
                break
            await asyncio.sleep(0.05)
        assert len(gw.router.members()) == 2, gw.router.members()
        assert agg.live_workers() == ["w-decode", "w-prefill"]

        events = []
        got_burn = asyncio.Event()

        async def on_event(msg):
            d = json.loads(msg.payload)
            events.append(d)
            if d.get("kind") == "slo_burn":
                got_burn.set()

        ev_sub = await nc.subscribe("lmstudio.events", cb=on_event)

        await agg.scrape_once()  # baseline tick: SLO windows anchor here

        trace_id = new_trace_id()
        reader, writer = await asyncio.open_connection("127.0.0.1", gw.port)
        try:
            await _send(
                writer, "POST", "/v1/chat/completions",
                {"model": MID, "max_tokens": 8, "temperature": 0.0,
                 "messages": [{"role": "user", "content": "trace me"}]},
                headers={"X-Trace-Id": trace_id},
            )
            status, _, resp = await _read_response(reader)
        finally:
            writer.close()
        assert status == 200, resp
        assert resp["choices"][0]["message"]["content"]

        # -- assembled tree over the debug subject (the tentpole claim) ------
        tree = None
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            msg = await nc.request(
                f"lmstudio.debug.trace.{trace_id}", b"", timeout=5.0
            )
            env = json.loads(msg.payload)
            if env.get("ok") and env["data"]["span_count"] >= 5:
                tree = env["data"]
                break
            await asyncio.sleep(0.1)
        assert tree is not None, "trace never assembled to >= 5 spans"
        assert tree["trace_id"] == trace_id

        # exactly one causal root: the gateway span; every hop links under it
        assert len(tree["roots"]) == 1, [r["stage"] for r in tree["roots"]]
        root = tree["roots"][0]
        assert root["stage"] == "gateway.request"
        all_spans = []
        _walk(root, all_spans)
        assert all(s["trace_id"] == trace_id for s in all_spans)

        attempts = [c for c in root["children"]
                    if c["stage"] == "router.attempt"]
        assert attempts, [c["stage"] for c in root["children"]]
        served = next(a for a in attempts if a["attrs"]["outcome"] == "ok")
        assert served["attrs"]["worker"] == "w-decode"
        assert served["attrs"]["prefill_worker"] == "w-prefill"

        serves = [c for c in served["children"] if c["stage"] == "worker.serve"]
        assert len(serves) == 1 and serves[0]["worker_id"] == "w-decode"
        pulls = [c for c in serves[0]["children"]
                 if c["stage"] == "worker.kv_pull"]
        assert len(pulls) == 1 and pulls[0]["worker_id"] == "w-decode"
        assert pulls[0]["attrs"]["peer"] == "w-prefill"
        assert pulls[0]["attrs"]["outcome"] == "ok"
        exports = [c for c in pulls[0]["children"]
                   if c["stage"] == "worker.kv_export"]
        assert len(exports) == 1 and exports[0]["worker_id"] == "w-prefill"
        assert exports[0]["attrs"]["outcome"] == "ok"
        # parent ids are consistent, not just tree-shaped
        assert serves[0]["parent_span_id"] == served["span_id"]
        assert pulls[0]["parent_span_id"] == serves[0]["span_id"]
        assert exports[0]["parent_span_id"] == pulls[0]["span_id"]

        # -- p95 parity: aggregator vs bench's merge on the SAME scrape ------
        texts = await agg.scrape_once()
        assert set(texts) == {"w-decode", "w-prefill"}
        bench_p95 = merge(
            bucket_pairs(t, "lmstudio_ttft_ms") for t in texts.values()
        ).quantile(0.95)
        assert bench_p95 > 0.0
        cluster = agg.render_cluster()
        check_prom_exposition(cluster)
        line = next(ln for ln in cluster.splitlines()
                    if ln.startswith("lmstudio_cluster_ttft_p95_ms"))
        assert float(line.rsplit(None, 1)[1]) == round(bench_p95, 3)

        # the request/reply surface serves the identical merged view
        msg = await nc.request("lmstudio.cluster.metrics.prom", b"",
                               timeout=5.0)
        check_prom_exposition(msg.payload.decode())
        assert "lmstudio_cluster_workers 2" in msg.payload.decode()

        # -- SLO burn: the second scrape saw real TTFT >> 0.001ms ------------
        await asyncio.wait_for(got_burn.wait(), timeout=5.0)
        burn = next(e for e in events if e.get("kind") == "slo_burn")
        assert burn["objective"] == "ttft_p95"
        assert burn["burn_fast"] >= 1.0 and burn["burn_slow"] >= 1.0
        assert "w-decode" in burn["per_worker"]
        assert agg.alerts_total >= 1
        await ev_sub.unsubscribe()

        # -- gateway /metrics: the HTTP-edge families, strictly checked ------
        status, text = await _http_get_text(gw.port, "/metrics")
        assert status == 200
        types = check_prom_exposition(text)
        assert types["lmstudio_gateway_ttft_ms"] == "histogram"
        # 2: the chat POST plus this very GET (counted at accept time)
        assert 'lmstudio_gateway_requests_total{gateway="gateway"} 2' in text
        assert 'lmstudio_gateway_responses_total{gateway="gateway",status="200"} 1' in text
        assert merge(
            [bucket_pairs(text, "lmstudio_gateway_ttft_ms")]
        ).count == 1
    finally:
        if agg is not None:
            await agg.stop()
        if gw is not None:
            await gw.stop()
        if nc is not None:
            await nc.close()
        for w in (wd, wp):
            if w is not None:
                try:
                    await w.drain()
                except (ConnectionError, asyncio.TimeoutError):
                    pass
        await broker.stop()

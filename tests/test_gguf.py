"""GGUF layer tests (SURVEY.md §4.1): reader/writer roundtrip, block-quant
roundtrip with error bounds, vectorized dequant vs an independent scalar
reference, tokenizer encode/decode on synthetic vocabs."""

import numpy as np
import pytest

from nats_llm_studio_tpu.gguf import (
    GGMLType,
    GGUFReader,
    GGUFTokenizer,
    GGUFWriter,
    dequantize,
    quantize,
)
from nats_llm_studio_tpu.gguf.constants import TokenType

RNG = np.random.default_rng(0)


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------

# (type, relative RMS error bound)
QUANT_CASES = [
    (GGMLType.Q8_0, 0.01),
    (GGMLType.Q4_0, 0.10),
    (GGMLType.Q4_1, 0.10),
    (GGMLType.Q5_0, 0.05),
    (GGMLType.Q5_1, 0.05),
    (GGMLType.Q4_K, 0.10),
    (GGMLType.Q5_K, 0.05),
    (GGMLType.Q6_K, 0.03),
    (GGMLType.Q8_K, 0.01),
]


@pytest.mark.parametrize("ttype,bound", QUANT_CASES)
def test_quant_roundtrip_error(ttype, bound):
    x = RNG.standard_normal(4096).astype(np.float32)
    blob = quantize(x, ttype)
    y = dequantize(blob, ttype, x.size)
    rel = np.sqrt(np.mean((x - y) ** 2)) / np.sqrt(np.mean(x**2))
    assert rel < bound, f"{ttype.name}: rel RMS {rel:.4f} >= {bound}"


@pytest.mark.parametrize("ttype", [GGMLType.F32, GGMLType.F16, GGMLType.BF16])
def test_float_roundtrip(ttype):
    x = RNG.standard_normal(1024).astype(np.float32)
    y = dequantize(quantize(x, ttype), ttype, x.size)
    tol = {GGMLType.F32: 0, GGMLType.F16: 1e-3, GGMLType.BF16: 1e-2}[ttype]
    assert np.allclose(x, y, rtol=tol, atol=tol)


def test_bf16_round_to_nearest_even():
    x = np.array([1.0, -1.0, 3.14159265], dtype=np.float32)
    y = dequantize(quantize(x, GGMLType.BF16), GGMLType.BF16, 3)
    assert y[0] == 1.0 and y[1] == -1.0
    assert abs(y[2] - 3.14159265) < 0.02


# -- independent scalar reference decoders (written per the public GGML spec,
#    deliberately loop-based so a layout bug in the vectorized path can't
#    self-confirm) ----------------------------------------------------------


def _f16_at(b, off):
    return np.frombuffer(bytes(b[off : off + 2]), dtype="<f2")[0].astype(np.float32)


def _scalar_q8_0(blob, n):
    out = []
    for blk in range(n // 32):
        b = blob[blk * 34 : (blk + 1) * 34]
        d = _f16_at(b, 0)
        q = np.frombuffer(bytes(b[2:34]), dtype=np.int8)
        out.extend((d * q.astype(np.float32)).tolist())
    return np.array(out, dtype=np.float32)


def _scalar_q4_0(blob, n):
    out = []
    for blk in range(n // 32):
        b = blob[blk * 18 : (blk + 1) * 18]
        d = _f16_at(b, 0)
        qs = b[2:18]
        lo = [(q & 0xF) - 8 for q in qs]
        hi = [(q >> 4) - 8 for q in qs]
        out.extend([d * v for v in lo + hi])
    return np.array(out, dtype=np.float32)


def _scalar_q4_k(blob, n):
    out = []
    for blk in range(n // 256):
        b = blob[blk * 144 : (blk + 1) * 144]
        d = _f16_at(b, 0)
        dmin = _f16_at(b, 2)
        scales = b[4:16]
        qs = b[16:144]
        sc, m = [], []
        for j in range(8):
            if j < 4:
                sc.append(scales[j] & 63)
                m.append(scales[j + 4] & 63)
            else:
                sc.append((scales[j + 4] & 0xF) | ((scales[j - 4] >> 6) << 4))
                m.append((scales[j + 4] >> 4) | ((scales[j] >> 6) << 4))
        q = qs
        idx = 0
        for j in range(0, 256, 64):
            d1, m1 = d * sc[idx], dmin * m[idx]
            d2, m2 = d * sc[idx + 1], dmin * m[idx + 1]
            chunk = q[(j // 64) * 32 : (j // 64) * 32 + 32]
            out.extend([d1 * (c & 0xF) - m1 for c in chunk])
            out.extend([d2 * (c >> 4) - m2 for c in chunk])
            idx += 2
    return np.array(out, dtype=np.float32)


def _scalar_q6_k(blob, n):
    out = []
    for blk in range(n // 256):
        b = blob[blk * 210 : (blk + 1) * 210]
        ql = b[0:128]
        qh = b[128:192]
        sc = np.frombuffer(bytes(b[192:208]), dtype=np.int8)
        d = _f16_at(b, 208)
        y = np.zeros(256, dtype=np.float32)
        for half in range(2):
            qlo = ql[64 * half : 64 * half + 64]
            qho = qh[32 * half : 32 * half + 32]
            sco = sc[8 * half : 8 * half + 8]
            base = 128 * half
            for l in range(32):
                is_ = l // 16
                q1 = ((qlo[l] & 0xF) | (((qho[l] >> 0) & 3) << 4)) - 32
                q2 = ((qlo[l + 32] & 0xF) | (((qho[l] >> 2) & 3) << 4)) - 32
                q3 = ((qlo[l] >> 4) | (((qho[l] >> 4) & 3) << 4)) - 32
                q4 = ((qlo[l + 32] >> 4) | (((qho[l] >> 6) & 3) << 4)) - 32
                y[base + l] = d * sco[is_] * q1
                y[base + l + 32] = d * sco[is_ + 2] * q2
                y[base + l + 64] = d * sco[is_ + 4] * q3
                y[base + l + 96] = d * sco[is_ + 6] * q4
        out.extend(y.tolist())
    return np.array(out, dtype=np.float32)


@pytest.mark.parametrize(
    "ttype,scalar_fn",
    [
        (GGMLType.Q8_0, _scalar_q8_0),
        (GGMLType.Q4_0, _scalar_q4_0),
        (GGMLType.Q4_K, _scalar_q4_k),
        (GGMLType.Q6_K, _scalar_q6_k),
    ],
)
def test_vectorized_matches_scalar_reference(ttype, scalar_fn):
    x = RNG.standard_normal(512).astype(np.float32) * 3.0
    blob = quantize(x, ttype)
    fast = dequantize(blob, ttype, x.size)
    slow = scalar_fn(blob, x.size)
    np.testing.assert_allclose(fast, slow, rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# reader/writer
# ---------------------------------------------------------------------------


def test_file_roundtrip(tmp_path):
    path = tmp_path / "tiny.gguf"
    w = GGUFWriter(path)
    w.add_dict(
        {
            "general.architecture": "llama",
            "general.name": "tiny-test",
            "llama.block_count": 2,
            "llama.embedding_length": 64,
            "f.pi": 3.25,
            "b.flag": True,
            "arr.ints": [1, 2, 3],
            "arr.strs": ["a", "bb", "ccc"],
            "arr.floats": [0.5, 1.5],
        }
    )
    emb = RNG.standard_normal((8, 64)).astype(np.float32)
    wq = RNG.standard_normal((64, 64)).astype(np.float32)
    big = RNG.standard_normal((4, 256)).astype(np.float32)
    w.add_tensor("token_embd.weight", emb, GGMLType.F32)
    w.add_tensor("blk.0.attn_q.weight", wq, GGMLType.F16)
    w.add_tensor("blk.0.ffn_up.weight", big, GGMLType.Q4_K)
    w.write()

    with GGUFReader(path) as r:
        assert r.architecture == "llama"
        assert r.metadata["general.name"] == "tiny-test"
        assert r.arch_field("block_count") == 2
        assert r.metadata["f.pi"] == pytest.approx(3.25)
        assert r.metadata["b.flag"] is True
        assert r.metadata["arr.ints"] == [1, 2, 3]
        assert r.metadata["arr.strs"] == ["a", "bb", "ccc"]
        assert r.metadata["arr.floats"] == pytest.approx([0.5, 1.5])
        assert set(r.tensors) == {
            "token_embd.weight",
            "blk.0.attn_q.weight",
            "blk.0.ffn_up.weight",
        }
        t = r.tensor("token_embd.weight")
        assert t.shape == (8, 64)
        np.testing.assert_array_equal(t.to_numpy(), emb)
        np.testing.assert_allclose(
            r.tensor("blk.0.attn_q.weight").to_numpy(), wq, rtol=1e-3, atol=1e-3
        )
        q = r.tensor("blk.0.ffn_up.weight")
        assert q.ggml_type == GGMLType.Q4_K
        assert q.shape == (4, 256)
        rel = np.sqrt(np.mean((q.to_numpy() - big) ** 2)) / np.sqrt(np.mean(big**2))
        assert rel < 0.10


def test_reader_rejects_garbage(tmp_path):
    p = tmp_path / "bad.gguf"
    p.write_bytes(b"NOPE" + b"\x00" * 100)
    with pytest.raises(ValueError):
        GGUFReader(p)


def test_tensor_offsets_aligned(tmp_path):
    path = tmp_path / "aligned.gguf"
    w = GGUFWriter(path)
    w.add("general.architecture", "llama")
    # 3 odd-size F32 tensors force padding between tensors
    for i in range(3):
        w.add_tensor(f"t{i}", RNG.standard_normal(7 * (i + 1)).astype(np.float32))
    w.write()
    with GGUFReader(path) as r:
        for t in r.tensors.values():
            assert t.offset % 32 == 0
        np.testing.assert_allclose(r.tensor("t2").to_numpy().size, 21)


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------


def _spm_vocab():
    tokens = ["<unk>", "<s>", "</s>", "▁", "a", "b", "ab", "▁ab", "▁a", "c"]
    scores = [0.0, 0.0, 0.0, -3.0, -1.0, -1.0, -0.5, -0.1, -0.6, -1.0]
    types = [TokenType.UNKNOWN, TokenType.CONTROL, TokenType.CONTROL] + [TokenType.NORMAL] * 7
    # byte fallback tokens
    for b in range(256):
        tokens.append(f"<0x{b:02X}>")
        scores.append(-100.0)
        types.append(TokenType.BYTE)
    return GGUFTokenizer(
        model="llama",
        tokens=tokens,
        scores=scores,
        token_types=[int(t) for t in types],
        bos_id=1,
        eos_id=2,
        add_bos=True,
    )


def test_spm_encode_decode():
    tok = _spm_vocab()
    ids = tok.encode("ab ab")
    assert ids[0] == tok.bos_id
    assert tok.vocab["▁ab"] in ids
    assert tok.decode(ids) == "ab ab"


def test_spm_byte_fallback():
    tok = _spm_vocab()
    ids = tok.encode("aé", add_bos=False)  # é not in vocab -> 2 utf-8 byte tokens
    assert tok.decode(ids) == "aé"


def _bpe_vocab():
    # byte-level units for ascii + merges building "hello"
    from nats_llm_studio_tpu.gguf.tokenizer import _byte_to_unicode

    b2u = _byte_to_unicode()
    units = sorted({b2u[b] for b in range(256)})
    tokens = list(units)
    merges = []
    for a, b in [("h", "e"), ("l", "l"), ("he", "ll"), ("hell", "o"), (b2u[32], "hello")]:
        merges.append(f"{a} {b}")
        tokens.append(a + b)
    tokens += ["<|eot|>"]
    return GGUFTokenizer(
        model="gpt2",
        tokens=tokens,
        merges=merges,
        token_types=[int(TokenType.NORMAL)] * (len(tokens) - 1) + [int(TokenType.CONTROL)],
        bos_id=None,
        eos_id=len(tokens) - 1,
        add_bos=False,
    )


def test_bpe_encode_decode():
    tok = _bpe_vocab()
    ids = tok.encode("hello hello")
    assert tok.decode(ids) == "hello hello"
    # merges actually applied: "hello" collapses to 1 token, " hello" to 1
    assert len(ids) == 2


def test_bpe_unicode_roundtrip():
    tok = _bpe_vocab()
    text = "héllo ✓"
    assert tok.decode(tok.encode(text)) == text


def test_from_metadata():
    md = {
        "tokenizer.ggml.model": "llama",
        "tokenizer.ggml.tokens": ["<unk>", "<s>", "</s>", "▁", "x"],
        "tokenizer.ggml.scores": [0.0, 0.0, 0.0, -1.0, -1.0],
        "tokenizer.ggml.token_type": [2, 3, 3, 1, 1],
        "tokenizer.ggml.bos_token_id": 1,
        "tokenizer.ggml.eos_token_id": 2,
        "tokenizer.ggml.add_bos_token": True,
    }
    tok = GGUFTokenizer.from_metadata(md)
    assert tok.vocab_size == 5
    assert tok.bos_id == 1
    assert tok.encode("x")[0] == 1


@pytest.mark.parametrize("ttype", [GGMLType.Q4_K, GGMLType.Q5_K])
def test_kquant_positive_offset_data(ttype):
    """Sub-blocks with a positive minimum (biases, norm weights near 1.0)
    must survive the affine encoding, whose offset term is non-positive."""
    x = np.full(256, 5.0, dtype=np.float32)
    y = dequantize(quantize(x, ttype), ttype, x.size)
    np.testing.assert_allclose(y, x, rtol=0.02)
    x2 = RNG.uniform(5.0, 5.01, 256).astype(np.float32)
    y2 = dequantize(quantize(x2, ttype), ttype, x2.size)
    assert np.abs(y2 - x2).max() < 0.05


def test_tokenizer_rejects_unknown_model():
    with pytest.raises(NotImplementedError):
        GGUFTokenizer(model="bert", tokens=["a"])


def test_spm_unk_fallback_without_byte_tokens():
    tokens = ["<unk>", "▁", "a", "b"]
    tok = GGUFTokenizer(
        model="llama",
        tokens=tokens,
        scores=[0.0, -1.0, -1.0, -1.0],
        token_types=[int(TokenType.UNKNOWN)] + [int(TokenType.NORMAL)] * 3,
        add_bos=False,
    )
    ids = tok.encode("aé")  # é has no byte tokens -> unk per SentencePiece
    assert tok.unk_id == 0
    assert 0 in ids and tok.vocab["a"] in ids

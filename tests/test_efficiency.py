"""Compute-efficiency plane (ISSUE 16): roofline units, the device-time
ledger's outcome attribution through real shed/cancel/spec paths, HBM drift
gating, and the merged cluster exposition carrying fleet MFU/MBU families.

Unit tests pin exact values (XLA counts 2*m*n*k flops for a matmul; the
rolling window math is checked against a fake clock); the batcher tests drive
real served / cancelled / deadline-aborted / speculative requests and assert
the ledger's per-category device-ms reconcile with the measured dispatch time
within 10% — the same invariant bench.py's ``efficiency`` phase enforces.
"""

import asyncio
import time

import jax
import jax.numpy as jnp
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.obs.aggregator import merge_expositions
from nats_llm_studio_tpu.obs.roofline import (
    WASTE_CATEGORIES,
    HbmLedger,
    RollingUtilization,
    classify_program,
    dispatch_shape_key,
    efficiency_enabled,
    extract_dispatch_cost,
    resolve_chip_peaks,
)
from nats_llm_studio_tpu.serve.batcher import BatcherOverloaded, ContinuousBatcher, _Request

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


async def _wait_for(pred, timeout=10.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# -- chip peak table ----------------------------------------------------------


def test_resolve_chip_peaks_table(monkeypatch):
    monkeypatch.delenv("TPU_PEAK_FLOPS", raising=False)
    monkeypatch.delenv("TPU_HBM_GBPS", raising=False)
    assert resolve_chip_peaks("TPU v5e") == (197e12, 819e9)
    assert resolve_chip_peaks("TPU v5 lite") == (197e12, 819e9)
    assert resolve_chip_peaks("TPU v5p") == (459e12, 2765e9)
    assert resolve_chip_peaks("TPU v6e") == (918e12, 1640e9)
    assert resolve_chip_peaks("TPU v4") == (275e12, 1228e9)
    # unknown kinds (and the CPU backend's empty kind) get the modest fallback
    assert resolve_chip_peaks("") == (5e11, 5e10)
    assert resolve_chip_peaks("Quantum Abacus 9000") == (5e11, 5e10)


def test_resolve_chip_peaks_env_overrides(monkeypatch):
    monkeypatch.setenv("TPU_PEAK_FLOPS", "123e12")
    monkeypatch.setenv("TPU_HBM_GBPS", "456")
    assert resolve_chip_peaks("TPU v5e") == (123e12, 456e9)
    assert resolve_chip_peaks("") == (123e12, 456e9)
    # garbage overrides fall back to the table, never raise
    monkeypatch.setenv("TPU_PEAK_FLOPS", "not-a-number")
    monkeypatch.setenv("TPU_HBM_GBPS", "")
    assert resolve_chip_peaks("TPU v5e") == (197e12, 819e9)


def test_efficiency_kill_switch(monkeypatch):
    monkeypatch.delenv("EFFICIENCY", raising=False)
    assert efficiency_enabled()
    for off in ("0", "false", "OFF", " no "):
        monkeypatch.setenv("EFFICIENCY", off)
        assert not efficiency_enabled()
    monkeypatch.setenv("EFFICIENCY", "1")
    assert efficiency_enabled()


def test_classify_program():
    assert classify_program("prefill_full") == "prefill"
    assert classify_program("admit_fused_paged") == "prefill"
    assert classify_program("decode_pos") == "decode"
    assert classify_program("spec_verify") == "decode"
    assert classify_program("ring_compact") == "other"
    assert set(WASTE_CATEGORIES) >= {"served", "spec_rejected", "other"}


# -- per-dispatch cost extraction ---------------------------------------------


def test_extract_dispatch_cost_exact_matmul():
    """XLA's cost model counts 2*m*n*k flops for one matmul — pin the exact
    value so a silently broken extraction can't pass as 'nonzero'."""
    fn = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((64, 64), jnp.float32)
    cost = extract_dispatch_cost(fn, (a, a), {})
    assert cost is not None
    flops, bytes_ = cost
    assert flops == 2 * 64**3 == 524288
    # two (64,64) f32 inputs + one output = 3 * 16 KiB minimum traffic
    assert bytes_ >= 3 * 64 * 64 * 4


def test_dispatch_shape_key_buckets():
    a = jnp.ones((8, 4), jnp.float32)
    b = jnp.ones((8, 4), jnp.float32)
    c = jnp.ones((16, 4), jnp.float32)
    assert dispatch_shape_key((a, 3), {}) == dispatch_shape_key((b, 3), {})
    assert dispatch_shape_key((a,), {}) != dispatch_shape_key((c,), {})
    assert dispatch_shape_key((a,), {"k": 1}) != dispatch_shape_key((a,), {"k": 2})


def test_extract_dispatch_cost_never_raises():
    assert extract_dispatch_cost(object(), (), {}) is None


# -- rolling utilization ------------------------------------------------------


def test_rolling_utilization_fake_clock():
    t = [0.0]
    u = RollingUtilization(window_s=10.0, clock=lambda: t[0])
    u.add(1e9, 2e9)
    t[0] = 10.0
    # span is now - oldest sample = 10 s
    assert u.rates() == (1e8, 2e8)
    assert u.utilization((1e12, 1e12)) == (1e-4, 2e-4)
    # past the window the sample expires and the plane reads idle, not stale
    t[0] = 21.0
    assert u.rates() == (0.0, 0.0)
    assert u.utilization((1e12, 1e12)) == (0.0, 0.0)


def test_rolling_utilization_clamps_to_one():
    t = [0.0]
    u = RollingUtilization(window_s=10.0, clock=lambda: t[0])
    u.add(1e15, 1e15)
    t[0] = 1.0
    assert u.utilization((1e9, 1e9)) == (1.0, 1.0)


# -- HBM ledger ---------------------------------------------------------------


def _ledger(samples, **kw):
    """HbmLedger over a scripted bytes_in_use sequence; events recorded."""
    it = iter(samples)
    events = []
    led = HbmLedger(
        {"weights": lambda: 1000},
        bytes_in_use_fn=lambda: next(it),
        drift_threshold_bytes=kw.pop("threshold", 100),
        sustain_ticks=kw.pop("sustain", 3),
        emit_fn=lambda kind, **f: events.append((kind, f)),
    )
    return led, events


def test_hbm_ledger_fires_once_then_rebaselines():
    # unexplained = in_use - 1000; baseline anchors at the first tick (=0)
    grow = [1000, 1200, 1300, 1400, 1400, 1400, 1400]
    led, events = _ledger(grow)
    for _ in grow:
        led.tick()
    assert led.drift_events == 1
    assert [k for k, _ in events] == ["hbm_drift"]
    assert events[0][1]["unexplained_bytes"] == 400
    # re-baselined at 400: the stable-but-larger footprint never re-fires
    s = led.last_sample()
    assert s["bytes_in_use"] == 1400 and s["priced_bytes"] == 1000
    assert s["drift_bytes"] == 0


def test_hbm_ledger_no_fire_below_threshold_or_nonmonotone():
    # oscillates: each dip resets the sustain counter
    led, events = _ledger([1000, 1250, 1100, 1250, 1100, 1250, 1100, 1250])
    for _ in range(8):
        led.tick()
    assert led.drift_events == 0 and not events
    # steady growth but under the threshold
    led2, events2 = _ledger([1000, 1030, 1060, 1090, 1099, 1099])
    for _ in range(6):
        led2.tick()
    assert led2.drift_events == 0 and not events2


def test_hbm_ledger_cpu_backend_is_inert():
    led = HbmLedger(
        {"weights": lambda: 1 << 30},
        bytes_in_use_fn=lambda: None,
        drift_threshold_bytes=1,
        sustain_ticks=1,
    )
    for _ in range(5):
        assert led.tick() == 0
    assert led.drift_events == 0
    s = led.last_sample()
    assert s["bytes_in_use"] == 0 and s["unexplained_bytes"] == 0
    assert s["priced_bytes"] == 1 << 30  # components still priced/reported


def test_hbm_ledger_broken_component_prices_zero():
    def boom():
        raise RuntimeError("pool gone")

    led = HbmLedger({"pool": boom}, bytes_in_use_fn=lambda: 500,
                    drift_threshold_bytes=10**9)
    led.tick()
    assert led.last_sample()["components"] == {"pool": 0}


# -- device-time ledger through real batcher paths ----------------------------


def _reconcile(stats):
    """Assert the ledger's attributed ms sum to the measured dispatch time
    within 10% (the bench.py efficiency-phase invariant), and return the
    per-category snapshot."""
    dt = stats.device_time_snapshot()
    ledger_ms = sum(dt["ms"].values())
    busy_ms = stats.dispatch_ms_total
    assert busy_ms > 0.0
    assert abs(ledger_ms - busy_ms) <= 0.10 * busy_ms, (dt["ms"], busy_ms)
    return dt


@async_test
async def test_ledger_attributes_served_and_cancelled(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        out = [t async for t in b.submit([1, 2, 3], SamplingParams(
            temperature=0.0, max_tokens=8))]
        assert len(out) == 8

        agen = b.submit_batched([4, 5, 6], SamplingParams(
            temperature=0.0, max_tokens=60))
        got = 0
        async for batch in agen:
            got += len(batch)
            if got >= 2:
                break
        await agen.aclose()
        await _wait_for(
            lambda: all(s is None for s in b._slots) and b.stats.cancelled == 1,
            what="slot freed after close",
        )
        dt = _reconcile(b.stats)
        assert dt["ms"]["served"] > 0.0
        assert dt["ms"]["cancelled"] > 0.0, dt["ms"]
        # tokens count toward goodput only for the served outcome
        assert dt["tokens"]["served"] >= 8
        assert b.stats.goodput_tokens_per_device_s() > 0.0
        # the rolling roofline saw both prefill and decode dispatches
        util = b.stats.utilization((1e12, 1e12))
        assert util["prefill"]["mfu"] > 0.0 and util["prefill"]["mbu"] > 0.0
        assert util["decode"]["mfu"] > 0.0 and util["decode"]["mbu"] > 0.0
        flops, bytes_ = b.stats.cost_counters()
        assert sum(flops.values()) > 0 and sum(bytes_.values()) > 0
    finally:
        b.stop()


@async_test
async def test_ledger_attributes_mid_decode_deadline_abort(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        agen = b.submit_batched([1, 2, 3], SamplingParams(
            temperature=0.0, max_tokens=60), deadline=time.monotonic() + 300.0)
        poked = False
        with pytest.raises(BatcherOverloaded):
            async for _batch in agen:
                if poked:
                    continue
                req = next((s for s in b._slots if isinstance(s, _Request)), None)
                if req is not None:
                    req.deadline = time.monotonic() - 0.001
                    poked = True
        await _wait_for(
            lambda: all(s is None for s in b._slots),
            what="slot freed after deadline abort",
        )
        dt = _reconcile(b.stats)
        assert dt["ms"]["deadline_abort"] > 0.0, dt["ms"]
        assert dt["ms"]["served"] == 0.0  # nothing completed: all waste
        assert b.stats.goodput_tokens_per_device_s() == 0.0
    finally:
        b.stop()


@async_test
async def test_ledger_attributes_spec_rejected(model):
    """Speculative decoding on a repetition-heavy prompt: verify dispatches
    run, and any drafted-but-rejected fraction of their device time lands in
    'spec_rejected' while the ledger still reconciles."""
    cfg, params = model
    REP = [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6]
    b = ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64],
        spec_decode_k=4, decode_burst=1,
    )
    try:
        out = [t async for t in b.submit(REP, SamplingParams(
            temperature=0.0, max_tokens=24))]
        assert len(out) == 24
        snap = b.stats.snapshot()
        assert snap["spec_verifies"] > 0
        dt = _reconcile(b.stats)
        assert dt["ms"]["served"] > 0.0
        if snap["spec_drafted"] > snap["spec_accepted"]:
            assert dt["ms"]["spec_rejected"] > 0.0, (snap, dt["ms"])
    finally:
        b.stop()


@async_test
async def test_ledger_waste_tag_reclassifies_prefill(model):
    """A request submitted with waste_tag='disagg_fallback_reprefill' (the
    worker's failed-KV-prefetch marker) charges its prefill device-ms to that
    category instead of 'served' — decode ms still counts as served."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        out = [t async for t in b.submit([9, 8, 7, 6], SamplingParams(
            temperature=0.0, max_tokens=6), waste_tag="disagg_fallback_reprefill")]
        assert len(out) == 6
        dt = _reconcile(b.stats)
        assert dt["ms"]["disagg_fallback_reprefill"] > 0.0, dt["ms"]
        assert dt["ms"]["served"] > 0.0  # the decode half is real goodput
        assert dt["tokens"]["served"] == 6
    finally:
        b.stop()


# -- cluster rollup -----------------------------------------------------------


def test_merge_expositions_averages_ratio_gauges():
    """Two workers at 40% and 20% MFU merge to 30%, not 60% — while totals
    (counters) still sum."""
    w1 = (
        "# TYPE lmstudio_mfu gauge\n"
        'lmstudio_mfu{class="decode",worker_id="w1"} 0.4\n'
        "# TYPE lmstudio_device_ms_total counter\n"
        'lmstudio_device_ms_total{category="served",worker_id="w1"} 100\n'
    )
    w2 = (
        "# TYPE lmstudio_mfu gauge\n"
        'lmstudio_mfu{class="decode",worker_id="w2"} 0.2\n'
        "# TYPE lmstudio_device_ms_total counter\n"
        'lmstudio_device_ms_total{category="served",worker_id="w2"} 50\n'
    )
    merged = merge_expositions([w1, w2])
    assert 'lmstudio_mfu{class="decode"} 0.3' in merged
    assert 'lmstudio_device_ms_total{category="served"} 150' in merged


@async_test
async def test_cluster_exposition_carries_efficiency_families(tmp_path, monkeypatch):
    """Acceptance e2e: after one real chat, the aggregator's merged cluster
    exposition carries fleet lmstudio_mfu / lmstudio_device_ms_total{category}
    families plus the gateway's lmstudio_gateway_* (folded in via the
    gateway's advert + directed metrics.prom subject), and the whole text
    passes the strict Prometheus checker. Gateway adverts must NOT count as
    workers in the router or the cluster gauge."""
    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.gateway import Gateway
    from nats_llm_studio_tpu.obs.aggregator import Aggregator
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    from test_disagg import MID, _publish_tiny, _registry
    from test_gateway import _read_response, _send
    from test_obs import check_prom_exposition

    monkeypatch.setenv("GATEWAY_ADVERT_INTERVAL_S", "0.05")
    models = tmp_path / "models"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    w = gw = agg = nc = None
    try:
        w = Worker(
            WorkerConfig(nats_url=broker.url, worker_id="w-eff",
                         cluster_advert_interval_s=0.05),
            _registry(models),
        )
        await w.start()
        nc = await connect(broker.url)
        agg = Aggregator(nc, scrape_interval_s=0.5)
        await agg.start(scrape_loop=False)
        gw = Gateway(nc, port=0, chat_timeout_s=50.0)
        await gw.start()

        await _wait_for(
            lambda: agg.live_workers() == ["w-eff"]
            and gw.ident in agg._scrape_targets()
            and len(gw.router.members()) == 1,
            what="worker + gateway advertising",
        )
        # the gateway advert is a scrape target but never a worker
        assert gw.ident not in agg.live_workers()
        assert [m.worker_id for m in gw.router.members()] == ["w-eff"]

        reader, writer = await asyncio.open_connection("127.0.0.1", gw.port)
        try:
            await _send(
                writer, "POST", "/v1/chat/completions",
                {"model": MID, "max_tokens": 6, "temperature": 0.0,
                 "messages": [{"role": "user", "content": "efficiency"}]},
            )
            status, _, resp = await _read_response(reader)
        finally:
            writer.close()
        assert status == 200, resp

        await agg.scrape_once()
        text = agg.render_cluster()
        check_prom_exposition(text)
        assert 'lmstudio_mfu{class="prefill"' in text
        assert 'lmstudio_mfu{class="decode"' in text
        assert 'lmstudio_mbu{class="decode"' in text
        assert 'lmstudio_device_ms_total{category="served"' in text
        assert "lmstudio_goodput_tokens_per_device_s" in text
        assert "lmstudio_program_flops_total{" in text
        assert "lmstudio_hbm_drift_bytes" in text
        # gateway families folded into the same cluster view
        assert "lmstudio_gateway_requests_total" in text
        # the gateway advert did not inflate the worker count
        assert "lmstudio_cluster_workers 1" in text
    finally:
        for x in (agg, gw):
            if x is not None:
                await x.stop()
        if w is not None:
            await w.drain()
        if nc is not None:
            await nc.close()
        await broker.stop()

"""Sharding tests on the 8-way virtual CPU mesh (SURVEY.md §4.3-4.4): mesh
spec parsing, TP/DP/EP-sharded forward matching the unsharded reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.parallel import build_mesh, parse_mesh_spec, shard_cache, shard_params
from nats_llm_studio_tpu.parallel.sharding import validate_mesh_for_config


def test_parse_mesh_spec():
    assert parse_mesh_spec("tp=8") == {"tp": 8}
    assert parse_mesh_spec("tp=4,dp=2") == {"dp": 2, "tp": 4}  # normalized order
    assert parse_mesh_spec("") == {}
    assert parse_mesh_spec("auto") == {}
    with pytest.raises(ValueError):
        parse_mesh_spec("zz=4")
    with pytest.raises(ValueError):
        parse_mesh_spec("tp=0")


def test_build_mesh_validates_device_count():
    assert build_mesh("tp=8").shape == {"tp": 8}
    assert dict(build_mesh("dp=2,tp=4").shape) == {"dp": 2, "tp": 4}
    assert build_mesh("").shape == {"tp": 8}
    with pytest.raises(ValueError):
        build_mesh("tp=3")


def test_validate_mesh_for_config():
    mesh = build_mesh("tp=8")
    validate_mesh_for_config(mesh, ModelConfig.tiny(n_heads=8, n_kv_heads=8, d_ff=128))
    with pytest.raises(ValueError):
        validate_mesh_for_config(mesh, ModelConfig.tiny(n_heads=6, n_kv_heads=2))


def _run(cfg, params, k, v, tokens):
    logits, k, v = forward(params, cfg, tokens, k, v, jnp.zeros((tokens.shape[0],), jnp.int32))
    return np.asarray(logits), k, v


@pytest.mark.parametrize("spec", ["tp=8", "dp=2,tp=4"])
def test_sharded_forward_matches_unsharded(spec):
    cfg = ModelConfig.tiny(n_heads=8, n_kv_heads=8, head_dim=8, d_model=64, d_ff=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray([[1, 2, 3, 4], [9, 8, 7, 6]], jnp.int32)

    k, v = make_cache(cfg, 2, 16)
    ref, _, _ = _run(cfg, params, k, v, tokens)

    mesh = build_mesh(spec)
    validate_mesh_for_config(mesh, cfg)
    sp = shard_params(params, mesh)
    k, v = make_cache(cfg, 2, 16)
    k, v = shard_cache(k, v, mesh)
    got, k2, v2 = _run(cfg, sp, k, v, tokens)

    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)
    # cache written identically under sharding
    k_ref, v_ref = make_cache(cfg, 2, 16)
    _, k_ref, v_ref = forward(params, cfg, tokens, k_ref, v_ref, jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k_ref), rtol=2e-3, atol=2e-3)


def test_moe_expert_parallel_matches():
    cfg = ModelConfig.tiny(
        n_heads=4, n_kv_heads=4, head_dim=8, d_model=32, d_ff=64, n_experts=4, n_experts_used=2
    )
    params = init_params(cfg, jax.random.PRNGKey(1))
    tokens = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)

    k, v = make_cache(cfg, 2, 8)
    ref, _, _ = _run(cfg, params, k, v, tokens)

    mesh = build_mesh("dp=2,ep=4")
    validate_mesh_for_config(mesh, cfg)
    sp = shard_params(params, mesh)
    k, v = make_cache(cfg, 2, 8)
    k, v = shard_cache(k, v, mesh)
    got, _, _ = _run(cfg, sp, k, v, tokens)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_sharded_decode_consistency():
    """Prefill + decode under TP matches unsharded full prefill."""
    cfg = ModelConfig.tiny(n_heads=8, n_kv_heads=8, head_dim=8, d_model=64, d_ff=128)
    params = init_params(cfg, jax.random.PRNGKey(2))
    seq = [1, 2, 3, 4, 5]
    full = jnp.asarray([seq], jnp.int32)

    k, v = make_cache(cfg, 1, 16)
    ref, _, _ = _run(cfg, params, k, v, full)

    mesh = build_mesh("tp=8")
    sp = shard_params(params, mesh)
    k, v = shard_cache(*make_cache(cfg, 1, 16), mesh)
    logits, k, v = forward(sp, cfg, full[:, :3], k, v, jnp.zeros((1,), jnp.int32))
    for t in range(3, 5):
        logits, k, v = forward(sp, cfg, full[:, t : t + 1], k, v, jnp.full((1,), t, jnp.int32))
        np.testing.assert_allclose(np.asarray(logits[0, 0]), ref[0, t], rtol=2e-3, atol=2e-3)


def test_streaming_sharded_loader_matches(tmp_path):
    """load_params_sharded (per-tensor streaming onto the mesh) must produce
    the same numbers as full-host load + shard_params."""
    from nats_llm_studio_tpu.gguf import GGUFReader
    from nats_llm_studio_tpu.models.export import export_params_to_gguf
    from nats_llm_studio_tpu.models.llama import load_params_from_gguf
    from nats_llm_studio_tpu.parallel.loader import load_params_sharded

    cfg = ModelConfig.tiny(n_heads=8, n_kv_heads=8, head_dim=8, d_model=64, d_ff=128, n_layers=3)
    params = init_params(cfg, jax.random.PRNGKey(9))
    path = tmp_path / "m.gguf"
    export_params_to_gguf(path, params, cfg)
    mesh = build_mesh("tp=8")
    with GGUFReader(path) as r:
        host = load_params_from_gguf(r, cfg)
        streamed = load_params_sharded(r, cfg, mesh)
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    k, v = make_cache(cfg, 1, 16)
    ref, _, _ = forward(host, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    k, v = shard_cache(*make_cache(cfg, 1, 16), mesh)
    got, _, _ = forward(streamed, cfg, tokens, k, v, jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_streaming_sharded_loader_moe(tmp_path):
    from nats_llm_studio_tpu.gguf import GGUFReader
    from nats_llm_studio_tpu.models.export import export_params_to_gguf
    from nats_llm_studio_tpu.parallel.loader import load_params_sharded

    cfg = ModelConfig.tiny(
        n_heads=4, n_kv_heads=4, head_dim=8, d_model=32, d_ff=64,
        n_experts=4, n_experts_used=2, n_layers=2,
    )
    params = init_params(cfg, jax.random.PRNGKey(10))
    path = tmp_path / "moe.gguf"
    export_params_to_gguf(path, params, cfg)
    mesh = build_mesh("dp=2,ep=4")
    with GGUFReader(path) as r:
        streamed = load_params_sharded(r, cfg, mesh)
    tokens = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    k, v = make_cache(cfg, 2, 8)
    ref, _, _ = forward(params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32))
    k, v = shard_cache(*make_cache(cfg, 2, 8), mesh)
    got, _, _ = forward(streamed, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-3, atol=2e-3)


def test_sp_ring_prefill_matches_dense():
    """Sequence-parallel prefill: dp x sp mesh routes the fresh-block
    attention through ring_attention (T sharded on sp, K/V rotating via
    ppermute) and must reproduce the unsharded logits and cache, then decode
    consistently on the sp-sharded cache (VERDICT round-1 item 8)."""
    cfg = ModelConfig.tiny(
        n_heads=8, n_kv_heads=8, head_dim=8, d_model=64, d_ff=128, max_seq_len=64
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    t = 8  # divisible by sp=4
    tokens = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8], [9, 8, 7, 6, 5, 4, 3, 2]], jnp.int32)

    k, v = make_cache(cfg, 2, 16)
    ref, k_ref, v_ref = forward(
        params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32)
    )

    mesh = build_mesh("dp=2,sp=4")
    validate_mesh_for_config(mesh, cfg.with_(max_seq_len=16))
    sp_params = shard_params(params, mesh)
    k, v = make_cache(cfg, 2, 16)
    k, v = shard_cache(k, v, mesh)
    got, k2, v2 = forward(
        sp_params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32), mesh=mesh
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k_ref), rtol=2e-3, atol=2e-3)

    # decode one token on the sp-sharded cache
    nxt = jnp.asarray([[11], [12]], jnp.int32)
    pos = jnp.full((2,), t, jnp.int32)
    want, _, _ = forward(params, cfg, nxt, k_ref, v_ref, pos)
    got2, _, _ = forward(sp_params, cfg, nxt, k2, v2, pos, mesh=mesh)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want), rtol=2e-3, atol=2e-3)

"""Ring attention over the sp axis vs dense causal attention (8-device
virtual CPU mesh — SURVEY.md §4.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.ops.layers import gqa_attention
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.ring_attention import ring_attention, ring_attention_sharded

RNG = jax.random.PRNGKey(7)


def _dense_causal(q, k, v, scale):
    t = q.shape[1]
    pos = jnp.arange(t)
    mask = jnp.broadcast_to(pos[None, :] <= pos[:, None], (q.shape[0], t, t))
    return gqa_attention(q, k, v, mask, scale)


@pytest.mark.parametrize(
    "spec,b,t,hq,hkv,d",
    [
        ("sp=8", 1, 64, 4, 4, 16),   # MHA, 8-way ring
        ("sp=4,dp=2", 2, 32, 8, 2, 8),  # GQA + dp on the same mesh
        ("sp=2,tp=4", 1, 16, 4, 4, 8),  # ring alongside a tp axis
    ],
)
def test_ring_matches_dense(spec, b, t, hq, hkv, d):
    kq, kk, kv = jax.random.split(RNG, 3)
    q = jax.random.normal(kq, (b, t, hq, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, hkv, d), jnp.float32)
    scale = d**-0.5
    want = _dense_causal(q, k, v, scale)
    mesh = build_mesh(spec)
    got = ring_attention(q, k, v, scale, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_ring_under_jit():
    mesh = build_mesh("sp=8")
    q = jax.random.normal(RNG, (1, 64, 2, 8), jnp.float32)
    scale = 8**-0.5
    fn = jax.jit(lambda q: ring_attention(q, q, q, scale, mesh))
    got = fn(q)
    want = _dense_causal(q, q, q, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_sharded_helper_falls_back_without_sp():
    mesh = build_mesh("tp=8")
    q = jax.random.normal(RNG, (1, 16, 2, 8), jnp.float32)
    scale = 8**-0.5
    got = ring_attention_sharded(q, q, q, scale, mesh)
    want = _dense_causal(q, q, q, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

"""Paged-KV equivalence and pool bookkeeping (PR 7 tentpole).

One refcounted fixed-size-block pool (serve/block_pool.py) replaces the
contiguous per-slot KV rings; per-slot block tables address it from the
admit/decode/spec jits. The legacy layout is kept behind ``paged=False``
as the bit-equivalence baseline: greedy decode through the batcher must be
IDENTICAL in both layouts — plain, chunked-prefill, prefix-cache hit
(partial and full), speculative-decode, and tp=2 on the 8 forced host
devices (conftest.py) — because the paged gather view rides the same pow2
window ladder, so every softmax reduces over the same extent. Also pins
the pool's refcount hygiene (fully free after drain), CoW divergence, LRU
eviction under pin, and the no-reset shed when the pool runs dry.
"""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.sharding import shard_params
from nats_llm_studio_tpu.serve.batcher import BatcherOverloaded, ContinuousBatcher
from nats_llm_studio_tpu.serve.block_pool import BlockPool
from nats_llm_studio_tpu.serve.prefix_cache import PrefixCache

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


async def _greedy_batch(params, cfg, prompts, n, mesh=None, **kw):
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                          buckets=[8, 64], mesh=mesh, **kw)
    try:
        async def one(p):
            sp = SamplingParams(temperature=0.0, max_tokens=n)
            return [t async for t in b.submit(p, sp)]

        return await asyncio.gather(*[one(p) for p in prompts])
    finally:
        b.stop()


# -- the tentpole: bit-identical greedy decode, paged vs contiguous ----------


@async_test
async def test_paged_greedy_matches_contiguous(model):
    """Short-path admits (solo + concurrent group) through the block pool
    reproduce the legacy ring's greedy tokens exactly."""
    cfg, params = model
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30, 40, 50]]
    want = await _greedy_batch(params, cfg, prompts, 6, paged=False)
    got = await _greedy_batch(params, cfg, prompts, 6, paged=True)
    assert got == want


@async_test
async def test_paged_chunked_prefill_matches(model):
    """Long prompts (chunked group admission + finish) land their KV in
    pool blocks and still decode the legacy sequence."""
    cfg, params = model
    prompts = [
        [(i * 5 + 1) % cfg.vocab_size for i in range(20)],
        [(i * 11 + 4) % cfg.vocab_size for i in range(33)],
    ]
    want = await _greedy_batch(params, cfg, prompts, 5, paged=False,
                               prefill_chunk=8)
    got = await _greedy_batch(params, cfg, prompts, 5, paged=True,
                              prefill_chunk=8)
    assert got == want


@async_test
async def test_paged_prefix_hit_matches_and_is_zero_copy(model):
    """A resent prompt takes the hit path in both layouts with identical
    output; in the paged layout the hit is a refcount bump — the CoW
    counter stays 0 (chunk-aligned sharing never writes a shared block)."""
    cfg, params = model
    # 18 tokens = 2 full chunks (C=8) + a 2-token suffix: a PARTIAL hit
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(18)]

    async def run(paged):
        b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                              buckets=[8, 64], prefill_chunk=8,
                              prefix_cache_blocks=16, paged=paged)
        try:
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            first = [t async for t in b.submit(prompt, sp)]
            again = [t async for t in b.submit(prompt, sp)]
            hits = b.prefix_cache.counters()["hits"]
            pool = b.pool_stats()
            return first, again, hits, pool
        finally:
            b.stop()

    w_first, w_again, w_hits, pool = await run(False)
    p_first, p_again, p_hits, ppool = await run(True)
    assert pool is None and ppool is not None
    assert p_first == w_first and p_again == w_again
    assert p_hits >= 1 and w_hits >= 1
    assert ppool["cow_copies"] == 0


@async_test
async def test_paged_full_prefix_hit_matches(model):
    """A prompt that is EXACTLY whole chunks full-hits on resend: the
    paged admit samples from the cached end-logits with zero KV programs,
    and the continuation still matches the legacy layout bit-for-bit."""
    cfg, params = model
    prompt = [(i * 3 + 2) % cfg.vocab_size for i in range(16)]  # 2x C=8

    async def run(paged):
        b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                              buckets=[8, 64], prefill_chunk=8,
                              prefix_cache_blocks=16, paged=paged)
        try:
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            outs = []
            for _ in range(3):
                outs.append([t async for t in b.submit(prompt, sp)])
            return outs, b.prefix_cache.counters()["full_hits"]
        finally:
            b.stop()

    w_outs, w_full = await run(False)
    p_outs, p_full = await run(True)
    assert p_outs == w_outs
    assert w_full >= 2 and p_full >= 2  # resends took the full-hit path
    assert p_outs[0] == p_outs[1] == p_outs[2]


@async_test
async def test_paged_spec_decode_matches(model):
    """Speculative decoding through the pool (block-table verify writes +
    positional layout) emits exactly the plain greedy sequence."""
    cfg, params = model
    prompt = [7, 8, 9, 7, 8, 9, 7, 8]  # repetition: prompt-lookup drafts hit
    want = await _greedy_batch(params, cfg, [prompt], 10, paged=False)
    legacy_spec = await _greedy_batch(params, cfg, [prompt], 10, paged=False,
                                      spec_decode_k=4)
    paged_spec = await _greedy_batch(params, cfg, [prompt], 10, paged=True,
                                     spec_decode_k=4)
    assert legacy_spec == want
    assert paged_spec == want


@async_test
async def test_tp2_paged_matches_unsharded(model):
    """The pool shards on the KV-heads axis under tp=2 (pool_spec); greedy
    decode through the sharded pool matches the unsharded paged batcher
    and the legacy layout."""
    cfg, params = model
    prompts = [[1, 2, 3], [9, 8, 7, 6], [4, 4, 4, 4, 4]]
    want = await _greedy_batch(params, cfg, prompts, 6, paged=False)
    mesh = build_mesh("tp=2", devices=jax.devices()[:2])
    sharded = shard_params(params, mesh, cfg)
    got = await _greedy_batch(sharded, cfg, prompts, 6, mesh=mesh, paged=True)
    assert got == want


# -- pool bookkeeping ---------------------------------------------------------


@async_test
async def test_pool_fully_free_after_drain(model):
    """Refcount leak check: once every request completes and the prefix
    cache is dropped, every block is back on the free list."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                          buckets=[8, 64], prefill_chunk=8,
                          prefix_cache_blocks=16, paged=True)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=5)
        prompts = [[1, 2, 3], [(i * 7) % cfg.vocab_size for i in range(18)],
                   [5, 6], [(i * 3) % cfg.vocab_size for i in range(18)]]

        async def one(p):
            return [t async for t in b.submit(p, sp)]

        await asyncio.gather(*[one(p) for p in prompts])
        st = b.pool_stats()
        # slots drained: only prefix-cache pins remain (refs == 1, so none
        # of the live blocks count as shared)
        assert st["blocks_shared"] == 0
        assert st["blocks_live"] == st["blocks_total"] - st["blocks_free"]
        b.drop_prefix_cache()
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"], st
        assert st["blocks_live"] == 0
    finally:
        b.stop()


@async_test
async def test_pool_exhausted_sheds_without_reset(model):
    """An admit that cannot get blocks sheds THAT request with a retryable
    BatcherOverloaded — live slots keep decoding and later submits
    succeed (no engine reset, no cache wipe)."""
    cfg, params = model
    # 7 usable blocks of 16 tokens: two long slots fit, four cannot. The
    # 33-token prompts round up to 3 blocks (48 positions) so prompt + 4
    # new tokens + pipeline overshoot (decode_burst=2, depth 2) never
    # needs a decode-time extension — the only alloc is at admit, where
    # the shed path is pre-dispatch.
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64,
                          buckets=[8, 64], paged=True, kv_pool_blocks=7,
                          decode_burst=2)
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=4)
        long_p = [(i * 5 + 1) % cfg.vocab_size for i in range(33)]

        async def one(p):
            return [t async for t in b.submit(list(p), sp)]

        results = await asyncio.gather(
            *[one(long_p[j:] + long_p[:j]) for j in range(4)],
            return_exceptions=True,
        )
        shed = [r for r in results if isinstance(r, BatcherOverloaded)]
        served = [r for r in results if isinstance(r, list)]
        assert served, results  # the pool served what fits
        for r in results:  # nothing failed for any OTHER reason
            assert isinstance(r, (list, BatcherOverloaded)), r
        if shed:  # shed errors are retryable-shaped, not resets
            assert "pool" in str(shed[0])
        # the engine is still healthy: a fresh request runs to completion
        out = await one([1, 2, 3])
        assert len(out) == 4
        st = b.pool_stats()
        assert st["blocks_free"] == st["blocks_total"]
    finally:
        b.stop()


def test_block_pool_refcounts_and_cow_copy(model):
    """BlockPool unit semantics + the CoW copy program: a shared block is
    copied (not aliased) into a fresh block, so the writer diverges while
    the other holder's bytes stay put."""
    pool = BlockPool(8, 16)
    ids = pool.alloc(3)
    assert ids is not None and 0 not in ids  # null block never handed out
    pool.incref([ids[0]])  # second holder (e.g. the prefix cache)
    pool.decref(ids)  # first holder frees: ids[1:] return, ids[0] pinned
    st = pool.stats()
    assert st["blocks_live"] == 1 and st["blocks_free"] == st["blocks_total"] - 1
    pool.decref([ids[0]])
    assert pool.stats()["blocks_free"] == pool.stats()["blocks_total"]

    # device-level divergence through the batcher's CoW copy jit
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], paged=True)
    try:
        T = b.kv_block_tokens
        shape = (4, cfg.n_layers, cfg.n_kv_heads, T, cfg.head_dim)
        kp = jnp.arange(int(jnp.prod(jnp.asarray(shape))),
                        dtype=jnp.float32).reshape(shape)
        vp = kp + 1000.0
        src_row = kp[2]
        kp2, vp2 = b._pool_copy_block(kp, vp, jnp.int32(1), jnp.int32(2))
        assert jnp.array_equal(kp2[1], src_row)  # dst got src's bytes
        kp3 = kp2.at[1].set(-1.0)  # writer diverges in its private block
        assert jnp.array_equal(kp3[2], src_row)  # sharer's block untouched
        assert float(vp2[1, 0, 0, 0, 0]) == float(vp2[2, 0, 0, 0, 0])
    finally:
        b.stop()


def test_prefix_eviction_skips_pinned_nodes():
    """Eviction-under-pin safety: reclaim only evicts UNPINNED leaves; a
    pinned node's blocks are freed when the pin is released, not before."""
    pool = BlockPool(16, 8)

    def acquire(payload):
        _, ids = payload
        pool.incref(ids)

    def free(payload):
        epoch, ids = payload
        pool.decref(ids, epoch=epoch)

    pc = PrefixCache(8, 8, node_blocks=2, acquire_fn=acquire, free_fn=free)
    a = list(range(8))
    b = list(range(8, 16))
    ids_a = pool.alloc(2)
    ids_b = pool.alloc(2)
    # mirror the batcher's harvest: the slot's refs transfer via acquire_fn
    pc.insert(a, [(pool.epoch, ids_a)])
    pc.insert(b, [(pool.epoch, ids_b)])
    pool.decref(ids_a)
    pool.decref(ids_b)
    assert pool.stats()["blocks_live"] == 4

    # query PAST the cached chunk: a whole-prompt match without stored
    # logits is deliberately dropped by _walk (no first token to sample)
    q_a = a + [100, 101, 102, 103]
    hit = pc.match(q_a)
    assert hit is not None and len(hit.nodes) == 1
    freed = pc.reclaim(8)  # wants everything; the pinned node must survive
    assert freed == 2  # only b's node went
    assert pc.peek(q_a) == 8  # a is still servable while pinned
    # release the pin, then reclaim can take it — blocks actually return
    pc.release(hit)
    assert pc.reclaim(8) == 2
    assert pool.stats()["blocks_free"] == pool.stats()["blocks_total"]

"""Gateway end-to-end over a real (tiny random-weight) model: JSON-schema
constrained decoding at temperature > 0 must yield schema-valid output over
plain HTTP, logprobs must surface as OpenAI ``logprobs.content`` entries,
``n=2`` must return two choices, and SSE streaming must concatenate to a
schema-valid document. A separate test drives the unmodified ``openai``
SDK against a 2-worker cluster (skipped when the SDK is not installed)."""

import asyncio
import json

import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.gateway import Gateway
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store import ModelStore
from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect

from conftest import async_test
from fakes import FakeRegistry
from test_gateway import _read_head, _read_response, _read_sse_events, _send
from test_serve_e2e import build_tiny_gguf

MODEL = "acme/tiny-e2e"

# integer/enum-only properties: the compiled language is length-bounded
# (~45 chars worst case), so max_tokens=80 can never truncate the document
# mid-stream — schema validity is guaranteed, not probabilistic
SCHEMA = {
    "type": "object",
    "properties": {
        "age": {"type": "integer"},
        "tag": {"enum": ["alpha", "beta"]},
    },
}
RESPONSE_FORMAT = {
    "type": "json_schema",
    "json_schema": {"name": "person", "schema": SCHEMA},
}


class RealModelGateway:
    """Embedded broker + one real-model worker + gateway on port 0."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path

    async def __aenter__(self):
        src = self.tmp_path / "tiny.gguf"
        build_tiny_gguf(src)
        store = ModelStore(self.tmp_path / "models")
        store.import_file(src, MODEL)
        self.broker = await EmbeddedBroker().start()
        self.worker = Worker(
            WorkerConfig(nats_url=self.broker.url),
            LocalRegistry(store, dtype="float32"),
        )
        await self.worker.start()
        self.nc = await connect(self.broker.url)
        self.gw = Gateway(self.nc, port=0, chat_timeout_s=50.0)
        await self.gw.start()
        return self

    async def __aexit__(self, *exc):
        await self.gw.stop()
        await self.nc.close()
        await self.worker.drain()
        await self.broker.stop()

    async def post_chat(self, body):
        reader, writer = await asyncio.open_connection("127.0.0.1", self.gw.port)
        try:
            await _send(writer, "POST", "/v1/chat/completions", body)
            return await _read_response(reader)
        finally:
            writer.close()


def chat_body(**kw):
    body = {
        "model": MODEL,
        "messages": [{"role": "user", "content": "give me a person"}],
        "max_tokens": 80,
    }
    body.update(kw)
    return body


@async_test
async def test_constrained_logprobs_and_n_over_http(tmp_path):
    jsonschema = pytest.importorskip("jsonschema")
    async with RealModelGateway(tmp_path) as h:
        # 1. json_schema constrained decode at temperature > 0: the sampled
        # document must parse and validate — the schema guarantees it
        status, _, resp = await h.post_chat(chat_body(
            temperature=0.9, seed=5, response_format=RESPONSE_FORMAT,
        ))
        assert status == 200, resp
        choice = resp["choices"][0]
        doc = json.loads(choice["message"]["content"])
        jsonschema.validate(doc, SCHEMA)
        assert choice["finish_reason"] == "stop"
        assert isinstance(doc["age"], int) and doc["tag"] in ("alpha", "beta")

        # 2. logprobs at temperature 0: one content entry per token, the
        # top alternative IS the greedy-chosen token
        status, _, resp = await h.post_chat(chat_body(
            max_tokens=5, temperature=0.0, logprobs=True, top_logprobs=3,
        ))
        assert status == 200, resp
        entries = resp["choices"][0]["logprobs"]["content"]
        assert len(entries) == 5
        for e in entries:
            assert isinstance(e["token"], str)
            assert e["logprob"] <= 0.0
            assert len(e["top_logprobs"]) == 3
            assert e["top_logprobs"][0]["token"] == e["token"]
            assert e["bytes"] == list(e["token"].encode())

        # 3. n=2: two indexed choices, summed usage
        status, _, resp = await h.post_chat(chat_body(
            max_tokens=6, temperature=0.8, seed=11, n=2,
        ))
        assert status == 200, resp
        assert [c["index"] for c in resp["choices"]] == [0, 1]
        for c in resp["choices"]:
            assert isinstance(c["message"]["content"], str)
        assert resp["usage"]["completion_tokens"] > 6  # both choices counted


@async_test
async def test_constrained_streaming_sse(tmp_path):
    jsonschema = pytest.importorskip("jsonschema")
    async with RealModelGateway(tmp_path) as h:
        reader, writer = await asyncio.open_connection("127.0.0.1", h.gw.port)
        try:
            await _send(writer, "POST", "/v1/chat/completions", chat_body(
                temperature=0.9, seed=3, stream=True,
                response_format=RESPONSE_FORMAT,
            ))
            status, headers = await _read_head(reader)
            assert status == 200
            assert headers["content-type"] == "text/event-stream"
            events = await _read_sse_events(reader)
        finally:
            writer.close()
        assert events[-1] == "[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        doc = json.loads(text)
        jsonschema.validate(doc, SCHEMA)
        assert chunks[-1]["choices"][0]["finish_reason"] == "stop"


@async_test
async def test_openai_sdk_against_two_worker_cluster():
    """Acceptance slice: an UNMODIFIED ``openai`` Python client completes a
    streaming chat against the gateway backed by a 2-worker cluster."""
    openai = pytest.importorskip("openai")
    broker = await EmbeddedBroker().start()
    workers = []
    for _ in range(2):
        w = Worker(
            WorkerConfig(nats_url=broker.url, cluster_advert_interval_s=0.05),
            FakeRegistry(),
        )
        await w.start()
        workers.append(w)
    nc = await connect(broker.url)
    gw = Gateway(nc, port=0,
                 retry=RetryPolicy(max_attempts=3, retry_on_timeout=True))
    await gw.start()
    try:
        client = openai.AsyncOpenAI(
            base_url=f"http://127.0.0.1:{gw.port}/v1", api_key="unused"
        )
        # streaming
        stream = await client.chat.completions.create(
            model="fake-echo-1",
            messages=[{"role": "user", "content": "hello world"}],
            stream=True,
        )
        parts, finish = [], None
        async for chunk in stream:
            parts.append(chunk.choices[0].delta.content or "")
            finish = chunk.choices[0].finish_reason or finish
        assert "".join(parts) == "echo: hello world "
        assert finish == "stop"
        # non-streaming
        resp = await client.chat.completions.create(
            model="fake-echo-1",
            messages=[{"role": "user", "content": "hello world"}],
        )
        assert resp.choices[0].message.content == "echo: hello world"
        # model listing
        models = await client.models.list()
        assert [m.id for m in models.data] == ["fake-echo-1"]
        await client.close()
    finally:
        await gw.stop()
        await nc.close()
        for w in workers:
            await w.drain()
        await broker.stop()

"""Multi-axis serving mesh (dp replicas / ep experts / sp ring prefill).

The named mesh generalization must not change the math: greedy decode
through the LIVE batcher path on the 8 forced host devices (conftest.py)
stays token-identical when the mesh gains a dp axis (independent batcher
replicas) or an sp axis (ring-attention prefill for long prompts), and a
routed-MoE model served over an ep axis matches its unsharded serving
output. Also pins the compact MESH_SHAPE grammar, dp-submesh construction,
dp/ep HBM accounting (dp = replication, never a divisor), advert capacity,
and the router's slot-normalized + sp-aware ranking.
"""

import asyncio

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.parallel import build_mesh, dp_submeshes, parse_mesh_spec, serving_mesh
from nats_llm_studio_tpu.parallel.memory import estimate_device_bytes
from nats_llm_studio_tpu.parallel.sharding import shard_params, validate_mesh_for_config
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.serve.dp import DataParallelBatcher, batcher_replicas
from nats_llm_studio_tpu.serve.router import ClusterRouter

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _batcher(params, cfg, mesh=None, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("buckets", [8, 64])
    return ContinuousBatcher(params, cfg, mesh=mesh, **kw)


async def _greedy(b, prompts, n=6):
    async def one(p):
        sp = SamplingParams(temperature=0.0, max_tokens=n)
        return [t async for t in b.submit(p, sp)]

    return await asyncio.gather(*[one(p) for p in prompts])


# -- compact named-axis grammar ----------------------------------------------


def test_compact_grammar_parses_like_explicit():
    assert parse_mesh_spec("dp2,ep2,tp2") == {"dp": 2, "ep": 2, "tp": 2}
    assert parse_mesh_spec("dp2,ep2,tp2") == parse_mesh_spec("dp=2,ep=2,tp=2")
    # mixed spellings and axis-order normalization (dp, pp, ep, sp, tp)
    assert list(parse_mesh_spec("tp4,dp=2")) == ["dp", "tp"]
    assert parse_mesh_spec("sp2") == {"sp": 2}


def test_compact_grammar_rejects_junk():
    with pytest.raises(ValueError, match="unknown mesh axis"):
        parse_mesh_spec("xx2")
    with pytest.raises(ValueError):
        parse_mesh_spec("dp")  # no factor
    with pytest.raises(ValueError, match="must be positive"):
        parse_mesh_spec("tp0")


def test_serving_mesh_off_spellings():
    for s in ("off", "none", "0", "1", "tp=1", "tp1"):
        assert serving_mesh(s, devices=jax.devices()) is None


def test_dp_submeshes_disjoint_slices():
    mesh = build_mesh("dp=2,tp=2", devices=jax.devices()[:4])
    subs = dp_submeshes(mesh)
    assert len(subs) == 2
    seen = set()
    for s in subs:
        assert dict(s.shape) == {"tp": 2}
        ids = {d.id for d in s.devices.flat}
        assert not ids & seen  # disjoint device slices
        seen |= ids
    # no dp axis -> unchanged; None -> [None]
    tp = build_mesh("tp=2", devices=jax.devices()[:2])
    assert dp_submeshes(tp) == [tp]
    assert dp_submeshes(None) == [None]


def test_validate_error_names_full_factoring():
    mesh = build_mesh("dp=2,ep=2,tp=2", devices=jax.devices()[:8])
    dense = ModelConfig.tiny(n_layers=2)  # no experts: the ep axis is dead
    with pytest.raises(ValueError, match="unservable on this mesh") as e:
        validate_mesh_for_config(mesh, dense)
    # the message names the FULL factoring, not just the failing axis
    assert "dp=2" in str(e.value) and "ep=2" in str(e.value) and "tp=2" in str(e.value)


# -- HBM accounting: dp replicates, ep shards experts ------------------------


def test_estimate_dp_is_replication_not_division():
    cfg = ModelConfig.tiny(n_layers=2)
    with_dp = estimate_device_bytes(cfg, {"dp": 2, "tp": 2}, batch=4)
    without = estimate_device_bytes(cfg, {"tp": 2}, batch=4)
    # per-CHIP bytes: each dp replica owns a disjoint slice holding its own
    # full weights-and-cache footprint, so dp must not divide anything
    assert with_dp == without


def test_estimate_pins_per_chip_bytes_at_dp2_ep2_tp2():
    cfg = ModelConfig.tiny(n_experts=8, n_experts_used=2, d_ff=32, n_layers=2)
    est = estimate_device_bytes(cfg, {"dp": 2, "ep": 2, "tp": 2}, batch=4)
    L, E, d, ff, V = 2, 8, 64, 32, 512
    hq, hkv, hd, by = 4, 2, 16, 4  # float32
    tp, ep = 2, 2
    want_params = (
        V * d * by  # embed (replicated)
        + d * by  # out_norm
        + d * V * by // tp  # lm_head
        + 2 * L * d * by  # attn_norm + ffn_norm
        + L * d * hq * hd * by // tp  # wq
        + 2 * L * d * hkv * hd * by // tp  # wk + wv (2 kv heads divide tp=2)
        + L * hq * hd * d * by // tp  # wo
        + L * d * E * by  # router (replicated)
        + 3 * L * E * d * ff * by // (ep * tp)  # expert stacks on ep x tp
    )
    assert est["params"] == want_params
    # KV cache: batch stays whole per replica; only the kv-head tp split
    assert est["kv_cache"] == 2 * L * 4 * cfg.max_seq_len * hkv * hd * by // tp
    assert est == estimate_device_bytes(cfg, {"ep": 2, "tp": 2}, batch=4)


# -- dp: replica facade, routing, and bit-identical serving ------------------


@async_test
async def test_dp2_greedy_matches_single_batcher(model):
    """dp=2,tp=2 on 4 host devices: two replica batchers behind the facade
    must reproduce the unsharded single-batcher greedy tokens exactly, and
    a concurrent wave must actually land on BOTH replicas."""
    cfg, params = model
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30, 40, 50]]
    ref = _batcher(params, cfg)
    try:
        want = await _greedy(ref, prompts)
    finally:
        ref.stop()

    mesh = build_mesh("dp=2,tp=2", devices=jax.devices()[:4])
    subs = dp_submeshes(mesh)
    reps = [_batcher(shard_params(params, s, cfg), cfg, mesh=s) for s in subs]
    dpb = DataParallelBatcher(reps)
    try:
        got = await _greedy(dpb, prompts)
        assert got == want
        served = [r.stats.requests for r in dpb.replicas]
        assert all(n >= 1 for n in served), served  # the wave distributed
        assert sum(served) == len(prompts)
    finally:
        dpb.stop()


def test_dp_facade_aggregates(model):
    cfg, params = model
    mesh = build_mesh("dp=2", devices=jax.devices()[:2])
    subs = dp_submeshes(mesh)
    assert all(dict(s.shape) == {"tp": 1} for s in subs)
    reps = [_batcher(shard_params(params, s, cfg), cfg, mesh=None) for s in subs]
    dpb = DataParallelBatcher(reps)
    try:
        assert dpb.max_slots == sum(r.max_slots for r in reps)  # multiplied capacity
        assert dpb.max_seq == reps[0].max_seq
        assert dpb.queue_depth == 0
        assert dpb.brownout_level == 0
        assert batcher_replicas(dpb) == reps
        assert batcher_replicas(reps[0]) == [reps[0]]
        snap = dpb.debug_snapshot()
        assert snap["dp"] == 2 and len(snap["replicas"]) == 2
    finally:
        dpb.stop()


# -- sp: ring-attention prefill in the live serving path ---------------------


@async_test
async def test_sp2_ring_prefill_greedy_matches_dense(model, monkeypatch):
    """With RING_PREFILL_MIN_TOKENS lowered to the admit bucket width, every
    fresh prefill on an sp=2 mesh runs the ppermute ring — greedy output
    must match the mesh-None dense path token for token."""
    cfg, params = model
    prompts = [[(i * 11 + 2) % cfg.vocab_size for i in range(12)],
               [(i * 5 + 1) % cfg.vocab_size for i in range(20)]]
    ref = _batcher(params, cfg)
    try:
        want = await _greedy(ref, prompts)
    finally:
        ref.stop()

    monkeypatch.setenv("RING_PREFILL_MIN_TOKENS", "8")
    mesh = build_mesh("sp=2", devices=jax.devices()[:2])
    b = _batcher(shard_params(params, mesh, cfg), cfg, mesh=mesh)
    try:
        got = await _greedy(b, prompts)
        assert got == want
        # the ring-family tag landed in the program metrics: proof the
        # dispatches actually took the sp path, not the dense fallback
        names = set(b.stats.program_histograms())
        assert any(n.endswith("_ring") for n in names), sorted(names)
    finally:
        b.stop()


@async_test
async def test_sp2_below_threshold_keeps_dense_lane(model, monkeypatch):
    """Prompts under RING_PREFILL_MIN_TOKENS must NOT ring even on an sp
    mesh — short prefills keep the single-chip lane."""
    cfg, params = model
    monkeypatch.setenv("RING_PREFILL_MIN_TOKENS", "4096")
    mesh = build_mesh("sp=2", devices=jax.devices()[:2])
    b = _batcher(shard_params(params, mesh, cfg), cfg, mesh=mesh)
    try:
        got = await _greedy(b, [[1, 2, 3]])
        assert len(got[0]) == 6
        assert not any(n.endswith("_ring") for n in b.stats.program_histograms())
    finally:
        b.stop()


# -- ep: routed MoE through the live serving FFN -----------------------------


@async_test
async def test_moe_ep2_serving_matches_unsharded(model):
    """A routed-MoE model served over an ep=2 mesh: same greedy tokens as
    the unsharded routed path (generous capacity factor — no drops), and
    the forward programs carry the _moe family tag."""
    cfg = ModelConfig.tiny(n_layers=2, n_experts=8, n_experts_used=2,
                           d_ff=32, max_seq_len=128,
                           moe_capacity_factor=8.0, use_routed_moe=True)
    params = init_params(cfg, jax.random.PRNGKey(7))
    prompts = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 8]]
    ref = _batcher(params, cfg)
    try:
        want = await _greedy(ref, prompts)
        assert any(n.endswith("_moe") for n in ref.stats.program_histograms())
    finally:
        ref.stop()

    mesh = build_mesh("ep=2", devices=jax.devices()[:2])
    validate_mesh_for_config(mesh, cfg)
    b = _batcher(shard_params(params, mesh, cfg), cfg, mesh=mesh)
    try:
        got = await _greedy(b, prompts)
        # capacity-no-drop tolerance: with capacity_factor=8 routing drops
        # nothing, so serving output is the same token stream
        assert got == want
    finally:
        b.stop()


# -- adverts + router: multiplied capacity, sp preference --------------------


def test_advert_carries_slots_and_mesh():
    from types import SimpleNamespace

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.serve.worker import Worker

    class Reg:
        mesh = build_mesh("dp=2,tp=2", devices=jax.devices()[:4])

        def loaded_engines(self):
            mk = lambda: SimpleNamespace(
                batcher=SimpleNamespace(queue_depth=3, max_slots=8,
                                        brownout_level=0))
            return {"m1": mk(), "m2": mk()}

    w = Worker(WorkerConfig(), Reg())
    adv = w.build_advert()
    assert adv["slots"] == 16  # dp-multiplied capacity, summed over engines
    assert adv["queue_depth"] == 6
    assert adv["mesh"] == {"dp": 2, "tp": 2}


def test_router_normalizes_depth_by_slots():
    r = ClusterRouter(None, stale_after_s=5.0)
    # w-big has MORE queued but MORE capacity: 4/16 < 2/4
    r.ingest({"worker_id": "w-big", "queue_depth": 4, "slots": 16, "models": ["m"]})
    r.ingest({"worker_id": "w-small", "queue_depth": 2, "slots": 4, "models": ["m"]})
    assert r.pick(model="m") == "w-big"
    # without slots info the raw depth still decides (legacy adverts)
    r2 = ClusterRouter(None, stale_after_s=5.0)
    r2.ingest({"worker_id": "w-a", "queue_depth": 4, "models": ["m"]})
    r2.ingest({"worker_id": "w-b", "queue_depth": 2, "models": ["m"]})
    assert r2.pick(model="m") == "w-b"


def test_router_prefers_sp_worker_for_long_prompts(monkeypatch):
    monkeypatch.setenv("RING_PREFILL_MIN_TOKENS", "64")
    r = ClusterRouter(None, stale_after_s=5.0)
    r.ingest({"worker_id": "w-dense", "queue_depth": 0, "models": ["m"],
              "mesh": {"tp": 4}})
    r.ingest({"worker_id": "w-ring", "queue_depth": 1, "models": ["m"],
              "mesh": {"sp": 2, "tp": 2}})
    long_msgs = [{"role": "user", "content": "x" * (4 * 64)}]
    short_msgs = [{"role": "user", "content": "hi"}]
    # long prompt: the sp-capable worker wins despite deeper queue
    assert r.pick(model="m", messages=long_msgs) == "w-ring"
    # short prompt: plain load order (idle dense worker wins)
    assert r.pick(model="m", messages=short_msgs) == "w-dense"

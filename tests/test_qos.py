"""Multi-tenant QoS (PR 20 tentpole): API keys, weighted fair share,
priority-class brownout, and preempt-to-host-tier.

Four layers of pinning:

* serve/qos.py units — ``API_KEYS`` spec parsing (malformed entries fail
  the boot, not silently admit), token-bucket rate limiting, monthly
  usage accounting, priority-header wire round-trips, DRR weighted-share
  convergence (single tenant == exact FIFO backcompat), and the top-K +
  ``other`` cardinality cap.
* Batcher policy — brownout sheds strictly by class (batch < standard <
  premium, cause-tagged ``brownout``), a premium admit on a full pool
  preempts the lowest-class victim to the host tier and the victim
  resumes bit-identically, and tenant-less submits keep the exact
  pre-QoS anonymous/standard behavior.
* Gateway front door — 401 for missing/invalid keys, typed 429s with
  ``Retry-After`` for rate and monthly-token quota, resolved tenant/
  class stamped onto the bus headers (never the client's claim), and
  the no-API_KEYS deployment serving unauthenticated exactly as before.
* Exposition — per-tenant families on the worker renderer, the gateway
  edge counters, and the aggregator's post-merge cardinality cap
  (disjoint per-worker top-Ks must not union past K cluster-wide).
"""

import asyncio
import time

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.gateway.server import _envelope_error_response
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.obs import PromRenderer
from nats_llm_studio_tpu.obs.aggregator import merge_into
from nats_llm_studio_tpu.serve.batcher import BatcherOverloaded, ContinuousBatcher
from nats_llm_studio_tpu.serve.brownout import BROWNOUT, BrownoutConfig, SHED_ONLY
from nats_llm_studio_tpu.serve.qos import (
    ANON_TENANT,
    DEFAULT_PRIORITY,
    DrrScheduler,
    TenantStats,
    TenantUsage,
    TokenBucket,
    cap_tenant_rows,
    class_rank,
    class_weight,
    format_priority_header,
    parse_api_keys,
    parse_priority_header,
)
from nats_llm_studio_tpu.transport.envelope import (
    error_is_retryable,
    shed_cause,
    shed_cause_of,
)

from conftest import async_test
from fakes import EchoEngine, FakeRegistry
from test_gateway import CHAT, GatewayHarness


# -- API_KEYS spec parsing ---------------------------------------------------


def test_parse_api_keys_full_and_defaults():
    keys = parse_api_keys(
        "sk-a:acme:premium:2.5:10:1000000, sk-b:hobby:batch, sk-c:corp"
    )
    a = keys["sk-a"]
    assert (a.tenant, a.priority, a.weight, a.rps, a.monthly_tokens) == (
        "acme", "premium", 2.5, 10.0, 1000000)
    b = keys["sk-b"]
    assert (b.tenant, b.priority, b.weight, b.rps, b.monthly_tokens) == (
        "hobby", "batch", 0.0, 0.0, 0)
    # class defaults to standard; whitespace around entries tolerated
    assert keys["sk-c"].priority == DEFAULT_PRIORITY
    assert parse_api_keys("") == {} and parse_api_keys(None) == {}


@pytest.mark.parametrize("spec,msg", [
    ("sk-a", "key:tenant:class"),                       # no tenant
    (":acme", "key:tenant:class"),                      # empty key
    ("sk-a:acme:platinum", "platinum"),                 # unknown class
    ("sk-a:acme:premium:heavy", "numeric"),             # non-numeric weight
    ("sk-a:acme,sk-a:beta", "duplicate"),               # duplicate key
])
def test_parse_api_keys_rejects_malformed(spec, msg):
    # a half-configured auth table must fail the gateway at boot, not
    # silently admit everyone
    with pytest.raises(ValueError, match=msg):
        parse_api_keys(spec)


def test_priority_classes_rank_and_weight():
    assert class_rank("batch") < class_rank("standard") < class_rank("premium")
    assert class_weight("batch") < class_weight("standard") < class_weight("premium")
    # unknown claims clamp to standard, never premium (headers are
    # attacker-ish input on the raw-NATS path)
    assert class_rank("root") == class_rank(DEFAULT_PRIORITY)
    assert class_weight("") == class_weight(DEFAULT_PRIORITY)


def test_priority_header_roundtrip():
    assert format_priority_header("premium", 2.5) == "premium:2.5"
    assert parse_priority_header("premium:2.5") == ("premium", 2.5)
    # weight 0 = derive from class: no suffix on the wire
    assert format_priority_header("standard") == "standard"
    assert parse_priority_header("standard") == ("standard", 0.0)
    # garbage tolerated: unknown class -> standard, bad weight -> 0
    assert parse_priority_header(None) == (DEFAULT_PRIORITY, 0.0)
    assert parse_priority_header("platinum:lots") == (DEFAULT_PRIORITY, 0.0)
    assert parse_priority_header("premium:-4") == ("premium", 0.0)


# -- rate limiting + usage accounting ----------------------------------------


def test_token_bucket_burst_and_retry_after():
    tb = TokenBucket(5.0)  # burst = 2 s of rate = 10
    assert all(tb.take() for _ in range(10))
    assert not tb.take()
    assert tb.retry_after_s() > 0.0
    # zero-rate bucket admits everything (rps unset in the key spec)
    free = TokenBucket(0.0)
    assert all(free.take() for _ in range(100))
    assert free.retry_after_s() == 0.0


def test_tenant_usage_quota_and_month_roll():
    u = TenantUsage()
    assert u.charge("acme", 7) == 7
    assert u.charge("acme", 3) == 10
    assert u.tokens_used("acme") == 10 and u.tokens_used("hobby") == 0
    assert u.over_quota("acme", 10) and not u.over_quota("acme", 11)
    assert not u.over_quota("acme", 0)  # 0 = unlimited
    snap = u.snapshot()
    assert snap["acme"] == {"tokens": 10, "requests": 2}
    # crossing the month boundary resets every counter
    u._month = "1999-01"
    assert u.tokens_used("acme") == 0
    assert u.snapshot() == {}


def test_cap_tenant_rows_scalar_and_dict():
    rows = {f"t{i}": i + 1 for i in range(6)}  # t5 biggest
    capped = cap_tenant_rows(rows, 2)
    assert capped == {"t5": 6, "t4": 5, "other": 1 + 2 + 3 + 4}
    # dict-valued rows rank by total and merge key-wise into ``other``
    drows = {"a": {"served": 9, "shed": 1},
             "b": {"served": 2, "shed": 0},
             "c": {"served": 1, "shed": 5}}
    dcap = cap_tenant_rows(drows, 1)
    assert dcap == {"a": {"served": 9, "shed": 1},
                    "other": {"served": 3, "shed": 5}}
    # disabled / under-K: pass-through
    assert cap_tenant_rows(rows, 0) == rows
    assert cap_tenant_rows(rows, 10) == rows


# -- DRR weighted fair share -------------------------------------------------


def _drr_items(n_per_tenant, cost=256):
    # interleaved arrival: b0, s0, p0, b1, s1, p1, ...
    out = []
    for i in range(n_per_tenant):
        for t in ("hobby", "corp", "acme"):
            out.append((t, cost, i))
    return out


_DRR_WEIGHT = {"hobby": 1.0, "corp": 4.0, "acme": 16.0}


def test_drr_weighted_share_convergence():
    drr = DrrScheduler(quantum=256)
    items = _drr_items(20)
    out = drr.order(items, tenant_of=lambda it: it[0],
                    cost_of=lambda it: it[1],
                    weight_of=lambda it: _DRR_WEIGHT[it[0]])
    assert sorted(map(id, out)) == sorted(map(id, items))  # a permutation
    # the first visit round serves items proportional to weight: 1 hobby,
    # 4 corp, 16 acme of the first 21 served
    head = out[:21]
    counts = {t: sum(1 for it in head if it[0] == t)
              for t in ("hobby", "corp", "acme")}
    assert counts == {"hobby": 1, "corp": 4, "acme": 16}, counts
    # FIFO within each tenant is preserved
    for t in ("hobby", "corp", "acme"):
        seqs = [it[2] for it in out if it[0] == t]
        assert seqs == sorted(seqs)


def test_drr_single_tenant_exact_fifo():
    drr = DrrScheduler(quantum=1)  # tiny quantum must not matter
    items = [("only", 999, i) for i in range(10)]
    assert drr.order(items, tenant_of=lambda it: it[0],
                     cost_of=lambda it: it[1],
                     weight_of=lambda it: 1.0) == items


def test_drr_deficit_resets_when_queue_empties():
    drr = DrrScheduler(quantum=256)
    items = [("a", 256, 0), ("b", 256, 0)]
    drr.order(items, tenant_of=lambda it: it[0],
              cost_of=lambda it: it[1], weight_of=lambda it: 16.0)
    # both queues drained inside the round: no banked credit while idle
    assert drr._deficit.get("a", 0.0) == 0.0
    assert drr._deficit.get("b", 0.0) == 0.0
    drr.forget("a")  # idempotent on absent tenants
    drr.forget("never-seen")


# -- shed-cause envelope markers ---------------------------------------------


def test_shed_cause_token_roundtrip():
    msg = f"displaced by weighted fair share ({shed_cause('fair_share')}); retry"
    assert shed_cause_of(msg) == "fair_share"
    assert error_is_retryable(msg)  # the token alone marks it retryable
    assert shed_cause_of({"error": "queue full (shed_cause=depth)"}) == "depth"
    # absent or unrecognized causes read as generic overload (old workers)
    assert shed_cause_of("overloaded: retry on another worker") is None
    assert shed_cause_of("boom (shed_cause=gremlins)") is None
    assert shed_cause_of(None) is None


def test_gateway_envelope_error_mapping():
    # quota / fair_share sheds are the client's fault -> typed 429 with
    # Retry-After; infrastructure sheds stay 503
    status, body, extra = _envelope_error_response(
        "monthly quota exhausted (shed_cause=quota)")
    assert status == 429 and body["error"]["type"] == "rate_limit_error"
    assert body["error"]["cause"] == "quota"
    assert extra == {"Retry-After": "1"}
    status, body, extra = _envelope_error_response(
        "displaced by weighted fair share (shed_cause=fair_share); retry")
    assert status == 429 and body["error"]["cause"] == "fair_share"
    status, body, extra = _envelope_error_response(
        "brownout: batch class shed first (shed_cause=brownout); retry "
        "on another worker")
    assert status == 503 and body["error"]["cause"] == "brownout"
    assert extra == {"Retry-After": "1"}


def test_tenant_stats_rollup():
    ts = TenantStats()
    for i in range(4):
        ts.record_request(f"t{i}")
    ts.record_served("t0", tokens=8, queue_age_ms=2.0)
    ts.record_shed("t1")
    ts.record_preempted("t2")
    snap = ts.snapshot()
    assert snap["t0"]["served"] == 1 and snap["t0"]["tokens"] == 8
    assert snap["t1"]["shed"] == 1 and snap["t2"]["preempted"] == 1
    capped = ts.snapshot(top_k=2)
    assert "other" in capped and len(capped) == 3


# -- batcher policy: brownout by class, preemption, anonymous backcompat -----


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=128)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompt(n, mul=7, add=3, vocab=509):
    return [(i * mul + add) % vocab for i in range(n)]


_QOS_KW = dict(max_slots=2, max_seq_len=64, buckets=[8, 64],
               prefill_chunk=32, kv_block_tokens=32, kv_pool_blocks=3,
               decode_burst=1, admit_coalesce_ms=0.0, paged=True,
               qos_preempt=True)


@async_test
async def test_brownout_sheds_batch_before_standard(model):
    """BROWNOUT is the lowest class still admitted: batch bounces with the
    cause-tagged retryable shed while standard and premium serve."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], max_queue=8,
                          brownout=BrownoutConfig())
    try:
        b.brownout.level = BROWNOUT
        sp = SamplingParams(temperature=0.0, max_tokens=2)
        with pytest.raises(BatcherOverloaded) as ei:
            async for _ in b.submit([1, 2], sp, tenant="hobby",
                                    priority="batch"):
                pass
        assert shed_cause_of(str(ei.value)) == "brownout"
        assert error_is_retryable(str(ei.value))
        out = [t async for t in b.submit([1, 2], sp, tenant="corp",
                                         priority="standard")]
        assert len(out) == 2
        out = [t async for t in b.submit([1, 2], sp, tenant="acme",
                                         priority="premium")]
        assert len(out) == 2
        snap = b.tenant_stats.snapshot()
        assert snap["hobby"]["shed"] == 1 and snap["hobby"]["served"] == 0
        assert snap["corp"]["served"] == 1 and snap["acme"]["served"] == 1
    finally:
        b.stop()


@async_test
async def test_shed_only_spares_premium(model):
    """At SHED_ONLY standard bounces too (the pre-QoS default-class
    behavior), but premium still rides through the gate."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], max_queue=8,
                          brownout=BrownoutConfig())
    try:
        b.brownout.level = SHED_ONLY
        sp = SamplingParams(temperature=0.0, max_tokens=2)
        with pytest.raises(BatcherOverloaded) as ei:
            async for _ in b.submit([1, 2], sp):  # anonymous -> standard
                pass
        assert "brownout shed-only" in str(ei.value)
        assert shed_cause_of(str(ei.value)) == "brownout"
        b.brownout.level = SHED_ONLY  # re-force (serving may have ticked it)
        out = [t async for t in b.submit([1, 2], sp, tenant="acme",
                                         priority="premium")]
        assert len(out) == 2
    finally:
        b.stop()


async def _pressure_pair(b, pa, pb, na, nb, qa, qb):
    """A (tenant/priority ``qa``) decodes first; once 2 of A's tokens
    arrived, B (``qb``) submits — whose admit exhausts the 3-block pool.
    Returns (a_tokens, b_tokens)."""
    spa = SamplingParams(temperature=0.0, max_tokens=na)
    spb = SamplingParams(temperature=0.0, max_tokens=nb)
    started = asyncio.get_running_loop().create_future()

    async def run_a():
        out = []
        async for t in b.submit(pa, spa, tenant=qa[0], priority=qa[1]):
            out.append(t)
            if len(out) == 2 and not started.done():
                started.set_result(None)
        return out

    async def run_b():
        return [t async for t in b.submit(pb, spb, tenant=qb[0],
                                          priority=qb[1])]

    ta = asyncio.ensure_future(run_a())
    await started
    tb = asyncio.ensure_future(run_b())
    return await ta, await tb


@async_test
async def test_premium_preempts_batch_bit_identical(model):
    """A premium admit on a full pool preempts the batch slot to the host
    tier (reason ``preempted``, counted per tenant) instead of shedding
    anyone; the victim resumes and finishes bit-identically with the
    ample-pool greedy sequence."""
    cfg, params = model
    pa, pb = _prompt(33), _prompt(40, mul=11, add=5)
    ample = ContinuousBatcher(params, cfg, **{**_QOS_KW,
                                              "kv_pool_blocks": 0})
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=12)
        want_a = [t async for t in ample.submit(pa, sp)]
        spb = SamplingParams(temperature=0.0, max_tokens=8)
        want_b = [t async for t in ample.submit(pb, spb)]
    finally:
        ample.stop()
    b = ContinuousBatcher(params, cfg, **_QOS_KW)
    try:
        got_a, got_b = await _pressure_pair(
            b, pa, pb, 12, 8, ("hobby", "batch"), ("acme", "premium"))
        assert got_a == want_a, "preempted slot did not resume bit-identically"
        assert got_b == want_b
        assert b._suspend_stats["suspended_total"] >= 1
        assert b._suspend_stats["resumed_total"] >= 1
        snap = b.tenant_stats.snapshot()
        # the victim was parked, not shed — preemption is its own counter
        assert snap["hobby"]["preempted"] >= 1
        assert snap["hobby"]["shed"] == 0 and snap["acme"]["shed"] == 0
        assert snap["hobby"]["served"] == 1 and snap["acme"]["served"] == 1
        assert b.stats.shed_cause_counts().get("kv_pool", 0) == 0
    finally:
        b.stop()


@async_test
async def test_tenantless_submit_is_anonymous_standard(model):
    """The raw-NATS backcompat contract at the batcher seam: a submit
    without tenant/priority serves exactly as before under the anonymous
    standard identity."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=3)
        out = [t async for t in b.submit([5, 6, 7], sp)]
        assert len(out) == 3
        snap = b.tenant_stats.snapshot()
        assert set(snap) == {ANON_TENANT}
        assert snap[ANON_TENANT]["requests"] == 1
        assert snap[ANON_TENANT]["served"] == 1
        assert snap[ANON_TENANT]["tokens"] == 3
    finally:
        b.stop()


@async_test
async def test_worker_renders_per_tenant_families(model):
    """The worker exposition carries the lmstudio_tenant_* families under
    the capped ``tenant`` label for every loaded engine."""
    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.serve.worker import Worker

    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=2)
        out = [t async for t in b.submit([1, 2], sp, tenant="acme",
                                         priority="premium")]
        assert len(out) == 2

        class _Eng:
            batcher = b

        class _Reg:
            def stats(self):
                return {}

            def loaded_engines(self):
                return {"acme/q": _Eng()}

        w = Worker(WorkerConfig(), _Reg())
        wid = w.worker_id
        text = w.render_prometheus()
        assert (f'\nlmstudio_tenant_requests_total'
                f'{{model="acme/q",tenant="acme",worker_id="{wid}"}} 1\n') in text
        assert (f'\nlmstudio_tenant_served_total'
                f'{{model="acme/q",tenant="acme",worker_id="{wid}"}} 1\n') in text
        assert (f'\nlmstudio_tenant_tokens_total'
                f'{{model="acme/q",tenant="acme",worker_id="{wid}"}} 2\n') in text
        assert (f'\nlmstudio_tenant_shed_total'
                f'{{model="acme/q",tenant="acme",worker_id="{wid}"}} 0\n') in text
        assert (f'\nlmstudio_tenant_preempted_total'
                f'{{model="acme/q",tenant="acme",worker_id="{wid}"}} 0\n') in text
        assert (f'lmstudio_tenant_queue_age_ms_total'
                f'{{model="acme/q",tenant="acme"') in text
    finally:
        b.stop()


# -- aggregator: post-merge tenant cardinality cap ---------------------------


def test_aggregator_caps_tenant_cardinality_after_merge():
    """Disjoint per-worker top-Ks union past K cluster-wide: the merge
    re-applies the cap so the cluster view stays at top-K + ``other``."""
    texts = []
    for w, base in (("w1", 0), ("w2", 6)):
        r = PromRenderer(default_labels={"worker_id": w})
        for i in range(6):
            r.counter("lmstudio_tenant_served_total", i + 1,
                      labels={"model": "m", "tenant": f"t{base + i}"})
        texts.append(r.render())
    out = PromRenderer()
    merge_into(out, texts, tenant_topk=3)
    text = out.render()
    # 12 distinct tenants in -> 3 named + "other" out, totals preserved
    assert text.count('tenant="') == 4
    assert 'lmstudio_tenant_served_total{model="m",tenant="other"} 25' in text
    # under the cap nothing rolls up
    out2 = PromRenderer()
    merge_into(out2, texts, tenant_topk=16)
    text2 = out2.render()
    assert text2.count('tenant="') == 12 and 'tenant="other"' not in text2


# -- gateway front door: auth, rate, quota, header stamping ------------------


class RecordingEngine(EchoEngine):
    """Echo engine that records every chat payload the worker hands it,
    so tests can see what crossed the bus (tenant/priority stamping)."""

    def __init__(self, model_id):
        super().__init__(model_id)
        self.payloads = []

    async def chat(self, payload):
        self.payloads.append(dict(payload))
        return await super().chat(payload)


class RecordingRegistry(FakeRegistry):
    def __init__(self):
        super().__init__()
        self.engine = RecordingEngine("fake-echo-1")
        self.engines = {"fake-echo-1": self.engine}


@async_test
async def test_gateway_requires_key_when_configured():
    async with GatewayHarness(api_keys="sk-a:acme:premium:2.5") as h:
        status, _, body = await h.request("POST", "/v1/chat/completions", CHAT)
        assert status == 401
        assert body["error"]["type"] == "authentication_error"
        assert body["error"]["code"] == "invalid_api_key"
        status, _, body = await h.request(
            "POST", "/v1/chat/completions", CHAT,
            headers={"Authorization": "Bearer sk-wrong"})
        assert status == 401 and body["error"]["code"] == "invalid_api_key"
        # /v1/models is gated on key validity too (no rate tokens spent)
        status, _, _ = await h.request("GET", "/v1/models")
        assert status == 401
        status, _, _ = await h.request(
            "GET", "/v1/models", headers={"Authorization": "Bearer sk-a"})
        assert status == 200
        # refusals show under the rejected family as tenant="unknown"
        text = h.gw.render_prometheus()
        assert 'lmstudio_gateway_tenant_rejected_total' in text
        assert 'tenant="unknown"' in text


@async_test
async def test_gateway_stamps_resolved_tenant_onto_bus():
    """The worker sees the tenant/class the KEY resolves to — never a
    client-claimed header — and the reply charges the tenant's usage."""
    reg = RecordingRegistry()
    async with GatewayHarness(registries=[reg],
                              api_keys="sk-a:acme:premium:2.5") as h:
        status, _, body = await h.request(
            "POST", "/v1/chat/completions", CHAT,
            headers={"Authorization": "Bearer sk-a",
                     # spoof attempts must be ignored in favor of the key
                     "X-Tenant": "victim", "X-Priority": "batch"})
        assert status == 200
        assert body["choices"][0]["message"]["content"].startswith("echo:")
        p = reg.engine.payloads[-1]
        assert p["_tenant"] == "acme"
        assert p["_priority"] == "premium:2.5"
        text = h.gw.render_prometheus()
        assert 'lmstudio_gateway_tenant_requests_total{' in text
        assert 'tenant="acme"' in text
        # completion usage booked against the tenant's month
        assert h.gw._usage.tokens_used("acme") == body["usage"]["completion_tokens"]


@async_test
async def test_gateway_rate_limit_429_with_retry_after():
    # rps=0.5 -> burst 1: the second request inside the window must 429
    async with GatewayHarness(api_keys="sk-r:acme:standard:0:0.5") as h:
        hdr = {"Authorization": "Bearer sk-r"}
        status, _, _ = await h.request("POST", "/v1/chat/completions", CHAT,
                                       headers=hdr)
        assert status == 200
        status, headers, body = await h.request(
            "POST", "/v1/chat/completions", CHAT, headers=hdr)
        assert status == 429
        assert body["error"]["code"] == "rate_limit_exceeded"
        assert body["error"]["cause"] == "quota"
        assert int(headers["retry-after"]) >= 1


@async_test
async def test_gateway_monthly_quota_429():
    # quota of 1 completion token: the first echo reply (3 words) burns it
    async with GatewayHarness(api_keys="sk-q:acme:standard:0:0:1") as h:
        hdr = {"Authorization": "Bearer sk-q"}
        status, _, _ = await h.request("POST", "/v1/chat/completions", CHAT,
                                       headers=hdr)
        assert status == 200
        status, headers, body = await h.request(
            "POST", "/v1/chat/completions", CHAT, headers=hdr)
        assert status == 429
        assert body["error"]["code"] == "insufficient_quota"
        assert body["error"]["cause"] == "quota"
        assert headers["retry-after"] == "3600"


@async_test
async def test_gateway_without_keys_serves_unauthenticated():
    """No API_KEYS configured == the pre-QoS deployment: every caller is
    the anonymous standard tenant, nothing is stamped on the bus."""
    reg = RecordingRegistry()
    async with GatewayHarness(registries=[reg]) as h:
        status, _, body = await h.request("POST", "/v1/chat/completions", CHAT)
        assert status == 200
        assert body["choices"][0]["message"]["content"].startswith("echo:")
        p = reg.engine.payloads[-1]
        assert "_tenant" not in p and "_priority" not in p

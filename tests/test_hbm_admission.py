"""Device-memory admission on the serving load path (VERDICT r4 missing #3).

A load that would blow the per-device HBM budget must be refused with an
honest error (or make room by evicting an IDLE engine) before touching the
device — never OOM mid-serving and take live dispatches with it. The
reference delegates this to LM Studio's loader
(/root/reference/nats_llm_studio.go:46-59); in-process it's ours.
"""

import asyncio

import jax
import pytest

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.export import export_params_to_gguf
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.parallel.memory import estimate_device_bytes
from nats_llm_studio_tpu.serve.api import EngineError
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store.manager import ModelStore

from conftest import async_test
from test_serve_e2e import byte_level_tokenizer_md


def _publish(models_dir, model_id, seed):
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(seed))
    d = models_dir / model_id
    d.mkdir(parents=True)
    export_params_to_gguf(
        d / "m.gguf", params, cfg, name=model_id,
        tokenizer_md=byte_level_tokenizer_md(cfg.vocab_size),
    )
    return cfg


def _estimate(cfg, dtype="float32", batch=2, seq=64):
    return estimate_device_bytes(cfg, {}, batch=batch, seq_len=seq)["total"]


@async_test
async def test_over_budget_load_refused_first_engine_serves(tmp_path, monkeypatch):
    models = tmp_path / "models"
    cfg = _publish(models, "acme/a", 1)
    _publish(models, "acme/b", 2)
    one = _estimate(cfg.with_(dtype="float32"))
    # room for one engine, not two
    monkeypatch.setenv("TPU_HBM_BUDGET_BYTES", str(int(one * 1.5)))
    reg = LocalRegistry(ModelStore(models), dtype="float32", max_batch_slots=2,
                        max_seq_len=64)
    eng_a = await reg.get_engine("acme/a")
    # keep A busy so it is not idle-evictable
    hold = asyncio.Event()
    release = asyncio.Event()

    async def occupy():
        async for chunk in eng_a.chat_stream(
            {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 40,
             "temperature": 0.0}
        ):
            hold.set()
            if chunk.get("object") == "chat.completion":
                break
            await asyncio.sleep(0)

    task = asyncio.create_task(occupy())
    await hold.wait()
    with pytest.raises(EngineError, match="insufficient device memory"):
        await reg.get_engine("acme/b")
    # the refusal left A serving untouched
    await task
    out = await eng_a.chat(
        {"messages": [{"role": "user", "content": "again"}], "max_tokens": 3,
         "temperature": 0.0}
    )
    assert out["usage"]["completion_tokens"] == 3
    assert reg.stats()["models_loaded"] == 1
    assert reg.stats()["hbm_committed_bytes"] > 0
    for eng in reg.loaded_engines().values():
        await eng.unload()


@async_test
async def test_idle_engine_evicted_to_fit(tmp_path, monkeypatch):
    models = tmp_path / "models"
    cfg = _publish(models, "acme/a", 1)
    _publish(models, "acme/b", 2)
    one = _estimate(cfg.with_(dtype="float32"))
    monkeypatch.setenv("TPU_HBM_BUDGET_BYTES", str(int(one * 1.5)))
    reg = LocalRegistry(ModelStore(models), dtype="float32", max_batch_slots=2,
                        max_seq_len=64)
    reg.evict_grace_s = 0.0  # tests move faster than the production grace
    eng_a = await reg.get_engine("acme/a")
    out = await eng_a.chat(
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 2,
         "temperature": 0.0}
    )
    assert out["usage"]["completion_tokens"] == 2
    # A is idle now -> loading B evicts it instead of refusing
    eng_b = await reg.get_engine("acme/b")
    assert set(reg.loaded_engines()) == {"acme/b"}
    out = await eng_b.chat(
        {"messages": [{"role": "user", "content": "yo"}], "max_tokens": 2,
         "temperature": 0.0}
    )
    assert out["usage"]["completion_tokens"] == 2
    # A reloads on demand (evicting idle B in turn)
    eng_a2 = await reg.get_engine("acme/a")
    assert set(reg.loaded_engines()) == {"acme/a"}
    for eng in reg.loaded_engines().values():
        await eng.unload()


@async_test
async def test_recently_used_idle_engine_not_evicted(tmp_path, monkeypatch):
    """The eviction grace: an engine targeted within evict_grace_s is never
    evicted even if its batcher is momentarily idle — closes the gap where
    a client holds the engine (get_engine bumped _last_used) but has not
    submitted yet."""
    models = tmp_path / "models"
    cfg = _publish(models, "acme/a", 1)
    _publish(models, "acme/b", 2)
    one = _estimate(cfg.with_(dtype="float32"))
    monkeypatch.setenv("TPU_HBM_BUDGET_BYTES", str(int(one * 1.5)))
    reg = LocalRegistry(ModelStore(models), dtype="float32", max_batch_slots=2,
                        max_seq_len=64)
    reg.evict_grace_s = 60.0  # nothing in this test is ever past the grace
    await reg.get_engine("acme/a")  # idle but freshly targeted
    with pytest.raises(EngineError, match="insufficient device memory"):
        await reg.get_engine("acme/b")
    assert set(reg.loaded_engines()) == {"acme/a"}
    for eng in reg.loaded_engines().values():
        await eng.unload()


@async_test
async def test_failed_load_releases_hbm_reservation(tmp_path, monkeypatch):
    """A load that reserves budget but then fails (corrupt file, device
    OOM) must release the reservation — a phantom commitment would refuse
    every later load until restart."""
    models = tmp_path / "models"
    cfg = _publish(models, "acme/a", 1)
    _publish(models, "acme/b", 2)
    one = _estimate(cfg.with_(dtype="float32"))
    monkeypatch.setenv("TPU_HBM_BUDGET_BYTES", str(int(one * 3)))
    reg = LocalRegistry(ModelStore(models), dtype="float32", max_batch_slots=2,
                        max_seq_len=64)
    await reg.get_engine("acme/a")
    committed = reg.stats()["hbm_committed_bytes"]
    assert committed > 0

    def boom(*a, **k):
        raise RuntimeError("simulated device OOM during load")

    monkeypatch.setattr(reg, "_load", boom)
    with pytest.raises(RuntimeError, match="simulated"):
        await reg.get_engine("acme/b")
    assert reg.stats()["hbm_committed_bytes"] == committed  # no phantom bytes
    monkeypatch.undo()
    for eng in reg.loaded_engines().values():
        await eng.unload()


@async_test
async def test_no_budget_known_means_no_check(tmp_path, monkeypatch):
    """CPU backends without memory stats (and no env override) skip
    admission — loads behave exactly as before."""
    models = tmp_path / "models"
    _publish(models, "acme/a", 1)
    _publish(models, "acme/b", 2)
    monkeypatch.delenv("TPU_HBM_BUDGET_BYTES", raising=False)
    reg = LocalRegistry(ModelStore(models), dtype="float32", max_batch_slots=2,
                        max_seq_len=64)
    await reg.get_engine("acme/a")
    await reg.get_engine("acme/b")
    assert set(reg.loaded_engines()) == {"acme/a", "acme/b"}
    for eng in reg.loaded_engines().values():
        await eng.unload()


@async_test
async def test_warm_on_load_smoke(tmp_path, monkeypatch):
    """TPU_WARM_ON_LOAD=1 pre-compiles the chunk/full-prefill programs at
    load time (instead of on the first unlucky long request) and must not
    break serving."""
    models = tmp_path / "models"
    _publish(models, "acme/a", 1)
    monkeypatch.delenv("TPU_HBM_BUDGET_BYTES", raising=False)
    monkeypatch.setenv("TPU_WARM_ON_LOAD", "1")
    reg = LocalRegistry(ModelStore(models), dtype="float32", max_batch_slots=2,
                        max_seq_len=64)
    eng = await reg.get_engine("acme/a")
    out = await eng.chat(
        {"messages": [{"role": "user", "content": "hi"}], "max_tokens": 3,
         "temperature": 0.0}
    )
    assert out["usage"]["completion_tokens"] == 3
    await eng.unload()

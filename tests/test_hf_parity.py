"""Cross-implementation parity with the transformers Llama reference.

VERDICT r2 missing #5: every model ever decoded in-tree was random-init or
in-tree-exported, so architecture fidelity rested on "matches my spec
reading". No pretrained checkpoint exists in this offline image, but the
*ecosystem's reference implementation* does: transformers' LlamaForCausalLM
(torch CPU). This test builds a tiny random HF Llama, maps its weights into
our param tree, and requires logit agreement — pinning RoPE convention
(rotate-half), GQA head grouping, SwiGLU ordering, RMSNorm placement, and
the lm_head path against the implementation the GGUF ecosystem itself
converts from (gguf-py reads HF checkpoints; llama.cpp executes them).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, make_cache


def _tiny_hf():
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        max_position_embeddings=128,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        attention_bias=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    return cfg, model


def _to_ours(hf_cfg, model) -> tuple[ModelConfig, dict]:
    cfg = ModelConfig(
        arch="llama",
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=hf_cfg.num_key_value_heads,
        head_dim=hf_cfg.head_dim,
        d_ff=hf_cfg.intermediate_size,
        rope_theta=hf_cfg.rope_theta,
        rms_eps=hf_cfg.rms_norm_eps,
        max_seq_len=hf_cfg.max_position_embeddings,
        dtype="float32",
    )

    def t(x):  # torch [out, in] -> ours [in, out]
        return jnp.asarray(x.detach().numpy().T)

    def stack(getter):
        return jnp.stack([getter(layer) for layer in model.model.layers])

    params = {
        "embed": jnp.asarray(model.model.embed_tokens.weight.detach().numpy()),
        "out_norm": jnp.asarray(model.model.norm.weight.detach().numpy()),
        "lm_head": t(model.lm_head.weight),
        "blocks": {
            "attn_norm": stack(lambda L: jnp.asarray(
                L.input_layernorm.weight.detach().numpy())),
            "ffn_norm": stack(lambda L: jnp.asarray(
                L.post_attention_layernorm.weight.detach().numpy())),
            "wq": stack(lambda L: t(L.self_attn.q_proj.weight)),
            "wk": stack(lambda L: t(L.self_attn.k_proj.weight)),
            "wv": stack(lambda L: t(L.self_attn.v_proj.weight)),
            "wo": stack(lambda L: t(L.self_attn.o_proj.weight)),
            "w_gate": stack(lambda L: t(L.mlp.gate_proj.weight)),
            "w_up": stack(lambda L: t(L.mlp.up_proj.weight)),
            "w_down": stack(lambda L: t(L.mlp.down_proj.weight)),
        },
    }
    return cfg, params


def test_logits_match_transformers_reference():
    hf_cfg, model = _tiny_hf()
    cfg, params = _to_ours(hf_cfg, model)

    rng = np.random.default_rng(7)
    tokens = rng.integers(0, hf_cfg.vocab_size, size=(2, 21))

    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()  # [B, T, V]

    k, v = make_cache(cfg, 2, 64)
    got, _, _ = forward(
        params, cfg, jnp.asarray(tokens, jnp.int32), k, v,
        jnp.zeros((2,), jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_transformers_reference():
    """The KV-cache decode path (prefill then one-token steps) must agree
    with the HF reference run on the full sequence at once."""
    hf_cfg, model = _tiny_hf()
    cfg, params = _to_ours(hf_cfg, model)

    rng = np.random.default_rng(11)
    tokens = rng.integers(0, hf_cfg.vocab_size, size=(1, 13))

    with torch.no_grad():
        want = model(torch.from_numpy(tokens)).logits.numpy()

    k, v = make_cache(cfg, 1, 64)
    prompt, tail = tokens[:, :8], tokens[:, 8:]
    logits, k, v = forward(
        params, cfg, jnp.asarray(prompt, jnp.int32), k, v,
        jnp.zeros((1,), jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(logits[:, -1]), want[:, 7], rtol=2e-4, atol=2e-4
    )
    for i in range(tail.shape[1]):
        pos = jnp.full((1,), 8 + i, jnp.int32)
        logits, k, v = forward(
            params, cfg, jnp.asarray(tail[:, i : i + 1], jnp.int32), k, v, pos,
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, -1]), want[:, 8 + i], rtol=2e-4, atol=2e-4,
            err_msg=f"decode step {i}",
        )

"""Object store + model manager tests (SURVEY.md §4.2: Object Store
round-trip over real embedded NATS)."""

import asyncio

import pytest

from nats_llm_studio_tpu.store import JetStreamStoreModule, ModelStore
from nats_llm_studio_tpu.store.manager import StoreError, split_model_id
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect
from nats_llm_studio_tpu.transport.jetstream import ObjectNotFound, ObjectStore

from conftest import async_test


class JsHarness:
    def __init__(self, store_dir=None):
        self.store_dir = store_dir

    async def __aenter__(self):
        self.broker = await EmbeddedBroker().start()
        self.module = JetStreamStoreModule(self.broker, store_dir=self.store_dir).install()
        self.nc = await connect(self.broker.url)
        self.os = ObjectStore(self.nc, timeout=5.0)
        return self

    async def __aexit__(self, *exc):
        await self.nc.close()
        await self.broker.stop()


@async_test
async def test_put_get_roundtrip_multichunk():
    async with JsHarness() as h:
        await h.os.ensure_bucket("llm-models")
        blob = bytes(range(256)) * 2000  # 512000 bytes -> 4 chunks at 128k
        info = await h.os.put("llm-models", "pub/model/weights.gguf", blob)
        assert info.chunks == 4
        assert info.size == len(blob)
        got = await h.os.get("llm-models", "pub/model/weights.gguf")
        assert got == blob


@async_test
async def test_small_and_empty_objects():
    async with JsHarness() as h:
        await h.os.ensure_bucket("b")
        await h.os.put("b", "tiny", b"x")
        assert await h.os.get("b", "tiny") == b"x"
        await h.os.put("b", "empty", b"")
        assert await h.os.get("b", "empty") == b""


@async_test
async def test_overwrite_uses_rollup():
    async with JsHarness() as h:
        await h.os.ensure_bucket("b")
        await h.os.put("b", "obj", b"version-1")
        await h.os.put("b", "obj", b"version-2-longer")
        assert await h.os.get("b", "obj") == b"version-2-longer"
        infos = await h.os.list("b")
        assert [i.name for i in infos] == ["obj"]


@async_test
async def test_list_and_delete():
    async with JsHarness() as h:
        await h.os.ensure_bucket("b")
        await h.os.put("b", "a/model/x.gguf", b"aaa")
        await h.os.put("b", "c/model/y.gguf", b"ccc")
        names = {i.name for i in await h.os.list("b")}
        assert names == {"a/model/x.gguf", "c/model/y.gguf"}
        await h.os.delete("b", "a/model/x.gguf")
        names = {i.name for i in await h.os.list("b")}
        assert names == {"c/model/y.gguf"}
        with pytest.raises(ObjectNotFound):
            await h.os.get("b", "a/model/x.gguf")


@async_test
async def test_missing_object_and_bucket():
    async with JsHarness() as h:
        await h.os.ensure_bucket("b")
        with pytest.raises(ObjectNotFound):
            await h.os.info("b", "nope")
        with pytest.raises(ObjectNotFound):
            await h.os.get("missing-bucket", "nope")
        assert await h.os.list_buckets() == ["b"]


@async_test
async def test_persistence_across_restart(tmp_path):
    store_dir = tmp_path / "js"
    async with JsHarness(store_dir=store_dir) as h:
        await h.os.ensure_bucket("b")
        await h.os.put("b", "persisted", b"DATA" * 1000)
    # new broker + module over the same store dir
    async with JsHarness(store_dir=store_dir) as h2:
        got = await h2.os.get("b", "persisted")
        assert got == b"DATA" * 1000


# ---------------------------------------------------------------------------
# ModelStore
# ---------------------------------------------------------------------------


@async_test
async def test_delete_survives_restart(tmp_path):
    """A purge (object delete) must be durable even when compaction has not
    rewritten the log: replay applies the persisted purge record."""
    store_dir = tmp_path / "js"
    async with JsHarness(store_dir=store_dir) as h:
        await h.os.ensure_bucket("b")
        await h.os.put("b", "doomed", b"X" * 5000)
        await h.os.put("b", "kept", b"K" * 5000)
        await h.os.delete("b", "doomed")
    async with JsHarness(store_dir=store_dir) as h2:
        assert (await h2.os.get("b", "kept")) == b"K" * 5000
        with pytest.raises(ObjectNotFound):
            await h2.os.get("b", "doomed")
        names = [o.name for o in await h2.os.list("b")]
        assert names == ["kept"]


@async_test
async def test_streamed_get_chunks(tmp_path):
    """get_chunks yields the object incrementally and verifies the digest."""
    async with JsHarness(store_dir=tmp_path / "js") as h:
        await h.os.ensure_bucket("b")
        data = bytes(range(256)) * 2000  # multiple chunks at small chunk size
        h.os.chunk_size = 8192
        await h.os.put("b", "obj", data)
        parts = [c async for c in h.os.get_chunks("b", "obj")]
        assert len(parts) > 1
        assert b"".join(parts) == data


@async_test
async def test_torn_tail_record_truncated(tmp_path):
    """A crash mid-append (header without full payload) must not corrupt the
    stream: reload truncates the torn record and keeps earlier objects."""
    import struct as _struct

    store_dir = tmp_path / "js"
    async with JsHarness(store_dir=store_dir) as h:
        await h.os.ensure_bucket("b")
        await h.os.put("b", "good", b"G" * 4000)
    # simulate the torn append: header promises 100 payload bytes, 10 land
    files = list(store_dir.glob("*.jsl"))
    assert len(files) == 1
    import json as _json

    head = _json.dumps({"seq": 999, "subject": "$O.b.C.x", "headers": None,
                        "ts": 0.0, "plen": 100}).encode()
    with open(files[0], "ab") as f:
        f.write(_struct.pack(">I", len(head)) + head + b"0123456789")
    async with JsHarness(store_dir=store_dir) as h2:
        assert (await h2.os.get("b", "good")) == b"G" * 4000


def test_split_model_id():
    assert split_model_id("meta/llama-3-8b") == ("meta", "llama-3-8b")
    assert split_model_id("bare-model") == ("local", "bare-model")
    assert split_model_id("/p/m/") == ("p", "m")


def test_local_cache_lifecycle(tmp_path):
    ms = ModelStore(tmp_path / "models")
    src = tmp_path / "w.gguf"
    src.write_bytes(b"GGUFDATA")
    dest = ms.import_file(src, "pub/mymodel")
    assert dest.read_bytes() == b"GGUFDATA"
    cached = ms.cached()
    assert [c.model_id for c in cached] == ["pub/mymodel"]
    assert ms.lookup("pub/mymodel").gguf_path == dest
    deleted = ms.delete_local("pub/mymodel")
    assert "pub" in deleted and "mymodel" in deleted
    assert ms.cached() == []
    with pytest.raises(StoreError) as ei:
        ms.delete_local("pub/mymodel")
    assert ei.value.dir is not None  # attempted dir carried for the envelope


@async_test
async def test_publish_and_pull_roundtrip(tmp_path):
    async with JsHarness() as h:
        ms_a = ModelStore(tmp_path / "worker_a", objstore=h.os)
        ms_b = ModelStore(tmp_path / "worker_b", objstore=h.os)
        src = tmp_path / "model.gguf"
        src.write_bytes(b"WEIGHTS" * 5000)
        ms_a.import_file(src, "acme/granite-tiny")
        obj = await ms_a.publish_model("acme/granite-tiny")
        assert obj == "acme/granite-tiny/model.gguf"
        # second worker pulls by model id
        path, transcript = await ms_b.pull("acme/granite-tiny")
        assert path.read_bytes() == src.read_bytes()
        assert "resolved to 1 object(s)" in transcript
        assert ms_b.lookup("acme/granite-tiny") is not None
        # and by full object name
        path2, _ = await ms_b.pull("acme/granite-tiny/model.gguf")
        assert path2 == path


@async_test
async def test_pull_missing_raises(tmp_path):
    async with JsHarness() as h:
        ms = ModelStore(tmp_path / "m", objstore=h.os)
        await h.os.ensure_bucket("llm-models")
        with pytest.raises(StoreError):
            await ms.pull("ghost/model")


def test_pull_requires_objstore(tmp_path):
    ms = ModelStore(tmp_path / "m")
    with pytest.raises(StoreError):
        asyncio.run(ms.pull("a/b"))


@async_test
async def test_overwrite_purges_old_chunks():
    """Re-publishing an object must not leak the previous revision's chunks
    in the stream (they are purged after the metadata rollup)."""
    async with JsHarness() as h:
        await h.os.ensure_bucket("b")
        big = b"x" * (300 * 1024)  # 3 chunks
        await h.os.put("b", "obj", big)
        st = h.module.streams["OBJ_b"]
        bytes_v1 = st.bytes_total()
        await h.os.put("b", "obj", big)
        assert st.bytes_total() <= bytes_v1 + 1024  # old chunks reclaimed
        assert await h.os.get("b", "obj") == big


@async_test
async def test_pull_with_model_id_override(tmp_path):
    """sync_model_from_bucket's model_id chooses the local cache dir."""
    async with JsHarness() as h:
        pub = ModelStore(tmp_path / "pub", objstore=h.os)
        src = tmp_path / "m.gguf"
        src.write_bytes(b"WEIGHTS")
        pub.import_file(src, "acme/original")
        await pub.publish_model("acme/original")
        ms = ModelStore(tmp_path / "worker", objstore=h.os)
        path, _ = await ms.pull("acme/original/m.gguf", model_id="other/renamed")
        assert ms.lookup("other/renamed") is not None
        assert ms.lookup("acme/original") is None
        assert path.read_bytes() == b"WEIGHTS"


@async_test
async def test_pull_from_file_url(tmp_path):
    """Catalog-style pull: a file:// URL streams into the local cache under
    a derived (or explicit) model id — the `lms get <public model>` analog."""
    src = tmp_path / "src" / "mini.gguf"
    src.parent.mkdir()
    src.write_bytes(b"GGUF-mini-bytes" * 100)
    ms = ModelStore(tmp_path / "models")
    url = src.as_uri()
    dest, transcript = await ms.pull(url)
    assert dest.read_bytes() == src.read_bytes()
    assert "downloads/mini" in str(dest)
    dest2, _ = await ms.pull(url, model_id="acme/mini")
    assert "acme/mini" in str(dest2)
    with pytest.raises(StoreError):
        await ms.pull("file:///nonexistent/nope.gguf")
    with pytest.raises(StoreError):
        await ms.pull("https://example.invalid/not-a-gguf.bin")

"""obs/ (PR 1): log-bucket histograms, trace context, event ring, Prometheus
rendering — unit coverage plus the end-to-end trace/metrics path over a real
embedded broker + tiny real model."""

import json
import random
import threading

from nats_llm_studio_tpu.obs import (
    EventRing,
    LogHistogram,
    PromRenderer,
    Trace,
)

from conftest import async_test


# -- LogHistogram ------------------------------------------------------------


def test_bucket_boundaries_geometric():
    h = LogHistogram(lo=1.0, hi=1000.0, growth=2.0)
    assert h.bounds[0] == 1.0
    assert h.bounds[-1] == 1000.0
    assert all(b2 > b1 for b1, b2 in zip(h.bounds, h.bounds[1:]))
    # every edge grows by at most the growth factor (last may clamp to hi)
    for b1, b2 in zip(h.bounds, h.bounds[1:]):
        assert b2 / b1 <= 2.0 + 1e-9
    # a second histogram on the same ladder shares the tuple (cache)
    assert LogHistogram(lo=1.0, hi=1000.0, growth=2.0).bounds is h.bounds


def test_record_underflow_overflow_and_extrema():
    h = LogHistogram(lo=1.0, hi=100.0, growth=2.0)
    h.record(0.001)  # below lo -> first bucket
    h.record(5000.0)  # above hi -> overflow bucket
    h.record(7.0)
    snap = h.snapshot()
    assert snap.count == h.count == 3
    assert snap.counts[0] == 1
    assert snap.counts[-1] == 1
    assert sum(snap.counts) == snap.count
    assert snap.vmin == 0.001 and snap.vmax == 5000.0 == h.max
    assert abs(snap.total - 5007.001) < 1e-9
    # percentile never escapes the recorded extrema
    assert 0.001 <= snap.percentile(0.0) <= 5000.0
    assert snap.percentile(0.999) == 5000.0


def test_percentile_tracks_exact_on_known_distributions():
    """Histogram percentiles vs exact sorted-index percentiles: within the
    bucket relative width (growth 1.25 -> 25%) on uniform, exponential-ish,
    and constant distributions."""
    rng = random.Random(7)
    dists = {
        "uniform": [rng.uniform(1.0, 1000.0) for _ in range(5000)],
        "heavy_tail": [2.0 ** rng.uniform(0, 12) for _ in range(5000)],
        "constant": [42.0] * 1000,
    }
    for name, values in dists.items():
        h = LogHistogram()  # default ladder: lo=0.01, hi=1e7, growth=1.25
        for v in values:
            h.record(v)
        exact_sorted = sorted(values)
        for q in (0.5, 0.9, 0.95, 0.99):
            exact = exact_sorted[min(len(values) - 1, int(len(values) * q))]
            est = h.percentile(q)
            assert abs(est - exact) <= 0.25 * exact + 1e-6, (
                f"{name} q={q}: est={est} exact={exact}"
            )


def test_snapshot_subtraction_isolates_a_phase():
    h = LogHistogram(lo=0.1, hi=1e4, growth=1.25)
    for _ in range(200):
        h.record(5.0)
    s0 = h.snapshot()
    for _ in range(300):
        h.record(500.0)
    delta = h.snapshot() - s0
    assert delta.count == 300
    assert abs(delta.total - 300 * 500.0) < 1e-6
    # the delta's distribution is ONLY the second phase
    assert abs(delta.percentile(0.5) - 500.0) <= 0.25 * 500.0
    # mismatched ladders refuse to subtract
    import pytest

    with pytest.raises(ValueError):
        h.snapshot() - LogHistogram(lo=1.0, hi=10.0, growth=2.0).snapshot()


def test_concurrent_record_and_snapshot():
    h = LogHistogram()
    n_threads, per_thread = 4, 5000
    bad = []
    stop = threading.Event()

    def writer(seed):
        rng = random.Random(seed)
        for _ in range(per_thread):
            h.record(rng.uniform(0.1, 1e4))

    def reader():
        while not stop.is_set():
            s = h.snapshot()
            if sum(s.counts) != s.count:
                bad.append(s)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert not bad, "snapshot saw counts/count out of sync (torn read)"
    final = h.snapshot()
    assert final.count == n_threads * per_thread
    assert sum(final.counts) == final.count


# -- Trace -------------------------------------------------------------------


def test_trace_marks_first_write_wins_and_report_spans():
    tr = Trace("abcd1234abcd1234")
    tr.mark("recv", 10.0)
    tr.mark("enqueue", 10.1)
    tr.mark("admit", 10.3)
    tr.mark("prefill", 10.7)
    tr.mark("first_token", 10.8)
    tr.mark("decode_done", 11.5)
    tr.mark("publish", 11.6)
    tr.mark("admit", 99.0)  # re-mark must NOT move the recorded time
    rep = tr.report()
    assert rep["trace_id"] == "abcd1234abcd1234"
    spans = rep["spans_ms"]
    assert abs(spans["queue_ms"] - 200.0) < 1e-6
    assert abs(spans["prefill_ms"] - 400.0) < 1e-6
    assert abs(spans["first_token_ms"] - 100.0) < 1e-6
    assert abs(spans["decode_ms"] - 700.0) < 1e-6
    assert abs(spans["publish_ms"] - 100.0) < 1e-6
    assert abs(spans["total_ms"] - 1600.0) < 1e-6
    assert rep["marks_ms"]["recv"] == 0.0
    assert abs(rep["marks_ms"]["publish"] - 1600.0) < 1e-6


def test_trace_report_skips_absent_stages():
    tr = Trace()
    tr.mark("recv", 1.0)
    tr.mark("publish", 1.5)
    rep = tr.report()
    assert abs(rep["spans_ms"]["total_ms"] - 500.0) < 1e-6
    assert "queue_ms" not in rep["spans_ms"]  # no enqueue/admit marks
    assert len(tr.trace_id) == 16


# -- EventRing ---------------------------------------------------------------


def test_event_ring_capacity_filter_and_dropped():
    ring = EventRing(capacity=4)
    for i in range(6):
        ring.emit("shed" if i % 2 else "cancel", i=i)
    assert ring.emitted == 6 and ring.dropped == 2
    evs = ring.snapshot()
    assert [e["seq"] for e in evs] == [2, 3, 4, 5]  # oldest-first window
    sheds = ring.snapshot(kind="shed")
    assert all(e["kind"] == "shed" for e in sheds) and len(sheds) == 2
    assert [e["seq"] for e in ring.snapshot(limit=2)] == [4, 5]
    ring.clear()
    assert ring.emitted == 0 and ring.snapshot() == []


# -- PromRenderer ------------------------------------------------------------


def test_prom_renderer_families_and_histogram_exposition():
    h = LogHistogram(lo=1.0, hi=8.0, growth=2.0)  # bounds 1,2,4,8
    for v in (0.5, 1.5, 3.0, 100.0):
        h.record(v)
    r = PromRenderer()
    r.counter("app_requests_total", 5, labels={"model": "a"}, help="reqs")
    r.counter("app_requests_total", 7, labels={"model": "b"})
    r.gauge("app_up", 1)
    r.histogram("app_latency_ms", h.snapshot(), labels={"model": "a"})
    text = r.render()
    # ONE TYPE line per family even with two label sets
    assert text.count("# TYPE app_requests_total counter") == 1
    assert '\napp_requests_total{model="a"} 5\n' in text
    assert '\napp_requests_total{model="b"} 7\n' in text
    # cumulative le buckets, +Inf equals total count, sum/count present
    assert '\napp_latency_ms_bucket{le="1",model="a"} 1\n' in text
    assert '\napp_latency_ms_bucket{le="2",model="a"} 2\n' in text
    assert '\napp_latency_ms_bucket{le="4",model="a"} 3\n' in text
    assert '\napp_latency_ms_bucket{le="+Inf",model="a"} 4\n' in text
    assert '\napp_latency_ms_count{model="a"} 4\n' in text
    import pytest

    with pytest.raises(ValueError):
        r.gauge("app_requests_total", 1)  # type conflict on one family


# -- compile-cache counters --------------------------------------------------


def test_compile_cache_listener_install_idempotent():
    from nats_llm_studio_tpu.obs import compile_cache as cc

    assert cc.install_compile_cache_listener() is True
    # a second (and third) install is a no-op, not a second registration —
    # otherwise every event would double-count
    assert cc.install_compile_cache_listener() is True
    assert cc.install_compile_cache_listener() is True


def test_compile_cache_counts_accumulate_and_snapshot_is_a_copy():
    from nats_llm_studio_tpu.obs import compile_cache as cc

    before = cc.compile_cache_counts()
    cc._on_event("/jax/compilation_cache/cache_hits")
    cc._on_event("/jax/compilation_cache/cache_hits")
    cc._on_event("/jax/compilation_cache/cache_misses")
    cc._on_event("/jax/unrelated/event")  # ignored, not a KeyError
    after = cc.compile_cache_counts()
    assert after["hits"] - before["hits"] == 2
    assert after["misses"] - before["misses"] == 1
    after["hits"] = -999  # mutating the snapshot must not touch the counters
    assert cc.compile_cache_counts()["hits"] >= 0


# -- strict exposition check (minimal line parser) ---------------------------


def check_prom_exposition(text: str) -> dict:
    """Minimal Prometheus text-exposition validator: every sample line
    parses, every family has exactly ONE # TYPE line, every sample belongs
    to a declared family, and every histogram's ``_bucket`` series is
    cumulative-monotone per label set with a ``+Inf`` bucket equal to its
    ``_count``, with ``_sum``/``_count`` present. Returns {family: type}."""
    import re

    typed: dict[str, str] = {}
    samples: dict[str, list] = {}
    sample_re = re.compile(
        r"([a-zA-Z_:][a-zA-Z0-9_:]*)"        # metric name
        r"(?:\{(.*)\})?"                      # optional label set
        r" (-?(?:[0-9.]+(?:e[+-]?[0-9]+)?|Inf)|\+Inf|NaN)$"
    )
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for ln in text.splitlines():
        if not ln or ln.startswith("# HELP"):
            continue
        if ln.startswith("# TYPE"):
            _, _, fam, typ = ln.split()
            assert fam not in typed, f"duplicate TYPE line for {fam}"
            assert typ in ("counter", "gauge", "histogram"), ln
            typed[fam] = typ
            continue
        assert not ln.startswith("#"), f"unknown comment line: {ln!r}"
        m = sample_re.fullmatch(ln)
        assert m, f"unparseable sample line: {ln!r}"
        name, labelstr, _val = m.groups()
        labels = dict(label_re.findall(labelstr)) if labelstr else {}
        samples.setdefault(name, []).append((labels, float(m.group(3))))
    for name in samples:
        base = name
        for suf in ("_bucket", "_sum", "_count"):
            stripped = name[: -len(suf)]
            if name.endswith(suf) and typed.get(stripped) == "histogram":
                base = stripped
        assert base in typed, f"sample {name} has no TYPE line"
    for fam, typ in typed.items():
        if typ != "histogram":
            continue
        by_series: dict[tuple, list] = {}
        for labels, val in samples.get(fam + "_bucket", []):
            assert "le" in labels, f"{fam} bucket without le: {labels}"
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            by_series.setdefault(key, []).append((float(labels["le"]), val))
        counts = {tuple(sorted(l.items())): v
                  for l, v in samples.get(fam + "_count", [])}
        sums = {tuple(sorted(l.items())): v
                for l, v in samples.get(fam + "_sum", [])}
        assert by_series, f"histogram {fam} exposes no buckets"
        for key, series in by_series.items():
            series.sort()
            les = [le for le, _ in series]
            assert les[-1] == float("inf"), f"{fam}{key} missing +Inf bucket"
            assert len(set(les)) == len(les), f"{fam}{key} duplicate le"
            cums = [c for _, c in series]
            assert all(b >= a for a, b in zip(cums, cums[1:])), (
                f"{fam}{key} buckets not cumulative-monotone: {series}"
            )
            assert key in counts, f"{fam}{key} missing _count"
            assert key in sums, f"{fam}{key} missing _sum"
            assert cums[-1] == counts[key], (
                f"{fam}{key} +Inf bucket != _count"
            )
    return typed


def test_exposition_checker_accepts_renderer_output_and_rejects_bad():
    import pytest

    h = LogHistogram(lo=1.0, hi=8.0, growth=2.0)
    for v in (0.5, 3.0, 100.0):
        h.record(v)
    r = PromRenderer()
    r.counter("x_total", 1, labels={"model": "a"})
    r.counter("x_total", 2, labels={"model": "b"})
    r.histogram("y_ms", h.snapshot(), labels={"model": "a"})
    r.histogram("y_ms", h.snapshot(), labels={"model": "b"})
    typed = check_prom_exposition(r.render())
    assert typed == {"x_total": "counter", "y_ms": "histogram"}

    with pytest.raises(AssertionError, match="duplicate TYPE"):
        check_prom_exposition(
            "# TYPE a counter\na 1\n# TYPE a counter\na 2\n"
        )
    with pytest.raises(AssertionError, match="no TYPE line"):
        check_prom_exposition("orphan_metric 3\n")
    with pytest.raises(AssertionError, match="cumulative"):
        check_prom_exposition(
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\nh_bucket{le="+Inf"} 5\n'
            "h_sum 9\nh_count 5\n"
        )
    with pytest.raises(AssertionError, match="missing _count"):
        check_prom_exposition(
            "# TYPE h histogram\n"
            'h_bucket{le="+Inf"} 2\nh_sum 3\n'
        )


# -- end-to-end: trace + metrics.prom + events over the wire -----------------


@async_test
async def test_trace_and_metrics_e2e_over_embedded_broker(tmp_path):
    """One real chat request carries a client-chosen X-Trace-Id through the
    broker, worker, engine, and batcher owner thread; the response stats show
    the full per-stage waterfall; metrics.prom exposes the histograms; the
    events subject serves the engine_load event."""
    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store import ModelStore

    from test_serve_e2e import E2E, build_tiny_gguf

    async with E2E() as h:
        src = tmp_path / "tiny.gguf"
        build_tiny_gguf(src)
        pub = ModelStore(tmp_path / "pub", objstore=h.objstore)
        pub.import_file(src, "acme/obs")
        await pub.publish_model("acme/obs")

        store = ModelStore(tmp_path / "worker", objstore=h.objstore)
        worker = Worker(
            WorkerConfig(nats_url=h.broker.url), LocalRegistry(store, dtype="float32")
        )
        await worker.start()
        resp = await h.req("pull_model", {"identifier": "acme/obs"})
        assert resp["ok"], resp

        trace_id = "cafe0123deadbeef"
        msg = await h.nc.request(
            "lmstudio.chat_model",
            json.dumps(
                {
                    "model": "acme/obs",
                    "messages": [{"role": "user", "content": "hi there"}],
                    "max_tokens": 6,
                    "temperature": 0.0,
                }
            ).encode(),
            timeout=50.0,
            headers={"X-Trace-Id": trace_id},
        )
        env = json.loads(msg.payload)
        assert env["ok"], env
        # the client's id is echoed top-level AND inside the stats report
        assert env["trace_id"] == trace_id
        rep = env["data"]["response"]["stats"]["trace"]
        assert rep["trace_id"] == trace_id
        spans = rep["spans_ms"]
        for k in ("queue_ms", "prefill_ms", "first_token_ms", "decode_ms",
                  "publish_ms", "total_ms"):
            assert k in spans and spans[k] >= 0.0, spans
        for stage in ("recv", "enqueue", "admit", "prefill", "first_token",
                      "decode_done", "publish"):
            assert stage in rep["marks_ms"], rep

        # an omitted header still yields a server-minted trace
        msg = await h.nc.request(
            "lmstudio.chat_model",
            json.dumps(
                {
                    "model": "acme/obs",
                    "messages": [{"role": "user", "content": "again"}],
                    "max_tokens": 3,
                    "temperature": 0.0,
                }
            ).encode(),
            timeout=50.0,
        )
        env2 = json.loads(msg.payload)
        assert env2["ok"] and env2["trace_id"] and env2["trace_id"] != trace_id

        # Prometheus exposition covers the tentpole histograms + counters
        msg = await h.nc.request("lmstudio.metrics.prom", b"", timeout=10.0)
        text = msg.payload.decode()
        assert "# TYPE lmstudio_admit_queue_delay_ms histogram" in text
        assert "# TYPE lmstudio_ttft_ms histogram" in text
        assert "# TYPE lmstudio_decode_step_ms histogram" in text
        # every family carries the worker_id default label (cluster scrapes
        # stay attributable), so match the label prefix, not the full set
        wid = worker.worker_id
        assert f'lmstudio_ttft_ms_bucket{{le="+Inf",model="acme/obs",worker_id="{wid}"}}' in text
        assert f'lmstudio_admit_queue_delay_ms_count{{model="acme/obs",worker_id="{wid}"}} 2' in text
        assert "# TYPE lmstudio_requests_total counter" in text
        assert "lmstudio_batcher_requests_total" in text
        # per-program device timing: one labeled histogram family over every
        # jit-grid program dispatched, plus tokens per dispatch
        assert text.count("# TYPE lmstudio_program_ms histogram") == 1
        assert text.count("# TYPE lmstudio_program_tokens histogram") == 1
        program_counts = [
            ln for ln in text.splitlines()
            if ln.startswith("lmstudio_program_ms_count{")
        ]
        assert program_counts and all('program="' in ln for ln in program_counts)
        assert len(program_counts) >= 2  # admit + decode at minimum
        # the whole exposition is STRICTLY valid: one TYPE line per family,
        # cumulative-monotone buckets, _sum/_count per histogram series
        typed = check_prom_exposition(text)
        assert typed["lmstudio_program_ms"] == "histogram"

        # the event ring saw the engine load; the subject serves it
        resp = await h.req("events", {"kind": "engine_load"})
        assert resp["ok"], resp
        assert any(
            ev["model"] == "acme/obs" for ev in resp["data"]["events"]
        ), resp["data"]
        assert resp["data"]["capacity"] > 0

        await worker.drain()

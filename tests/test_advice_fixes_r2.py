"""Regression tests for the round-2 advisor findings (ADVICE.md):

1. the `lmstudio.profile` subject must ignore a client-supplied 'dir'
   (covered in test_worker.py::test_profile_subject);
2. a failed admit/decode dispatch may have consumed donated K/V buffers —
   the batcher must reset its cache (failing active streams honestly)
   instead of wedging every subsequent dispatch;
3. `_pull_url` must reject unsafe URL basenames and enforce a download
   size ceiling;
4. the broker must bound a slow consumer's outbound buffer and drop the
   client, like real nats-server;
5. `broker.stop()` must close the object-store module's append-log handles
   deterministically (no GC-held "a+b" fds).
"""

import asyncio

import jax
import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
from nats_llm_studio_tpu.store.manager import ModelStore, StoreError
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# -- 2: batcher resets (not wedges) after a failed admit dispatch ------------


@pytest.mark.parametrize("paged", [False, True])
@async_test
async def test_failed_admit_resets_batcher(model, paged):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], paged=paged)
    sp = SamplingParams(temperature=0.0, max_tokens=64)

    attr = "_admit_fused_paged" if paged else "_admit_fused"
    orig = getattr(b, attr)
    fail_next = {"on": False}

    def poisoned(*a, **kw):
        if fail_next["on"]:
            fail_next["on"] = False
            raise RuntimeError("simulated device OOM after donation")
        return orig(*a, **kw)

    setattr(b, attr, poisoned)

    # stream A occupies a slot and keeps decoding
    a_tokens = asyncio.Event()
    a_err: list[BaseException] = []

    async def run_a():
        try:
            async for _ in b.submit([1, 2, 3], sp):
                a_tokens.set()
        except RuntimeError as e:
            a_err.append(e)

    task_a = asyncio.create_task(run_a())
    await asyncio.wait_for(a_tokens.wait(), timeout=30)

    # B's admit dispatch fails after donating K/V: B gets the error...
    fail_next["on"] = True
    with pytest.raises(RuntimeError):
        async for _ in b.submit([4, 5], sp):
            pass
    # ...and A is failed honestly by the cache reset (its KV rows are gone)
    await asyncio.wait_for(task_a, timeout=30)
    assert a_err and "reset" in str(a_err[0])

    # the batcher is NOT wedged: a fresh request decodes normally
    got = []
    async for tok in b.submit([6, 7, 8], SamplingParams(temperature=0.0, max_tokens=4)):
        got.append(tok)
    assert len(got) == 4
    await asyncio.to_thread(b.stop)


# -- 3: URL pull hardening ---------------------------------------------------


@async_test
async def test_pull_url_rejects_unsafe_basenames(tmp_path):
    ms = ModelStore(tmp_path / "models")
    for bad in ("https://x.test/..gguf", "https://x.test/a/...gguf",
                "https://x.test/%2e%2e.gguf", "https://x.test/-evil.gguf"):
        with pytest.raises(StoreError, match="unsafe|expects"):
            await ms.pull(bad)
    # no network was touched: rejection happens before any fetch, so the
    # cache dir must not have grown a 'downloads' publisher
    assert not (tmp_path / "models" / "downloads").exists()


def test_model_id_traversal_rejected(tmp_path):
    """Client-controlled model ids become mkdir/rmtree targets via
    model_dir()/delete_local(); hostile components must be rejected at the
    split_model_id altitude so EVERY path (URL pull with model_id override,
    bucket sync, delete) is covered."""
    ms = ModelStore(tmp_path / "models")
    for bad in ("../../etc", "pub/..", "..", "a/../b", "pub/.hidden",
                "pub/mo\x00del", "pub\\win", ""):
        with pytest.raises(StoreError, match="unsafe"):
            ms.model_dir(bad)
    # normal ids still work
    assert ms.model_dir("meta-llama/Meta-Llama-3-8B-Instruct").name == (
        "Meta-Llama-3-8B-Instruct"
    )
    assert ms.model_dir("granite-2b").parent.name == "local"


@async_test
async def test_pull_url_size_ceiling(tmp_path):
    big = tmp_path / "big.gguf"
    big.write_bytes(b"x" * 4096)
    ms = ModelStore(tmp_path / "models", max_url_pull_bytes=1024)
    with pytest.raises(StoreError, match="ceiling"):
        await ms.pull(big.as_uri())
    # nothing committed to the cache
    assert not list((tmp_path / "models").rglob("*.gguf"))
    # a file under the ceiling still pulls fine
    small = tmp_path / "small.gguf"
    small.write_bytes(b"y" * 512)
    dest, _ = await ms.pull(small.as_uri())
    assert dest.read_bytes() == b"y" * 512


# -- 4: broker slow-consumer bound ------------------------------------------


@async_test
async def test_slow_consumer_dropped_with_bounded_memory():
    broker = await EmbeddedBroker(max_pending=64 * 1024).start()
    try:
        # raw socket subscriber that stops reading after the handshake
        reader, writer = await asyncio.open_connection("127.0.0.1", broker.port)
        await reader.readline()  # INFO
        writer.write(b"CONNECT {}\r\nSUB flood 1\r\nPING\r\n")
        await writer.drain()
        while (await reader.readline()).strip() != b"PONG":
            pass
        stalled_conn = next(iter(broker._clients))

        nc = await connect(broker.url)
        payload = b"z" * (64 * 1024)
        # far beyond max_pending + any loopback TCP buffering
        for _ in range(256):
            await nc.publish("flood", payload)
            if stalled_conn.closed:
                break
            await asyncio.sleep(0)
        # the stalled client must be dropped, with its buffer bounded
        for _ in range(200):
            if stalled_conn.closed:
                break
            await asyncio.sleep(0.05)
        assert stalled_conn.closed, "slow consumer was never dropped"
        assert stalled_conn._pending <= broker.max_pending + broker.max_payload
        # the publisher is unaffected
        await nc.flush()
        await nc.close()
        writer.close()
    finally:
        await broker.stop()


# -- 5: broker.stop() closes object-store log handles ------------------------


@async_test
async def test_store_module_closed_on_broker_stop(tmp_path):
    from nats_llm_studio_tpu.store.objectstore import JetStreamStoreModule
    from nats_llm_studio_tpu.transport.jetstream import ObjectStore

    broker = await EmbeddedBroker().start()
    module = JetStreamStoreModule(broker, store_dir=tmp_path / "js").install()
    nc = await connect(broker.url)
    store = ObjectStore(nc)
    await store.ensure_bucket("b")
    await store.put("b", "k.gguf", b"payload")
    assert module._files  # an append-log handle is open
    await nc.close()
    await broker.stop()
    assert not module._files  # closed deterministically, not left to GC

"""Transport layer tests: protocol parser, broker routing, client API.

Covers the NATS semantics the reference delegates to nats-server + nats.go:
pub/sub, wildcard subjects, queue-group load balancing
(/root/reference/README.md:478-484), request-reply, headers, streaming.
"""

import asyncio
import collections

import pytest

from nats_llm_studio_tpu.transport import EmbeddedBroker, connect
from nats_llm_studio_tpu.transport import protocol as p
from nats_llm_studio_tpu.utils import subject_matches

from conftest import async_test


# --- pure protocol tests -----------------------------------------------------


def test_subject_matching():
    assert subject_matches("lmstudio.*", "lmstudio.chat_model")
    assert not subject_matches("lmstudio.*", "lmstudio.a.b")
    assert subject_matches("lmstudio.>", "lmstudio.a.b")
    assert not subject_matches("lmstudio.>", "lmstudio")
    assert subject_matches("a.*.c", "a.b.c")
    assert subject_matches(">", "anything.at.all")
    assert not subject_matches("a.b", "a.b.c")


def test_parser_roundtrip_pub():
    parser = p.Parser()
    data = p.encode_pub("foo.bar", b"hello", reply="inbox.1")
    events = list(parser.feed(data))
    assert len(events) == 1
    ev = events[0]
    assert ev.op == "PUB" and ev.subject == "foo.bar"
    assert ev.reply == "inbox.1" and ev.payload == b"hello"


def test_parser_split_feeds():
    parser = p.Parser()
    data = p.encode_pub("s", b"x" * 1000) + p.PING + p.encode_pub("t", b"")
    events = []
    for i in range(0, len(data), 7):  # drip-feed 7 bytes at a time
        events.extend(parser.feed(data[i : i + 7]))
    assert [type(e).__name__ for e in events] == ["MsgEvent", "CtrlEvent", "MsgEvent"]
    assert events[0].payload == b"x" * 1000
    assert events[2].subject == "t" and events[2].payload == b""


def test_parser_headers_roundtrip():
    parser = p.Parser()
    data = p.encode_pub("s", b"payload", headers={"Nats-Stream-Done": "1", "X-Seq": "42"})
    (ev,) = parser.feed(data)
    assert ev.op == "HPUB"
    assert ev.headers == {"Nats-Stream-Done": "1", "X-Seq": "42"}
    assert ev.payload == b"payload"


def test_parser_binary_payload_with_crlf():
    parser = p.Parser()
    payload = b"a\r\nb\r\n\x00\xff" * 10
    (ev,) = parser.feed(p.encode_pub("bin", payload))
    assert ev.payload == payload


# --- broker + client integration --------------------------------------------


async def _broker():
    return await EmbeddedBroker().start()


@async_test
async def test_pub_sub_roundtrip():
    broker = await _broker()
    try:
        nc = await connect(broker.url)
        sub = await nc.subscribe("greet.*")
        await nc.flush()
        await nc.publish("greet.world", b"hi", headers={"K": "V"})
        msg = await sub.next_msg(timeout=5)
        assert msg.subject == "greet.world"
        assert msg.payload == b"hi"
        assert msg.headers == {"K": "V"}
        await nc.close()
    finally:
        await broker.stop()


@async_test
async def test_request_reply():
    broker = await _broker()
    try:
        server = await connect(broker.url)

        async def handler(msg):
            await msg.respond(b"pong:" + msg.payload)

        await server.subscribe("svc.echo", cb=handler)
        await server.flush()

        client = await connect(broker.url)
        resp = await client.request("svc.echo", b"ping", timeout=5)
        assert resp.payload == b"pong:ping"
        await client.close()
        await server.close()
    finally:
        await broker.stop()


@async_test
async def test_request_timeout():
    broker = await _broker()
    try:
        client = await connect(broker.url)
        with pytest.raises(asyncio.TimeoutError):
            await client.request("nobody.home", b"", timeout=0.2)
        await client.close()
    finally:
        await broker.stop()


@async_test
async def test_queue_group_load_balancing():
    """Each message goes to exactly one member per queue group
    (README.md:478-484); plain subscribers all get a copy."""
    broker = await _broker()
    try:
        counts = collections.Counter()
        workers = []
        for i in range(3):
            nc = await connect(broker.url)

            async def handler(msg, i=i):
                counts[i] += 1

            await nc.subscribe("work.q", queue="workers", cb=handler)
            await nc.flush()
            workers.append(nc)

        monitor = await connect(broker.url)
        mon_sub = await monitor.subscribe("work.q")
        await monitor.flush()

        pub = await connect(broker.url)
        N = 60
        for _ in range(N):
            await pub.publish("work.q", b"job")
        await pub.flush()
        await asyncio.sleep(0.2)

        assert sum(counts.values()) == N  # one worker per message
        assert all(c > 0 for c in counts.values())  # all members participate
        got = 0
        while got < N:  # monitor (non-queue) saw every message
            await mon_sub.next_msg(timeout=2)
            got += 1

        for nc in workers + [monitor, pub]:
            await nc.close()
    finally:
        await broker.stop()


@async_test
async def test_unsubscribe_stops_delivery():
    broker = await _broker()
    try:
        nc = await connect(broker.url)
        sub = await nc.subscribe("x")
        await nc.flush()
        await nc.publish("x", b"1")
        assert (await sub.next_msg(timeout=5)).payload == b"1"
        await sub.unsubscribe()
        await nc.flush()
        await nc.publish("x", b"2")
        await nc.flush()
        with pytest.raises((asyncio.TimeoutError, BrokenPipeError)):
            await sub.next_msg(timeout=0.2)
        await nc.close()
    finally:
        await broker.stop()


@async_test
async def test_large_payload_at_limit_and_over():
    """max_payload matches real nats-server's 1 MiB default: a payload at
    the limit passes, one over it is rejected at the broker (so in-tree
    client defaults behave identically against a stock server)."""
    broker = await _broker()
    try:
        nc = await connect(broker.url)
        sub = await nc.subscribe("big")
        await nc.flush()
        blob = bytes(range(256)) * 4096  # exactly 1 MiB
        await nc.publish("big", blob)
        msg = await sub.next_msg(timeout=10)
        assert msg.payload == blob
        with pytest.raises((ValueError, ConnectionError)):
            await nc.publish("big", blob + b"x")
            await nc.flush()
        await nc.close()
    finally:
        await broker.stop()


@async_test
async def test_request_stream_terminal_header():
    broker = await _broker()
    try:
        server = await connect(broker.url)

        async def handler(msg):
            for i in range(3):
                await server.publish(msg.reply, f"chunk{i}".encode())
            await server.publish(msg.reply, b"done", headers={"Nats-Stream-Done": "1"})

        await server.subscribe("stream.svc", cb=handler)
        await server.flush()

        client = await connect(broker.url)
        chunks = []
        async for m in client.request_stream("stream.svc", b"", timeout=10):
            chunks.append(m.payload)
        assert chunks == [b"chunk0", b"chunk1", b"chunk2", b"done"]
        await client.close()
        await server.close()
    finally:
        await broker.stop()


@async_test
async def test_broker_survives_protocol_fuzz():
    """Random garbage byte streams must never crash the broker: every
    connection gets -ERR or a drop, and well-formed clients keep working
    throughout (SURVEY.md §5 failure detection)."""
    import random as _random

    broker = await _broker()
    try:
        nc = await connect(broker.url)
        sub = await nc.subscribe("alive")
        await nc.flush()
        rnd = _random.Random(7)
        for i in range(24):
            r, w = await asyncio.open_connection("127.0.0.1", broker.port)
            await r.readline()  # INFO
            if i % 3 == 0:
                blob = bytes(rnd.randrange(256) for _ in range(rnd.randrange(1, 400)))
            elif i % 3 == 1:
                blob = b"PUB  \r\nxx\r\nSUB\r\nHPUB a 999999999\r\n"
            else:
                blob = ("\r\n".join(
                    rnd.choice(["PING", "PONG", "CONNECT {", "MSG x 1 5", "UNSUB",
                                "PUB a b c d e", "SUB " + "s" * 300 + " 1"])
                    for _ in range(8)) + "\r\n").encode()
            try:
                w.write(blob)
                await w.drain()
                got = b""
                try:
                    while len(got) < 4096:
                        chunk = await asyncio.wait_for(r.read(1024), timeout=0.5)
                        if not chunk:
                            break
                        got += chunk
                except asyncio.TimeoutError:
                    pass
                # for inputs containing complete invalid frames the broker
                # must reply (-ERR, or PONG for the interleaved PINGs) or
                # drop the connection — never silently buffer them. Pure
                # random bytes may legitimately sit as an incomplete frame.
                if i % 3 != 0:
                    dropped = r.at_eof()
                    responded = (b"-ERR" in got) or (b"PONG" in got) or dropped
                    assert responded, (i, blob[:40], got[:80])
            except (ConnectionError, OSError):
                pass  # dropped mid-write: acceptable rejection
            finally:
                w.close()
        # the broker still routes for well-formed clients
        await nc.publish("alive", b"yes")
        msg = await sub.next_msg(timeout=5)
        assert msg.payload == b"yes"
        await nc.close()
    finally:
        await broker.stop()

"""CLI entry-point smoke tests: the four subcommands parse, --help works,
and publish/chat drive a real broker end-to-end (the reference's README
flow, minus the external binaries)."""

import subprocess
import sys


def run_cli(*args, timeout=60):
    return subprocess.run(
        [sys.executable, "-m", "nats_llm_studio_tpu", *args],
        capture_output=True, text=True, timeout=timeout,
    )


def test_help_lists_subcommands():
    r = run_cli("--help")
    assert r.returncode == 0
    for cmd in ("serve", "broker", "publish", "chat"):
        assert cmd in r.stdout


def test_subcommand_help():
    for cmd in ("serve", "broker", "publish", "chat"):
        r = run_cli(cmd, "--help")
        assert r.returncode == 0, r.stderr


def test_unknown_subcommand_fails_cleanly():
    r = run_cli("frobnicate")
    assert r.returncode != 0
    assert "invalid choice" in r.stderr

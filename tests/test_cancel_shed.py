"""Request cancellation + overload shedding (VERDICT r4 missing #1/#2).

The Go reference cancels the in-flight engine call when the chat context
expires (/root/reference/nats_llm_studio.go:328, :158-167); our analog is a
cancel signal from submit_batched's exit path into the batcher owner thread.
Overload: the admit queue is depth/age-bounded and sheds with an honest
BatcherOverloaded instead of queueing silently (the r4 bench measured a
38.6 s p95 admit delay with zero rejections).
"""

import asyncio
import time

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve.batcher import BatcherOverloaded, ContinuousBatcher

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


async def _wait_for(pred, timeout=10.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


@async_test
async def test_generator_close_frees_slot(model):
    """Closing the token stream mid-generation (client disconnect) must free
    the batcher slot within ~one burst instead of decoding to max_tokens."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=60)  # would run ~60 steps
        agen = b.submit_batched([1, 2, 3], sp)
        got = 0
        async for batch in agen:
            got += len(batch)
            if got >= 2:
                break
        await agen.aclose()  # GeneratorExit -> finally -> cancel
        await _wait_for(
            lambda: all(s is None for s in b._slots) and b.stats.cancelled == 1,
            what="slot freed after close",
        )
        # far fewer steps than a full run: the slot did not decode to 60
        assert b.stats.tokens < 40, b.stats.snapshot()
        # the batcher still serves new requests afterwards
        out = [t async for t in b.submit([4, 5], SamplingParams(temperature=0.0, max_tokens=3))]
        assert len(out) == 3
    finally:
        b.stop()


@async_test
async def test_consumer_task_cancellation_frees_slot(model):
    """asyncio cancellation (the worker's chat deadline) propagating through
    submit_batched's await must run the finally and free the slot — the
    in-process analog of the Go ctx cancelling the HTTP call."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        started = asyncio.Event()

        async def consume():
            sp = SamplingParams(temperature=0.0, max_tokens=60)
            async for _batch in b.submit_batched([7, 8, 9], sp):
                started.set()

        task = asyncio.create_task(consume())
        await started.wait()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        await _wait_for(
            lambda: all(s is None for s in b._slots) and b.stats.cancelled == 1,
            what="slot freed after task cancel",
        )
    finally:
        b.stop()


@async_test
async def test_cancel_before_admit_drops_from_queue(model):
    """A request cancelled while still queued (slot-starved) must be dropped
    at intake/waitlist, never admitted."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64])
    try:
        first_toks: list[int] = []

        async def occupy():
            sp = SamplingParams(temperature=0.0, max_tokens=56)
            async for t in b.submit([1, 2], sp):
                first_toks.append(t)

        occ = asyncio.create_task(occupy())
        await _wait_for(lambda: len(first_toks) >= 1, what="occupier streaming")

        async def queued():
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            async for _ in b.submit([3, 4], sp):
                pass

        waiter = asyncio.create_task(queued())
        # deterministic: cancel only once the request is visibly waiting
        # (slot-starved), not on a sleep that races the occupier's finish
        await _wait_for(lambda: b._wl_len == 1, what="request in waitlist")
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        await occ
        await _wait_for(lambda: b.stats.cancelled == 1, what="queued cancel counted")
        assert b.stats.requests == 1  # the cancelled request was never admitted
    finally:
        b.stop()


@async_test
async def test_depth_bound_sheds_at_submit(model):
    """Past max_queue waiting requests, submit fails fast with
    BatcherOverloaded so the caller can retry on a queue-group peer."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64], max_queue=2
    )
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            return [t async for t in b.submit(p, sp)]

        results = await asyncio.gather(
            *[run([i + 1, i + 2]) for i in range(6)], return_exceptions=True
        )
        shed = [r for r in results if isinstance(r, BatcherOverloaded)]
        served = [r for r in results if isinstance(r, list)]
        assert shed, results  # the bound actually fired
        assert served and all(len(r) == 4 for r in served)
        assert b.stats.shed == len(shed), b.stats.snapshot()
    finally:
        b.stop()


@async_test
async def test_age_bound_sheds_stale_waiters(model):
    """A waiter older than max_queue_age_ms is shed with an honest error at
    admit time; the active stream is untouched."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64],
        max_queue_age_ms=1.0,
    )
    try:
        first_toks: list[int] = []

        async def occupy():
            sp = SamplingParams(temperature=0.0, max_tokens=40)
            async for t in b.submit([1, 2], sp):
                first_toks.append(t)

        occ = asyncio.create_task(occupy())
        await _wait_for(lambda: len(first_toks) >= 1, what="occupier streaming")

        with pytest.raises(BatcherOverloaded):
            async for _ in b.submit([3, 4], SamplingParams(temperature=0.0, max_tokens=4)):
                pass
        await occ
        assert len(first_toks) == 40  # occupier unaffected by the shed
        assert b.stats.shed >= 1, b.stats.snapshot()
        snap = b.stats.snapshot()
        assert snap["shed"] >= 1 and "cancelled" in snap
    finally:
        b.stop()

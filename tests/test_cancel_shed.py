"""Request cancellation + overload shedding (VERDICT r4 missing #1/#2).

The Go reference cancels the in-flight engine call when the chat context
expires (/root/reference/nats_llm_studio.go:328, :158-167); our analog is a
cancel signal from submit_batched's exit path into the batcher owner thread.
Overload: the admit queue is depth/age-bounded and sheds with an honest
BatcherOverloaded instead of queueing silently (the r4 bench measured a
38.6 s p95 admit delay with zero rejections).
"""

import asyncio
import time

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve.batcher import BatcherOverloaded, ContinuousBatcher

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


async def _wait_for(pred, timeout=10.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


@async_test
async def test_generator_close_frees_slot(model):
    """Closing the token stream mid-generation (client disconnect) must free
    the batcher slot within ~one burst instead of decoding to max_tokens."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=60)  # would run ~60 steps
        agen = b.submit_batched([1, 2, 3], sp)
        got = 0
        async for batch in agen:
            got += len(batch)
            if got >= 2:
                break
        await agen.aclose()  # GeneratorExit -> finally -> cancel
        await _wait_for(
            lambda: all(s is None for s in b._slots) and b.stats.cancelled == 1,
            what="slot freed after close",
        )
        # far fewer steps than a full run: the slot did not decode to 60
        assert b.stats.tokens < 40, b.stats.snapshot()
        # the batcher still serves new requests afterwards
        out = [t async for t in b.submit([4, 5], SamplingParams(temperature=0.0, max_tokens=3))]
        assert len(out) == 3
    finally:
        b.stop()


@async_test
async def test_consumer_task_cancellation_frees_slot(model):
    """asyncio cancellation (the worker's chat deadline) propagating through
    submit_batched's await must run the finally and free the slot — the
    in-process analog of the Go ctx cancelling the HTTP call."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        started = asyncio.Event()

        async def consume():
            sp = SamplingParams(temperature=0.0, max_tokens=60)
            async for _batch in b.submit_batched([7, 8, 9], sp):
                started.set()

        task = asyncio.create_task(consume())
        await started.wait()
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        await _wait_for(
            lambda: all(s is None for s in b._slots) and b.stats.cancelled == 1,
            what="slot freed after task cancel",
        )
    finally:
        b.stop()


@async_test
async def test_cancel_before_admit_drops_from_queue(model):
    """A request cancelled while still queued (slot-starved) must be dropped
    at intake/waitlist, never admitted."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64])
    try:
        first_toks: list[int] = []

        async def occupy():
            sp = SamplingParams(temperature=0.0, max_tokens=56)
            async for t in b.submit([1, 2], sp):
                first_toks.append(t)

        occ = asyncio.create_task(occupy())
        await _wait_for(lambda: len(first_toks) >= 1, what="occupier streaming")

        async def queued():
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            async for _ in b.submit([3, 4], sp):
                pass

        waiter = asyncio.create_task(queued())
        # deterministic: cancel only once the request is visibly waiting
        # (slot-starved), not on a sleep that races the occupier's finish
        await _wait_for(lambda: b._wl_len == 1, what="request in waitlist")
        waiter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await waiter
        await occ
        await _wait_for(lambda: b.stats.cancelled == 1, what="queued cancel counted")
        assert b.stats.requests == 1  # the cancelled request was never admitted
    finally:
        b.stop()


@async_test
async def test_depth_bound_sheds_at_submit(model):
    """Past max_queue waiting requests, submit fails fast with
    BatcherOverloaded so the caller can retry on a queue-group peer."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64], max_queue=2
    )
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            return [t async for t in b.submit(p, sp)]

        results = await asyncio.gather(
            *[run([i + 1, i + 2]) for i in range(6)], return_exceptions=True
        )
        shed = [r for r in results if isinstance(r, BatcherOverloaded)]
        served = [r for r in results if isinstance(r, list)]
        assert shed, results  # the bound actually fired
        assert served and all(len(r) == 4 for r in served)
        assert b.stats.shed == len(shed), b.stats.snapshot()
    finally:
        b.stop()


@async_test
async def test_age_bound_sheds_stale_waiters(model):
    """A waiter older than max_queue_age_ms is shed with an honest error at
    admit time; the active stream is untouched."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64],
        max_queue_age_ms=1.0,
    )
    try:
        first_toks: list[int] = []

        async def occupy():
            sp = SamplingParams(temperature=0.0, max_tokens=56)
            async for t in b.submit([1, 2], sp):
                first_toks.append(t)

        occ = asyncio.create_task(occupy())
        # enqueue the waiter as soon as the occupier holds the slot (NOT
        # after its first token): the waiter must age out while the slot is
        # still busy for many bursts, or a submit landing near the
        # occupier's completion gets admitted instead of shed (flaky)
        await _wait_for(lambda: b.stats.requests >= 1, what="occupier admitted")

        with pytest.raises(BatcherOverloaded):
            async for _ in b.submit([3, 4], SamplingParams(temperature=0.0, max_tokens=4)):
                pass
        await occ
        assert len(first_toks) == 56  # occupier unaffected by the shed
        assert b.stats.shed >= 1, b.stats.snapshot()
        snap = b.stats.snapshot()
        assert snap["shed"] >= 1 and "cancelled" in snap
    finally:
        b.stop()


@async_test
async def test_cancel_during_group_chunked_admit(model):
    """A request cancelled while its batched chunked admit is still
    prefilling (slot reserved, not yet installed) must be dropped at first
    delivery — slot freed, no tokens delivered, the OTHER group member
    unaffected."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64],
        prefill_chunk=8, max_group_long=2,
    )
    try:
        longs = [
            [(i * 5 + 1) % cfg.vocab_size for i in range(30)],
            [(i * 9 + 4) % cfg.vocab_size for i in range(27)],
        ]
        keep_toks: list[int] = []

        async def keeper():
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            async for t in b.submit(longs[0], sp):
                keep_toks.append(t)

        victim_streaming = asyncio.Event()

        async def victim():
            # enough tokens that the victim is still mid-stream when the
            # cancel lands even if the reservation window is missed on a
            # fast machine (first burst delivers ~8 of 40)
            sp = SamplingParams(temperature=0.0, max_tokens=40)
            async for _ in b.submit(longs[1], sp):
                victim_streaming.set()

        k = asyncio.create_task(keeper())
        v = asyncio.create_task(victim())
        await asyncio.sleep(0)  # both enqueued -> one chunked group admit
        # cancel while the group admit holds its slot reservations (the
        # _RESERVED placeholders) when observable, else at the victim's
        # first delivered batch — either way the victim is provably
        # unfinished at cancel time, so CancelledError must propagate
        from nats_llm_studio_tpu.serve.batcher import _RESERVED

        await _wait_for(
            lambda: any(s is _RESERVED for s in b._slots)
            or victim_streaming.is_set(),
            what="group admit in flight or victim streaming",
        )
        v.cancel()
        with pytest.raises(asyncio.CancelledError):
            await v
        await k
        assert len(keep_toks) == 5  # group sibling completed normally
        await _wait_for(
            lambda: all(s is None for s in b._slots), what="slots freed"
        )
        assert b.stats.cancelled >= 1, b.stats.snapshot()
    finally:
        b.stop()


@async_test
async def test_submit_after_stop_raises_batcher_stopped(model):
    """A submit that races a drain/stop (e.g. idle-eviction unloading the
    engine) fails fast with BatcherStopped — the shape the registry maps
    to a retry-on-another-worker envelope, never a hang."""
    from nats_llm_studio_tpu.serve.batcher import BatcherStopped

    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        out = [t async for t in b.submit([1, 2], SamplingParams(temperature=0.0, max_tokens=2))]
        assert len(out) == 2
    finally:
        b.stop()
    with pytest.raises(BatcherStopped):
        async for _ in b.submit([3, 4], SamplingParams(temperature=0.0, max_tokens=2)):
            pass

"""Flight recorder + deep debug subjects + retry trace propagation (PR 8).

Unit coverage for obs/recorder.py (ring, interval, windowed dumps, rate
limiting), the acceptance flow — a chaos pump crash must leave a flight
dump whose frames carry the pre-crash queue depth and whose event tail
contains the restart — and the DEBUG_SUBJECTS surface
(lmstudio.debug.snapshot / lmstudio.debug.dump), including agreement
between the snapshot's pool view and the lmstudio_kv_pool_* gauges.
"""

import asyncio
import json

import pytest

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.obs import EVENTS, FlightRecorder
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store.manager import ModelStore
from nats_llm_studio_tpu.transport import (
    EmbeddedBroker,
    RetryPolicy,
    connect,
    envelope_error,
    envelope_ok,
)
from nats_llm_studio_tpu.transport import faults

from conftest import async_test
from fakes import FakeRegistry
from test_faults import MID, _chat_body, _publish_tiny, _wait_for


# -- FlightRecorder units ----------------------------------------------------


def test_ring_capacity_oldest_first_and_counters():
    rec = FlightRecorder(capacity=4, interval_ms=1.0)
    for i in range(6):
        rec.sample({"i": i})
    assert rec.frames_sampled == 6
    assert [f["i"] for f in rec.frames()] == [2, 3, 4, 5]
    assert [f["i"] for f in rec.tail(2)] == [4, 5]
    # every frame is stamped with wall + monotonic time
    assert all("ts" in f and "mono" in f for f in rec.frames())


def test_due_respects_interval():
    rec = FlightRecorder(interval_ms=1000.0)
    assert rec.due(now=100.0)  # nothing sampled yet
    rec.sample({"a": 1}, now=100.0)
    assert not rec.due(now=100.5)
    assert rec.due(now=101.0)


def test_disabled_recorder_is_inert(tmp_path):
    rec = FlightRecorder(enabled=False, dump_dir=str(tmp_path))
    assert not rec.due()
    rec.sample({"a": 1})
    assert rec.frames_sampled == 0 and rec.frames() == []
    assert rec.dump("anything", force=True) is None
    assert list(tmp_path.iterdir()) == []


def test_frames_window_by_monotonic_stamp():
    rec = FlightRecorder(interval_ms=1.0)
    for i in range(5):
        rec.sample({"i": i}, now=100.0 + i)  # mono 100..104
    win = rec.frames(last_s=2.5)  # cutoff 104 - 2.5 = 101.5
    assert [f["i"] for f in win] == [2, 3, 4]


def test_counter_fns_merged_and_exceptions_swallowed():
    def boom():
        raise RuntimeError("nope")

    rec = FlightRecorder(interval_ms=1.0,
                         counter_fns={"good": lambda: 7, "bad": boom})
    rec.sample({"queue_depth": 3})
    (fr,) = rec.frames()
    assert fr["good"] == 7 and fr["queue_depth"] == 3
    assert "bad" not in fr


def test_dump_writes_json_rate_limits_and_force(tmp_path):
    rec = FlightRecorder(interval_ms=1.0, dump_dir=str(tmp_path),
                         engine="acme/x", dump_min_interval_s=60.0)
    for i in range(3):
        rec.sample({"i": i})
    EVENTS.emit("unit_marker", n=1)
    path = rec.dump("kv_pool_exhausted", trace={"trace_id": "t1"},
                    extra={"needed": 2})
    assert path is not None
    doc = json.loads(open(path).read())
    assert doc["reason"] == "kv_pool_exhausted"
    assert doc["engine"] == "acme/x"
    assert [f["i"] for f in doc["frames"]] == [0, 1, 2]
    assert doc["trace"] == {"trace_id": "t1"}
    assert doc["extra"] == {"needed": 2}
    assert any(e["kind"] == "unit_marker" for e in doc["events"])
    # the dump itself is announced on the event ring
    assert any(e["kind"] == "flight_dump" and e["path"] == path
               for e in EVENTS.snapshot(limit=8))
    # within the min interval: suppressed...
    assert rec.dump("kv_pool_exhausted") is None
    # ...unless forced (restart/operator dumps must always land)
    assert rec.dump("engine_restart", force=True) is not None
    assert rec.dumps_written == 2
    assert len(list(tmp_path.glob("flight-*.json"))) == 2


def test_dump_without_dir_returns_none():
    rec = FlightRecorder(interval_ms=1.0)
    rec.sample({"a": 1})
    assert rec.dump("x", force=True) is None


# -- acceptance: chaos pump crash leaves a usable flight dump ----------------


@async_test
async def test_pump_crash_produces_flight_dump_with_precrash_frames(tmp_path):
    """ISSUE 8 acceptance: crash the pump via the chaos harness, let the
    supervisor restart the engine, then assert the engine_restart dump
    exists, its frames carry the pre-crash queue depth, and its event tail
    contains the restart."""
    models = tmp_path / "models"
    dumps = tmp_path / "dumps"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        reg = LocalRegistry(
            ModelStore(models), dtype="float32", max_batch_slots=2,
            max_seq_len=64, restart_backoff_s=0.05, restart_backoff_max_s=0.2,
            max_restarts=10, restart_window_s=60.0,
            obs_recorder=True, obs_recorder_interval_ms=5.0,
            obs_dump_dir=str(dumps),
        )
        worker = Worker(
            WorkerConfig(nats_url=broker.url, supervise_interval_s=0.05,
                         engine_heartbeat_timeout_s=0.0),
            reg,
        )
        await worker.start()
        nc = await connect(broker.url)
        env = json.loads(
            (await nc.request("lmstudio.chat_model", _chat_body("warmup"),
                              timeout=60)).payload
        )
        assert env["ok"] is True, env
        eng = await reg.get_engine(MID)
        rec = eng.batcher.recorder
        assert rec is not None and rec.frames_sampled > 0
        # worker-level counters ride every frame via recorder_counters
        assert "engine_restarts" in rec.tail(1)[0]
        assert "reconnects" in rec.tail(1)[0]

        faults.install(faults.FaultPlan().raise_at(faults.PUMP, 0,
                                                   message="chaos crash"))
        try:
            env = json.loads(
                (await nc.request("lmstudio.chat_model",
                                  _chat_body("victim", max_tokens=40),
                                  timeout=30)).payload
            )
            assert env["ok"] is False and env["retryable"] is True, env
            await _wait_for(lambda: reg.engine_restarts_total >= 1,
                            what="supervisor engine restart")
            await _wait_for(
                lambda: list(dumps.glob("flight-*-engine_restart.json")),
                what="engine_restart flight dump",
            )
        finally:
            faults.clear()

        (path,) = dumps.glob("flight-*-engine_restart.json")
        doc = json.loads(path.read_text())
        assert doc["reason"] == "engine_restart"
        assert doc["engine"] == MID
        assert doc["extra"]["restart_reason"]
        # pre-crash frames made it into the dump, each with queue depth
        assert doc["frames"], "dump has no pre-crash frames"
        assert all("queue_depth" in fr for fr in doc["frames"])
        assert all("active_slots" in fr for fr in doc["frames"])
        # the event tail contains the restart itself
        kinds = [e["kind"] for e in doc["events"]]
        assert "engine_restart" in kinds
        assert "engine_crash" in kinds
        # the crash dump (rate-limit class, unforced) landed too
        assert list(dumps.glob("flight-*-engine_crash.json"))

        # the restarted engine serves again, with a fresh recorder
        env = json.loads(
            (await nc.request(
                "lmstudio.chat_model", _chat_body("after restart"), timeout=60,
                retry=RetryPolicy(max_attempts=10, backoff_s=0.05),
            )).payload
        )
        assert env["ok"] is True, env
        eng2 = await reg.get_engine(MID)
        assert eng2.batcher.recorder is not rec
        await nc.close()
        await worker.drain()
    finally:
        await broker.stop()


# -- debug subjects ----------------------------------------------------------


@async_test
async def test_debug_snapshot_and_dump_subjects(tmp_path):
    models = tmp_path / "models"
    dumps = tmp_path / "dumps"
    _publish_tiny(models)
    broker = await EmbeddedBroker().start()
    try:
        reg = LocalRegistry(
            ModelStore(models), dtype="float32", max_batch_slots=2,
            max_seq_len=64, obs_recorder=True, obs_recorder_interval_ms=5.0,
            obs_dump_dir=str(dumps),
        )
        worker = Worker(
            WorkerConfig(nats_url=broker.url, debug_subjects=True), reg
        )
        await worker.start()
        nc = await connect(broker.url)

        async def req(op, payload):
            msg = await nc.request(f"lmstudio.{op}",
                                   json.dumps(payload).encode(), timeout=30)
            return json.loads(msg.payload)

        env = json.loads(
            (await nc.request("lmstudio.chat_model", _chat_body("warm"),
                              timeout=60)).payload
        )
        assert env["ok"] is True, env
        eng = await reg.get_engine(MID)

        # snapshot with a slot mid-decode: the slot table shows the live
        # request's position and (paged) block table with refcounts
        blocker = asyncio.ensure_future(
            nc.request("lmstudio.chat_model",
                       _chat_body("blocker", max_tokens=40), timeout=60)
        )
        await _wait_for(lambda: any(s is not None for s in eng.batcher._slots),
                        what="blocker admitted")
        resp = await req("debug.snapshot", {})
        assert resp["ok"], resp
        snap = resp["data"]["engines"][MID]
        assert snap["max_slots"] == 2 and snap["queue_depth"] >= 0
        await _wait_for(
            lambda: eng.batcher.debug_snapshot()["slots"],
            what="slot visible in the debug view",
        )
        live = eng.batcher.debug_snapshot()
        (slot,) = live["slots"].values()
        assert slot["pos"] >= 1 and slot["max_tokens"] == 40
        if live["paged"]:
            assert slot["blocks"]
            assert len(slot["block_refcounts"]) == len(slot["blocks"])
            assert all(rc >= 1 for rc in slot["block_refcounts"])
        assert (await blocker).payload  # finish the blocker

        # snapshot's pool view agrees with the lmstudio_kv_pool_* gauges
        # scraped at the same (idle) instant
        snap = (await req("debug.snapshot", {"model": MID}))["data"]["engines"][MID]
        prom = (await nc.request("lmstudio.metrics.prom", b"",
                                 timeout=10)).payload.decode()
        if "pool" in snap:
            gauges = {}
            for ln in prom.splitlines():
                if ln.startswith("lmstudio_kv_pool_blocks"):
                    name = ln.split("{")[0]
                    gauges[name] = float(ln.rsplit(" ", 1)[1])
            assert gauges["lmstudio_kv_pool_blocks_total"] == snap["pool"]["blocks_total"]
            assert gauges["lmstudio_kv_pool_blocks_free"] == snap["pool"]["blocks_free"]
            assert gauges["lmstudio_kv_pool_blocks_shared"] == snap["pool"]["blocks_shared"]
        # recorder surface rides the snapshot
        assert snap["recorder_frames_sampled"] > 0
        assert snap["recorder_tail"]

        # unknown model → error envelope
        resp = await req("debug.snapshot", {"model": "acme/nope"})
        assert not resp["ok"] and "not loaded" in resp["error"]

        # forced dump replies with the written path
        resp = await req("debug.dump", {})
        assert resp["ok"], resp
        path = resp["data"]["dumps"][MID]
        doc = json.loads(open(path).read())
        assert doc["reason"] == "debug_request"
        # model filter misses → honest error, no file
        resp = await req("debug.dump", {"model": "acme/nope"})
        assert not resp["ok"] and "no dump written" in resp["error"]

        await nc.close()
        await worker.drain()
    finally:
        await broker.stop()


@async_test
async def test_debug_subjects_absent_by_default():
    """DEBUG_SUBJECTS off (the default): the subjects are never subscribed,
    so a request simply finds no responder."""
    broker = await EmbeddedBroker().start()
    try:
        worker = Worker(WorkerConfig(nats_url=broker.url), FakeRegistry())
        await worker.start()
        nc = await connect(broker.url)
        for op in ("debug.snapshot", "debug.dump"):
            with pytest.raises(asyncio.TimeoutError):
                await nc.request(f"lmstudio.{op}", b"{}", timeout=0.4)
        await nc.close()
        await worker.drain()
    finally:
        await broker.stop()


# -- retry trace propagation (satellite 3) -----------------------------------


@async_test
async def test_retry_keeps_one_trace_id_with_attempt_tags():
    """RetryPolicy re-issues carry the SAME X-Trace-Id with 1-based
    X-Attempt tags, so the attempts of one logical request share a story."""
    broker = await EmbeddedBroker().start()
    try:
        server = await connect(broker.url)
        seen: list[tuple[str, str]] = []

        async def handler(msg):
            h = msg.headers or {}
            seen.append((h.get("X-Trace-Id", ""), h.get("X-Attempt", "")))
            if len(seen) < 3:
                await msg.respond(envelope_error("busy", retryable=True))
            else:
                await msg.respond(envelope_ok({"served": len(seen)}))

        await server.subscribe("svc.flaky", cb=handler)
        await server.flush()

        nc = await connect(broker.url)
        msg = await nc.request(
            "svc.flaky", b"", timeout=5,
            retry=RetryPolicy(max_attempts=5, backoff_s=0.01),
        )
        env = json.loads(msg.payload)
        assert env["ok"] and env["data"]["served"] == 3
        assert len(seen) == 3
        assert len({tid for tid, _ in seen}) == 1, seen  # one trace id
        assert seen[0][0]  # and it is non-empty
        assert [a for _, a in seen] == ["1", "2", "3"]
        await nc.close()
        await server.close()
    finally:
        await broker.stop()


@async_test
async def test_worker_trace_report_carries_attempt():
    """The worker reads X-Attempt into the Trace, and the response's trace
    report says which attempt of the logical request finally succeeded."""
    broker = await EmbeddedBroker().start()
    try:
        worker = Worker(WorkerConfig(nats_url=broker.url), FakeRegistry())
        await worker.start()
        nc = await connect(broker.url)
        body = json.dumps({
            "model": "fake-echo-1",
            "messages": [{"role": "user", "content": "hi"}],
        }).encode()
        msg = await nc.request(
            "lmstudio.chat_model", body, timeout=10,
            headers={"X-Trace-Id": "feedfacefeedface", "X-Attempt": "3"},
        )
        env = json.loads(msg.payload)
        assert env["ok"], env
        rep = env["data"]["response"]["stats"]["trace"]
        assert rep["trace_id"] == "feedfacefeedface"
        assert rep["attempt"] == 3
        # untagged requests stay attempt-free (shape unchanged)
        msg = await nc.request("lmstudio.chat_model", body, timeout=10)
        rep = json.loads(msg.payload)["data"]["response"]["stats"]["trace"]
        assert "attempt" not in rep
        await nc.close()
        await worker.drain()
    finally:
        await broker.stop()

"""Continuous batcher tests: batched greedy decode must reproduce
single-stream generation exactly; slots admit/release mid-flight;
oversubscription queues (SURVEY.md §7 hard part #5)."""

import asyncio

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import Generator, SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def reference_greedy(cfg, params, prompt, n):
    gen = Generator(params, cfg, max_seq_len=64, buckets=[8, 16, 32, 64])
    sp = SamplingParams(temperature=0.0, max_tokens=n)
    return [t for t, _ in gen.generate(prompt, sp)]


@async_test
async def test_concurrent_greedy_matches_single_stream(model):
    cfg, params = model
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30, 40, 50]]
    want = [reference_greedy(cfg, params, p, 6) for p in prompts]

    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64])
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            return [t async for t in b.submit(p, sp)]

        got = await asyncio.gather(*[run(p) for p in prompts])
        assert list(got) == want
    finally:
        b.stop()


@async_test
async def test_join_mid_generation(model):
    cfg, params = model
    a, c = [1, 2, 3], [4, 5, 6, 7]
    want_a = reference_greedy(cfg, params, a, 8)
    want_c = reference_greedy(cfg, params, c, 8)

    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        got_a: list[int] = []
        got_c: list[int] = []

        async def run_a():
            sp = SamplingParams(temperature=0.0, max_tokens=8)
            async for t in b.submit(a, sp):
                got_a.append(t)

        async def run_c_later():
            while len(got_a) < 2:  # join after A has streamed a couple tokens
                await asyncio.sleep(0.01)
            sp = SamplingParams(temperature=0.0, max_tokens=8)
            async for t in b.submit(c, sp):
                got_c.append(t)

        await asyncio.gather(run_a(), run_c_later())
        assert got_a == want_a
        assert got_c == want_c
    finally:
        b.stop()


@async_test
async def test_oversubscription_queues(model):
    cfg, params = model
    prompts = [[i + 1, i + 2] for i in range(6)]
    want = [reference_greedy(cfg, params, p, 4) for p in prompts]
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            return [t async for t in b.submit(p, sp)]

        got = await asyncio.gather(*[run(p) for p in prompts])
        assert list(got) == want
        assert b.stats.requests == 6
        assert b.stats.peak_active <= 2
    finally:
        b.stop()


@async_test
async def test_stop_ids_and_max_tokens(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        first = reference_greedy(cfg, params, [3, 4], 1)[0]
        sp = SamplingParams(temperature=0.0, max_tokens=8, stop_ids=frozenset({first}))
        out = [t async for t in b.submit([3, 4], sp)]
        assert out == []  # first token is the stop token
        sp2 = SamplingParams(temperature=0.0, max_tokens=3)
        out2 = [t async for t in b.submit([3, 4], sp2)]
        assert len(out2) == 3
    finally:
        b.stop()


@async_test
async def test_prompt_too_long_raises(model):
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=16, buckets=[8, 16])
    try:
        with pytest.raises(ValueError):
            async for _ in b.submit(list(range(1, 20)), SamplingParams()):
                pass
    finally:
        b.stop()


@async_test
async def test_seeded_sampling_reproducible_across_batch_composition(model):
    """A seeded request must reproduce its completion token-for-token no
    matter what else shares the batch (per-row fold_in PRNG)."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=1.5, max_tokens=6, seed=1234)

        async def seeded():
            return [t async for t in b.submit([2, 3, 4], sp)]

        alone = await seeded()
        # same request again, now alongside three noisy neighbours
        noise = SamplingParams(temperature=2.0, max_tokens=12)
        crowd = await asyncio.gather(
            seeded(),
            *[
                _collect(b, [9 + i, 8, 7], noise)
                for i in range(3)
            ],
        )
        assert crowd[0] == alone
    finally:
        b.stop()


async def _collect(b, prompt, sp):
    return [t async for t in b.submit(prompt, sp)]


@async_test
async def test_chunked_prefill_matches_single_shot(model):
    """A prompt longer than prefill_chunk must produce the same greedy
    continuation as the unchunked reference (chunk boundaries exercise the
    start_pos > 0 prefill path)."""
    cfg, params = model
    prompt = [(i * 7 + 3) % cfg.vocab_size for i in range(25)]
    want = reference_greedy(cfg, params, prompt, 6)
    b = ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64], prefill_chunk=8
    )
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=6)
        got = [t async for t in b.submit(prompt, sp)]
        assert got == want
    finally:
        b.stop()


@async_test
async def test_chunked_prefill_interleaves_decode(model):
    """While a long prompt is admitted in chunks, an already-active stream
    must keep receiving tokens — at least one per chunk boundary, not zero
    until the whole prefill finishes (VERDICT round-1 weak #4)."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64], prefill_chunk=8
    )
    try:
        events: list[tuple[str, int]] = []
        sp_a = SamplingParams(temperature=0.0, max_tokens=40)

        async def stream_a():
            async for t in b.submit([1, 2, 3], sp_a):
                events.append(("a", t))

        task_a = asyncio.create_task(stream_a())
        # let A admit and produce a couple of tokens
        while sum(1 for k, _ in events if k == "a") < 2:
            await asyncio.sleep(0.01)
        long_prompt = [(i * 5 + 1) % cfg.vocab_size for i in range(30)]  # 4 chunks

        async def stream_b():
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            async for t in b.submit(long_prompt, sp):
                events.append(("b", t))

        await stream_b()
        await task_a
        # tokens A received after B's admit started but before B's first token
        idx_b = next(i for i, (k, _) in enumerate(events) if k == "b")
        a_before = sum(1 for k, _ in events[:idx_b] if k == "a")
        # B's prompt spans 4 chunks -> >= 3 interleaved decode steps; allow
        # scheduling slack but require genuine interleaving
        assert a_before >= 4, events
        # B's admit interleaved with decode steps that ADVANCED the ring:
        # its output must still match the single-stream reference (catches
        # prefix/ring misalignment, not just scheduling)
        b_toks = [t for k, t in events if k == "b"]
        assert b_toks == reference_greedy(cfg, params, long_prompt, 4)
    finally:
        b.stop()


@async_test
async def test_chunked_prefill_flash_continuation_matches(model):
    """With use_flash_attention on, chunk continuations ride the
    cache-backed flash kernel (interpret mode on CPU) — output must still
    match the dense single-stream reference exactly."""
    cfg, params = model
    fcfg = cfg.with_(use_flash_attention=True)
    prompts = [
        [(i * 7 + 3) % cfg.vocab_size for i in range(25)],
        [(i * 5 + 1) % cfg.vocab_size for i in range(30)],
    ]
    want = [reference_greedy(cfg, params, p, 5) for p in prompts]
    b = ContinuousBatcher(
        params, fcfg, max_slots=2, max_seq_len=64, buckets=[8, 64],
        prefill_chunk=8, max_group_long=2,
    )
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            return [t async for t in b.submit(p, sp)]

        tasks = [asyncio.create_task(run(p)) for p in prompts]
        await asyncio.sleep(0)
        got = await asyncio.gather(*tasks)
        assert list(got) == want
    finally:
        b.stop()


@async_test
async def test_chunked_group_admit_deterministic(model):
    """Concurrent LONG prompts (each > prefill_chunk, mixed lengths across
    chunk boundaries) form ONE batched chunked admit and every stream must
    match the single-stream reference — pins the per-row end-chunk logit
    select, per-row ring shifts, and the batched finish."""
    cfg, params = model
    prompts = [
        [(i * 7 + 3) % cfg.vocab_size for i in range(25)],   # 4 chunks
        [(i * 5 + 1) % cfg.vocab_size for i in range(30)],   # 4 chunks
        [(i * 3 + 2) % cfg.vocab_size for i in range(17)],   # 3 chunks
        [(i * 11 + 5) % cfg.vocab_size for i in range(9)],   # 2 chunks
    ]
    want = [reference_greedy(cfg, params, p, 5) for p in prompts]
    b = ContinuousBatcher(
        params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64],
        prefill_chunk=8, max_group_long=4,
    )
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            return [t async for t in b.submit(p, sp)]

        tasks = [asyncio.create_task(run(p)) for p in prompts]
        await asyncio.sleep(0)  # all enqueued before the owner thread starts
        got = await asyncio.gather(*tasks)
        assert list(got) == want
        assert b.stats.chunked_group_admits >= 2, b.stats.snapshot()
    finally:
        b.stop()


@async_test
async def test_chunked_group_admit_interleaves_and_spares_live_stream(model):
    """A batched chunked admit must (a) keep a live stream decoding at
    chunk boundaries, (b) deliver it NO junk from the reserved rows, and
    (c) produce reference-exact output for the grouped long prompts even
    though interleaved decodes moved the ring mid-admit."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=3, max_seq_len=64, buckets=[8, 64],
        prefill_chunk=8, max_group_long=2,
    )
    try:
        events: list[tuple[str, int]] = []
        sp_a = SamplingParams(temperature=0.0, max_tokens=44)

        async def stream_a():
            async for t in b.submit([1, 2, 3], sp_a):
                events.append(("a", t))

        task_a = asyncio.create_task(stream_a())
        while sum(1 for k, _ in events if k == "a") < 2:
            await asyncio.sleep(0.01)
        longs = [
            [(i * 5 + 1) % cfg.vocab_size for i in range(30)],
            [(i * 9 + 4) % cfg.vocab_size for i in range(27)],
        ]
        want = [reference_greedy(cfg, params, p, 4) for p in longs]

        async def stream_long(tag, p):
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            async for t in b.submit(p, sp):
                events.append((tag, t))

        await asyncio.gather(*(stream_long(f"l{i}", p)
                               for i, p in enumerate(longs)))
        await task_a
        assert b.stats.chunked_group_admits == 2, b.stats.snapshot()
        # (a) live stream kept flowing during the grouped admit
        idx_l = next(i for i, (k, _) in enumerate(events) if k.startswith("l"))
        a_before = sum(1 for k, _ in events[:idx_l] if k == "a")
        assert a_before >= 4, events
        # (b)+(c) exact reference outputs — junk delivery or ring
        # misalignment would break these
        for i, w in enumerate(want):
            assert [t for k, t in events if k == f"l{i}"] == w
        # the live stream's own output is also reference-exact
        assert [t for k, t in events if k == "a"] == reference_greedy(
            cfg, params, [1, 2, 3], 44
        )
    finally:
        b.stop()


@async_test
async def test_group_admit_deterministic(model):
    """Force the batched-admission path deterministically: fill the inbox
    BEFORE starting the owner thread so all requests form one group, and
    check every stream against the single-stream reference (pins the
    per-row offset/placement/last-logit math, including mixed lengths in
    one bucket and pad-rows-repeat-row-0)."""
    cfg, params = model
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30, 40, 50], [2, 4]]
    want = [reference_greedy(cfg, params, p, 5) for p in prompts]
    b = ContinuousBatcher(params, cfg, max_slots=8, max_seq_len=64, buckets=[8, 64])
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            return [t async for t in b.submit(p, sp)]

        # enqueue all submissions in one loop tick; the batcher thread starts
        # on the first submit and drains the inbox as one waitlist -> one
        # grouped admit (5 requests -> mpad 8, 3 pad rows repeating row 0)
        tasks = [asyncio.create_task(run(p)) for p in prompts]
        await asyncio.sleep(0)  # let every submit enqueue before work starts
        got = await asyncio.gather(*tasks)
        assert list(got) == want
        assert b.stats.requests == len(prompts)
        # the batched path must actually have run — without this the test
        # could silently degrade to admit_one coverage on timing changes
        assert b.stats.grouped_admits >= 2, b.stats.snapshot()
    finally:
        b.stop()


@async_test
async def test_wide_group_admit_deterministic(model):
    """max_group_admit above 8 (throughput-tuned deployments): 16 requests
    form ONE [16, bucket] fused admit and every stream still matches the
    single-stream reference; the queue-delay metric records one entry per
    request."""
    cfg, params = model
    prompts = [[i + 1, i + 2, i % 5 + 1] for i in range(16)]
    want = [reference_greedy(cfg, params, p, 4) for p in prompts]
    b = ContinuousBatcher(params, cfg, max_slots=16, max_seq_len=64,
                          buckets=[8, 64], max_group_admit=16)
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=4)
            return [t async for t in b.submit(p, sp)]

        tasks = [asyncio.create_task(run(p)) for p in prompts]
        await asyncio.sleep(0)
        got = await asyncio.gather(*tasks)
        assert list(got) == want
        assert b.stats.grouped_admits >= 9, b.stats.snapshot()  # wide path ran
        assert b.stats.admit_delay_ms.count == len(prompts)
        snap = b.stats.snapshot()
        assert snap["admit_queue_delay_p95_ms"] >= snap["admit_queue_delay_p50_ms"] >= 0.0
    finally:
        b.stop()


@async_test
async def test_ring_wrap_compaction_restores_windows(model):
    """Drive the shared ring past wrap with a live stream, drain to one
    slot, and assert (a) the compaction fired and cleared the wrapped flag,
    (b) the surviving stream's greedy tokens still match the single-stream
    reference — i.e. the on-device roll re-aligned every live row's
    validity window exactly (VERDICT r2 weak #7 recovery path)."""
    cfg, params = model
    S = 256
    cfg = cfg.with_(max_seq_len=S)
    buckets = [8, 16, 32, 64, 128, S]
    long_p, short_p = [1, 2, 3], [4, 5, 6, 7]
    gen = Generator(params, cfg, max_seq_len=S, buckets=buckets)
    want_long = [t for t, _ in gen.generate(long_p, SamplingParams(temperature=0.0, max_tokens=248))]
    want_short = [t for t, _ in gen.generate(short_p, SamplingParams(temperature=0.0, max_tokens=60))]

    # paged=False: this test exercises the legacy ring layout's wrap +
    # compaction machinery, which the paged block pool replaces outright
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=S,
                          buckets=buckets, paged=False)
    try:
        got_long: list[int] = []
        got_short: list[int] = []

        async def run_long():
            # A drives the ring head to ~251; its ~56-token tail after B's
            # trigger gives B several burst-records of margin to overlap
            sp = SamplingParams(temperature=0.0, max_tokens=248)
            async for t in b.submit(long_p, sp):
                got_long.append(t)

        async def run_short_late():
            # join near the wrap with a SMALL pos; survive the wrap (which
            # lands just after A exits), then the compaction re-rolls the
            # ring around B's live window. Trigger at 192/248: late enough
            # that B's 60 tokens span the wrap, early enough that B's admit
            # beats A's exit even when a loaded CI host starves the loop
            while len(got_long) < 192:
                await asyncio.sleep(0.001)
            sp = SamplingParams(temperature=0.0, max_tokens=60)
            async for t in b.submit(short_p, sp):
                got_short.append(t)

        await asyncio.gather(run_long(), run_short_late())
        assert b.stats.peak_active == 2, b.stats.snapshot()  # streams overlapped
        assert b.stats.ring_compactions >= 1, b.stats.snapshot()
        assert b._ring_wrapped is False
        assert got_long == want_long
        assert got_short == want_short
    finally:
        b.stop()


@async_test
async def test_idle_full_prefill_matches(model):
    """An idle engine admits a long prompt through prefill_full (one fresh
    dispatch at a pow2 token bucket, right-padded) instead of chunking.
    Output must equal the single-stream reference at several lengths
    straddling bucket edges, and a FOLLOWING admit while the first stream
    decodes must still be correct (the rolled-in pad junk above n lands on
    future ring slots decode overwrites — never in any validity window).
    Flash is on (interpret-mode kernels on CPU): the shortcut is gated on
    the fresh-flash path, since the dense fallback's [Hq, bucket, S] score
    matrix is exactly what chunking exists to bound."""
    cfg, params = model
    fcfg = cfg.with_(use_flash_attention=True)
    b = ContinuousBatcher(
        params, fcfg, max_slots=2, max_seq_len=64, buckets=[8, 64], prefill_chunk=4
    )
    try:
        for ln in (5, 9, 31, 38):  # bucket edges: 8|16|32|64
            p = [(i * 7 + 3 + ln) % cfg.vocab_size for i in range(ln)]
            want = reference_greedy(cfg, params, p, 5)
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            got = [t async for t in b.submit(p, sp)]
            assert got == want, (ln, got, want)
        # pad-junk check: long idle admit, then a joiner decodes alongside
        p1 = [(i * 5 + 1) % cfg.vocab_size for i in range(21)]  # bucket 32
        p2 = [4, 5, 6]
        want1 = reference_greedy(cfg, params, p1, 16)
        want2 = reference_greedy(cfg, params, p2, 8)
        got1: list[int] = []

        async def first():
            async for t in b.submit(p1, SamplingParams(temperature=0.0, max_tokens=16)):
                got1.append(t)

        t1 = asyncio.create_task(first())
        while len(got1) < 2:
            await asyncio.sleep(0.01)
        got2 = [t async for t in b.submit(p2, SamplingParams(temperature=0.0, max_tokens=8))]
        await t1
        assert got1 == want1
        assert got2 == want2
    finally:
        b.stop()

"""int8 KV cache (ops/kvcache.py, cfg.kv_quant="int8"): quantized-cache
serving must stay numerically faithful and internally consistent.

Tiers: codec roundtrip; forward-vs-fp closeness; EXACT consistency between
chunked prefill / incremental decode and single-shot quantized prefill (the
same values quantize identically wherever they land); batcher greedy vs the
independent Generator oracle, both quantized (the serving hot path: ring
writes, fused admits, rolls, compaction all preserve codes+scales)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nats_llm_studio_tpu.engine.generator import Generator, SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.ops.kvcache import KVQ, quantize_rows
from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

from conftest import async_test


def _cfg(**kw):
    base = dict(n_layers=2, max_seq_len=64, kv_quant="int8")
    base.update(kw)
    return ModelConfig.tiny(**base)


def test_quantize_rows_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5, 16), jnp.float32) * 4.0
    kv = quantize_rows(x)
    assert kv.q.dtype == jnp.int8 and kv.s.shape == (3, 5)
    back = kv.q.astype(jnp.float32) * kv.s[..., None]
    # absmax int8: worst-case error is amax/254 per element
    amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
    assert (np.abs(np.asarray(back) - np.asarray(x)) <= amax / 254 + 1e-7).all()
    # zero rows stay exactly zero (scale guard against /0)
    z = quantize_rows(jnp.zeros((2, 4)))
    assert (np.asarray(z.q) == 0).all()


def test_forward_close_to_fp_cache():
    cfg = _cfg()
    params = init_params(cfg.with_(kv_quant="none"), jax.random.PRNGKey(1))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0, cfg.vocab_size)
    start = jnp.zeros((2,), jnp.int32)

    kf, vf = make_cache(cfg.with_(kv_quant="none"), 2, 32)
    want, _, _ = forward(params, cfg.with_(kv_quant="none"), tokens, kf, vf, start)

    kq, vq = make_cache(cfg, 2, 32)
    assert isinstance(kq, KVQ)
    got, kq, vq = forward(params, cfg, tokens, kq, vq, start)
    # int8 KV is approximate; logits stay close and the argmax agrees
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0.15, atol=0.15)
    assert (np.asarray(got[:, -1].argmax(-1)) == np.asarray(want[:, -1].argmax(-1))).all()


def test_incremental_decode_consistent_with_single_shot():
    """Prefill + per-token decode over the quantized cache must EXACTLY
    match a single-shot quantized prefill of the same sequence: identical
    values quantize identically wherever they are written."""
    cfg = _cfg()
    params = init_params(cfg.with_(kv_quant="none"), jax.random.PRNGKey(3))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (1, 12), 0, cfg.vocab_size)

    k1, v1 = make_cache(cfg, 1, 32)
    want, _, _ = forward(params, cfg, tokens, k1, v1, jnp.zeros((1,), jnp.int32))

    k2, v2 = make_cache(cfg, 1, 32)
    logits, k2, v2 = forward(params, cfg, tokens[:, :6], k2, v2,
                             jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(logits[:, -1]), np.asarray(want[:, 5]),
                               rtol=2e-5, atol=2e-5)
    for i in range(6, 12):
        logits, k2, v2 = forward(params, cfg, tokens[:, i : i + 1], k2, v2,
                                 jnp.full((1,), i, jnp.int32))
        np.testing.assert_allclose(
            np.asarray(logits[:, -1]), np.asarray(want[:, i]),
            rtol=2e-5, atol=2e-5, err_msg=f"pos {i}",
        )


@async_test
async def test_batcher_quantized_matches_generator_oracle():
    """The serving hot path end-to-end on a quantized cache: ring-aligned
    fused admits, batched decode, rolls — greedy tokens must equal the
    naive Generator's, itself running the same quantized math."""
    cfg = _cfg()
    params = init_params(cfg.with_(kv_quant="none"), jax.random.PRNGKey(5))
    prompts = [[1, 2, 3], [9, 8, 7, 6], [5], [10, 20, 30]]

    gen = Generator(params, cfg, max_seq_len=64, buckets=[8, 64])
    want = [
        [t for t, _ in gen.generate(p, SamplingParams(temperature=0.0, max_tokens=6))]
        for p in prompts
    ]

    b = ContinuousBatcher(params, cfg, max_slots=4, max_seq_len=64, buckets=[8, 64])
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            return [t async for t in b.submit(p, sp)]

        got = await asyncio.gather(*(run(p) for p in prompts))
        assert list(got) == want
    finally:
        b.stop()


@async_test
async def test_ring_compaction_quantized():
    """Wrap + compaction on the quantized ring: the roll must move codes
    AND scales together (a mismatch would corrupt every surviving row)."""
    cfg = _cfg(max_seq_len=256)
    params = init_params(cfg.with_(kv_quant="none"), jax.random.PRNGKey(6))
    buckets = [8, 16, 32, 64, 128, 256]
    gen = Generator(params, cfg, max_seq_len=256, buckets=buckets)
    want_long = [t for t, _ in gen.generate([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=248))]
    want_short = [t for t, _ in gen.generate([4, 5, 6, 7], SamplingParams(temperature=0.0, max_tokens=60))]

    # paged=False: ring wrap/compaction is legacy-layout machinery; the
    # paged pool never rolls (tested in test_paged_kv.py instead)
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=256,
                          buckets=buckets, paged=False)
    try:
        got_long, got_short = [], []

        async def run_long():
            sp = SamplingParams(temperature=0.0, max_tokens=248)
            async for t in b.submit([1, 2, 3], sp):
                got_long.append(t)

        async def run_short_late():
            while len(got_long) < 220:
                await asyncio.sleep(0.002)
            sp = SamplingParams(temperature=0.0, max_tokens=60)
            async for t in b.submit([4, 5, 6, 7], sp):
                got_short.append(t)

        await asyncio.gather(run_long(), run_short_late())
        assert b.stats.peak_active == 2
        assert b.stats.ring_compactions >= 1
        assert got_long == want_long
        assert got_short == want_short
    finally:
        b.stop()


@async_test
async def test_chunked_flash_kvq_continuation_matches_oracle():
    """Chunked prefill with use_flash_attention + int8 KV routes chunk
    continuations through the quantized chunk kernel
    (flash_attention_chunk_kvq, per-tile VMEM dequant) — greedy output must
    still equal the Generator oracle running the same quantized math
    through the dense path."""
    cfg = _cfg(use_flash_attention=True)
    params = init_params(cfg.with_(kv_quant="none"), jax.random.PRNGKey(5))
    # > prefill_chunk so continuations run; group of 2 exercises the
    # batched [m, C] chunk dispatch too
    prompts = [
        [(i * 7 + 3) % cfg.vocab_size for i in range(25)],
        [(i * 5 + 1) % cfg.vocab_size for i in range(30)],
    ]
    gen = Generator(params, cfg.with_(use_flash_attention=False),
                    max_seq_len=64, buckets=[8, 64])
    want = [
        [t for t, _ in gen.generate(p, SamplingParams(temperature=0.0, max_tokens=5))]
        for p in prompts
    ]
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64,
                          buckets=[8, 64], prefill_chunk=8, max_group_long=2)
    try:
        async def run(p):
            sp = SamplingParams(temperature=0.0, max_tokens=5)
            return [t async for t in b.submit(p, sp)]

        tasks = [asyncio.create_task(run(p)) for p in prompts]
        await asyncio.sleep(0)
        got = await asyncio.gather(*tasks)
        assert list(got) == want
    finally:
        b.stop()

"""End-to-end deadline propagation + adaptive brownout (ISSUE 5).

Deadlines: request()/request_stream() stamp the caller's budget as
``X-Deadline-Ms``; the worker converts it to a monotonic deadline and the
batcher (serve/batcher.py) sheds expired requests BEFORE prefill — at
submit and at admit — and cooperatively aborts mid-decode slots whose
deadline passes, all with retryable envelopes cause-tagged ``deadline``.

Brownout: serve/brownout.py degrades service under overload instead of
falling over — NORMAL → BROWNOUT → SHED_ONLY with hysteresis on queue
depth / queue-age p95 / HBM headroom, pausing spec decode, shrinking the
decode burst, and tightening the admit limit per level.
"""

import asyncio
import contextlib
import time

import jax
import pytest

from nats_llm_studio_tpu.engine.generator import SamplingParams
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import init_params
from nats_llm_studio_tpu.obs import EVENTS
from nats_llm_studio_tpu.serve.batcher import (
    BatcherOverloaded,
    ContinuousBatcher,
    _Request,
)
from nats_llm_studio_tpu.serve.brownout import (
    BROWNOUT,
    NORMAL,
    SHED_ONLY,
    BrownoutConfig,
    BrownoutController,
)
from nats_llm_studio_tpu.transport.envelope import (
    deadline_header_value,
    deadline_remaining_s,
    error_is_retryable,
)

from conftest import async_test


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny(n_layers=2, max_seq_len=64)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


async def _wait_for(pred, timeout=10.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


# -- deadline header (transport/envelope.py) ---------------------------------


def test_deadline_header_round_trip():
    """A stamped budget comes back within clock-read slop; garbage or an
    absent header degrades to None (never fails a servable request)."""
    v = deadline_header_value(5.0)
    remaining = deadline_remaining_s(v)
    assert remaining is not None and 4.5 < remaining <= 5.0
    # an already-expired budget parses as negative, not None: the serving
    # path must SEE the expiry to shed it retryably rather than ignore it
    past = deadline_remaining_s(deadline_header_value(-3.0))
    assert past is not None and past < 0
    assert deadline_remaining_s(None) is None
    assert deadline_remaining_s("") is None
    assert deadline_remaining_s("not-a-number") is None


# -- BrownoutController (serve/brownout.py) ----------------------------------


def test_brownout_escalates_immediately_and_deescalates_with_dwell():
    cfg = BrownoutConfig(depth_hi=0.75, depth_lo=0.40, age_hi_ms=1500.0,
                         age_lo_ms=500.0, dwell_s=2.0)
    bo = BrownoutController(cfg, engine="t")
    t = 100.0
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=t) == NORMAL
    # one hot signal escalates on the very next tick (no dwell going up)
    assert bo.update(depth_frac=0.8, age_p95_ms=0.0, now=t + 0.1) == BROWNOUT
    # calm must hold CONTINUOUSLY for dwell_s before stepping back down
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=t + 1.0) == BROWNOUT
    # a hot blip resets the dwell clock
    assert bo.update(depth_frac=0.5, age_p95_ms=0.0, now=t + 2.0) == BROWNOUT
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=t + 3.0) == BROWNOUT
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=t + 4.0) == BROWNOUT
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=t + 5.1) == NORMAL
    assert bo.transitions == 2


def test_brownout_shed_only_edge_and_stepwise_recovery():
    cfg = BrownoutConfig(depth_hi=0.5, shed_only_scale=1.5, dwell_s=1.0)
    bo = BrownoutController(cfg, engine="t")
    # pressure past hi*scale jumps straight to SHED_ONLY
    assert bo.update(depth_frac=0.9, age_p95_ms=0.0, now=10.0) == SHED_ONLY
    # recovery is one level per dwell, not a cliff back to NORMAL
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=11.0) == SHED_ONLY
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=12.1) == BROWNOUT
    assert bo.update(depth_frac=0.1, age_p95_ms=0.0, now=13.2) == NORMAL
    # hbm headroom below the floor is an escalation signal on its own
    # (0.04 is under the 0.05 floor but above the shed-only-scaled 0.033
    # mark, so it browns out without jumping straight to shed-only)
    assert bo.update(depth_frac=0.0, age_p95_ms=0.0,
                     hbm_headroom_frac=0.04, now=14.0) == BROWNOUT
    # headroom through the floor even at the scaled mark: SHED_ONLY
    assert bo.update(depth_frac=0.0, age_p95_ms=0.0,
                     hbm_headroom_frac=0.01, now=15.0) == SHED_ONLY


def test_brownout_levers():
    bo = BrownoutController(BrownoutConfig(tighten_frac=0.5), engine="t")
    assert not bo.pause_spec and not bo.pause_prefix_harvest
    assert bo.effective_burst(8) == 8
    assert bo.effective_queue_limit(32) == 32
    bo.level = BROWNOUT
    assert bo.pause_spec and bo.pause_prefix_harvest
    assert bo.effective_burst(8) == 4
    assert bo.effective_queue_limit(32) == 16
    assert bo.effective_queue_limit(0) == 0  # zero-disables convention holds
    bo.level = SHED_ONLY
    assert bo.effective_burst(8) == 1
    assert bo.effective_queue_limit(1) == 1  # never tightened below 1


def test_brownout_transitions_hit_the_event_ring():
    seq0 = EVENTS.emitted
    bo = BrownoutController(BrownoutConfig(depth_hi=0.5, dwell_s=0.5),
                            engine="ring-test")
    bo.update(depth_frac=0.6, age_p95_ms=0.0, now=1.0)
    bo.update(depth_frac=0.0, age_p95_ms=0.0, now=2.0)
    bo.update(depth_frac=0.0, age_p95_ms=0.0, now=2.6)
    evs = [e for e in EVENTS.snapshot(kind="brownout")
           if e["seq"] >= seq0 and e.get("engine") == "ring-test"]
    assert [e["level_name"] for e in evs] == ["brownout", "normal"]
    assert evs[0]["reasons"] == ["depth"] and evs[0]["prev"] == "normal"


# -- batcher: deadline shed/abort (serve/batcher.py) -------------------------


@async_test
async def test_expired_deadline_shed_at_submit_without_prefill(model):
    """A request whose budget already ran out at submit is shed immediately
    with a retryable message, cause-tagged ``deadline`` — and never admitted,
    so no prefill work is wasted on it."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=8)
        with pytest.raises(BatcherOverloaded) as ei:
            async for _ in b.submit([1, 2, 3], sp,
                                    deadline=time.monotonic() - 0.5):
                pass
        assert "deadline" in str(ei.value)
        assert error_is_retryable(str(ei.value))
        assert b.stats.shed_cause_counts().get("deadline") == 1
        assert b.stats.requests == 0  # never admitted → no prefill dispatched
        # a deadline-free request afterwards is unaffected
        out = [t async for t in b.submit([4, 5], SamplingParams(
            temperature=0.0, max_tokens=3))]
        assert len(out) == 3
    finally:
        b.stop()


@async_test
async def test_queued_deadline_expiry_sheds_before_prefill(model):
    """A slot-starved waiter whose deadline passes while queued is shed at
    admit time (the queued-side sweep), before any prefill dispatch."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64])
    try:
        first_toks: list[int] = []

        async def occupy():
            sp = SamplingParams(temperature=0.0, max_tokens=56)
            async for t in b.submit([1, 2], sp):
                first_toks.append(t)

        occ = asyncio.create_task(occupy())
        await _wait_for(lambda: b.stats.requests >= 1, what="occupier admitted")

        # valid at submit, expires while waiting for the occupied slot
        with pytest.raises(BatcherOverloaded) as ei:
            async for _ in b.submit([3, 4], SamplingParams(
                    temperature=0.0, max_tokens=4),
                    deadline=time.monotonic() + 0.005):
                pass
        assert "deadline" in str(ei.value)
        assert error_is_retryable(str(ei.value))
        await occ
        assert len(first_toks) == 56  # occupier unaffected by the shed
        assert b.stats.shed_cause_counts().get("deadline") == 1
        assert b.stats.requests == 1  # the shed waiter was never admitted
    finally:
        b.stop()


@async_test
async def test_mid_decode_deadline_abort_frees_slot(model):
    """A slot whose deadline passes mid-decode is cooperatively aborted
    through the consumer-gone cancel path: the consumer gets a retryable
    error, the slot frees within ~one decode burst, and the cancel is
    cause-tagged ``deadline`` (distinct from a client disconnect)."""
    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        sp = SamplingParams(temperature=0.0, max_tokens=60)
        agen = b.submit_batched([1, 2, 3], sp,
                                deadline=time.monotonic() + 300.0)
        poked = False
        with pytest.raises(BatcherOverloaded) as ei:
            async for _batch in agen:
                if poked:
                    continue
                # first delivery: the request is live in a slot — rewrite its
                # deadline to the past so the owner loop's active-side sweep
                # fires deterministically on its next tick
                req = next((s for s in b._slots if isinstance(s, _Request)),
                           None)
                if req is not None:
                    req.deadline = time.monotonic() - 0.001
                    poked = True
        assert "deadline exceeded mid-decode" in str(ei.value)
        assert error_is_retryable(str(ei.value))
        await _wait_for(
            lambda: all(s is None for s in b._slots)
            and b.stats.cancel_causes.get("deadline") == 1,
            what="slot freed with a deadline-tagged cancel",
        )
        assert b.stats.tokens < 40, b.stats.snapshot()  # did not run to 60
        # the batcher still serves afterwards
        out = [t async for t in b.submit([7, 8], SamplingParams(
            temperature=0.0, max_tokens=3))]
        assert len(out) == 3
    finally:
        b.stop()


# -- batcher: brownout under overload ----------------------------------------


@async_test
async def test_brownout_e2e_overload_and_recovery(model):
    """A seeded overload storm against a 1-slot batcher drives the
    controller NORMAL → BROWNOUT (visible in the event ring and the level
    gauge) and back to NORMAL once calm holds for the dwell; every request
    is either served or fails with an honest retryable error."""
    cfg, params = model
    seq0 = EVENTS.emitted
    bo_cfg = BrownoutConfig(
        depth_hi=0.3, depth_lo=0.15, age_hi_ms=1e9, age_lo_ms=1e9,
        dwell_s=0.3, shed_only_scale=100.0,  # keep the storm out of SHED_ONLY
    )
    b = ContinuousBatcher(
        params, cfg, max_slots=1, max_seq_len=64, buckets=[8, 64],
        max_queue=8, brownout=bo_cfg,
    )
    try:
        levels_seen: set[int] = set()

        async def sample_level():
            while True:
                levels_seen.add(b.brownout_level)
                await asyncio.sleep(0.001)

        sampler = asyncio.create_task(sample_level())

        async def client(i: int):
            sp = SamplingParams(temperature=0.0, max_tokens=6)
            return [t async for t in b.submit([i + 1, i + 2], sp)]

        results = await asyncio.gather(
            *[client(i) for i in range(10)], return_exceptions=True
        )
        sampler.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await sampler

        served = [r for r in results if isinstance(r, list)]
        failed = [r for r in results if not isinstance(r, list)]
        assert len(served) + len(failed) == 10  # nobody left unanswered
        assert served and all(len(r) == 6 for r in served)
        for exc in failed:  # every failure is an honest retryable shed
            assert isinstance(exc, BatcherOverloaded), exc
            assert error_is_retryable(str(exc)), exc

        assert max(levels_seen) >= BROWNOUT  # the storm actually browned out
        assert b.brownout.transitions >= 1
        evs = [e for e in EVENTS.snapshot(kind="brownout") if e["seq"] >= seq0]
        assert any(e["level_name"] == "brownout" for e in evs)
        # while browned out the levers were armed: spec paused, burst halved,
        # admit limit tightened (pure functions of the level they reached)
        assert bo_cfg.tighten_frac == 0.5  # default held for this run
        assert b.brownout.effective_queue_limit(8) in (4, 8)

        # recovery: a calm trickle keeps the owner loop ticking (it blocks
        # when fully idle) until the dwell elapses and the level steps down
        t_end = time.monotonic() + 15.0
        while b.brownout_level != NORMAL and time.monotonic() < t_end:
            out = [t async for t in b.submit([1], SamplingParams(
                temperature=0.0, max_tokens=2))]
            assert len(out) == 2
            await asyncio.sleep(0.05)
        assert b.brownout_level == NORMAL
        evs = [e for e in EVENTS.snapshot(kind="brownout") if e["seq"] >= seq0]
        assert any(e["level_name"] == "normal" for e in evs)  # hysteresis ran
        assert not b.brownout.pause_spec  # levers disarm with the level
    finally:
        b.stop()


@async_test
async def test_shed_only_bounces_new_submits_retryably(model):
    """At SHED_ONLY every new submit is shed immediately with a retryable
    message, cause-tagged ``brownout``; already-working requests drain."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64],
        max_queue=8, brownout=BrownoutConfig(),
    )
    try:
        b.brownout.level = SHED_ONLY  # force the level; the tick would clear
        # it only after a calm dwell, giving this assertion a stable window
        with pytest.raises(BatcherOverloaded) as ei:
            async for _ in b.submit([1, 2], SamplingParams(
                    temperature=0.0, max_tokens=2)):
                pass
        assert "brownout shed-only" in str(ei.value)
        assert error_is_retryable(str(ei.value))
        assert b.stats.shed_cause_counts().get("brownout") == 1
    finally:
        b.stop()


@async_test
async def test_shed_only_recovers_while_idle_via_submit_ticks(model):
    """A drained pipeline parks the owner loop on the inbox, so only the
    submit path can tick the controller: sustained calm retries must step
    SHED_ONLY back down instead of bouncing forever (the stuck-brownout
    regression found driving a live worker)."""
    cfg, params = model
    b = ContinuousBatcher(
        params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64],
        max_queue=8, brownout=BrownoutConfig(dwell_s=0.2),
    )
    try:
        b.brownout.level = SHED_ONLY  # as if a storm just drained
        served = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 15.0:
            try:
                async for _ in b.submit([1, 2], SamplingParams(
                        temperature=0.0, max_tokens=2)):
                    pass
                served = True
                break
            except BatcherOverloaded:
                await asyncio.sleep(0.05)
        assert served, "submits still bouncing after 15s of calm retries"
        assert b.brownout.level < SHED_ONLY
    finally:
        b.stop()


# -- prometheus exposition (serve/worker.py) ---------------------------------


@async_test
async def test_prometheus_deadline_and_brownout_families(model):
    """The worker renders lmstudio_deadline_shed_total /
    lmstudio_deadline_aborted_total / lmstudio_brownout_level for every
    loaded engine — zero-valued when quiet, counting once deadlines fire."""
    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.serve.worker import Worker

    cfg, params = model
    b = ContinuousBatcher(params, cfg, max_slots=2, max_seq_len=64, buckets=[8, 64])
    try:
        class _Eng:
            batcher = b

        class _Reg:
            def stats(self):
                return {}

            def loaded_engines(self):
                return {"acme/dl": _Eng()}

        w = Worker(WorkerConfig(), _Reg())
        wid = w.worker_id
        text = w.render_prometheus()
        assert (f'\nlmstudio_deadline_shed_total'
                f'{{model="acme/dl",worker_id="{wid}"}} 0\n') in text
        assert (f'\nlmstudio_deadline_aborted_total'
                f'{{model="acme/dl",worker_id="{wid}"}} 0\n') in text
        assert (f'\nlmstudio_brownout_level'
                f'{{model="acme/dl",worker_id="{wid}"}} 0\n') in text

        # fire one submit-side shed and check the counter + cause label move
        with pytest.raises(BatcherOverloaded):
            async for _ in b.submit([1, 2], SamplingParams(
                    temperature=0.0, max_tokens=2),
                    deadline=time.monotonic() - 1.0):
                pass
        text = w.render_prometheus()
        assert (f'\nlmstudio_deadline_shed_total'
                f'{{model="acme/dl",worker_id="{wid}"}} 1\n') in text
        assert (f'\nlmstudio_batcher_shed_by_cause_total'
                f'{{cause="deadline",model="acme/dl",worker_id="{wid}"}} 1\n') in text
    finally:
        b.stop()

"""Native (C++/ctypes) dequant parity with the NumPy reference."""

import numpy as np
import pytest

from nats_llm_studio_tpu import native
from nats_llm_studio_tpu.gguf import GGMLType, quantize
from nats_llm_studio_tpu.gguf.quants import _DEQUANT, _blocks

RNG = np.random.default_rng(3)


def test_toolchain_builds():
    # g++ is part of the target environment; the native path must come up
    assert native.available()


@pytest.mark.parametrize(
    "ttype", [GGMLType.Q8_0, GGMLType.Q4_0, GGMLType.Q4_K, GGMLType.Q5_K, GGMLType.Q6_K]
)
def test_native_matches_numpy(ttype):
    x = (RNG.standard_normal(8192) * 2.5).astype(np.float32)
    blob = quantize(x, ttype)
    want = _DEQUANT[ttype](_blocks(blob, ttype, x.size)).reshape(-1)
    got = native.dequantize_native(blob, int(ttype), x.size)
    assert got is not None
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_native_handles_positive_offset_kquants():
    x = RNG.uniform(3.0, 4.0, 4096).astype(np.float32)
    blob = quantize(x, GGMLType.Q4_K)
    got = native.dequantize_native(blob, int(GGMLType.Q4_K), x.size)
    np.testing.assert_allclose(got, x, rtol=0.05, atol=0.05)


def test_unsupported_type_returns_none():
    assert native.dequantize_native(b"\x00" * 64, 999, 32) is None

"""70B load-path dress rehearsal (VERDICT r3 missing #3).

BASELINE.md config 3 claims Llama-3-70B fits a v5e-8 with int8 weights; the
pieces (streaming loader, memory planner) are individually tested, but this
test exercises the COMBINATION the claim depends on: a split multi-shard
GGUF with TRUE 70B per-layer geometry (d_model 8192, d_ff 28672, 64 query /
8 KV heads of dim 128 — the exact Meta-Llama-3-70B block shape) at reduced
layer count, streamed tensor-by-tensor through ``load_params_sharded`` onto
the 8-device mesh with ``quant="int8"``, with MEASURED per-device bytes
checked against ``parallel.memory.estimate_device_bytes`` and extrapolated
to the full 80-layer model.

Weights are zeros: byte accounting depends on shapes/dtypes only, and zero
tensors make the multi-GB fixture cheap to write and quantize. The fixture
ships as Q8_0 (what 70B-class public checkpoints actually use) across two
shards in the llama.cpp gguf-split layout (mirrors reference capability:
`lms get` pulls any-size models, nats_llm_studio.go:46-59).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from nats_llm_studio_tpu.gguf import open_gguf
from nats_llm_studio_tpu.gguf.constants import GGMLType
from nats_llm_studio_tpu.gguf.writer import GGUFWriter
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.ops.wquant import QTensor
from nats_llm_studio_tpu.parallel import build_mesh
from nats_llm_studio_tpu.parallel.loader import load_params_sharded
from nats_llm_studio_tpu.parallel.memory import estimate_device_bytes

# true Meta-Llama-3-70B block geometry; vocab reduced (embedding table size
# is linear in vocab and extrapolated separately below), layers reduced 80->2
D, FF, HQ, HKV, HD = 8192, 28672, 64, 8, 128
TEST_VOCAB, TEST_L = 2048, 2
TRUE_VOCAB, TRUE_L = 128256, 80

CFG_TEST = ModelConfig(
    arch="llama", vocab_size=TEST_VOCAB, d_model=D, n_layers=TEST_L,
    n_heads=HQ, n_kv_heads=HKV, head_dim=HD, d_ff=FF,
    rope_theta=500000.0, max_seq_len=8192, dtype="bfloat16",
)
CFG_70B = CFG_TEST.with_(vocab_size=TRUE_VOCAB, n_layers=TRUE_L)


def _zeros(*shape) -> np.ndarray:
    return np.zeros(shape, np.float32)


def _write_70b_split(tmp_path, n_shards: int = 2):
    """Emit the shard set directly (per-tensor, no full-tree
    materialization — the property the real 70B path needs on the writer
    side too). Shard 1 carries the metadata + embeddings + layer 0;
    shard 2 carries layer 1."""
    md = {
        "general.architecture": "llama",
        "general.name": "llama70b-rehearsal",
        "llama.block_count": TEST_L,
        "llama.embedding_length": D,
        "llama.attention.head_count": HQ,
        "llama.attention.head_count_kv": HKV,
        "llama.attention.key_length": HD,
        "llama.feed_forward_length": FF,
        "llama.rope.freq_base": 500000.0,
        "llama.context_length": 8192,
        "llama.vocab_size": TEST_VOCAB,
    }
    n_tensors = 3 + TEST_L * 9

    def layer_tensors(w: GGUFWriter, i: int) -> None:
        pre = f"blk.{i}"
        w.add_tensor(f"{pre}.attn_norm.weight", _zeros(D), GGMLType.F32)
        w.add_tensor(f"{pre}.ffn_norm.weight", _zeros(D), GGMLType.F32)
        # stored [out, in] like llama.cpp writes
        w.add_tensor(f"{pre}.attn_q.weight", _zeros(HQ * HD, D), GGMLType.Q8_0)
        w.add_tensor(f"{pre}.attn_k.weight", _zeros(HKV * HD, D), GGMLType.Q8_0)
        w.add_tensor(f"{pre}.attn_v.weight", _zeros(HKV * HD, D), GGMLType.Q8_0)
        w.add_tensor(f"{pre}.attn_output.weight", _zeros(D, HQ * HD), GGMLType.Q8_0)
        w.add_tensor(f"{pre}.ffn_gate.weight", _zeros(FF, D), GGMLType.Q8_0)
        w.add_tensor(f"{pre}.ffn_up.weight", _zeros(FF, D), GGMLType.Q8_0)
        w.add_tensor(f"{pre}.ffn_down.weight", _zeros(D, FF), GGMLType.Q8_0)

    paths = []
    for i in range(n_shards):
        p = tmp_path / f"llama70b-{i + 1:05d}-of-{n_shards:05d}.gguf"
        w = GGUFWriter(p)
        shard_md = dict(md) if i == 0 else {"general.architecture": "llama"}
        shard_md |= {"split.no": i, "split.count": n_shards,
                     "split.tensors.count": n_tensors}
        w.add_dict(shard_md)
        if i == 0:
            w.add_tensor("token_embd.weight", _zeros(TEST_VOCAB, D), GGMLType.Q8_0)
            w.add_tensor("output_norm.weight", _zeros(D), GGMLType.F32)
            w.add_tensor("output.weight", _zeros(TEST_VOCAB, D), GGMLType.Q8_0)
        layer_tensors(w, i)
        w.write()
        paths.append(p)
    return paths


def _bytes_per_device(params) -> dict[str, int]:
    """Actual committed bytes per device id, from addressable shards."""
    out: dict[str, int] = {}
    for leaf in jax.tree.leaves(
        params, is_leaf=lambda x: isinstance(x, QTensor)
    ):
        arrays = [leaf.q, leaf.s] if isinstance(leaf, QTensor) else [leaf]
        for arr in arrays:
            for sh in arr.addressable_shards:
                key = str(sh.device)
                out[key] = out.get(key, 0) + sh.data.nbytes
    return out


def test_70b_split_load_matches_memory_budget(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    paths = _write_70b_split(tmp_path)
    mesh = build_mesh("tp=8")
    with open_gguf(paths[0]) as r:  # auto-discovers the sibling shard
        assert len(r.tensors) == 3 + TEST_L * 9
        cfg_rt = ModelConfig.from_gguf_metadata(r.metadata).with_(dtype="bfloat16")
        assert (cfg_rt.d_model, cfg_rt.d_ff, cfg_rt.n_heads, cfg_rt.n_kv_heads) == (
            D, FF, HQ, HKV,
        )
        params = load_params_sharded(r, cfg_rt, mesh, quant="int8")

    per_dev = _bytes_per_device(params)
    assert len(per_dev) == 8
    measured = max(per_dev.values())
    # replicated-vs-sharded asymmetry between devices must be tiny
    assert max(per_dev.values()) - min(per_dev.values()) < (16 << 20)

    budget = estimate_device_bytes(CFG_TEST, {"tp": 8}, quant="int8")["params"]
    # the planner must agree with what the loader actually committed
    assert abs(measured - budget) / budget < 0.05, (measured, budget)

    # --- extrapolate the MEASURED bytes to the full 80-layer, 128k-vocab
    # model and check the BASELINE config-3 claim: fits 16 GB/chip with
    # room for cache+workspace ------------------------------------------
    blocks_bytes = max(_bytes_per_device({"blocks": params["blocks"]}).values())
    nonlayer_bytes = measured - blocks_bytes
    per_layer = blocks_bytes / TEST_L
    # embed + lm_head scale linearly with vocab; out_norm is negligible
    extrap = nonlayer_bytes * (TRUE_VOCAB / TEST_VOCAB) + TRUE_L * per_layer
    budget70 = estimate_device_bytes(CFG_70B, {"tp": 8}, quant="int8")["params"]
    assert abs(extrap - budget70) / budget70 < 0.05, (extrap, budget70)
    full70 = estimate_device_bytes(
        CFG_70B, {"tp": 8}, quant="int8", batch=8, seq_len=4096,
        cache_dtype_bytes=1,
    )
    assert full70["total"] < 16 * 2**30, full70  # fits a v5e-8 chip

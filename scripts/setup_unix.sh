#!/usr/bin/env bash
# Host bootstrap for nats-llm-studio-tpu (the analog of the reference's
# scripts/setup_unix.sh, which installed LM Studio + nats-server; here both
# roles are served in-tree, so setup is: venv check, .env, dirs, smoke test).
set -euo pipefail

NATS_PORT="${NATS_PORT:-4222}"
MODELS_DIR="${LMSTUDIO_MODELS_DIR:-$HOME/.lmstudio/models}"
STORE_DIR="${NATS_STORE_DIR:-$PWD/nats_data}"

echo "==> nats-llm-studio-tpu setup"

command -v python >/dev/null || { echo "python not found"; exit 1; }
python - <<'EOF'
import importlib, sys
missing = [m for m in ("jax", "numpy") if importlib.util.find_spec(m) is None]
if missing:
    sys.exit(f"missing python deps: {missing} (pip install nats-llm-studio-tpu)")
import jax
print(f"    jax {jax.__version__}, default backend: {jax.default_backend()}")
EOF

mkdir -p "$MODELS_DIR" "$STORE_DIR"
echo "    models dir: $MODELS_DIR"
echo "    broker store: $STORE_DIR"

cat > .env <<EOF
NATS_URL=nats://127.0.0.1:${NATS_PORT}
LMSTUDIO_MODELS_DIR=${MODELS_DIR}
NATS_QUEUE_GROUP=lmstudio-workers
MODEL_BUCKET=llm-models
MAX_BATCH_SLOTS=8
MAX_SEQ_LEN=4096
# TPU_MESH=tp=8            # uncomment to pin a mesh layout
# TPU_QUANT=int8           # weight-only int8 (fits 70B on v5e-8)
# URL_PULL_SCHEMES=https   # schemes pull_model may fetch directly
# JAX_COORDINATOR_ADDRESS= # host:port for multi-host meshes
EOF
echo "    wrote .env"

echo "==> smoke test (embedded broker + worker handshake)"
REPO_DIR="$(cd "$(dirname "$0")/.." && pwd)" python - <<'EOF'
import asyncio
import importlib.util
import os
import sys

if importlib.util.find_spec("nats_llm_studio_tpu") is None:
    sys.path.insert(0, os.environ["REPO_DIR"])  # running from a source checkout

from nats_llm_studio_tpu.config import WorkerConfig
from nats_llm_studio_tpu.serve import Worker
from nats_llm_studio_tpu.serve.registry import LocalRegistry
from nats_llm_studio_tpu.store import JetStreamStoreModule, ModelStore
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect


async def main():
    broker = await EmbeddedBroker().start()
    JetStreamStoreModule(broker).install()
    cfg = WorkerConfig(nats_url=broker.url)
    worker = Worker(cfg, LocalRegistry(ModelStore(cfg.models_dir)))
    await worker.start()
    nc = await connect(broker.url)
    msg = await nc.request("lmstudio.health", b"{}", timeout=5)
    assert b'"ok": true' in msg.payload or b'"ok":true' in msg.payload, msg.payload
    await nc.close()
    await worker.drain()
    await broker.stop()
    print("    health check OK")


asyncio.run(main())
EOF

cat <<'EOF'
==> done. Next:
    python -m nats_llm_studio_tpu serve --embedded-broker          # start serving
    python -m nats_llm_studio_tpu publish <model.gguf> <pub>/<name>
    python -m nats_llm_studio_tpu chat <pub>/<name> "hello" --stream
EOF

"""On-chip ablation: where does chunked-prefill time go at 16k?

Compares, on the same int8-weight 8B geometry with int8 KV:
  single  — one fresh-prefill flash dispatch over [1, T]
  scan    — prefill_scan-style: lax.scan over G chunks per dispatch
  chunks  — one dispatch per [1, C] chunk (the live-stream interleave path)

Usage: python scripts/ablate_chunked.py [T] [C] [G]
"""

import sys
import time
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import LLAMA3_8B, init_params_int8, _sync
from nats_llm_studio_tpu.models.llama import forward, make_cache

T = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
C = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
G = int(sys.argv[3]) if len(sys.argv) > 3 else 8

cfg = LLAMA3_8B.with_(max_seq_len=T, use_flash_attention=True,
                      decode_unroll=True, kv_quant="int8")
params = init_params_int8(cfg)
fwd = partial(forward, cfg=cfg)
n_chunks = T // C


@partial(jax.jit, donate_argnums=(2, 3))
def single(params, tokens, k, v):
    logits, k, v = fwd(params, tokens=tokens, k_cache=k, v_cache=v,
                       start_pos=jnp.zeros((1,), jnp.int32),
                       logit_positions=jnp.full((1,), T - 1, jnp.int32),
                       fresh_prefill=True)
    return logits, k, v


@partial(jax.jit, donate_argnums=(1, 2))
def scan_group(params, k1, v1, tokens, n, j0):
    final0 = jnp.zeros((1, 1, cfg.vocab_size), jnp.float32)

    def body(carry, inp):
        k1, v1, final = carry
        toks, j = inp
        start = j * C
        logits, k1, v1 = fwd(params, tokens=toks, k_cache=k1, v_cache=v1,
                             start_pos=jnp.full((1,), start, jnp.int32),
                             logit_positions=jnp.clip(n - 1 - start, 0, C - 1)[None],
                             uniform_start=True)
        final = jnp.where((n - 1) // C == j, logits, final)
        return (k1, v1, final), None

    (k1, v1, final), _ = jax.lax.scan(
        body, (k1, v1, final0),
        (tokens, j0 + jnp.arange(tokens.shape[0], dtype=jnp.int32)))
    return final, k1, v1


@partial(jax.jit, donate_argnums=(2, 3), static_argnums=(6,))
def one_chunk(params, tokens, k1, v1, start, last_pos, window):
    logits, k1, v1 = fwd(params, tokens=tokens, k_cache=k1, v_cache=v1,
                         start_pos=start, logit_positions=last_pos,
                         uniform_start=True, attn_window=window)
    return logits, k1, v1


def timed(fn, reps=2):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def win_bucket(x):
    w = 1 << max(0, x - 1).bit_length()
    return min(w, T)


tokens = jnp.ones((1, T), jnp.int32)

# single fresh dispatch
k, v = make_cache(cfg, 1, T)
logits, k, v = single(params, tokens, k, v)
_sync(logits)

def run_single():
    global k, v
    logits, k, v = single(params, tokens, k, v)
    _sync(logits)

t_single = timed(run_single)
print(f"single : {t_single:.3f}s  {T / t_single:,.0f} tok/s")

# scan-grouped
tok_g = jnp.ones((G, 1, C), jnp.int32)
def run_scan():
    k1, v1 = make_cache(cfg, 1, T)
    logits = None
    for j0 in range(0, n_chunks, G):
        logits, k1, v1 = scan_group(params, k1, v1, tok_g, jnp.int32(T), jnp.int32(j0))
    _sync(logits)

run_scan()  # compile
t_scan = timed(run_scan)
print(f"scan{G:>3}: {t_scan:.3f}s  {T / t_scan:,.0f} tok/s  ({n_chunks // G} dispatches)")

# per-chunk dispatches (pow2 windows)
tok_c = jnp.ones((1, C), jnp.int32)
wins = sorted({win_bucket(s + C) for s in range(0, T, C)})
def run_chunks():
    k1, v1 = make_cache(cfg, 1, T)
    logits = None
    for j in range(n_chunks):
        start = j * C
        logits, k1, v1 = one_chunk(
            params, tok_c, k1, v1, jnp.full((1,), start, jnp.int32),
            jnp.full((1,), C - 1, jnp.int32), win_bucket(start + C))
    _sync(logits)

run_chunks()  # compile all windows
t_chunks = timed(run_chunks)
print(f"chunks : {t_chunks:.3f}s  {T / t_chunks:,.0f} tok/s  ({n_chunks} dispatches)")

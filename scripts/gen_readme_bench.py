"""Regenerate README.md's benchmark table from a bench artifact, mechanically.

VERDICT r3 and r4 both caught the README quoting stale numbers against the
round's final `BENCH_r*.json`. This script makes that impossible: the table
between `<!-- BENCH:BEGIN -->` and `<!-- BENCH:END -->` is produced from the
artifact's keys only — every number in it greps verbatim out of the JSON.

Usage:
    python bench.py > /tmp/bench.json          # or the driver's BENCH_r0N.json
    python scripts/gen_readme_bench.py /tmp/bench.json [README.md]

Accepts either the raw one-line bench output or the driver wrapper
({"parsed": {...}} / {"tail": "..."}).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

BEGIN = "<!-- BENCH:BEGIN -->"
END = "<!-- BENCH:END -->"


def load_bench(path: str) -> dict:
    raw = json.loads(Path(path).read_text())
    if "detail" in raw:
        return raw
    if isinstance(raw.get("parsed"), dict) and "detail" in raw["parsed"]:
        return raw["parsed"]
    # driver wrapper whose tail holds (a suffix of) the printed line
    tail = raw.get("tail", "")
    start = tail.find('{"metric"')
    if start >= 0:
        return json.loads(tail[start:].strip())
    raise SystemExit(f"{path}: no bench payload found (need 'detail' or 'parsed')")


def _get(d: dict, dotted: str, default=None):
    for part in dotted.split("."):
        if not isinstance(d, dict) or part not in d:
            return default
        d = d[part]
    return d


def render(bench: dict, src_name: str) -> str:
    det = bench["detail"]
    best = bench["metric"].rsplit(".", 1)[-1]  # e.g. "b96"
    head = bench["value"]
    vs = bench["vs_baseline"]

    rows: list[tuple[str, str]] = []
    rows.append((
        "**Llama-3-8B** geometry, batched ring decode (headline, BASELINE "
        "config 2)",
        f"**{head} tok/s/chip** at {best} (`llama3_8b.sweep.{best}`) — "
        f"{vs}× the ≥2000 north star",
    ))

    e2e = det.get("e2e", {})
    if e2e:
        t256 = e2e.get("e2e_tok_s_256")
        frac = f" — {round(100 * t256 / head, 1)}% of the same run's device-scan rate" if t256 else ""
        rows.append((
            f"Served end-to-end over NATS, {e2e.get('e2e_tok_s_clients')} "
            "streaming clients × 256-token streams",
            f"**{t256} tok/s** aggregate (`e2e.e2e_tok_s_256`){frac}",
        ))
        sus = e2e.get("e2e_sustained_tok_s")
        sus_frac = f" = {round(100 * sus / head, 1)}% of device scan" if sus else ""
        rows.append((
            "Same, 128-token streams (round-3-comparable) / closed-loop "
            "sustained",
            f"{e2e.get('e2e_tok_s')} (`e2e.e2e_tok_s`) / {sus} "
            f"(`e2e.e2e_sustained_tok_s`){sus_frac}",
        ))
        rows.append((
            f"TTFT p50, {e2e.get('ttft_clients')} clients, README-shaped "
            "payload",
            f"**{e2e.get('ttft_p50_ms')} ms GROSS** through the benchmark "
            f"tunnel whose measured no-op round trip is "
            f"{e2e.get('transport_rt_ms')} ms (`transport_rt_ms`)",
        ))
        tw = e2e.get("throughput_wave", {})
        rows.append((
            f"TTFT under load ({tw.get('clients')} concurrent clients)",
            f"p50 {tw.get('ttft_p50_ms')} / **p95 {tw.get('ttft_p95_ms')} ms** "
            f"(`throughput_wave`), admit queue delay p95 "
            f"{_get(tw, 'batcher_phase.admit_queue_delay_p95_ms')} ms",
        ))
        ov = e2e.get("overload", {})
        if ov:
            label = f"Sustained overload ({ov.get('clients')} closed-loop clients"
            if ov.get("slots"):
                label += f" vs {ov['slots']} slots"
            if ov.get("admit_age_bound_ms"):
                label += f", {ov['admit_age_bound_ms']:g} ms admit-age bound"
            label += ")"
            rows.append((
                label,
                f"**{ov.get('served_tok_s')} tok/s** served, "
                f"{ov.get('completed')} completed, "
                f"**{ov.get('sheds_observed_by_clients')} shed** with honest "
                f"error envelopes, admit queue delay p95 "
                f"{_get(ov, 'batcher_phase.admit_queue_delay_p95_ms')} ms "
                "(`e2e.overload`) — bounded shedding, not silent queueing",
            ))
        ring = e2e.get("ring_compaction", {})
        if ring and ring.get("ring_compactions"):
            rows.append((
                "Ring compaction under load (wrapped ring re-rolled with a "
                "live stream)",
                f"{ring.get('ring_compactions')} roll, survivor inter-chunk "
                f"gap p50 {ring.get('survivor_gap_pre_roll_p50_ms')} → "
                f"{ring.get('survivor_gap_post_roll_p50_ms')} ms "
                "pre→post roll (`e2e.ring_compaction`)",
            ))

    el = det.get("e2e_long", {})
    if el:
        lw = el.get("long_wave", {})
        rows.append((
            "**Long-context SERVING** (chunked group admission)",
            f"{lw.get('clients')} concurrent **{lw.get('prompt_tokens_each')}"
            f"-token** prompts: TTFT p50 {lw.get('ttft_p50_ms')} ms, "
            f"**{lw.get('prefill_tok_s')} tok/s** served prefill, live "
            f"streams' inter-chunk gap p95 "
            f"{lw.get('interference_gap_p95_ms')} ms (`e2e_long.long_wave`)",
        ))
        xs = el.get("xl_single", {})
        x16 = el.get("xl16_single", {})
        parts = []
        if xs:
            parts.append(
                f"**{xs.get('prompt_tokens')}-token** single: TTFT "
                f"{xs.get('ttft_ms')} ms = {xs.get('prefill_tok_s')} tok/s "
                "(`xl_single`)"
            )
        if x16:
            parts.append(
                f"**{x16.get('prompt_tokens')}-token** single: "
                f"{x16.get('ttft_ms')} ms = {x16.get('prefill_tok_s')} tok/s "
                "(`xl16_single`)"
            )
        if parts:
            rows.append(("XL single prompts served through `chat_model`",
                         "; ".join(parts)))

    lp = det.get("long_prefill", {})
    if lp:
        rows.append((
            f"{lp.get('tokens')}-token single-dispatch flash prefill",
            f"**{lp.get('tok_s')} tok/s** (`long_prefill`)",
        ))

    moe = det.get("moe", {})
    if moe:
        rows.append((
            "MoE on-chip (scaled Mixtral: 8 experts, top-2, int8)",
            f"routed decode **{_get(moe, 'routed.tok_s')} tok/s** at batch "
            f"{_get(moe, 'geometry.batch')}; routed-vs-dense prefill "
            f"speedup **{moe.get('routed_prefill_speedup')}×**, deep "
            f"prefill {_get(moe, 'prefill_deep.routed_speedup')}× "
            "(`moe`) — decode is weight-traffic-bound at b32, so both forms "
            "read all experts and tie there",
        ))
        sb = moe.get("small_batch", {})
        if sb:
            rows.append((
                "MoE small-batch decode (b1 / b4, routed vs dense)",
                f"routed speedup {_get(sb, 'b1.routed_speedup')}× / "
                f"{_get(sb, 'b4.routed_speedup')}×, measured capacity-"
                f"overflow drop fraction "
                f"{_get(sb, 'drop_fraction.decode_b32')} at b32 decode, "
                f"{_get(sb, 'drop_fraction.prefill_4x128')} at prefill "
                "(`moe.small_batch`)",
            ))

    g2 = det.get("granite2b", {})
    if g2:
        rows.append((
            "granite-3.0-2b parity (config 1)",
            f"{g2.get('tok_s')} tok/s/chip at batch 32 (`granite2b`)",
        ))

    lines = [
        BEGIN,
        f"On one TPU v5e chip (random weights, int8 weight-only + int8 KV "
        f"cache; every number below quotes a `{src_name}` key verbatim — "
        "this table is generated by `scripts/gen_readme_bench.py`, do not "
        "edit by hand):",
        "",
        "| Measurement | Result |",
        "|---|---|",
    ]
    lines += [f"| {k} | {v} |" for k, v in rows]
    lines.append(END)
    return "\n".join(lines)


def main() -> None:
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    bench_path = sys.argv[1]
    readme = Path(sys.argv[2] if len(sys.argv) > 2 else
                  Path(__file__).resolve().parent.parent / "README.md")
    bench = load_bench(bench_path)
    text = readme.read_text()
    i, j = text.find(BEGIN), text.find(END)
    if i < 0 or j < 0:
        raise SystemExit(f"{readme}: markers {BEGIN} / {END} not found")
    block = render(bench, Path(bench_path).name)
    readme.write_text(text[:i] + block + text[j + len(END):])
    print(f"rewrote {readme} bench table from {bench_path}")


if __name__ == "__main__":
    main()

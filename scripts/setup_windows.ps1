# Host bootstrap for nats-llm-studio-tpu on Windows (analog of the
# reference's scripts/setup_windows.ps1 — no winget/choco installs needed:
# broker and engine are in-tree).
$ErrorActionPreference = "Stop"

$NatsPort = if ($env:NATS_PORT) { $env:NATS_PORT } else { "4222" }
$ModelsDir = if ($env:LMSTUDIO_MODELS_DIR) { $env:LMSTUDIO_MODELS_DIR } else { "$HOME\.lmstudio\models" }
$StoreDir = if ($env:NATS_STORE_DIR) { $env:NATS_STORE_DIR } else { "$PWD\nats_data" }

Write-Host "==> nats-llm-studio-tpu setup"

python -c "import jax, numpy; print(f'    jax {jax.__version__}, backend: {jax.default_backend()}')"
if ($LASTEXITCODE -ne 0) { throw "python/jax not available (pip install nats-llm-studio-tpu)" }

New-Item -ItemType Directory -Force -Path $ModelsDir | Out-Null
New-Item -ItemType Directory -Force -Path $StoreDir | Out-Null

@"
NATS_URL=nats://127.0.0.1:$NatsPort
LMSTUDIO_MODELS_DIR=$ModelsDir
NATS_QUEUE_GROUP=lmstudio-workers
MODEL_BUCKET=llm-models
MAX_BATCH_SLOTS=8
MAX_SEQ_LEN=4096
# TPU_QUANT=int8
# URL_PULL_SCHEMES=https
"@ | Set-Content -Path ".env"
Write-Host "    wrote .env"

Write-Host "==> done. Next:"
Write-Host "    python -m nats_llm_studio_tpu serve --embedded-broker"
Write-Host "    python -m nats_llm_studio_tpu publish <model.gguf> <pub>/<name>"
Write-Host "    python -m nats_llm_studio_tpu chat <pub>/<name> ""hello"" --stream"

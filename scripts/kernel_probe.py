"""Standalone probe of flash_decode_cache on the real chip: correctness vs
dense, then timing inside a scan (the serving shape)."""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from nats_llm_studio_tpu.ops.flash_attention import flash_decode_cache
from nats_llm_studio_tpu.ops.layers import gqa_attention_hmajor

L, B, HKV, S, D = 40, 8, 8, 1024, 64
HQ = 32

key = jax.random.PRNGKey(0)
kq, kk, kv = jax.random.split(key, 3)
q = jax.random.normal(kq, (B, HQ, D), jnp.bfloat16)
kc = jax.random.normal(kk, (B, L, HKV, S, D), jnp.bfloat16)
vc = jax.random.normal(kv, (B, L, HKV, S, D), jnp.bfloat16)
pos = jnp.asarray([0, 17, 100, 255, 256, 511, 777, 1023], jnp.int32)
scale = D**-0.5

# correctness on-device, layer 3
got = flash_decode_cache(q, kc, vc, jnp.int32(3), pos, scale)
mask = jnp.arange(S)[None, None, :] <= pos[:, None, None]
want = gqa_attention_hmajor(
    q[:, None].astype(jnp.float32),
    kc[:, 3].astype(jnp.float32),
    vc[:, 3].astype(jnp.float32),
    mask,
    scale,
)[:, 0]
err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want)))
print(f"correctness max_abs_err = {err:.5f}", flush=True)

# timing: L sequential calls (as the layer scan does), scanned 32 steps
@jax.jit
def attn_sweep(q, kc, vc, pos):
    def step(acc, l):
        out = flash_decode_cache(q, kc, vc, l, pos, scale)
        return acc + out.astype(jnp.float32).sum(), None

    def outer(carry, _):
        acc, pos = carry
        acc, _ = jax.lax.scan(step, acc, jnp.arange(L, dtype=jnp.int32))
        return (acc * 1e-9, pos), None

    (acc, _), _ = jax.lax.scan(outer, (jnp.float32(0), pos), None, length=32)
    return acc

out = attn_sweep(q, kc, vc, pos)
np.asarray(out)
t0 = time.perf_counter()
out = attn_sweep(q, kc, vc, pos)
np.asarray(out)
dt = (time.perf_counter() - t0) / 32
live_frac = float(jnp.sum(pos + 1)) / (B * S)
print(f"attn-only step: {dt*1e3:.3f} ms  (live fraction {live_frac:.2f}, "
      f"full cache {kc.nbytes*2/1e9:.2f} GB)", flush=True)

"""Gateway e2e smoke: embedded broker + one real-model worker + the OpenAI
HTTP gateway, exercised with raw sockets — one streaming SSE chat and one
JSON-schema constrained completion. Exits non-zero on any broken contract.

CI runs this as its own step; locally:

    JAX_PLATFORMS=cpu python scripts/gateway_smoke.py
"""

import asyncio
import json
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import _export_tiny_gguf  # noqa: E402
from nats_llm_studio_tpu.config import WorkerConfig  # noqa: E402
from nats_llm_studio_tpu.gateway import Gateway  # noqa: E402
from nats_llm_studio_tpu.serve import Worker  # noqa: E402
from nats_llm_studio_tpu.serve.registry import LocalRegistry  # noqa: E402
from nats_llm_studio_tpu.store.manager import ModelStore  # noqa: E402
from nats_llm_studio_tpu.transport import EmbeddedBroker, connect  # noqa: E402

MODEL = "ci/gw-smoke"

# integer/enum-only properties: the compiled language is length-bounded, so
# max_tokens can never truncate the document — validity is guaranteed
SCHEMA = {
    "type": "object",
    "properties": {
        "age": {"type": "integer"},
        "tag": {"enum": ["alpha", "beta"]},
    },
}


async def post_chat(port: int, body: dict) -> tuple[int, dict, bytes]:
    """Raw-socket POST /v1/chat/completions; the gateway answers with
    ``Connection: close``, so the body is simply everything until EOF."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        raw = json.dumps(body).encode()
        writer.write(
            (
                "POST /v1/chat/completions HTTP/1.1\r\nHost: smoke\r\n"
                f"Content-Length: {len(raw)}\r\n\r\n"
            ).encode()
            + raw
        )
        await writer.drain()
        head = await reader.readuntil(b"\r\n\r\n")
        status = int(head.split(b" ", 2)[1])
        lines = head.decode("latin-1").split("\r\n")[1:]
        headers = {}
        for line in lines:
            if ":" in line:
                k, v = line.split(":", 1)
                headers[k.strip().lower()] = v.strip()
        payload = b""
        while True:
            chunk = await reader.read(65536)
            if not chunk:
                break
            payload += chunk
        return status, headers, payload
    finally:
        writer.close()


async def main() -> None:
    with tempfile.TemporaryDirectory() as td:
        models_dir = Path(td) / "models"
        _export_tiny_gguf(models_dir, MODEL)
        broker = await EmbeddedBroker().start()
        worker = Worker(
            WorkerConfig(nats_url=broker.url),
            LocalRegistry(ModelStore(models_dir), dtype="float32"),
        )
        await worker.start()
        nc = await connect(broker.url)
        gw = await Gateway(nc, port=0).start()
        try:
            # 1. streaming SSE chat
            status, headers, payload = await post_chat(gw.port, {
                "model": MODEL,
                "messages": [{"role": "user", "content": "smoke test"}],
                "max_tokens": 8, "temperature": 0.0, "stream": True,
            })
            assert status == 200, (status, payload[:200])
            assert headers.get("content-type") == "text/event-stream", headers
            events = [
                e[len("data: "):]
                for e in payload.decode().split("\n\n")
                if e.startswith("data: ")
            ]
            assert events[-1] == "[DONE]", events[-1]
            chunks = [json.loads(e) for e in events[:-1]]
            text = "".join(
                c["choices"][0]["delta"].get("content", "") for c in chunks
            )
            assert text, "streaming produced no content"
            # random tiny weights rarely emit EOS inside 8 tokens
            fin = chunks[-1]["choices"][0]["finish_reason"]
            assert fin in ("stop", "length"), chunks[-1]
            print(f"streaming ok: {len(chunks)} chunks, {len(text)} chars")

            # 2. constrained (json_schema) completion at temperature > 0:
            # the response MUST be a schema-valid document
            status, _, payload = await post_chat(gw.port, {
                "model": MODEL,
                "messages": [{"role": "user", "content": "emit a person"}],
                "max_tokens": 80, "temperature": 0.9, "seed": 5,
                "response_format": {
                    "type": "json_schema",
                    "json_schema": {"name": "person", "schema": SCHEMA},
                },
            })
            assert status == 200, (status, payload[:200])
            resp = json.loads(payload)
            doc = json.loads(resp["choices"][0]["message"]["content"])
            assert isinstance(doc, dict), doc
            assert isinstance(doc["age"], int), doc
            assert doc["tag"] in ("alpha", "beta"), doc
            assert resp["choices"][0]["finish_reason"] == "stop", resp
            print(f"constrained ok: {resp['choices'][0]['message']['content']}")
        finally:
            await gw.stop()
            await nc.close()
            await worker.drain()
            await broker.stop()
    print("gateway smoke passed")


if __name__ == "__main__":
    asyncio.run(main())

"""Raw HBM bandwidth probes: how fast can this chip actually read the KV
cache in various shapes/paths? Establishes the attention roofline."""

import sys
import time
import functools

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def timeit(name, fn, *args, n=20, nbytes=0):
    out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0].ravel()[0])
    dt = (time.perf_counter() - t0) / n
    print(f"{name:32s}: {dt*1e3:8.3f} ms  {nbytes/dt/1e9:7.1f} GB/s", flush=True)


L, B, H, S, D = 40, 8, 8, 1024, 64
cache = jax.random.normal(jax.random.PRNGKey(0), (L, B, H, S, D), jnp.bfloat16)
NB = cache.nbytes
print(f"cache {NB/1e9:.3f} GB  [L,B,H,S,D]=[{L},{B},{H},{S},{D}] bf16", flush=True)

# 1) XLA full reduce — upper bound for reads of this buffer
timeit("xla sum (whole)", jax.jit(lambda c: jnp.sum(c, dtype=jnp.float32)), cache, nbytes=NB)

# 2) XLA reduce reshaped to 2D
c2 = cache.reshape(L * B * H * S, D)
timeit("xla sum 2d", jax.jit(lambda c: jnp.sum(c, dtype=jnp.float32)), c2, nbytes=NB)

# 3) XLA batched matvec (decode-score shape): [LBH, S, D] x [LBH, D, 8]
c3 = cache.reshape(L * B * H, S, D)
qv = jax.random.normal(jax.random.PRNGKey(1), (L * B * H, D, 8), jnp.bfloat16)
timeit(
    "xla batched matvec",
    jax.jit(lambda c, q: jnp.einsum("nsd,ndg->nsg", c, q, preferred_element_type=jnp.float32).sum()),
    c3, qv, nbytes=NB,
)


# 4) Pallas copy-reduce, block over S rows of one (l,b,h): grid (L*B*H,)
def red_kernel(x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    s = jnp.sum(x_ref[...], dtype=jnp.float32)
    o_ref[...] = o_ref[...] + jnp.broadcast_to(s[None, None], o_ref.shape)


def pallas_reduce(c3, block_rows):
    n = c3.shape[0]
    return pl.pallas_call(
        red_kernel,
        grid=(n // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, c3.shape[1]), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
    )(c3)


flat = cache.reshape(L * B * H * S, D)
for rows in (512, 2048, 8192):
    f = jax.jit(functools.partial(pallas_reduce, block_rows=rows))
    timeit(f"pallas reduce rows={rows}", f, flat, nbytes=NB)

# 5) same but lanes=128 layout (D folded): [*, 128]
flat128 = cache.reshape(L * B * H * S // 2, 128)
for rows in (512, 4096):
    f = jax.jit(functools.partial(pallas_reduce, block_rows=rows))
    timeit(f"pallas reduce128 rows={rows}", f, flat128, nbytes=NB)

# 6) grid-step overhead: tiny blocks, many steps
f = jax.jit(functools.partial(pallas_reduce, block_rows=64))
timeit("pallas reduce rows=64", f, flat[: 64 * 4096], nbytes=64 * 4096 * D * 2)

"""Ablation timings for the decode step on the real chip.

Methodology: the remote-device tunnel costs ~3-5 ms per jit dispatch, so
every variant here runs as a 64-iteration ``lax.scan`` inside ONE jit call —
per-step numbers are pure device time (dispatch amortized to <0.1 ms).

Variants:
  full      - real forward + sample_rows        (the serving decode step)
  greedy    - real forward + argmax only        (isolates the sampler)
  window    - forward with attn_window=128      (isolates KV-cache reads)
  matmuls   - layer matmuls only, no attention  (weight streaming floor)
  attn      - cache write + attention only      (cache bandwidth)
  sampler   - sample_rows on fixed logits       (sampler alone)

Run:  python scripts/ablate_decode.py [batch] [quant]   (quant: none|int8)
"""

from __future__ import annotations

import sys
import time
from functools import partial

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
import numpy as np

from __graft_entry__ import GRANITE_2B
from nats_llm_studio_tpu.engine.sampling import sample_rows
from nats_llm_studio_tpu.models.llama import ensure_lm_head, forward, init_params, make_cache
from nats_llm_studio_tpu.ops.layers import gqa_attention_hmajor, rms_norm, swiglu
from nats_llm_studio_tpu.ops.wquant import mm, quantizable, quantize_weight

STEPS = 64


def _sync(out) -> None:
    np.asarray(jax.tree.leaves(out)[0].ravel()[0])


def scan_bench(name, step, carry, args=(), n_outer=5, extra=""):
    """step: (args, carry) -> carry. Times STEPS iterations inside one jit.
    ``args`` (e.g. params) passes through jit arguments so weights are real
    HBM operands, not baked-in constants."""

    @jax.jit
    def run(args, carry):
        return jax.lax.scan(
            lambda c, _: (step(args, c), None), carry, None, length=STEPS
        )[0]

    out = run(args, carry)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(n_outer):
        out = run(args, out)
    _sync(out)
    dt = (time.perf_counter() - t0) / (n_outer * STEPS)
    print(f"{name:8s}: {dt*1e3:7.3f} ms/step {extra}", flush=True)
    return dt


def main() -> None:
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    quant = sys.argv[2] if len(sys.argv) > 2 else "int8"
    seq = 1024
    cfg = GRANITE_2B
    params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
    if quant == "int8":
        params = {
            k: (quantize_weight(v, device=True) if quantizable(k) and k == "lm_head"
                else v)
            for k, v in params.items()
        }
        params["blocks"] = {
            k: (quantize_weight(v, device=True) if quantizable(k) else v)
            for k, v in params["blocks"].items()
        }
    nbytes = sum(x.nbytes for x in jax.tree.leaves(params))
    print(f"batch={batch} quant={quant} params={nbytes/1e9:.2f} GB", flush=True)

    K, V = make_cache(cfg, batch, seq)
    kv_bytes = K.nbytes + V.nbytes
    fwd = partial(forward, cfg=cfg)
    temp = jnp.full((batch,), 0.8, jnp.float32)
    topk = jnp.zeros((batch,), jnp.int32)
    topp = jnp.ones((batch,), jnp.float32)
    seeds = jnp.arange(batch, dtype=jnp.int32)

    # full: forward + sampler (pos advances each step like real decode)
    def full_step(params, c):
        tok, K, V, pos = c
        logits, K, V = fwd(params, tokens=tok[:, None], k_cache=K, v_cache=V, start_pos=pos)
        nxt = sample_rows(logits[:, -1, :], seeds, pos, temp, topk, topp)
        return (nxt, K, V, pos + 1)

    c0 = (jnp.ones((batch,), jnp.int32), K, V, jnp.full((batch,), 128, jnp.int32))
    dt = scan_bench("full", full_step, c0, args=params)
    print(f"          = {batch/dt:7.1f} tok/s", flush=True)

    def greedy_step(params, c):
        tok, K, V, pos = c
        logits, K, V = fwd(params, tokens=tok[:, None], k_cache=K, v_cache=V, start_pos=pos)
        return (jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32), K, V, pos + 1)

    K, V = make_cache(cfg, batch, seq)
    scan_bench("greedy", greedy_step, (jnp.ones((batch,), jnp.int32), K, V,
                                       jnp.full((batch,), 128, jnp.int32)), args=params)

    def window_step(params, c):
        tok, K, V, pos = c
        logits, K, V = fwd(params, tokens=tok[:, None], k_cache=K, v_cache=V,
                           start_pos=pos, attn_window=256)
        return (jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32), K, V, pos + 1)

    K, V = make_cache(cfg, batch, seq)
    scan_bench("window", window_step, (jnp.ones((batch,), jnp.int32), K, V,
                                       jnp.full((batch,), 128, jnp.int32)), args=params)

    # noattn: full forward structure — cache write + scan threading of the
    # caches as xs/ys — but the attention read replaced by a q passthrough.
    # (full - noattn) = attention read; (noattn - matmuls) = cache threading.
    hq_, hkv_, d_ = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def noattn_step(params, c):
        tok, K, V, pos = c
        x = params["embed"][tok[:, None]].astype(jnp.dtype(cfg.dtype)) * cfg.embedding_scale
        zero = jnp.zeros((), jnp.int32)

        def block(carry, inputs):
            x, K, V = carry
            p, l = inputs
            h = rms_norm(x, p["attn_norm"], cfg.rms_eps)
            q = mm(h, p["wq"]).reshape(batch, 1, hq_, d_)
            k = mm(h, p["wk"]).reshape(batch, 1, hkv_, d_)
            v = mm(h, p["wv"]).reshape(batch, 1, hkv_, d_)

            def write_row(cache_b, rows_b, s):  # cache_b [L,H,S,D]
                return jax.lax.dynamic_update_slice(
                    cache_b, rows_b[None].astype(cache_b.dtype), (l, zero, s, zero)
                )

            K = jax.vmap(write_row)(K, k.transpose(0, 2, 1, 3), pos)
            V = jax.vmap(write_row)(V, v.transpose(0, 2, 1, 3), pos)
            x = x + mm(q.reshape(batch, 1, hq_ * d_), p["wo"]) * cfg.residual_scale
            h = rms_norm(x, p["ffn_norm"], cfg.rms_eps)
            x = x + swiglu(h, p["w_gate"], p["w_up"], p["w_down"]) * cfg.residual_scale
            return (x, K, V), None

        (x, K, V), _ = jax.lax.scan(
            block, (x, K, V),
            (params["blocks"], jnp.arange(cfg.n_layers, dtype=jnp.int32)),
        )
        logits = mm(rms_norm(x, params["out_norm"], cfg.rms_eps), params["lm_head"])
        return (jnp.argmax(logits[:, -1, :], -1).astype(jnp.int32), K, V, pos + 1)

    K, V = make_cache(cfg, batch, seq)
    scan_bench("noattn", noattn_step, (jnp.ones((batch,), jnp.int32), K, V,
                                       jnp.full((batch,), 128, jnp.int32)), args=params)

    # matmuls only (same weights incl lm_head, no attention/cache/embed)
    x0 = jnp.ones((batch, 1, cfg.d_model), jnp.dtype(cfg.dtype))

    def matmul_step(params, x):
        def block(x, p):
            h = rms_norm(x, p["attn_norm"], cfg.rms_eps)
            q = mm(h, p["wq"])
            k = mm(h, p["wk"])
            v = mm(h, p["wv"])
            o = jnp.concatenate([q, k, v], -1)[..., : cfg.n_heads * cfg.head_dim]
            x = x + mm(o, p["wo"]) * cfg.residual_scale
            h = rms_norm(x, p["ffn_norm"], cfg.rms_eps)
            x = x + swiglu(h, p["w_gate"], p["w_up"], p["w_down"]) * cfg.residual_scale
            return x, None

        x, _ = jax.lax.scan(block, x, params["blocks"])
        logits = mm(rms_norm(x, params["out_norm"], cfg.rms_eps), params["lm_head"])
        return x * 0.999 + jnp.sum(logits, dtype=x.dtype) * 1e-12

    scan_bench("matmuls", matmul_step, x0, args=params)

    # attention only: cache write + gqa read over the carried full cache
    # (layout [B, L, Hkv, S, D], same carry structure as models.llama.forward)
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def attn_step(_, c):
        acc, K, V, pos = c
        q = jnp.ones((batch, 1, hq, d), K.dtype) * acc.astype(K.dtype)
        k1 = jnp.ones((batch, hkv, 1, d), K.dtype)
        key_pos = jnp.arange(seq, dtype=jnp.int32)
        mask = key_pos[None, None, :] <= pos[:, None, None]
        zero = jnp.zeros((), jnp.int32)

        def block(carry, l):
            acc, K, V = carry

            def write_row(cache_b, rows_b, s):  # cache_b [L,H,S,D]
                return jax.lax.dynamic_update_slice(cache_b, rows_b[None], (l, zero, s, zero))

            K = jax.vmap(write_row)(K, k1, pos)
            V = jax.vmap(write_row)(V, k1, pos)
            kc = jax.lax.dynamic_slice(
                K, (zero, l, zero, zero, zero), (batch, 1, hkv, seq, d))[:, 0]
            vc = jax.lax.dynamic_slice(
                V, (zero, l, zero, zero, zero), (batch, 1, hkv, seq, d))[:, 0]
            out = gqa_attention_hmajor(q, kc, vc, mask, cfg.attn_scale)
            return (acc + jnp.sum(out, dtype=jnp.float32), K, V), None

        (acc2, K, V), _ = jax.lax.scan(
            block, (jnp.zeros((), jnp.float32), K, V),
            jnp.arange(cfg.n_layers, dtype=jnp.int32))
        return (acc2 * 1e-9, K, V, pos + 1)

    K, V = make_cache(cfg, batch, seq)
    dt = scan_bench("attn", attn_step,
                    (jnp.zeros((), jnp.float32), K, V, jnp.full((batch,), 128, jnp.int32)),
                    extra=f"(cache {kv_bytes/1e9:.2f} GB)")
    print(f"          = {kv_bytes/dt/1e9:7.1f} GB/s cache read", flush=True)

    logits0 = jax.random.normal(jax.random.PRNGKey(1), (batch, cfg.vocab_size), jnp.float32)

    def sampler_step(_, c):
        logits, i = c
        nxt = sample_rows(logits, seeds, i, temp, topk, topp)
        return (logits + nxt[:, None] * 1e-9, i + 1)

    scan_bench("sampler", sampler_step, (logits0, jnp.zeros((batch,), jnp.int32)))


if __name__ == "__main__":
    main()

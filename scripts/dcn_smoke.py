"""Multi-process (DCN-path) smoke: proves `jax.distributed.initialize` +
cross-process mesh actually RUN, not just parse env vars (VERDICT r3 #8).

Two local processes, CPU backend, 4 virtual devices each, one coordinator:
build a global dp=2 x tp=4 mesh spanning both processes, run (a) a psum
over dp inside shard_map and (b) one jitted tiny-llama forward with the
batch dp-sharded and the KV cache sharding-constrained onto the mesh — the
same SPMD program shape `main.py`'s `jax.distributed.initialize` hook
(NATS control plane + XLA collectives tensor plane, SURVEY.md §5) promises
for multi-host. On real multi-host TPU the only change is the coordinator
address and device count; the program is identical.

Usage:
  python scripts/dcn_smoke.py            # launcher: spawns 2 workers
  python scripts/dcn_smoke.py worker N P # internal: worker N, coord port P
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = str(Path(__file__).resolve().parent.parent)


def worker(pid: int, port: str) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    )
    import jax

    # jax can be pre-imported by the interpreter in this image, making the
    # env var too late — force the platform through the config API too
    # (same recipe as tests/conftest.py; without it the ambient tunnel's
    # real TPU platform wins and local_devices() is the one chip)
    jax.config.update("jax_platforms", "cpu")

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    assert len(jax.local_devices()) == 4, jax.local_devices()
    assert len(jax.devices()) == 8, "global device view must span both processes"

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sys.path.insert(0, REPO)
    from nats_llm_studio_tpu.models.config import ModelConfig
    from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
    from nats_llm_studio_tpu.parallel import build_mesh

    mesh = build_mesh("dp=2,tp=4")  # 8 global devices, 4 per process

    # (a) cross-process collective: psum over the dp axis
    from jax import shard_map

    f = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(x, "dp"),
            mesh=mesh,
            in_specs=P("dp", None),
            out_specs=P(None, None),
        ),
        in_shardings=NamedSharding(mesh, P("dp", None)),
    )
    x = jnp.ones((2, 4), jnp.float32)
    out = f(x)
    local = np.asarray(out.addressable_shards[0].data)
    assert np.allclose(local, 2.0), local  # dp=2 ranks of ones summed
    print(f"PSUM_OK {pid}", flush=True)

    # (b) one tiny sharded forward: batch on dp, cache constrained on-mesh
    cfg = ModelConfig.tiny(n_layers=2)
    params = init_params(cfg, jax.random.PRNGKey(0))  # deterministic, replicated
    tokens = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)

    @jax.jit
    def step(params, tokens):
        k, v = make_cache(cfg, 2, 16)
        k = jax.lax.with_sharding_constraint(k, NamedSharding(mesh, P("dp")))
        v = jax.lax.with_sharding_constraint(v, NamedSharding(mesh, P("dp")))
        logits, _, _ = forward(
            params, cfg, tokens, k, v, jnp.zeros((2,), jnp.int32)
        )
        return jax.lax.with_sharding_constraint(logits, NamedSharding(mesh, P()))

    logits = step(params, tokens)
    arr = np.asarray(logits.addressable_shards[0].data)
    assert np.all(np.isfinite(arr))
    # both processes must compute identical replicated logits
    print(f"LOGITS_SUM {pid} {float(np.abs(arr).sum()):.6f}", flush=True)
    jax.distributed.shutdown()


def launch() -> int:
    with socket.socket() as s:  # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = str(s.getsockname()[1])
    procs = [
        subprocess.Popen(
            [sys.executable, __file__, "worker", str(i), port],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={k: v for k, v in os.environ.items()
                 if k not in ("XLA_FLAGS", "JAX_PLATFORMS")},
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    ok = all(p.returncode == 0 for p in procs)
    sums = []
    for i, out in enumerate(outs):
        print(f"--- worker {i} ---\n{out}")
        if f"PSUM_OK {i}" not in out:
            ok = False
        for line in out.splitlines():
            if line.startswith("LOGITS_SUM"):
                sums.append(line.split()[-1])
    if len(sums) != 2 or sums[0] != sums[1]:
        ok = False  # replicated forward diverged across processes
    print("DCN_SMOKE", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        worker(int(sys.argv[2]), sys.argv[3])
    else:
        sys.exit(launch())

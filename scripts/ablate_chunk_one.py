"""Time ONE mid-prompt chunk under variants to isolate the per-chunk cost.

Variants (same 8B int8 geometry):
  fresh      — start=0 flash over [1, C] (no cache read)
  cont_kvq   — continuation at start=S/2, int8 KV chunk kernel, full window
  cont_kvq_w — same with a bounded pow2 window
  cont_bf16  — continuation with a bf16 cache (flash_attention_chunk)
  matmul_ref — model fwd with T=C and NO attention read (fresh at start 0,
               flash, tiny cache) — the pure matmul floor

Usage: python scripts/ablate_chunk_one.py [C] [S]
"""

import sys
import time
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import LLAMA3_8B, init_params_int8, _sync
from nats_llm_studio_tpu.models.llama import forward, make_cache

C = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
S = int(sys.argv[2]) if len(sys.argv) > 2 else 16384


def timed(fn, reps=3):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def report(name, fn):
    fn()  # compile
    t = timed(fn)
    print(f"{name:>11}: {t * 1e3:8.1f} ms")


def run(cfg, start, window=None, fresh=False, seq=None):
    seq = seq or S
    cfgx = cfg.with_(max_seq_len=seq)
    fwd = partial(forward, cfg=cfgx)

    @partial(jax.jit, static_argnums=(4,))
    def prog(params, tokens, k, v, window):
        logits, k, v = fwd(params, tokens=tokens, k_cache=k, v_cache=v,
                           start_pos=jnp.full((1,), start, jnp.int32),
                           logit_positions=jnp.full((1,), C - 1, jnp.int32),
                           fresh_prefill=fresh, uniform_start=not fresh,
                           attn_window=window)
        return logits, k, v

    k, v = make_cache(cfgx, 1, seq)
    tokens = jnp.ones((1, C), jnp.int32)

    def go():
        logits, _, _ = prog(params, tokens, k, v, window)
        _sync(logits)

    return go


base = LLAMA3_8B.with_(max_seq_len=S, use_flash_attention=True,
                       decode_unroll=True, kv_quant="int8")
params = init_params_int8(base)

report("matmul_ref", run(base, 0, fresh=True, seq=max(2 * C, 512)))
report("fresh", run(base, 0, fresh=True))
report("cont_kvq", run(base, S // 2))
report("cont_kvq_w", run(base, S // 2, window=1 << (S // 2 + C - 1).bit_length()))
bf16 = base.with_(kv_quant="none")
report("cont_bf16", run(bf16, S // 2))

"""Benchmark: the north-star metric on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline (BASELINE.md config 2, the metric string itself names the model):
**Llama-3-8B geometry, int8 weight-only, batched ring decode** — batch sweep
{8, 16, 32}, best batch reported. Also measured, in `detail`:

* `e2e` — the SAME 8B engine served end-to-end over the NATS wire
  (`lmstudio.chat_model` streaming, 8 concurrent clients): TTFT p50/p95 and
  aggregate tok/s. This is the honest "via nats req" number.
* `long_prefill` — single-dispatch 16k-token flash prefill (SURVEY §5
  long-context), tok/s and seconds.
* `granite2b` — config-1 parity (the round-1/2 flagship), decode tok/s.

Weights are random (throughput depends on shapes/dtypes, not values); the 8B
bf16 tree would not fit HBM next to its int8 copy, so init streams one leaf
at a time: create bf16 -> quantize on device -> free (peak = int8 model +
one bf16 leaf). Set JAX_PLATFORMS=cpu BENCH_TINY=1 for a smoke run.
"""

from __future__ import annotations

import gc
import json
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x) -> None:
    """Force completion: block_until_ready alone does not flush execution on
    every remote-device transport, a device->host copy does."""
    jax.block_until_ready(x)
    np.asarray(jax.tree.leaves(x)[0])

from nats_llm_studio_tpu.engine.sampling import sample
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.ops.wquant import quantizable, quantize_weight

NORTH_STAR_TOK_S = 2000.0

# Meta-Llama-3-8B-Instruct geometry (BASELINE.md config 2): 32 layers,
# d=4096, ff=14336, GQA 32q/8kv, head_dim 128, vocab 128256, rope 500k.
LLAMA3_8B = ModelConfig(
    arch="llama",
    vocab_size=128256,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    head_dim=128,
    d_ff=14336,
    rope_theta=500000.0,
    max_seq_len=8192,
    dtype="bfloat16",
)


def init_params_int8(cfg: ModelConfig, seed: int = 0):
    """Leaf-streamed random init, quantized on device.

    8B bf16 is ~16 GB — materializing it before quantization would OOM a
    16 GB chip. Each leaf is created and quantized inside one jit program
    (the bf16 original is a program-local transient), then blocked on, so
    peak HBM = int8 model so far + one bf16 leaf.

    Covers the dense no-bias tree only (the schema below mirrors
    models.llama.init_params for that case); guarded so a MoE/attn-bias
    config cannot silently bench an incomplete tree.
    """
    assert not cfg.attn_bias and not cfg.is_moe, (
        "init_params_int8 builds the dense no-bias schema; extend it before "
        f"benching arch={cfg.arch!r} (attn_bias={cfg.attn_bias}, moe={cfg.is_moe})"
    )
    dt = cfg.dtype

    @partial(jax.jit, static_argnums=(1,))
    def _randn(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    @partial(jax.jit, static_argnums=(1,))
    def _randq(k, shape):
        w = (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)
        return quantize_weight(w, device=True)

    key = jax.random.PRNGKey(seed)
    counter = [0]

    def leaf(name: str, *shape):
        counter[0] += 1
        k = jax.random.fold_in(key, counter[0])
        out = _randq(k, shape) if quantizable(name) else _randn(k, shape)
        jax.block_until_ready(out)
        return out

    L, d, hq, hkv, hd, ff = (
        cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
        cfg.head_dim, cfg.d_ff,
    )
    blocks = {
        "attn_norm": jnp.ones((L, d), dt),
        "ffn_norm": jnp.ones((L, d), dt),
        "wq": leaf("wq", L, d, hq * hd),
        "wk": leaf("wk", L, d, hkv * hd),
        "wv": leaf("wv", L, d, hkv * hd),
        "wo": leaf("wo", L, hq * hd, d),
        "w_gate": leaf("w_gate", L, d, ff),
        "w_up": leaf("w_up", L, d, ff),
        "w_down": leaf("w_down", L, ff, d),
    }
    return {
        "embed": leaf("embed", cfg.vocab_size, d),
        "out_norm": jnp.ones((d,), dt),
        "lm_head": leaf("lm_head", d, cfg.vocab_size),
        "blocks": blocks,
    }


# ---------------------------------------------------------------------------
# device-side decode throughput (ring-slot scan, the serving hot path shape)
# ---------------------------------------------------------------------------


def decode_bench(cfg, params, batch, prompt_len, seq_len, steps) -> dict:
    fwd = partial(forward, cfg=cfg)

    # donate the cache: timing reruns prefill into the SAME buffers — a
    # second [B, L, Hkv, S, D] cache next to params would OOM at batch 32
    @partial(jax.jit, donate_argnums=(2, 3))
    def prefill(params, tokens, k, v, start):
        logits, k, v = fwd(
            params, tokens=tokens, k_cache=k, v_cache=v, start_pos=start,
            logit_positions=jnp.full((tokens.shape[0],), tokens.shape[1] - 1, jnp.int32),
        )
        return sample(logits[:, -1, :], jax.random.PRNGKey(1), temperature=0.0), k, v

    def bucket_window(max_pos: int) -> int | None:
        w = -(-(max_pos + 1) // 256) * 256
        return w if w < seq_len else None

    @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(4, 6))
    def decode_n(params, tok, k, v, n, pos0, window):
        """n decode steps as one device-side scan: measures chip throughput
        without per-step host dispatch (the remote-device tunnel costs ~ms
        per call, which would swamp a memory-bound step)."""

        def body(carry, i):
            tok, k, v = carry
            pos = pos0 + i
            logits, k, v = fwd(params, tokens=tok[:, None], k_cache=k, v_cache=v,
                               start_pos=pos, ring_slot=pos[0] % k.shape[3],
                               attn_window=window)
            nxt = sample(logits[:, -1, :], jax.random.PRNGKey(2), temperature=0.0)
            return (nxt, k, v), nxt

        (tok, k, v), toks = jax.lax.scan(body, (tok, k, v), jnp.arange(n, dtype=jnp.int32))
        return tok, k, v, toks

    k, v = make_cache(cfg, batch, seq_len)
    tokens = jnp.ones((batch, prompt_len), jnp.int32)
    start = jnp.zeros((batch,), jnp.int32)

    tok, k, v = prefill(params, tokens, k, v, start)  # compile
    _sync(tok)
    t0 = time.perf_counter()
    tok, k, v = prefill(params, tokens, k, v, start)
    _sync(tok)
    prefill_s = time.perf_counter() - t0

    pos0 = jnp.full((batch,), prompt_len, jnp.int32)
    window = bucket_window(prompt_len + 3 * steps)
    tok, k, v, _ = decode_n(params, tok, k, v, steps, pos0, window)  # compile
    _sync(tok)
    pos0 = pos0 + steps
    t0 = time.perf_counter()
    tok, k, v, toks = decode_n(params, tok, k, v, steps, pos0, window)
    _sync(toks)
    dt = time.perf_counter() - t0
    del k, v, tok, toks
    gc.collect()
    return {
        "tok_s": round(batch * steps / dt, 1),
        "prefill_s": round(prefill_s, 4),
        "step_ms": round(1e3 * dt / steps, 3),
    }


# ---------------------------------------------------------------------------
# long-context prefill (single-dispatch flash kernel, SURVEY §5)
# ---------------------------------------------------------------------------


def long_prefill_bench(cfg, params, T: int) -> dict:
    cfg = cfg.with_(max_seq_len=max(cfg.max_seq_len, T),
                    use_flash_attention=jax.default_backend() == "tpu")
    fwd = partial(forward, cfg=cfg)

    @partial(jax.jit, donate_argnums=(2, 3))
    def prefill(params, tokens, k, v, start):
        logits, k, v = fwd(
            params, tokens=tokens, k_cache=k, v_cache=v, start_pos=start,
            logit_positions=jnp.full((1,), tokens.shape[1] - 1, jnp.int32),
            fresh_prefill=True,
        )
        return logits[:, -1, :], k, v

    tokens = jnp.ones((1, T), jnp.int32)
    start = jnp.zeros((1,), jnp.int32)
    k, v = make_cache(cfg, 1, T)
    logits, k, v = prefill(params, tokens, k, v, start)  # compile
    _sync(logits)
    t0 = time.perf_counter()
    logits, k, v = prefill(params, tokens, k, v, start)
    _sync(logits)
    dt = time.perf_counter() - t0
    del k, v, logits
    gc.collect()
    return {"tokens": T, "seconds": round(dt, 3), "tok_s": round(T / dt, 1)}


# ---------------------------------------------------------------------------
# end-to-end over the NATS wire (BASELINE.md's metric definition)
# ---------------------------------------------------------------------------


# the README example payload is a short single-turn chat (~15 prompt tokens,
# /root/reference/README.md:227-230 usage block) — BASELINE.md config 2's
# "chat_model req-reply (README example payload)" is measured with this shape
SHORT_PROMPT = "Hello! Introduce yourself briefly."
LONG_PROMPT = "benchmark prompt: " + "tell me about tensor processing units. " * 3


def e2e_nats_bench(cfg, params, model_id: str, clients_a: int = 8,
                   clients_b: int = 96) -> dict:
    """Embedded broker + worker + real engine, driven via
    ``lmstudio.chat_model`` request/stream over the NATS wire.

    Three measured phases on one serving stack (96 slots — int8 KV halves
    per-slot cache so the serving batch rides the same b96 capacity
    frontier the device-scan headline uses):
      A. 8 concurrent clients, README-shaped short prompts -> TTFT p50/p95
         (the BASELINE config-2 latency bar),
      B. 96 concurrent clients x 128 tokens -> aggregate served tok/s
         (vs the same round's device-scan number; long enough streams to
         amortize the admit waves),
      C. 8 clients, ~140-token prompts -> ttft_long p50 (honesty check for
         heavier payloads).

    The warmup covers every program the measured phases reach: group-admit
    widths (mpad 1,2,4,8 — bursts above 8 split into pipelined groups of 8)
    and every decode-window bucket (round-2 advisor: a fresh window compile
    inside the timed phase skews TTFT p95).
    """
    import asyncio

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.gguf.tokenizer import GGUFTokenizer, _byte_to_unicode
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.api import ModelNotFound, Registry
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
    from nats_llm_studio_tpu.serve.registry import JaxChatEngine
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    b2u = _byte_to_unicode()
    vocab = [b2u[i] for i in range(256)]
    vocab += [f"<filler_{i}>" for i in range(cfg.vocab_size - 257)]
    vocab.append("<|eot|>")
    tokenizer = GGUFTokenizer(
        "gpt2", vocab, merges=[], eos_id=cfg.vocab_size - 1, add_bos=False
    )
    slots = int(os.environ.get("BENCH_E2E_SLOTS", str(max(clients_a, clients_b))))
    batcher = ContinuousBatcher(
        params, cfg, max_slots=slots, max_seq_len=512,
        buckets=[64, 256, 512],
    )
    engine = JaxChatEngine(model_id, batcher, tokenizer, cfg, meta={})

    class Preloaded(Registry):
        async def list_models(self):
            return {"object": "list", "data": [engine.info()]}

        async def pull(self, identifier):
            raise ModelNotFound(identifier)

        async def delete(self, model_id):
            raise ModelNotFound(model_id)

        async def get_engine(self, mid):
            if mid != model_id:
                raise ModelNotFound(mid)
            return engine

        async def sync_from_bucket(self, name, model_id=None):
            raise ModelNotFound(name)

        def stats(self):
            return {"models_loaded": [model_id]}

    async def drive() -> dict:
        # cleanup is load-bearing: granite parity runs AFTER e2e in the same
        # process, so a wave error must not leak the serving cache in HBM
        broker = await EmbeddedBroker().start()
        worker = Worker(WorkerConfig(nats_url=broker.url), Preloaded())
        await worker.start()
        nc = await connect(broker.url)

        async def one_chat(tag: int, prompt: str, max_tokens: int):
            body = json.dumps(
                {
                    "model": model_id,
                    "messages": [{"role": "user", "content": f"{prompt} [{tag}]"}],
                    "max_tokens": max_tokens,
                    "temperature": 0.8,
                    "seed": tag,
                    "stream": True,
                }
            ).encode()
            t0 = time.perf_counter()
            ttft = None
            n_tok = 0
            async for msg in nc.request_stream(
                "lmstudio.chat_model", body, timeout=600.0, idle_timeout=300.0
            ):
                if (msg.headers or {}).get("Nats-Stream-Done") is not None:
                    # chunks coalesce decode bursts, so tokens are counted
                    # from the aggregate's usage block, not per message
                    try:
                        done = json.loads(msg.payload)
                        n_tok = done["data"]["response"]["usage"]["completion_tokens"]
                    except Exception:  # noqa: BLE001 — error envelope
                        pass
                    break
                if ttft is None:
                    ttft = time.perf_counter() - t0
            return ttft if ttft is not None else float("nan"), n_tok, time.perf_counter() - t0

        async def wave(n: int, prompt: str, max_tokens: int, base_tag: int):
            t0 = time.perf_counter()
            results = await asyncio.gather(
                *(one_chat(base_tag + i, prompt, max_tokens) for i in range(n))
            )
            wall = time.perf_counter() - t0
            ttfts = sorted(r[0] * 1e3 for r in results if r[0] == r[0]) or [0.0]
            toks = sum(r[1] for r in results)
            return {
                "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 1),
                "ttft_p95_ms": round(ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.95))], 1),
                "tok_s": round(toks / wall, 1),
                "clients": n,
                "max_tokens": max_tokens,
            }

        try:
            # compile warmup: single admit, group-admit widths 2/4/8, both
            # prompt buckets (64 and 256), and every decode window the
            # phases reach (the width waves sweep the ring across 64/256/
            # None)
            await one_chat(0, SHORT_PROMPT, 16)
            w = 2
            while w <= min(8, max(clients_a, clients_b)):
                await asyncio.gather(
                    *(one_chat(100 * w + i, SHORT_PROMPT, 16) for i in range(w))
                )
                w *= 2
            # long-prompt warmup at FULL phase-C width: the measured
            # phase's group admit is mpad=clients_a at bucket 256 — a
            # different program than the short-prompt waves; an unwarmed
            # one costs seconds of compile inside the timed window
            await asyncio.gather(
                *(one_chat(900 + i, LONG_PROMPT, 16) for i in range(clients_a))
            )

            a = await wave(clients_a, SHORT_PROMPT, 32, base_tag=1000)
            b = await wave(clients_b, SHORT_PROMPT, 128, base_tag=2000)
            c = await wave(clients_a, LONG_PROMPT, 32, base_tag=4000)
        finally:
            # each step individually guarded: a dead connection must not
            # skip broker/batcher teardown (the serving cache would stay in
            # HBM and OOM the granite phase that runs next in-process)
            for step in (nc.close, worker.drain, broker.stop,
                         lambda: asyncio.to_thread(batcher.stop)):
                try:
                    await step()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass

        # the driver's chip is reached through a tunnel whose dispatch +
        # readback round trip is ~100 ms (vs ~1 ms chip-local); TTFT pays
        # two of them (launch ack, first-token readback). Measure the noop
        # round trip and report it so the number is interpretable against
        # the <200 ms bar defined for a local v5e.
        noop = jax.jit(lambda x: x + 1)
        z = jnp.zeros((8,), jnp.int32)
        np.asarray(noop(z))
        rts = []
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(noop(z))
            rts.append(time.perf_counter() - t0)
        rt_ms = round(1e3 * sorted(rts)[1], 1)

        return {
            # flat headline keys, each labeled with ITS measurement's
            # concurrency (phase A latency, phase B throughput)
            "ttft_p50_ms": a["ttft_p50_ms"],  # config-2 latency bar, phase A
            "ttft_p95_ms": a["ttft_p95_ms"],
            "ttft_clients": a["clients"],
            "e2e_tok_s": b["tok_s"],  # served throughput, phase B
            "e2e_tok_s_clients": b["clients"],
            "transport_rt_ms": rt_ms,
            "ttft_p50_net_of_transport_ms": round(
                max(0.0, a["ttft_p50_ms"] - 2 * rt_ms), 1
            ),
            "short_wave": a,
            "throughput_wave": b,
            "long_prompt_wave": c,
            "batcher": batcher.stats.snapshot(),
        }

    return asyncio.run(drive())


# ---------------------------------------------------------------------------


def main() -> None:
    tiny = bool(os.environ.get("BENCH_TINY"))
    detail: dict = {"quant": "int8", "platform": jax.devices()[0].platform}

    if tiny:
        # smoke path: an UNQUANTIZED tiny model — named honestly so nobody
        # mistakes a smoke line for an 8B int8 measurement
        cfg = ModelConfig.tiny()
        from nats_llm_studio_tpu.models.llama import ensure_lm_head

        params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
        r = decode_bench(cfg, params, batch=2, prompt_len=16, seq_len=64, steps=8)
        print(json.dumps({
            "metric": "tiny_smoke_decode_tok_s",
            "value": r["tok_s"], "unit": "tok/s/chip",
            "vs_baseline": 0.0,
            "detail": {"quant": cfg.dtype, "platform": detail["platform"],
                       "tiny": r},
        }))
        return

    # -- headline: Llama-3-8B int8, batch sweep -----------------------------
    # flash prefill on the real chip (the serving stack's configuration;
    # decode's T=1 path is unaffected by the flag); decode_unroll makes
    # every per-layer cache access a static view (1440 -> 1799 tok/s at
    # b32); int8 KV (ops/kvcache.py) halves cache traffic AND capacity,
    # moving the batch frontier from b48 to b96 — measured b48 2608,
    # b64 3436, b96 4391 tok/s. BENCH_KV=none reverts to the bf16 cache.
    on_tpu = jax.default_backend() == "tpu"
    kv = os.environ.get("BENCH_KV", "int8")
    cfg = LLAMA3_8B.with_(use_flash_attention=on_tpu, decode_unroll=True,
                          kv_quant=kv)
    detail["kv_quant"] = kv
    params = init_params_int8(cfg)
    # defaults scale with the kv mode: the bf16 cache's HBM frontier is b48
    # (b56+ trips the 15.75 GB AOT compile budget next to the 8.7 GB int8
    # params — the estimate double-counts the donated cache); int8 KV halves
    # the cache and moves it to b96
    # b80 rides below the b96 HBM-pressure edge (b96 swings ~15% run to run
    # as the allocator sits ~0.5 GB from the ceiling); best-of reports it
    # when b96 lands on a bad run
    default_batches = "8,16,32,48,64,80,96" if kv == "int8" else "8,16,32,48"
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES", default_batches).split(",")]
    prompt_len = int(os.environ.get("BENCH_PROMPT", "128"))
    # seq 512 (not 1024): the b32 [B, L, Hkv, S, D] cache at 1024 puts the
    # compile-time HBM estimate 0.4 GB over the 15.75 GB budget next to the
    # 8.7 GB int8 params (the AOT path double-counts the donated cache);
    # decode reads are window-bounded, so seq only sizes the allocation
    seq_len = int(os.environ.get("BENCH_SEQ", "512"))
    steps = int(os.environ.get("BENCH_STEPS", "128"))
    sweep = {}
    for b in batches:
        sweep[f"b{b}"] = decode_bench(cfg, params, b, prompt_len, seq_len, steps)
    best_b = max(sweep, key=lambda k: sweep[k]["tok_s"])
    tok_s = sweep[best_b]["tok_s"]
    detail["llama3_8b"] = {"sweep": sweep, "best": best_b,
                           "prompt_len": prompt_len, "decode_steps": steps}

    # -- long-context prefill (16k, single flash dispatch) ------------------
    if os.environ.get("BENCH_LONG", "1") != "0":
        try:
            detail["long_prefill"] = long_prefill_bench(
                cfg, params, int(os.environ.get("BENCH_LONG_T", "16384"))
            )
        except Exception as e:  # noqa: BLE001 — report, don't die
            detail["long_prefill_error"] = f"{type(e).__name__}: {e}"

    # -- end-to-end over NATS with the SAME 8B engine ------------------------
    if os.environ.get("BENCH_E2E", "1") != "0":
        try:
            detail["e2e"] = e2e_nats_bench(
                cfg, params, "bench/llama3-8b",
                clients_b=96 if kv == "int8" else 48,
            )
        except Exception as e:  # noqa: BLE001 — e2e is best-effort detail
            detail["e2e_error"] = f"{type(e).__name__}: {e}"

    del params
    gc.collect()

    # -- config-1 parity: granite-2b ----------------------------------------
    if os.environ.get("BENCH_GRANITE", "1") != "0":
        try:
            from __graft_entry__ import GRANITE_2B

            gcfg = GRANITE_2B.with_(
                use_flash_attention=jax.default_backend() == "tpu",
                decode_unroll=True,
            )
            gparams = init_params_int8(gcfg, seed=1)
            detail["granite2b"] = decode_bench(
                gcfg, gparams, 32, prompt_len, 1024, steps
            )
            del gparams
            gc.collect()
        except Exception as e:  # noqa: BLE001
            detail["granite2b_error"] = f"{type(e).__name__}: {e}"

    print(json.dumps({
        "metric": f"llama3_8b_int8_decode_tok_s.{best_b}",
        "value": tok_s,
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s / NORTH_STAR_TOK_S, 3),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()

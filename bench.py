"""Benchmark: the north-star metric on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Headline (BASELINE.md config 2, the metric string itself names the model):
**Llama-3-8B geometry, int8 weight-only + int8 KV, batched ring decode** —
batch sweep up to 96, best batch reported. Also measured, in `detail`:

* `e2e` — the SAME 8B engine served end-to-end over the NATS wire
  (`lmstudio.chat_model` streaming): TTFT p50/p95 at 8 clients; aggregate
  tok/s at 96 clients for 128- and 256-token streams, synchronized-wave
  AND closed-loop (sustained); per-phase batcher occupancy and admit
  queue-delay percentiles. The honest "via nats req" numbers.
* `e2e_long` — long-context SERVING: a >=4k-token 4-client wave with
  interference streams (chunked group admission) and a ~8k-token single,
  TTFT / prefill tok/s / inter-chunk gap percentiles, prompt token counts
  read back from usage.
* `long_prefill` — single-dispatch 16k-token flash prefill (SURVEY §5
  long-context), tok/s and seconds.
* `prefix_cache` — shared-system-prompt serving with the automatic prefix
  KV cache ON vs OFF (serve/prefix_cache.py): TTFT p50 and total prefill
  seconds for the same sequential turn mix, plus the scraped
  `lmstudio_prefix_cache_hit_tokens_total` Prometheus counter.
* `moe` — scaled Mixtral geometry (8 experts, top-2) on-chip: decode tok/s
  and prefill for BOTH dispatch forms (routed vs dense).
* `granite2b` — config-1 parity (the round-1/2 flagship), decode tok/s.

Weights are random (throughput depends on shapes/dtypes, not values); the 8B
bf16 tree would not fit HBM next to its int8 copy, so init streams one leaf
at a time: create bf16 -> quantize on device -> free (peak = int8 model +
one bf16 leaf). Set JAX_PLATFORMS=cpu BENCH_TINY=1 for a smoke run.
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x) -> None:
    """Force completion: block_until_ready alone does not flush execution on
    every remote-device transport, a device->host copy does."""
    jax.block_until_ready(x)
    np.asarray(jax.tree.leaves(x)[0])

from nats_llm_studio_tpu.engine.sampling import sample
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import forward, init_params, make_cache
from nats_llm_studio_tpu.ops.wquant import (
    quantizable,
    quantize_weight,
    quantize_weight4,
)

NORTH_STAR_TOK_S = 2000.0

# Meta-Llama-3-8B-Instruct geometry (BASELINE.md config 2): 32 layers,
# d=4096, ff=14336, GQA 32q/8kv, head_dim 128, vocab 128256, rope 500k.
LLAMA3_8B = ModelConfig(
    arch="llama",
    vocab_size=128256,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    n_kv_heads=8,
    head_dim=128,
    d_ff=14336,
    rope_theta=500000.0,
    max_seq_len=8192,
    dtype="bfloat16",
)


def init_params_int8(cfg: ModelConfig, seed: int = 0, mode: str = "int8",
                     group: int = 32):
    """Leaf-streamed random init, quantized on device.

    8B bf16 is ~16 GB — materializing it before quantization would OOM a
    16 GB chip. Each leaf is created and quantized inside one jit program
    (the bf16 original is a program-local transient), then blocked on, so
    peak HBM = quantized model so far + one bf16 leaf. ``mode`` picks the
    device representation: "int8" (per-channel QTensor, the headline) or
    "int4" (grouped QTensor4, the decode_kernel phase's comparison arm).

    Covers the dense and MoE no-bias trees (the schema below mirrors
    models.llama.init_params for those cases); guarded so an attn-bias
    config cannot silently bench an incomplete tree.
    """
    assert not cfg.attn_bias, (
        "init_params_int8 builds the no-bias schema; extend it before "
        f"benching arch={cfg.arch!r} (attn_bias={cfg.attn_bias})"
    )
    dt = cfg.dtype

    @partial(jax.jit, static_argnums=(1,))
    def _randn(k, shape):
        return (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)

    @partial(jax.jit, static_argnums=(1,))
    def _randq(k, shape):
        w = (jax.random.normal(k, shape, jnp.float32) * 0.02).astype(dt)
        if mode == "int4":
            return quantize_weight4(w, group=group, device=True)
        return quantize_weight(w, device=True)

    key = jax.random.PRNGKey(seed)
    counter = [0]

    def leaf(name: str, *shape):
        counter[0] += 1
        k = jax.random.fold_in(key, counter[0])
        out = _randq(k, shape) if quantizable(name) else _randn(k, shape)
        jax.block_until_ready(out)
        return out

    L, d, hq, hkv, hd, ff = (
        cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
        cfg.head_dim, cfg.d_ff,
    )
    blocks = {
        "attn_norm": jnp.ones((L, d), dt),
        "ffn_norm": jnp.ones((L, d), dt),
        "wq": leaf("wq", L, d, hq * hd),
        "wk": leaf("wk", L, d, hkv * hd),
        "wv": leaf("wv", L, d, hkv * hd),
        "wo": leaf("wo", L, hq * hd, d),
    }
    if cfg.is_moe:
        e = cfg.n_experts
        blocks |= {
            "router": leaf("router", L, d, e),  # stays bf16 (not in _QUANT_KEYS)
            "w_gate_e": leaf("w_gate_e", L, e, d, ff),
            "w_up_e": leaf("w_up_e", L, e, d, ff),
            "w_down_e": leaf("w_down_e", L, e, ff, d),
        }
    else:
        blocks |= {
            "w_gate": leaf("w_gate", L, d, ff),
            "w_up": leaf("w_up", L, d, ff),
            "w_down": leaf("w_down", L, ff, d),
        }
    return {
        "embed": leaf("embed", cfg.vocab_size, d),
        "out_norm": jnp.ones((d,), dt),
        "lm_head": leaf("lm_head", d, cfg.vocab_size),
        "blocks": blocks,
    }


# ---------------------------------------------------------------------------
# device-side decode throughput (ring-slot scan, the serving hot path shape)
# ---------------------------------------------------------------------------


def decode_bench(cfg, params, batch, prompt_len, seq_len, steps) -> dict:
    fwd = partial(forward, cfg=cfg)

    # donate the cache: timing reruns prefill into the SAME buffers — a
    # second [B, L, Hkv, S, D] cache next to params would OOM at batch 32
    @partial(jax.jit, donate_argnums=(2, 3))
    def prefill(params, tokens, k, v, start):
        logits, k, v = fwd(
            params, tokens=tokens, k_cache=k, v_cache=v, start_pos=start,
            logit_positions=jnp.full((tokens.shape[0],), tokens.shape[1] - 1, jnp.int32),
        )
        return sample(logits[:, -1, :], jax.random.PRNGKey(1), temperature=0.0), k, v

    def bucket_window(max_pos: int) -> int | None:
        w = -(-(max_pos + 1) // 256) * 256
        return w if w < seq_len else None

    @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(4, 6))
    def decode_n(params, tok, k, v, n, pos0, window):
        """n decode steps as one device-side scan: measures chip throughput
        without per-step host dispatch (the remote-device tunnel costs ~ms
        per call, which would swamp a memory-bound step)."""

        def body(carry, i):
            tok, k, v = carry
            pos = pos0 + i
            logits, k, v = fwd(params, tokens=tok[:, None], k_cache=k, v_cache=v,
                               start_pos=pos, ring_slot=pos[0] % k.shape[3],
                               attn_window=window)
            nxt = sample(logits[:, -1, :], jax.random.PRNGKey(2), temperature=0.0)
            return (nxt, k, v), nxt

        (tok, k, v), toks = jax.lax.scan(body, (tok, k, v), jnp.arange(n, dtype=jnp.int32))
        return tok, k, v, toks

    k, v = make_cache(cfg, batch, seq_len)
    tokens = jnp.ones((batch, prompt_len), jnp.int32)
    start = jnp.zeros((batch,), jnp.int32)

    tok, k, v = prefill(params, tokens, k, v, start)  # compile
    _sync(tok)
    # best-of-2 timed runs: a single sample can absorb a transient infra
    # stall (the r3 artifact's b64 prefill_s was 8.77 s vs 0.77/1.15 for
    # its neighbors — an outlier, not steady state). Published points must
    # be steady-state (VERDICT r3 weak #2).
    prefill_s = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        tok, k, v = prefill(params, tokens, k, v, start)
        _sync(tok)
        prefill_s = min(prefill_s, time.perf_counter() - t0)

    pos0 = jnp.full((batch,), prompt_len, jnp.int32)
    window = bucket_window(prompt_len + 3 * steps)
    tok, k, v, _ = decode_n(params, tok, k, v, steps, pos0, window)  # compile
    _sync(tok)
    pos0 = pos0 + steps
    t0 = time.perf_counter()
    tok, k, v, toks = decode_n(params, tok, k, v, steps, pos0, window)
    _sync(toks)
    dt = time.perf_counter() - t0
    del k, v, tok, toks
    gc.collect()
    return {
        "tok_s": round(batch * steps / dt, 1),
        "prefill_s": round(prefill_s, 4),
        "step_ms": round(1e3 * dt / steps, 3),
    }


# ---------------------------------------------------------------------------
# MoE decode + dispatch ablation (BASELINE config 4, VERDICT r3 missing #2)
# ---------------------------------------------------------------------------

# Mixtral-8x7B itself (47B params) cannot fit one 16 GB chip even int8, so
# the on-chip MoE number uses a SCALED Mixtral geometry: identical routing
# shape (8 experts, top-2, SwiGLU experts), halved d_model/d_ff, 16 layers
# -> ~5.9 GB int8 expert weights + attention. The measurement of record for
# the routed path (parallel/moe.py) on real silicon.
SCALED_MIXTRAL = ModelConfig(
    arch="llama",
    vocab_size=32000,
    d_model=2048,
    n_layers=16,
    n_heads=16,
    n_kv_heads=8,
    head_dim=128,
    d_ff=7168,
    rope_theta=1e6,
    max_seq_len=4096,
    dtype="bfloat16",
    n_experts=8,
    n_experts_used=2,
)


def moe_bench(cfg=None, batch=32, prompt_len=128, seq_len=512,
              steps=128) -> dict:
    """Decode tok/s and prefill time for the SAME MoE weights under both
    dispatch forms: routed (sparse scatter/gather, parallel/moe.py) and
    dense reference (every expert computes every token, E/k = 4x the
    FLOPs). Decode at serving batch is weight-traffic-bound (both forms
    read all experts), so the FLOP saving shows up at prefill token counts
    — report both rather than cherry-picking."""
    on_tpu = jax.default_backend() == "tpu"
    base = (cfg or SCALED_MIXTRAL).with_(
        use_flash_attention=on_tpu, decode_unroll=True, kv_quant="int8"
    )
    params = init_params_int8(base, seed=2)
    out: dict = {
        "geometry": {
            "d_model": base.d_model, "d_ff": base.d_ff,
            "n_layers": base.n_layers, "n_experts": base.n_experts,
            "n_experts_used": base.n_experts_used, "batch": batch,
        }
    }
    for name, routed in (("routed", True), ("dense", False)):
        out[name] = decode_bench(
            base.with_(use_routed_moe=routed), params, batch, prompt_len,
            seq_len, steps,
        )
    out["routed_decode_speedup"] = round(
        out["routed"]["tok_s"] / out["dense"]["tok_s"], 3
    )
    # prefill covers batch*prompt_len tokens in one dispatch — the
    # FLOP-bound regime where dense dispatch pays E/k x
    out["routed_prefill_speedup"] = round(
        out["dense"]["prefill_s"] / out["routed"]["prefill_s"], 3
    )

    # deep-prefill ablation: at batch*512 tokens the expert FLOPs dominate
    # everything else, so the E/k = 4x dense dispatch waste is maximally
    # visible — the number that justifies the routed path's existence
    long_t = int(os.environ.get("BENCH_MOE_PREFILL", "512"))
    ab = {}
    for nm, routed in (("routed", True), ("dense", False)):
        cfg_i = base.with_(use_routed_moe=routed)
        fwd = partial(forward, cfg=cfg_i)

        @partial(jax.jit, donate_argnums=(2, 3))
        def pre(params, tokens, k, v):
            logits, k, v = fwd(
                params, tokens=tokens, k_cache=k, v_cache=v,
                start_pos=jnp.zeros((tokens.shape[0],), jnp.int32),
                logit_positions=jnp.full((tokens.shape[0],), tokens.shape[1] - 1,
                                         jnp.int32),
                fresh_prefill=True,
            )
            return logits, k, v

        toks = jnp.ones((batch, long_t), jnp.int32)
        k, v = make_cache(base, batch, long_t)
        logits, k, v = pre(params, toks, k, v)
        _sync(logits)
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            logits, k, v = pre(params, toks, k, v)
            _sync(logits)
            best = min(best, time.perf_counter() - t0)
        ab[nm] = round(best, 4)
        del k, v, logits
        gc.collect()
    out["prefill_deep"] = {
        "tokens": batch * long_t, **ab,
        "routed_speedup": round(ab["dense"] / ab["routed"], 3),
    }

    # small-batch decode (VERDICT r4 weak #4 follow-up): at b32, top-2-of-8
    # activates every expert and routed buys nothing at decode; b <= 4 is
    # where sparse routing can skip expert weight reads on ONE chip. Also
    # report the measured capacity-overflow drop fraction (the exact
    # serving-path routing on sample activations) — the drop-rate stat the
    # r4 review asked for alongside the ablation.
    if os.environ.get("BENCH_MOE_SMALL", "1") != "0":
        from nats_llm_studio_tpu.parallel.moe import routed_drop_fraction

        small: dict = {"capacity_factor": base.moe_capacity_factor}
        for b in (1, 4):
            r = decode_bench(base.with_(use_routed_moe=True), params, b,
                             prompt_len, seq_len, steps)
            dn = decode_bench(base.with_(use_routed_moe=False), params, b,
                              prompt_len, seq_len, steps)
            small[f"b{b}"] = {
                "routed_tok_s": r["tok_s"],
                "dense_tok_s": dn["tok_s"],
                "routed_speedup": round(r["tok_s"] / dn["tok_s"], 3),
            }
        blk0 = jax.tree.map(lambda a: a[0], params["blocks"])
        key = jax.random.PRNGKey(11)
        drops = {}
        for shape_name, shp in (("decode_b1", (1, 1)), ("decode_b4", (4, 1)),
                                ("decode_b32", (32, 1)),
                                ("prefill_4x128", (4, 128))):
            x = jax.random.normal(
                jax.random.fold_in(key, len(drops)),
                (*shp, base.d_model), jnp.dtype(base.dtype),
            )
            drops[shape_name] = round(routed_drop_fraction(
                x, blk0, base, base.moe_capacity_factor), 4)
        small["drop_fraction"] = drops
        out["small_batch"] = small

    del params
    gc.collect()
    return out


# ---------------------------------------------------------------------------
# long-context prefill (single-dispatch flash kernel, SURVEY §5)
# ---------------------------------------------------------------------------


def long_prefill_bench(cfg, params, T: int) -> dict:
    cfg = cfg.with_(max_seq_len=max(cfg.max_seq_len, T),
                    use_flash_attention=jax.default_backend() == "tpu")
    fwd = partial(forward, cfg=cfg)

    @partial(jax.jit, donate_argnums=(2, 3))
    def prefill(params, tokens, k, v, start):
        logits, k, v = fwd(
            params, tokens=tokens, k_cache=k, v_cache=v, start_pos=start,
            logit_positions=jnp.full((1,), tokens.shape[1] - 1, jnp.int32),
            fresh_prefill=True,
        )
        return logits[:, -1, :], k, v

    tokens = jnp.ones((1, T), jnp.int32)
    start = jnp.zeros((1,), jnp.int32)
    k, v = make_cache(cfg, 1, T)
    logits, k, v = prefill(params, tokens, k, v, start)  # compile
    _sync(logits)
    t0 = time.perf_counter()
    logits, k, v = prefill(params, tokens, k, v, start)
    _sync(logits)
    dt = time.perf_counter() - t0
    del k, v, logits
    gc.collect()
    return {"tokens": T, "seconds": round(dt, 3), "tok_s": round(T / dt, 1)}


# ---------------------------------------------------------------------------
# end-to-end over the NATS wire (BASELINE.md's metric definition)
# ---------------------------------------------------------------------------


# the README example payload is a short single-turn chat (~15 prompt tokens,
# /root/reference/README.md:227-230 usage block) — BASELINE.md config 2's
# "chat_model req-reply (README example payload)" is measured with this shape
SHORT_PROMPT = "Hello! Introduce yourself briefly."
# ~120 tokens — a heavier-payload honesty check, NOT long context (the r3
# artifact mislabeled this wave "long_prompt"; true long-context serving is
# measured by e2e_long_context_bench with >= 4096 REAL prompt tokens)
MEDIUM_PROMPT = "benchmark prompt: " + "tell me about tensor processing units. " * 3


def make_long_prompt(n_tokens: int) -> str:
    """~n_tokens ASCII chars: the bench tokenizer is byte-level BPE with no
    merges, so every ASCII character is exactly one token (the response's
    usage.prompt_tokens confirms the count in the artifact)."""
    base = "the quick brown fox jumps over the lazy dog near the river bank. "
    return (base * (n_tokens // len(base) + 1))[:n_tokens]


def _make_bench_tokenizer(cfg):
    from nats_llm_studio_tpu.gguf.tokenizer import GGUFTokenizer, _byte_to_unicode

    b2u = _byte_to_unicode()
    vocab = [b2u[i] for i in range(256)]
    vocab += [f"<filler_{i}>" for i in range(cfg.vocab_size - 257)]
    vocab.append("<|eot|>")
    return GGUFTokenizer(
        "gpt2", vocab, merges=[], eos_id=cfg.vocab_size - 1, add_bos=False
    )


def _pctl(sorted_vals, q: float) -> float:
    """Percentile over an ASCENDING-sorted list (0.0 for empty) — the one
    index rule every CLIENT-SIDE reported p50/p95 shares. Batcher-side
    percentiles come from obs.LogHistogram snapshots instead."""
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1, int(len(sorted_vals) * q))]


def _phase_hists(batcher) -> dict:
    """Snapshot every batcher histogram at a phase boundary (for delta)."""
    return {name: h.snapshot() for name, h in batcher.stats.histograms().items()}


def _phase_delta(batcher, s0: dict, h0: dict) -> dict:
    """Batcher counters for ONE measured phase (difference against the
    snapshot taken before it) — the r3 artifact's tokens_per_step_avg mixed
    warmup and every phase into one cumulative number, hiding the
    throughput phase's true occupancy. ``h0`` holds the phase-start
    ``HistSnapshot`` per histogram (see ``_phase_hists``); subtracting
    snapshots isolates each phase's distribution without any deque replay."""
    s1 = batcher.stats.snapshot()
    h1 = _phase_hists(batcher)
    delays = h1["admit_queue_delay_ms"] - h0["admit_queue_delay_ms"]
    steps = s1["decode_steps"] - s0["decode_steps"]
    toks = s1["tokens"] - s0["tokens"]
    out = {
        "tokens": toks,
        "decode_steps": steps,
        "tokens_per_step_avg": round(toks / steps, 2) if steps else 0.0,
        "admit_queue_delay_p50_ms": round(delays.percentile(0.5), 1),
        "admit_queue_delay_p95_ms": round(delays.percentile(0.95), 1),
    }
    for name in ("ttft_ms", "decode_step_ms"):
        d = h1[name] - h0[name]
        if d.count:
            out[f"batcher_{name[:-3]}_p50_ms"] = round(d.percentile(0.5), 1)
            out[f"batcher_{name[:-3]}_p95_ms"] = round(d.percentile(0.95), 1)
    return out


def e2e_nats_bench(cfg, params, model_id: str, clients_a: int = 8,
                   clients_b: int = 96) -> dict:
    """Embedded broker + worker + real engine, driven via
    ``lmstudio.chat_model`` request/stream over the NATS wire.

    Measured phases on one serving stack (96 slots — int8 KV halves
    per-slot cache so the serving batch rides the same b96 capacity
    frontier the device-scan headline uses):
      A.  8 concurrent clients, README-shaped short prompts -> TTFT p50/p95
          (the BASELINE config-2 latency bar),
      B.  96 concurrent clients x 128 tokens, one synchronized wave ->
          aggregate served tok/s (the ramp-dominated worst case),
      B2. the same width CLOSED-LOOP (each client sends its next request
          the moment the previous completes, 2 rounds) -> sustained tok/s,
          the steady state a deployed worker actually sees,
      C.  8 clients, ~140-token prompts -> heavier-payload honesty check.

    The warmup covers every program the measured phases reach: singleton
    admits at both prompt buckets, group-admit widths (mpad 2..32), and
    every decode-window bucket (round-2 advisor: a fresh compile inside
    the timed phase skews TTFT p95).
    """
    import asyncio

    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    tokenizer = _make_bench_tokenizer(cfg)
    slots = int(os.environ.get("BENCH_E2E_SLOTS", str(max(clients_a, clients_b))))
    # wide group admits: a 96-client wave rides 3 pipelined [32, bucket]
    # prefills instead of 12 [8, *] — the dominant term in wave ramp time
    # (the served/device gap, VERDICT r3 weak #1) and in TTFT p95 under
    # load (missing #4)
    group = int(os.environ.get("BENCH_GROUP", "32"))
    # burst 16 (vs 8 in r4): the per-burst host/dispatch fixed cost (~29 ms
    # on the tunnel) halves per step, worth ~+200 tok/s sustained; 32 was
    # measured WORSE for closed-loop (completed slots idle a whole 860 ms
    # burst before readmission — occupancy fell 90 -> 77 tokens/step)
    burst = int(os.environ.get("BENCH_BURST", "16"))
    # coalesce 15 ms (vs the 3 ms default): a synchronized 96-client wave
    # trickles through the broker over tens of ms — eagerly admitting the
    # first handful as a narrow group wastes the wide-admit programs on
    # small MXU tiles; the wider window costs 15 ms of TTFT floor and
    # buys back most of the ramp
    coalesce = float(os.environ.get("BENCH_COALESCE_MS", "15"))
    batcher = ContinuousBatcher(
        params, cfg, max_slots=slots, max_seq_len=512,
        buckets=[64, 256, 512], max_group_admit=group, decode_burst=burst,
        admit_coalesce_ms=coalesce,
    )

    async def body(nc, one_chat):
        async def wave(n: int, prompt: str, max_tokens: int, base_tag: int,
                       rounds: int = 1):
            """``rounds`` > 1 = CLOSED-LOOP clients: each sends its next
            request the moment the previous completes, so admits stagger
            naturally against decode instead of arriving as one
            synchronized ramp — the steady state a deployed worker
            actually sees (the reference's clients are independent
            services, /root/reference/README.md:508-562)."""
            s0 = batcher.stats.snapshot()
            d0 = _phase_hists(batcher)

            async def client(i: int):
                out = []
                for r in range(rounds):
                    tag = base_tag + rounds * i + r
                    out.append(await one_chat(tag, f"{prompt} [{tag}]",
                                              max_tokens))
                return out

            t0 = time.perf_counter()
            per_client = await asyncio.gather(*(client(i) for i in range(n)))
            wall = time.perf_counter() - t0
            results = [r for rs in per_client for r in rs]
            ttfts = sorted(r["ttft_s"] * 1e3 for r in results
                           if r["ttft_s"] == r["ttft_s"])
            toks = sum(r["completion_tokens"] for r in results)
            return {
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "ttft_p95_ms": round(_pctl(ttfts, 0.95), 1),
                "tok_s": round(toks / wall, 1),
                "clients": n,
                "max_tokens": max_tokens,
                "requests": len(results),
                "parse_failures": sum(1 for r in results if r["parse_fail"]),
                "batcher_phase": _phase_delta(batcher, s0, d0),
            }

        # compile warmup: single admit at BOTH prompt buckets (a straggler
        # outside its wave's group takes the singleton admit_fused path —
        # unwarmed, its compile lands in the measured p95), every
        # group-admit width the waves can reach (mpad 2..max_group_admit),
        # and every decode window the phases sweep the ring across
        # (64/256/None)
        await one_chat(0, SHORT_PROMPT, 16)
        await one_chat(1, MEDIUM_PROMPT, 16)
        w = 2
        while w <= min(batcher.max_group_admit, max(clients_a, clients_b)):
            await asyncio.gather(
                *(one_chat(100 * w + i, SHORT_PROMPT, 16) for i in range(w))
            )
            w *= 2
        # medium-prompt warmup across group widths, REPEATED: arrival
        # timing can split a warmup gather into smaller groups (e.g. 4+4),
        # leaving a bucket-256 admit width uncompiled — one run measured a
        # flat 6.6 s compile inside the medium wave from exactly this.
        # Two passes over widths {2, 4, 8} make a missed mpad vanishingly
        # unlikely.
        for rep in range(2):
            w = 2
            while w <= min(8, clients_a):
                await asyncio.gather(
                    *(one_chat(900 + 100 * rep + 10 * w + i, MEDIUM_PROMPT, 16)
                      for i in range(w))
                )
                w *= 2
        # drive the ring past the last window bucket once: closed-loop
        # rounds (no cold reset between a client's requests) push the
        # shared ring head past 248 where decode switches to the
        # full-window (None) program — a distinct compile that must not
        # land inside the measured sustained wave
        await one_chat(990, SHORT_PROMPT, 250)

        # drain between waves: the depth-2 pipeline leaves one zombie
        # burst in flight after a wave's last stream ends; a new wave's
        # admits queueing behind its readback would charge ~a burst +
        # round trip (~190 ms measured) to TTFT that no steady-state
        # request pays
        await asyncio.sleep(0.75)
        a = await wave(clients_a, SHORT_PROMPT, 32, base_tag=1000)
        await asyncio.sleep(0.75)
        b = await wave(clients_b, SHORT_PROMPT, 128, base_tag=2000)
        await asyncio.sleep(0.75)
        # rounds=3 (vs 2 in r4): the first round is a synchronized cold
        # ramp; more rounds measure more of the actual steady state the
        # phase exists to report (the ramp's share drops from ~1/5 to ~1/8)
        b2 = await wave(clients_b, SHORT_PROMPT, 128, base_tag=20000,
                        rounds=int(os.environ.get("BENCH_SUSTAINED_ROUNDS", "3")))
        await asyncio.sleep(0.75)
        # 256-token streams: the decode floor dominates and the fixed wave
        # edges (ramp + final-readback sync on a ~115 ms-RT tunnel)
        # amortize — the regime sustained serving actually runs in. The
        # 128-token wave above stays for round-3 comparability.
        b3 = await wave(clients_b, SHORT_PROMPT, 256, base_tag=40000)
        await asyncio.sleep(0.75)
        c = await wave(clients_a, MEDIUM_PROMPT, 32, base_tag=4000)
        await asyncio.sleep(0.75)

        # -- ring-compaction-under-load phase (VERDICT r4 weak #5) ----------
        # One stream drives the shared 512-ring head near wrap, a second
        # joins late with a small position, the first ends -> the ring wraps
        # while the survivor is live -> maybe_compact() re-rolls. Run TWICE:
        # rep 0 compiles the compact program + post-roll windows, rep 1 is
        # the measured recovery. The survivor's inter-chunk gaps split at
        # the roll timestamp quantify windowed-read recovery.
        async def ring_phase(base_tag: int) -> dict:
            s0 = batcher.stats.snapshot()
            d0 = _phase_hists(batcher)
            gaps: list[tuple[float, float]] = []
            roll_t: float | None = None

            async def poll_roll():
                nonlocal roll_t
                while roll_t is None:
                    if batcher.stats.ring_compactions > s0["ring_compactions"]:
                        roll_t = time.perf_counter()
                        return
                    await asyncio.sleep(0.02)

            poller = asyncio.create_task(poll_roll())
            t0 = time.perf_counter()
            # driver: decodes until the 512-ring's length cap (~pos 505+)
            driver = asyncio.create_task(
                one_chat(base_tag, SHORT_PROMPT, 430)
            )
            # survivor joins LATE (driver ~70 steps from its cap) so at the
            # wrap its own position is small — maybe_compact() rolls only
            # when the live window bucket is <= max_seq/2, and the late
            # join leaves ~30 post-roll bursts to measure
            while (batcher.stats.tokens
                   - s0["tokens"]) < 360 and not driver.done():
                await asyncio.sleep(0.02)
            surv = await one_chat(base_tag + 1, SHORT_PROMPT, 320, gaps=gaps)
            drv = await driver
            poller.cancel()
            wall = time.perf_counter() - t0
            phase = _phase_delta(batcher, s0, d0)
            rolls = batcher.stats.ring_compactions - s0["ring_compactions"]
            pre = sorted(g * 1e3 for t, g in gaps
                         if roll_t is None or t < roll_t)
            post = sorted(g * 1e3 for t, g in gaps
                          if roll_t is not None and t >= roll_t)
            return {
                "ring_compactions": rolls,
                "survivor_gap_pre_roll_p50_ms": round(_pctl(pre, 0.5), 1),
                "survivor_gap_post_roll_p50_ms": round(_pctl(post, 0.5), 1),
                "gap_samples_pre": len(pre),
                "gap_samples_post": len(post),
                "driver_tokens": drv["completion_tokens"],
                "survivor_tokens": surv["completion_tokens"],
                "wall_s": round(wall, 2),
                "parse_failures": int(drv["parse_fail"]) + int(surv["parse_fail"]),
                "batcher_phase": phase,
            }

        await ring_phase(base_tag=6000)  # compile rep (compact_ring + windows)
        await asyncio.sleep(0.75)
        ring = await ring_phase(base_tag=6100)
        await asyncio.sleep(0.75)

        # -- sustained-overload phase (VERDICT r4 missing #2 measurement) ---
        # 1.5x slots closed-loop clients against a 2 s admit-age bound:
        # requests that cannot be served within the bound get an immediate
        # honest shed reply and the client retries after a short backoff
        # (modeling the bus handing it to a queue-group peer). Replaces the
        # r4 silent 38.6 s admit-delay tail with a bounded p95 + an
        # explicit shed count. Prior bounds are restored afterwards.
        async def overload_phase(n_clients: int, rounds: int,
                                 base_tag: int) -> dict:
            prev_age, prev_queue = batcher.max_queue_age_ms, batcher.max_queue
            batcher.max_queue_age_ms = float(
                os.environ.get("BENCH_SHED_AGE_MS", "2000"))
            batcher.max_queue = int(
                os.environ.get("BENCH_SHED_QUEUE", str(4 * batcher.max_slots)))
            s0 = batcher.stats.snapshot()
            d0 = _phase_hists(batcher)
            bo = getattr(batcher, "brownout", None)
            bo_trans0 = bo.transitions if bo is not None else 0
            aborted0 = batcher.stats.cancel_causes.get("deadline", 0)
            try:
                async def client(i: int):
                    completed = sheds = other = toks = abandoned = 0
                    ttfts_c = []
                    for r in range(rounds):
                        tag = base_tag + 16 * (rounds * i + r)
                        for attempt in range(8):
                            res = await one_chat(tag + attempt,
                                                 f"{SHORT_PROMPT} [{i}.{r}]", 128)
                            if not res["parse_fail"]:
                                completed += 1
                                toks += res["completion_tokens"]
                                if res["ttft_s"] == res["ttft_s"]:
                                    ttfts_c.append(res["ttft_s"])
                                break
                            err = res.get("error") or ""
                            if "shed" in err or "overloaded" in err or "full" in err:
                                sheds += 1
                                await asyncio.sleep(0.25)  # retry (peer analog)
                            else:
                                other += 1
                                break
                        else:  # shed on every attempt: the round is ABANDONED
                            abandoned += 1  # keeps completed+other+abandoned
                            # == rounds so the accounting always balances
                    return completed, sheds, other, ttfts_c, toks, abandoned

                t0 = time.perf_counter()
                per = await asyncio.gather(*(client(i) for i in range(n_clients)))
                wall = time.perf_counter() - t0
            finally:
                batcher.max_queue_age_ms = prev_age
                batcher.max_queue = prev_queue
            phase = _phase_delta(batcher, s0, d0)
            completed = sum(p[0] for p in per)
            sheds_seen = sum(p[1] for p in per)
            other = sum(p[2] for p in per)
            ttfts = sorted(t * 1e3 for p in per for t in p[3])
            total_toks = sum(p[4] for p in per)
            abandoned = sum(p[5] for p in per)
            return {
                "clients": n_clients,
                "rounds": rounds,
                "abandoned_rounds": abandoned,
                "slots": batcher.max_slots,
                "admit_age_bound_ms": float(
                    os.environ.get("BENCH_SHED_AGE_MS", "2000")),
                "admit_queue_bound": int(
                    os.environ.get("BENCH_SHED_QUEUE", str(4 * batcher.max_slots))),
                "completed": completed,
                "sheds_observed_by_clients": sheds_seen,
                "other_errors": other,
                "batcher_shed_total": batcher.stats.shed - s0["shed"],
                # deadline/brownout phase deltas (ISSUE 5): how much of the
                # shedding was deadline-driven and whether the controller
                # actually browned out during the storm
                "deadline_shed": (
                    batcher.stats.shed_cause_counts().get("deadline", 0)
                    - (s0.get("shed_causes") or {}).get("deadline", 0)
                ),
                "deadline_aborted": (
                    batcher.stats.cancel_causes.get("deadline", 0) - aborted0
                ),
                "brownout_level": getattr(batcher, "brownout_level", 0),
                "brownout_transitions": (
                    (bo.transitions - bo_trans0) if bo is not None else 0
                ),
                "served_tok_s": round(total_toks / wall, 1),
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "ttft_p95_ms": round(_pctl(ttfts, 0.95), 1),
                "wall_s": round(wall, 2),
                "batcher_phase": phase,  # admit delay p95 <= the age bound
            }

        overload = await overload_phase(
            n_clients=int(os.environ.get("BENCH_SHED_CLIENTS",
                                         str(3 * clients_b // 2))),
            rounds=2, base_tag=60000,
        )
        return a, b, b2, b3, c, ring, overload

    a, b, b2, b3, c, ring, overload = _drive_engine(
        cfg, params, model_id, tokenizer, batcher, body)

    # the driver's chip is reached through a tunnel whose dispatch +
    # readback round trip is ~100 ms (vs ~1 ms chip-local); TTFT pays
    # two of them (launch ack, first-token readback). Measure the noop
    # round trip and report it so the number is interpretable against
    # the <200 ms bar defined for a local v5e.
    noop = jax.jit(lambda x: x + 1)
    z = jnp.zeros((8,), jnp.int32)
    np.asarray(noop(z))
    rts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(noop(z))
        rts.append(time.perf_counter() - t0)
    rt_ms = round(1e3 * sorted(rts)[1], 1)

    return {
        # flat headline keys, each labeled with ITS measurement's
        # concurrency (phase A latency, phase B throughput)
        "ttft_p50_ms": a["ttft_p50_ms"],  # config-2 latency bar, phase A
        "ttft_p95_ms": a["ttft_p95_ms"],
        "ttft_clients": a["clients"],
        "e2e_tok_s": b["tok_s"],  # served throughput, phase B (128-tok, r3-comparable)
        "e2e_tok_s_clients": b["clients"],
        "e2e_sustained_tok_s": b2["tok_s"],  # closed-loop, phase B2
        "e2e_tok_s_256": b3["tok_s"],  # 256-token streams, phase B3
        "transport_rt_ms": rt_ms,
        "ttft_p50_net_of_transport_ms": round(
            max(0.0, a["ttft_p50_ms"] - 2 * rt_ms), 1
        ),
        "short_wave": a,
        "throughput_wave": b,
        "sustained_wave": b2,
        "long_stream_wave": b3,
        "medium_prompt_wave": c,
        "ring_compaction": ring,
        "overload": overload,
        # CUMULATIVE run-wide counters (warmup + every phase above),
        # marked as such. Latency percentiles are deliberately absent:
        # a run-wide histogram folds the warmup ramp and all seven phases'
        # admit-delay samples into one distribution that contradicts every
        # per-phase number (the r05 artifact's cumulative admit p95 read
        # 6.9 s against a 38 ms throughput-wave delta) — each phase's
        # ``batcher_phase`` delta block is the authoritative latency
        # record; this block is for conservation checks only (sheds +
        # completions + cancels must balance across phases).
        "batcher": {
            "scope": "cumulative_counters",
            **batcher.stats.counters(),
            "peak_active_slots": batcher.stats.peak_active,
            "shed_causes": batcher.stats.shed_cause_counts(),
        },
    }


# ---------------------------------------------------------------------------
# long-context SERVING (VERDICT r3 missing #1): >= 4096 REAL prompt tokens
# through lmstudio.chat_model with chunked prefill, measured end-to-end
# ---------------------------------------------------------------------------


def _drive_engine(cfg, params, model_id, tokenizer, batcher, body_fn):
    """Stand up broker+worker+engine around ``batcher``, run ``body_fn``
    (async, given a connected client and a one_chat helper), tear down."""
    import asyncio

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.api import ModelNotFound, Registry
    from nats_llm_studio_tpu.serve.registry import JaxChatEngine
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    engine = JaxChatEngine(model_id, batcher, tokenizer, cfg, meta={})

    class Preloaded(Registry):
        async def list_models(self):
            return {"object": "list", "data": [engine.info()]}

        async def pull(self, identifier):
            raise ModelNotFound(identifier)

        async def delete(self, model_id_):
            raise ModelNotFound(model_id_)

        async def get_engine(self, mid):
            if mid != model_id:
                raise ModelNotFound(mid)
            return engine

        async def sync_from_bucket(self, name, model_id=None):
            raise ModelNotFound(name)

        def stats(self):
            return {"models_loaded": [model_id]}

        def loaded_engines(self):
            # base Registry returns {} — expose the engine so the worker's
            # Prometheus exposition renders its per-model rows (the prefix
            # phase asserts hit counters off the wire, not in-process)
            return {model_id: engine}

    async def drive():
        broker = await EmbeddedBroker().start()
        worker = Worker(WorkerConfig(nats_url=broker.url), Preloaded())
        await worker.start()
        nc = await connect(broker.url)

        async def one_chat(tag: int, prompt: str, max_tokens: int,
                           gaps: list | None = None,
                           temperature: float = 0.8):
            body = json.dumps(
                {
                    "model": model_id,
                    "messages": [{"role": "user", "content": prompt}],
                    "max_tokens": max_tokens,
                    "temperature": temperature,
                    "seed": tag,
                    "stream": True,
                }
            ).encode()
            t0 = time.perf_counter()
            ttft = None
            prev = t0
            n_tok = prompt_toks = 0
            parse_fail = False
            error = None
            async for msg in nc.request_stream(
                "lmstudio.chat_model", body, timeout=1800.0, idle_timeout=900.0
            ):
                now = time.perf_counter()
                if (msg.headers or {}).get("Nats-Stream-Done") is not None:
                    try:
                        done = json.loads(msg.payload)
                        usage = done["data"]["response"]["usage"]
                        n_tok = usage["completion_tokens"]
                        prompt_toks = usage["prompt_tokens"]
                    except Exception:  # noqa: BLE001 — error envelope
                        parse_fail = True
                        try:  # keep the envelope's error string (shed vs other)
                            error = json.loads(msg.payload).get("error")
                        except Exception:  # noqa: BLE001
                            pass
                    break
                if ttft is None:
                    ttft = now - t0
                elif gaps is not None:
                    gaps.append((now, now - prev))  # (timestamp, inter-chunk gap)
                prev = now
            return {
                "ttft_s": ttft if ttft is not None else float("nan"),
                "wall_s": time.perf_counter() - t0,
                "completion_tokens": n_tok,
                "prompt_tokens": prompt_toks,
                "parse_fail": parse_fail,
                "error": error,
            }

        try:
            return await body_fn(nc, one_chat)
        finally:
            for step in (nc.close, worker.drain, broker.stop,
                         lambda: asyncio.to_thread(batcher.stop)):
                try:
                    await step()
                except Exception:  # noqa: BLE001 — best-effort teardown
                    pass

    return asyncio.run(drive())


def e2e_long_context_bench(cfg, params, model_id: str, n_long: int = 4,
                           long_tokens: int = 4200, xl_tokens: int = 7936) -> dict:
    """Long-context serving measured end-to-end, in TWO engines sized to the
    chip (the AOT compile path double-counts the donated KV cache, so an 8k
    ring affords ~3 slots next to 8.7 GB of int8 weights — a 4.6k ring
    affords 8):

    * wave engine (max_seq 4608): ``n_long`` concurrent clients each send a
      >= 4096-token prompt (full-history resend is the reference product's
      steady state, /root/reference/README.md:196-205) while 2 short
      streams decode throughout — their inter-chunk gap p95 bounds the
      stall chunked admission imposes on live streams;
    * XL engine (max_seq 8192, 2 slots): one ``xl_tokens`` prompt alone —
      the 8k-class point.

    Token counts are read back from usage.prompt_tokens (byte-level
    tokenizer: 1 ASCII char = 1 token), not assumed."""
    import asyncio

    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    tokenizer = _make_bench_tokenizer(cfg)
    wave_seq = int(os.environ.get("BENCH_LONG_SEQ", "4608"))
    slots = int(os.environ.get("BENCH_LONG_SLOTS", str(n_long + 2)))
    chunk = int(os.environ.get("BENCH_LONG_CHUNK", "512"))
    if wave_seq >= 4608:  # tiny smoke runs shrink everything via env
        assert long_tokens >= 4096, "the wave must carry >=4k-token prompts"

    coalesce = float(os.environ.get("BENCH_COALESCE_MS", "15"))
    wave_batcher = ContinuousBatcher(
        params, cfg, max_slots=slots, max_seq_len=wave_seq,
        buckets=[b for b in (512, 1024, 2048) if b < wave_seq] + [wave_seq],
        prefill_chunk=chunk, admit_coalesce_ms=coalesce,
    )

    async def wave_body(nc, one_chat):
        # warmup: compiles the singleton [1, chunk] prefill + finish, the
        # BATCHED chunked-admit programs at widths 2 and 4 ([m, chunk]
        # chunks + finish_admit_group), the short-prompt admit, and the
        # decode windows the measured phase reaches — all outside the
        # timed window
        # prompt lengths CLAMPED below the ring so env-shrunk smoke configs
        # (BENCH_LONG_SEQ=256) don't silently discard the warmup as
        # too-long errors and push the compiles into the measured window
        wlen = min(chunk + 256, wave_seq - 64)
        wlen2 = min(chunk + 300, wave_seq - 48)
        # deterministic chunk-program warmup FIRST: every (width, window)
        # chunked-prefill program, compiled directly — the pow2 window
        # ladder multiplied the program count, and chat-driven warmup
        # coverage races on arrival timing (a missed pair lands a
        # multi-second compile inside the measured TTFT; seen as the
        # 5.2 s long-wave TTFT in the r5 iteration runs)
        await asyncio.to_thread(_warm_retry, wave_batcher)
        # solo short + short pair: the measured phase starts with 2
        # interference shorts decoding alone at a COLD ring — that is the
        # smallest decode window and the mpad-2 group admit, two programs
        # none of the long warmups reach (the long note_admit wraps the
        # ring -> full-window decode). The r4-f compile log caught an
        # 11 s decode compile inside the measured wave from exactly this.
        await one_chat(30, SHORT_PROMPT, 24)
        await asyncio.gather(
            one_chat(31, SHORT_PROMPT, 24), one_chat(32, SHORT_PROMPT, 24)
        )
        await one_chat(0, make_long_prompt(wlen), 8)
        await asyncio.gather(
            one_chat(1, SHORT_PROMPT, 8),
            *(one_chat(2 + i, make_long_prompt(wlen2), 8) for i in range(2)),
        )
        # solo long at the TOP bucket: the singleton finish/decode programs
        # at the wave_seq bucket are otherwise first compiled INSIDE the
        # measured wave whenever one long straggles behind the group admit
        # (coalesce is only 15 ms) — the r05 e2e_long loss was exactly an
        # in-window remote_compile flaking mid-stream
        await one_chat(4, make_long_prompt(long_tokens), 8)
        # TWO passes at full width: a split warmup gather (e.g. 2+2) would
        # leave the width-4 chunk/finish programs uncompiled and their
        # ~20 s compile would land inside the measured wave (seen once in
        # the r4 iteration runs)
        for rep in range(2):
            await asyncio.gather(
                *(one_chat(5 + 10 * rep + i, make_long_prompt(long_tokens), 8)
                  for i in range(4))
            )
        await asyncio.sleep(0.75)  # drain in-flight zombie bursts

        # measured: 2 short interference streams decode while n_long long
        # prompts chunk-prefill through the same batcher
        s0 = wave_batcher.stats.snapshot()
        d0 = _phase_hists(wave_batcher)
        gaps: list[float] = []
        t0 = time.perf_counter()
        short_tasks = [
            asyncio.create_task(one_chat(10 + i, SHORT_PROMPT, 160, gaps=gaps))
            for i in range(2)
        ]
        await asyncio.sleep(0.3)  # shorts admitted + decoding first
        t_longs = time.perf_counter()
        longs = await asyncio.gather(
            *(one_chat(100 + i, make_long_prompt(long_tokens), 32)
              for i in range(n_long))
        )
        # prefill throughput over the LONGS' own window (send -> all four
        # complete); the full-wave wall below additionally waits for the
        # interference shorts' 160-token decode, which would otherwise
        # deflate "prefill" with unrelated decode time
        longs_wall = time.perf_counter() - t_longs
        shorts = await asyncio.gather(*short_tasks)
        wall = time.perf_counter() - t0
        phase = _phase_delta(wave_batcher, s0, d0)

        ttfts = sorted(r["ttft_s"] * 1e3 for r in longs if r["ttft_s"] == r["ttft_s"])
        gap_ms = sorted(g * 1e3 for _, g in gaps)
        total_prefill_toks = sum(r["prompt_tokens"] for r in longs)
        total_out = sum(r["completion_tokens"] for r in list(longs) + list(shorts))
        return {
            "clients": n_long,
            "prompt_tokens_each": longs[0]["prompt_tokens"],
            "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
            "ttft_max_ms": round(ttfts[-1], 1) if ttfts else 0.0,
            "prefill_tok_s": round(total_prefill_toks / longs_wall, 1),
            "wave_tok_s": round(total_out / wall, 1),
            "parse_failures": sum(1 for r in list(longs) + list(shorts)
                                  if r["parse_fail"]),
            "interference_gap_p50_ms": round(_pctl(gap_ms, 0.5), 1),
            "interference_gap_p95_ms": round(_pctl(gap_ms, 0.95), 1),
            "batcher_phase": phase,
            "max_seq_len": wave_seq,
            "prefill_chunk": chunk,
            "slots": slots,
        }

    long_wave = _drive_engine(cfg, params, model_id, tokenizer, wave_batcher,
                              wave_body)
    gc.collect()

    def xl_point(xl_seq: int, n_tokens: int) -> dict:
        """One N-token prompt served alone on a 2-slot engine with an
        xl_seq ring (2 slots x 16k int8 KV ~ 2.2 GB next to 8.7 GB int8
        weights — inside the AOT double-count budget). The model config's
        context length is raised to the ring size: ContinuousBatcher clamps
        max_seq_len to cfg.max_seq_len, which silently rejected 16k prompts
        on the 8k-configured 8B geometry."""
        xl_cfg = cfg.with_(max_seq_len=max(cfg.max_seq_len, xl_seq))
        xl_batcher = ContinuousBatcher(
            params, xl_cfg, max_slots=2, max_seq_len=xl_seq,
            buckets=[b for b in (512, 2048) if b < xl_seq] + [xl_seq],
            prefill_chunk=1024,
        )

        async def xl_body(nc, one_chat):
            # every chunk window's program, compiled deterministically (the
            # pow2 ladder is 4-5 programs at 8-16k; an unwarmed one's
            # multi-second compile would land inside the measured TTFT),
            # then one chat to warm admit/finish/decode programs
            await asyncio.to_thread(_warm_retry, xl_batcher, (1,))
            await one_chat(0, make_long_prompt(1536), 8)
            # full-length pass: warms the measured request's own full-window
            # decode program too (post-TTFT, but keeps wall honest)
            await one_chat(1, make_long_prompt(n_tokens), 8)
            xl = await one_chat(500, make_long_prompt(n_tokens), 32)
            return {
                "prompt_tokens": xl["prompt_tokens"],
                "ttft_ms": round(xl["ttft_s"] * 1e3, 1),
                "prefill_tok_s": (
                    round(xl["prompt_tokens"] / xl["ttft_s"], 1)
                    if xl["ttft_s"] == xl["ttft_s"] and xl["ttft_s"] > 0 else 0.0
                ),
                "completion_tokens": xl["completion_tokens"],
                "parse_fail": xl["parse_fail"],
                "max_seq_len": xl_seq,
            }

        out = _drive_engine(xl_cfg, params, model_id, tokenizer, xl_batcher,
                            xl_body)
        gc.collect()
        return out

    xl_single = xl_point(int(os.environ.get("BENCH_XL_SEQ", "8192")), xl_tokens)
    result = {"long_wave": long_wave, "xl_single": xl_single}
    # the 16k-class point: the same context length long_prefill proves
    # on-device, SERVED through chat_model (skipped for env-shrunk smokes)
    if os.environ.get("BENCH_XL16", "1") != "0" and wave_seq >= 4608:
        result["xl16_single"] = xl_point(16384, 15872)
    return result


# ---------------------------------------------------------------------------
# prefix cache: shared-system-prompt serving, cache ON vs OFF
# ---------------------------------------------------------------------------


def prefix_cache_bench(cfg, params, model_id: str) -> dict:
    """Shared-system-prompt serving with the prefix KV cache ON vs OFF
    (serve/prefix_cache.py): the same sequential turn mix — a fixed
    multi-chunk "system prompt + history" resent with a fresh tail each
    turn, the reference product's steady state — served twice on
    identically-sized engines. ON must beat OFF on BOTH TTFT p50 and total
    prefill seconds (only the uncached suffix is prefilled on a hit). The
    worker's Prometheus exposition is scraped so the hit counter is proven
    on the wire, not just in-process."""
    import asyncio

    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    tokenizer = _make_bench_tokenizer(cfg)
    seq = int(os.environ.get("BENCH_PREFIX_SEQ", "4608"))
    chunk = int(os.environ.get("BENCH_PREFIX_CHUNK", "512"))
    slots = int(os.environ.get("BENCH_PREFIX_SLOTS", "4"))
    n_turns = int(os.environ.get("BENCH_PREFIX_TURNS", "6"))
    blocks = int(os.environ.get("BENCH_PREFIX_BLOCKS", "64"))
    # the shared prefix ends 17 tokens past a chunk edge, so every reuse is
    # a PARTIAL hit resuming mid-prompt (the common case: a resent history
    # rarely ends exactly on a block boundary)
    shared = make_long_prompt(min(5 * chunk, seq // 2) + 17)

    def run_mode(cache_blocks: int) -> dict:
        batcher = ContinuousBatcher(
            params, cfg, max_slots=slots, max_seq_len=seq,
            buckets=[b for b in (512, 1024, 2048) if b < seq] + [seq],
            prefill_chunk=chunk, prefix_cache_blocks=cache_blocks,
        )

        async def body(nc, one_chat):
            await asyncio.to_thread(_warm_retry, batcher, (1,))
            warm = make_long_prompt(min(chunk + 300, seq - 64))
            await one_chat(900, warm, 8)
            if cache_blocks > 0:
                # resend: the repeat takes the HIT path, compiling the
                # cached-block write + suffix programs outside the window
                await one_chat(901, warm, 8)
            s0 = batcher.stats.snapshot()
            h0 = _phase_hists(batcher)
            t0 = time.perf_counter()
            turns = [
                await one_chat(1000 + i, f"{shared} [turn {i:03d}] reply now", 16)
                for i in range(n_turns)
            ]
            wall = time.perf_counter() - t0
            h1 = _phase_hists(batcher)
            phase = _phase_delta(batcher, s0, h0)
            prefill_s = (h1["prefill_ms"] - h0["prefill_ms"]).total / 1e3
            hit_total = 0.0
            prom_line = ""
            if cache_blocks > 0:
                try:
                    reply = await nc.request("lmstudio.metrics.prom", b"",
                                             timeout=30.0)
                    for ln in reply.payload.decode().splitlines():
                        if ln.startswith("lmstudio_prefix_cache_hit_tokens_total"):
                            prom_line = ln
                            hit_total = float(ln.rsplit(" ", 1)[-1])
                            break
                except Exception:  # noqa: BLE001 — exposition is best-effort
                    pass
            ttfts = sorted(r["ttft_s"] * 1e3 for r in turns
                           if r["ttft_s"] == r["ttft_s"])
            out = {
                "turns": n_turns,
                "prompt_tokens_each": turns[0]["prompt_tokens"],
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "ttft_max_ms": round(ttfts[-1], 1) if ttfts else 0.0,
                "prefill_s": round(prefill_s, 3),
                "wall_s": round(wall, 2),
                "parse_failures": sum(1 for r in turns if r["parse_fail"]),
                "batcher_phase": phase,
            }
            pc = batcher.prefix_cache
            if pc is not None:
                out["cache"] = pc.stats()
                out["prom_hit_tokens_total"] = hit_total
                out["prom_line"] = prom_line
            return out

        out = _drive_engine(cfg, params, model_id, tokenizer, batcher, body)
        gc.collect()
        return out

    on = run_mode(blocks)
    off = run_mode(0)
    return {
        "max_seq_len": seq,
        "prefill_chunk": chunk,
        "shared_prefix_tokens": len(shared),
        "cache_on": on,
        "cache_off": off,
        "ttft_p50_speedup": (
            round(off["ttft_p50_ms"] / on["ttft_p50_ms"], 2)
            if on["ttft_p50_ms"] else 0.0
        ),
        "prefill_s_saved": round(off["prefill_s"] - on["prefill_s"], 3),
    }


def kv_tiering_bench(cfg, params, model_id: str, *, seq: int | None = None,
                     chunk: int | None = None, slots: int | None = None,
                     n_prompts: int | None = None,
                     max_new: int | None = None) -> dict:
    """Hierarchical KV tiers (serve/kv_tiers.py) under a working set that
    CANNOT fit the HBM prefix budget: ``n_prompts`` distinct multi-chunk
    documents, each served twice, against a prefix cache sized for ONE of
    them. With tiering ON, round-1 evictions demote to the host tier and
    round-2 admits promote back — prefix hit tokens and TTFT p50 must beat
    the tiering-OFF run (where round 2 re-prefills almost everything), with
    ZERO ``kv_pool``-cause sheds. A third engine built on the same spill
    store with no live donor then proves restart-with-warm-cache: its first
    repeat prompt scores nonzero hit tokens. Decode step p50 ON/OFF is
    reported as the demotion-overhead ratio."""
    import asyncio

    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
    from nats_llm_studio_tpu.serve.kv_tiers import KVTierManager, MemorySpillStore

    tokenizer = _make_bench_tokenizer(cfg)
    seq = seq or int(os.environ.get("BENCH_KV_TIER_SEQ", "512"))
    chunk = chunk or int(os.environ.get("BENCH_KV_TIER_CHUNK", "64"))
    slots = slots or int(os.environ.get("BENCH_KV_TIER_SLOTS", "2"))
    n_prompts = n_prompts or int(os.environ.get("BENCH_KV_TIER_PROMPTS", "20"))
    max_new = max_new or int(os.environ.get("BENCH_KV_TIER_MAX_NEW", "8"))
    # the cache budget holds exactly ONE document's full chunks; the
    # working set is n_prompts documents — 10x+ the cacheable budget
    n_chunks = 2
    prompt_tokens = n_chunks * chunk + 17
    block_tokens = 16
    cache_blocks = n_chunks * (chunk // block_tokens)
    # pool: live slots + the cache budget + promotion scratch — tight
    # enough that swap-don't-shed matters, big enough that honest serving
    # never needs a kv_pool shed
    per_slot = -(-(prompt_tokens + max_new) // block_tokens)
    pool_blocks = slots * per_slot + 3 * cache_blocks + 2

    def doc(i: int) -> str:
        return (f"[doc {i:03d}] " + make_long_prompt(prompt_tokens))[:prompt_tokens]

    spill = MemorySpillStore()  # survives across the engines below

    def build(tier_on: bool) -> ContinuousBatcher:
        b = ContinuousBatcher(
            params, cfg, max_slots=slots, max_seq_len=seq,
            buckets=[x for x in (128, 256) if x < seq] + [seq],
            prefill_chunk=chunk, prefix_cache_blocks=cache_blocks,
            kv_block_tokens=block_tokens, kv_pool_blocks=pool_blocks,
        )
        if tier_on:
            # host budget 0 = spill-through: every demoted chunk goes
            # straight to the (in-process) Object Store, so the restart
            # sub-phase deterministically finds complete chains there.
            # Host-LRU behavior is pinned by tests/test_kv_tiers.py; this
            # phase measures the pool↔tier swap and the cold-tier restart.
            b.kv_tiers = KVTierManager(
                int(os.environ.get("BENCH_KV_TIER_HOST_BYTES", "0")),
                chunk_tokens=b.prefill_chunk, spill=spill,
                namespace="kv/bench", max_spill_objects=256,
            )
        return b

    def run_mode(tier_on: bool) -> dict:
        batcher = build(tier_on)

        async def body(nc, one_chat):
            await asyncio.to_thread(_warm_retry, batcher, (1,))
            await one_chat(900, doc(999), max_new)
            rounds = []
            for rnd in (1, 2):
                if tier_on and rnd == 2:
                    # round-1 demotions must be durably in the spill store
                    # before the repeat wave tries to promote them back
                    await asyncio.to_thread(batcher.kv_tiers.flush)
                s0 = batcher.stats.snapshot()
                h0 = _phase_hists(batcher)
                hit0 = batcher.prefix_cache.hit_tokens
                t0 = time.perf_counter()
                reqs = [
                    await one_chat(rnd * 1000 + i, doc(i), max_new)
                    for i in range(n_prompts)
                ]
                wall = time.perf_counter() - t0
                ttfts = sorted(r["ttft_s"] * 1e3 for r in reqs
                               if r["ttft_s"] == r["ttft_s"])
                rounds.append({
                    "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                    "hit_tokens": batcher.prefix_cache.hit_tokens - hit0,
                    "wall_s": round(wall, 2),
                    "batcher_phase": _phase_delta(batcher, s0, h0),
                })
            sheds = dict(batcher.stats.shed_cause_counts())
            out = {
                "round1": rounds[0],
                "round2": rounds[1],
                "shed_by_cause": sheds,
                "pool": batcher.pool_stats(),
                "cache": batcher.prefix_cache.stats(),
            }
            tier = batcher.tier_stats()
            if tier is not None:
                out["tier"] = tier
            if tier_on:
                if sheds.get("kv_pool", 0):
                    raise RuntimeError(
                        f"tiering on but {sheds['kv_pool']} kv_pool sheds — "
                        "swap-don't-shed is broken"
                    )
                if not tier or tier.get("demoted_chunks", 0) <= 0:
                    raise RuntimeError("tiering on but nothing demoted under "
                                       "10x working-set pressure")
                if tier.get("promoted_chunks", 0) <= 0:
                    raise RuntimeError("tiering on but round 2 promoted "
                                       "nothing back from the host tier")
            return out

        out = _drive_engine(cfg, params, model_id, tokenizer, batcher, body)
        gc.collect()
        return out

    on = run_mode(True)
    off = run_mode(False)

    # -- restart-with-warm-cache: fresh engine, same spill store, NO donor --
    restart_b = build(True)
    restart_b.start()
    warm_tokens = 0
    for export in restart_b.kv_tiers.warm_exports(limit=4):
        warm_tokens += int(restart_b.import_prefix_blocks(export).get("tokens", 0))

    async def restart_body(nc, one_chat):
        await asyncio.to_thread(_warm_retry, restart_b, (1,))
        hit0 = restart_b.prefix_cache.hit_tokens
        r = await one_chat(3000, doc(n_prompts - 1), max_new)
        return {
            "warm_imported_tokens": warm_tokens,
            "first_repeat_hit_tokens": restart_b.prefix_cache.hit_tokens - hit0,
            "ttft_ms": round(r["ttft_s"] * 1e3, 1),
        }

    restart = _drive_engine(cfg, params, model_id, tokenizer, restart_b,
                            restart_body)
    if restart["first_repeat_hit_tokens"] <= 0:
        raise RuntimeError(
            "restart with a populated spill tier served its first repeat "
            "prompt with zero prefix hit tokens (warm import broken)"
        )

    on_step = on["round2"]["batcher_phase"].get("batcher_decode_step_p50_ms", 0.0)
    off_step = off["round2"]["batcher_phase"].get("batcher_decode_step_p50_ms", 0.0)
    return {
        "prompts": n_prompts,
        "prompt_tokens_each": prompt_tokens,
        "pool_blocks": pool_blocks,
        "cache_blocks": cache_blocks,
        "working_set_blocks": n_prompts * cache_blocks,
        "tier_on": on,
        "tier_off": off,
        "restart": restart,
        "repeat_ttft_p50_speedup": (
            round(off["round2"]["ttft_p50_ms"] / on["round2"]["ttft_p50_ms"], 2)
            if on["round2"]["ttft_p50_ms"] else 0.0
        ),
        "repeat_hit_tokens_on_vs_off": [on["round2"]["hit_tokens"],
                                        off["round2"]["hit_tokens"]],
        "decode_step_p50_ratio": (
            round(on_step / off_step, 3) if off_step else 0.0
        ),
    }


# ---------------------------------------------------------------------------
# multi-tenant QoS: 3-class overload fairness + preempt vs shed-retry
# ---------------------------------------------------------------------------


def qos_bench(cfg, params, model_id: str = "bench/qos", *,
              slots: int | None = None, n_each: int | None = None,
              max_new: int | None = None) -> dict:
    """Multi-tenant QoS plane (serve/qos.py + batcher admission), driven at
    the batcher seam where the policy lives. Two sub-phases:

    * mix — a 3-class overload (batch/standard/premium tenants, interleaved
      arrival, queue bound far under the offered load) vs a premium-only
      solo baseline of identical geometry. DRR admission must keep premium
      p95 TTFT within ``BENCH_QOS_TTFT_FACTOR`` (default 1.25) of solo,
      with ZERO premium sheds — 100% of the shed lands on batch/standard
      (the depth + fair_share causes).
    * preempt — a premium admit against a full KV pool: with preemption ON
      the batch victim parks on the host tier (resuming bit-identically)
      and premium serves immediately; with slot-suspend OFF the premium
      request takes the kv_pool shed and retries until the pool frees.
      The wall-clock ratio is the cost of shed-retry the preempt path
      removes."""
    import asyncio

    from nats_llm_studio_tpu.engine.generator import SamplingParams
    from nats_llm_studio_tpu.serve.batcher import (
        BatcherOverloaded,
        ContinuousBatcher,
    )
    from nats_llm_studio_tpu.transport.envelope import shed_cause_of

    slots = slots or int(os.environ.get("BENCH_QOS_SLOTS", "2"))
    n_each = n_each or int(os.environ.get("BENCH_QOS_REQS", "6"))
    max_new = max_new or int(os.environ.get("BENCH_QOS_NEW", "8"))
    prompt_len = int(os.environ.get("BENCH_QOS_PROMPT", "48"))
    max_queue = int(os.environ.get("BENCH_QOS_QUEUE", "8"))
    ttft_factor = float(os.environ.get("BENCH_QOS_TTFT_FACTOR", "1.25"))

    def toks(i: int) -> list[int]:
        return [(j * 7 + 3 + i * 13) % 509 for j in range(prompt_len)]

    async def timed_submit(b, prompt, tenant, priority, n_new):
        sp = SamplingParams(temperature=0.0, max_tokens=n_new)
        t0 = time.perf_counter()
        ttft = None
        out = []
        try:
            async for t in b.submit(prompt, sp, tenant=tenant,
                                    priority=priority):
                if ttft is None:
                    ttft = time.perf_counter() - t0
                out.append(t)
        except BatcherOverloaded as e:
            return {"ok": False, "tenant": tenant,
                    "cause": shed_cause_of(str(e)) or "overload"}
        return {"ok": True, "tenant": tenant, "tokens": out,
                "ttft_ms": round((ttft or 0.0) * 1e3, 2),
                "wall_ms": round((time.perf_counter() - t0) * 1e3, 2)}

    def mix_batcher() -> ContinuousBatcher:
        return ContinuousBatcher(
            params, cfg, max_slots=slots, max_seq_len=64 + prompt_len,
            buckets=[64 + prompt_len], max_queue=max_queue,
            admit_coalesce_ms=25.0,
        )

    # -- mix: premium-only solo baseline, then the 3-class overload ----------
    async def run_solo():
        b = mix_batcher()
        try:
            await timed_submit(b, toks(99), "warm", "standard", 2)
            rs = await asyncio.gather(*[
                timed_submit(b, toks(i), "acme", "premium", max_new)
                for i in range(n_each)
            ])
            return sorted(r["ttft_ms"] for r in rs if r["ok"])
        finally:
            b.stop()

    async def run_overload():
        b = mix_batcher()
        try:
            await timed_submit(b, toks(99), "warm", "standard", 2)
            jobs = []
            for i in range(n_each):
                jobs.append(("hobby", "batch", toks(100 + i)))
                jobs.append(("corp", "standard", toks(200 + i)))
                jobs.append(("acme", "premium", toks(i)))
            rs = await asyncio.gather(*[
                timed_submit(b, p, t, c, max_new) for t, c, p in jobs
            ])
            snap = b.tenant_stats.snapshot()
            return rs, snap, dict(b.stats.shed_cause_counts())
        finally:
            b.stop()

    solo_ttfts = asyncio.run(run_solo())
    gc.collect()
    results, tenants, causes = asyncio.run(run_overload())
    gc.collect()
    prem = [r for r in results if r["tenant"] == "acme"]
    prem_ttfts = sorted(r["ttft_ms"] for r in prem if r["ok"])
    shed_by_tenant = {t: row["shed"] for t, row in tenants.items()
                      if row["shed"]}
    if [r for r in prem if not r["ok"]] or shed_by_tenant.get("acme", 0):
        raise RuntimeError(
            f"premium was shed under the 3-class overload: {shed_by_tenant} "
            "(shed must land on batch/standard only)"
        )
    if sum(shed_by_tenant.values()) <= 0:
        raise RuntimeError(
            "overload mix shed nothing — the phase measured no contention "
            f"(causes: {causes})"
        )
    solo_p95 = _pctl(solo_ttfts, 0.95)
    prem_p95 = _pctl(prem_ttfts, 0.95)
    ratio = round(prem_p95 / solo_p95, 3) if solo_p95 else 0.0
    if solo_p95 and ratio > ttft_factor:
        raise RuntimeError(
            f"premium p95 TTFT degraded {ratio}x vs solo under overload "
            f"(bound {ttft_factor}x): solo {solo_p95:.1f} ms, "
            f"mix {prem_p95:.1f} ms"
        )
    mix = {
        "offered_per_class": n_each,
        "solo_ttft_p95_ms": round(solo_p95, 2),
        "premium_ttft_p95_ms": round(prem_p95, 2),
        "premium_ttft_ratio": ratio,
        "premium_served": sum(1 for r in prem if r["ok"]),
        "shed_by_tenant": shed_by_tenant,
        "shed_by_cause": causes,
        "served_by_tenant": {t: row["served"] for t, row in tenants.items()},
    }

    # -- preempt: premium admit on a full pool, preempt ON vs suspend OFF ----
    pre_kw = dict(max_slots=2, max_seq_len=64, buckets=[8, 64],
                  prefill_chunk=32, kv_block_tokens=32, kv_pool_blocks=3,
                  decode_burst=1, admit_coalesce_ms=0.0, paged=True)
    pa = [(j * 7 + 3) % 509 for j in range(33)]
    pb = [(j * 11 + 5) % 509 for j in range(40)]
    na, nb = 12, 8

    async def serve_plain(b, prompt, n_new):
        sp = SamplingParams(temperature=0.0, max_tokens=n_new)
        return [t async for t in b.submit(prompt, sp)]

    ample = ContinuousBatcher(params, cfg, **{**pre_kw, "kv_pool_blocks": 0})
    try:
        want_a = asyncio.run(serve_plain(ample, pa, na))
    finally:
        ample.stop()
    gc.collect()

    async def pressure(b, retry_b: bool):
        """A (batch) decodes first; once 2 tokens arrive, B (premium)
        lands on the exhausted pool. ``retry_b`` = client-side retry loop
        for the shed-mode engine."""
        spa = SamplingParams(temperature=0.0, max_tokens=na)
        spb = SamplingParams(temperature=0.0, max_tokens=nb)
        started = asyncio.get_running_loop().create_future()

        async def run_a():
            t0 = time.perf_counter()
            out = []
            async for t in b.submit(pa, spa, tenant="hobby",
                                    priority="batch"):
                out.append(t)
                if len(out) == 2 and not started.done():
                    started.set_result(None)
            return out, (time.perf_counter() - t0) * 1e3

        async def run_b():
            t0 = time.perf_counter()
            retries = 0
            while True:
                try:
                    out = [t async for t in b.submit(
                        pb, spb, tenant="acme", priority="premium")]
                    return out, (time.perf_counter() - t0) * 1e3, retries
                except BatcherOverloaded:
                    if not retry_b:
                        raise
                    retries += 1
                    await asyncio.sleep(0.025)

        ta = asyncio.ensure_future(run_a())
        await started
        tb = asyncio.ensure_future(run_b())
        (a_toks, a_ms), (b_toks, b_ms, retries) = await asyncio.gather(ta, tb)
        return a_toks, a_ms, b_ms, retries

    b_on = ContinuousBatcher(params, cfg, **{**pre_kw, "qos_preempt": True})
    try:
        a_toks, a_on_ms, b_on_ms, _ = asyncio.run(pressure(b_on, False))
        preempted = b_on.tenant_stats.snapshot().get(
            "hobby", {}).get("preempted", 0)
        on_sheds = dict(b_on.stats.shed_cause_counts())
    finally:
        b_on.stop()
    gc.collect()
    if preempted < 1:
        raise RuntimeError("premium admit on a full pool preempted nothing")
    if on_sheds.get("kv_pool", 0):
        raise RuntimeError(
            f"preempt mode shed {on_sheds['kv_pool']}x on kv_pool — "
            "preempt-to-host-tier is broken"
        )
    if a_toks != want_a:
        raise RuntimeError(
            "preempted batch slot did not resume bit-identically "
            f"({len(a_toks)} vs {len(want_a)} tokens)"
        )

    b_off = ContinuousBatcher(params, cfg, **{**pre_kw, "kv_suspend": False})
    try:
        _, a_off_ms, b_off_ms, retries = asyncio.run(pressure(b_off, True))
        off_sheds = dict(b_off.stats.shed_cause_counts())
    finally:
        b_off.stop()
    gc.collect()
    if off_sheds.get("kv_pool", 0) < 1:
        raise RuntimeError(
            "shed-retry mode never shed on kv_pool — the comparison "
            f"measured nothing (causes: {off_sheds})"
        )

    return {
        "mix": mix,
        "preempt": {
            "victim_resumed_bit_identical": True,
            "victims_preempted": preempted,
            "premium_wall_preempt_ms": round(b_on_ms, 1),
            "premium_wall_shed_retry_ms": round(b_off_ms, 1),
            "shed_retry_attempts": retries,
            "shed_retry_cost_ratio": (
                round(b_off_ms / b_on_ms, 2) if b_on_ms else 0.0
            ),
            "victim_wall_preempt_ms": round(a_on_ms, 1),
            "victim_wall_shed_mode_ms": round(a_off_ms, 1),
            "kv_pool_sheds_shed_mode": off_sheds.get("kv_pool", 0),
        },
    }


# ---------------------------------------------------------------------------
# speculative decoding: prompt-lookup drafts, spec ON vs OFF
# ---------------------------------------------------------------------------


def make_incompressible_prompt(n_tokens: int, seed: int = 3) -> str:
    """~n_tokens of pseudo-random ASCII letters: no repeated n-gram for the
    prompt-lookup index to hit (the adversarial mix for spec decoding)."""
    import random as _random

    r = _random.Random(seed)
    letters = "abcdefghijklmnopqrstuvwxyz "
    return "".join(r.choice(letters) for _ in range(n_tokens))


def spec_decode_bench(cfg, params, model_id: str, *, seq: int | None = None,
                      n_reqs: int | None = None, max_new: int | None = None,
                      spec_k: int | None = None) -> dict:
    """Low-occupancy serving with speculative decoding ON vs OFF
    (serve/spec.py): two prompt mixes — repetition-heavy (greedy; the
    n-gram index hits, drafts accept, decode skips ahead) and
    incompressible (sampled; near-zero hits, measures the overhead floor)
    — each served on spec-on and spec-off engines of identical geometry.
    Reports client-side decode tok/s and TTFT p50 per mode plus the
    drafted/accepted counters scraped off the worker's Prometheus
    exposition (proving the acceptance rate on the wire). Spec-on must
    beat spec-off on the repetition mix at low batch; the incompressible
    mix bounds the regression when drafting never pays."""
    import asyncio

    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    tokenizer = _make_bench_tokenizer(cfg)
    seq = seq or int(os.environ.get("BENCH_SPEC_SEQ", "1024"))
    slots = int(os.environ.get("BENCH_SPEC_SLOTS", "4"))
    n_reqs = n_reqs or int(os.environ.get("BENCH_SPEC_REQS", "8"))
    max_new = max_new or int(os.environ.get("BENCH_SPEC_NEW", "96"))
    spec_k = spec_k or int(os.environ.get("BENCH_SPEC_K", "6"))
    prompt_len = min(max(64, seq // 4), seq - max_new - 2 * (spec_k + 1))

    # repetition-heavy: a looped phrase (the byte-level bench tokenizer
    # turns the repeats into recurring token n-grams) decoded GREEDILY, so
    # generated continuations recur too; incompressible: random letters,
    # sampled at temperature 0.8
    rep_prompt = make_long_prompt(prompt_len)
    inc_prompt = make_incompressible_prompt(prompt_len)
    mixes = [("repetition", rep_prompt, 0.0), ("incompressible", inc_prompt, 0.8)]

    def run_mode(k: int, mix_name: str, prompt: str, temperature: float) -> dict:
        batcher = ContinuousBatcher(
            params, cfg, max_slots=slots, max_seq_len=seq,
            buckets=[b for b in (256, 512) if b < seq] + [seq],
            spec_decode_k=k, spec_max_active=slots,
        )

        async def body(nc, one_chat):
            # warm admit/decode/verify programs outside the timed window —
            # same prompt shape (same prefill bucket) and same generation
            # length (same decode/verify window ladder) as the measured
            # requests, or their compiles land inside the window
            await one_chat(800, f"{prompt} [req 800]", max_new,
                           temperature=temperature)
            if k > 0:
                # a greedy repetition-heavy chat reliably drafts, forcing
                # the verify program to compile here even when THIS mix
                # rarely proposes (the incompressible warm chat may never
                # hit, leaving spec_verify cold)
                await one_chat(801, f"{rep_prompt} [req 801]", max_new,
                               temperature=0.0)
            s0 = batcher.stats.snapshot()
            h0 = _phase_hists(batcher)
            t0 = time.perf_counter()
            sem = asyncio.Semaphore(slots)

            async def one(i: int):
                async with sem:
                    # unique suffix so admits don't collapse into the
                    # prefix cache; the shared body still feeds the n-gram
                    # index
                    return await one_chat(
                        1000 + i, f"{prompt} [req {i:03d}]", max_new,
                        temperature=temperature,
                    )

            reqs = await asyncio.gather(*[one(i) for i in range(n_reqs)])
            wall = time.perf_counter() - t0
            phase = _phase_delta(batcher, s0, h0)
            ttfts = sorted(r["ttft_s"] * 1e3 for r in reqs
                           if r["ttft_s"] == r["ttft_s"])
            decode_tok = sum(max(0, r["completion_tokens"] - 1) for r in reqs)
            decode_s = sum(r["wall_s"] - r["ttft_s"] for r in reqs
                           if r["ttft_s"] == r["ttft_s"])
            out = {
                "requests": n_reqs,
                "completion_tokens": sum(r["completion_tokens"] for r in reqs),
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "decode_tok_s": (
                    round(decode_tok / decode_s, 1) if decode_s > 0 else 0.0
                ),
                "wall_s": round(wall, 2),
                "parse_failures": sum(1 for r in reqs if r["parse_fail"]),
                "batcher_phase": phase,
            }
            s1 = batcher.stats.snapshot()
            out["verifies"] = s1["spec_verifies"] - s0["spec_verifies"]
            drafted = s1["spec_drafted"] - s0["spec_drafted"]
            accepted = s1["spec_accepted"] - s0["spec_accepted"]
            out["drafted"] = drafted
            out["accepted"] = accepted
            if drafted:
                out["accept_rate"] = round(accepted / drafted, 3)
            if k > 0:
                try:  # prove the counters on the wire, not just in-process
                    reply = await nc.request("lmstudio.metrics.prom", b"",
                                             timeout=30.0)
                    for ln in reply.payload.decode().splitlines():
                        if ln.startswith(("lmstudio_spec_drafted_total",
                                          "lmstudio_spec_accepted_total")):
                            out.setdefault("prom_lines", []).append(ln)
                except Exception:  # noqa: BLE001 — exposition is best-effort
                    pass
            return out

        out = _drive_engine(cfg, params, model_id, tokenizer, batcher, body)
        gc.collect()
        return out

    result: dict = {"max_seq_len": seq, "slots": slots, "spec_k": spec_k,
                    "max_new": max_new}
    for mix_name, prompt, temperature in mixes:
        on = run_mode(spec_k, mix_name, prompt, temperature)
        off = run_mode(0, mix_name, prompt, temperature)
        result[mix_name] = {
            "temperature": temperature,
            "spec_on": on,
            "spec_off": off,
            "decode_speedup": (
                round(on["decode_tok_s"] / off["decode_tok_s"], 2)
                if off["decode_tok_s"] else 0.0
            ),
        }
    return result


# ---------------------------------------------------------------------------
# paged KV: one refcounted block pool vs contiguous per-slot rings
# ---------------------------------------------------------------------------


def paged_kv_bench(cfg, params, model_id: str, *, seq: int | None = None,
                   slots: int | None = None, max_new: int | None = None) -> dict:
    """The paged-KV block pool (serve/block_pool.py) against the legacy
    contiguous per-slot rings, at the SAME KV HBM budget:

    * capacity: the legacy engine worst-case-sizes ``slots`` rows of
      ``seq`` tokens each; the paged engine gets a pool of exactly that
      many blocks but 2x the slot count, and the same closed-loop load
      (2x ``slots`` concurrent clients, typical prompts ~seq/8) must run
      them all concurrently — peak_active_slots proves >= 1.5x live slots
      in the same bytes, and the admit-queue p95 delta shows the queueing
      the extra slots absorb (the r05 overload mix hit 6.9 s p95 once its
      96 worst-case rows saturated);
    * sharing: one engine with the prefix cache, a chunk-aligned prompt
      admitted once then resent by 2x ``slots`` concurrent clients — every
      resend must take the FULL-hit zero-copy path (block-table incref,
      no KV copy program at all): the pool gauges prove it
      (blocks_shared > 0 while the sharers decode, cow_copies delta 0,
      full_hits == resends), and the worker's Prometheus exposition is
      scraped so the gauges are proven on the wire."""
    import asyncio

    from nats_llm_studio_tpu.parallel.memory import kv_pool_block_bytes
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    tokenizer = _make_bench_tokenizer(cfg)
    seq = seq or int(os.environ.get("BENCH_PAGED_SEQ", "1024"))
    slots = slots or int(os.environ.get("BENCH_PAGED_SLOTS", "8"))
    max_new = max_new or int(os.environ.get("BENCH_PAGED_NEW", "32"))
    chunk = int(os.environ.get("BENCH_PAGED_CHUNK",
                               str(max(16, min(256, seq // 4)))))
    rounds = int(os.environ.get("BENCH_PAGED_ROUNDS", "2"))
    # effective block size: the batcher snaps kv_block_tokens down to
    # divide the prefill chunk — mirror it so the budget math is exact
    T = 16
    while T > 1 and chunk % T:
        T //= 2
    # the legacy engine's whole KV budget, expressed in pool blocks: that
    # exact block count IS the paged engine's pool (same bytes, one null
    # block modulo) — any slot-count win is layout, not extra HBM
    budget_blocks = slots * (-(-seq // T))
    budget_bytes = budget_blocks * kv_pool_block_bytes(
        cfg, T, kv_quant=cfg.kv_quant
    )
    content_len = max(16, seq // 8)  # typical prompt << worst-case seq
    workers = 2 * slots
    buckets = [b for b in (64, 256, 512) if b < seq] + [seq]

    def run_capacity(paged: bool) -> dict:
        mode_slots = 2 * slots if paged else slots
        batcher = ContinuousBatcher(
            params, cfg, max_slots=mode_slots, max_seq_len=seq,
            buckets=buckets, prefill_chunk=chunk,
            paged=paged, kv_pool_blocks=budget_blocks if paged else 0,
        )

        async def body(nc, one_chat):
            # warm the singleton + group admit programs and the decode
            # windows the measured load reaches, outside the timed window
            prompt = make_long_prompt(content_len)
            await one_chat(800, f"{prompt} [w]", max_new, temperature=0.0)
            await asyncio.gather(*(
                one_chat(801 + i, f"{prompt} [w{i}]", max_new, temperature=0.0)
                for i in range(min(8, mode_slots))
            ))
            s0 = batcher.stats.snapshot()
            h0 = _phase_hists(batcher)

            async def client(i: int):
                out = []
                for r in range(rounds):
                    out.append(await one_chat(
                        1000 + 16 * (rounds * i + r),
                        f"{prompt} [c {i:02d}.{r}]", max_new, temperature=0.0,
                    ))
                return out

            t0 = time.perf_counter()
            per = await asyncio.gather(*(client(i) for i in range(workers)))
            wall = time.perf_counter() - t0
            phase = _phase_delta(batcher, s0, h0)
            reqs = [r for p in per for r in p]
            ttfts = sorted(r["ttft_s"] * 1e3 for r in reqs
                           if r["ttft_s"] == r["ttft_s"])
            out = {
                "paged": paged,
                "slots": mode_slots,
                "clients": workers,
                "completed": sum(1 for r in reqs if not r["parse_fail"]),
                "parse_failures": sum(1 for r in reqs if r["parse_fail"]),
                "served_tok_s": round(
                    sum(r["completion_tokens"] for r in reqs) / wall, 1
                ),
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "ttft_p95_ms": round(_pctl(ttfts, 0.95), 1),
                "peak_active_slots": batcher.stats.peak_active,
                "wall_s": round(wall, 2),
                "batcher_phase": phase,
            }
            pool = batcher.pool_stats()
            if pool is not None:
                out["pool"] = pool
            return out

        out = _drive_engine(cfg, params, model_id, tokenizer, batcher, body)
        gc.collect()
        return out

    def run_sharing() -> dict:
        n_hits = 2 * slots
        batcher = ContinuousBatcher(
            params, cfg, max_slots=n_hits, max_seq_len=seq,
            buckets=buckets, prefill_chunk=chunk, paged=True,
            prefix_cache_blocks=6 * max(1, chunk // T),
        )

        async def body(nc, one_chat):
            await asyncio.to_thread(_warm_retry, batcher, (1,))
            # measure the template overhead with an UNRELATED probe, then
            # pad the shared prompt to land exactly on a chunk edge: the
            # resend's cached prefix covers ALL n tokens, which is the
            # full-hit (sample-from-cached-logits, zero-prefill) path
            probe = await one_chat(700, "p" * 64, 4)
            overhead = probe["prompt_tokens"] - 64
            base = make_long_prompt(chunk + 23)
            pad = (-(len(base) + overhead)) % batcher.prefill_chunk
            prompt = base + "x" * pad
            miss = await one_chat(701, prompt, max_new, temperature=0.0)
            # one warm resend: the full-hit path's sample-from-cached-logits
            # program compiles here, outside the measured resend wave
            await one_chat(702, prompt, max_new, temperature=0.0)
            p0 = batcher.pool_stats()
            c0 = batcher.prefix_cache.counters()
            shared_peak = 0
            done_evt = asyncio.Event()

            async def poll_shared():
                # blocks_shared is only nonzero WHILE sharers hold refs on
                # the cached blocks (it falls back to cache-only refs when
                # their slots free) — sample it in flight
                nonlocal shared_peak
                while not done_evt.is_set():
                    st = batcher.pool_stats()
                    if st is not None:
                        shared_peak = max(shared_peak, st["blocks_shared"])
                    await asyncio.sleep(0.005)

            poller = asyncio.create_task(poll_shared())
            t0 = time.perf_counter()
            hits = await asyncio.gather(*(
                one_chat(710 + i, prompt, max_new, temperature=0.0)
                for i in range(n_hits)
            ))
            wall = time.perf_counter() - t0
            done_evt.set()
            await poller
            p1 = batcher.pool_stats()
            c1 = batcher.prefix_cache.counters()
            prom_lines: list[str] = []
            try:  # prove the gauges on the wire, not just in-process
                reply = await nc.request("lmstudio.metrics.prom", b"",
                                         timeout=30.0)
                prom_lines = [
                    ln for ln in reply.payload.decode().splitlines()
                    if ln.startswith("lmstudio_kv_pool_")
                ][:12]
            except Exception:  # noqa: BLE001 — exposition is best-effort
                pass
            ttfts = sorted(r["ttft_s"] * 1e3 for r in hits
                           if r["ttft_s"] == r["ttft_s"])
            full_hits = c1["full_hits"] - c0["full_hits"]
            cow = p1["cow_copies"] - p0["cow_copies"]
            return {
                "resends": n_hits,
                "prompt_tokens": miss["prompt_tokens"],
                "parse_failures": sum(1 for r in hits if r["parse_fail"]),
                "full_hits": full_hits,
                "cow_copies": cow,
                "zero_copy": bool(full_hits == n_hits and cow == 0),
                "blocks_shared_peak": shared_peak,
                "miss_ttft_ms": round(miss["ttft_s"] * 1e3, 1)
                if miss["ttft_s"] == miss["ttft_s"] else 0.0,
                "hit_ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "hit_ttft_p95_ms": round(_pctl(ttfts, 0.95), 1),
                "wall_s": round(wall, 2),
                "pool": p1,
                "prom_lines": prom_lines,
            }

        out = _drive_engine(cfg, params, model_id, tokenizer, batcher, body)
        gc.collect()
        return out

    paged_cap = run_capacity(True)
    legacy_cap = run_capacity(False)
    sharing = run_sharing()
    legacy_peak = max(1, legacy_cap.get("peak_active_slots", 1))
    return {
        "max_seq_len": seq,
        "prefill_chunk": chunk,
        "kv_block_tokens": T,
        "kv_budget_blocks": budget_blocks,
        "kv_budget_bytes": budget_bytes,
        "paged": paged_cap,
        "legacy": legacy_cap,
        "slots_ratio": round(
            paged_cap.get("peak_active_slots", 0) / legacy_peak, 2
        ),
        "admit_p95_ms_paged": paged_cap["batcher_phase"][
            "admit_queue_delay_p95_ms"],
        "admit_p95_ms_legacy": legacy_cap["batcher_phase"][
            "admit_queue_delay_p95_ms"],
        "prefix_sharing": sharing,
    }


# ---------------------------------------------------------------------------
# decode kernels: Pallas paged attention vs the XLA gather-view path, and
# grouped-int4 weights vs int8 at equal HBM
# ---------------------------------------------------------------------------


def decode_kernel_bench(cfg, params, *, batches=None, seq=None,
                        max_new=None, quant_batch=None) -> dict:
    """The Pallas paged-decode kernel (ops/paged_attention.py) against the
    XLA gather-view fallback on the SAME paged engine, plus grouped-int4
    weights against int8 at equal HBM:

    * kernel: for each batch width, one paged batcher per forced
      DECODE_KERNEL value serves the same closed greedy wave — decode
      step_ms p50/p95 from the batcher histograms, served tok/s, and the
      engine's first-seen decode-program count (stats.decode_recompiles:
      the Pallas grid spans the whole table width, so it must register no
      more program keys than the XLA window ladder). Greedy tokens must
      MATCH between the kernels — the bit-equivalence the unit tests prove
      per-program, re-proven here at wave scale. Off-TPU the forced Pallas
      path runs in interpreter mode — correct but slow — so the CPU smoke
      keeps the wave tiny and only the TPU step_ms numbers are meaningful
      (``backend`` records which kind this artifact is).
    * quant: fresh leaf-streamed params in int8 and grouped int4 through
      the device-scan decode bench — tok/s, measured weight bytes, and the
      paged-KV slots each mode funds at the int8 run's TOTAL budget
      (weights + quant_batch slots of ``seq``-token block-pool KV): the
      int4 tree's freed HBM must buy at least as many slots as int8.
    """
    import asyncio

    from nats_llm_studio_tpu.engine.generator import SamplingParams
    from nats_llm_studio_tpu.parallel.memory import kv_pool_block_bytes
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    backend = jax.default_backend()
    batches = batches or [int(x) for x in os.environ.get(
        "BENCH_DK_BATCHES", "32,96").split(",")]
    seq = seq or int(os.environ.get("BENCH_DK_SEQ", "512"))
    max_new = max_new or int(os.environ.get("BENCH_DK_NEW", "48"))
    prompt_len = max(8, seq // 16)
    out: dict = {"backend": backend, "max_seq_len": seq,
                 "decode_new": max_new}

    def run_wave(kernel: str, b: int) -> dict:
        # the knob is read once, at batcher construction — scope the forced
        # value to exactly that window so nothing else inherits it
        prev = os.environ.get("DECODE_KERNEL")
        os.environ["DECODE_KERNEL"] = kernel
        try:
            batcher = ContinuousBatcher(
                params, cfg, max_slots=b, max_seq_len=seq,
                buckets=[x for x in (64, 256) if x < seq] + [seq],
                paged=True,
            )
        finally:
            if prev is None:
                os.environ.pop("DECODE_KERNEL", None)
            else:
                os.environ["DECODE_KERNEL"] = prev
        sp = SamplingParams(temperature=0.0, max_tokens=max_new)
        base = list(range(2, 2 + prompt_len))

        async def one(i: int) -> list[int]:
            return [t async for t in batcher.submit(base + [2 + i % 64], sp)]

        async def wave() -> dict:
            await one(0)  # compile admit + decode programs off the clock
            s0 = batcher.stats.snapshot()
            h0 = _phase_hists(batcher)
            t0 = time.perf_counter()
            toks = await asyncio.gather(*(one(i) for i in range(b)))
            wall = time.perf_counter() - t0
            phase = _phase_delta(batcher, s0, h0)
            return {
                "kernel": batcher.decode_kernel,
                "batch": b,
                "served_tok_s": round(sum(len(t) for t in toks) / wall, 1),
                "wall_s": round(wall, 3),
                "decode_step_p50_ms": phase.get(
                    "batcher_decode_step_p50_ms", 0.0),
                "decode_step_p95_ms": phase.get(
                    "batcher_decode_step_p95_ms", 0.0),
                "decode_recompiles": batcher.stats.snapshot()[
                    "decode_recompiles"],
                "_toks": toks,
            }

        try:
            return asyncio.run(wave())
        finally:
            batcher.stop()
            gc.collect()

    kernels = {}
    for b in batches:
        xla = run_wave("xla", b)
        pal = run_wave("pallas", b)
        match = xla.pop("_toks") == pal.pop("_toks")
        kernels[f"b{b}"] = {
            "xla": xla,
            "pallas": pal,
            "greedy_match": match,
            "step_p50_ratio": round(
                pal["decode_step_p50_ms"] / xla["decode_step_p50_ms"], 3)
            if xla["decode_step_p50_ms"] else None,
        }
    out["kernel"] = kernels
    out["greedy_match_all"] = all(v["greedy_match"] for v in kernels.values())

    if os.environ.get("BENCH_DK_QUANT", "1") != "0":
        qb = quant_batch or int(os.environ.get(
            "BENCH_DK_QB", str(min(batches))))
        T = 16
        slot_bytes = (-(-seq // T)) * kv_pool_block_bytes(
            cfg, T, kv_quant=cfg.kv_quant)
        quant: dict = {}
        for mode in ("int8", "int4"):
            qparams = init_params_int8(cfg, seed=3, mode=mode)
            wbytes = int(sum(x.nbytes for x in jax.tree.leaves(qparams)))
            r = decode_bench(cfg, qparams, qb, prompt_len, seq,
                             max(8, max_new))
            del qparams
            gc.collect()
            quant[mode] = {**r, "weight_bytes": wbytes}
        budget = quant["int8"]["weight_bytes"] + qb * slot_bytes
        for mode in ("int8", "int4"):
            quant[mode]["slots_at_int8_budget"] = int(
                (budget - quant[mode]["weight_bytes"]) // slot_bytes)
        out["quant"] = quant
        out["int4_tok_s_ratio"] = round(
            quant["int4"]["tok_s"] / quant["int8"]["tok_s"], 3)
        out["int4_extra_slots"] = (quant["int4"]["slots_at_int8_budget"]
                                   - quant["int8"]["slots_at_int8_budget"])
    return out


# ---------------------------------------------------------------------------
# tensor-parallel serving: the SAME engine at tp=1 vs tp=N across the mesh
# ---------------------------------------------------------------------------


def tensor_parallel_bench(cfg, params, model_id: str, *, seq: int | None = None,
                          slots: int | None = None, n_reqs: int | None = None,
                          max_new: int | None = None) -> dict:
    """Serving through ``lmstudio.chat_model`` at tp=1 vs tp=N (N = every
    local device, downshifted until the model's head layout divides):
    per-replica served tok/s, batcher decode step_ms p50, and TTFT p50 for
    the same closed wave. tp=N runs ONE replica across N chips — its
    per-replica number is the whole mesh's; ``tok_s_per_chip`` is the
    honest efficiency divisor. Skipped (with a reason) on one device."""
    import asyncio

    from nats_llm_studio_tpu.parallel import build_mesh
    from nats_llm_studio_tpu.parallel.sharding import (
        kv_replicated, shard_params, validate_mesh_for_config,
    )
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    devices = jax.devices()
    if len(devices) < 2:
        return {"skipped": "single device — no tp axis to bench"}
    tokenizer = _make_bench_tokenizer(cfg)
    seq = seq or int(os.environ.get("BENCH_TP_SEQ", "512"))
    slots = slots or int(os.environ.get("BENCH_TP_SLOTS", "8"))
    n_reqs = n_reqs or int(os.environ.get("BENCH_TP_REQS", "16"))
    max_new = max_new or int(os.environ.get("BENCH_TP_NEW", "64"))

    def servable(tp: int) -> bool:
        try:
            validate_mesh_for_config(
                build_mesh(f"tp={tp}", devices=devices[:tp]), cfg)
            return True
        except ValueError:
            return False

    tp_n = int(os.environ.get("BENCH_TP_N", "0")) or len(devices)
    while tp_n > 1 and not servable(tp_n):
        tp_n //= 2  # e.g. 4 heads on 8 forced host devices -> tp=4
    if tp_n < 2:
        return {"skipped": f"no tp>1 layout divides heads={cfg.n_heads}/"
                           f"{cfg.n_kv_heads} on {len(devices)} devices"}

    def run_mode(tp: int) -> dict:
        mesh = build_mesh(f"tp={tp}", devices=devices[:tp]) if tp > 1 else None
        p = shard_params(params, mesh, cfg) if mesh is not None else params
        batcher = ContinuousBatcher(
            p, cfg, max_slots=slots, max_seq_len=seq,
            buckets=[b for b in (64, 256) if b < seq] + [seq], mesh=mesh,
        )

        async def body(nc, one_chat):
            # warm the singleton admit, the group widths the wave can
            # coalesce into, and the decode windows it sweeps — compiles
            # must not land inside the measured wall
            await one_chat(900, SHORT_PROMPT, 8)
            w = 2
            while w <= min(batcher.max_group_admit, n_reqs, slots):
                await asyncio.gather(
                    *(one_chat(900 + 10 * w + i, SHORT_PROMPT, 8)
                      for i in range(w))
                )
                w *= 2
            await one_chat(990, SHORT_PROMPT, max_new)
            await asyncio.sleep(0.5)  # drain in-flight zombie bursts
            s0 = batcher.stats.snapshot()
            h0 = _phase_hists(batcher)
            t0 = time.perf_counter()
            reqs = await asyncio.gather(
                *(one_chat(1000 + i, f"{SHORT_PROMPT} [{i}]", max_new)
                  for i in range(n_reqs))
            )
            wall = time.perf_counter() - t0
            phase = _phase_delta(batcher, s0, h0)
            ttfts = sorted(r["ttft_s"] * 1e3 for r in reqs
                           if r["ttft_s"] == r["ttft_s"])
            toks = sum(r["completion_tokens"] for r in reqs)
            tok_s = round(toks / wall, 1)
            out = {
                "tp": tp,
                "chips_per_replica": tp,
                "tok_s_per_replica": tok_s,  # one replica serves the wave
                "tok_s_per_chip": round(tok_s / tp, 1),
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "step_ms_p50": phase.get("batcher_decode_step_p50_ms", 0.0),
                "requests": n_reqs,
                "max_tokens": max_new,
                "parse_failures": sum(1 for r in reqs if r["parse_fail"]),
                "batcher_phase": phase,
            }
            if mesh is not None and kv_replicated(mesh, cfg):
                out["kv_replicated"] = True  # GQA fallback path measured
            return out

        out = _drive_engine(cfg, params if mesh is None else p, model_id,
                            tokenizer, batcher, body)
        del p
        gc.collect()
        return out

    on = run_mode(tp_n)
    off = run_mode(1)
    return {
        "devices": len(devices),
        "max_seq_len": seq,
        "slots": slots,
        f"tp{tp_n}": on,
        "tp1": off,
        "per_replica_speedup": (
            round(on["tok_s_per_replica"] / off["tok_s_per_replica"], 2)
            if off.get("tok_s_per_replica") else 0.0
        ),
    }


# ---------------------------------------------------------------------------
# multi-axis serving mesh: dp replicas / routed MoE / sp ring prefill
# ---------------------------------------------------------------------------


def multi_axis_bench(cfg, params, model_id: str, *, seq: int | None = None,
                     slots: int | None = None, n_reqs: int | None = None,
                     max_new: int | None = None) -> dict:
    """The three axes the named mesh adds beyond tp, each measured through
    the LIVE serving path: (a) dp=2 batcher replicas vs one dp=1 replica —
    aggregate tok/s for the same closed wave plus the per-replica request
    split; (b) routed (capacity-factor) vs dense-dispatch MoE — prefill
    wall for a prompt-heavy wave on the same weights; (c) sp=2 ring
    prefill on vs off — long-prompt TTFT. Skipped on one device."""
    import asyncio

    from nats_llm_studio_tpu.parallel import build_mesh, dp_submeshes
    from nats_llm_studio_tpu.parallel.sharding import shard_params
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
    from nats_llm_studio_tpu.serve.dp import DataParallelBatcher

    devices = jax.devices()
    if len(devices) < 2:
        return {"skipped": "single device — no dp/sp axis to bench"}
    tokenizer = _make_bench_tokenizer(cfg)
    seq = seq or int(os.environ.get("BENCH_MA_SEQ", "256"))
    slots = slots or int(os.environ.get("BENCH_MA_SLOTS", "4"))
    n_reqs = n_reqs or int(os.environ.get("BENCH_MA_REQS", "8"))
    max_new = max_new or int(os.environ.get("BENCH_MA_NEW", "16"))
    buckets = [b for b in (64,) if b < seq] + [seq]

    def wave(batcher, prompts, new, replicas=None, wcfg=None, wtok=None,
             mid=None):
        """Closed wave through the broker+worker path: wall, aggregate
        tok/s, TTFT p50 — plus the per-replica request split (wave only,
        warm excluded) when ``replicas`` is given. ``wcfg``/``wtok``/``mid``
        override the engine config for the MoE sub-phase."""
        nrep = len(replicas) if replicas else 1

        async def body(nc, one_chat):
            # compiles must not land inside the wall: warm the singleton
            # admit and the group widths the wave coalesces into. A dp
            # facade spreads a burst least-loaded, so widths are scaled by
            # the replica count (each replica sees a w-wide group) and a
            # second singleton round reaches the sibling replica's grid
            for r_ in range(nrep):
                await one_chat(900 + r_, prompts[0], 4)
            w = 2
            while w <= min(batcher.max_group_admit, len(prompts), slots):
                await asyncio.gather(
                    *(one_chat(910 + w + i, prompts[0], 4)
                      for i in range(w * nrep))
                )
                w *= 2
            await asyncio.sleep(0.3)
            pre = ([r.stats.snapshot().get("requests", 0) for r in replicas]
                   if replicas else None)
            t0 = time.perf_counter()
            reqs = await asyncio.gather(
                *(one_chat(1000 + i, p, new) for i, p in enumerate(prompts))
            )
            wall = time.perf_counter() - t0
            toks = sum(r["completion_tokens"] for r in reqs)
            ttfts = sorted(r["ttft_s"] * 1e3 for r in reqs
                           if r["ttft_s"] == r["ttft_s"])
            res = {
                "wall_s": round(wall, 3),
                "tok_s": round(toks / wall, 1) if wall else 0.0,
                "ttft_p50_ms": round(_pctl(ttfts, 0.5), 1),
                "requests": len(prompts),
            }
            if pre is not None:
                res["replica_requests"] = [
                    r.stats.snapshot().get("requests", 0) - p0
                    for r, p0 in zip(replicas, pre)
                ]
            return res

        return _drive_engine(wcfg or cfg, params, mid or model_id,
                             wtok or tokenizer, batcher, body)

    out: dict = {"devices": len(devices)}
    short = [f"{SHORT_PROMPT} [{i}]" for i in range(n_reqs)]

    # -- (a) dp replicas: aggregate tok/s, dp=2 vs dp=1 ---------------------
    mesh = build_mesh("dp=2", devices=devices[:2])
    reps = [
        ContinuousBatcher(shard_params(params, s, cfg), cfg, max_slots=slots,
                          max_seq_len=seq, buckets=buckets, mesh=s)
        for s in dp_submeshes(mesh)
    ]
    dpb = DataParallelBatcher(reps)
    dpb.start()  # registry._load starts engines eagerly; mirror it so the
    # worker supervisor never reads a not-yet-started replica as crashed
    on = wave(dpb, short, max_new, replicas=reps)
    del dpb, reps
    gc.collect()
    single = ContinuousBatcher(params, cfg, max_slots=slots, max_seq_len=seq,
                               buckets=buckets, mesh=None)
    off = wave(single, short, max_new)
    del single
    gc.collect()
    out["dp"] = {
        "dp2": on, "dp1": off,
        "aggregate_speedup": (round(on["tok_s"] / off["tok_s"], 2)
                              if off.get("tok_s") else 0.0),
    }

    # -- (b) routed vs dense MoE dispatch: prefill-heavy wave ---------------
    moe_kw = dict(n_layers=2, n_experts=8, n_experts_used=2, d_ff=32,
                  max_seq_len=seq, moe_capacity_factor=2.0)
    moe_routed = ModelConfig.tiny(use_routed_moe=True, **moe_kw)
    moe_dense = ModelConfig.tiny(use_routed_moe=False, **moe_kw)
    moe_params = init_params(moe_routed, jax.random.PRNGKey(3))
    # byte tokenizer: 1 char = 1 token, so this is a prefill-dominated wave
    moe_prompts = ["m" * (seq // 2) + str(i) for i in range(4)]

    def moe_wave(mcfg):
        b = ContinuousBatcher(moe_params, mcfg, max_slots=slots,
                              max_seq_len=seq, buckets=buckets, mesh=None)
        r = wave(b, moe_prompts, 2, wcfg=mcfg,
                 wtok=_make_bench_tokenizer(mcfg), mid="bench/moe")
        del b
        gc.collect()
        return r

    routed = moe_wave(moe_routed)
    dense = moe_wave(moe_dense)
    out["moe"] = {
        "routed": routed, "dense": dense,
        "prefill_speedup": (
            round(dense["wall_s"] / routed["wall_s"], 2)
            if routed.get("wall_s") else 0.0
        ),
    }

    # -- (c) sp ring prefill on vs off: long-prompt TTFT --------------------
    long_prompts = ["l" * (seq // 2 + i) for i in range(4)]
    saved_env = os.environ.get("RING_PREFILL_MIN_TOKENS")
    try:
        os.environ["RING_PREFILL_MIN_TOKENS"] = str(seq // 4)
        sp_mesh = build_mesh("sp=2", devices=devices[:2])
        b = ContinuousBatcher(shard_params(params, sp_mesh, cfg), cfg,
                              max_slots=slots, max_seq_len=seq,
                              buckets=buckets, mesh=sp_mesh)
        sp_on = wave(b, long_prompts, 4)
        hists = set(b.stats.program_histograms())
        sp_on["ring_programs"] = sorted(
            n for n in hists if n.endswith("_ring"))
        del b
        gc.collect()
    finally:
        if saved_env is None:
            os.environ.pop("RING_PREFILL_MIN_TOKENS", None)
        else:
            os.environ["RING_PREFILL_MIN_TOKENS"] = saved_env
    b = ContinuousBatcher(params, cfg, max_slots=slots, max_seq_len=seq,
                          buckets=buckets, mesh=None)
    sp_off = wave(b, long_prompts, 4)
    del b
    gc.collect()
    out["sp"] = {
        "sp2_ring": sp_on, "sp_off": sp_off,
        "long_prefill_wall_ratio": (
            round(sp_off["wall_s"] / sp_on["wall_s"], 2)
            if sp_on.get("wall_s") else 0.0
        ),
    }
    return out


# ---------------------------------------------------------------------------
# observability overhead: flight recorder on vs off
# ---------------------------------------------------------------------------


def obs_overhead_bench(cfg, params, *, seq: int | None = None,
                       slots: int | None = None, n_reqs: int | None = None,
                       max_new: int | None = None,
                       rounds: int | None = None) -> dict:
    """Decode throughput with the flight recorder (obs/recorder.py) sampling
    every 25 ms vs recorder disabled, on two batchers of identical geometry.
    Rounds interleave off/on so clock drift and thermal state hit both arms
    equally; medians are compared. The recorder must cost <1% decode tok/s —
    but a CPU CI box's run-to-run noise can exceed 1%, so the bound is
    ``max(1%, observed off-arm spread)``: on quiet hardware (TPU) the real
    1% bound applies, on noisy hardware the phase still proves the recorder
    is indistinguishable from noise."""
    import asyncio
    import statistics

    from nats_llm_studio_tpu.engine.generator import SamplingParams
    from nats_llm_studio_tpu.obs import FlightRecorder
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    seq = seq or int(os.environ.get("BENCH_OBS_SEQ", "512"))
    slots = slots or int(os.environ.get("BENCH_OBS_SLOTS", "4"))
    n_reqs = n_reqs or int(os.environ.get("BENCH_OBS_REQS", "8"))
    max_new = max_new or int(os.environ.get("BENCH_OBS_NEW", "64"))
    rounds = rounds or int(os.environ.get("BENCH_OBS_ROUNDS", "3"))
    prompt_len = max(4, min(32, seq // 4))
    buckets = [b for b in (64, 128, 256) if b < seq] + [seq]

    def build(enabled: bool) -> ContinuousBatcher:
        rec = FlightRecorder(enabled=enabled, interval_ms=25.0, dump_dir="")
        return ContinuousBatcher(params, cfg, max_slots=slots,
                                 max_seq_len=seq, buckets=buckets,
                                 recorder=rec)

    async def round_tok_s(batcher: ContinuousBatcher) -> float:
        sp = SamplingParams(temperature=0.0, max_tokens=max_new)

        async def one(i: int) -> int:
            prompt = [(i * 31 + j) % 97 + 1 for j in range(prompt_len)]
            return len([t async for t in batcher.submit(prompt, sp)])

        t0 = time.perf_counter()
        counts = await asyncio.gather(*[one(i) for i in range(n_reqs)])
        return sum(counts) / (time.perf_counter() - t0)

    async def drive() -> dict:
        b_off, b_on = build(False), build(True)
        try:
            # warm both engines' programs outside the timed rounds
            await round_tok_s(b_off)
            await round_tok_s(b_on)
            off_runs, on_runs = [], []
            for _ in range(rounds):
                off_runs.append(await round_tok_s(b_off))
                on_runs.append(await round_tok_s(b_on))
            frames = b_on.recorder.frames_sampled
        finally:
            b_off.stop()
            b_on.stop()
        off_med = statistics.median(off_runs)
        on_med = statistics.median(on_runs)
        delta_pct = (off_med - on_med) / off_med * 100 if off_med else 0.0
        noise_pct = ((max(off_runs) - min(off_runs)) / off_med * 100
                     if off_med else 0.0)
        return {
            "rounds": rounds, "requests_per_round": n_reqs,
            "max_new": max_new, "recorder_interval_ms": 25.0,
            "off_tok_s": [round(v, 1) for v in off_runs],
            "on_tok_s": [round(v, 1) for v in on_runs],
            "off_median_tok_s": round(off_med, 1),
            "on_median_tok_s": round(on_med, 1),
            "overhead_pct": round(delta_pct, 2),
            "noise_floor_pct": round(noise_pct, 2),
            "frames_sampled": frames,
        }

    out = asyncio.run(drive())
    assert out["frames_sampled"] > 0, "recorder-on arm never sampled a frame"
    assert out["overhead_pct"] < max(1.0, out["noise_floor_pct"]), (
        f"flight recorder cost {out['overhead_pct']:.2f}% decode tok/s "
        f"(noise floor {out['noise_floor_pct']:.2f}%): {out}"
    )
    gc.collect()
    return out


def efficiency_bench(cfg, params, *, seq: int | None = None,
                     slots: int | None = None, n_reqs: int | None = None,
                     max_new: int | None = None) -> dict:
    """Compute-efficiency plane (obs/roofline.py) under the standard
    overload mix: served requests, client cancels mid-stream, and tight
    deadlines. Asserts the roofline gauges report nonzero MFU and MBU for
    BOTH prefill and decode program classes, and that the device-time
    ledger's category sums reconcile with the batcher's measured dispatch
    wall time to within 10% — every device-ms is attributed somewhere.
    Reports MFU/MBU, the waste breakdown as a percentage of device time,
    and goodput (served tokens per attributed device-second)."""
    import asyncio

    from nats_llm_studio_tpu.engine.generator import SamplingParams
    from nats_llm_studio_tpu.obs.roofline import chip_peaks
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher

    seq = seq or int(os.environ.get("BENCH_EFF_SEQ", "256"))
    slots = slots or int(os.environ.get("BENCH_EFF_SLOTS", "4"))
    n_reqs = n_reqs or int(os.environ.get("BENCH_EFF_REQS", "9"))
    max_new = max_new or int(os.environ.get("BENCH_EFF_NEW", "32"))
    prompt_len = max(4, min(32, seq // 4))
    buckets = [b for b in (64, 128, 256) if b < seq] + [seq]

    batcher = ContinuousBatcher(params, cfg, max_slots=slots,
                                max_seq_len=seq, buckets=buckets)

    async def drive() -> dict:
        sp = SamplingParams(temperature=0.0, max_tokens=max_new)

        def prompt_for(i: int) -> list[int]:
            return [(i * 31 + j) % 97 + 1 for j in range(prompt_len)]

        async def served(i: int) -> int:
            return len([t async for t in batcher.submit(prompt_for(i), sp)])

        async def cancelled(i: int) -> int:
            # client disconnect after 2 tokens: GeneratorExit -> cancel ->
            # the slot's accrued device-ms lands in the cancelled category
            agen = batcher.submit_batched(prompt_for(i), sp)
            got = 0
            async for batch in agen:
                got += len(batch)
                if got >= 2:
                    break
            await agen.aclose()
            return got

        async def tight_deadline(i: int) -> int:
            # a deadline the decode cannot finish inside: either sheds
            # pre-prefill (no device time, no category) or aborts
            # mid-decode (deadline_abort gets the accrued ms) — both are
            # honest outcomes; the reconciliation below must hold either way
            got = 0
            try:
                async for t in batcher.submit(
                    prompt_for(i), sp, deadline=time.monotonic() + 0.25
                ):
                    got += 1
            except Exception:  # noqa: BLE001 — shed/abort envelopes expected
                pass
            return got

        kinds = (served, cancelled, tight_deadline)
        t0 = time.perf_counter()
        results = await asyncio.gather(
            *[kinds[i % len(kinds)](i) for i in range(n_reqs)],
            return_exceptions=True,
        )
        wall_s = time.perf_counter() - t0
        # read the gauges BEFORE stopping: the rolling window is live
        st = batcher.stats
        util = st.utilization()
        dt = st.device_time_snapshot()
        flops, bytes_ = st.cost_counters()
        return {
            "wall_s": round(wall_s, 3),
            "tokens_served": sum(r for r in results if isinstance(r, int)),
            "util": util,
            "device_ms": dt["ms"],
            "device_tokens": dt["tokens"],
            "goodput_tokens_per_device_s": st.goodput_tokens_per_device_s(),
            "dispatch_ms_total": st.dispatch_ms_total,
            "flops_total": sum(flops.values()),
            "bytes_total": sum(bytes_.values()),
        }

    try:
        out = asyncio.run(drive())
    finally:
        batcher.stop()

    for cls in ("prefill", "decode"):
        u = out["util"][cls]
        assert u["mfu"] > 0 and u["mbu"] > 0, (
            f"{cls} roofline gauges are zero (cost extraction broken?): "
            f"{out['util']}"
        )
    ledger_ms = sum(out["device_ms"].values())
    busy_ms = out["dispatch_ms_total"]
    assert busy_ms > 0, "no dispatches were timed"
    drift_pct = abs(ledger_ms - busy_ms) / busy_ms * 100
    assert drift_pct <= 10.0, (
        f"device-time ledger ({ledger_ms:.1f} ms) does not reconcile with "
        f"measured dispatch time ({busy_ms:.1f} ms): {drift_pct:.1f}% apart"
    )
    served_ms = out["device_ms"].get("served", 0.0)
    waste_pct = {
        k: round(v / ledger_ms * 100, 2)
        for k, v in sorted(out["device_ms"].items()) if v > 0 and k != "served"
    }
    pf, pb = chip_peaks()
    result = {
        "requests": n_reqs, "max_new": max_new,
        "wall_s": out["wall_s"],
        "tokens_served": out["tokens_served"],
        "peak_flops": pf, "peak_hbm_bytes_s": pb,
        "mfu_prefill": round(out["util"]["prefill"]["mfu"], 6),
        "mbu_prefill": round(out["util"]["prefill"]["mbu"], 6),
        "mfu_decode": round(out["util"]["decode"]["mfu"], 6),
        "mbu_decode": round(out["util"]["decode"]["mbu"], 6),
        "device_ms": {k: round(v, 1) for k, v in sorted(out["device_ms"].items()) if v},
        "served_ms_pct": round(served_ms / ledger_ms * 100, 2) if ledger_ms else 0.0,
        "waste_pct": waste_pct,
        "goodput_tokens_per_device_s": round(out["goodput_tokens_per_device_s"], 1),
        "ledger_vs_dispatch_pct": round(drift_pct, 2),
        "flops_total": out["flops_total"],
        "bytes_total": out["bytes_total"],
    }
    gc.collect()
    return result


# ---------------------------------------------------------------------------


def _export_tiny_gguf(models_dir, mid: str, seed: int = 5,
                      max_seq_len: int = 64) -> None:
    """Export a 2-layer tiny model with a byte-level gpt2 tokenizer to
    ``models_dir/mid/m.gguf`` — the resilience phases (chaos, cluster) run
    it so they measure the recovery machinery, not XLA. ``max_seq_len``
    sizes the context (the gateway phase needs prompts past a full prefill
    chunk so the n-fan-out actually shares prefix blocks)."""
    from pathlib import Path

    from nats_llm_studio_tpu.gguf.constants import TokenType
    from nats_llm_studio_tpu.gguf.tokenizer import _byte_to_unicode
    from nats_llm_studio_tpu.models.export import export_params_to_gguf

    tcfg = ModelConfig.tiny(n_layers=2, max_seq_len=max_seq_len)
    tparams = init_params(tcfg, jax.random.PRNGKey(seed))
    b2u = _byte_to_unicode()
    tokens = [b2u[b] for b in range(256)]
    while len(tokens) < tcfg.vocab_size - 1:
        tokens.append(f"<filler_{len(tokens)}>")
    tokens.append("<|eot|>")
    tok_md = {
        "tokenizer.ggml.model": "gpt2",
        "tokenizer.ggml.tokens": tokens,
        "tokenizer.ggml.token_type": (
            [int(TokenType.NORMAL)] * (tcfg.vocab_size - 1)
            + [int(TokenType.CONTROL)]
        ),
        "tokenizer.ggml.merges": [],
        "tokenizer.ggml.eos_token_id": tcfg.vocab_size - 1,
        "tokenizer.ggml.add_bos_token": False,
    }
    d = Path(models_dir) / mid
    d.mkdir(parents=True)
    export_params_to_gguf(d / "m.gguf", tparams, tcfg, name=mid,
                          tokenizer_md=tok_md)


def chaos_bench() -> dict:
    """Fault-injected serving (transport/faults.py): a seeded FaultPlan
    severs the client's broker connection mid-run AND crashes the engine
    pump loop once. Every request must still complete — auto-reconnect +
    request retry on the client, supervisor engine restart on the worker.
    Reports recovery behavior (reconnects, restarts, restart latency, total
    wall time), not throughput; runs a tiny model so the phase measures the
    resilience machinery, not XLA."""
    import asyncio
    import tempfile
    from pathlib import Path

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store.manager import ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect
    from nats_llm_studio_tpu.transport import faults

    mid = "bench/chaos-tiny"
    n_reqs = int(os.environ.get("BENCH_CHAOS_REQS", "8"))

    async def run(models_dir: Path) -> dict:
        _export_tiny_gguf(models_dir, mid)
        broker = await EmbeddedBroker().start()
        registry = LocalRegistry(
            ModelStore(models_dir), dtype="float32", max_batch_slots=2,
            max_seq_len=64, restart_backoff_s=0.05, restart_backoff_max_s=0.2,
            max_restarts=10, restart_window_s=60.0,
        )
        worker = Worker(
            WorkerConfig(nats_url=broker.url, supervise_interval_s=0.1,
                         engine_heartbeat_timeout_s=0.0),
            registry,
        )
        await worker.start()
        nc = await connect(broker.url, reconnect_wait_s=0.02,
                           reconnect_max_wait_s=0.2)
        body = json.dumps({
            "model": mid,
            "messages": [{"role": "user", "content": "chaos probe"}],
            "max_tokens": 8, "temperature": 0.0, "stream": False,
        }).encode()
        # warm the engine before installing the plan so fault steps land in
        # the measured serving loop, not the initial load
        r = json.loads(
            (await nc.request("lmstudio.chat_model", body, timeout=60)).payload
        )
        assert r.get("ok"), r
        plan = faults.install(
            faults.FaultPlan(seed=int(os.environ.get("BENCH_CHAOS_SEED", "7")))
            .sever(faults.BROKER_PUBLISH, 2, subject="lmstudio.chat_model")
            .raise_at(faults.PUMP, 8, message="bench chaos pump fault")
        )
        retry = RetryPolicy(max_attempts=12, backoff_s=0.2, max_backoff_s=1.0,
                            retry_on_timeout=True)
        t0 = time.perf_counter()
        completed = 0
        try:
            for _ in range(n_reqs):
                r = json.loads(
                    (await nc.request("lmstudio.chat_model", body, timeout=30,
                                      retry=retry)).payload
                )
                if r.get("ok"):
                    completed += 1
            wall_s = time.perf_counter() - t0
        finally:
            faults.clear()
        prom = (
            await nc.request("lmstudio.metrics.prom", b"", timeout=10)
        ).payload.decode()
        restart_ms = {
            line.split()[0].rsplit("_", 1)[-1]: float(line.split()[-1])
            for line in prom.splitlines()
            if line.startswith("lmstudio_engine_restart_ms_")
        }
        out = {
            "requests": n_reqs,
            "completed": completed,
            "faults_fired": plan.fired(),
            "all_faults_fired": plan.done(),
            "client_reconnects": nc.reconnects,
            "last_reconnect_s": round(nc.last_reconnect_s, 4),
            "engine_restarts": registry.engine_restarts_total,
            "inflight_failed_retryable": registry.inflight_failed_retryable
            + sum(
                eng.batcher.stats.inflight_failed_retryable
                for eng in registry.loaded_engines().values()
                if getattr(eng, "batcher", None) is not None
            ),
            "restart_latency_ms": restart_ms,
            "wall_s": round(wall_s, 3),
        }
        await nc.close()
        await worker.drain()
        await broker.stop()
        return out

    with tempfile.TemporaryDirectory() as td:
        return asyncio.run(run(Path(td) / "models"))


def cluster_bench(*, n_workers: int | None = None, n_clients: int | None = None,
                  reqs_per_client: int | None = None,
                  max_new: int | None = None) -> dict:
    """Multi-worker failover (serve/router.py + ISSUE 10 chaos): N workers
    share the queue group on one embedded broker; a worker-scoped sever
    rule (faults.sever_worker) kills one mid-overload-wave, with
    auto-reconnect disabled so the kill is permanent — its queue subs die
    with the connection and the broker routes every later request to the
    survivors. Acceptance: every request is served or fails with a
    *cleanly retryable* envelope — zero client-side timeout expiries — and
    no retry is ever SERVED by a worker named in its own
    X-Excluded-Workers header (the worker self-check bounces those hops;
    the per-worker prom counters in the output are the evidence). Reports
    aggregate tok/s and server-side p95 TTFT (merged per-worker
    lmstudio_ttft_ms histograms) for the cluster wave vs a single-worker
    baseline wave."""
    import asyncio
    import tempfile
    from pathlib import Path

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.obs import bucket_pairs, merge
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store.manager import ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect
    from nats_llm_studio_tpu.transport import faults
    from nats_llm_studio_tpu.transport import protocol as proto
    from nats_llm_studio_tpu.transport.envelope import deadline_header_value

    mid = "bench/cluster-tiny"
    n_workers = n_workers or int(os.environ.get("BENCH_CLUSTER_WORKERS", "2"))
    n_clients = n_clients or int(os.environ.get("BENCH_CLUSTER_CLIENTS", "144"))
    reqs = reqs_per_client or int(os.environ.get("BENCH_CLUSTER_REQS", "1"))
    max_new = max_new or int(os.environ.get("BENCH_CLUSTER_NEW", "8"))
    slots = int(os.environ.get("BENCH_CLUSTER_SLOTS", "4"))
    attempt_s = float(os.environ.get("BENCH_CLUSTER_ATTEMPT_TIMEOUT_S", "8"))
    budget_s = float(os.environ.get("BENCH_CLUSTER_BUDGET_S", "90"))
    kill_step = int(os.environ.get("BENCH_CLUSTER_KILL_STEP",
                                   str(max(4, n_clients // 4))))

    def prom_sum(text: str, family: str) -> float:
        return sum(
            float(line.rsplit(None, 1)[1])
            for line in text.splitlines()
            if line.startswith(family + "{") or line.startswith(family + " ")
        )

    def ttft_p95(prom_texts: list[str]) -> float:
        """p95 from the workers' lmstudio_ttft_ms buckets via the shared
        delta-first merge (nats_llm_studio_tpu.obs.merge — upper bucket
        edge, resolution-honest, no interpolation)."""
        return merge(
            bucket_pairs(t, "lmstudio_ttft_ms") for t in prom_texts
        ).quantile(0.95)

    async def spawn(broker, models_dir: Path, wid: str):
        registry = LocalRegistry(
            ModelStore(models_dir), dtype="float32", max_batch_slots=slots,
            max_seq_len=64, restart_backoff_s=0.05, restart_backoff_max_s=0.2,
            max_restarts=10, restart_window_s=60.0, worker_id=wid,
        )
        worker = Worker(
            WorkerConfig(
                nats_url=broker.url, worker_id=wid,
                cluster_advert_interval_s=0.2,
                supervise_interval_s=0.1, engine_heartbeat_timeout_s=0.0,
                # the kill must be permanent: a severed worker stays dead
                max_reconnects=0,
            ),
            registry,
        )
        await worker.start()
        return worker

    def body_for(tag: str) -> bytes:
        return json.dumps({
            "model": mid,
            "messages": [{"role": "user", "content": f"cluster probe {tag}"}],
            "max_tokens": max_new, "temperature": 0.0, "stream": False,
        }).encode()

    async def wave(nc, tag: str) -> dict:
        out = {"served": 0, "retryable": 0, "hard_failed": 0, "timeouts": 0,
               "tokens": 0}
        lat: list[float] = []
        retry = RetryPolicy(max_attempts=20, backoff_s=0.05, max_backoff_s=0.5,
                            retry_on_timeout=True)

        async def client(i: int) -> None:
            for r_i in range(reqs):
                # explicit wall budget + short per-attempt timeout: an
                # attempt stuck on the killed worker times out quickly and
                # rehops (through the exclusion header) inside the budget
                headers = {proto.DEADLINE_HEADER: deadline_header_value(budget_s)}
                t0 = time.perf_counter()
                try:
                    msg = await nc.request(
                        "lmstudio.chat_model", body_for(f"{tag} c{i} r{r_i}"),
                        timeout=attempt_s, headers=headers, retry=retry,
                    )
                except asyncio.TimeoutError:
                    out["timeouts"] += 1
                    continue
                r = json.loads(msg.payload)
                lat.append(time.perf_counter() - t0)
                if r.get("ok"):
                    out["served"] += 1
                    usage = (r["data"]["response"].get("usage") or {})
                    out["tokens"] += int(usage.get("completion_tokens", 0))
                elif r.get("retryable"):
                    out["retryable"] += 1
                else:
                    out["hard_failed"] += 1

        t0 = time.perf_counter()
        await asyncio.gather(*[client(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0
        out["wall_s"] = round(wall, 3)
        out["tok_s"] = round(out["tokens"] / wall, 1) if wall > 0 else 0.0
        lat.sort()
        out["p95_latency_ms"] = round(1000 * _pctl(lat, 0.95), 1) if lat else 0.0
        return out

    async def scrape(nc, wid: str) -> str:
        msg = await nc.request(f"lmstudio.worker.{wid}.metrics.prom", b"",
                               timeout=10)
        return msg.payload.decode()

    async def run(models_dir: Path) -> dict:
        _export_tiny_gguf(models_dir, mid)

        # -- baseline: the same wave against ONE worker ----------------------
        broker = await EmbeddedBroker().start()
        worker = await spawn(broker, models_dir, "w-base")
        nc = await connect(broker.url, reconnect_wait_s=0.02,
                           reconnect_max_wait_s=0.2)
        warm = json.loads(
            (await nc.request("lmstudio.chat_model", body_for("warm"),
                              timeout=120)).payload
        )
        assert warm.get("ok"), warm
        single = await wave(nc, "single")
        single["ttft_p95_ms"] = ttft_p95([await scrape(nc, "w-base")])
        await nc.close()
        await worker.drain()
        await broker.stop()

        # -- cluster: N workers, one killed mid-wave -------------------------
        broker = await EmbeddedBroker().start()
        wids = [f"w-{i}" for i in range(n_workers)]
        workers = [await spawn(broker, models_dir, wid) for wid in wids]
        nc = await connect(broker.url, reconnect_wait_s=0.02,
                           reconnect_max_wait_s=0.2)
        for wid in wids:
            # warm every engine through its directed subject so fault steps
            # land in the measured wave, not the initial load
            warm = json.loads(
                (await nc.request(f"lmstudio.worker.{wid}.chat_model",
                                  body_for(f"warm {wid}"), timeout=120)).payload
            )
            assert warm.get("ok"), warm
        victim = wids[0]
        plan = faults.install(
            faults.FaultPlan(seed=int(os.environ.get("BENCH_CLUSTER_SEED", "11")))
            .sever_worker(victim, kill_step)
        )
        try:
            cluster = await wave(nc, "cluster")
        finally:
            faults.clear()
        survivors = {}
        prom_texts = []
        for wid in wids[1:]:
            text = await scrape(nc, wid)
            prom_texts.append(text)
            survivors[wid] = {
                "requests_total": prom_sum(text, "lmstudio_requests_total"),
                "excluded_bounce_total": prom_sum(
                    text, "lmstudio_excluded_bounce_total"),
                "drain_bounce_total": prom_sum(
                    text, "lmstudio_drain_bounce_total"),
                "reconnects_total": prom_sum(text, "lmstudio_reconnects_total"),
            }
        cluster["ttft_p95_ms"] = ttft_p95(prom_texts)
        total = n_clients * reqs
        cluster["all_served_or_retryable"] = (
            cluster["timeouts"] == 0 and cluster["hard_failed"] == 0
            and cluster["served"] + cluster["retryable"] == total
        )
        await nc.close()
        for w in workers:
            try:
                await w.drain()
            except (ConnectionError, asyncio.TimeoutError):
                pass  # the victim's connection is (deliberately) dead
        await broker.stop()
        return {
            "workers": n_workers,
            "clients": n_clients,
            "reqs_per_client": reqs,
            "victim": victim,
            "kill_step": kill_step,
            "worker_killed": plan.done(),
            "faults_fired": plan.fired(),
            "single": single,
            "cluster": cluster,
            "survivor_counters": survivors,
            "cluster_vs_single_tok_s": (
                round(cluster["tok_s"] / single["tok_s"], 3)
                if single["tok_s"] else 0.0
            ),
        }

    with tempfile.TemporaryDirectory() as td:
        return asyncio.run(run(Path(td) / "models"))


def disagg_bench(*, n_clients: int | None = None,
                 reqs_per_client: int | None = None,
                 max_new: int | None = None) -> dict:
    """Disaggregated prefill/decode serving (ISSUE 13): the same overload
    wave against (a) a 2-prefill + 2-decode role topology — the role-aware
    ClusterRouter two-hops every chat, so the decode worker pulls the
    prompt's paged-KV blocks from a prefill peer over the kv_export
    subject and decodes from the imported prefix — and (b) 4 monolithic
    workers. Disaggregation's claim is decode-latency STABILITY, not raw
    throughput: with prefill moved off the decode workers, their
    lmstudio_decode_step_ms distribution sits tighter than monolithic
    workers whose decode steps interleave with chunked prefill. Reports
    per-topology served/retryable counts, merged decode-step mean/std/
    variance/p95 (log-histogram bucket midpoints — resolution-honest),
    server-side TTFT p95, and the transfer totals (bytes, ms, failures)
    that prove blocks actually moved rather than every chat silently
    falling back to local prefill."""
    import asyncio
    import tempfile
    from pathlib import Path

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.obs import bucket_pairs, merge
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.serve.router import ClusterRouter
    from nats_llm_studio_tpu.store.manager import ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect

    mid = "bench/disagg-tiny"
    n_clients = n_clients or int(os.environ.get("BENCH_DISAGG_CLIENTS", "16"))
    reqs = reqs_per_client or int(os.environ.get("BENCH_DISAGG_REQS", "2"))
    max_new = max_new or int(os.environ.get("BENCH_DISAGG_NEW", "8"))
    slots = int(os.environ.get("BENCH_DISAGG_SLOTS", "4"))
    attempt_s = float(os.environ.get("BENCH_DISAGG_ATTEMPT_TIMEOUT_S", "20"))

    def prom_sum(texts: list[str], family: str, must: str = "") -> float:
        return sum(
            float(line.rsplit(None, 1)[1])
            for text in texts
            for line in text.splitlines()
            if (line.startswith(family + "{") or line.startswith(family + " "))
            and must in line
        )

    def hist_stats(texts: list[str], family: str) -> dict:
        """Mean/variance/p95 across N workers' log-histogram buckets via
        the shared delta-first merge (nats_llm_studio_tpu.obs.merge holds
        the elision and +Inf-collapse rules this bench used to hand-roll)."""
        m = merge(bucket_pairs(t, family) for t in texts)
        if m.count <= 0:
            return {"count": 0, "mean_ms": 0.0, "std_ms": 0.0,
                    "var": 0.0, "p95_ms": 0.0}
        return {"count": int(m.count), "mean_ms": round(m.mean, 3),
                "std_ms": round(m.std, 3), "var": round(m.variance, 4),
                "p95_ms": round(m.quantile(0.95), 3)}

    async def spawn(broker, models_dir: Path, wid: str, role: str):
        registry = LocalRegistry(
            ModelStore(models_dir), dtype="float32", max_batch_slots=slots,
            max_seq_len=64, worker_id=wid,
            # tiny chunks so the short bench prompts cover whole chunks —
            # otherwise nothing is exportable and the phase measures the
            # fallback path instead of the transfer
            prefill_chunk=8, prefix_cache_blocks=64,
        )
        worker = Worker(
            WorkerConfig(
                nats_url=broker.url, worker_id=wid, worker_role=role,
                cluster_advert_interval_s=0.2,
                supervise_interval_s=0.1, engine_heartbeat_timeout_s=0.0,
            ),
            registry,
        )
        await worker.start()
        return worker

    def body_for(tag: str, content: str, tokens: int) -> bytes:
        return json.dumps({
            "model": mid,
            "messages": [{"role": "user", "content": content or tag}],
            "max_tokens": tokens, "temperature": 0.0, "stream": False,
        }).encode()

    async def wave(router, tag: str) -> dict:
        out = {"served": 0, "retryable": 0, "hard_failed": 0, "timeouts": 0,
               "tokens": 0}
        retry = RetryPolicy(max_attempts=8, backoff_s=0.05, max_backoff_s=0.5,
                            retry_on_timeout=True)

        async def client(i: int) -> None:
            for r_i in range(reqs):
                # distinct prompts: every request is a cold prefix on the
                # decode side, so every two-hop really moves blocks
                body = body_for(tag, f"disagg probe {tag} c{i} r{r_i}", max_new)
                try:
                    msg = await router.request_chat(body, timeout=attempt_s,
                                                    retry=retry)
                except (asyncio.TimeoutError, ConnectionError):
                    out["timeouts"] += 1
                    continue
                r = json.loads(msg.payload)
                if r.get("ok"):
                    out["served"] += 1
                    usage = (r["data"]["response"].get("usage") or {})
                    out["tokens"] += int(usage.get("completion_tokens", 0))
                elif r.get("retryable"):
                    out["retryable"] += 1
                else:
                    out["hard_failed"] += 1

        t0 = time.perf_counter()
        await asyncio.gather(*[client(i) for i in range(n_clients)])
        wall = time.perf_counter() - t0
        out["wall_s"] = round(wall, 3)
        out["tok_s"] = round(out["tokens"] / wall, 1) if wall > 0 else 0.0
        return out

    async def run_topology(models_dir: Path, roles: list[str],
                           tag: str) -> dict:
        broker = await EmbeddedBroker().start()
        wids = [f"w-{tag}{i}" for i in range(len(roles))]
        workers = [await spawn(broker, models_dir, wid, role)
                   for wid, role in zip(wids, roles)]
        nc = await connect(broker.url, reconnect_wait_s=0.02,
                           reconnect_max_wait_s=0.2)
        router = await ClusterRouter(nc).start()
        try:
            deadline = time.monotonic() + 10.0
            while (len(router.members()) < len(wids)
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.05)
            for wid in wids:
                # warm every engine through its directed subject: compiles
                # land before the measured wave on both roles
                warm = json.loads(
                    (await nc.request(f"lmstudio.worker.{wid}.chat_model",
                                      body_for(tag, f"warm {wid}", 2),
                                      timeout=120)).payload
                )
                assert warm.get("ok"), warm
            res = await wave(router, tag)
            decode_wids = [w for w, role in zip(wids, roles)
                           if role != "prefill"]
            texts = {wid: (await nc.request(
                f"lmstudio.worker.{wid}.metrics.prom", b"", timeout=10
            )).payload.decode() for wid in wids}
            decode_texts = [texts[w] for w in decode_wids]
            res["decode_step_ms"] = hist_stats(decode_texts,
                                               "lmstudio_decode_step_ms")
            res["ttft_p95_ms"] = hist_stats(decode_texts,
                                            "lmstudio_ttft_ms")["p95_ms"]
            res["two_hop_total"] = router.stats.two_hop_total
            all_texts = list(texts.values())
            res["transfer"] = {
                "import_bytes": prom_sum(
                    all_texts, "lmstudio_kv_transfer_bytes_total",
                    'direction="import"'),
                "export_bytes": prom_sum(
                    all_texts, "lmstudio_kv_transfer_bytes_total",
                    'direction="export"'),
                "import_ms": round(prom_sum(
                    all_texts, "lmstudio_kv_transfer_ms_total",
                    'direction="import"'), 3),
                "failures": prom_sum(
                    all_texts, "lmstudio_kv_transfer_failures_total"),
            }
            return res
        finally:
            await router.stop()
            await nc.close()
            for w in workers:
                try:
                    await w.drain()
                except (ConnectionError, asyncio.TimeoutError):
                    pass
            await broker.stop()

    async def run(models_dir: Path) -> dict:
        _export_tiny_gguf(models_dir, mid)
        disagg = await run_topology(
            models_dir, ["prefill", "prefill", "decode", "decode"], "d")
        mono = await run_topology(models_dir, ["", "", "", ""], "m")
        total = n_clients * reqs
        var_d = disagg["decode_step_ms"]["var"]
        var_m = mono["decode_step_ms"]["var"]
        return {
            "clients": n_clients, "reqs_per_client": reqs, "max_new": max_new,
            "topology": "2 prefill + 2 decode vs 4 monolithic",
            "disagg": disagg,
            "monolithic": mono,
            "all_served_or_retryable": all(
                t["timeouts"] == 0 and t["hard_failed"] == 0
                and t["served"] + t["retryable"] == total
                for t in (disagg, mono)
            ),
            "disagg_lower_decode_variance": (
                var_d < var_m if var_m > 0 else False),
            "decode_variance_ratio": (
                round(var_d / var_m, 6) if var_m > 0 else 0.0),
        }

    with tempfile.TemporaryDirectory() as td:
        return asyncio.run(run(Path(td) / "models"))


def gateway_bench(*, n_reqs: int | None = None,
                  max_new: int | None = None) -> dict:
    """OpenAI HTTP front-door phase (gateway/server.py), three questions:

    (a) what does the HTTP/SSE hop cost? — streaming TTFT p50 through the
        gateway vs the SAME request raw over NATS, same worker, same model;
    (b) what does the fused constrained-decode mask cost per step? — an
        all-True mask forces the masked ext program while changing nothing
        about the distribution, so greedy tokens must stay bit-identical
        and the wall-clock delta IS the mask machinery;
    (c) what do n=4 prompt-sharing choices cost in HBM? — peak live paged-KV
        blocks for n=4 vs n=1 (siblings admit as zero-copy shares of the
        choice-0 prompt blocks, so the ratio lands well under 4x).

    Runs the tiny model so it measures the gateway and batcher machinery,
    not XLA."""
    import asyncio
    import tempfile
    from pathlib import Path

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.engine.generator import SamplingParams
    from nats_llm_studio_tpu.gateway import Gateway
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store.manager import ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    mid = "bench/gw-tiny"
    n_reqs = n_reqs or int(os.environ.get("BENCH_GATEWAY_REQS", "6"))
    max_new = max_new or int(os.environ.get("BENCH_GATEWAY_NEW", "16"))

    class _AllowAll:
        """All-True token mask: routes decode through the masked ext
        program without constraining anything."""

        def __init__(self, vocab):
            self.vocab = vocab
            self.start = 0

        def mask(self, state):
            return np.ones(self.vocab, dtype=bool)

        def advance(self, state, tid):
            return state

        def live(self, state):
            return True

        def accepting(self, state):
            return True

    async def run(models_dir: Path) -> dict:
        # 512-token context: prefill chunks stay at 256, so the fan-out
        # prompt below can span a FULL chunk — prefix-cache harvest (and
        # therefore sibling block sharing) only engages on whole chunks
        _export_tiny_gguf(models_dir, mid, max_seq_len=512)
        broker = await EmbeddedBroker().start()
        registry = LocalRegistry(
            ModelStore(models_dir), dtype="float32",
            max_batch_slots=8, max_seq_len=512,
        )
        worker = Worker(WorkerConfig(nats_url=broker.url), registry)
        await worker.start()
        nc = await connect(broker.url)
        gw = await Gateway(nc, port=0).start()

        stream_req = {
            "model": mid,
            "messages": [{"role": "user", "content": "ttft probe"}],
            "max_tokens": 4, "temperature": 0.0, "stream": True,
        }
        raw_body = json.dumps(stream_req).encode()

        async def raw_ttft() -> float:
            agen = nc.request_stream("lmstudio.chat_model", raw_body,
                                     timeout=60.0)
            t0 = time.perf_counter()
            try:
                async for _ in agen:
                    return time.perf_counter() - t0
            finally:
                await agen.aclose()
            raise RuntimeError("raw stream yielded nothing")

        http_head = (
            f"POST /v1/chat/completions HTTP/1.1\r\nHost: b\r\n"
            f"Content-Length: {len(raw_body)}\r\n\r\n"
        ).encode() + raw_body

        async def gw_ttft() -> float:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           gw.port)
            try:
                t0 = time.perf_counter()
                writer.write(http_head)
                await writer.drain()
                await reader.readuntil(b"\r\n\r\n")   # response head
                await reader.readuntil(b"\n\n")       # first SSE event
                return time.perf_counter() - t0
            finally:
                writer.close()

        # warm both paths (engine load + compiles land here, not in p50)
        await raw_ttft()
        await gw_ttft()
        raw_s = sorted([await raw_ttft() for _ in range(n_reqs)])
        via_s = sorted([await gw_ttft() for _ in range(n_reqs)])
        raw_p50 = _pctl(raw_s, 0.50) * 1e3
        via_p50 = _pctl(via_s, 0.50) * 1e3
        ttft = {
            "raw_nats_p50_ms": round(raw_p50, 2),
            "gateway_p50_ms": round(via_p50, 2),
            "http_hop_delta_ms": round(via_p50 - raw_p50, 2),
            "reqs": n_reqs,
        }

        # (b) constrained-mask per-step overhead on the live batcher
        eng = await registry.get_engine(mid)
        batcher = eng.batcher
        sp = SamplingParams(temperature=0.0, max_tokens=max_new)
        ids = [3, 1, 4, 1, 5]
        dfa = _AllowAll(eng.cfg.vocab_size)

        async def timed(constrain) -> tuple[float, list]:
            t0 = time.perf_counter()
            toks = [t async for t in batcher.submit(ids, sp,
                                                    constrain=constrain)]
            return time.perf_counter() - t0, toks

        await timed(None)       # warm the plain program
        await timed(dfa)        # warm the masked ext program
        plain_s, plain_toks = min([await timed(None) for _ in range(3)],
                                  key=lambda r: r[0])
        ext_s, ext_toks = min([await timed(dfa) for _ in range(3)],
                              key=lambda r: r[0])
        per_plain = plain_s / max(1, len(plain_toks)) * 1e3
        per_ext = ext_s / max(1, len(ext_toks)) * 1e3
        constrained = {
            "plain_ms_per_tok": round(per_plain, 3),
            "masked_ms_per_tok": round(per_ext, 3),
            "overhead_pct": round((per_ext / per_plain - 1.0) * 100, 1)
            if per_plain else 0.0,
            # the bit-identity claim, measured: an all-True mask through the
            # ext program must not change a single greedy token
            "identical_tokens": ext_toks == plain_toks,
        }

        # (c) n=4 vs n=1 peak paged-KV block cost through the n fan-out.
        # The prompt spans a full 256-token prefill chunk so choice 0's
        # prompt blocks land in the prefix cache and the three siblings
        # admit as zero-copy shares of them. Counts are relative to the
        # pre-request pool state (prefix-cache residents stay live).
        async def peak_blocks(n: int, content: str) -> tuple[int, int]:
            payload = {
                "model": mid,
                "messages": [{"role": "user", "content": content}],
                "max_tokens": 10, "temperature": 0.8, "seed": 3, "n": n,
            }
            st0 = batcher.pool_stats()
            task = asyncio.ensure_future(eng.chat(payload))
            peak_live = peak_shared = 0
            while not task.done():
                st = batcher.pool_stats()
                if st is not None:
                    peak_live = max(peak_live,
                                    st["blocks_live"] - st0["blocks_live"])
                    peak_shared = max(peak_shared, st["blocks_shared"])
                await asyncio.sleep(0.002)
            await task
            return peak_live, peak_shared

        fanout: dict = {}
        if batcher.pool_stats() is not None:
            # distinct prompts per arm: no cross-arm prefix-cache hits
            n1_live, _ = await peak_blocks(1, "a" * 300)
            n4_live, n4_shared = await peak_blocks(4, "b" * 300)
            fanout = {
                "n1_peak_blocks_live": n1_live,
                "n4_peak_blocks_live": n4_live,
                "n4_peak_blocks_shared": n4_shared,
                "blocks_ratio": round(n4_live / n1_live, 2) if n1_live else 0.0,
                "cow_copies": batcher.pool_stats()["cow_copies"],
            }
        else:
            fanout = {"skipped": "paged KV off (KV_PAGED=0)"}

        out = {
            "ttft": ttft,
            "constrained_mask": constrained,
            "n_fanout": fanout,
            "gateway_requests_total": gw.requests_total,
            "gateway_streams_total": gw.streams_total,
        }
        await gw.stop()
        await nc.close()
        await worker.drain()
        await broker.stop()
        return out

    with tempfile.TemporaryDirectory() as td:
        return asyncio.run(run(Path(td) / "models"))


def obs_cluster_bench(*, n_reqs: int | None = None,
                      max_new: int | None = None) -> dict:
    """Cluster observability plane (ISSUE 14): a 1-prefill + 1-decode role
    topology served through the steered ClusterRouter with the fleet
    Aggregator attached. Exercises the plane end to end and reports what
    it claims: (a) the aggregator's cluster-merged TTFT p95 must agree
    with this bench's own delta-first merge over the SAME scrape — they
    share nats_llm_studio_tpu.obs.merge, so the phase asserts equality,
    not closeness; (b) a served two-hop chat queried back through
    ``lmstudio.debug.trace.<trace_id>`` must come back as ONE assembled
    tree whose stages cover the steering attempt, the decode serve, the
    decode-side KV pull, and the prefill-side KV export."""
    import asyncio
    import tempfile
    from pathlib import Path

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.obs import Aggregator, bucket_pairs, merge
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.serve.router import ClusterRouter
    from nats_llm_studio_tpu.store.manager import ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect

    mid = "bench/obs-cluster-tiny"
    n_reqs = n_reqs or int(os.environ.get("BENCH_OBS_CLUSTER_REQS", "4"))
    max_new = max_new or int(os.environ.get("BENCH_OBS_CLUSTER_NEW", "8"))

    async def spawn(broker, models_dir: Path, wid: str, role: str):
        registry = LocalRegistry(
            ModelStore(models_dir), dtype="float32", max_batch_slots=2,
            max_seq_len=64, worker_id=wid,
            # whole tiny prompts must cover full chunks or nothing is
            # exportable and the trace never grows its kv hops
            prefill_chunk=8, prefix_cache_blocks=32,
        )
        worker = Worker(
            WorkerConfig(
                nats_url=broker.url, worker_id=wid, worker_role=role,
                cluster_advert_interval_s=0.2,
                supervise_interval_s=0.1, engine_heartbeat_timeout_s=0.0,
            ),
            registry,
        )
        await worker.start()
        return worker

    async def run(models_dir: Path) -> dict:
        _export_tiny_gguf(models_dir, mid)
        broker = await EmbeddedBroker().start()
        roles = {"w-obs-p": "prefill", "w-obs-d": "decode"}
        workers = [await spawn(broker, models_dir, wid, role)
                   for wid, role in roles.items()]
        nc = await connect(broker.url, reconnect_wait_s=0.02,
                           reconnect_max_wait_s=0.2)
        router = await ClusterRouter(nc).start()
        agg = Aggregator(nc, scrape_interval_s=0.2)
        # no scrape loop: the phase drives scrape_once() itself so the
        # p95-parity comparison runs against one known scrape
        await agg.start(scrape_loop=False)
        try:
            deadline = time.monotonic() + 10.0
            while ((len(router.members()) < len(roles)
                    or len(agg.live_workers()) < len(roles))
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.05)
            retry = RetryPolicy(max_attempts=6, backoff_s=0.05,
                                max_backoff_s=0.5, retry_on_timeout=True)
            served, trace_ids = 0, []
            for i in range(n_reqs):
                body = json.dumps({
                    "model": mid,
                    "messages": [{"role": "user",
                                  "content": f"obs cluster probe {i}"}],
                    "max_tokens": max_new, "temperature": 0.0, "stream": False,
                }).encode()
                msg = await router.request_chat(body, timeout=60.0, retry=retry)
                r = json.loads(msg.payload)
                if r.get("ok"):
                    served += 1
                    tid = (r["data"]["response"].get("stats") or {}).get(
                        "trace", {}).get("trace_id")
                    if tid:
                        trace_ids.append(tid)

            # span batches are fire-and-forget: give the last flush a beat,
            # then poll until the newest trace shows its kv hops
            tree: dict = {}
            if trace_ids:
                q = f"lmstudio.debug.trace.{trace_ids[-1]}"
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    resp = json.loads(
                        (await nc.request(q, b"", timeout=5)).payload)
                    tree = resp.get("data") or {}
                    if tree.get("span_count", 0) >= 4:
                        break
                    await asyncio.sleep(0.1)
            stages: set[str] = set()

            def walk(nodes: list) -> None:
                for n in nodes:
                    if n.get("stage"):
                        stages.add(n["stage"])
                    walk(n.get("children") or [])

            walk(tree.get("roots") or [])

            texts = await agg.scrape_once()
            bench_p95 = merge(
                bucket_pairs(t, "lmstudio_ttft_ms") for t in texts.values()
            ).quantile(0.95)
            agg_p95 = next(
                (float(line.rsplit(None, 1)[1])
                 for line in agg.render_cluster().splitlines()
                 if line.startswith("lmstudio_cluster_ttft_p95_ms")), -1.0)
            return {
                "served": served,
                "scraped_workers": len(texts),
                "agg_ttft_p95_ms": agg_p95,
                "merge_ttft_p95_ms": round(bench_p95, 3),
                "p95_match": agg_p95 == round(bench_p95, 3),
                "trace_span_count": tree.get("span_count", 0),
                "trace_stages": sorted(stages),
                "two_hop_trace": {"router.attempt", "worker.serve",
                                  "worker.kv_pull",
                                  "worker.kv_export"} <= stages,
                "spans_ingested": agg.spans.spans_total,
                "slo_alerts": agg.alerts_total,
            }
        finally:
            await agg.stop()
            await router.stop()
            await nc.close()
            for w in workers:
                try:
                    await w.drain()
                except (ConnectionError, asyncio.TimeoutError):
                    pass
            await broker.stop()

    with tempfile.TemporaryDirectory() as td:
        return asyncio.run(run(Path(td) / "models"))


def autoscale_bench(*, n_clients: int | None = None,
                    reqs_per_client: int | None = None,
                    max_new: int | None = None) -> dict:
    """Elastic autoscaling (ISSUE 15): the seconds-cold-start claims and
    the kill-and-replace loop, end to end on one embedded broker.

    (a) time-to-first-served-token COLD vs PRECOMPILED: the first worker
        loads the tiny model against an empty persistent XLA compile
        cache and pays the compiles; the second spawn (fresh registry,
        fresh batcher, same cache dir) re-jits the grid from the cache —
        exactly the artifact pull-time precompile (registry.pull) writes
        at pull_model time, so the delta IS the cold-start saving the
        precompile hook buys. Per-stage cache hit/miss deltas are the
        evidence the second load actually hit.
    (b) kill-and-replace wall time: an :class:`Autoscaler` with
        min_workers=2 watches the advert stream; severing one worker's
        connection mid-wave must trigger a below_min spawn, and the
        replacement's first advert triggers a warm prefix-cache handoff
        from the survivor — re-serving the survivor-primed prompt at the
        replacement must land prefix-cache hits (hit tokens reported).
    (c) the ramp wave's aggregate tok/s, every request served or cleanly
        retryable (zero client-side timeout expiries)."""
    import asyncio
    import tempfile
    from pathlib import Path

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.obs import (
        compile_cache_counts,
        install_compile_cache_listener,
    )
    from nats_llm_studio_tpu.serve import Autoscaler, Worker
    from nats_llm_studio_tpu.serve.registry import LocalRegistry
    from nats_llm_studio_tpu.store.manager import ModelStore
    from nats_llm_studio_tpu.transport import EmbeddedBroker, RetryPolicy, connect
    from nats_llm_studio_tpu.transport import protocol as proto
    from nats_llm_studio_tpu.transport.envelope import deadline_header_value

    mid = "bench/autoscale-tiny"
    n_clients = n_clients or int(os.environ.get("BENCH_AUTOSCALE_CLIENTS", "8"))
    reqs = reqs_per_client or int(os.environ.get("BENCH_AUTOSCALE_REQS", "2"))
    max_new = max_new or int(os.environ.get("BENCH_AUTOSCALE_NEW", "8"))
    attempt_s = float(os.environ.get("BENCH_AUTOSCALE_ATTEMPT_TIMEOUT_S", "8"))
    budget_s = float(os.environ.get("BENCH_AUTOSCALE_BUDGET_S", "90"))
    replace_wait_s = float(os.environ.get("BENCH_AUTOSCALE_REPLACE_WAIT_S", "60"))

    # the precompiled-vs-cold comparison needs a persistent compile cache;
    # when the operator hasn't configured one (JAX_COMPILE_CACHE_DIR), point
    # jax at a scratch dir with the thresholds floored so the tiny model's
    # sub-second CPU compiles still persist
    cache_preconfigured = bool(
        getattr(jax.config, "jax_compilation_cache_dir", None))
    if not cache_preconfigured:
        scratch = tempfile.mkdtemp(prefix="bench_autoscale_jitcache_")
        jax.config.update("jax_compilation_cache_dir", scratch)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # jax latches "no persistent cache" at the process's FIRST
            # compile (earlier ladder phases have long since compiled);
            # re-init so the scratch dir actually takes effect
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc,
            )
            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — older jax: deltas read 0, phase still runs
            pass
    install_compile_cache_listener()

    def make_worker(broker, models_dir: Path, wid: str) -> Worker:
        registry = LocalRegistry(
            ModelStore(models_dir), dtype="float32", max_batch_slots=4,
            max_seq_len=128, prefill_chunk=8, prefix_cache_blocks=32,
            restart_backoff_s=0.05, restart_backoff_max_s=0.2,
            max_restarts=10, restart_window_s=60.0, worker_id=wid,
        )
        return Worker(
            WorkerConfig(nats_url=broker.url, worker_id=wid,
                         cluster_advert_interval_s=0.1,
                         supervise_interval_s=0.1,
                         engine_heartbeat_timeout_s=0.0,
                         kv_transfer_timeout_s=120.0),
            registry,
        )

    def body_for(content: str) -> bytes:
        return json.dumps({
            "model": mid,
            "messages": [{"role": "user", "content": content}],
            "max_tokens": max_new, "temperature": 0.0, "stream": False,
        }).encode()

    async def run(models_dir: Path) -> dict:
        # 128-token context: the chat template alone costs ~20 tokens, so
        # the warm-handoff probe needs headroom past the 64-token default
        _export_tiny_gguf(models_dir, mid, seed=13, max_seq_len=128)
        broker = await EmbeddedBroker().start()
        nc = await connect(broker.url, reconnect_wait_s=0.02,
                           reconnect_max_wait_s=0.2)

        # stamp autoscale events off the bus as they land — replace wall
        # time is kill -> spawn_live, measured the way an operator would
        event_marks: dict[str, float] = {}
        spawned_ids: list[str] = []

        async def on_event(msg) -> None:
            try:
                ev = json.loads(msg.payload)
            except ValueError:
                return
            if ev.get("kind") != "autoscale":
                return
            event_marks.setdefault(ev.get("action", ""), time.perf_counter())
            if ev.get("action") == "spawn" and ev.get("worker_id"):
                spawned_ids.append(ev["worker_id"])

        ev_sub = await nc.subscribe("lmstudio.events", cb=on_event)

        # primes the donor's prefix cache AND is re-served at the
        # replacement after handoff — long enough to fill whole prefill
        # chunks (the cache only harvests full blocks)
        warm_probe = "warm handoff probe: the survivor primes this prefix"

        # -- (a) cold vs precompiled time-to-first-served-token --------------
        cc0 = compile_cache_counts()
        t0 = time.perf_counter()
        victim = make_worker(broker, models_dir, "w-cold")
        await victim.start()
        r = json.loads((await nc.request(
            "lmstudio.worker.w-cold.chat_model", body_for(warm_probe),
            timeout=120)).payload)
        assert r.get("ok"), r
        ttfs_cold = time.perf_counter() - t0
        cc1 = compile_cache_counts()

        t0 = time.perf_counter()
        survivor = make_worker(broker, models_dir, "w-pre")
        await survivor.start()
        r = json.loads((await nc.request(
            "lmstudio.worker.w-pre.chat_model", body_for(warm_probe),
            timeout=120)).payload)
        assert r.get("ok"), r
        ttfs_pre = time.perf_counter() - t0
        cc2 = compile_cache_counts()

        # -- (b) kill-and-replace under the autoscaler -----------------------
        spawned: dict[str, Worker] = {}

        async def spawn_fn(wid: str):
            w = make_worker(broker, models_dir, wid)
            await w.start()
            spawned[wid] = w
            return w

        a = Autoscaler(
            nc, nats_url=broker.url, min_workers=2, max_workers=3,
            interval_s=0.25, stale_after_s=1.0, spawn_grace_s=60.0,
            cooldown_s=1.0, up_dwell_s=0.5, down_dwell_s=1e9,
            handoff_prefixes=4, spawn_fn=spawn_fn,
        )
        # subscribe first, tick only once both live workers have adverted:
        # the loop must start in steady state, not spawn its way out of an
        # empty membership view
        await a.start(control_loop=False)
        for _ in range(200):
            if len(a._members) >= 2:
                break
            await asyncio.sleep(0.05)
        assert len(a._members) >= 2, a._members
        a._task = asyncio.ensure_future(a._loop())

        kill_at = time.perf_counter()
        await victim.nc.close()  # permanent: its queue subs die with it

        wave = {"served": 0, "retryable": 0, "hard_failed": 0,
                "timeouts": 0, "tokens": 0}
        retry = RetryPolicy(max_attempts=40, backoff_s=0.05, max_backoff_s=0.5,
                            retry_on_timeout=True)

        async def client(i: int) -> None:
            for r_i in range(reqs):
                # explicit wall budget + short per-attempt timeout: an
                # attempt stuck on the killed worker times out quickly and
                # rehops inside the budget
                headers = {proto.DEADLINE_HEADER: deadline_header_value(budget_s)}
                try:
                    msg = await nc.request(
                        "lmstudio.chat_model",
                        body_for(f"ramp probe c{i} r{r_i}"),
                        timeout=attempt_s, headers=headers, retry=retry,
                    )
                except asyncio.TimeoutError:
                    wave["timeouts"] += 1
                    continue
                resp = json.loads(msg.payload)
                if resp.get("ok"):
                    wave["served"] += 1
                    usage = (resp["data"]["response"].get("usage") or {})
                    wave["tokens"] += int(usage.get("completion_tokens", 0))
                elif resp.get("retryable"):
                    wave["retryable"] += 1
                else:
                    wave["hard_failed"] += 1

        t0 = time.perf_counter()
        await asyncio.gather(*[client(i) for i in range(n_clients)])
        wave_wall = time.perf_counter() - t0
        wave["wall_s"] = round(wave_wall, 3)
        wave["tok_s"] = (round(wave["tokens"] / wave_wall, 1)
                         if wave_wall > 0 else 0.0)
        total = n_clients * reqs
        all_ok = (wave["timeouts"] == 0 and wave["hard_failed"] == 0
                  and wave["served"] + wave["retryable"] == total)

        # the replacement's first advert triggers the warm handoff from the
        # survivor; wait (bounded) for the blocks to land before re-serving
        # the primed prompt at it
        deadline = time.monotonic() + replace_wait_s
        repl_wid = None
        repl = None
        while time.monotonic() < deadline:
            repl_wid = spawned_ids[0] if spawned_ids else None
            repl = spawned.get(repl_wid) if repl_wid else None
            if repl is not None and repl._warm_handoff_received >= 1:
                break
            await asyncio.sleep(0.1)

        warm_hits: dict = {}
        ttfs_replacement = -1.0
        replacement_error = ""
        if repl is not None:
            r = json.loads((await nc.request(
                f"lmstudio.worker.{repl_wid}.chat_model",
                body_for(warm_probe), timeout=120,
                retry=RetryPolicy(max_attempts=6, backoff_s=0.2,
                                  max_backoff_s=1.0, retry_on_timeout=True),
            )).payload)
            if r.get("ok"):
                # upper bound: the replacement may have served wave traffic
                # earlier; this stamps kill -> primed-prompt served
                ttfs_replacement = time.perf_counter() - kill_at
            else:
                replacement_error = str(r.get("error", ""))
            eng = repl.registry.loaded_engines().get(mid)
            if eng is not None and getattr(eng, "batcher", None) is not None:
                warm_hits = dict(eng.batcher.prefix_cache.counters())

        autoscale_prom = a.render_prometheus()
        out = {
            "clients": n_clients,
            "reqs_per_client": reqs,
            "ttfs_cold_s": round(ttfs_cold, 3),
            "ttfs_precompiled_s": round(ttfs_pre, 3),
            "compile_cache_preconfigured": cache_preconfigured,
            "cold_compile_cache": {
                "misses": cc1["misses"] - cc0["misses"],
                "hits": cc1["hits"] - cc0["hits"],
            },
            "precompiled_compile_cache": {
                "misses": cc2["misses"] - cc1["misses"],
                "hits": cc2["hits"] - cc1["hits"],
            },
            "wave": wave,
            "all_served_or_retryable": all_ok,
            "replace_wall_s": (
                round(event_marks["spawn_live"] - kill_at, 3)
                if "spawn_live" in event_marks else -1.0
            ),
            "ttfs_replacement_s": round(ttfs_replacement, 3),
            "replacement": repl_wid or "",
            "replacement_error": replacement_error,
            "warm_handoff_received": (
                repl._warm_handoff_received if repl is not None else 0),
            "survivor_handoff_sent": survivor._warm_handoff_sent,
            "warm_prefix_hits": int(warm_hits.get("hits", 0)),
            "warm_prefix_hit_tokens": int(warm_hits.get("hit_tokens", 0)),
            "spawns_total": a.spawns_total,
            "drains_total": a.drains_total,
            "spawn_failures_total": a.spawn_failures_total,
            "breaker_open": a.breaker_open(),
            "autoscale_prom_families": sum(
                1 for line in autoscale_prom.splitlines()
                if line.startswith("# TYPE lmstudio_autoscale_")
            ),
        }
        await a.stop()
        try:
            await ev_sub.unsubscribe()
        except (ConnectionError, ValueError):
            pass
        await nc.close()
        for w in [victim, survivor, *spawned.values()]:
            try:
                await w.drain()
            except (ConnectionError, asyncio.TimeoutError):
                pass  # the victim's connection is (deliberately) dead
        await broker.stop()
        return out

    with tempfile.TemporaryDirectory() as td:
        return asyncio.run(run(Path(td) / "models"))


FINAL_LINE_BUDGET = 1500  # harness line-buffer bound on the final JSON line


def _summarize_detail(detail: dict) -> dict:
    """Per-phase summary for the final line: top-level scalars verbatim,
    phase dicts reduced to their scalar members — sweeps, histograms, and
    nested sub-phases live in the BENCH_LOCAL_*.json sibling instead."""
    out: dict = {}
    for k, v in detail.items():
        if isinstance(v, dict):
            s = {kk: vv for kk, vv in v.items()
                 if vv is None or isinstance(vv, (str, int, float, bool))}
            if s:
                out[k] = s
        elif v is None or isinstance(v, (str, int, float, bool)):
            out[k] = v
    return out


def _print_final(obj: dict) -> None:
    """Emit the results object as ONE compact JSON line, guaranteed LAST on
    stdout: flush both streams first so buffered warmup chatter cannot land
    after (or interleave with) the line a harness machine-parses.

    The line is capped at FINAL_LINE_BUDGET chars: past that, the full
    ``detail`` moves to a sibling BENCH_LOCAL_<timestamp>.json (path
    reported as ``detail_file``) and the line carries a scalar per-phase
    summary, largest entries dropped first until it fits."""
    from pathlib import Path

    line = json.dumps(obj, separators=(",", ":"))
    if len(line) > FINAL_LINE_BUDGET:
        obj = dict(obj)
        full = obj.get("detail") or {}
        path = Path(__file__).with_name(
            time.strftime("BENCH_LOCAL_%Y%m%d_%H%M%S.json"))
        try:
            path.write_text(json.dumps(full, indent=2, sort_keys=True))
            obj["detail_file"] = str(path)
        except OSError as e:  # read-only checkout: keep the summary anyway
            obj["detail_file_error"] = f"{type(e).__name__}: {e}"
        summary = _summarize_detail(full)
        obj["detail"] = summary
        line = json.dumps(obj, separators=(",", ":"))
        while len(line) > FINAL_LINE_BUDGET and summary:
            # shrink inside the biggest phase before dropping any phase
            # outright: CI smoke asserts phase *presence* on this line, so
            # a phase key must survive even if its fields don't
            biggest = max(summary, key=lambda k: len(json.dumps({k: summary[k]})))
            entry = summary[biggest]
            if isinstance(entry, dict) and entry:
                fattest = max(entry, key=lambda k: len(json.dumps({k: entry[k]})))
                entry.pop(fattest)
            else:
                # scalar or already-empty dict: popping the key is the only
                # shrink left (unreachable in practice — a full set of empty
                # phase dicts is far under budget)
                summary.pop(biggest)
            line = json.dumps(obj, separators=(",", ":"))
    # the artifact contract: whatever shrinking happened above, the line a
    # harness machine-parses MUST fit its line buffer — blowing this is a
    # bench bug (a phase emitting unbounded scalars), not a soft condition
    assert len(line) <= FINAL_LINE_BUDGET, (
        f"final line {len(line)} chars > {FINAL_LINE_BUDGET} after shrink"
    )
    sys.stderr.flush()
    sys.stdout.flush()
    print(line, flush=True)


# transient transport shapes worth ONE bench-phase retry (the r5 artifact
# lost the whole e2e_long phase to a single remote_compile "response body
# closed" mid-stream); anything else is deterministic and fails the phase
# on the first attempt
_TRANSIENT_MARKERS = (
    "response body closed", "body closed", "remote_compile",
    "timeout", "timed out",
    "connection", "broken pipe", "reset by peer",
    # a flaked KV-block transfer (disagg phase) is a slow-peer artifact,
    # not a determinism bug: the worker already fell back to local
    # prefill, so the retried phase measures a clean wave. Note
    # asyncio.TimeoutError is caught by "timeout" via its TYPE name even
    # when str(e) is empty — the chain walker includes type names.
    "kv export", "kv transfer",
)

# jax wraps compile-service transport flakes in its own runtime-error
# types whose str() sometimes keeps only the status code, not the marker
# text (the r05 loss surfaced as "JaxRuntimeError: INTERNAL: ..."): an
# INTERNAL/UNAVAILABLE runtime error is worth the one retry — a
# deterministic compile failure reproduces identically on attempt two, so
# retrying never masks a real bug, it only re-times a flake
_TRANSIENT_TYPES = ("jaxruntimeerror", "xlaruntimeerror")


def _transient_error(e: BaseException) -> bool:
    """True when ``e`` looks like a transient transport/compile-service
    flake. Walks the cause/context chain — jax re-raises with the
    interesting gRPC detail one level down, where a bare str(e) check
    (the pre-r6 classifier) never saw it."""
    parts = []
    cur: BaseException | None = e
    for _ in range(8):
        if cur is None:
            break
        parts.append(f"{type(cur).__name__}: {cur}")
        nxt = cur.__cause__ or cur.__context__
        cur = nxt if nxt is not cur else None
    text = " | ".join(parts).lower()
    if any(s in text for s in _TRANSIENT_MARKERS):
        return True
    # a tpu_compile_helper subprocess dying mid-compile is a flaky compile
    # service UNLESS it died of OOM — an OOM reproduces deterministically
    # on attempt two (same program, same HBM), so retrying just doubles the
    # time to the same failure
    if "tpu_compile_helper" in text and not any(
        s in text for s in ("out of memory", "oom", "resource exhausted")
    ):
        return True
    return any(t in text for t in _TRANSIENT_TYPES) and (
        "internal" in text or "unavailable" in text
    )


def _warm_retry(batcher, widths: tuple[int, ...] | None = None) -> int:
    """``warm_chunk_programs`` with ONE retry on transient compile-service
    errors: the deterministic pre-warm exists to keep compiles out of the
    timed window, so a remote_compile flake during warmup must not kill
    the whole phase before its measurement even starts (the r05 e2e_long
    loss). A second failure propagates to ``_run_phase``'s own retry."""
    try:
        return batcher.warm_chunk_programs(widths)
    except Exception as e:  # noqa: BLE001 — classify, retry once
        if not _transient_error(e):
            raise
        time.sleep(2.0)
        return batcher.warm_chunk_programs(widths)


def _run_phase(detail: dict, name: str, fn) -> None:
    """Run one best-effort bench phase: ``detail[name]`` on success,
    ``detail[f"{name}_error"]`` on failure, with one retry on transient
    transport errors (``_transient_error``) — a successful retry records
    ``retried`` in the phase dict and the first error under
    ``{name}_first_error`` so the artifact shows the wobble instead of
    hiding it."""
    for attempt in (0, 1):
        try:
            result = fn()
            detail[name] = result
            detail.pop(f"{name}_error", None)
            if attempt and isinstance(result, dict):
                result["retried"] = True
            return
        except Exception as e:  # noqa: BLE001 — report, don't die
            msg = f"{type(e).__name__}: {e}"
            detail[f"{name}_error"] = msg
            if attempt or not _transient_error(e):
                return
            detail[f"{name}_first_error"] = msg
            gc.collect()
            time.sleep(2.0)  # let the flaked tunnel/compile stream settle


def main() -> None:
    tiny = bool(os.environ.get("BENCH_TINY"))
    detail: dict = {"quant": "int8", "platform": jax.devices()[0].platform}

    if tiny:
        # smoke path: an UNQUANTIZED tiny model — named honestly so nobody
        # mistakes a smoke line for an 8B int8 measurement
        cfg = ModelConfig.tiny()
        from nats_llm_studio_tpu.models.llama import ensure_lm_head

        params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
        r = decode_bench(cfg, params, batch=2, prompt_len=16, seq_len=64, steps=8)
        tiny_detail = {"quant": cfg.dtype, "platform": detail["platform"],
                       "tiny": r}
        if os.environ.get("BENCH_SPEC", "1") != "0":
            # micro-run of the spec phase (CI smoke coverage)
            _run_phase(tiny_detail, "spec_decode", lambda: spec_decode_bench(
                cfg, params, "bench/tiny",
                seq=256, n_reqs=2, max_new=24, spec_k=4,
            ))
        if os.environ.get("BENCH_PAGED", "1") != "0":
            # micro-run of the paged-KV phase: equal-budget capacity ratio
            # + zero-copy full-prefix sharing at tiny scale (CI smoke)
            _run_phase(tiny_detail, "paged_kv", lambda: paged_kv_bench(
                cfg, params, "bench/tiny", seq=256, slots=2, max_new=12,
            ))
        if os.environ.get("BENCH_KV_TIER", "1") != "0":
            # micro-run of the KV-tiering phase: 10 documents against a
            # 1-document prefix budget — demote on round 1, promote on
            # round 2, restart-with-warm-cache, zero kv_pool sheds
            _run_phase(tiny_detail, "kv_tiering", lambda: kv_tiering_bench(
                cfg, params, "bench/tiny",
                seq=256, chunk=64, slots=2, n_prompts=10, max_new=8,
            ))
        if os.environ.get("BENCH_QOS", "1") != "0":
            # micro-run of the multi-tenant QoS phase: 3-class overload
            # fairness (premium TTFT held, shed confined to batch/standard)
            # + preempt-to-host-tier vs shed-retry on a full pool
            _run_phase(tiny_detail, "qos", lambda: qos_bench(
                cfg, params, "bench/tiny", slots=2, n_each=4, max_new=8,
            ))
        if os.environ.get("BENCH_DECODE_KERNEL", "1") != "0":
            # micro-run of the decode-kernel phase: forced Pallas runs in
            # interpreter mode on CPU, so the smoke proves greedy parity
            # and the recompile-count ordering, not step latency
            _run_phase(tiny_detail, "decode_kernel",
                       lambda: decode_kernel_bench(
                           cfg, params, batches=[2], seq=128, max_new=8,
                           quant_batch=2,
                       ))
        if os.environ.get("BENCH_TP", "1") != "0":
            # micro-run of the tensor-parallel phase: meaningful under
            # forced host devices (XLA_FLAGS=--xla_force_host_platform_
            # device_count=8), reports skipped on one device
            _run_phase(tiny_detail, "tensor_parallel",
                       lambda: tensor_parallel_bench(
                           cfg, params, "bench/tiny",
                           seq=128, slots=4, n_reqs=4, max_new=16,
                       ))
        if os.environ.get("BENCH_MULTI_AXIS", "1") != "0":
            # micro-run of the multi-axis mesh phase: dp=2 replica aggregate
            # vs dp=1, routed-vs-dense MoE prefill, sp ring on/off — only
            # meaningful under forced host devices, skips on one device
            _run_phase(tiny_detail, "multi_axis", lambda: multi_axis_bench(
                cfg, params, "bench/tiny",
                seq=128, slots=2, n_reqs=4, max_new=8,
            ))
        if os.environ.get("BENCH_OBS", "1") != "0":
            # micro-run of the recorder-overhead phase: on CPU smoke the
            # noise-floor guard does the work; TPU runs get the real 1% bound
            _run_phase(tiny_detail, "obs_overhead", lambda: obs_overhead_bench(
                cfg, params, seq=128, slots=2, n_reqs=2, max_new=12, rounds=2,
            ))
        if os.environ.get("BENCH_EFFICIENCY", "1") != "0":
            # micro-run of the compute-efficiency phase: nonzero MFU/MBU
            # for both program classes + device-time ledger reconciliation
            # under the served/cancel/deadline mix (CI smoke asserts the
            # phase lands in the detail)
            _run_phase(tiny_detail, "efficiency", lambda: efficiency_bench(
                cfg, params, seq=128, slots=2, n_reqs=6, max_new=16,
            ))
        if os.environ.get("BENCH_CHAOS", "1") != "0":
            # fault-injected serving: recovery must hold in CI smoke too
            _run_phase(tiny_detail, "chaos", chaos_bench)
        if os.environ.get("BENCH_CLUSTER", "1") != "0":
            # micro-run of the multi-worker failover phase: two workers,
            # one killed mid-wave — every request served or cleanly
            # retryable (CI smoke asserts the flag on the final line)
            _run_phase(tiny_detail, "cluster", lambda: cluster_bench(
                n_workers=2, n_clients=12, reqs_per_client=2, max_new=8,
            ))
        if os.environ.get("BENCH_DISAGG", "1") != "0":
            # micro-run of the disaggregated prefill/decode phase: 2+2 role
            # topology vs 4 monolithic under a small overload wave — CI
            # smoke asserts the phase lands in the detail
            _run_phase(tiny_detail, "disagg", lambda: disagg_bench(
                n_clients=8, reqs_per_client=2, max_new=8,
            ))
        if os.environ.get("BENCH_GATEWAY", "1") != "0":
            # micro-run of the HTTP front-door phase: gateway-vs-raw TTFT,
            # all-True-mask per-step overhead (tokens must stay identical),
            # and the n=4 prompt-sharing block cost (CI smoke)
            _run_phase(tiny_detail, "gateway", lambda: gateway_bench(
                n_reqs=4, max_new=12,
            ))
        if os.environ.get("BENCH_OBS_CLUSTER", "1") != "0":
            # micro-run of the cluster observability phase: assembled
            # two-hop trace + aggregator-vs-bench TTFT p95 parity (CI
            # smoke asserts the phase lands in the detail)
            _run_phase(tiny_detail, "obs_cluster", lambda: obs_cluster_bench(
                n_reqs=3, max_new=8,
            ))
        if os.environ.get("BENCH_AUTOSCALE", "1") != "0":
            # micro-run of the elastic autoscaling phase: cold vs
            # precompiled spawn TTFS, kill-and-replace with warm prefix
            # handoff (CI smoke asserts the phase lands in the detail)
            _run_phase(tiny_detail, "autoscale", lambda: autoscale_bench(
                n_clients=6, reqs_per_client=2, max_new=8,
            ))
        _print_final({
            "metric": "tiny_smoke_decode_tok_s",
            "value": r["tok_s"], "unit": "tok/s/chip",
            "vs_baseline": 0.0,
            "detail": tiny_detail,
        })
        return

    # -- headline: Llama-3-8B int8, batch sweep -----------------------------
    # flash prefill on the real chip (the serving stack's configuration;
    # decode's T=1 path is unaffected by the flag); decode_unroll makes
    # every per-layer cache access a static view (1440 -> 1799 tok/s at
    # b32); int8 KV (ops/kvcache.py) halves cache traffic AND capacity,
    # moving the batch frontier from b48 to b96 — measured b48 2608,
    # b64 3436, b96 4391 tok/s. BENCH_KV=none reverts to the bf16 cache.
    on_tpu = jax.default_backend() == "tpu"
    kv = os.environ.get("BENCH_KV", "int8")
    cfg = LLAMA3_8B.with_(use_flash_attention=on_tpu, decode_unroll=True,
                          kv_quant=kv)
    detail["kv_quant"] = kv
    params = init_params_int8(cfg)
    # defaults scale with the kv mode: the bf16 cache's HBM frontier is b48
    # (b56+ trips the 15.75 GB AOT compile budget next to the 8.7 GB int8
    # params — the estimate double-counts the donated cache); int8 KV halves
    # the cache and moves it to b96
    # b80 rides below the b96 HBM-pressure edge (b96 swings ~15% run to run
    # as the allocator sits ~0.5 GB from the ceiling); best-of reports it
    # when b96 lands on a bad run
    default_batches = "8,16,32,48,64,80,96" if kv == "int8" else "8,16,32,48"
    batches = [int(b) for b in
               os.environ.get("BENCH_BATCHES", default_batches).split(",")]
    prompt_len = int(os.environ.get("BENCH_PROMPT", "128"))
    # seq 512 (not 1024): the b32 [B, L, Hkv, S, D] cache at 1024 puts the
    # compile-time HBM estimate 0.4 GB over the 15.75 GB budget next to the
    # 8.7 GB int8 params (the AOT path double-counts the donated cache);
    # decode reads are window-bounded, so seq only sizes the allocation
    seq_len = int(os.environ.get("BENCH_SEQ", "512"))
    steps = int(os.environ.get("BENCH_STEPS", "128"))
    sweep = {}
    for b in batches:
        sweep[f"b{b}"] = decode_bench(cfg, params, b, prompt_len, seq_len, steps)
    # steady-state guard (VERDICT r3 weak #2): flag any point whose
    # prefill_s is >2x every neighbor's — a stall that slipped past
    # best-of-2 timing stays visible in the artifact instead of being
    # silently published as steady state
    keys = [f"b{b}" for b in batches]
    for i, kname in enumerate(keys):
        neigh = [sweep[keys[j]]["prefill_s"] for j in (i - 1, i + 1)
                 if 0 <= j < len(keys)]
        if neigh and sweep[kname]["prefill_s"] > 2 * max(neigh):
            sweep[kname]["prefill_outlier"] = True
    best_b = max(sweep, key=lambda k: sweep[k]["tok_s"])
    tok_s = sweep[best_b]["tok_s"]
    detail["llama3_8b"] = {"sweep": sweep, "best": best_b,
                           "prompt_len": prompt_len, "decode_steps": steps}

    # every phase below goes through _run_phase: best-effort, one retry on
    # transient transport failures, retried/first-error recorded per phase

    # -- long-context prefill (16k, single flash dispatch) ------------------
    if os.environ.get("BENCH_LONG", "1") != "0":
        _run_phase(detail, "long_prefill", lambda: long_prefill_bench(
            cfg, params, int(os.environ.get("BENCH_LONG_T", "16384"))
        ))

    # -- end-to-end over NATS with the SAME 8B engine ------------------------
    if os.environ.get("BENCH_E2E", "1") != "0":
        _run_phase(detail, "e2e", lambda: e2e_nats_bench(
            cfg, params, "bench/llama3-8b",
            clients_b=96 if kv == "int8" else 48,
        ))
        gc.collect()

    # -- long-context SERVING: >=4k-token prompts via chat_model -------------
    if os.environ.get("BENCH_E2E_LONG", "1") != "0":
        _run_phase(detail, "e2e_long", lambda: e2e_long_context_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- prefix cache: shared-system-prompt serving, ON vs OFF ---------------
    if os.environ.get("BENCH_PREFIX", "1") != "0":
        _run_phase(detail, "prefix_cache", lambda: prefix_cache_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- speculative decoding: prompt-lookup drafts, ON vs OFF ---------------
    if os.environ.get("BENCH_SPEC", "1") != "0":
        _run_phase(detail, "spec_decode", lambda: spec_decode_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- paged KV: block pool vs contiguous rings at equal HBM ---------------
    if os.environ.get("BENCH_PAGED", "1") != "0":
        _run_phase(detail, "paged_kv", lambda: paged_kv_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- KV tiering: swap-don't-shed at 10x the prefix budget, ON vs OFF ----
    if os.environ.get("BENCH_KV_TIER", "1") != "0":
        _run_phase(detail, "kv_tiering", lambda: kv_tiering_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- multi-tenant QoS: 3-class fairness + preempt vs shed-retry ----------
    if os.environ.get("BENCH_QOS", "1") != "0":
        _run_phase(detail, "qos", lambda: qos_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- decode kernels: Pallas vs XLA step latency, int4 vs int8 ------------
    if os.environ.get("BENCH_DECODE_KERNEL", "1") != "0":
        _run_phase(detail, "decode_kernel", lambda: decode_kernel_bench(
            cfg, params
        ))
        gc.collect()

    # -- tensor-parallel serving: tp=1 vs tp=N on the same engine ------------
    if os.environ.get("BENCH_TP", "1") != "0":
        _run_phase(detail, "tensor_parallel", lambda: tensor_parallel_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- multi-axis mesh: dp replicas / routed MoE / sp ring prefill ---------
    if os.environ.get("BENCH_MULTI_AXIS", "1") != "0":
        _run_phase(detail, "multi_axis", lambda: multi_axis_bench(
            cfg, params, "bench/llama3-8b"
        ))
        gc.collect()

    # -- observability overhead: flight recorder on vs off -------------------
    if os.environ.get("BENCH_OBS", "1") != "0":
        _run_phase(detail, "obs_overhead", lambda: obs_overhead_bench(
            cfg, params
        ))
        gc.collect()

    # -- compute efficiency: MFU/MBU roofline + waste attribution ------------
    if os.environ.get("BENCH_EFFICIENCY", "1") != "0":
        _run_phase(detail, "efficiency", lambda: efficiency_bench(
            cfg, params
        ))
        gc.collect()

    # -- chaos: fault-injected serving recovery (own tiny model) -------------
    if os.environ.get("BENCH_CHAOS", "1") != "0":
        _run_phase(detail, "chaos", chaos_bench)
        gc.collect()

    # -- cluster: kill-a-worker failover under overload (own tiny model) -----
    if os.environ.get("BENCH_CLUSTER", "1") != "0":
        _run_phase(detail, "cluster", cluster_bench)
        gc.collect()

    # -- disagg: 2+2 prefill/decode roles vs 4 monolithic (own tiny model) ---
    if os.environ.get("BENCH_DISAGG", "1") != "0":
        _run_phase(detail, "disagg", disagg_bench)
        gc.collect()

    # -- gateway: HTTP hop TTFT, constrained-mask cost, n fan-out HBM --------
    if os.environ.get("BENCH_GATEWAY", "1") != "0":
        _run_phase(detail, "gateway", gateway_bench)
        gc.collect()

    # -- obs_cluster: assembled two-hop trace + aggregator p95 parity --------
    if os.environ.get("BENCH_OBS_CLUSTER", "1") != "0":
        _run_phase(detail, "obs_cluster", obs_cluster_bench)
        gc.collect()

    # -- autoscale: cold/precompiled/warm-handoff TTFS, kill-and-replace -----
    if os.environ.get("BENCH_AUTOSCALE", "1") != "0":
        _run_phase(detail, "autoscale", autoscale_bench)
        gc.collect()

    del params
    gc.collect()

    # -- config-1 parity: granite-2b ----------------------------------------
    if os.environ.get("BENCH_GRANITE", "1") != "0":
        def _granite_phase() -> dict:
            from __graft_entry__ import GRANITE_2B

            gcfg = GRANITE_2B.with_(
                use_flash_attention=jax.default_backend() == "tpu",
                decode_unroll=True,
            )
            gparams = init_params_int8(gcfg, seed=1)
            try:
                return decode_bench(gcfg, gparams, 32, prompt_len, 1024, steps)
            finally:
                del gparams
                gc.collect()

        _run_phase(detail, "granite2b", _granite_phase)

    # -- MoE on-chip number (BASELINE config 4): routed vs dense dispatch ---
    if os.environ.get("BENCH_MOE", "1") != "0":
        _run_phase(detail, "moe", lambda: moe_bench(
            batch=int(os.environ.get("BENCH_MOE_BATCH", "32")),
            prompt_len=prompt_len, steps=steps,
        ))

    _print_final({
        "metric": f"llama3_8b_int8_decode_tok_s.{best_b}",
        "value": tok_s,
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s / NORTH_STAR_TOK_S, 3),
        "detail": detail,
    })


if __name__ == "__main__":
    main()

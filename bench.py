"""Benchmark: batched decode throughput + prefill TTFT on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Model: flagship granite-3.0-2b geometry (BASELINE.md config 1) with random
bf16 weights — throughput depends on shapes/dtypes, not weight values.
Baseline reference: the north-star 2000 tok/s/chip (BASELINE.md config 2).
Runs on the ambient JAX platform (real TPU under the driver; set
JAX_PLATFORMS=cpu BENCH_TINY=1 for a smoke run).
"""

from __future__ import annotations

import json
import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def _sync(x) -> None:
    """Force completion: block_until_ready alone does not flush execution on
    every remote-device transport, a device->host copy does."""
    jax.block_until_ready(x)
    np.asarray(x)

from nats_llm_studio_tpu.engine.sampling import sample
from nats_llm_studio_tpu.models.config import ModelConfig
from nats_llm_studio_tpu.models.llama import ensure_lm_head, forward, init_params, make_cache

NORTH_STAR_TOK_S = 2000.0


def e2e_nats_bench(cfg, params, n_concurrent: int = 8, max_tokens: int = 32) -> dict:
    """End-to-end serving benchmark: embedded broker + worker + real engine,
    driven via ``lmstudio.chat_model`` request/stream over the NATS wire —
    BASELINE.md's metric definition ("via nats req"), not raw engine speed.

    Returns {"ttft_p50_ms", "ttft_p95_ms", "e2e_tok_s", ...} measured at
    ``n_concurrent`` streaming clients (after a compile warmup request).
    """
    import asyncio

    from nats_llm_studio_tpu.config import WorkerConfig
    from nats_llm_studio_tpu.gguf.tokenizer import GGUFTokenizer, _byte_to_unicode
    from nats_llm_studio_tpu.serve import Worker
    from nats_llm_studio_tpu.serve.api import ModelNotFound, Registry
    from nats_llm_studio_tpu.serve.batcher import ContinuousBatcher
    from nats_llm_studio_tpu.serve.registry import JaxChatEngine
    from nats_llm_studio_tpu.transport import EmbeddedBroker, connect

    model_id = "bench/granite-2b"
    b2u = _byte_to_unicode()
    vocab = [b2u[i] for i in range(256)]
    vocab += [f"<filler_{i}>" for i in range(cfg.vocab_size - 257)]
    vocab.append("<|eot|>")
    tokenizer = GGUFTokenizer(
        "gpt2", vocab, merges=[], eos_id=cfg.vocab_size - 1, add_bos=False
    )
    # default burst width (8): raising it to 16 gains ~13% aggregate tok/s
    # but costs ~15% TTFT p50 (admits wait out a longer burst) — favor latency
    batcher = ContinuousBatcher(params, cfg, max_slots=n_concurrent, max_seq_len=1024)
    engine = JaxChatEngine(model_id, batcher, tokenizer, cfg, meta={})

    class Preloaded(Registry):
        async def list_models(self):
            return {"object": "list", "data": [engine.info()]}

        async def pull(self, identifier):
            raise ModelNotFound(identifier)

        async def delete(self, model_id):
            raise ModelNotFound(model_id)

        async def get_engine(self, mid):
            if mid != model_id:
                raise ModelNotFound(mid)
            return engine

        async def sync_from_bucket(self, name, model_id=None):
            raise ModelNotFound(name)

        def stats(self):
            return {"models_loaded": [model_id]}

    prompt = "benchmark prompt: " + "tell me about tensor processing units. " * 3

    async def drive() -> dict:
        broker = await EmbeddedBroker().start()
        worker = Worker(WorkerConfig(nats_url=broker.url), Preloaded())
        await worker.start()
        nc = await connect(broker.url)

        async def one_chat(tag: int) -> tuple[float, int, float]:
            body = json.dumps(
                {
                    "model": model_id,
                    "messages": [{"role": "user", "content": f"{prompt} [{tag}]"}],
                    "max_tokens": max_tokens,
                    "temperature": 0.8,
                    "seed": tag,
                    "stream": True,
                }
            ).encode()
            t0 = time.perf_counter()
            ttft = None
            n_tok = 0
            async for msg in nc.request_stream(
                "lmstudio.chat_model", body, timeout=600.0, idle_timeout=300.0
            ):
                if (msg.headers or {}).get("Nats-Stream-Done") is not None:
                    break
                if ttft is None:
                    ttft = time.perf_counter() - t0
                n_tok += 1
            return ttft if ttft is not None else float("nan"), n_tok, time.perf_counter() - t0

        # compile warmup: single admit, every padded group-admit width the
        # measured phase might split into (mpad in {2, 4, ..}), and the
        # decode burst — so no XLA compile lands inside the timed window
        await one_chat(0)
        w = 2
        while w <= n_concurrent:
            await asyncio.gather(*(one_chat(100 * w + i) for i in range(w)))
            w *= 2
        t0 = time.perf_counter()
        results = await asyncio.gather(*(one_chat(i + 1) for i in range(n_concurrent)))
        wall = time.perf_counter() - t0
        await nc.close()
        await worker.drain()
        await broker.stop()
        batcher.stop()
        # a stream whose very first token is a stop token has no TTFT sample
        ttfts = sorted(r[0] * 1e3 for r in results if r[0] == r[0]) or [0.0]
        total_toks = sum(r[1] for r in results)
        return {
            "ttft_p50_ms": round(ttfts[len(ttfts) // 2], 1),
            "ttft_p95_ms": round(ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.95))], 1),
            "e2e_tok_s": round(total_toks / wall, 1),
            "clients": n_concurrent,
            "max_tokens": max_tokens,
        }

    return asyncio.run(drive())


def main() -> None:
    tiny = bool(os.environ.get("BENCH_TINY"))
    if tiny:
        cfg = ModelConfig.tiny()
        batch, prompt_len, seq_len, steps = 2, 16, 64, 8
    else:
        from __graft_entry__ import GRANITE_2B

        cfg = GRANITE_2B.with_(use_flash_attention=jax.default_backend() == "tpu")
        # batch 32 is the serving sweet spot on one v5e chip: weight reads
        # amortize 4x better than batch 8 while cache+weights still fit HBM
        batch = int(os.environ.get("BENCH_BATCH", "32"))
        prompt_len = int(os.environ.get("BENCH_PROMPT", "128"))
        seq_len = int(os.environ.get("BENCH_SEQ", "1024"))
        steps = int(os.environ.get("BENCH_STEPS", "128"))

    quant = os.environ.get("BENCH_QUANT", "int8" if not tiny else "none")

    def build_params():
        params = ensure_lm_head(init_params(cfg, jax.random.PRNGKey(0)))
        if quant != "int8":
            return params
        # quantize on device: per-leaf absmax/round is fast there and avoids
        # a 5 GB host round-trip. Pop leaves as they quantize so the bf16
        # originals free eagerly — holding both copies OOMs at batch >= 48.
        from nats_llm_studio_tpu.ops.wquant import quantizable, quantize_weight

        def q(path, leaf):
            if not quantizable(path):
                return leaf
            out = quantize_weight(leaf, device=True)
            jax.block_until_ready(out.q)
            return out

        blocks = params.pop("blocks")
        out_blocks = {}
        for key in list(blocks.keys()):
            out_blocks[key] = q(key, blocks.pop(key))
        return {
            "embed": params["embed"],
            "out_norm": params["out_norm"],
            "lm_head": q("lm_head", params.pop("lm_head")),
            "blocks": out_blocks,
        }

    params = build_params()

    fwd = partial(forward, cfg=cfg)

    @jax.jit
    def prefill(params, tokens, k, v, start):
        logits, k, v = fwd(params, tokens=tokens, k_cache=k, v_cache=v, start_pos=start)
        return sample(logits[:, -1, :], jax.random.PRNGKey(1), temperature=0.0), k, v

    def bucket_window(max_pos: int) -> int | None:
        """Smallest 256-multiple covering every live slot (the batcher uses
        its bucket list the same way pre-wrap); None = full cache."""
        w = -(-(max_pos + 1) // 256) * 256
        return w if w < seq_len else None

    @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(5,))
    def decode(params, tok, k, v, pos, window):
        # serving-path decode: ring write slot == position (uniform rows)
        logits, k, v = fwd(params, tokens=tok[:, None], k_cache=k, v_cache=v, start_pos=pos,
                           ring_slot=pos[0] % k.shape[3], attn_window=window)
        return sample(logits[:, -1, :], jax.random.PRNGKey(2), temperature=0.0), k, v

    @partial(jax.jit, donate_argnums=(2, 3), static_argnums=(4, 6))
    def decode_n(params, tok, k, v, n, pos0, window):
        """n decode steps as one device-side scan: measures chip throughput
        without per-step host dispatch (the remote-device tunnel costs ~ms per
        call, which would swamp a ~6 ms memory-bound step)."""

        def body(carry, i):
            tok, k, v = carry
            pos = pos0 + i
            logits, k, v = fwd(params, tokens=tok[:, None], k_cache=k, v_cache=v,
                               start_pos=pos, ring_slot=pos[0] % k.shape[3],
                               attn_window=window)
            nxt = sample(logits[:, -1, :], jax.random.PRNGKey(2), temperature=0.0)
            return (nxt, k, v), nxt

        (tok, k, v), toks = jax.lax.scan(body, (tok, k, v), jnp.arange(n, dtype=jnp.int32))
        return tok, k, v, toks

    k, v = make_cache(cfg, batch, seq_len)
    tokens = jnp.ones((batch, prompt_len), jnp.int32)
    start = jnp.zeros((batch,), jnp.int32)

    # compile both programs
    tok, k, v = prefill(params, tokens, k, v, start)
    pos = jnp.full((batch,), prompt_len, jnp.int32)
    host_window = bucket_window(prompt_len + steps)
    tok, k, v = decode(params, tok, k, v, pos, host_window)
    _sync(tok)

    # prefill latency (compiled)
    k2, v2 = make_cache(cfg, batch, seq_len)
    t0 = time.perf_counter()
    tok2, k2, v2 = prefill(params, tokens, k2, v2, start)
    _sync(tok2)
    prefill_s = time.perf_counter() - t0
    del k2, v2

    # host-driven decode loop (includes per-step dispatch overhead)
    t0 = time.perf_counter()
    for i in range(steps):
        pos = jnp.full((batch,), prompt_len + 1 + i, jnp.int32)
        tok, k, v = decode(params, tok, k, v, pos, host_window)
    _sync(tok)
    host_dt = time.perf_counter() - t0
    host_tok_s = batch * steps / host_dt

    # device-side scan loop (chip throughput) — compile, then time a fresh run
    pos0 = jnp.full((batch,), prompt_len + 1 + steps, jnp.int32)
    window = bucket_window(prompt_len + 1 + 3 * steps)
    tok, k, v, _ = decode_n(params, tok, k, v, steps, pos0, window)
    _sync(tok)
    pos0 = pos0 + steps
    t0 = time.perf_counter()
    tok, k, v, toks = decode_n(params, tok, k, v, steps, pos0, window)
    _sync(toks)
    dt = time.perf_counter() - t0
    tok_s = batch * steps / dt

    detail = {
        "batch": batch,
        "prompt_len": prompt_len,
        "decode_steps": steps,
        "quant": quant,
        "prefill_s": round(prefill_s, 4),
        "host_loop_tok_s": round(host_tok_s, 1),
        "platform": jax.devices()[0].platform,
    }

    if not tiny and os.environ.get("BENCH_E2E", "1") != "0":
        # free the raw-engine buffers before the serving stack builds its own
        del k, v, tok, toks, params
        try:
            detail["e2e"] = e2e_nats_bench(cfg, build_params())
        except Exception as e:  # noqa: BLE001 — e2e is best-effort detail
            detail["e2e_error"] = f"{type(e).__name__}: {e}"

    print(
        json.dumps(
            {
                "metric": f"granite2b_{quant if quant != 'none' else cfg.dtype}_decode_tok_s"
                + (".tiny" if tiny else f".b{batch}"),
                "value": round(tok_s, 1),
                "unit": "tok/s/chip",
                "vs_baseline": round(tok_s / NORTH_STAR_TOK_S, 3),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()

"""Llama-family transformer in pure functional JAX.

One module covers the whole north-star zoo (BASELINE.md) and beyond:
Llama-3 (dense), Granite-3.x (dense + embedding/residual/attention/logit
multipliers), Mixtral (MoE FFN), Qwen2 (QKV biases), and Gemma (GeGLU,
(1+w) RMSNorm, scaled tied embeddings) — in GGUF these differ only by
metadata scales and a handful of family flags (models.config), not by
topology.

TPU-first structure: all per-layer weights carry a leading ``[L]`` axis and
the layer stack runs as a single ``lax.scan`` — one compiled block regardless
of depth, with the full KV cache riding the scan as carry (in-place updates;
see _attention_block for the measured design rationale). No Python loops, no
dynamic shapes under jit.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.flash_attention import (
    chunk_block_multiple,
    flash_attention_auto,
    flash_attention_chunk_auto,
    flash_attention_chunk_kvq_auto,
)
from ..ops.kvcache import KVQ, kv_update_slice
from ..ops.kvcache import is_quantized as kv_is_quantized
from ..ops.layers import (
    apply_rope,
    gqa_attention_hmajor,
    rms_norm,
    rope_cos_sin,
    swiglu,
)
from ..ops.wquant import mm, q_einsum
from .config import ModelConfig

Params = dict[str, Any]


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _attention_block(
    x: jax.Array,
    p: Params,
    cfg: ModelConfig,
    k_all: jax.Array,  # FULL cache [B, L, Hkv, S, D] — scan carry, updated in place
    v_all: jax.Array,
    layer: jax.Array,  # int32 scalar — this block's index into the L axis
    start_pos: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    mask: jax.Array,
    attn_window: int | None = None,
    allow_flash: bool = True,
    ring_slot: jax.Array | None = None,  # scalar: shared decode write slot
    mesh=None,  # enables the sp ring-attention prefill when the mesh has sp>1
    fresh_prefill: bool = False,  # static: caller guarantees start_pos == 0
    uniform_start: bool = False,  # static: caller guarantees every row of
    # start_pos is EQUAL (chunked prefill) — enables the cache-backed flash
    # continuation kernel instead of the dense [T, S] f32 score fallback
) -> tuple[jax.Array, jax.Array, jax.Array]:
    b, t, _ = x.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    s_max = k_all.shape[3]
    q = mm(x, p["wq"])
    k = mm(x, p["wk"])
    v = mm(x, p["wv"])
    if cfg.attn_bias:  # qwen2-family QKV biases
        q = q + p["bq"]
        k = k + p["bk"]
        v = v + p["bv"]
    q = q.reshape(b, t, hq, d)
    k = k.reshape(b, t, hkv, d)
    v = v.reshape(b, t, hkv, d)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    zero = jnp.zeros((), start_pos.dtype)
    win = attn_window if (attn_window is not None and attn_window < s_max) else s_max
    is_ring_decode = t == 1 and ring_slot is not None

    def _slice_codes(codes):
        if isinstance(layer, int):  # unrolled decode: static slice = view
            return codes[:, layer, :, :win]
        sl = jax.lax.dynamic_slice(codes, (zero, layer, zero, zero, zero),
                                   (b, 1, hkv, win, d))
        if is_ring_decode and mesh is None and jax.default_backend() == "tpu":
            # RING decode only: the attention dot wants the slice S-minor
            # while the cache at rest is write-friendly D/B-minor; left
            # alone, XLA materializes the slice AND relayout-copies it
            # (~300 us/layer at batch 32 — half the decode step).
            # Constraining the slice's layout merges both into one pass:
            # 19.3 -> 15.6 ms/step (granite-2b b32). In the POSITIONAL path
            # the per-row scatter pins a different cache layout and the same
            # constraint backfires into full-cache relayouts (~16x slower —
            # caught by scripts/ablate_decode.py).
            from jax.experimental.layout import Layout, with_layout_constraint

            sl = with_layout_constraint(
                sl, Layout(major_to_minor=(1, 0, 2, 4, 3))
            )
        return sl[:, 0]

    def layer_slice(cache):
        if not kv_is_quantized(cache):
            return _slice_codes(cache)
        # KVQ: slice codes (with the layout treatment) and scales
        if isinstance(layer, int):
            s_sl = cache.s[:, layer, :, :win]
        else:
            s_sl = jax.lax.dynamic_slice(
                cache.s, (zero, layer, zero, zero), (b, 1, hkv, win)
            )[:, 0]
        return KVQ(q=_slice_codes(cache.q), s=s_sl)

    def as_attn_operand(slab):
        """bf16 slabs cast to q.dtype; quantized slabs pass through (the
        attention fn folds the scales outside the int8 dots)."""
        return slab if kv_is_quantized(slab) else slab.astype(q.dtype)

    if is_ring_decode:
        # Ring decode (the serving hot path): every row writes its fresh
        # k/v at the SAME shared slot, so the cache update is ONE
        # dynamic-update-slice spanning the batch — no per-row scatter
        # (XLA lowers batched ragged scatters to a serialized while-loop,
        # ~4.5 ms/step at batch 8) and no layout conflict (the in-loop DUS
        # pins the cache to its default layout; without it XLA relayouts
        # the whole cache per step for the attention dot, ~3 ms/step).
        # Per-row validity is carried entirely by the ring mask built in
        # forward(); attention reads the full cache at measured ~400 GB/s.
        upd_k = k.transpose(0, 2, 1, 3)[:, None]  # [B,1,Hkv,1,D]
        upd_v = v.transpose(0, 2, 1, 3)[:, None]
        idx = (zero, layer, zero, ring_slot, zero)
        k_all = kv_update_slice(k_all, upd_k, idx)
        v_all = kv_update_slice(v_all, upd_v, idx)

        # attn_window in ring mode is the caller's promise that the ring has
        # not wrapped yet (ring_slot < window and all live tokens sit below
        # it) — then reading cache[:, :, :win] is complete. After the first
        # wrap the caller must pass None and attention reads the full ring.
        out = gqa_attention_hmajor(
            q,
            as_attn_operand(layer_slice(k_all)),
            as_attn_operand(layer_slice(v_all)),
            mask[:, :, :win],
            cfg.attn_scale,
        )
        return mm(out.reshape(b, t, hq * d), p["wo"]), k_all, v_all

    # Positional path (prefill, and decode without a shared ring slot):
    # the caches ride the layer scan as CARRY (not xs/ys — scan ys do not
    # alias xs, which would copy the whole cache every step). The fresh
    # rows scatter into the full array at (b, layer, :, pos, :); the carry
    # buffer's last use in the loop body is this scatter, so XLA performs
    # it in place. Batch is the LEADING cache axis so the vmapped scatter's
    # preferred batch-outermost physical layout IS the default layout — any
    # other order inserts a full-cache relayout copy per layer (measured:
    # 344 ms/step vs 5 ms). The ragged scatter itself lowers to a
    # serialized row loop (~4.5 ms/step at batch 8 — the reason serving
    # uses the ring path), but it also pins the cache layout, which keeps
    # the attention dot reading the cache IN PLACE at ~400 GB/s; every
    # structure that removed the scatter made XLA materialize+relayout the
    # slab per layer and lost more than the scatter costs.
    def write_row(cache_b, rows_b, s):  # cache_b [L,Hkv,S,D]; rows_b [Hkv,T,D]
        return kv_update_slice(cache_b, rows_b[None], (layer, zero, s, zero))

    write = jax.vmap(write_row)
    k_all = write(k_all, k.transpose(0, 2, 1, 3), start_pos)
    v_all = write(v_all, v.transpose(0, 2, 1, 3), start_pos)

    sp_ring = False
    if mesh is not None and t > 1:
        # long prompts only (RING_PREFILL_MIN_TOKENS): t is static under
        # jit, so each prefill bucket's program bakes its own ring-vs-dense
        # decision and short prompts keep the single-chip prefill lane
        from ..parallel.ring_attention import use_ring_prefill

        sp_ring = use_ring_prefill(mesh, t)

    if t > 1 and (sp_ring or (cfg.use_flash_attention and allow_flash)):
        # prefill at start_pos 0: the cache holds exactly k/v, so causal
        # attention over the fresh block equals attention over the cache.
        # At start_pos > 0 (chunked prefill) the fresh block misses earlier
        # cache entries, so fall back to full-cache attention — lax.cond
        # executes only the taken branch per step.
        def _fresh_block(ops):
            q, k, v = ops
            if sp_ring:
                # sequence-parallel prefill: T sharded on sp, K/V blocks
                # rotate the ring via ppermute (parallel/ring_attention) —
                # the long-context path where one chip cannot hold [T, T]
                from ..parallel.ring_attention import ring_attention

                return ring_attention(q, k, v, cfg.attn_scale, mesh)
            return flash_attention_auto(q, k, v, cfg.attn_scale)

        if fresh_prefill:
            # the caller guarantees start_pos == 0 (single-shot prefill /
            # fused admits). Crucially this SKIPS COMPILING the dense
            # branch: lax.cond compiles both sides, and the dense
            # [B, Hkv, G, T, S] scores buffer at long context is itself a
            # compile-time OOM (16k x 16k f32 = 32 GB)
            out = _fresh_block((q, k, v))
        else:
            def _chunk_tileable(dt, quantized: bool) -> bool:
                # mirror of the chunk kernels' block_k halving: the window
                # must divide by SOME power-of-two tile >= the operand's
                # sublane multiple (int8 codes need 32 rows), or the kernel
                # raises at trace time mid-serving (an odd max_seq like
                # 4600 is accepted by the batcher but only the dense path
                # can serve it)
                mult = chunk_block_multiple(quantized, jnp.dtype(dt).itemsize)
                bk = 512
                while win % bk and bk > mult:
                    bk //= 2
                return win % bk == 0

            def _continue(ops):
                # chunk continuation without the dense [T, win] f32 score
                # matrix (~1 GB/layer at a 4.6k window — most of a chunk's
                # wall time); start is a scalar-prefetch operand so ONE
                # program serves every chunk offset at a given window.
                qq = ops[0]
                k_sl = layer_slice(k_all)
                quantized = kv_is_quantized(k_sl)
                if uniform_start and not sp_ring and _chunk_tileable(qq.dtype, quantized):
                    v_sl = layer_slice(v_all)
                    if quantized:
                        # int8 KV: codes + scales stream straight into the
                        # kernel and dequantize per tile IN VMEM — half the
                        # HBM bytes of a bf16 slab and, decisively, no
                        # full-window dequant transient per layer per chunk
                        # (the r4 O(T^2) long-context prefill tail)
                        return flash_attention_chunk_kvq_auto(
                            qq, k_sl.q, k_sl.s, v_sl.q, v_sl.s,
                            cfg.attn_scale, start_pos[0]
                        )
                    return flash_attention_chunk_auto(
                        qq, k_sl.astype(qq.dtype), v_sl.astype(qq.dtype),
                        cfg.attn_scale, start_pos[0]
                    )
                return gqa_attention_hmajor(
                    qq, as_attn_operand(k_sl),
                    as_attn_operand(layer_slice(v_all)),
                    mask[:, :, :win], cfg.attn_scale,
                )

            out = jax.lax.cond(jnp.all(start_pos == 0), _fresh_block, _continue, (q, k, v))
    else:
        out = gqa_attention_hmajor(
            q,
            as_attn_operand(layer_slice(k_all)),
            as_attn_operand(layer_slice(v_all)),
            mask[:, :, :win],
            cfg.attn_scale,
        )
    return mm(out.reshape(b, t, hq * d), p["wo"]), k_all, v_all


def _moe_ffn(x: jax.Array, p: Params, cfg: ModelConfig) -> jax.Array:
    """Mixtral top-k routed FFN, dense-dispatch form (every expert computes
    every token; routing weights zero the unused ones). Correct everywhere;
    the expert-parallel ``shard_map`` path in parallel/ replaces this on a
    mesh with an ``expert`` axis."""
    router_logits = (x @ p["router"]).astype(jnp.float32)  # [B,T,E]
    top_w, top_idx = jax.lax.top_k(router_logits, cfg.n_experts_used)
    top_w = jax.nn.softmax(top_w, axis=-1)  # normalize over the selected k
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32) * top_w[..., None], axis=-2
    )  # dense combine weights [B,T,E]
    gate = jax.nn.silu(q_einsum("btd,edf->btef", x, p["w_gate_e"]))
    up = q_einsum("btd,edf->btef", x, p["w_up_e"])
    expert_out = q_einsum("btef,efd->bted", gate * up, p["w_down_e"])
    return jnp.einsum("bted,bte->btd", expert_out, combine.astype(x.dtype))


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # int32 [B, T]
    k_cache: jax.Array,  # [B, L, Hkv, S, D] (heads-major, see make_cache)
    v_cache: jax.Array,
    start_pos: jax.Array,  # int32 [B] — write offset per row (0 for prefill)
    attn_window: int | None = None,  # static: attend to cache[:window] only
    mesh=None,  # static: enables the expert-parallel routed-MoE shard_map
    ring_slot: jax.Array | None = None,  # int32 scalar: shared decode write slot
    logit_positions: jax.Array | None = None,  # int32 [B]: lm_head at these only
    fresh_prefill: bool = False,  # static: start_pos==0 guaranteed; skips
    # compiling the dense fallback branch (whose [B,Hkv,G,T,S] scores are a
    # compile-time OOM at long context)
    uniform_start: bool = False,  # static: every row of start_pos is EQUAL
    # (chunked-prefill callers) — the continuation branch then uses the
    # cache-backed flash kernel instead of the dense [T, win] f32 fallback
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (logits [B, T, vocab] f32, new k_cache, new v_cache);
    with ``logit_positions`` (per-row prompt-end indices) the logits are
    [B, 1, vocab] — prefill callers that only sample the next token skip T×
    the lm_head FLOPs and, decisively for long context, the [B, T, vocab]
    f32 materialization (16k × 128k vocab would be 8.4 GB).

    Handles prefill (T > 1, start_pos = 0) and batched decode (T = 1,
    start_pos = current length per row) with one trace. Right-padded prompts
    are safe: pad keys sit at positions only pad queries can see, and decode
    overwrites them in order. ``attn_window`` (a compile-time bucket >= every
    live sequence length) bounds attention reads to the active cache prefix.

    Decode modes (T = 1):
    * ``ring_slot`` given (the serving hot path): the cache S axis is a RING
      indexed by a global step counter shared across rows, not by per-row
      position. Every row's fresh k/v land at slot ``ring_slot``; a row with
      current length p attends to the p+1 ring slots ending at ``ring_slot``
      (its tokens are contiguous there because the batcher aligns each
      admitted prefix to end at the ring head, and every row writes every
      step). One shared slot = one batched cache write per layer — the shape
      XLA compiles to an in-place update at full HBM speed.
    * ``ring_slot`` None (tests, ragged callers): slots equal per-row
      positions, written by a per-layer batched scatter.
    """
    b, t = tokens.shape
    s_max = k_cache.shape[3]
    positions = start_pos[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]  # [B,T]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    key_pos = jnp.arange(s_max, dtype=jnp.int32)
    if t == 1 and ring_slot is not None:
        # ring validity: slot j holds row b's token iff it is one of the
        # start_pos+1 most recent ring slots (ending at ring_slot, wrapped)
        age = jnp.mod(ring_slot - key_pos, s_max)  # [S]
        mask = age[None, None, :] <= start_pos[:, None, None]  # [B,1,S]
    else:
        mask = key_pos[None, None, :] <= positions[:, :, None]  # [B,T,S]

    x = params["embed"][tokens].astype(jnp.dtype(cfg.dtype)) * cfg.embedding_scale

    def block_body(x, k_all, v_all, p, layer, allow_flash=True):
        attn_out, k_all, v_all = _attention_block(
            rms_norm(x, p["attn_norm"], cfg.rms_eps, cfg.norm_plus_one),
            p, cfg, k_all, v_all, layer,
            start_pos, cos, sin, mask, attn_window, allow_flash,
            ring_slot if t == 1 else None, mesh, fresh_prefill, uniform_start,
        )
        x = x + attn_out * cfg.residual_scale
        h = rms_norm(x, p["ffn_norm"], cfg.rms_eps, cfg.norm_plus_one)
        if cfg.is_moe:
            if cfg.use_routed_moe:
                from ..parallel.moe import routed_moe_ffn

                ffn_out = routed_moe_ffn(h, p, cfg, mesh, cfg.moe_capacity_factor)
            else:
                ffn_out = _moe_ffn(h, p, cfg)
        else:
            ffn_out = swiglu(h, p["w_gate"], p["w_up"], p["w_down"], cfg.mlp_act)
        x = x + ffn_out * cfg.residual_scale
        return x, k_all, v_all

    if cfg.decode_unroll and t == 1:
        # Unrolled decode: static layer indices make every cache access a
        # zero-copy view, at ~n_layers x the compile time.
        for l in range(cfg.n_layers):
            p = jax.tree.map(lambda a: a[l], params["blocks"])
            x, k_cache, v_cache = block_body(
                x, k_cache, v_cache, p, l, allow_flash=False
            )
    else:
        def block(carry, inputs):
            x, k_all, v_all = carry
            p, layer = inputs
            return block_body(x, k_all, v_all, p, layer), None

        layer_idx = jnp.arange(cfg.n_layers, dtype=jnp.int32)
        (x, k_cache, v_cache), _ = jax.lax.scan(
            block, (x, k_cache, v_cache), (params["blocks"], layer_idx)
        )

    logits = lm_head_logits(params, cfg, x, logit_positions, t)
    return logits, k_cache, v_cache


def _paged_attn_dispatch(q, k_pool, v_pool, tbl, pos, layer, scale: float, mesh):
    """The Pallas paged-decode kernel, shard_mapped over tp when a mesh is
    present (pallas_call is not GSPMD-partitionable, so the heads split is
    explicit: q heads and pool heads shard on tp, tables/positions
    replicate — the same layout pool_spec pins for the XLA path). The
    batcher only routes here when Hkv % tp == 0 (the replicated-KV GQA
    fallback stays on the XLA path)."""
    from ..ops.paged_attention import paged_decode_attention_auto

    tp = 0
    if mesh is not None:
        from ..parallel.mesh import AXIS_TP

        tp = mesh.shape.get(AXIS_TP, 1)
    if tp <= 1:
        return paged_decode_attention_auto(q, k_pool, v_pool, tbl, pos, layer, scale)

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_TP

    qspec = P(None, None, AXIS_TP, None)
    cspec = P(None, None, AXIS_TP, None, None)  # pool codes: heads at index 2
    sspec = P(None, None, AXIS_TP, None)
    rep2, rep1, rep0 = P(None, None), P(None), P()
    if kv_is_quantized(k_pool):
        def f(qh, kq, ks, vq, vs, tb, ps, ly):
            return paged_decode_attention_auto(
                qh, KVQ(q=kq, s=ks), KVQ(q=vq, s=vs), tb, ps, ly, scale
            )

        fn = shard_map(
            f, mesh=mesh,
            in_specs=(qspec, cspec, sspec, cspec, sspec, rep2, rep1, rep0),
            out_specs=qspec, check_rep=False,
        )
        return fn(q, k_pool.q, k_pool.s, v_pool.q, v_pool.s, tbl, pos,
                  jnp.asarray(layer, jnp.int32))

    def g(qh, kp, vp, tb, ps, ly):
        return paged_decode_attention_auto(qh, kp, vp, tb, ps, ly, scale)

    fn = shard_map(
        g, mesh=mesh,
        in_specs=(qspec, cspec, cspec, rep2, rep1, rep0),
        out_specs=qspec, check_rep=False,
    )
    return fn(q, k_pool, v_pool, tbl, pos, jnp.asarray(layer, jnp.int32))


def forward_decode_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # int32 [B, W] — W == 1 decode, W == k+1 spec verify
    k_pool,             # [NBp, L, Hkv, T, D] paged block pool (or KVQ pair)
    v_pool,
    tbl: jax.Array,     # [B, NB] int32 block table (NB static = max width)
    start_pos: jax.Array,  # int32 [B] — tokens already in each slot's cache
    mesh=None,
) -> tuple[jax.Array, Any, Any]:
    """Decode forward that reads/writes the paged pool DIRECTLY — no
    ``kv_pool_gather_view`` materialization, no windowed attention, no
    pow2-ladder recompiles (the attention grid spans the whole table width,
    ops/paged_attention.py). Per layer: project q/k/v, rope at the slot's
    positions, scatter the W fresh rows into the pool (quantize-on-write
    under KVQ — identical codes to the view path's ``kv_update_slice``),
    then run the Pallas kernel over the slot's entire paged history
    (write-then-attend: the causal frontier includes the fresh rows).

    Returns (logits [B, W, vocab] f32, k_pool, v_pool). Math mirrors
    ``forward``'s positional path op-for-op outside the attention
    accumulation order (online softmax vs dense), so greedy decode is
    token-identical through the batcher."""
    from ..ops.kvcache import kv_pool_write_rows

    b, w = tokens.shape
    hq, hkv, d = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    positions = start_pos[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    x = params["embed"][tokens].astype(jnp.dtype(cfg.dtype)) * cfg.embedding_scale

    # TP_OVERLAP: the row-sharded projections' all-reduce runs as a
    # ppermute ring (parallel/overlap.py) instead of one blocking psum —
    # decode-only (this stack), default off, dense-FFN only (MoE keeps its
    # own dispatch collectives)
    overlap = False
    if mesh is not None:
        from ..parallel.mesh import AXIS_TP
        from ..parallel.overlap import tp_overlap_enabled

        overlap = (tp_overlap_enabled() and not cfg.is_moe
                   and mesh.shape.get(AXIS_TP, 1) > 1
                   and cfg.n_kv_heads % mesh.shape.get(AXIS_TP, 1) == 0)

    def block_body(x, kp, vp, p, layer):
        h = rms_norm(x, p["attn_norm"], cfg.rms_eps, cfg.norm_plus_one)
        q = mm(h, p["wq"])
        k = mm(h, p["wk"])
        v = mm(h, p["wv"])
        if cfg.attn_bias:
            q = q + p["bq"]
            k = k + p["bk"]
            v = v + p["bv"]
        q = apply_rope(q.reshape(b, w, hq, d), cos, sin)
        k = apply_rope(k.reshape(b, w, hkv, d), cos, sin)
        v = v.reshape(b, w, hkv, d)
        kp = kv_pool_write_rows(kp, k, tbl, start_pos, layer)
        vp = kv_pool_write_rows(vp, v, tbl, start_pos, layer)
        out = _paged_attn_dispatch(q, kp, vp, tbl, start_pos, layer,
                                   cfg.attn_scale, mesh)
        attn_in = out.reshape(b, w, hq * d)
        if overlap:
            from ..parallel.overlap import overlap_row_proj

            proj = overlap_row_proj(attn_in, p["wo"], mesh)
        else:
            proj = mm(attn_in, p["wo"])
        x = x + proj * cfg.residual_scale
        hh = rms_norm(x, p["ffn_norm"], cfg.rms_eps, cfg.norm_plus_one)
        if cfg.is_moe:
            if cfg.use_routed_moe:
                from ..parallel.moe import routed_moe_ffn

                ffn_out = routed_moe_ffn(hh, p, cfg, mesh, cfg.moe_capacity_factor)
            else:
                ffn_out = _moe_ffn(hh, p, cfg)
        elif overlap:
            from ..parallel.overlap import overlap_ffn

            ffn_out = overlap_ffn(hh, p["w_gate"], p["w_up"], p["w_down"],
                                  cfg.mlp_act, mesh)
        else:
            ffn_out = swiglu(hh, p["w_gate"], p["w_up"], p["w_down"], cfg.mlp_act)
        x = x + ffn_out * cfg.residual_scale
        return x, kp, vp

    def block(carry, inputs):
        x, kp, vp = carry
        p, layer = inputs
        return block_body(x, kp, vp, p, layer), None

    layer_idx = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    (x, k_pool, v_pool), _ = jax.lax.scan(
        block, (x, k_pool, v_pool), (params["blocks"], layer_idx)
    )
    logits = lm_head_logits(params, cfg, x, None, w)
    return logits, k_pool, v_pool


def lm_head_logits(params: Params, cfg: ModelConfig, x: jax.Array,
                   logit_positions: jax.Array | None, t: int) -> jax.Array:
    """Shared output head (norm + lm_head, tied-embedding fallback,
    logit_positions gather): the dense forward and the pipeline-parallel
    forward (parallel/pipeline.py) must never diverge here."""
    if logit_positions is not None and t > 1:
        x = jnp.take_along_axis(x, logit_positions[:, None, None], axis=1)  # [B,1,d]
    x = rms_norm(x, params["out_norm"], cfg.rms_eps, cfg.norm_plus_one)
    lm_head = params.get("lm_head")
    if lm_head is None:
        lm_head = params["embed"].T
    return mm(x, lm_head).astype(jnp.float32) * cfg.logit_scale


def ensure_lm_head(params: Params) -> Params:
    """Materialize a contiguous [d_model, vocab] lm_head for tied-embedding
    models. forward() falls back to ``embed.T`` when absent, which is correct
    but leaves the output projection reading a transposed view every decode
    step; serving paths call this once at load so the hot loop gets the
    matmul-native layout (and the quantizer can see the leaf)."""
    if "lm_head" in params:
        return params
    params = dict(params)
    params["lm_head"] = jnp.swapaxes(params["embed"], 0, 1)  # eager: materializes
    return params


def make_cache(
    cfg: ModelConfig, batch: int, seq_len: int | None = None, dtype: str | None = None
) -> tuple[jax.Array, jax.Array]:
    """Zeroed KV cache pair, layout [B, L, Hkv, S, D] — batch-major so the
    per-row scatter's preferred physical layout IS the default layout (see
    _attention_block), heads-major within a row so each (batch, head) slab
    is contiguous and the decode attention dot streams it sequentially; the
    TP axis annotates Hkv and a sequence/ring axis annotates S without
    relayout (SURVEY.md §5). In ring-decode serving the S axis is a ring
    indexed by a shared step counter, not per-row position (see forward).

    With ``cfg.kv_quant == "int8"`` each cache is a ``KVQ`` pytree (int8
    codes + f32 per-position-per-head scales, ops/kvcache.py) in the same
    layout — half the HBM traffic and capacity per step."""
    s = seq_len or cfg.max_seq_len
    shape = (batch, cfg.n_layers, cfg.n_kv_heads, s, cfg.head_dim)
    if cfg.kv_quant == "int8":
        from ..ops.kvcache import kv_zeros

        return kv_zeros(shape), kv_zeros(shape)
    dt = jnp.dtype(dtype or cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


# ---------------------------------------------------------------------------
# parameter construction
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random small-scale init (tests / golden-logit fixtures)."""
    dt = jnp.dtype(cfg.dtype)
    keys = iter(jax.random.split(key, 24))

    def rand(*shape):
        return (jax.random.normal(next(keys), shape, jnp.float32) * 0.02).astype(dt)

    L, d, hq, hkv, hd, ff = (
        cfg.n_layers,
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.head_dim,
        cfg.d_ff,
    )
    blocks: Params = {
        "attn_norm": jnp.ones((L, d), dt),
        "ffn_norm": jnp.ones((L, d), dt),
        "wq": rand(L, d, hq * hd),
        "wk": rand(L, d, hkv * hd),
        "wv": rand(L, d, hkv * hd),
        "wo": rand(L, hq * hd, d),
    }
    if cfg.attn_bias:
        blocks |= {
            "bq": rand(L, hq * hd),
            "bk": rand(L, hkv * hd),
            "bv": rand(L, hkv * hd),
        }
    if cfg.is_moe:
        e = cfg.n_experts
        blocks |= {
            "router": rand(L, d, e),
            "w_gate_e": rand(L, e, d, ff),
            "w_up_e": rand(L, e, d, ff),
            "w_down_e": rand(L, e, ff, d),
        }
    else:
        blocks |= {"w_gate": rand(L, d, ff), "w_up": rand(L, d, ff), "w_down": rand(L, ff, d)}
    params: Params = {
        "embed": rand(cfg.vocab_size, d),
        "out_norm": jnp.ones((d,), dt),
        "blocks": blocks,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = rand(d, cfg.vocab_size)
    return params


# -- GGUF loading -----------------------------------------------------------


def _rope_deinterleave(w: np.ndarray, n_heads: int, head_dim: int) -> np.ndarray:
    """GGUF llama-family q/k weights expect interleaved-pair rotation
    (ggml "NORM" RoPE); our kernel rotates (first-half, second-half). Permute
    the output features so both agree: out index h*D + 2i+j -> h*D + j*D/2+i.
    """
    d_in = w.shape[0]
    return (
        w.reshape(d_in, n_heads, head_dim // 2, 2)
        .transpose(0, 1, 3, 2)
        .reshape(d_in, n_heads * head_dim)
    )


def load_params_from_gguf(reader, cfg: ModelConfig, dtype: str | None = None) -> Params:
    """Build the stacked-params pytree from a GGUFReader.

    Tensor names follow the public GGUF convention (token_embd, blk.N.*,
    output_norm, output). Weights are stored [out, in] (after the reader's
    dim reversal) and transposed here to [in, out] so forward() uses plain
    ``x @ w`` — the layout XLA maps straight onto the MXU.
    """
    dt = jnp.dtype(dtype or cfg.dtype)

    def t(name: str) -> np.ndarray:
        return reader.tensor(name).to_numpy()

    def mat(name: str) -> jax.Array:
        return jnp.asarray(np.ascontiguousarray(t(name).T), dt)

    L = cfg.n_layers
    stacked: dict[str, list] = {}

    def push(key: str, arr) -> None:
        stacked.setdefault(key, []).append(arr)

    for i in range(L):
        pre = f"blk.{i}"
        push("attn_norm", jnp.asarray(t(f"{pre}.attn_norm.weight"), dt))
        push("ffn_norm", jnp.asarray(t(f"{pre}.ffn_norm.weight"), dt))
        wq = np.ascontiguousarray(t(f"{pre}.attn_q.weight").T)
        wk = np.ascontiguousarray(t(f"{pre}.attn_k.weight").T)
        push("wq", jnp.asarray(_rope_deinterleave(wq, cfg.n_heads, cfg.head_dim), dt))
        push("wk", jnp.asarray(_rope_deinterleave(wk, cfg.n_kv_heads, cfg.head_dim), dt))
        push("wv", mat(f"{pre}.attn_v.weight"))
        push("wo", mat(f"{pre}.attn_output.weight"))
        if cfg.attn_bias:
            # biases live in the same output-feature space as the weights,
            # so q/k biases need the same rope pair permutation
            push("bq", jnp.asarray(_rope_deinterleave(
                t(f"{pre}.attn_q.bias")[None], cfg.n_heads, cfg.head_dim)[0], dt))
            push("bk", jnp.asarray(_rope_deinterleave(
                t(f"{pre}.attn_k.bias")[None], cfg.n_kv_heads, cfg.head_dim)[0], dt))
            push("bv", jnp.asarray(t(f"{pre}.attn_v.bias"), dt))
        if cfg.is_moe:
            push("router", mat(f"{pre}.ffn_gate_inp.weight"))
            # stacked expert tensors: reader shape (E, ff, d) -> [E, d, ff]
            push("w_gate_e", jnp.asarray(t(f"{pre}.ffn_gate_exps.weight").transpose(0, 2, 1), dt))
            push("w_up_e", jnp.asarray(t(f"{pre}.ffn_up_exps.weight").transpose(0, 2, 1), dt))
            push("w_down_e", jnp.asarray(t(f"{pre}.ffn_down_exps.weight").transpose(0, 2, 1), dt))
        else:
            push("w_gate", mat(f"{pre}.ffn_gate.weight"))
            push("w_up", mat(f"{pre}.ffn_up.weight"))
            push("w_down", mat(f"{pre}.ffn_down.weight"))

    params: Params = {
        "embed": jnp.asarray(t("token_embd.weight"), dt),
        "out_norm": jnp.asarray(t("output_norm.weight"), dt),
        "blocks": {k: jnp.stack(v) for k, v in stacked.items()},
    }
    if "output.weight" in reader.tensors:
        params["lm_head"] = mat("output.weight")
    return params

"""Model hyperparameters, readable from GGUF metadata.

Key names follow the public GGUF conventions (``<arch>.block_count`` etc.)
that conversion tools write; ``from_gguf`` therefore loads any
llama/granite/mixtral-family file without sidecar config.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any


@dataclass(frozen=True)
class ModelConfig:
    arch: str = "llama"
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    head_dim: int = 128
    d_ff: int = 14336
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    # MoE (Mixtral-style); 0 experts = dense
    n_experts: int = 0
    n_experts_used: int = 0
    # Granite-3.x multipliers (all 1.0 / None for llama)
    embedding_scale: float = 1.0
    residual_scale: float = 1.0
    attention_scale: float | None = None  # None -> 1/sqrt(head_dim)
    logit_scale: float = 1.0
    # Qwen2-family: QKV projections carry biases
    attn_bias: bool = False
    # Gemma-family: GELU MLP and RMSNorm computing x * (1 + w)
    mlp_act: str = "silu"  # "silu" | "gelu"
    norm_plus_one: bool = False
    dtype: str = "bfloat16"  # compute/weight dtype name (tests use float32)
    # KV cache storage: "none" (cache in `dtype`) or "int8" (codes + per-
    # position-per-head scales, ops/kvcache.py — halves decode's cache
    # traffic and capacity, unlocking larger serving batches)
    kv_quant: str = "none"
    # Pallas flash-attention for prefill (requires prefill at start_pos 0,
    # which the engine guarantees); decode keeps the fused XLA path
    use_flash_attention: bool = False
    # MoE dispatch: routed (sparse scatter/gather + optional ep shard_map,
    # parallel/moe.py) vs dense reference (every expert computes every token)
    use_routed_moe: bool = False
    moe_capacity_factor: float = 2.0
    # Unroll the decode-step layer loop (t == 1) instead of lax.scan: every
    # layer/cache index becomes static, so XLA reads each cache slab as a
    # view — no dynamic-slice materialization, no per-layer kernel-launch
    # overhead (a pallas_call costs ~93 us on the serving chip; 40 layers of
    # that is most of a decode step). Costs ~n_layers x compile time for the
    # decode program only; prefill keeps the scan.
    decode_unroll: bool = False

    @property
    def attn_scale(self) -> float:
        return self.attention_scale if self.attention_scale is not None else self.head_dim**-0.5

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    def with_(self, **kw: Any) -> "ModelConfig":
        return replace(self, **kw)

    # -- construction --------------------------------------------------------

    @classmethod
    def from_gguf_metadata(cls, md: dict[str, Any]) -> "ModelConfig":
        arch = str(md.get("general.architecture", "llama"))

        def g(key: str, default: Any = None) -> Any:
            return md.get(f"{arch}.{key}", default)

        n_heads = int(g("attention.head_count", 32))
        d_model = int(g("embedding_length", 4096))
        head_dim = int(g("attention.key_length", d_model // n_heads))
        vocab = md.get(f"{arch}.vocab_size")
        if vocab is None:
            toks = md.get("tokenizer.ggml.tokens")
            vocab = len(toks) if toks is not None else 32000
        # architecture-family quirks beyond the metadata keys (the same
        # special-casing llama.cpp's build_* graph constructors apply).
        # Families whose topology this model does NOT implement are rejected
        # loudly — half-running them (dropped shared experts, missing
        # post-norms/softcapping) would load fine and produce garbage.
        if arch in ("gemma2", "gemma3", "qwen2moe"):
            raise NotImplementedError(
                f"architecture {arch!r} needs topology this model does not "
                "implement (post-norms/softcapping or shared experts)"
            )
        family: dict[str, Any] = {}
        if arch == "qwen2":
            family["attn_bias"] = True
        elif arch == "gemma":
            # NOTE: no norm_plus_one here — llama.cpp's GGUF converter folds
            # gemma's (1+w) into the stored norm weights, so GGUF-loaded
            # models use the plain multiply. The flag exists for checkpoints
            # that keep the HF convention.
            family |= {
                "mlp_act": "gelu",
                "tie_embeddings": True,
                # gemma scales embeddings by sqrt(d_model)
                "embedding_scale": float(d_model) ** 0.5,
            }
        kwargs: dict[str, Any] = dict(
            arch=arch,
            vocab_size=int(vocab),
            d_model=d_model,
            n_layers=int(g("block_count", 32)),
            n_heads=n_heads,
            n_kv_heads=int(g("attention.head_count_kv", n_heads)),
            head_dim=head_dim,
            d_ff=int(g("feed_forward_length", 4 * d_model)),
            rope_theta=float(g("rope.freq_base", 10000.0)),
            rms_eps=float(g("attention.layer_norm_rms_epsilon", 1e-5)),
            max_seq_len=int(g("context_length", 8192)),
            n_experts=int(g("expert_count", 0) or 0),
            n_experts_used=int(g("expert_used_count", 0) or 0),
            embedding_scale=float(g("embedding_scale", 1.0)),
            residual_scale=float(g("residual_scale", 1.0)),
            attention_scale=(
                float(g("attention.scale")) if g("attention.scale") is not None else None
            ),
            # GGUF stores granite's logit scale as a divisor (engines multiply
            # final logits by 1/f_logit_scale); internally we keep a multiplier
            logit_scale=1.0 / float(g("logit_scale", 1.0)),
        )
        kwargs.update(family)  # family quirks win over absent metadata keys
        return cls(**kwargs)

    @classmethod
    def tiny(cls, **kw: Any) -> "ModelConfig":
        """A 4-layer toy config for CPU tests."""
        base = dict(
            vocab_size=512,
            d_model=64,
            n_layers=4,
            n_heads=4,
            n_kv_heads=2,
            head_dim=16,
            d_ff=128,
            max_seq_len=256,
            dtype="float32",
        )
        base.update(kw)
        return cls(**base)

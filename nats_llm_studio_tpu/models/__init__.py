"""Model architectures in pure functional JAX.

The reference's "model zoo" is LM Studio's external catalog — models are
opaque GGUF ids shelled out to `lms get` (/root/reference/nats_llm_studio.go:51)
and executed by llama.cpp. Here the architectures the north-star configs name
(BASELINE.md: Llama-3 8B/70B, Granite-3.0-2B, Mixtral-8x7B) are in-tree.

Params are pytrees with all per-layer weights stacked on a leading [L] axis so
the layer stack runs as one compiled ``lax.scan`` block (one XLA compilation
unit regardless of depth) and sharding rules address whole stacks at once.
"""

from .config import ModelConfig
from .llama import forward, init_params, load_params_from_gguf

__all__ = ["ModelConfig", "forward", "init_params", "load_params_from_gguf"]

"""Export a params pytree to a GGUF file.

Round-trips with ``load_params_from_gguf``: the fixture-creation path for
integration tests (SURVEY.md §4.1) and the conversion path for publishing
models into the Object Store bucket in the reference's
``<publisher>/<model>/<file>.gguf`` layout (/root/reference/README.md:279-281).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np

from ..gguf.constants import GGMLType
from ..gguf.writer import GGUFWriter
from .config import ModelConfig


def _np(x) -> np.ndarray:
    arr = np.asarray(x, dtype=np.float32) if getattr(x, "dtype", None) != np.float32 else np.asarray(x)
    return arr


def _rope_interleave(w: np.ndarray, n_heads: int, head_dim: int) -> np.ndarray:
    """Inverse of models.llama._rope_deinterleave: (first-half, second-half)
    feature order back to GGUF's interleaved pairs."""
    d_in = w.shape[0]
    return (
        w.reshape(d_in, n_heads, 2, head_dim // 2)
        .transpose(0, 1, 3, 2)
        .reshape(d_in, n_heads * head_dim)
    )


def export_params_to_gguf(
    path: str | Path,
    params: dict[str, Any],
    cfg: ModelConfig,
    tokenizer_md: dict[str, Any] | None = None,
    name: str = "exported-model",
    quant: GGMLType = GGMLType.F32,
    norm_quant: GGMLType = GGMLType.F32,
) -> Path:
    w = GGUFWriter(path)
    md: dict[str, Any] = {
        "general.architecture": cfg.arch,
        "general.name": name,
        f"{cfg.arch}.block_count": cfg.n_layers,
        f"{cfg.arch}.embedding_length": cfg.d_model,
        f"{cfg.arch}.attention.head_count": cfg.n_heads,
        f"{cfg.arch}.attention.head_count_kv": cfg.n_kv_heads,
        f"{cfg.arch}.attention.key_length": cfg.head_dim,
        f"{cfg.arch}.attention.value_length": cfg.head_dim,
        f"{cfg.arch}.feed_forward_length": cfg.d_ff,
        f"{cfg.arch}.rope.freq_base": cfg.rope_theta,
        f"{cfg.arch}.attention.layer_norm_rms_epsilon": cfg.rms_eps,
        f"{cfg.arch}.context_length": cfg.max_seq_len,
        f"{cfg.arch}.vocab_size": cfg.vocab_size,
    }
    if cfg.is_moe:
        md[f"{cfg.arch}.expert_count"] = cfg.n_experts
        md[f"{cfg.arch}.expert_used_count"] = cfg.n_experts_used
    if cfg.arch == "granite":
        md["granite.embedding_scale"] = cfg.embedding_scale
        md["granite.residual_scale"] = cfg.residual_scale
        md["granite.logit_scale"] = 1.0 / cfg.logit_scale  # stored as divisor
        if cfg.attention_scale is not None:
            md["granite.attention.scale"] = cfg.attention_scale
    w.add_dict(md)
    if tokenizer_md:
        w.add_dict(tokenizer_md)

    def put(gguf_name: str, arr: np.ndarray, q: GGMLType) -> None:
        w.add_tensor(gguf_name, arr, q)

    # embeddings / head / final norm — stored [out, in] like llama.cpp writes
    put("token_embd.weight", _np(params["embed"]), quant)
    put("output_norm.weight", _np(params["out_norm"]), norm_quant)
    if "lm_head" in params:
        put("output.weight", _np(params["lm_head"]).T, quant)

    blocks = params["blocks"]
    L = cfg.n_layers
    for i in range(L):
        pre = f"blk.{i}"

        def layer(key: str) -> np.ndarray:
            return _np(blocks[key][i])

        put(f"{pre}.attn_norm.weight", layer("attn_norm"), norm_quant)
        put(f"{pre}.ffn_norm.weight", layer("ffn_norm"), norm_quant)
        wq = _rope_interleave(layer("wq"), cfg.n_heads, cfg.head_dim)
        wk = _rope_interleave(layer("wk"), cfg.n_kv_heads, cfg.head_dim)
        put(f"{pre}.attn_q.weight", wq.T, quant)
        put(f"{pre}.attn_k.weight", wk.T, quant)
        put(f"{pre}.attn_v.weight", layer("wv").T, quant)
        put(f"{pre}.attn_output.weight", layer("wo").T, quant)
        if cfg.attn_bias:
            bq = _rope_interleave(layer("bq")[None], cfg.n_heads, cfg.head_dim)[0]
            bk = _rope_interleave(layer("bk")[None], cfg.n_kv_heads, cfg.head_dim)[0]
            put(f"{pre}.attn_q.bias", bq, GGMLType.F32)
            put(f"{pre}.attn_k.bias", bk, GGMLType.F32)
            put(f"{pre}.attn_v.bias", layer("bv"), GGMLType.F32)
        if cfg.is_moe:
            put(f"{pre}.ffn_gate_inp.weight", layer("router").T, GGMLType.F32)
            put(f"{pre}.ffn_gate_exps.weight", layer("w_gate_e").transpose(0, 2, 1), quant)
            put(f"{pre}.ffn_up_exps.weight", layer("w_up_e").transpose(0, 2, 1), quant)
            put(f"{pre}.ffn_down_exps.weight", layer("w_down_e").transpose(0, 2, 1), quant)
        else:
            put(f"{pre}.ffn_gate.weight", layer("w_gate").T, quant)
            put(f"{pre}.ffn_up.weight", layer("w_up").T, quant)
            put(f"{pre}.ffn_down.weight", layer("w_down").T, quant)
    return w.write()

from .nuid import next_nuid
from .subjects import subject_matches, valid_subject

__all__ = ["next_nuid", "subject_matches", "valid_subject"]

"""NATS subject syntax: validation and wildcard matching.

Implements the standard NATS rules the reference relies on implicitly through
nats-server (subjects ``lmstudio.*`` — /root/reference/README.md:17-21):
tokens separated by ``.``, ``*`` matches exactly one token, ``>`` matches one
or more trailing tokens.
"""

from __future__ import annotations


def valid_subject(subject: str, allow_wildcards: bool = False) -> bool:
    if not subject or subject.startswith(".") or subject.endswith("."):
        return False
    for tok in subject.split("."):
        if not tok:
            return False
        if any(c in tok for c in (" ", "\t", "\r", "\n")):
            return False
        if not allow_wildcards and tok in ("*", ">"):
            return False
    return True


def subject_matches(pattern: str, subject: str) -> bool:
    """True if a subscription ``pattern`` (may contain wildcards) matches ``subject``."""
    ptoks = pattern.split(".")
    stoks = subject.split(".")
    i = 0
    for i, ptok in enumerate(ptoks):
        if ptok == ">":
            return i < len(stoks)
        if i >= len(stoks):
            return False
        if ptok != "*" and ptok != stoks[i]:
            return False
    return len(ptoks) == len(stoks)

"""NUID — fast unique identifiers for inboxes and upload ids.

Mirrors the shape of NATS NUIDs (22 base-62 chars) so inbox subjects look like
``_INBOX.<22 chars>.<seq>``, matching what nats.go clients generate (the
reference's client example relies on ordinary request/reply inboxes,
/root/reference/README.md:508-562).
"""

from __future__ import annotations

import os
import threading

_DIGITS = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
_BASE = 62
_PRE_LEN = 12
_SEQ_LEN = 10
_MAX_SEQ = _BASE**_SEQ_LEN


class _Nuid:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._prefix = self._random_prefix()
        self._seq = int.from_bytes(os.urandom(8), "big") % (_MAX_SEQ // 2)
        self._inc = 100 + int.from_bytes(os.urandom(2), "big") % 300

    @staticmethod
    def _random_prefix() -> str:
        raw = os.urandom(_PRE_LEN)
        return "".join(_DIGITS[b % _BASE] for b in raw)

    def next(self) -> str:
        with self._lock:
            self._seq += self._inc
            if self._seq >= _MAX_SEQ:
                self._prefix = self._random_prefix()
                self._seq = int.from_bytes(os.urandom(8), "big") % (_MAX_SEQ // 2)
            seq = self._seq
        out = []
        for _ in range(_SEQ_LEN):
            seq, rem = divmod(seq, _BASE)
            out.append(_DIGITS[rem])
        return self._prefix + "".join(reversed(out))


_global = _Nuid()


def next_nuid() -> str:
    """Return a process-unique 22-char identifier."""
    return _global.next()

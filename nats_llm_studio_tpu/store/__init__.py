"""Model repository: JetStream-style Object Store + local model cache.

The reference describes JetStream Object Store as the central ``.gguf``
repository but never implements it (/root/reference/README.md:250-318; the
``sync_model_from_bucket`` subject is explicitly conceptual, :286-289). Here
it is first-class: a server-side store module on the embedded broker speaking
the public JetStream wire subjects (``$JS.API.>``, ``$O.<bucket>.>``), a
client, and a model manager maintaining the reference's on-disk cache layout
``<models_dir>/<publisher>/<model>/`` (nats_llm_studio.go:120).
"""

from .manager import ModelStore
from .objectstore import JetStreamStoreModule

__all__ = ["ModelStore", "JetStreamStoreModule"]

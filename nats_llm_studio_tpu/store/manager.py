"""ModelStore: local GGUF cache + Object Store distribution.

Reproduces the reference's on-disk contract — models live at
``<models_dir>/<publisher>/<model>/*.gguf`` (nats_llm_studio.go:120, README
default ``~/.lmstudio/models``) and bucket objects are named
``<publisher>/<model>/<file>.gguf`` (README.md:279-281). The reference's
delete-path duplication bug (publisher derived from an id that already
contains it, nats_llm_studio.go:111-120 — SURVEY.md §2.1) is consciously
fixed here: ids are always ``publisher/model`` and never re-prefixed.
"""

from __future__ import annotations

import asyncio
import os
import re
import shutil
from dataclasses import dataclass
from pathlib import Path

from ..gguf.reader import _SPLIT_RE
from ..transport.jetstream import ObjectNotFound, ObjectStore
from ..utils.nuid import next_nuid



def _tmp_part(dest_dir: Path, fname: str) -> Path:
    """Unique temp path per pull: concurrent pulls of the same target must
    not interleave writes into a shared .part file."""
    return dest_dir / f".{fname}.{os.getpid()}.{next_nuid()[:8]}.part"


def _shard_family(files: "list[Path]") -> "list[Path]":
    """The files that form ONE model: files[0]'s gguf-split family (every
    shard with the same base and total), or just files[0] for a plain file.
    Keeps publish from shipping unrelated .gguf files that share a dir."""
    first = files[0]
    m = _SPLIT_RE.match(first.name)
    if not m:
        return [first]
    base, total = m.group(1), m.group(3)
    fam = [
        f for f in files
        if (fm := _SPLIT_RE.match(f.name)) and fm.group(1) == base and fm.group(3) == total
    ]
    return sorted(fam)


def _check_split_complete(names: "list[str]") -> None:
    """Every split-named object's family must be complete — pulling a
    partial shard set would cache a model that cannot load."""
    have = set(names)
    for nm in names:
        m = _SPLIT_RE.match(Path(nm).name)
        if not m:
            continue
        base, total = m.group(1), int(m.group(3))
        prefix = nm.rsplit("/", 1)[0]
        for i in range(total):
            want = f"{prefix}/{base}-{i + 1:05d}-of-{total:05d}.gguf"
            if want not in have:
                raise StoreError(
                    f"incomplete split set in bucket: missing {want!r}"
                )

class StoreError(Exception):
    def __init__(self, msg: str, dir: str | None = None):
        super().__init__(msg)
        self.dir = dir


@dataclass
class CachedModel:
    model_id: str  # "publisher/model"
    publisher: str
    name: str
    path: Path  # directory
    files: list[Path]  # .gguf files inside

    @property
    def gguf_path(self) -> Path:
        return self.files[0]

    @property
    def size(self) -> int:
        return sum(f.stat().st_size for f in self.files)


# every path component a model id may contribute to the cache layout: must
# start alphanumeric (excludes '.', '..', hidden files), stay in a
# conservative charset (excludes separators, NUL, '~', '%'-escapes resolving
# later), and not END in '.' or ' ' — Windows strips those, so two distinct
# advertised ids would collide on one directory there. Trailing '_'/'-' are
# safe on every platform and stay allowed (ids cached by earlier releases
# must remain listable/deletable). Model ids are CLIENT-CONTROLLED
# (pull/delete/sync subjects), and model_dir()/delete_local() turn them into
# mkdir/rmtree targets.
_SAFE_COMPONENT = re.compile(r"[A-Za-z0-9](?:[A-Za-z0-9._\- ]*[A-Za-z0-9_\-])?\Z")
# lenient variant for dirs that ALREADY exist in the cache (written by an
# earlier release whose pattern allowed trailing '.'): same conservative
# charset — no traversal, no separators — so listing/deleting them stays
# safe ON POSIX; only CREATION is held to the strict pattern. Without this
# a legacy 'pub/llama3.' dir could never be reclaimed over the bus.
# Trailing SPACE stays excluded even here: split_model_id's whole-id strip
# collapses 'pub/llama3 ' to 'pub/llama3', so a trailing-space id can only
# ever alias its sibling (rmtree the WRONG model) — those dirs were never
# addressable over the bus and must not be advertised. On Windows the
# lenient mode is DISABLED: the filesystem strips trailing '.' on access,
# so 'pub/llama3.' would alias the distinct strict-valid 'pub/llama3' —
# and legacy trailing-dot dirs cannot exist there anyway (uncreatable).
_SAFE_COMPONENT_LEGACY = re.compile(r"[A-Za-z0-9](?:[A-Za-z0-9._\- ]*[A-Za-z0-9._\-])?\Z")


def split_model_id(model_id: str, strict: bool = True) -> tuple[str, str]:
    """"publisher/model" -> (publisher, model); bare names get publisher
    "local" (mirrors the reference's fallback of deriving the publisher from
    the id prefix, nats_llm_studio.go:112-118, without the duplication).

    Every '/'-separated component is validated against a conservative
    pattern: a hostile id like '../../../etc' must never become a
    filesystem path (model_dir -> mkdir; delete_local -> rmtree).
    ``strict=False`` (lookup/list/delete of dirs that already exist) accepts
    the legacy charset on POSIX so caches written by earlier releases stay
    reachable; creation paths — and everything on Windows, where trailing
    '.'/' ' alias other dirs — always use the strict pattern."""
    model_id = model_id.strip().strip("/")
    lenient = not strict and os.name != "nt"
    pattern = _SAFE_COMPONENT_LEGACY if lenient else _SAFE_COMPONENT
    for comp in model_id.split("/"):
        if not pattern.match(comp):
            raise StoreError(f"unsafe model id component {comp!r} in {model_id!r}")
    if "/" in model_id:
        pub, _, name = model_id.partition("/")
        return pub, name
    return "local", model_id


class ModelStore:
    """Local cache directory + optional Object Store bucket."""

    def __init__(self, models_dir: str | Path, objstore: ObjectStore | None = None,
                 bucket: str = "llm-models",
                 url_schemes: tuple[str, ...] = ("https", "http", "file"),
                 max_url_pull_bytes: int = 100 << 30):
        self.models_dir = Path(models_dir).expanduser()
        self.models_dir.mkdir(parents=True, exist_ok=True)
        self.objstore = objstore
        self.bucket = bucket
        # which URL schemes pull() may fetch. Library default is permissive;
        # SERVING processes pass the config's (https-only by default) — a
        # shared-bus client must not be able to drive the worker to GET
        # internal endpoints or read local files into the served cache (SSRF)
        self.url_schemes = tuple(url_schemes)
        # ceiling on a single URL pull: a hostile/huge URL must not fill the
        # worker's disk (default matches the reference's 100 GiB JetStream
        # file-store bound, setup_unix.sh:93)
        self.max_url_pull_bytes = max_url_pull_bytes

    # -- local cache ---------------------------------------------------------

    def model_dir(self, model_id: str, strict: bool = True) -> Path:
        pub, name = split_model_id(model_id, strict=strict)
        return self.models_dir / pub / name

    def cached(self) -> list[CachedModel]:
        out = []
        for pub_dir in sorted(p for p in self.models_dir.iterdir() if p.is_dir()):
            for model_dir in sorted(p for p in pub_dir.iterdir() if p.is_dir()):
                # only list ids that round-trip through split_model_id's
                # lenient validation — a hand-placed dir with an unsafe name
                # would otherwise be advertised but impossible to load or
                # delete over the bus (lookup/delete would raise). The
                # LEGACY pattern here keeps caches from earlier releases
                # (trailing '.'/' ') listable and reclaimable.
                if not (_SAFE_COMPONENT_LEGACY.match(pub_dir.name)
                        and _SAFE_COMPONENT_LEGACY.match(model_dir.name)):
                    continue
                files = sorted(model_dir.glob("*.gguf"))
                if files:
                    out.append(
                        CachedModel(
                            model_id=f"{pub_dir.name}/{model_dir.name}",
                            publisher=pub_dir.name,
                            name=model_dir.name,
                            path=model_dir,
                            files=files,
                        )
                    )
        return out

    def lookup(self, model_id: str) -> CachedModel | None:
        d = self.model_dir(model_id, strict=False)
        files = sorted(d.glob("*.gguf")) if d.is_dir() else []
        if not files:
            return None
        pub, name = split_model_id(model_id, strict=False)
        return CachedModel(f"{pub}/{name}", pub, name, d, files)

    def delete_local(self, model_id: str) -> str:
        """Remove the model directory; returns the deleted dir (the
        reference replies ``deleted_dir``, nats_llm_studio.go:316-323).
        Lenient validation: legacy-named dirs must stay deletable."""
        d = self.model_dir(model_id, strict=False)
        if not d.is_dir():
            raise StoreError(f"model directory not found: {d}", dir=str(d))
        shutil.rmtree(d)
        # drop the publisher dir too if now empty (keep models_dir tidy)
        try:
            d.parent.rmdir()
        except OSError:
            pass
        return str(d)

    def import_file(self, src: str | Path, model_id: str) -> Path:
        """Copy a local .gguf into the cache layout (the `lms import` analog,
        /root/reference/README.md:316)."""
        src = Path(src)
        dest_dir = self.model_dir(model_id)
        dest_dir.mkdir(parents=True, exist_ok=True)
        dest = dest_dir / src.name
        shutil.copyfile(src, dest)
        return dest

    # -- object store --------------------------------------------------------

    def _require_store(self) -> ObjectStore:
        if self.objstore is None:
            raise StoreError("object store not configured")
        return self.objstore

    async def publish_model(self, model_id: str, gguf_path: str | Path | None = None) -> str:
        """Upload a cached model (or explicit file) to the bucket as
        ``<publisher>/<model>/<file>.gguf``. A model cached as a gguf-split
        shard set uploads EVERY shard (a worker pulling the model needs the
        complete set to load it). Returns the first object name."""
        store = self._require_store()
        if gguf_path is None:
            cm = self.lookup(model_id)
            if cm is None:
                raise StoreError(f"model {model_id!r} not in local cache")
            paths = _shard_family(cm.files)
        else:
            paths = [Path(gguf_path)]
        pub, name = split_model_id(model_id)
        await store.ensure_bucket(self.bucket)
        obj_names = []
        for p in paths:
            obj_name = f"{pub}/{name}/{p.name}"
            data = await asyncio.to_thread(p.read_bytes)  # keep the loop serving
            await store.put(self.bucket, obj_name, data)
            obj_names.append(obj_name)
        return obj_names[0]

    async def pull(self, identifier: str, model_id: str | None = None) -> tuple[Path, str]:
        """Fetch a model from the bucket into the local cache (the `lms get`
        replacement, nats_llm_studio.go:46-59; conceptual sync flow
        README.md:286-318). ``identifier`` is an object name
        ``publisher/model/file.gguf``, a model id ``publisher/model``, or an
        ``http(s)://`` / ``file://`` URL to a GGUF (the catalog-download
        capability `lms get` has for public models); ``model_id`` overrides
        the cache location (README.md:306 lets the sync flow choose the
        local model dir). Returns (local_path, transcript)."""
        if identifier.startswith(("http://", "https://", "file://")):
            scheme = identifier.split("://", 1)[0]
            if scheme not in self.url_schemes:
                raise StoreError(
                    f"URL pulls via {scheme!r} are not allowed on this worker"
                )
            return await self._pull_url(identifier, model_id)
        store = self._require_store()
        lines = [f"pulling {identifier!r} from bucket {self.bucket!r}"]
        obj_name = identifier.strip().strip("/")
        if not obj_name.endswith(".gguf"):
            # model id: pull EVERY object under the prefix (a split model is
            # several shard objects; one shard alone cannot be loaded)
            objs = await store.list(self.bucket)
            matches = sorted(
                o.name for o in objs if o.name.startswith(obj_name + "/")
            )
            if not matches:
                raise StoreError(f"no objects under {obj_name!r} in bucket {self.bucket!r}")
            _check_split_complete(matches)
            lines.append(f"resolved to {len(matches)} object(s)")
            # stage every shard, commit only when the whole set landed —
            # the single-file temp/rename atomicity must hold for the SET
            # (a partial set would look cached but fail to load)
            staged: list[tuple[Path, Path, int]] = []
            try:
                for nm in matches:
                    staged.append(await self._pull_object(nm, model_id))
            except BaseException:
                for _, tmp, _ in staged:
                    tmp.unlink(missing_ok=True)
                raise
            for dest, tmp, total in staged:
                tmp.replace(dest)
                lines.append(f"wrote {total} bytes to {dest}")
            return staged[0][0], "\n".join(lines)
        dest, tmp, total = await self._pull_object(obj_name, model_id)
        tmp.replace(dest)
        lines.append(f"wrote {total} bytes to {dest}")
        return dest, "\n".join(lines)

    async def _pull_object(
        self, obj_name: str, model_id: str | None
    ) -> tuple[Path, Path, int]:
        """Stream one bucket object to a staging file; returns
        (dest, tmp, bytes) — the caller commits with tmp.replace(dest)."""
        store = self._require_store()
        parts = obj_name.split("/")
        if len(parts) < 3:
            raise StoreError(
                f"object name {obj_name!r} must be <publisher>/<model>/<file>.gguf"
            )
        fname = parts[-1]
        # object names are CLIENT-CONTROLLED (any bus client can `nats obj
        # put` arbitrary names and then ask a worker to pull them): every
        # component that becomes a filesystem path must pass the strict
        # creation pattern, or 'a/../../x/f.gguf' would mkdir/write outside
        # models_dir
        for comp in parts:
            if not _SAFE_COMPONENT.match(comp):
                raise StoreError(
                    f"unsafe object name component {comp!r} in {obj_name!r}"
                )
        if model_id:
            dest_dir = self.model_dir(model_id)
        else:
            dest_dir = self.models_dir / parts[0] / "/".join(parts[1:-1])
        dest_dir.mkdir(parents=True, exist_ok=True)
        dest = dest_dir / fname
        # stream chunk-at-a-time into a temp file: peak RAM is O(chunk), not
        # O(object) — a 40 GB GGUF must not be materialized (VERDICT weak #6);
        # the rename commits only after size+digest verify in get_chunks
        tmp = _tmp_part(dest_dir, fname)
        total = 0
        try:
            with open(tmp, "wb") as f:
                async for chunk in store.get_chunks(self.bucket, obj_name):
                    total += len(chunk)
                    # buffered ~128 KB writes are ~us-cheap; a to_thread hop
                    # per chunk would cost more than the write itself. Yield
                    # periodically so a multi-GB pull cannot starve the loop.
                    f.write(chunk)
                    if total % (64 << 20) < len(chunk):
                        await asyncio.sleep(0)
        except ObjectNotFound as e:
            tmp.unlink(missing_ok=True)
            raise StoreError(f"object {obj_name!r} not found: {e}") from None
        except BaseException:
            tmp.unlink(missing_ok=True)
            raise
        return dest, tmp, total

    async def _pull_url(self, url: str, model_id: str | None) -> tuple[Path, str]:
        """Stream a GGUF from an HTTP(S)/file URL into the local cache —
        restores the reference's `lms get <any catalog model>` capability
        (nats_llm_studio.go:46-59) without the LM Studio catalog."""
        import urllib.parse
        import urllib.request

        fname = Path(urllib.parse.urlparse(url).path).name or "model.gguf"
        if not fname.endswith(".gguf"):
            raise StoreError(f"URL pull expects a .gguf file, got {fname!r}")
        # the URL basename becomes a path component of the cache layout: a
        # stem like '..' or one with separators/odd bytes would escape the
        # publisher/model directory scheme (round-2 advisor)
        stem = fname.removesuffix(".gguf")
        if not re.fullmatch(r"[A-Za-z0-9][A-Za-z0-9._-]*", stem) or ".." in stem:
            raise StoreError(f"unsafe model filename in URL: {fname!r}")
        mid = model_id or f"downloads/{stem}"
        dest_dir = self.model_dir(mid)
        dest_dir.mkdir(parents=True, exist_ok=True)
        dest = dest_dir / fname
        tmp = _tmp_part(dest_dir, fname)

        allowed = self.url_schemes

        class _SchemeGuardRedirect(urllib.request.HTTPRedirectHandler):
            # urlopen follows cross-scheme redirects; without this a
            # https-only allowlist could still be driven to http://
            # internal endpoints via a 302 (the SSRF the gate exists for)
            def redirect_request(self, req, fp, code, msg, headers, newurl):
                scheme = urllib.parse.urlparse(newurl).scheme
                if scheme not in allowed:
                    raise OSError(
                        f"redirect to disallowed scheme {scheme!r}: {newurl}"
                    )
                return super().redirect_request(req, fp, code, msg, headers, newurl)

        opener = urllib.request.build_opener(_SchemeGuardRedirect())

        limit = self.max_url_pull_bytes

        def fetch() -> int:
            total = 0
            with opener.open(url, timeout=60.0) as r, open(tmp, "wb") as f:
                expect = r.headers.get("Content-Length")
                if expect is not None and int(expect) > limit:
                    raise OSError(
                        f"download of {expect} bytes exceeds the "
                        f"{limit}-byte URL pull ceiling"
                    )
                while True:
                    chunk = r.read(1 << 20)
                    if not chunk:
                        break
                    f.write(chunk)
                    total += len(chunk)
                    if total > limit:
                        raise OSError(
                            f"download exceeded the {limit}-byte URL pull ceiling"
                        )
            # a premature close makes read() return b'' without an error —
            # verify against the advertised size before committing
            if expect is not None and total != int(expect):
                raise OSError(f"truncated download: got {total} of {expect} bytes")
            return total

        try:
            total = await asyncio.to_thread(fetch)
        except BaseException as e:
            tmp.unlink(missing_ok=True)
            if isinstance(e, (OSError, ValueError)):
                raise StoreError(f"download failed for {url!r}: {e}") from None
            raise
        tmp.replace(dest)
        return dest, f"downloaded {url!r}\nwrote {total} bytes to {dest}"

"""Server-side object store module for the embedded broker.

Implements the slice of the public JetStream wire API that the Object Store
pattern needs, so the in-tree client (transport/jetstream.py) — and any
foreign client using direct-get — can store/fetch model blobs:

* ``$JS.API.STREAM.CREATE.<name>`` / ``INFO`` / ``DELETE`` / ``PURGE`` /
  ``NAMES`` — JSON request-reply
* ``$JS.API.DIRECT.GET.<name>`` — ``{"last_by_subj"}`` or
  ``{"seq", "next_by_subj"}`` lookups, replied with Nats-Subject /
  Nats-Sequence headers (404 via status header)
* message capture for stream subjects with ``Nats-Rollup: sub`` per-subject
  rollup (object-store metadata updates)

State is in-memory with optional file-backed persistence of chunk payloads
under a store dir (the JetStream file-store analog, setup_unix.sh:87-95).
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field
from pathlib import Path

from ..transport.broker import EmbeddedBroker
from ..utils import subject_matches

log = logging.getLogger(__name__)

_API_PREFIX = "$JS.API."


@dataclass
class _StoredMsg:
    seq: int
    subject: str
    headers: dict[str, str] | None
    payload: bytes
    ts: float


@dataclass
class _Stream:
    name: str
    config: dict
    next_seq: int = 1
    msgs: list[_StoredMsg] = field(default_factory=list)  # ordered by seq

    @property
    def subjects(self) -> list[str]:
        return list(self.config.get("subjects") or [])

    def captures(self, subject: str) -> bool:
        return any(subject_matches(pat, subject) for pat in self.subjects)

    def bytes_total(self) -> int:
        return sum(len(m.payload) for m in self.msgs)


class JetStreamStoreModule:
    """Attach with ``JetStreamStoreModule(broker).install()``."""

    def __init__(self, broker: EmbeddedBroker, store_dir: str | Path | None = None):
        self.broker = broker
        self.streams: dict[str, _Stream] = {}
        self.store_dir = Path(store_dir) if store_dir else None
        if self.store_dir:
            self.store_dir.mkdir(parents=True, exist_ok=True)
            self._load_persisted()

    def install(self) -> "JetStreamStoreModule":
        self.broker.register_internal(_API_PREFIX + ">", self._on_api)
        self.broker.register_internal("$O.>", self._on_capture)
        return self

    # -- persistence (file-store analog) ------------------------------------

    def _stream_file(self, name: str) -> Path:
        assert self.store_dir is not None
        return self.store_dir / f"{name}.jsl"

    def _persist_append(self, stream: _Stream, msg: _StoredMsg) -> None:
        if not self.store_dir:
            return
        rec = {
            "seq": msg.seq,
            "subject": msg.subject,
            "headers": msg.headers,
            "payload_hex": msg.payload.hex(),
            "ts": msg.ts,
        }
        with open(self._stream_file(stream.name), "a") as f:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def _persist_rewrite(self, stream: _Stream) -> None:
        if not self.store_dir:
            return
        path = self._stream_file(stream.name)
        tmp = path.with_suffix(".tmp")
        with open(tmp, "w") as f:
            f.write(json.dumps({"config": stream.config, "next_seq": stream.next_seq}) + "\n")
            for m in stream.msgs:
                f.write(
                    json.dumps(
                        {
                            "seq": m.seq,
                            "subject": m.subject,
                            "headers": m.headers,
                            "payload_hex": m.payload.hex(),
                            "ts": m.ts,
                        },
                        separators=(",", ":"),
                    )
                    + "\n"
                )
        tmp.replace(path)

    def _load_persisted(self) -> None:
        assert self.store_dir is not None
        for f in sorted(self.store_dir.glob("*.jsl")):
            try:
                lines = f.read_text().splitlines()
                head = json.loads(lines[0])
                st = _Stream(name=f.stem, config=head["config"], next_seq=head["next_seq"])
                for line in lines[1:]:
                    r = json.loads(line)
                    st.msgs.append(
                        _StoredMsg(
                            r["seq"], r["subject"], r.get("headers"),
                            bytes.fromhex(r["payload_hex"]), r.get("ts", 0.0),
                        )
                    )
                self.streams[st.name] = st
            except (ValueError, KeyError, IndexError):
                log.warning("skipping corrupt stream file %s", f)

    # -- capture -------------------------------------------------------------

    async def _on_capture(self, subject: str, payload: bytes, reply, headers) -> None:
        if subject.startswith(_API_PREFIX):
            return
        for stream in self.streams.values():
            if not stream.captures(subject):
                continue
            rollup = (headers or {}).get("Nats-Rollup")
            if rollup == "sub":
                stream.msgs = [m for m in stream.msgs if m.subject != subject]
            elif rollup == "all":
                stream.msgs.clear()
            msg = _StoredMsg(stream.next_seq, subject, headers, payload, time.time())
            stream.next_seq += 1
            stream.msgs.append(msg)
            if rollup:
                self._persist_rewrite(stream)
            else:
                self._persist_append(stream, msg)
            if reply:
                ack = {"stream": stream.name, "seq": msg.seq}
                await self.broker.publish_internal(reply, json.dumps(ack).encode())

    # -- API -----------------------------------------------------------------

    async def _reply_json(self, reply: str | None, obj: dict) -> None:
        if reply:
            await self.broker.publish_internal(reply, json.dumps(obj).encode())

    async def _reply_error(self, reply: str | None, code: int, desc: str) -> None:
        await self._reply_json(
            reply, {"error": {"code": code, "err_code": code * 100, "description": desc}}
        )

    async def _on_api(self, subject: str, payload: bytes, reply, headers) -> None:
        op = subject[len(_API_PREFIX) :]
        try:
            body = json.loads(payload) if payload.strip() else {}
        except ValueError:
            await self._reply_error(reply, 400, "bad request payload")
            return
        try:
            if op.startswith("STREAM.CREATE.") or op.startswith("STREAM.UPDATE."):
                await self._stream_create(op.rsplit(".", 1)[1], body, reply)
            elif op.startswith("STREAM.INFO."):
                await self._stream_info(op.rsplit(".", 1)[1], reply)
            elif op.startswith("STREAM.DELETE."):
                await self._stream_delete(op.rsplit(".", 1)[1], reply)
            elif op.startswith("STREAM.PURGE."):
                await self._stream_purge(op.rsplit(".", 1)[1], body, reply)
            elif op == "STREAM.NAMES":
                names = sorted(self.streams)
                await self._reply_json(
                    reply, {"streams": names, "total": len(names), "offset": 0, "limit": 1024}
                )
            elif op.startswith("DIRECT.GET."):
                await self._direct_get(op[len("DIRECT.GET.") :], body, reply)
            else:
                await self._reply_error(reply, 404, f"unknown JS API op {op}")
        except Exception as e:  # noqa: BLE001 — API errors become error replies
            log.exception("JS API error on %s", subject)
            await self._reply_error(reply, 500, str(e))

    async def _stream_create(self, name: str, config: dict, reply) -> None:
        existing = self.streams.get(name)
        if existing is None:
            config = dict(config or {})
            config.setdefault("name", name)
            config.setdefault("subjects", [name])
            self.streams[name] = _Stream(name=name, config=config)
            self._persist_rewrite(self.streams[name])
        else:
            existing.config.update(config or {})
        await self._stream_info(name, reply)

    def _state(self, st: _Stream) -> dict:
        return {
            "messages": len(st.msgs),
            "bytes": st.bytes_total(),
            "first_seq": st.msgs[0].seq if st.msgs else 0,
            "last_seq": st.msgs[-1].seq if st.msgs else st.next_seq - 1,
            "num_subjects": len({m.subject for m in st.msgs}),
        }

    async def _stream_info(self, name: str, reply) -> None:
        st = self.streams.get(name)
        if st is None:
            await self._reply_error(reply, 404, "stream not found")
            return
        await self._reply_json(
            reply,
            {"type": "io.nats.jetstream.api.v1.stream_info_response",
             "config": st.config, "state": self._state(st), "created": ""},
        )

    async def _stream_delete(self, name: str, reply) -> None:
        st = self.streams.pop(name, None)
        if st is None:
            await self._reply_error(reply, 404, "stream not found")
            return
        if self.store_dir:
            self._stream_file(name).unlink(missing_ok=True)
        await self._reply_json(reply, {"success": True})

    async def _stream_purge(self, name: str, body: dict, reply) -> None:
        st = self.streams.get(name)
        if st is None:
            await self._reply_error(reply, 404, "stream not found")
            return
        filt = body.get("filter")
        before = len(st.msgs)
        if filt:
            st.msgs = [m for m in st.msgs if not subject_matches(filt, m.subject)]
        else:
            st.msgs.clear()
        self._persist_rewrite(st)
        await self._reply_json(reply, {"success": True, "purged": before - len(st.msgs)})

    async def _direct_get(self, stream_name: str, body: dict, reply) -> None:
        st = self.streams.get(stream_name)
        if reply is None:
            return
        if st is None:
            await self.broker.publish_internal(
                reply, b"", headers={"Status": "404", "Description": "Stream Not Found"}
            )
            return
        msg: _StoredMsg | None = None
        if "last_by_subj" in body:
            pat = body["last_by_subj"]
            for m in reversed(st.msgs):
                if subject_matches(pat, m.subject):
                    msg = m
                    break
        else:
            seq = int(body.get("seq") or 0)
            pat = body.get("next_by_subj")
            for m in st.msgs:
                if m.seq >= seq and (pat is None or subject_matches(pat, m.subject)):
                    msg = m
                    break
        if msg is None:
            await self.broker.publish_internal(
                reply, b"", headers={"Status": "404", "Description": "Message Not Found"}
            )
            return
        hdrs = dict(msg.headers or {})
        hdrs.update(
            {
                "Nats-Stream": st.name,
                "Nats-Subject": msg.subject,
                "Nats-Sequence": str(msg.seq),
                "Nats-Num-Pending": "0",
            }
        )
        await self.broker.publish_internal(reply, msg.payload, headers=hdrs)


__all__ = ["JetStreamStoreModule"]

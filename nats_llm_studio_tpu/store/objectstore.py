"""Server-side object store module for the embedded broker.

Implements the slice of the public JetStream wire API that the Object Store
pattern needs, so the in-tree client (transport/jetstream.py) — and any
foreign client using direct-get — can store/fetch model blobs:

* ``$JS.API.STREAM.CREATE.<name>`` / ``INFO`` / ``DELETE`` / ``PURGE`` /
  ``NAMES`` — JSON request-reply
* ``$JS.API.DIRECT.GET.<name>`` — ``{"last_by_subj"}`` or
  ``{"seq", "next_by_subj"}`` lookups, replied with Nats-Subject /
  Nats-Sequence headers (404 via status header)
* message capture for stream subjects with ``Nats-Rollup: sub`` per-subject
  rollup (object-store metadata updates)

With a store dir, payloads live ON DISK in a binary append-log per stream
(the JetStream file-store analog, setup_unix.sh:87-95): broker RAM holds
only per-message index entries, so a 40 GB model blob costs O(chunk) memory
and its bytes are written exactly once. Rollups/purges mark bytes dead; the
log compacts when dead bytes outweigh live ones. Without a store dir the
module is the memory-store analog (payloads in RAM, nothing persisted).

Log record format: ``u32 header_len | header JSON | payload bytes``; the
first record is the stream header ``{"config", "next_seq"}`` with an empty
payload.
"""

from __future__ import annotations

import json
import logging
import struct
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import BinaryIO

from ..transport.broker import EmbeddedBroker
from ..utils import subject_matches

log = logging.getLogger(__name__)

_API_PREFIX = "$JS.API."
_COMPACT_MIN_DEAD = 64 * 1024 * 1024


@dataclass
class _StoredMsg:
    seq: int
    subject: str
    headers: dict[str, str] | None
    ts: float
    plen: int
    payload: bytes | None = None  # memory mode only
    offset: int = -1  # disk mode: payload offset within the stream log


@dataclass
class _Stream:
    name: str
    config: dict
    next_seq: int = 1
    msgs: list[_StoredMsg] = field(default_factory=list)  # ordered by seq
    dead_bytes: int = 0  # payload bytes in the log no longer referenced

    @property
    def subjects(self) -> list[str]:
        return list(self.config.get("subjects") or [])

    def captures(self, subject: str) -> bool:
        return any(subject_matches(pat, subject) for pat in self.subjects)

    def bytes_total(self) -> int:
        return sum(m.plen for m in self.msgs)


class JetStreamStoreModule:
    """Attach with ``JetStreamStoreModule(broker).install()``."""

    def __init__(self, broker: EmbeddedBroker, store_dir: str | Path | None = None):
        self.broker = broker
        self.streams: dict[str, _Stream] = {}
        self.store_dir = Path(store_dir) if store_dir else None
        self._files: dict[str, BinaryIO] = {}  # open "a+b" log handles
        if self.store_dir:
            self.store_dir.mkdir(parents=True, exist_ok=True)
            self._load_persisted()

    def install(self) -> "JetStreamStoreModule":
        self.broker.register_internal(_API_PREFIX + ">", self._on_api)
        self.broker.register_internal("$O.>", self._on_capture)
        # broker.stop() closes the append-log handles deterministically
        # (round-2 advisor: GC-held "a+b" handles block dir removal on
        # Windows and leak fds across test restarts)
        self.broker.register_module(self)
        return self

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()

    # -- persistence (file-store analog: binary append-log) ------------------

    def _stream_file(self, name: str) -> Path:
        assert self.store_dir is not None
        return self.store_dir / f"{name}.jsl"

    def _file(self, name: str) -> BinaryIO:
        f = self._files.get(name)
        if f is None or f.closed:
            f = open(self._stream_file(name), "a+b")
            self._files[name] = f
        return f

    @staticmethod
    def _write_record(f: BinaryIO, head: dict, payload: bytes) -> int:
        """Append one record; returns the payload's file offset."""
        hb = json.dumps(head, separators=(",", ":")).encode()
        f.seek(0, 2)
        f.write(struct.pack(">I", len(hb)))
        f.write(hb)
        off = f.tell()
        f.write(payload)
        return off

    def _persist_header(self, stream: _Stream) -> None:
        """(Re)create the log with just the stream header (new stream)."""
        if not self.store_dir:
            return
        f = self._file(stream.name)
        f.truncate(0)
        self._write_record(
            f, {"config": stream.config, "next_seq": stream.next_seq}, b""
        )
        f.flush()

    def _persist_append(self, stream: _Stream, msg: _StoredMsg, payload: bytes) -> None:
        if not self.store_dir:
            msg.payload = payload
            return
        f = self._file(stream.name)
        head = {
            "seq": msg.seq,
            "subject": msg.subject,
            "headers": msg.headers,
            "ts": msg.ts,
            "plen": msg.plen,
        }
        msg.offset = self._write_record(f, head, payload)
        f.flush()

    def _payload(self, stream: _Stream, msg: _StoredMsg) -> bytes:
        if msg.payload is not None:
            return msg.payload
        f = self._file(stream.name)
        f.seek(msg.offset)
        return f.read(msg.plen)

    def _persist_ctl(self, stream: _Stream, ctl: dict) -> None:
        """Append a control record (e.g. a purge) so replay reproduces
        drops that compaction has not yet made physical."""
        if not self.store_dir:
            return
        f = self._file(stream.name)
        self._write_record(f, {"ctl": ctl}, b"")
        f.flush()

    def _mark_dead(self, stream: _Stream, msgs: list[_StoredMsg]) -> None:
        stream.dead_bytes += sum(m.plen + 96 for m in msgs)

    def _maybe_compact(self, stream: _Stream) -> None:
        """Rewrite the log with only live records once dead bytes outweigh
        live ones — purges/rollups never rewrite the log inline, so dropping
        a multi-GB object is O(1) until compaction actually pays. Small logs
        (metadata-dominated) compact eagerly; that path is bounded at 8 MB
        of blocking IO."""
        if not self.store_dir or stream.dead_bytes == 0:
            return
        small = self._stream_file(stream.name).stat().st_size < 8 * 1024 * 1024
        if not small and (
            stream.dead_bytes < _COMPACT_MIN_DEAD
            or stream.dead_bytes < stream.bytes_total()
        ):
            return
        self._compact(stream)

    def _compact(self, stream: _Stream) -> None:
        assert self.store_dir is not None
        path = self._stream_file(stream.name)
        tmp = path.with_suffix(".tmp")
        old = self._file(stream.name)
        with open(tmp, "wb") as f:
            self._write_record(
                f, {"config": stream.config, "next_seq": stream.next_seq}, b""
            )
            for m in stream.msgs:
                head = {
                    "seq": m.seq,
                    "subject": m.subject,
                    "headers": m.headers,
                    "ts": m.ts,
                    "plen": m.plen,
                }
                if m.payload is not None:
                    payload = m.payload
                else:
                    old.seek(m.offset)
                    payload = old.read(m.plen)
                m.offset = self._write_record(f, head, payload)
        old.close()
        del self._files[stream.name]
        tmp.replace(path)
        stream.dead_bytes = 0

    def _load_persisted(self) -> None:
        assert self.store_dir is not None
        for path in sorted(self.store_dir.glob("*.jsl")):
            try:
                st: _Stream | None = None
                kept: list[_StoredMsg] = []
                live = 0
                max_seq = 0
                fsize = path.stat().st_size
                torn_at: int | None = None
                with open(path, "rb") as f:
                    while True:
                        rec_start = f.tell()
                        raw = f.read(4)
                        if not raw:
                            break
                        if len(raw) < 4:
                            torn_at = rec_start
                            break
                        (hlen,) = struct.unpack(">I", raw)
                        hb = f.read(hlen)
                        if len(hb) < hlen:
                            torn_at = rec_start
                            break
                        head = json.loads(hb)
                        if "config" in head:
                            st = _Stream(
                                name=path.stem, config=head["config"],
                                next_seq=head["next_seq"],
                            )
                            continue
                        assert st is not None
                        if "ctl" in head:
                            # replayed purge: reproduce the runtime drop
                            filt = head["ctl"].get("filter")
                            if filt:
                                kept = [
                                    m for m in kept
                                    if not subject_matches(filt, m.subject)
                                ]
                            else:
                                kept = []
                            continue
                        plen = int(head.get("plen", 0))
                        off = f.tell()
                        if off + plen > fsize:
                            # torn tail: header landed, payload did not
                            torn_at = rec_start
                            break
                        f.seek(plen, 1)
                        live += plen
                        max_seq = max(max_seq, head["seq"])
                        rollup = (head.get("headers") or {}).get("Nats-Rollup")
                        if rollup == "sub":
                            kept = [m for m in kept if m.subject != head["subject"]]
                        elif rollup == "all":
                            kept = []
                        kept.append(
                            _StoredMsg(
                                head["seq"], head["subject"], head.get("headers"),
                                head.get("ts", 0.0), plen, offset=off,
                            )
                        )
                if st is None:
                    raise ValueError("missing stream header")
                if torn_at is not None:
                    log.warning(
                        "truncating torn tail record of %s at offset %d",
                        path, torn_at,
                    )
                    with open(path, "r+b") as f:
                        f.truncate(torn_at)
                st.msgs = kept
                st.next_seq = max(st.next_seq, max_seq + 1)
                st.dead_bytes = live - st.bytes_total()
                self.streams[st.name] = st
            except (ValueError, KeyError, AssertionError, struct.error):
                log.warning("skipping corrupt stream file %s", path)

    # -- capture -------------------------------------------------------------

    async def _on_capture(self, subject: str, payload: bytes, reply, headers) -> None:
        if subject.startswith(_API_PREFIX):
            return
        for stream in self.streams.values():
            if not stream.captures(subject):
                continue
            rollup = (headers or {}).get("Nats-Rollup")
            if rollup == "sub":
                dropped = [m for m in stream.msgs if m.subject == subject]
                stream.msgs = [m for m in stream.msgs if m.subject != subject]
                self._mark_dead(stream, dropped)
            elif rollup == "all":
                self._mark_dead(stream, stream.msgs)
                stream.msgs.clear()
            msg = _StoredMsg(
                stream.next_seq, subject, headers, time.time(), len(payload)
            )
            stream.next_seq += 1
            stream.msgs.append(msg)
            self._persist_append(stream, msg, payload)
            if rollup:
                self._maybe_compact(stream)
            if reply:
                ack = {"stream": stream.name, "seq": msg.seq}
                await self.broker.publish_internal(reply, json.dumps(ack).encode())

    # -- API -----------------------------------------------------------------

    async def _reply_json(self, reply: str | None, obj: dict) -> None:
        if reply:
            await self.broker.publish_internal(reply, json.dumps(obj).encode())

    async def _reply_error(self, reply: str | None, code: int, desc: str) -> None:
        await self._reply_json(
            reply, {"error": {"code": code, "err_code": code * 100, "description": desc}}
        )

    async def _on_api(self, subject: str, payload: bytes, reply, headers) -> None:
        op = subject[len(_API_PREFIX) :]
        try:
            body = json.loads(payload) if payload.strip() else {}
        except ValueError:
            await self._reply_error(reply, 400, "bad request payload")
            return
        try:
            if op.startswith("STREAM.CREATE.") or op.startswith("STREAM.UPDATE."):
                await self._stream_create(op.rsplit(".", 1)[1], body, reply)
            elif op.startswith("STREAM.INFO."):
                await self._stream_info(op.rsplit(".", 1)[1], reply)
            elif op.startswith("STREAM.DELETE."):
                await self._stream_delete(op.rsplit(".", 1)[1], reply)
            elif op.startswith("STREAM.PURGE."):
                await self._stream_purge(op.rsplit(".", 1)[1], body, reply)
            elif op == "STREAM.NAMES":
                names = sorted(self.streams)
                await self._reply_json(
                    reply, {"streams": names, "total": len(names), "offset": 0, "limit": 1024}
                )
            elif op.startswith("DIRECT.GET."):
                await self._direct_get(op[len("DIRECT.GET.") :], body, reply)
            else:
                await self._reply_error(reply, 404, f"unknown JS API op {op}")
        except Exception as e:  # noqa: BLE001 — API errors become error replies
            log.exception("JS API error on %s", subject)
            await self._reply_error(reply, 500, str(e))

    async def _stream_create(self, name: str, config: dict, reply) -> None:
        existing = self.streams.get(name)
        if existing is None:
            config = dict(config or {})
            config.setdefault("name", name)
            config.setdefault("subjects", [name])
            self.streams[name] = _Stream(name=name, config=config)
            self._persist_header(self.streams[name])
        else:
            existing.config.update(config or {})
        await self._stream_info(name, reply)

    def _state(self, st: _Stream) -> dict:
        return {
            "messages": len(st.msgs),
            "bytes": st.bytes_total(),
            "first_seq": st.msgs[0].seq if st.msgs else 0,
            "last_seq": st.msgs[-1].seq if st.msgs else st.next_seq - 1,
            "num_subjects": len({m.subject for m in st.msgs}),
        }

    async def _stream_info(self, name: str, reply) -> None:
        st = self.streams.get(name)
        if st is None:
            await self._reply_error(reply, 404, "stream not found")
            return
        await self._reply_json(
            reply,
            {"type": "io.nats.jetstream.api.v1.stream_info_response",
             "config": st.config, "state": self._state(st), "created": ""},
        )

    async def _stream_delete(self, name: str, reply) -> None:
        st = self.streams.pop(name, None)
        if st is None:
            await self._reply_error(reply, 404, "stream not found")
            return
        f = self._files.pop(name, None)
        if f is not None:
            f.close()
        if self.store_dir:
            self._stream_file(name).unlink(missing_ok=True)
        await self._reply_json(reply, {"success": True})

    async def _stream_purge(self, name: str, body: dict, reply) -> None:
        st = self.streams.get(name)
        if st is None:
            await self._reply_error(reply, 404, "stream not found")
            return
        filt = body.get("filter")
        before = len(st.msgs)
        if filt:
            dropped = [m for m in st.msgs if subject_matches(filt, m.subject)]
            st.msgs = [m for m in st.msgs if not subject_matches(filt, m.subject)]
        else:
            dropped = st.msgs
            st.msgs = []
        self._mark_dead(st, dropped)
        self._persist_ctl(st, {"op": "purge", "filter": filt})
        self._maybe_compact(st)
        await self._reply_json(reply, {"success": True, "purged": before - len(st.msgs)})

    async def _direct_get(self, stream_name: str, body: dict, reply) -> None:
        st = self.streams.get(stream_name)
        if reply is None:
            return
        if st is None:
            await self.broker.publish_internal(
                reply, b"", headers={"Status": "404", "Description": "Stream Not Found"}
            )
            return
        msg: _StoredMsg | None = None
        if "last_by_subj" in body:
            pat = body["last_by_subj"]
            for m in reversed(st.msgs):
                if subject_matches(pat, m.subject):
                    msg = m
                    break
        else:
            seq = int(body.get("seq") or 0)
            pat = body.get("next_by_subj")
            for m in st.msgs:
                if m.seq >= seq and (pat is None or subject_matches(pat, m.subject)):
                    msg = m
                    break
        if msg is None:
            await self.broker.publish_internal(
                reply, b"", headers={"Status": "404", "Description": "Message Not Found"}
            )
            return
        hdrs = dict(msg.headers or {})
        hdrs.update(
            {
                "Nats-Stream": st.name,
                "Nats-Subject": msg.subject,
                "Nats-Sequence": str(msg.seq),
                "Nats-Num-Pending": "0",
            }
        )
        await self.broker.publish_internal(reply, self._payload(st, msg), headers=hdrs)


__all__ = ["JetStreamStoreModule"]
